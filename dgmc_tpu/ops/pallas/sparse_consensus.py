"""Fused sparse consensus update: ``mlp(o_s[:, :, None] - o_t_cand)``.

Per sparse consensus iteration the reference computes a 2-layer MLP on
the difference between each source node's consensus colouring and its
K candidates' (reference ``dgmc/models/dgmc.py:216-223``). Unfused, XLA
materializes the ``[B, N_s, K, R]`` difference tensor and the hidden
activations in HBM — at DBP15K training shape (15000 x 21 x 32 f32)
that's ~80 MB of round-trips per iteration plus the same again saved for
the backward, ten times per step.

This kernel tiles the source axis, forms the difference block and hidden
activations in VMEM only, and writes just the per-candidate delta. The
backward recomputes the tile (flash-attention-style) and accumulates the
weight gradients in a float32 VMEM accumulator across the grid sweep —
TPU grids are sequential, so revisiting the same output block is a safe
accumulation.

Two fusion boundaries are exposed:

- :func:`sparse_consensus_delta` — the narrow form: takes pre-gathered
  candidates ``[B, N_s, K, R]`` (saved as residuals for the backward).
- :func:`fused_candidate_delta` — the WIDENED round-trip form: takes the
  full ψ₂ output table ``[B, N_t, R]`` plus ``S_idx`` and folds the
  candidate gather into the custom_vjp. Residuals shrink to the table
  itself, the backward rematerializes the gather, and ``d_o_t`` reduces
  through one fused float32 segment-sum per iteration — the candidate
  tensor stops round-tripping HBM between forward and backward.

Mosaic layout note: the kernel never reshapes across the sublane axis
(``[TILE, K, R] -> [TILE*K, R]`` is an unsupported relayout). Instead the
candidate tensor arrives pre-flattened from XLA (``[B, N_s*K, R]``, a
free layout-preserving reshape) and the per-source expansion
``e -> e // K`` happens as a one-hot MXU matmul built from 2-D iotas.

Falls back to interpret mode off-TPU (tests run it on CPU).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dgmc_tpu.parallel.compat import shape_dtype_struct

TILE_S = 128


def _expand_mat(k_cand, tile, dtype):
    """One-hot ``[tile*K, tile]`` with ``E[e, t] = 1 iff e // K == t``."""
    e = jax.lax.broadcasted_iota(jnp.int32, (tile * k_cand, tile), 0)
    t = jax.lax.broadcasted_iota(jnp.int32, (tile * k_cand, tile), 1)
    return (e // k_cand == t).astype(dtype)


def _dot(a, b, contract=((1,), (0,)), prefer=jnp.float32):
    return jax.lax.dot_general(a, b, (contract, ((), ())),
                               preferred_element_type=prefer)


def _fwd_kernel(k_cand, o_s_ref, cand_ref, w1_ref, b1_ref, w2_ref, b2_ref,
                out_ref):
    o_s = o_s_ref[0]                          # [TILE, R]
    cand = cand_ref[0]                        # [TILE*K, R]
    ts = o_s.shape[0]
    expand = _expand_mat(k_cand, ts, o_s.dtype)
    # Mosaic matmuls accumulate in 32-bit; downcast the expansion once.
    d = (_dot(expand, o_s).astype(o_s.dtype) - cand)       # [TILE*K, R]
    h = jnp.maximum(_dot(d, w1_ref[...]) + b1_ref[0], 0.0)
    # Scalar extracts must be 32-bit on Mosaic; cast the bias first.
    b2 = b2_ref[...].astype(jnp.float32)[0, 0]
    out = _dot(h.astype(cand.dtype), w2_ref[...]) + b2
    out_ref[0] = out.astype(out_ref.dtype)                 # [TILE*K, 1]


def _bwd_kernel(k_cand, o_s_ref, cand_ref, w1_ref, b1_ref, w2t_ref, g_ref,
                d_os_ref, d_cand_ref, d_w1_ref, d_b1_ref, d_w2_ref,
                d_b2_ref):
    @pl.when((pl.program_id(0) == 0) & (pl.program_id(1) == 0))
    def _init():
        d_w1_ref[...] = jnp.zeros_like(d_w1_ref)
        d_b1_ref[...] = jnp.zeros_like(d_b1_ref)
        d_w2_ref[...] = jnp.zeros_like(d_w2_ref)
        d_b2_ref[...] = jnp.zeros_like(d_b2_ref)

    o_s = o_s_ref[0]                          # [TILE, R]
    cand = cand_ref[0]                        # [TILE*K, R]
    g = g_ref[0].astype(jnp.float32)          # [TILE*K, 1]
    ts = o_s.shape[0]
    w1 = w1_ref[...]
    w2t = w2t_ref[...]                        # [1, R]

    expand = _expand_mat(k_cand, ts, o_s.dtype)
    d = (_dot(expand, o_s).astype(o_s.dtype) - cand)       # [TILE*K, R]
    pre = _dot(d, w1) + b1_ref[0]                          # [TILE*K, R] f32
    h = jnp.maximum(pre, 0.0)
    # out = h @ w2 + b2; d_h[e, r] = g[e] * w2[r]
    d_h = g * w2t.astype(jnp.float32)                      # bcast [TILE*K,R]
    d_pre = jnp.where(pre > 0, d_h, 0.0)
    d_d = _dot(d_pre.astype(w1.dtype), w1,
               contract=((1,), (1,)))                      # [TILE*K, R] f32
    d_cand_ref[0] = (-d_d).astype(d_cand_ref.dtype)
    # d_os[t] = sum_{e: e//K == t} d_d[e] — the transposed expansion.
    d_os_ref[0] = _dot(expand, d_d.astype(expand.dtype),
                       contract=((0,), (0,))).astype(d_os_ref.dtype)


    # Weight-gradient partials accumulate in f32 across the whole grid.
    d_w1_ref[...] += _dot(d, d_pre.astype(d.dtype), contract=((0,), (0,)))
    d_b1_ref[...] += d_pre.sum(axis=0, keepdims=True)
    d_w2_ref[...] += _dot(h.astype(d.dtype), g.astype(d.dtype),
                          contract=((0,), (0,)))
    d_b2_ref[...] += g.sum()[None, None]


def _pad_rows(a, pad):
    return jnp.pad(a, ((0, 0), (0, pad)) + ((0, 0),) * (a.ndim - 2))


def _w_specs(R):
    return [
        pl.BlockSpec((R, R), lambda b, i: (0, 0), memory_space=pltpu.VMEM),
        pl.BlockSpec((1, R), lambda b, i: (0, 0), memory_space=pltpu.VMEM),
    ]


def _forward(o_s, cand, w1, b1, w2, b2, interpret):
    from dgmc_tpu.ops.pallas.dispatch import promote_vma, vma_union
    B, N_s, R = o_s.shape
    K = cand.shape[2]
    vma = vma_union(o_s, cand, w1, b1, w2, b2)
    o_s, cand, w1, b1, w2, b2 = promote_vma(vma, o_s, cand, w1, b1, w2, b2)
    pad = (-N_s) % TILE_S
    o_s_p = _pad_rows(o_s, pad)
    cand_p = _pad_rows(cand, pad).reshape(B, (N_s + pad) * K, R)
    grid = (B, (N_s + pad) // TILE_S)
    out = pl.pallas_call(
        functools.partial(_fwd_kernel, K),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, TILE_S, R), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE_S * K, R), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ] + _w_specs(R) + [
            pl.BlockSpec((R, 1), lambda b, i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda b, i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, TILE_S * K, 1), lambda b, i: (b, i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=shape_dtype_struct((B, (N_s + pad) * K, 1),
                                     jnp.float32, vma=vma),
        interpret=interpret,
    )(o_s_p, cand_p, w1, b1[None, :], w2, b2.reshape(1, 1))
    return out.reshape(B, N_s + pad, K)[:, :N_s]


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def sparse_consensus_delta(o_s, cand, w1, b1, w2, b2, interpret=False):
    """``relu((o_s[:, :, None] - cand) @ w1 + b1) @ w2 + b2`` →
    ``[B, N_s, K]`` float32, difference tensor never materialized."""
    return _forward(o_s, cand, w1, b1, w2, b2, interpret)


def sparse_consensus_delta_reference(o_s, cand, w1, b1, w2, b2):
    """Unfused jnp semantics (for tests / non-TPU paths)."""
    d = o_s[:, :, None, :] - cand
    h = jnp.maximum(jnp.einsum('bskr,rq->bskq', d, w1,
                               preferred_element_type=jnp.float32)
                    + b1, 0.0)
    out = jnp.einsum('bskq,qo->bsko', h.astype(w2.dtype), w2,
                     preferred_element_type=jnp.float32)
    return out[..., 0] + b2[0]


def _fwd(o_s, cand, w1, b1, w2, b2, interpret=False):
    out = _forward(o_s, cand, w1, b1, w2, b2, interpret)
    return out, (o_s, cand, w1, b1, w2, b2)


def _backward(o_s, cand, w1, b1, w2, b2, g, interpret):
    from dgmc_tpu.ops.pallas.dispatch import promote_vma, vma_union
    B, N_s, R = o_s.shape
    K = cand.shape[2]
    vma = vma_union(o_s, cand, w1, b1, w2, g)
    o_s, cand, w1, b1, w2, g = promote_vma(vma, o_s, cand, w1, b1, w2, g)
    pad = (-N_s) % TILE_S
    n_pad = N_s + pad
    o_s_p = _pad_rows(o_s, pad)
    cand_p = _pad_rows(cand, pad).reshape(B, n_pad * K, R)
    g_p = _pad_rows(g, pad).reshape(B, n_pad * K, 1)
    grid = (B, n_pad // TILE_S)
    f32 = jnp.float32
    d_os, d_cand, d_w1, d_b1, d_w2, d_b2 = pl.pallas_call(
        functools.partial(_bwd_kernel, K),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, TILE_S, R), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE_S * K, R), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ] + _w_specs(R) + [
            pl.BlockSpec((1, R), lambda b, i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE_S * K, 1), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, TILE_S, R), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE_S * K, R), lambda b, i: (b, i, 0),
                         memory_space=pltpu.VMEM),
            # Weight-grad accumulators: every grid step maps to the same
            # block; TPU grids run sequentially, so += is well-defined.
            pl.BlockSpec((R, R), lambda b, i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, R), lambda b, i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), lambda b, i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda b, i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            shape_dtype_struct((B, n_pad, R), o_s.dtype, vma=vma),
            shape_dtype_struct((B, n_pad * K, R), cand.dtype, vma=vma),
            shape_dtype_struct((R, R), f32, vma=vma),
            shape_dtype_struct((1, R), f32, vma=vma),
            shape_dtype_struct((R, 1), f32, vma=vma),
            shape_dtype_struct((1, 1), f32, vma=vma),
        ],
        interpret=interpret,
    )(o_s_p, cand_p, w1, b1[None, :], w2.reshape(1, R), g_p)
    return (d_os[:, :N_s], d_cand.reshape(B, n_pad, K, R)[:, :N_s],
            d_w1.astype(w1.dtype), d_b1[0].astype(b1.dtype),
            d_w2.astype(w2.dtype), d_b2[0].astype(b2.dtype))


def _bwd(interpret, res, g):
    return _backward(*res, g, interpret)


sparse_consensus_delta.defvjp(_fwd, _bwd)


# ---------------------------------------------------------------------------
# Widened fusion boundary: candidate gather folded into the kernel's VJP
# ---------------------------------------------------------------------------


def _gather_rows(o_t, S_idx):
    """``o_t[b, S_idx[b, s, k], :]`` → ``[B, N_s, K, R]`` (mode='clip':
    candidate ids come from top-k / negatives / GT injection, in-bounds by
    construction)."""
    B, N_s, K = S_idx.shape
    flat = jnp.take_along_axis(o_t, S_idx.reshape(B, N_s * K, 1), axis=1,
                               mode='clip')
    return flat.reshape(B, N_s, K, o_t.shape[-1])


@functools.partial(jax.custom_vjp, nondiff_argnums=(7,))
def fused_candidate_delta(o_s, o_t, S_idx, w1, b1, w2, b2, interpret=False):
    """Consensus delta with the candidate GATHER inside the fusion
    boundary: ``mlp(o_s[:, :, None] - o_t[S_idx])`` → ``[B, N_s, K]`` f32.

    Versus :func:`sparse_consensus_delta` (which receives pre-gathered
    candidates), this widens the differentiable unit across the
    gather→ψ₂-output round-trip:

    - the forward feeds the gathered rows straight into the tile kernel
      and saves ``(o_s, o_t, S_idx, weights)`` as residuals —
      ``O(B·N_t·R)`` instead of the ``O(B·N_s·K·R)`` candidate tensor
      the narrow kernel (and XLA's gather VJP) keeps live in HBM per
      iteration;
    - the backward REMATERIALIZES the gather (flash-attention-style, like
      the tile recompute inside the kernel) and reduces ``d_cand`` to
      ``d_o_t`` with one flat f32 segment-sum per iteration — exactly
      the scatter XLA's ``take_along_axis`` VJP would emit, but with the
      candidate tensor never saved across the forward/backward boundary.

    The f32-accumulation contract holds throughout: the kernel's logits
    and the ``d_o_t`` reduction accumulate in float32 regardless of the
    compute dtype (pinned by tests/models/test_precision.py).
    """
    return _forward(o_s, _gather_rows(o_t, S_idx), w1, b1, w2, b2,
                    interpret)


def fused_candidate_delta_reference(o_s, o_t, S_idx, w1, b1, w2, b2):
    """Unfused jnp semantics (tests / non-TPU paths)."""
    return sparse_consensus_delta_reference(o_s, _gather_rows(o_t, S_idx),
                                            w1, b1, w2, b2)


def _rt_fwd(o_s, o_t, S_idx, w1, b1, w2, b2, interpret=False):
    out = _forward(o_s, _gather_rows(o_t, S_idx), w1, b1, w2, b2, interpret)
    return out, (o_s, o_t, S_idx, w1, b1, w2, b2)


def _rt_bwd(interpret, res, g):
    o_s, o_t, S_idx, w1, b1, w2, b2 = res
    cand = _gather_rows(o_t, S_idx)                        # remat
    d_os, d_cand, d_w1, d_b1, d_w2, d_b2 = _backward(
        o_s, cand, w1, b1, w2, b2, g, interpret)
    B, N_s, K = S_idx.shape
    N_t = o_t.shape[1]
    acc = jnp.promote_types(o_t.dtype, jnp.float32)
    flat = d_cand.reshape(B, N_s * K, -1).astype(acc)

    def scat(c, idx):
        return jax.ops.segment_sum(c, idx, num_segments=N_t)

    d_o_t = jax.vmap(scat)(flat, S_idx.reshape(B, N_s * K)).astype(
        o_t.dtype)
    return d_os, d_o_t, None, d_w1, d_b1, d_w2, d_b2


fused_candidate_delta.defvjp(_rt_fwd, _rt_bwd)
