from dgmc_tpu.ops.pallas.consensus import (consensus_update,
                                           consensus_update_reference,
                                           fused_consensus_available)

__all__ = [
    'consensus_update',
    'consensus_update_reference',
    'fused_consensus_available',
]
