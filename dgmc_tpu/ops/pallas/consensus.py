"""Fused consensus-update kernel — the dense hot loop's memory wall.

Every consensus step of the dense variant computes
``S_hat += mlp(o_s[:, :, None, :] - o_t[:, None, :, :])`` (reference
``dgmc/models/dgmc.py:178-179``), where the broadcast difference tensor
``D`` has shape ``[B, N_s, N_t, R]`` — R times the size of the
correspondence matrix itself. XLA materializes it in HBM; at DBP15K scale
(15k x 20k x 32 floats) that's ~38 GB per step, the exact blow-up that
forces the reference onto its sparse path.

The Pallas kernel tiles ``(N_s, N_t)``, forms each ``[TILE_S, TILE_T, R]``
difference block in VMEM only, runs the 2-layer MLP on the MXU
(``[TILE_S*TILE_T, R] @ [R, R]`` then ``@ [R, 1]``), and writes the
``[TILE_S, TILE_T]`` result — HBM traffic drops from ``O(N_s*N_t*R)`` to
``O(N_s*N_t)``. The backward pass recomputes ``D`` tile-by-tile in a
``lax.scan`` (flash-attention-style rematerialization), so the gradient
never materializes ``D`` either.

Falls back to a pure-jnp path off-TPU (``interpret=True`` under tests).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dgmc_tpu.parallel.compat import shape_dtype_struct

TILE_S = 128
TILE_T = 128


def _mlp_tile(d, w1, b1, w2, b2):
    """2-layer MLP on a flattened difference tile. d: [S*T, R].

    The final contraction accumulates in float32 regardless of the compute
    dtype: the per-step delta is added to the f32 ``S_hat`` logits, and the
    unfused path / sparse kernel both emit f32 deltas
    (``preferred_element_type``) — the fused dense kernel must not be the
    one place a bf16 rounding sneaks into the logit accumulation."""
    h = jnp.maximum(d @ w1 + b1, 0.0)
    out = jax.lax.dot_general(h, w2, (((1,), (0,)), ((), ())),
                              preferred_element_type=jnp.float32)
    return out + b2.astype(jnp.float32)


def _consensus_kernel(o_s_ref, o_t_ref, w1_ref, b1_ref, w2_ref, b2_ref,
                      out_ref):
    o_s = o_s_ref[0]          # [TILE_S, R]
    o_t = o_t_ref[0]          # [TILE_T, R]
    ts, tt = o_s.shape[0], o_t.shape[0]
    d = (o_s[:, None, :] - o_t[None, :, :]).reshape(ts * tt, -1)
    out = _mlp_tile(d, w1_ref[:], b1_ref[0], w2_ref[:], b2_ref[0])
    out_ref[0] = out.reshape(ts, tt)


def _forward_pallas(o_s, o_t, w1, b1, w2, b2, interpret=False):
    from dgmc_tpu.ops.pallas.dispatch import promote_vma, vma_union
    B, N_s, R = o_s.shape
    N_t = o_t.shape[1]
    vma = vma_union(o_s, o_t, w1, b1, w2, b2)
    o_s, o_t, w1, b1, w2, b2 = promote_vma(vma, o_s, o_t, w1, b1, w2, b2)
    pad_s = (-N_s) % TILE_S
    pad_t = (-N_t) % TILE_T
    o_s_p = jnp.pad(o_s, ((0, 0), (0, pad_s), (0, 0)))
    o_t_p = jnp.pad(o_t, ((0, 0), (0, pad_t), (0, 0)))
    grid = (B, (N_s + pad_s) // TILE_S, (N_t + pad_t) // TILE_T)
    out = pl.pallas_call(
        _consensus_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, TILE_S, R), lambda b, i, j: (b, i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TILE_T, R), lambda b, i, j: (b, j, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R, R), lambda b, i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, R), lambda b, i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((R, 1), lambda b, i, j: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1), lambda b, i, j: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((1, TILE_S, TILE_T),
                               lambda b, i, j: (b, i, j),
                               memory_space=pltpu.VMEM),
        out_shape=shape_dtype_struct((B, N_s + pad_s, N_t + pad_t),
                                     jnp.float32, vma=vma),
        interpret=interpret,
    )(o_s_p, o_t_p, w1, b1[None, :], w2, b2[None, :])
    return out[:, :N_s, :N_t]


def consensus_update_reference(o_s, o_t, w1, b1, w2, b2):
    """Unfused jnp semantics (materializes D — for tests / CPU)."""
    d = o_s[:, :, None, :] - o_t[:, None, :, :]
    h = jnp.maximum(jnp.einsum('bstr,rq->bstq', d, w1) + b1, 0.0)
    return jnp.einsum('bstq,qo->bsto', h, w2)[..., 0] + b2[0]


@functools.partial(jax.custom_vjp, nondiff_argnums=(6,))
def consensus_update(o_s, o_t, w1, b1, w2, b2, interpret=False):
    """``mlp(o_s[:, :, None] - o_t[:, None, :])`` -> ``[B, N_s, N_t]``
    without materializing the difference tensor."""
    return _forward_pallas(o_s, o_t, w1, b1, w2, b2, interpret=interpret)


def _fwd(o_s, o_t, w1, b1, w2, b2, interpret=False):
    out = _forward_pallas(o_s, o_t, w1, b1, w2, b2, interpret=interpret)
    return out, (o_s, o_t, w1, b1, w2, b2)


def _bwd(interpret, res, g):
    """Tile-recompute backward: scan over target tiles; D is rebuilt per
    tile and never stored."""
    o_s, o_t, w1, b1, w2, b2 = res
    B, N_s, R = o_s.shape
    N_t = o_t.shape[1]

    pad = (-N_t) % TILE_T
    o_t_p = jnp.pad(o_t, ((0, 0), (0, pad), (0, 0)))
    g_p = jnp.pad(g, ((0, 0), (0, 0), (0, pad)))
    nblk = o_t_p.shape[1] // TILE_T
    o_t_blocks = jnp.moveaxis(
        o_t_p.reshape(B, nblk, TILE_T, R), 1, 0)          # [nblk,B,T,R]
    g_blocks = jnp.moveaxis(
        g_p.reshape(B, N_s, nblk, TILE_T), 2, 0)          # [nblk,B,S,T]

    # Carry and reduce gradient accumulators in float32 even under the
    # bf16 compute policy: the per-tile sums span B*S*T terms and the scan
    # accumulates across all target tiles — a bf16 running sum stops
    # absorbing addends once it is ~256x their size. One downcast at the
    # end matches the policy's "bf16 compute, f32 accumulation" contract.
    acc = jnp.promote_types(o_s.dtype, jnp.float32)

    def step(carry, inp):
        d_os, d_w1, d_b1, d_w2, d_b2 = carry
        o_t_b, g_b = inp                                   # [B,T,R], [B,S,T]
        d = o_s[:, :, None, :] - o_t_b[:, None, :, :]      # [B,S,T,R]
        pre = jnp.einsum('bstr,rq->bstq', d, w1) + b1
        h = jnp.maximum(pre, 0.0)
        # out = h @ w2 + b2
        d_h = g_b[..., None] * w2[:, 0]                    # [B,S,T,R]
        d_pre = jnp.where(pre > 0, d_h, 0.0)
        d_d = jnp.einsum('bstq,rq->bstr', d_pre, w1)
        d_os = d_os + d_d.sum(axis=2).astype(acc)
        d_ot_b = -d_d.sum(axis=1)                          # [B,T,R]
        d_w1 = d_w1 + jnp.einsum('bstr,bstq->rq', d, d_pre,
                                 preferred_element_type=acc)
        d_b1 = d_b1 + d_pre.astype(acc).sum(axis=(0, 1, 2))
        d_w2 = d_w2 + jnp.einsum('bstq,bst->q', h, g_b,
                                 preferred_element_type=acc)[:, None]
        d_b2 = d_b2 + g_b.astype(acc).sum()[None]
        return (d_os, d_w1, d_b1, d_w2, d_b2), d_ot_b

    zeros = (jnp.zeros(o_s.shape, acc), jnp.zeros(w1.shape, acc),
             jnp.zeros(b1.shape, acc), jnp.zeros(w2.shape, acc),
             jnp.zeros((1,), acc))
    (d_os, d_w1, d_b1, d_w2, d_b2), d_ot_blocks = jax.lax.scan(
        step, zeros, (o_t_blocks, g_blocks))
    d_ot = jnp.moveaxis(d_ot_blocks, 0, 1).reshape(B, -1, R)[:, :N_t]
    cast = lambda a, like: a.astype(like.dtype)  # noqa: E731
    return (cast(d_os, o_s), cast(d_ot, o_t), cast(d_w1, w1),
            cast(d_b1, b1), cast(d_w2, w2), cast(d_b2, b2))


consensus_update.defvjp(_fwd, _bwd)


def fused_consensus_available():
    """True when the default backend can run the compiled kernel."""
    return jax.default_backend() == 'tpu'
