"""Scatter-free routing for the sparse correspondence candidate set.

The sparse consensus loop's device pattern (reference
``dgmc/models/dgmc.py:204-223``) is, per iteration: project indicator
functions onto the target graph through the candidate set
(``r_t[t] += S[s, k] * r_s[s]`` for every candidate ``S_idx[s, k] == t`` —
a ``segment_sum``, i.e. a scatter-add), and gather the consensus
colourings back at the candidates (whose autodiff transpose is another
scatter-add). TPU has no fast scatter (measured ~1.2-3 ms per scatter op
regardless of payload, ``benchmarks/README.md``); it has a fast MXU.

``S_idx`` is **iteration-invariant** within one forward/backward: the
candidate search runs once per step, and the 10 consensus iterations plus
the whole backward pass all route through the same index set. So the
candidate set is sorted ONCE per step, on device, into node-range-aligned
blocks — the device-side analog of the host-side edge blocking in
``dgmc_tpu/ops/blocked.py`` — and every scatter the loop needs becomes a
blocked one-hot MXU contraction over that structure:

- :func:`sparse_project` (forward ``r_t`` projection): gather ``r_s`` rows
  at the blocked source ids, scale by the per-candidate ``S`` value, and
  contract with the ``[E_b, rows]`` one-hot routing tensor. The backward
  needs NO routing at all: in original ``[N_s, K]`` order the cotangent is
  ``d_r_t`` gathered at ``S_idx`` (a gather), reduced over ``K`` — every
  candidate of source row ``s`` lives in row ``s``.
- :func:`sparse_gather` (candidate gather with a matmul transpose): the
  forward is a plain ``take_along_axis`` row gather; the backward routes
  the cotangent rows through the blocked structure instead of emitting
  XLA's scatter-add gather-VJP.

The routing tensors depend only on ``S_idx``, so XLA CSEs one copy across
all consensus iterations AND both passes of a training step.

Static-shape blocking on device: after sorting candidates by target, the
entries of target range ``r`` (``rows`` consecutive target nodes) occupy
one contiguous run; each range takes ``ceil(count_r / E_b)`` blocks, and
the total is bounded by ``num_ranges + E // E_b`` blocks — the static
block count. Block start offsets derive from a ``searchsorted`` over the
per-range cumulative block counts; ragged tails are masked.
"""

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct

from dgmc_tpu.ops.blocked import _routed


@struct.dataclass
class CorrRoute:
    """Blocked routing structure for one candidate set ``S_idx [B, N_s, K]``.

    ``ent [B, NB, E_b]`` — flat candidate id (``s * K + k``) per blocked
    entry; ``src [B, NB, E_b]`` — its source row ``s``; ``dst_local`` /
    ``mask`` / ``range_id`` / ``rows`` / ``num_ranges`` — as in
    :class:`~dgmc_tpu.ops.blocked.EdgeBlocks`. ``n_t`` is the target node
    count (static).
    """
    ent: jnp.ndarray
    src: jnp.ndarray
    dst_local: jnp.ndarray
    mask: jnp.ndarray
    range_id: jnp.ndarray
    rows: int = struct.field(pytree_node=False)
    num_ranges: int = struct.field(pytree_node=False)
    n_t: int = struct.field(pytree_node=False)


def build_corr_route(S_idx, n_t, rows=128, block_entries=512):
    """Sort + block the candidate set on device; see module docstring.

    S_idx: ``[B, N_s, K]`` int32 target ids in ``[0, n_t)``. Entries from
    padded source rows may hold arbitrary valid ids — their contributions
    are zeroed by the ``S`` scale (forward) or a zero cotangent (backward),
    exactly as the segment-sum formulation they replace.
    """
    B, N_s, K = S_idx.shape
    E = N_s * K
    num_ranges = -(-n_t // rows)
    nb = num_ranges + E // block_entries
    eb = jnp.arange(block_entries, dtype=jnp.int32)

    def one(idx_flat):
        order = jnp.argsort(idx_flat, stable=True).astype(jnp.int32)
        sdst = idx_flat[order]
        bounds = jnp.arange(num_ranges + 1, dtype=jnp.int32) * rows
        starts = jnp.searchsorted(sdst, bounds, side='left').astype(
            jnp.int32)
        counts = starts[1:] - starts[:-1]                   # [NR]
        bpr = -(-counts // block_entries)                   # blocks per range
        cum = jnp.cumsum(bpr)
        j = jnp.arange(nb, dtype=jnp.int32)
        rid = jnp.searchsorted(cum, j, side='right').astype(jnp.int32)
        live = rid < num_ranges
        rid_c = jnp.minimum(rid, num_ranges - 1)
        prev = jnp.where(rid_c > 0, cum[rid_c - 1], 0)
        within = j - prev
        bstart = starts[rid_c] + within * block_entries
        nvalid = jnp.clip(counts[rid_c] - within * block_entries, 0,
                          block_entries)
        offs = jnp.clip(bstart[:, None] + eb[None, :], 0, E - 1)
        mask = (eb[None, :] < nvalid[:, None]) & live[:, None]
        ent = order[offs]
        loc = sdst[offs] - rid_c[:, None] * rows
        return ent, ent // K, jnp.clip(loc, 0, rows - 1), mask, rid_c

    ent, src, loc, mask, rid = jax.vmap(one)(S_idx.reshape(B, E))
    return CorrRoute(ent=ent, src=src, dst_local=loc, mask=mask,
                     range_id=rid, rows=rows, num_ranges=num_ranges,
                     n_t=n_t)


def _route_sum(table, idx, route, scale=None):
    """``out[b, t] = Σ_{entries e: dst_e = t} scale_e * table[b, idx_e]``
    as blocked one-hot contractions (no scatter)."""
    return _routed(table, idx, route.dst_local, route.mask, route.range_id,
                   route.rows, route.num_ranges, route.n_t, None,
                   scale=scale)


@jax.custom_vjp
def sparse_project(S, r_s, S_idx, route):
    """``r_t[b, t, :] = Σ_{s,k: S_idx[b,s,k]=t} S[b,s,k] * r_s[b,s,:]`` —
    the consensus indicator projection (reference
    ``dgmc/models/dgmc.py:211-213``) without materializing the
    ``[B, N_s, K, R]`` contribution tensor and without any scatter."""
    scale = jax.vmap(lambda s, e: jnp.take(s, e, mode='clip'))(
        S.reshape(S.shape[0], -1), route.ent)              # [B, NB, E_b]
    scale = jnp.where(route.mask, scale, 0.0)
    return _route_sum(r_s, route.src, route, scale=scale)


def _project_fwd(S, r_s, S_idx, route):
    return sparse_project(S, r_s, S_idx, route), (S, r_s, S_idx)


def _project_bwd(res, d_r_t):
    S, r_s, S_idx = res
    B, N_s, K = S_idx.shape
    # In original [N_s, K] order the transpose is gathers + a K-reduction:
    # d_S[s,k] = <d_r_t[S_idx[s,k]], r_s[s]>; d_r_s[s] = Σ_k S[s,k] * g[s,k].
    flat = S_idx.reshape(B, N_s * K)
    g = jnp.take_along_axis(d_r_t, flat[..., None], axis=1, mode='clip')
    g = g.reshape(B, N_s, K, -1)                           # [B, N_s, K, R]
    d_S = jnp.einsum('bskr,bsr->bsk', g, r_s)
    d_r_s = jnp.einsum('bsk,bskr->bsr', S, g)
    return d_S, d_r_s, None, None


sparse_project.defvjp(_project_fwd, _project_bwd)


@jax.custom_vjp
def sparse_gather(feat, S_idx, route):
    """``feat[b, S_idx[b, s, k], :]`` — the candidate-row gather (reference
    ``dgmc/models/dgmc.py:205,216``) whose backward is a blocked one-hot
    contraction instead of XLA's scatter-add gather-VJP."""
    B, N_s, K = S_idx.shape
    flat = jnp.take_along_axis(feat, S_idx.reshape(B, N_s * K)[..., None],
                               axis=1, mode='clip')
    return flat.reshape(B, N_s, K, feat.shape[-1])


def _gather_fwd(feat, S_idx, route):
    return sparse_gather(feat, S_idx, route), (route,)


def _gather_bwd(res, g):
    (route,) = res
    B = g.shape[0]
    table = g.reshape(B, -1, g.shape[-1])                  # [B, E, R]
    return _route_sum(table, route.ent, route), None, None


sparse_gather.defvjp(_gather_fwd, _gather_bwd)
