from dgmc_tpu.ops.graph import (GraphBatch, gather_nodes, scatter_to_nodes,
                                degree)
from dgmc_tpu.ops.softmax import masked_softmax
from dgmc_tpu.ops.segment import segment_sum, segment_mean
from dgmc_tpu.ops.topk import chunked_topk, dense_topk
from dgmc_tpu.ops.spline import open_spline_basis

__all__ = [
    'GraphBatch',
    'gather_nodes',
    'scatter_to_nodes',
    'degree',
    'masked_softmax',
    'segment_sum',
    'segment_mean',
    'chunked_topk',
    'dense_topk',
    'open_spline_basis',
]
