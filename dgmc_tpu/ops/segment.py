"""Segment reductions — the TPU-native replacement for ``torch_scatter``.

The reference consumes ``torch_scatter.scatter_add`` (reference
``dgmc/models/dgmc.py:3,212``) and mean-aggregation inside every PyG
``MessagePassing`` layer (reference ``dgmc/models/rel.py:9``). On TPU these
become XLA segment reductions, which lower to efficient one-hot matmuls or
scatters that XLA can fuse with their producers.

All functions take a static ``num_segments`` so shapes stay known to the
compiler.
"""

import jax
import jax.numpy as jnp


def segment_sum(data, segment_ids, num_segments):
    """Sum ``data`` rows into ``num_segments`` buckets per ``segment_ids``.

    data: ``[E, ...]``, segment_ids: ``[E]`` int32; returns
    ``[num_segments, ...]``.
    """
    return jax.ops.segment_sum(data, segment_ids, num_segments=num_segments)


def segment_mean(data, segment_ids, num_segments, weights=None):
    """Mean-reduce ``data`` rows per segment.

    ``weights`` (optional ``[E]`` float, e.g. an edge-validity mask) scales
    each row's contribution and the denominator; empty segments yield zeros.
    """
    if weights is not None:
        data = data * weights[..., None]
        counts = segment_sum(weights, segment_ids, num_segments)
    else:
        counts = segment_sum(jnp.ones(segment_ids.shape, data.dtype),
                             segment_ids, num_segments)
    totals = segment_sum(data, segment_ids, num_segments)
    return totals / jnp.maximum(counts, 1.0)[..., None]
