"""Masked softmax over padded correspondence scores.

Mirrors the reference's ``masked_softmax`` (reference
``dgmc/models/dgmc.py:15-19``: fill ``-inf`` outside the mask, softmax, zero
outside the mask) but is safe for fully-masked rows (padded source nodes),
which would produce NaNs in a naive implementation.
"""

import jax.numpy as jnp


def masked_softmax(src, mask, axis=-1):
    """Softmax of ``src`` along ``axis`` restricted to ``mask``.

    Entries outside ``mask`` get probability 0. Rows with no valid entry
    return all zeros instead of NaN.
    """
    neg = jnp.finfo(src.dtype).min
    masked = jnp.where(mask, src, neg)
    m = jnp.max(masked, axis=axis, keepdims=True)
    # Guard fully-masked rows: their max is `neg`; shift so exp() is finite.
    m = jnp.where(jnp.isfinite(m), m, 0.0)
    e = jnp.exp(masked - m) * mask.astype(src.dtype)
    denom = jnp.sum(e, axis=axis, keepdims=True)
    return e / jnp.maximum(denom, jnp.finfo(src.dtype).tiny)
