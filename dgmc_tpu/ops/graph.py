"""Padded, statically-shaped graph batches — the TPU-native data model.

The reference keeps graphs ragged (flat node lists + a ``batch`` vector,
densified on demand via ``to_dense_batch``, reference
``dgmc/models/dgmc.py:154-158``; collation with ``__inc__`` edge-index
offsets, reference ``dgmc/utils/data.py:9-16``). XLA wants static shapes, so
here the padded representation *is* the representation: every batch of graphs
lives in ``[B, N, ...]`` / ``[B, E, ...]`` arrays with boolean validity
masks, and edge endpoints are graph-local indices. ``to_dense_batch`` and
``Batch`` collation therefore vanish from the device path entirely — they
happen once, host-side, at dataset build time (see
``dgmc_tpu/utils/data.py``).
"""

from typing import Optional

import jax
import jax.numpy as jnp
from flax import struct


@struct.dataclass
class GraphBatch:
    """A batch of ``B`` graphs padded to ``N`` nodes and ``E`` edges each.

    Attributes:
        x: ``[B, N, C]`` node features (zeros at padding).
        senders: ``[B, E]`` int32 graph-local source node of each edge.
        receivers: ``[B, E]`` int32 graph-local target node of each edge.
        node_mask: ``[B, N]`` bool, True at real nodes.
        edge_mask: ``[B, E]`` bool, True at real edges. Padded edges point at
            node 0 and must be masked out of every aggregation.
        edge_attr: optional ``[B, E, D]`` edge features (pseudo-coordinates
            for SplineCNN).
        blocks_in / blocks_out: optional blocked-adjacency structure
            (``dgmc_tpu/ops/blocked.py``) for scatter-free MXU
            aggregation at large graph sizes; attach host-side via
            ``dgmc_tpu.ops.blocked.attach_blocks``.
    """
    x: jnp.ndarray
    senders: jnp.ndarray
    receivers: jnp.ndarray
    node_mask: jnp.ndarray
    edge_mask: jnp.ndarray
    edge_attr: Optional[jnp.ndarray] = None
    blocks_in: Optional[object] = None
    blocks_out: Optional[object] = None

    @property
    def num_graphs(self):
        return self.x.shape[0]

    @property
    def num_nodes(self):
        return self.x.shape[1]

    @property
    def num_edges(self):
        return self.senders.shape[1]

    def replace_x(self, x):
        return self.replace(x=x)

    def astype(self, dtype):
        g = self.replace(x=self.x.astype(dtype))
        if self.edge_attr is not None:
            g = g.replace(edge_attr=self.edge_attr.astype(dtype))
        return g


def gather_nodes(x, idx):
    """Batched node gather: ``x[b, idx[b, e]]``.

    x: ``[B, N, C]``, idx: ``[B, E]`` → ``[B, E, C]``.

    ``mode='clip'``: edge endpoints are host-built and in-bounds (padded
    edges point at node 0 under ``edge_mask=False``); the default 'fill'
    mode would append a select_n pass over every gathered row.
    """
    return jnp.take_along_axis(x, idx[..., None], axis=1, mode='clip')


def scatter_to_nodes(messages, receivers, edge_mask, num_nodes, aggr='sum'):
    """Batched edge→node aggregation (the ``MessagePassing`` reduce step).

    messages: ``[B, E, C]``, receivers: ``[B, E]``, edge_mask: ``[B, E]``.
    Returns ``[B, N, C]``. ``aggr`` is ``'sum'`` or ``'mean'`` (masked; empty
    neighborhoods give zeros, matching PyG's behavior the reference relies
    on).
    """
    out_dtype = messages.dtype
    # Accumulate reductions in float32 even under the bf16 compute policy:
    # a bf16 running sum stops absorbing contributions once it is ~256x
    # any addend. One downcast at the end matches the policy's
    # "bf16 compute, f32 accumulation" contract everywhere else
    # (ops/blocked.py, the Pallas kernels, MaskedBatchNorm).
    acc = jnp.promote_types(out_dtype, jnp.float32)
    messages = jnp.where(edge_mask[..., None], messages, 0).astype(acc)

    def one(m, r):
        return jax.ops.segment_sum(m, r, num_segments=num_nodes)

    out = jax.vmap(one)(messages, receivers)
    if aggr == 'mean':
        deg = degree(receivers, edge_mask, num_nodes)
        out = out / jnp.maximum(deg, 1.0)[..., None]
    elif aggr != 'sum':
        raise ValueError(f'Unknown aggregation: {aggr!r}')
    return out.astype(out_dtype)


def degree(receivers, edge_mask, num_nodes):
    """Masked in-degree per node: ``[B, E]`` → ``[B, N]`` float."""

    def one(r, m):
        return jax.ops.segment_sum(m.astype(jnp.float32), r,
                                   num_segments=num_nodes)

    return jax.vmap(one)(receivers, edge_mask)
