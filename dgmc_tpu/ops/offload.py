"""Host-RAM offload tier: corpus tables in host memory, device chunks
streamed through an N-deep prefetch ring.

The streamed-S layout (``ops/topk.streamed_topk`` + ``parallel/``)
bounds per-device memory at ``O(chunk x block)`` for the SEARCH, but the
corpus ψ₁ embedding table itself still had to live on device. This
module removes that last O(corpus) device resident: the table stays in
host RAM (pinned where the platform supports it), and a
:class:`PrefetchRing` keeps the next ``depth`` source chunks in flight
to the device while the current chunk's per-tile top-k computes — the
host-side face of the double-buffered chunk loop, driven at the same
chunk boundaries. The result shortlist (the "cold" sparse S rows)
streams straight back to host through async device-to-host copies, so
the on-device working set is ``O(depth x chunk)`` whatever the corpus
size — the mechanism the 10M-row SCALE_r08 leg rides, and the same one
the serving embedding cache (ROADMAP item 1) will reuse.

Two layers:

- :class:`PrefetchRing` — generic host→device chunk ring: slot ``i``
  lands on ``devices[i % n]`` (round-robin over every addressable
  device: chunks are row-independent, so the ring is also the
  data-parallel dispatch), ``get(i)`` serves chunk ``i`` and tops the
  window back up to ``depth`` in-flight puts, evicting everything
  behind the cursor. ``jax.device_put`` is async, so the transfers
  genuinely overlap compute the host has already dispatched.
- :func:`offloaded_streamed_topk` — the chunk-streamed candidate search
  driven from the host against a ring-fed corpus, **bit-identical** to
  :func:`~dgmc_tpu.ops.topk.streamed_topk` on the same inputs (same
  per-chunk programs in the same order; tie order included), returning
  host-resident results plus an :class:`OffloadStats` account.

``python -m dgmc_tpu.ops.offload`` is the scale driver: it builds a
synthetic corpus of ``--rows`` ψ₁ embeddings host-side, shortlists it
against ``--targets`` device-resident targets through the ring, records
through the standard obs stack (one ``RunObserver`` step per chunk,
the per-chunk executable's ``memory_analysis`` as the static per-device
memory bound), verifies a prefix against the in-device path, and prints
one JSON summary line — the offloaded leg of ``benchmarks/
scale_bench.py``.
"""

import dataclasses
import functools
import time
from typing import Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from dgmc_tpu.ops.topk import DEFAULT_BLOCK

__all__ = ['DEFAULT_PREFETCH_DEPTH', 'PrefetchRing', 'OffloadStats',
           'offloaded_streamed_topk', 'offloaded_corpus_topk', 'main']

#: Measured default (benchmarks/DISPATCH_DEFAULTS.md, offload section):
#: depth 2 already hides the host→device copy behind the per-chunk
#: search on this container (ring misses only on the cold start), and
#: deeper rings just hold more device memory for the same wall clock —
#: the on-device working set is O(depth x chunk).
DEFAULT_PREFETCH_DEPTH = 2


def _pinned_put(x, device):
    """``jax.device_put`` onto ``device``; when the platform exposes a
    ``pinned_host`` memory space (TPU runtimes — this container's CPU
    backend does not), corpus staging buffers could additionally be
    pinned there; the portable path commits straight to the device."""
    import jax
    return jax.device_put(x, device)


class PrefetchRing:
    """N-deep host→device prefetch ring over a chunked host table.

    ``source`` is either a host array whose leading axis is the chunk
    axis, or a callable ``i -> host chunk`` (for tables too big or too
    lazy to materialize at once; ``n_chunks`` is then required).
    ``get(i)`` must be called with a non-decreasing cursor: it returns
    chunk ``i`` on ``devices[i % len(devices)]``, issues the puts for
    ``i+1 .. i+depth``, and evicts every slot behind the cursor — at
    most ``depth + 1`` chunks are device-resident per sweep, whatever
    the corpus size.
    """

    def __init__(self, source: Union[np.ndarray, Callable[[int], np.ndarray]],
                 depth: int = DEFAULT_PREFETCH_DEPTH,
                 n_chunks: Optional[int] = None,
                 devices: Optional[Sequence] = None):
        import jax
        self._fn = (source.__getitem__ if hasattr(source, '__getitem__')
                    else source)
        if n_chunks is None:
            if not hasattr(source, 'shape'):
                raise ValueError('n_chunks is required for a callable '
                                 'source')
            n_chunks = source.shape[0]
        self.n_chunks = int(n_chunks)
        self.depth = max(1, int(depth))
        # Addressable devices only: device_put to a remote host's
        # device raises — a multi-process caller gets its local slice.
        self.devices = list(devices or jax.local_devices())
        self._slots: Dict[int, object] = {}
        self.puts = 0
        self.misses = 0
        self.evictions = 0

    def _issue(self, i):
        if i < self.n_chunks and i not in self._slots:
            self._slots[i] = _pinned_put(
                self._fn(i), self.devices[i % len(self.devices)])
            self.puts += 1

    def get(self, i: int):
        """Device chunk ``i`` (its put issued now on a cold miss), with
        the window ``i+1 .. i+depth`` re-armed and slots behind the
        cursor evicted."""
        if i not in self._slots:
            self.misses += 1
            self._issue(i)
        out = self._slots[i]
        for j in range(i + 1, min(i + 1 + self.depth, self.n_chunks)):
            self._issue(j)
        for j in [j for j in self._slots if j < i]:
            del self._slots[j]
            self.evictions += 1
        return out

    @property
    def in_flight(self) -> int:
        return len(self._slots)


@dataclasses.dataclass
class OffloadStats:
    """The account one offloaded sweep returns (and the obs artifacts
    record): what lived where, and how the ring behaved."""
    rows: int
    chunks: int
    chunk: int
    prefetch_depth: int
    devices: int
    host_resident_bytes: int        # corpus + results, host RAM
    bytes_streamed: int             # corpus bytes moved host->device
    ring_misses: int                # chunks served cold (no prefetch)
    ring_evictions: int
    wall_s: float

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


def offloaded_streamed_topk(h_s_host, h_t, k, chunk,
                            t_mask=None, block=DEFAULT_BLOCK,
                            depth: int = DEFAULT_PREFETCH_DEPTH,
                            devices: Optional[Sequence] = None,
                            on_chunk: Optional[Callable[[int], None]] = None):
    """Chunk-streamed top-k with the source table in HOST memory.

    Bit-identical to ``streamed_topk(h_s, h_t, k, chunk, ...)`` run on
    device (``tests/ops/test_offload.py``): the same ``_chunked_topk``
    program scores the same chunks in the same order — the ring only
    changes WHERE each chunk waits. Returns
    ``(vals, idx, OffloadStats)`` with ``vals``/``idx`` as host numpy
    ``[B, N_s, k]`` (the shortlist streams back through async
    device→host copies as it is produced — at most ``depth`` chunk
    results ride the device at once).

    ``devices`` round-robins chunks across several devices (rows are
    independent, so the ring doubles as data-parallel dispatch);
    ``on_chunk`` fires after each chunk's dispatch — the obs step hook.
    """
    import jax

    from dgmc_tpu.ops.topk import _chunked_topk, _tile_sort

    h_s_host = np.asarray(h_s_host)
    B, N_s, C = h_s_host.shape
    chunk = int(chunk)
    devices = list(devices or jax.local_devices())
    n_chunks = -(-N_s // chunk)
    sort_tiles = _tile_sort()

    def host_chunk(i):
        piece = h_s_host[:, i * chunk:(i + 1) * chunk]
        if piece.shape[1] < chunk:     # ragged tail: padded, like the
            piece = np.pad(            # in-graph scan's padded rows
                piece, ((0, 0), (0, chunk - piece.shape[1]), (0, 0)))
        return piece

    ring = PrefetchRing(host_chunk, depth=depth, n_chunks=n_chunks,
                        devices=devices)
    # The target side is the small, hot operand: one replica per device,
    # placed up front.
    per_dev_t = [jax.device_put(h_t, d) for d in devices]
    per_dev_m = (None if t_mask is None
                 else [jax.device_put(t_mask, d) for d in devices])

    vals = np.empty((B, n_chunks * chunk, k), h_s_host.dtype)
    idx = np.empty((B, n_chunks * chunk, k), np.int32)
    pending: List = []          # (chunk index, device vals, device idx)

    def drain(limit):
        while len(pending) > limit:
            i, dv, di = pending.pop(0)
            vals[:, i * chunk:(i + 1) * chunk] = np.asarray(dv)
            idx[:, i * chunk:(i + 1) * chunk] = np.asarray(di)

    t0 = time.perf_counter()
    for i in range(n_chunks):
        d = i % len(devices)
        dv, di = _chunked_topk(ring.get(i), per_dev_t[d], k,
                               None if per_dev_m is None else per_dev_m[d],
                               block, True, False, sort_tiles)
        # Start the device->host copy immediately; materialize lazily so
        # at most `depth` chunk results are ever device-resident.
        for a in (dv, di):
            if hasattr(a, 'copy_to_host_async'):
                a.copy_to_host_async()
        pending.append((i, dv, di))
        drain(depth)
        if on_chunk is not None:
            on_chunk(i)
    drain(0)
    wall = time.perf_counter() - t0

    vals, idx = vals[:, :N_s], idx[:, :N_s]
    stats = OffloadStats(
        rows=N_s, chunks=n_chunks, chunk=chunk, prefetch_depth=depth,
        devices=len(devices),
        host_resident_bytes=h_s_host.nbytes + vals.nbytes + idx.nbytes,
        bytes_streamed=ring.puts * B * chunk * C * h_s_host.itemsize,
        ring_misses=ring.misses, ring_evictions=ring.evictions,
        wall_s=round(wall, 3))
    return vals, idx, stats


@functools.lru_cache(maxsize=None)
def _corpus_merge(k, block, sort_tiles):
    """One cached jitted merge step per (k, block, extractor) config:
    chunk-local top-k (the exact in-graph per-tile programs) folded into
    the running carry, carry first so lower target indices win ties.
    Cached at module scope so a SERVING process re-running the sweep per
    query reuses one executable instead of re-jitting per call."""
    import jax
    import jax.numpy as jnp

    from dgmc_tpu.ops.topk import _chunked_topk

    @jax.jit
    def merge(run_vals, run_idx, hs, ht_c, m_c, start):
        cv, ci = _chunked_topk(hs, ht_c, k, m_c, block, True, False,
                               sort_tiles)
        ci = start + ci
        all_v = jnp.concatenate([run_vals, cv], axis=-1)
        all_i = jnp.concatenate([run_idx, ci], axis=-1)
        nv, pos = jax.lax.top_k(all_v, k)
        return nv, jnp.take_along_axis(all_i, pos, axis=-1)

    return merge


def offloaded_corpus_topk(h_s, h_t_host, k, chunk, t_mask=None,
                          block=DEFAULT_BLOCK,
                          depth: int = DEFAULT_PREFETCH_DEPTH,
                          device=None,
                          on_chunk: Optional[Callable[[int], None]] = None):
    """Top-k candidate search with the TARGET (corpus) table in host RAM.

    The mirror image of :func:`offloaded_streamed_topk`: there the big
    table is the *source* side streamed in row chunks against a
    device-resident target; here the queries (``h_s``, small) live on
    device and the CORPUS ``h_t_host`` streams through the
    :class:`PrefetchRing` in **target**-axis chunks, each merged into a
    running per-row top-k carry — the serving layout
    (``dgmc_tpu/serve/``), where a query is a handful of rows and the
    corpus is the thing bigger than a chip.

    Bit-identical to ``chunked_topk(h_s, h_t, k, t_mask, block)`` on the
    same inputs, tie order included (``tests/serve/test_offload_corpus.
    py``): every chunk runs the SAME per-tile programs over the same
    tiles in the same target order, and the cross-chunk merge
    concatenates the running carry *first* so earlier target indices
    keep winning ties exactly like the in-graph scan. Masked / padded
    columns score ``finfo.min`` with their true index and unfilled
    carry slots stay ``(-inf, idx 0)``, both matching the device path's
    degenerate orderings.

    Returns host-numpy ``(vals, idx, OffloadStats)`` with
    ``vals``/``idx`` shaped ``[B, N_s, k]``.
    """
    import jax

    from dgmc_tpu.ops.topk import _tile_sort

    h_t_host = np.asarray(h_t_host)
    B, N_t, C = h_t_host.shape
    chunk = int(chunk)
    n_chunks = -(-N_t // chunk)
    sort_tiles = _tile_sort()
    device = device or jax.local_devices()[0]
    h_s = jax.device_put(h_s, device)
    mask_host = (None if t_mask is None else np.asarray(t_mask))

    def host_chunk(i):
        piece = h_t_host[:, i * chunk:(i + 1) * chunk]
        if piece.shape[1] < chunk:
            piece = np.pad(
                piece, ((0, 0), (0, chunk - piece.shape[1]), (0, 0)))
        return piece

    def chunk_mask(i):
        lo = i * chunk
        m = np.zeros((B, chunk), bool)
        n = min(chunk, N_t - lo)
        m[:, :n] = True if mask_host is None else mask_host[:, lo:lo + n]
        return m

    ring = PrefetchRing(host_chunk, depth=depth, n_chunks=n_chunks,
                        devices=[device])

    merge = _corpus_merge(k, block, sort_tiles)
    N_s = h_s.shape[1]
    run_vals = jax.device_put(
        np.full((B, N_s, k), -np.inf, h_t_host.dtype), device)
    run_idx = jax.device_put(np.zeros((B, N_s, k), np.int32), device)

    t0 = time.perf_counter()
    for i in range(n_chunks):
        run_vals, run_idx = merge(
            run_vals, run_idx, h_s, ring.get(i),
            jax.device_put(chunk_mask(i), device), np.int32(i * chunk))
        if on_chunk is not None:
            on_chunk(i)
    vals = np.asarray(run_vals)
    idx = np.asarray(run_idx)
    wall = time.perf_counter() - t0
    stats = OffloadStats(
        rows=N_t, chunks=n_chunks, chunk=chunk, prefetch_depth=depth,
        devices=1,
        host_resident_bytes=h_t_host.nbytes + vals.nbytes + idx.nbytes,
        bytes_streamed=ring.puts * B * chunk * C * h_t_host.itemsize,
        ring_misses=ring.misses, ring_evictions=ring.evictions,
        wall_s=round(wall, 3))
    return vals, idx, stats


# ---------------------------------------------------------------------------
# CLI: the offloaded-corpus scale driver (scale_bench's offload leg)
# ---------------------------------------------------------------------------


def _synthetic_corpus(rows, dim, seed, batch=1 << 20):
    """Host-side synthetic ψ₁ embedding table, built in bounded pieces
    (a 2^23 x C normal draw in one call would transiently double the
    table)."""
    rng = np.random.RandomState(seed)
    out = np.empty((1, rows, dim), np.float32)
    for start in range(0, rows, batch):
        n = min(batch, rows - start)
        out[0, start:start + n] = rng.randn(n, dim).astype(np.float32)
    return out


def main(argv=None):
    import argparse
    import json
    import sys

    parser = argparse.ArgumentParser(
        prog='python -m dgmc_tpu.ops.offload',
        description='Offloaded-corpus shortlist driver: host-RAM ψ₁ '
                    'table, N-deep device prefetch ring, chunk-streamed '
                    'top-k across every device — the ≥2^23-row '
                    'SCALE_r08 offload leg.')
    parser.add_argument('--rows', type=int, default=1 << 23,
                        help='corpus rows (source entities)')
    parser.add_argument('--targets', type=int, default=1 << 17)
    parser.add_argument('--dim', type=int, default=16)
    parser.add_argument('--k', type=int, default=10)
    parser.add_argument('--chunk', type=int, default=1 << 15)
    parser.add_argument('--block', type=int, default=8192)
    parser.add_argument('--prefetch-depth', '--prefetch_depth',
                        dest='prefetch_depth', type=int,
                        default=DEFAULT_PREFETCH_DEPTH)
    parser.add_argument('--seed', type=int, default=8)
    parser.add_argument('--verify-rows', dest='verify_rows', type=int,
                        default=1 << 12,
                        help='leading corpus rows re-shortlisted '
                             'through the fully device-resident '
                             'streamed path and compared exactly '
                             '(0 = skip)')
    from dgmc_tpu.obs import add_obs_flag
    add_obs_flag(parser)
    args = parser.parse_args(argv)

    import jax

    from dgmc_tpu.obs import RunObserver
    from dgmc_tpu.obs.memory import compiled_memory
    from dgmc_tpu.ops.topk import _chunked_topk, _tile_sort, streamed_topk

    obs = RunObserver(args.obs_dir,
                      watchdog_deadline_s=args.watchdog_deadline,
                      obs_port=args.obs_port)
    devices = jax.local_devices()
    rng = np.random.RandomState(args.seed + 1)
    corpus = _synthetic_corpus(args.rows, args.dim, args.seed)
    h_t = rng.randn(1, args.targets, args.dim).astype(np.float32)

    # Static per-device memory evidence: the per-chunk search executable
    # is the ONLY device program this driver runs — its memory_analysis
    # bound IS the per-device static footprint (the corpus never lands).
    probe = np.zeros((1, args.chunk, args.dim), np.float32)
    lowered = jax.jit(
        lambda a, b: _chunked_topk(a, b, args.k, None, args.block, True,
                                   False, _tile_sort())).lower(probe, h_t)
    mem = compiled_memory(lowered.compile()) or {}
    if mem:
        obs.log(0, event='aot_memory_offload_chunk', **mem)
        print(f'# per-chunk executable static memory: '
              f'{mem["total_bytes"] / 2**30:.3f} GiB per device',
              file=sys.stderr, flush=True)

    t0 = time.time()
    stepper = {'cm': None}

    def chunk_step(i):
        # One observer step per chunk: step p50 over chunks is the
        # ring's sustained service time.
        if stepper['cm'] is not None:
            stepper['cm'].__exit__(None, None, None)
        stepper['cm'] = obs.step()
        stepper['cm'].__enter__()

    chunk_step(-1)
    vals, idx, stats = offloaded_streamed_topk(
        corpus, h_t, args.k, args.chunk, block=args.block,
        depth=args.prefetch_depth, devices=devices, on_chunk=chunk_step)
    if stepper['cm'] is not None:
        stepper['cm'].__exit__(None, None, None)
    wall = time.time() - t0

    verified = None
    if args.verify_rows:
        n = min(args.verify_rows, args.rows)
        dv, di = streamed_topk(
            np.ascontiguousarray(corpus[:, :n]), h_t, args.k, args.chunk,
            block=args.block, pallas=False, return_values=True)
        verified = bool(np.array_equal(np.asarray(di), idx[:, :n])
                        and np.array_equal(np.asarray(dv), vals[:, :n]))

    rec = {
        'metric': 'offloaded_shortlist',
        'rows': args.rows, 'targets': args.targets, 'dim': args.dim,
        'k': args.k, 'chunk': args.chunk, 'block': args.block,
        'devices': len(devices),
        'wall_s': round(wall, 1),
        'rows_per_sec': round(args.rows / max(stats.wall_s, 1e-9), 1),
        'offload': stats.to_json(),
        'per_device_static_bytes': mem or None,
        'verified_rows': None if verified is None else
        min(args.verify_rows, args.rows),
        'verified_equal': verified,
    }
    obs.log(stats.chunks, event='offload_summary',
            offload_equal=None if verified is None else float(verified),
            host_resident_bytes=stats.host_resident_bytes,
            prefetch_depth=stats.prefetch_depth,
            ring_misses=stats.ring_misses)
    obs.snapshot_memory('offload')
    obs.close()
    print(json.dumps(rec))
    return 0 if (verified is not False) else 1


if __name__ == '__main__':
    import sys
    sys.exit(main())
