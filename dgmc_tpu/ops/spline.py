"""Closed-form open B-spline basis — the ``torch_spline_conv`` replacement.

The reference's ``SplineCNN`` backbone delegates to the C++/CUDA
``torch_spline_conv`` kernel via PyG's ``SplineConv`` (reference
``dgmc/models/spline.py:4,21``; degree-1 open splines, ``kernel_size=5`` per
pseudo-coordinate dimension). For degree 1 the basis is closed-form: each
dimension has exactly two active knots with hat-function weights
``(1 - frac, frac)``, so an edge activates ``2^D`` of the ``K^D`` kernel
weight matrices with product weights. That is a handful of elementwise ops —
no custom kernel needed for the basis itself; the heavy lifting (weighting
node features with the basis) is laid out as a single MXU matmul in
``dgmc_tpu/models/spline.py``.
"""

import itertools

import jax.numpy as jnp


def open_spline_basis(pseudo, kernel_size, degree=1):
    """Degree-1 open B-spline basis over pseudo-coordinates in ``[0, 1]``.

    Args:
        pseudo: ``[..., D]`` edge pseudo-coordinates.
        kernel_size: knots per dimension (the reference uses 5).
        degree: only 1 is supported (the reference never uses another).

    Returns:
        ``(basis, combo_idx)`` with shapes ``[..., 2**D]``: the product basis
        weight of each active knot combination and its flattened index into
        the ``K**D`` kernel weight axis (dimension 0 has stride 1, matching
        a C-order enumeration ``idx = sum_d knot_d * K**d``).
    """
    if degree != 1:
        raise NotImplementedError('Only degree-1 (linear) open B-splines are '
                                  'supported, as in the reference.')
    K = kernel_size
    D = pseudo.shape[-1]

    p = jnp.clip(pseudo, 0.0, 1.0) * (K - 1)
    lo = jnp.clip(jnp.floor(p), 0, K - 2).astype(jnp.int32)   # [..., D]
    frac = p - lo                                             # in [0, 1]

    w = jnp.stack([1.0 - frac, frac], axis=-1)                # [..., D, 2]
    knot = jnp.stack([lo, lo + 1], axis=-1)                   # [..., D, 2]

    combos = list(itertools.product((0, 1), repeat=D))        # 2^D tuples
    basis_terms = []
    idx_terms = []
    for combo in combos:
        bw = jnp.ones(pseudo.shape[:-1], dtype=pseudo.dtype)
        fi = jnp.zeros(pseudo.shape[:-1], dtype=jnp.int32)
        for d, c in enumerate(combo):
            bw = bw * w[..., d, c]
            fi = fi + knot[..., d, c] * (K ** d)
        basis_terms.append(bw)
        idx_terms.append(fi)
    basis = jnp.stack(basis_terms, axis=-1)                   # [..., 2^D]
    combo_idx = jnp.stack(idx_terms, axis=-1)                 # [..., 2^D]
    return basis, combo_idx
