"""Measured-runtime attribution: what the hardware actually did.

Every performance claim the repo makes about the consensus loop and the
streamed-S chunk loop is either host-side step timing
(:class:`~dgmc_tpu.obs.run.RunObserver`) or a *static* model
(``analysis/hlo_sched`` overlap, ``hlo_liveness`` peaks, ``obs/cost``
FLOPs). This module closes the loop with the **measured** account, read
from the profiler artifacts every CLI can already capture with
``--profile-dir`` (``jax.profiler.trace``'s
``plugins/profile/<session>/*.trace.json.gz`` trace-event export):

- **Per-stage device wall-clock**: device-track op slices attributed to
  the pipeline stages (``psi1`` / ``initial_corr`` / ``topk`` /
  ``consensus_iter`` / ``psi2`` / ``loss`` / ``optimizer``) through the
  SAME ``jax.named_scope`` paths already pinned in lowered HLO — the
  static cost model and the measured one share a vocabulary
  (:mod:`dgmc_tpu.obs.trace_events`).
- **Comm-vs-compute occupancy and measured overlap**: busy-time unions
  of collective vs non-collective device slices; the measured overlap
  fraction is comm∩compute over comm — the runtime counterpart of
  ``hlo_sched``'s dependency-permitted fraction.
- **Idle/gap analysis**: device idle inside the profiled window
  (device waiting on host) and host time blocked in fetches/
  ``block_until_ready`` (host waiting on device).
- **Static-vs-measured reconciliation**: measured MFU from per-step
  device-active time vs ``obs/cost``'s host-step-time MFU, measured
  overlap vs the schedule model's modeled fraction — with the
  divergence itself reported as a diagnostic, because "the model says
  0.1353 and the silicon delivered 0.04" is exactly the finding the
  ROADMAP's overlap items need.

Results land as the ``attribution.json`` artifact; headline fields
merge into ``efficiency.json`` (``measured`` block + top-level
``measured_overlap_fraction`` / ``measured_mfu`` / ``idle_fraction``)
so ``obs.report`` renders them and ``obs.diff`` gates on them
(``--min-measured-overlap``, ``--max-idle-regression``).

Graceful degradation is a contract, not an accident: on a device-less
capture (this CPU container) the parser reports host-track attribution
and marks every device field **unavailable** — named in the
``unavailable`` list — rather than fabricating zeros, and exits 0.

Usage::

    python -m dgmc_tpu.obs.attribution <profile-dir>  --obs-dir RUN
    python -m dgmc_tpu.obs.attribution <obs-dir>            # host trace
    dgmc-obs-attribution <profile-dir|obs-dir> [--json]

No jax import anywhere: like report/diff, this must run on a box that
only has the artifacts.
"""

import argparse
import json
import os
import sys

from dgmc_tpu.obs.observe import read_json_artifact as _read_json
from dgmc_tpu.obs.trace_events import (STAGE_NAMES, TraceParseError,
                                       build_tracks, event_stage,
                                       find_profiler_traces,
                                       intersect_intervals, is_comm_event,
                                       is_host_wait_event, merge_intervals,
                                       read_trace_file, sum_intervals)

__all__ = [
    'SCHEMA_VERSION', 'STEP_ANNOTATION', 'attribute_events',
    'reconcile', 'build_attribution', 'merge_into_efficiency',
    'render_attribution', 'main',
]

#: attribution.json schema version (pinned by the strict schema test).
SCHEMA_VERSION = 1

#: Name of the per-step profiler annotation the CLIs emit inside the
#: capture window (``jax.profiler.StepTraceAnnotation`` via
#: ``RunObserver.step``): the parser counts these slices to normalize
#: device-active time per step. The obs host trace's ``cat: 'step'``
#: spans serve the same role in host-trace mode.
STEP_ANNOTATION = 'dgmc_step'

#: Device-side fields that go in the ``unavailable`` list when the
#: capture has no device tracks (the CPU-container degradation path).
_DEVICE_FIELDS = (
    'stages[device]', 'occupancy.device_active_s',
    'occupancy.device_idle_s', 'occupancy.device_idle_fraction',
    'occupancy.compute_busy_s', 'occupancy.comm_busy_s',
    'occupancy.overlapped_s', 'occupancy.measured_overlap_fraction',
    'per_step.device_active_s', 'reconciliation.measured_mfu',
    'reconciliation.measured_overlap_fraction',
)


def _r(v, nd=6):
    return None if v is None else round(v, nd)


def _is_step_slice(name, args):
    return name == STEP_ANNOTATION or args.get('cat') == 'step'


def _stage_table(tracks):
    """Per-stage wall-clock over a track set: merged-union seconds per
    stage (nesting/async overlap collapses), event counts, and the
    share of the summed stage wall-clock. Step-annotation spans are
    bookkeeping, not stage work, and are excluded."""
    per_stage = {}
    for tr in tracks:
        for ts, dur, name, args in tr.slices:
            if _is_step_slice(name, args):
                continue
            st = event_stage(name, args)
            row = per_stage.setdefault(st, {'intervals': [], 'events': 0})
            row['intervals'].append((ts, ts + dur))
            row['events'] += 1
    walls = {st: sum_intervals(merge_intervals(row['intervals'])) / 1e6
             for st, row in per_stage.items()}
    total = sum(walls.values())
    table = {}
    for st in (*STAGE_NAMES, 'other'):
        if st not in per_stage:
            continue
        table[st] = {
            'wall_s': _r(walls[st]),
            'events': per_stage[st]['events'],
            'share': _r(walls[st] / total, 4) if total else 0.0,
        }
    return table


def attribute_events(payloads):
    """The measured account from parsed trace payloads (one per host).

    Returns a dict with ``device_available``, ``window_s``, ``steps``,
    ``stages`` (+ ``stage_source``), ``occupancy``, ``per_step``,
    ``tracks`` and ``unavailable`` — every device field ``None`` (and
    named in ``unavailable``) when the capture has no device tracks,
    never a fabricated zero.
    """
    tracks = []
    for p in payloads:
        tracks.extend(build_tracks(p.get('traceEvents', [])))
    device = [t for t in tracks if t.device]
    host = [t for t in tracks if not t.device]

    bounds = [(ts, ts + dur) for t in tracks for ts, dur, _, _ in t.slices]
    window_us = (max(e for _, e in bounds) - min(s for s, _ in bounds)) \
        if bounds else 0.0
    window_s = window_us / 1e6

    # -- step windows (profiler annotations or host-trace step spans) --
    step_ivs = [(ts, ts + dur)
                for t in tracks for ts, dur, name, args in t.slices
                if _is_step_slice(name, args)]
    steps = None
    if step_ivs:
        merged_steps = merge_intervals(step_ivs)
        steps = {
            'observed': len(step_ivs),
            'wall_s': _r(sum_intervals(merged_steps) / 1e6),
            'mean_s': _r(sum_intervals(merged_steps) / 1e6
                         / len(step_ivs)),
        }

    # -- device side -------------------------------------------------------
    occupancy = {
        'window_s': _r(window_s),
        'device_active_s': None,
        'device_idle_s': None,
        'device_idle_fraction': None,
        'compute_busy_s': None,
        'comm_busy_s': None,
        'overlapped_s': None,
        'measured_overlap_fraction': None,
        'host_busy_s': None,
        'host_wait_s': None,
        'host_wait_fraction': None,
        'idle_fraction': None,
        'idle_source': None,
    }
    per_step = None
    unavailable = []
    if device:
        dev_ivs, comp_ivs, comm_ivs = [], [], []
        for t in device:
            for ts, dur, name, args in t.slices:
                if _is_step_slice(name, args):
                    continue
                iv = (ts, ts + dur)
                dev_ivs.append(iv)
                (comm_ivs if is_comm_event(name, args)
                 else comp_ivs).append(iv)
        dev_u = merge_intervals(dev_ivs)
        comp_u = merge_intervals(comp_ivs)
        comm_u = merge_intervals(comm_ivs)
        active = sum_intervals(dev_u) / 1e6
        comm = sum_intervals(comm_u) / 1e6
        overlapped = sum_intervals(
            intersect_intervals(comm_u, comp_u)) / 1e6
        occupancy.update(
            device_active_s=_r(active),
            device_idle_s=_r(max(window_s - active, 0.0)),
            device_idle_fraction=_r(
                max(1.0 - active / window_s, 0.0) if window_s else 0.0,
                4),
            compute_busy_s=_r(sum_intervals(comp_u) / 1e6),
            comm_busy_s=_r(comm),
            overlapped_s=_r(overlapped),
            # None, not 0, when the window moved nothing between
            # devices: an overlap fraction over zero communication is
            # undefined, and 0.0 would read as "fully serialized".
            measured_overlap_fraction=(_r(overlapped / comm, 4)
                                       if comm else None),
        )
        if steps and active:
            per_step = {
                'device_active_s': _r(active / steps['observed']),
                'steps': steps['observed'],
            }
    else:
        unavailable.extend(_DEVICE_FIELDS)

    # -- host side ---------------------------------------------------------
    if host:
        # Profiler step ANNOTATIONS are bookkeeping, not host work —
        # each covers its whole step, so counting them would pin host
        # busy at 100% and blind the idle gate (the device path
        # excludes them too). The obs run-trace's cat:'step' spans DO
        # count: there they are the host-activity signal itself.
        host_ivs = [(ts, ts + dur)
                    for t in host for ts, dur, name, _ in t.slices
                    if name != STEP_ANNOTATION]
        wait_ivs = [(ts, ts + dur)
                    for t in host for ts, dur, name, _ in t.slices
                    if is_host_wait_event(name)]
        busy = sum_intervals(merge_intervals(host_ivs)) / 1e6
        wait = sum_intervals(merge_intervals(wait_ivs)) / 1e6
        occupancy.update(
            host_busy_s=_r(busy),
            host_wait_s=_r(wait),
            host_wait_fraction=_r(wait / window_s, 4) if window_s
            else 0.0)

    # One comparable idle headline per run: device idle when measured,
    # host idle otherwise — with the source named so obs.diff refuses
    # to compare a device-idle run against a host-idle one (the same
    # contract as the memory row).
    if occupancy['device_idle_fraction'] is not None:
        occupancy['idle_fraction'] = occupancy['device_idle_fraction']
        occupancy['idle_source'] = 'device'
    elif occupancy['host_busy_s'] is not None and window_s:
        occupancy['idle_fraction'] = _r(
            max(1.0 - occupancy['host_busy_s'] / window_s, 0.0), 4)
        occupancy['idle_source'] = 'host'

    stage_source = None
    stages = {}
    if device:
        stages = _stage_table(device)
        stage_source = 'device'
    elif host:
        stages = _stage_table(host)
        stage_source = 'host'

    return {
        'device_available': bool(device),
        'window_s': _r(window_s),
        'steps': steps,
        'stages': stages,
        'stage_source': stage_source,
        'occupancy': occupancy,
        'per_step': per_step,
        'tracks': [
            {'process': t.process, 'thread': t.thread,
             'device': t.device, 'events': len(t.slices),
             'busy_s': _r(sum_intervals(t.busy_intervals()) / 1e6)}
            for t in tracks],
        'unavailable': unavailable,
    }


def _static_headline(efficiency, key):
    """The static account's headline value for ``key`` — the shared
    :func:`dgmc_tpu.obs.cost.headline_of` convention, so the two sides
    of the reconciliation pick the same program ``obs.report``
    summarizes."""
    from dgmc_tpu.obs.cost import headline_of
    return headline_of(efficiency, key)


def reconcile(account, efficiency, timings=None):
    """Static-vs-measured reconciliation block.

    Static side: ``efficiency.json`` — ``obs/cost``'s FLOPs +
    host-step-time MFU and ``analysis/hlo_sched``'s modeled overlap
    fraction. Measured side: the trace account. Divergence fields are
    deliberately signed diagnostics, not gates — the gates live in
    ``obs.diff`` where thresholds are explicit.
    """
    eff = efficiency or {}
    occ = account.get('occupancy') or {}
    per_step = account.get('per_step') or {}
    rec = {
        'static_mfu': eff.get('mfu'),
        'measured_mfu': None,
        'mfu_ratio': None,
        'static_overlap_fraction': _static_headline(
            eff, 'overlap_fraction'),
        'measured_overlap_fraction': occ.get(
            'measured_overlap_fraction'),
        'overlap_divergence': None,
        'host_step_p50_s': ((timings or {}).get('steps') or {}).get(
            'p50_s'),
        'device_step_active_s': per_step.get('device_active_s'),
        'notes': [],
    }
    flops = _static_headline(eff, 'flops')
    peak = eff.get('peak_flops')
    dev_step = per_step.get('device_active_s')
    if flops and peak and dev_step:
        # MFU against device-ACTIVE time: utilization of the cycles
        # the chip actually spent, next to cost.py's utilization of
        # the host-observed step (which also pays dispatch + idle).
        rec['measured_mfu'] = float(f'{flops / (dev_step * peak):.4g}')
        if rec['static_mfu']:
            rec['mfu_ratio'] = _r(
                rec['measured_mfu'] / rec['static_mfu'], 4)
            rec['notes'].append(
                f'measured MFU {rec["measured_mfu"]:.4g} over device-'
                f'active time vs {rec["static_mfu"]:.4g} over host '
                f'step time: the gap is dispatch + device idle')
    if rec['measured_overlap_fraction'] is not None \
            and rec['static_overlap_fraction'] is not None:
        rec['overlap_divergence'] = _r(
            rec['measured_overlap_fraction']
            - rec['static_overlap_fraction'], 4)
        rec['notes'].append(
            f'measured overlap {rec["measured_overlap_fraction"]:.4f} '
            f'vs dependency-permitted '
            f'{rec["static_overlap_fraction"]:.4f}: the schedule '
            f'realized {rec["overlap_divergence"]:+.4f} of the model')
    return rec


# ---------------------------------------------------------------------------
# Artifact assembly
# ---------------------------------------------------------------------------




def _is_obs_dir(path):
    return os.path.isdir(path) and any(
        os.path.exists(os.path.join(path, name))
        for name in ('timings.json', 'metrics.jsonl', 'trace.json'))


def build_attribution(path, obs_dir=None):
    """Assemble the ``attribution.json`` payload for ``path`` (a
    ``--profile-dir`` capture or an obs dir).

    Profiler trace exports win when present; otherwise the obs dir's
    host-side ``trace.json`` is the (host-only) source. ``obs_dir``
    supplies the static account (``efficiency.json`` / ``timings.json``)
    for the reconciliation block; when ``path`` itself is an obs dir it
    defaults to it. Returns ``(payload, obs_dir)``; raises
    :class:`TraceParseError` only when NO source at all is readable.
    """
    errors = []
    trace_files = find_profiler_traces(path)
    if obs_dir is None and _is_obs_dir(path):
        obs_dir = path
    payloads, parsed_files = [], []
    for tf in trace_files:
        try:
            payloads.append(read_trace_file(tf))
            parsed_files.append(tf)
        except TraceParseError as e:
            # One corrupt per-host export must not discard the others:
            # record the reason, attribute what parsed.
            errors.append(str(e))
    source_kind = 'profiler'
    host_trace = os.path.join(obs_dir, 'trace.json') if obs_dir else None
    if not payloads:
        source_kind = 'host-trace'
        if host_trace and os.path.exists(host_trace):
            try:
                payloads.append(read_trace_file(host_trace))
                parsed_files.append(host_trace)
            except TraceParseError as e:
                errors.append(str(e))
        if not payloads:
            raise TraceParseError(
                path, 'no readable profiler trace export '
                      '(plugins/profile/*/*.trace.json.gz) and no '
                      'host-side trace.json'
                      + (f'; errors: {"; ".join(errors)}'
                         if errors else ''))
    account = attribute_events(payloads)
    occ = account['occupancy']
    if occ.get('idle_source') == 'host' and source_kind == 'host-trace':
        # Host idle from the obs run trace (gaps between step/section
        # spans) and host idle from a profiler capture (python-tracer
        # coverage) are different quantities: name the source so
        # obs.diff refuses to compare them, the same way it refuses
        # device-vs-host memory peaks.
        occ['idle_source'] = 'host-trace'
    payload = {
        'schema': SCHEMA_VERSION,
        'source': {
            'kind': source_kind,
            'path': os.fspath(path),
            'trace_files': parsed_files,
            'obs_dir': obs_dir,
        },
        'errors': errors,
        **account,
        'reconciliation': None,
    }
    if obs_dir:
        efficiency = _read_json(os.path.join(obs_dir, 'efficiency.json'))
        timings = _read_json(os.path.join(obs_dir, 'timings.json'))
        if efficiency or timings:
            payload['reconciliation'] = reconcile(
                account, efficiency, timings)
    return payload, obs_dir


def merge_into_efficiency(obs_dir, payload):
    """Merge the measured headline into ``<obs_dir>/efficiency.json``.

    The full measured account lands under a ``measured`` block;
    headline fields (``measured_overlap_fraction``, ``measured_mfu``,
    ``device_idle_fraction``, ``idle_fraction``/``idle_source``) merge
    top-level ONLY when actually measured — an unavailable device
    field stays absent so ``obs.report``/``obs.diff`` see "no
    account", never a fabricated zero. Idempotent: a rerun replaces
    the measured block wholesale. Existing run rows are preserved
    verbatim (the same contract as ``obs.cost --obs-dir``).
    """
    path = os.path.join(obs_dir, 'efficiency.json')
    eff = _read_json(path) or {'programs': {}}
    occ = payload.get('occupancy') or {}
    rec = payload.get('reconciliation') or {}
    eff['measured'] = {
        'device_available': payload.get('device_available'),
        'source': payload.get('source'),
        'steps': payload.get('steps'),
        'occupancy': occ,
        'per_step': payload.get('per_step'),
        'reconciliation': payload.get('reconciliation'),
        'unavailable': payload.get('unavailable', []),
    }
    for key, value in (
            ('measured_overlap_fraction',
             occ.get('measured_overlap_fraction')),
            ('measured_mfu', rec.get('measured_mfu')),
            ('device_idle_fraction', occ.get('device_idle_fraction')),
            ('idle_fraction', occ.get('idle_fraction')),
            ('idle_source', occ.get('idle_source'))):
        if value is not None:
            eff[key] = value
        else:
            # A rerun that LOST a measurement must also lose the stale
            # headline — obs.diff's lost-account rule needs absence to
            # mean absence.
            eff.pop(key, None)
    os.makedirs(obs_dir, exist_ok=True)
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(eff, f, indent=1)
    os.replace(tmp, path)
    return eff


def _fmt_s(v):
    from dgmc_tpu.obs.observe import fmt_seconds
    return fmt_seconds(v)


def render_attribution(payload):
    """Human-readable account (shared with ``obs.report``'s render)."""
    lines = ['== measured-runtime attribution ==']
    src = payload.get('source') or {}
    lines.append(f'  source           {src.get("kind")} '
                 f'({len(src.get("trace_files") or [])} trace file(s))')
    if not payload.get('device_available'):
        lines.append('  ** no device tracks in this capture: host-track '
                     'attribution only; device fields unavailable **')
    if payload.get('errors'):
        for err in payload['errors']:
            lines.append(f'  parse error      {err}')
    occ = payload.get('occupancy') or {}
    lines.append(f'  window           {_fmt_s(occ.get("window_s"))}')
    steps = payload.get('steps')
    if steps:
        lines.append(f'  steps observed   {steps["observed"]} '
                     f'(mean {_fmt_s(steps.get("mean_s"))})')
    if occ.get('device_active_s') is not None:
        lines.append(
            f'  device active    {_fmt_s(occ["device_active_s"])} '
            f'(idle {occ.get("device_idle_fraction", 0):.2%} of '
            f'window)')
        lines.append(
            f'  compute / comm   {_fmt_s(occ.get("compute_busy_s"))} / '
            f'{_fmt_s(occ.get("comm_busy_s"))}')
        if occ.get('measured_overlap_fraction') is not None:
            lines.append(f'  measured overlap '
                         f'{_fmt_s(occ.get("overlapped_s"))} = '
                         f'{occ["measured_overlap_fraction"]:.4f} '
                         f'of comm time')
    if occ.get('host_busy_s') is not None:
        lines.append(
            f'  host busy / wait {_fmt_s(occ["host_busy_s"])} / '
            f'{_fmt_s(occ.get("host_wait_s"))}')
    if occ.get('idle_fraction') is not None:
        lines.append(f'  idle fraction    {occ["idle_fraction"]:.2%} '
                     f'[{occ.get("idle_source")}]')
    stages = payload.get('stages') or {}
    if stages:
        lines.append(f'  -- per-stage wall-clock '
                     f'[{payload.get("stage_source")}] --')
        lines.append(f'  {"stage":<16} {"wall":>12} {"share":>8} '
                     f'{"events":>8}')
        for st, row in stages.items():
            lines.append(f'  {st:<16} {_fmt_s(row["wall_s"]):>12} '
                         f'{row["share"]:>8.2%} {row["events"]:>8}')
    rec = payload.get('reconciliation')
    if rec:
        lines.append('  -- static vs measured --')
        for label, key in (('MFU (static)', 'static_mfu'),
                           ('MFU (measured)', 'measured_mfu'),
                           ('overlap (static)',
                            'static_overlap_fraction'),
                           ('overlap (measured)',
                            'measured_overlap_fraction')):
            v = rec.get(key)
            lines.append(f'  {label:<18} '
                         f'{v if v is not None else "unavailable"}')
        for note in rec.get('notes', []):
            lines.append(f'    {note}')
    if payload.get('unavailable'):
        lines.append('  unavailable      '
                     + ', '.join(payload['unavailable']))
    return '\n'.join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='python -m dgmc_tpu.obs.attribution',
        description='Measured-runtime attribution from a --profile-dir '
                    'capture (or an obs dir\'s host trace): per-stage '
                    'device wall-clock, measured overlap, idle '
                    'analysis, static-vs-measured reconciliation. '
                    'Writes attribution.json and merges the headline '
                    'into efficiency.json.')
    parser.add_argument('path',
                        help='a --profile-dir capture root (or one '
                             'profiler session dir), or an obs dir')
    parser.add_argument('--obs-dir', '--obs_dir', dest='obs_dir',
                        default=None,
                        help='obs run directory supplying the static '
                             'account (efficiency.json/timings.json) '
                             'and receiving attribution.json + the '
                             'efficiency merge (default: PATH when it '
                             'is an obs dir)')
    parser.add_argument('--out', default=None,
                        help='write attribution.json here instead of '
                             '<obs-dir>/attribution.json')
    parser.add_argument('--json', action='store_true',
                        help='print the machine-readable payload')
    args = parser.parse_args(argv)

    if not os.path.exists(args.path):
        print(f'attribution: no such path: {args.path}', file=sys.stderr)
        return 2
    try:
        payload, obs_dir = build_attribution(args.path,
                                             obs_dir=args.obs_dir)
    except TraceParseError as e:
        print(f'attribution: {e}', file=sys.stderr)
        return 2

    out_path = args.out
    if out_path is None:
        root = obs_dir if obs_dir else os.fspath(args.path)
        out_path = os.path.join(root, 'attribution.json') \
            if os.path.isdir(root) else root
    tmp = out_path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(payload, f, indent=1)
    os.replace(tmp, out_path)

    if obs_dir:
        merge_into_efficiency(obs_dir, payload)

    if args.json:
        print(json.dumps(payload, indent=1))
    else:
        print(render_attribution(payload))
        print(f'  -> {out_path}'
              + (f' (efficiency.json merged in {obs_dir})'
                 if obs_dir else ''))
    return 0


if __name__ == '__main__':
    sys.exit(main())
