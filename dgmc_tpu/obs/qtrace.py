"""Per-query tracing and tail-latency attribution for the serve path.

``SERVE_r01`` made the serving worker's p50/p95 a *number*; this module
makes it an *account*. Every ``/match`` request carries a trace id —
accepted from a W3C-style ``traceparent`` header or minted server-side,
deterministically from the worker seed — and decomposes into the fixed
span vocabulary :data:`SERVE_SPAN_NAMES` (``admission_queue_wait``,
``bucket_resolve``, ``pad_and_stage``, ``device_execute``,
``shortlist_merge``, ``consensus_rerank``, ``serialize``). The spans
that wrap device work map onto the SAME model-stage vocabulary the
static cost account and the profiler attribution use
(:data:`~dgmc_tpu.analysis.hlo_comm.SERVE_SPAN_STAGES` →
:data:`~dgmc_tpu.analysis.hlo_comm.STAGE_NAMES`): static, measured and
served planes reconcile, no third dialect. :meth:`QueryTrace.span`
REJECTS names outside the vocabulary at record time — the pin is
enforced where drift would start, not just in a test.

Retention is deterministic and bounded, because a serving worker must
hold its account over millions of queries in O(1) memory:

- **slowest-K reservoir** — the K slowest queries are always kept
  (min-heap on total latency); the tail is the point of the exercise.
- **every error** — kept in its own bounded ring with an explicit
  truncation counter; an error trace is never lost to sampling.
- **deterministic sample** of the rest — keep iff
  ``hash(seed, trace_id) < sample_rate``: a fixed seed replays to an
  identical kept-set, so two runs of the bench disagree about nothing.

Kept span trees land in a bounded ``qtrace.jsonl`` (rewritten
atomically from the in-memory rings, so the file size is bounded by
construction) next to a ``qtrace_summary.json`` carrying the
*full-population* per-stage :class:`~dgmc_tpu.obs.live.
StreamingHistogram` account — every query feeds the histograms even
when its span tree is sampled out. The same histograms export through
``/metrics`` (``dgmc_query_stage_seconds{stage=...}``), and an optional
SLO hook hands breaching span trees to the flight recorder.

``python -m dgmc_tpu.obs.qtrace <obs-dir>`` renders the report:
per-stage p50/p95/p99 and the p95−p50 gap attributed to a named
dominant stage, plus Chrome trace-event export
(:func:`chrome_trace_events`) viewable side by side with profiler
traces through the same ``obs.trace_events`` parser.

jax-free (stdlib + the import-light obs/analysis helpers): the report
runs in monitor processes and CI without a backend bring-up.
"""

import argparse
import collections
import hashlib
import heapq
import json
import math
import os
import re
import threading
import time
from contextlib import contextmanager

from dgmc_tpu.analysis.hlo_comm import (SERVE_SPAN_NAMES,
                                        SERVE_SPAN_STAGES)
from dgmc_tpu.obs.live import StreamingHistogram, histogram_family
from dgmc_tpu.obs.observe import percentile
from dgmc_tpu.utils.io import write_json_atomic

__all__ = ['QueryTrace', 'QueryTracer', 'parse_traceparent',
           'format_traceparent', 'chrome_trace_events', 'load_records',
           'stage_percentiles', 'gap_attribution', 'render_report',
           'main', 'SERVE_SPAN_NAMES', 'SERVE_SPAN_STAGES',
           'QTRACE_LATENCY_BOUNDS']

#: Per-stage latency histogram bounds (seconds): ×1.25 rungs from
#: 0.1 ms to ~130 s. Serve spans live in the sub-ms..second range the
#: 2× step ladder (``DEFAULT_LATENCY_BOUNDS``) is too coarse for — a
#: p95−p50 gap attribution needs quantile error bounded by 25 %, not
#: 100 %.
QTRACE_LATENCY_BOUNDS = tuple(0.0001 * 1.25 ** i for i in range(64))

_TRACEPARENT = re.compile(
    r'^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$')


def parse_traceparent(header):
    """``(trace_id, parent_span_id)`` from a W3C ``traceparent`` header;
    ``None`` when absent or malformed. A bad header mints a fresh trace
    instead of failing the query — trace plumbing must never cost a
    match answer."""
    if not header:
        return None
    m = _TRACEPARENT.match(str(header).strip().lower())
    if not m or m.group(2) == '0' * 32 or m.group(3) == '0' * 16:
        return None
    return m.group(2), m.group(3)


def format_traceparent(trace_id, span_id, sampled=True):
    """Render the W3C header the service echoes back (version 00)."""
    return f'00-{trace_id}-{span_id}-{"01" if sampled else "00"}'


class QueryTrace:
    """One in-flight query's span tree.

    Spans are recorded flat as ``(name, start_s, dur_s)`` relative to
    the trace start; the tree structure is the fixed pipeline order of
    :data:`SERVE_SPAN_NAMES` under one root, so a flat list loses
    nothing. Names outside the vocabulary raise — the no-third-dialect
    pin, enforced at record time.
    """

    def __init__(self, trace_id, span_id, seq, parent_id=None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.seq = int(seq)
        self.parent_id = parent_id
        self.start_unix = time.time()
        self._t0 = time.perf_counter()
        self.spans = []
        self.total_s = None

    @contextmanager
    def span(self, name):
        """Time one serve stage; records even when the body raises (an
        error trace with its partial span tree is exactly the trace
        worth keeping)."""
        if name not in SERVE_SPAN_STAGES:
            raise ValueError(
                f'unknown serve span {name!r}; the vocabulary is '
                f'{SERVE_SPAN_NAMES} (dgmc_tpu.analysis.hlo_comm)')
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.spans.append((name, t0 - self._t0,
                               time.perf_counter() - t0))

    def record(self, name, start_s, dur_s):
        """Append one pre-timed span (manual instrumentation and the
        determinism tests; same vocabulary pin as :meth:`span`)."""
        if name not in SERVE_SPAN_STAGES:
            raise ValueError(
                f'unknown serve span {name!r}; the vocabulary is '
                f'{SERVE_SPAN_NAMES} (dgmc_tpu.analysis.hlo_comm)')
        self.spans.append((name, float(start_s), float(dur_s)))

    def close(self, total_s=None):
        """Stop the end-to-end clock (idempotent; ``total_s`` overrides
        the wall measurement — the tests' synthetic-latency hook)."""
        if total_s is not None:
            self.total_s = float(total_s)
        elif self.total_s is None:
            self.total_s = time.perf_counter() - self._t0
        return self.total_s

    def stage_ms(self):
        """Per-span-name total milliseconds (a name instrumented twice
        — e.g. host pad + device staging both under ``pad_and_stage`` —
        sums), the ``stages_ms`` payload field clients read."""
        out = {}
        for name, _start, dur in self.spans:
            out[name] = out.get(name, 0.0) + dur * 1e3
        return {k: round(v, 4) for k, v in out.items()}

    def response_traceparent(self):
        return format_traceparent(self.trace_id, self.span_id)


class QueryTracer:
    """Bounded, deterministic per-query trace retention for one worker.

    Args:
        path: ``qtrace.jsonl`` destination (``None`` disables the file
            tier; histograms and counters still run). The summary lands
            beside it as ``qtrace_summary.json``.
        sample_rate: keep fraction for non-error, non-reservoir traces,
            decided by ``hash(seed, trace_id)`` — deterministic, not
            ``random()``.
        slowest_k: always-keep reservoir size (min-heap on total
            latency).
        capacity: sampled-ring bound; with the error ring and the
            reservoir this bounds ``qtrace.jsonl`` at
            ``capacity + error_capacity + slowest_k`` records.
        error_capacity: error-ring bound. Errors are never *sampled*
            out; past the bound the OLDEST are evicted and counted
            (``errors_truncated``), never silently.
        seed: the worker seed — trace-id minting and sampling both
            derive from it, so a fixed seed replays an identical
            kept-set.
        slo_s: end-to-end SLO; a breaching query fires ``on_breach``
            with its record (the service wires this to a flight-
            recorder dump carrying the offending span tree).
    """

    def __init__(self, path=None, sample_rate=0.05, slowest_k=8,
                 capacity=256, error_capacity=256, seed=0, slo_s=None,
                 on_breach=None, bounds=QTRACE_LATENCY_BOUNDS,
                 flush_interval_s=1.0):
        if not 0.0 <= float(sample_rate) <= 1.0:
            raise ValueError(f'sample_rate must be in [0, 1]: '
                             f'{sample_rate}')
        self.path = path
        self.sample_rate = float(sample_rate)
        self.slowest_k = max(0, int(slowest_k))
        self.capacity = max(0, int(capacity))
        self.error_capacity = max(1, int(error_capacity))
        self.seed = int(seed)
        self.slo_s = None if slo_s is None else float(slo_s)
        self.on_breach = on_breach
        self.flush_interval_s = float(flush_interval_s)
        self._lock = threading.Lock()
        self._seq = 0
        self._queries = 0
        self._errors_seen = 0
        self._slo_breaches = 0
        self._kept = collections.Counter()
        self._sampled = collections.deque(maxlen=self.capacity)
        self._errors = collections.deque(maxlen=self.error_capacity)
        self._slowest = []          # min-heap of (total_s, seq, record)
        self._hist_total = StreamingHistogram(bounds)
        self._hist_stage = {name: StreamingHistogram(bounds)
                            for name in SERVE_SPAN_NAMES}
        self._dirty = False
        self._last_flush = 0.0

    @property
    def summary_path(self):
        if not self.path:
            return None
        return os.path.join(os.path.dirname(self.path) or '.',
                            'qtrace_summary.json')

    def start(self, traceparent=None):
        """Open a trace: adopt the caller's W3C trace context when the
        header parses, mint a deterministic id otherwise."""
        with self._lock:
            seq = self._seq
            self._seq += 1
        span_id = hashlib.sha256(
            f'{self.seed}:span:{seq}'.encode()).hexdigest()[:16]
        parsed = parse_traceparent(traceparent)
        if parsed is not None:
            trace_id, parent_id = parsed
        else:
            trace_id = hashlib.sha256(
                f'{self.seed}:trace:{seq}'.encode()).hexdigest()[:32]
            parent_id = None
        return QueryTrace(trace_id, span_id, seq, parent_id)

    def _sample_keep(self, trace_id):
        if self.sample_rate >= 1.0:
            return True
        if self.sample_rate <= 0.0:
            return False
        h = hashlib.sha256(
            f'{self.seed}:keep:{trace_id}'.encode()).digest()
        return int.from_bytes(h[:8], 'big') / 2.0 ** 64 \
            < self.sample_rate

    def finish(self, trace, status=200, bucket=None, error=None,
               total_s=None):
        """Close a trace and route it through retention; returns the
        record dict. Histograms see EVERY query; the file tiers see the
        deterministic kept-set."""
        total = trace.close(total_s)
        record = {
            'trace_id': trace.trace_id,
            'span_id': trace.span_id,
            'parent_id': trace.parent_id,
            'seq': trace.seq,
            'time_unix': trace.start_unix,
            'status': int(status),
            'bucket': bucket,
            'error': error,
            'total_ms': round(total * 1e3, 4),
            'spans': [{'name': n, 'start_ms': round(s * 1e3, 4),
                       'dur_ms': round(d * 1e3, 4)}
                      for n, s, d in trace.spans],
        }
        is_error = int(status) >= 400 or error is not None
        breach = self.slo_s is not None and total > self.slo_s
        by_name = {}
        for name, _start, dur in trace.spans:
            by_name[name] = by_name.get(name, 0.0) + dur
        with self._lock:
            self._queries += 1
            self._hist_total.observe(total)
            for name, dur in by_name.items():
                self._hist_stage[name].observe(dur)
            if is_error:
                self._errors_seen += 1
                self._errors.append(record)
                self._kept['error'] += 1
            if self.slowest_k:
                entry = (total, trace.seq, record)
                if len(self._slowest) < self.slowest_k:
                    heapq.heappush(self._slowest, entry)
                    self._kept['slowest'] += 1
                elif entry > self._slowest[0]:
                    heapq.heapreplace(self._slowest, entry)
                    self._kept['slowest'] += 1
            if not is_error and self.capacity \
                    and self._sample_keep(trace.trace_id):
                self._sampled.append(record)
                self._kept['sampled'] += 1
            if breach:
                self._slo_breaches += 1
            self._dirty = True
        if breach and self.on_breach is not None:
            self.on_breach(record)      # outside the lock: may dump
        return record

    # -- file tier ---------------------------------------------------

    def _records_locked(self):
        by_seq = {}

        def add(record, reason):
            entry = by_seq.setdefault(record['seq'],
                                      {'record': record, 'kept': []})
            entry['kept'].append(reason)

        for record in self._errors:
            add(record, 'error')
        for _total, _seq, record in self._slowest:
            add(record, 'slowest')
        for record in self._sampled:
            add(record, 'sampled')
        return [dict(e['record'], kept=sorted(set(e['kept'])))
                for _seq, e in sorted(by_seq.items())]

    def flush(self):
        """Atomically rewrite ``qtrace.jsonl`` + ``qtrace_summary.json``
        from the in-memory rings. The file never grows past the ring
        bounds because it IS the rings, serialized."""
        if not self.path:
            return False
        with self._lock:
            records = self._records_locked()
            summary = self._summary_locked()
        tmp = f'{self.path}.tmp.{os.getpid()}'
        try:
            os.makedirs(os.path.dirname(self.path) or '.',
                        exist_ok=True)
            with open(tmp, 'w') as f:
                for record in records:
                    f.write(json.dumps(record) + '\n')
            os.replace(tmp, self.path)
        except OSError:
            return False
        write_json_atomic(self.summary_path, summary, indent=1,
                          quiet=True)
        self._last_flush = time.time()
        self._dirty = False
        return True

    def maybe_flush(self, interval_s=None):
        """Time-throttled flush for the query path: cheap when clean or
        recently flushed, so per-query overhead stays in the noise."""
        if not self._dirty or not self.path:
            return False
        interval = self.flush_interval_s if interval_s is None \
            else float(interval_s)
        if time.time() - self._last_flush < interval:
            return False
        return self.flush()

    # -- summaries ---------------------------------------------------

    def _hist_quantiles_locked(self, hist):
        if not hist.count:
            return None
        return {
            'count': hist.count,
            'sum_ms': round(hist.sum * 1e3, 4),
            'p50_ms': round(hist.quantile(0.50) * 1e3, 4),
            'p95_ms': round(hist.quantile(0.95) * 1e3, 4),
            'p99_ms': round(hist.quantile(0.99) * 1e3, 4),
        }

    def _summary_locked(self):
        stages = {}
        for name in SERVE_SPAN_NAMES:
            q = self._hist_quantiles_locked(self._hist_stage[name])
            if q is not None:
                stages[name] = q
        end_to_end = self._hist_quantiles_locked(self._hist_total)
        gap = None
        if end_to_end is not None:
            by_stage = {
                name: round(max(0.0, q['p95_ms'] - q['p50_ms']), 4)
                for name, q in stages.items()}
            dominant = max(by_stage, key=by_stage.get) \
                if any(by_stage.values()) else None
            gap = {
                'p95_minus_p50_ms': round(
                    end_to_end['p95_ms'] - end_to_end['p50_ms'], 4),
                'by_stage_ms': by_stage,
                'dominant_stage': dominant,
            }
        slowest = [record for _total, _seq, record
                   in sorted(self._slowest, reverse=True)]
        return {
            'queries': self._queries,
            'errors': self._errors_seen,
            'errors_truncated': max(
                0, self._errors_seen - len(self._errors)),
            'slo_breaches': self._slo_breaches,
            'sample_rate': self.sample_rate,
            'slowest_k': self.slowest_k,
            'capacity': self.capacity,
            'seed': self.seed,
            'kept': dict(self._kept),
            'stage_vocabulary': list(SERVE_SPAN_NAMES),
            'end_to_end': end_to_end,
            'stages': stages,
            'gap_attribution': gap,
            'slowest': slowest,
        }

    def summary(self):
        """The full-population account (every query, histograms), the
        payload of ``qtrace_summary.json``."""
        with self._lock:
            return self._summary_locked()

    def metric_families(self):
        """Metric families for the ``/metrics`` exposition: per-stage
        latency histograms (``stage`` label), the end-to-end trace
        histogram, and the retention counters. Plugged into
        :meth:`~dgmc_tpu.obs.run.RunObserver.add_metrics_provider`."""
        with self._lock:
            stage_snaps = {name: self._hist_stage[name].snapshot()
                           for name in SERVE_SPAN_NAMES}
            total_snap = self._hist_total.snapshot()
            kept = dict(self._kept)
            queries = self._queries
            breaches = self._slo_breaches
        samples = []
        for stage in SERVE_SPAN_NAMES:
            snap = stage_snaps[stage]
            for bound, cum in snap['buckets']:
                le = '+Inf' if math.isinf(bound) \
                    else repr(float(bound))
                samples.append(
                    ('_bucket', {'stage': stage, 'le': le}, cum))
            samples.append(('_sum', {'stage': stage}, snap['sum']))
            samples.append(('_count', {'stage': stage},
                            snap['count']))
        return [
            ('dgmc_query_stage_seconds', 'histogram',
             'Per-stage serve span latency (qtrace vocabulary).',
             samples),
            histogram_family(
                'dgmc_query_trace_seconds',
                'End-to-end /match latency (qtrace, every query).',
                total_snap),
            ('dgmc_qtrace_queries_total', 'counter',
             'Queries traced.', [('', {}, queries)]),
            ('dgmc_qtrace_kept_total', 'counter',
             'Trace-retention admissions by reason.',
             [('', {'reason': r}, kept.get(r, 0))
              for r in ('sampled', 'slowest', 'error')]),
            ('dgmc_qtrace_slo_breaches_total', 'counter',
             'Queries over the end-to-end SLO.', [('', {}, breaches)]),
        ]


# ---------------------------------------------------------------------------
# Offline analysis: records -> report / Chrome export
# ---------------------------------------------------------------------------

def load_records(path):
    """Read a ``qtrace.jsonl`` (or an obs dir holding one — supervised
    roots resolve to the LAST attempt, like ``report.load_run``).
    Returns ``(records, summary_or_None, resolved_path)``."""
    if os.path.isdir(path):
        candidates = [os.path.join(path, 'qtrace.jsonl')]
        attempts = sorted(
            d for d in os.listdir(path) if d.startswith('attempt_'))
        candidates = [os.path.join(path, a, 'qtrace.jsonl')
                      for a in reversed(attempts)] + candidates
        for cand in candidates:
            if os.path.exists(cand):
                path = cand
                break
        else:
            raise FileNotFoundError(
                f'no qtrace.jsonl under {path} (or its attempt_*/)')
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    summary = None
    summary_path = os.path.join(os.path.dirname(path) or '.',
                                'qtrace_summary.json')
    try:
        with open(summary_path) as f:
            summary = json.load(f)
    except (OSError, ValueError):
        pass
    return records, summary, path


def stage_percentiles(records):
    """Exact per-stage and end-to-end percentiles over kept records
    (``{'end_to_end': {...}, 'stages': {name: {...}}}``). Exact —
    unlike the histogram summary — but over the KEPT set, which the
    slowest-K reservoir biases toward the tail; the report says which
    account it is printing."""
    def quant(values):
        values = sorted(values)
        return {'count': len(values),
                'p50_ms': round(percentile(values, 0.50), 4),
                'p95_ms': round(percentile(values, 0.95), 4),
                'p99_ms': round(percentile(values, 0.99), 4)}

    by_stage = collections.defaultdict(list)
    totals = []
    for record in records:
        totals.append(float(record.get('total_ms') or 0.0))
        per = {}
        for span in record.get('spans') or []:
            per[span['name']] = per.get(span['name'], 0.0) \
                + float(span['dur_ms'])
        for name, ms in per.items():
            by_stage[name].append(ms)
    out = {'end_to_end': quant(totals) if totals else None,
           'stages': {}}
    for name in SERVE_SPAN_NAMES:
        if by_stage.get(name):
            out['stages'][name] = quant(by_stage[name])
    return out


def gap_attribution(percentiles):
    """Attribute the end-to-end p95−p50 gap to stages: each stage's own
    p95−p50 spread, largest spread named dominant. ``None`` without an
    end-to-end account."""
    e2e = percentiles.get('end_to_end')
    if not e2e:
        return None
    by_stage = {
        name: round(max(0.0, q['p95_ms'] - q['p50_ms']), 4)
        for name, q in (percentiles.get('stages') or {}).items()}
    gap = round(e2e['p95_ms'] - e2e['p50_ms'], 4)
    dominant = max(by_stage, key=by_stage.get) \
        if any(by_stage.values()) else None
    share = None
    if dominant is not None and gap > 0:
        share = round(min(1.0, by_stage[dominant] / gap), 4)
    return {'p95_minus_p50_ms': gap, 'by_stage_ms': by_stage,
            'dominant_stage': dominant, 'dominant_share': share}


def chrome_trace_events(records):
    """Chrome trace-event payload for kept records: one thread row per
    query, ``ph: 'X'`` slices named by the serve span vocabulary with
    the mapped model stages in ``args`` — loadable by
    ``obs.trace_events`` beside a profiler capture."""
    events = [{'ph': 'M', 'name': 'process_name', 'pid': 0, 'tid': 0,
               'args': {'name': 'dgmc-qtrace'}}]
    for record in records:
        tid = int(record.get('seq') or 0)
        base_us = float(record.get('time_unix') or 0.0) * 1e6
        label = (f"query {str(record.get('trace_id') or '')[:8]} "
                 f"({record.get('status')})")
        events.append({'ph': 'M', 'name': 'thread_name', 'pid': 0,
                       'tid': tid, 'args': {'name': label}})
        for span in record.get('spans') or []:
            events.append({
                'ph': 'X', 'name': span['name'], 'pid': 0, 'tid': tid,
                'ts': base_us + float(span['start_ms']) * 1e3,
                'dur': float(span['dur_ms']) * 1e3,
                'args': {
                    'trace_id': record.get('trace_id'),
                    'stages': list(SERVE_SPAN_STAGES[span['name']]),
                }})
    return {'traceEvents': events, 'displayTimeUnit': 'ms'}


def _render_span_tree(record, indent='  '):
    lines = [f"trace {record.get('trace_id')} seq {record.get('seq')}: "
             f"{record.get('total_ms')} ms, status "
             f"{record.get('status')}"
             + (f", bucket {record['bucket']}"
                if record.get('bucket') else '')
             + (f", error {record['error']}"
                if record.get('error') else '')
             + (f" [kept: {','.join(record['kept'])}]"
                if record.get('kept') else '')]
    for span in record.get('spans') or []:
        end = span['start_ms'] + span['dur_ms']
        lines.append(f"{indent}{span['name']:<22} "
                     f"{span['start_ms']:9.3f} ..{end:9.3f} ms  "
                     f"({span['dur_ms']:.3f} ms)")
    return lines


def render_report(records, summary=None, slowest=1):
    """The human report: per-stage table, gap attribution, slowest span
    trees. Exact percentiles over the kept set; the full-population
    histogram account is quoted from the summary when present."""
    lines = []
    pct = stage_percentiles(records)
    gap = gap_attribution(pct)
    seen = summary.get('queries') if summary else None
    lines.append(f'qtrace: {len(records)} kept records'
                 + (f' of {seen} queries observed' if seen else ''))
    if summary and summary.get('errors'):
        trunc = summary.get('errors_truncated') or 0
        lines.append(f"errors: {summary['errors']}"
                     + (f' ({trunc} evicted by the error-ring bound)'
                        if trunc else ''))
    e2e = pct['end_to_end']
    if e2e is None:
        lines.append('no records — nothing to attribute')
        return '\n'.join(lines)
    lines.append(f"end-to-end (kept set): p50 {e2e['p50_ms']:.3f}  "
                 f"p95 {e2e['p95_ms']:.3f}  p99 {e2e['p99_ms']:.3f} ms")
    lines.append('')
    lines.append(f"{'stage':<22}{'count':>7}{'p50 ms':>10}"
                 f"{'p95 ms':>10}{'p99 ms':>10}{'p95-p50':>10}")
    for name in SERVE_SPAN_NAMES:
        q = pct['stages'].get(name)
        if q is None:
            lines.append(f'{name:<22}{"-":>7}{"-":>10}{"-":>10}'
                         f'{"-":>10}{"-":>10}')
            continue
        spread = max(0.0, q['p95_ms'] - q['p50_ms'])
        lines.append(f"{name:<22}{q['count']:>7}{q['p50_ms']:>10.3f}"
                     f"{q['p95_ms']:>10.3f}{q['p99_ms']:>10.3f}"
                     f"{spread:>10.3f}")
    lines.append('')
    if gap and gap['dominant_stage']:
        share = f" ({gap['dominant_share']:.0%} of the gap)" \
            if gap.get('dominant_share') is not None else ''
        lines.append(
            f"p95-p50 gap {gap['p95_minus_p50_ms']:.3f} ms; dominant "
            f"stage: {gap['dominant_stage']} "
            f"(+{gap['by_stage_ms'][gap['dominant_stage']]:.3f} ms"
            f"{share})")
    else:
        lines.append('p95-p50 gap: no stage spread to attribute')
    ranked = sorted(records,
                    key=lambda r: float(r.get('total_ms') or 0.0),
                    reverse=True)
    for record in ranked[:max(0, int(slowest))]:
        lines.append('')
        lines.extend(_render_span_tree(record))
    return '\n'.join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='python -m dgmc_tpu.obs.qtrace',
        description='Attribute serve tail latency (p95-p50) to stages '
                    'from a worker qtrace.jsonl.')
    parser.add_argument('path',
                        help='qtrace.jsonl, or an obs dir holding one '
                             '(supervised roots resolve to the last '
                             'attempt)')
    parser.add_argument('--slowest', type=int, default=1,
                        help='span trees to print for the slowest N '
                             'kept queries (default 1)')
    parser.add_argument('--json', action='store_true',
                        help='emit the machine-readable report instead '
                             'of text')
    parser.add_argument('--chrome', metavar='OUT',
                        help='also export kept records as Chrome '
                             'trace-event JSON to OUT')
    args = parser.parse_args(argv)
    try:
        records, summary, resolved = load_records(args.path)
    except (OSError, ValueError) as e:
        print(f'qtrace: {e}')
        return 1
    if args.chrome:
        write_json_atomic(args.chrome, chrome_trace_events(records))
        print(f'chrome trace: {args.chrome}')
    if args.json:
        pct = stage_percentiles(records)
        print(json.dumps({
            'path': resolved,
            'records': len(records),
            'percentiles': pct,
            'gap_attribution': gap_attribution(pct),
            'summary': summary,
        }, indent=1, sort_keys=True))
        return 0
    print(f'[{resolved}]')
    print(render_report(records, summary, slowest=args.slowest))
    return 0


if __name__ == '__main__':      # pragma: no cover
    raise SystemExit(main())
