"""Trace-event parsing for measured-runtime attribution (jax-free).

``jax.profiler.trace`` (the ``--profile-dir`` flag every CLI carries)
drops its trace-event export under
``<profile_dir>/plugins/profile/<session>/<host>.trace.json.gz`` — a
Chrome-trace JSON whose ``ph: 'X'`` complete events record, with
microsecond timestamps, what each *device* and *host thread* actually
spent its time on. That file is the only artifact in the repo that
holds measured on-chip wall-clock; everything else is host-side step
timing or a static model. This module turns it into structured tracks
so :mod:`dgmc_tpu.obs.attribution` can build the measured account:

- :func:`read_trace_file` — one ``.trace.json``/``.trace.json.gz``
  payload (gzip detected by magic bytes, not extension); corrupt or
  truncated content raises :class:`TraceParseError` with the reason,
  so a half-written capture degrades to a named error instead of a
  fabricated zero table.
- :func:`find_profiler_traces` — the newest profiler session's trace
  exports under a ``--profile-dir`` (one file per host on multi-host
  captures).
- :func:`build_tracks` — events grouped per ``(pid, tid)`` with the
  ``process_name``/``thread_name`` metadata resolved and device
  processes (``/device:TPU:0``-style names — the XLA profiler's
  spelling) flagged, sorted slices per track.
- Interval algebra (:func:`merge_intervals`, :func:`sum_intervals`,
  :func:`intersect_intervals`) — busy-time unions that are robust to
  the overlapping/nested slices real traces contain (an async
  collective's in-flight window overlaps the ops it runs under;
  summing raw durations would double-count it).
- Classification shared with the static models: stage attribution
  reuses :func:`dgmc_tpu.analysis.hlo_comm.stage_of` over the same
  ``jax.named_scope`` paths already pinned in lowered HLO
  (tests/obs/test_scopes.py), and comm-vs-compute splits on the same
  :data:`~dgmc_tpu.analysis.hlo_comm.COLLECTIVE_OPS` table the lint
  SHD tier and ``obs/cost.py`` count — so the measured account and the
  static account can never disagree about what counts as a stage or a
  collective.

The trace-event grammar this parser accepts is pinned by golden
fixtures in ``tests/obs/test_attribution.py`` the way SHD/SCH rules
pin golden HLO.
"""

import dataclasses
import glob
import gzip
import json
import os
import re
from typing import Dict, List, Tuple

# Shared vocabulary with the static models: the SAME scope names the
# lowered HLO pins and the SAME collective-op table the SHD/SCH tiers
# walk. (hlo_comm is pure text analysis — no jax import.)
from dgmc_tpu.analysis.hlo_comm import (COLLECTIVE_OPS, SERVE_SPAN_NAMES,
                                        SERVE_SPAN_STAGES, STAGE_NAMES,
                                        stage_of)

__all__ = [
    'TraceParseError', 'Track', 'read_trace_file', 'find_profiler_traces',
    'build_tracks', 'merge_intervals', 'sum_intervals',
    'intersect_intervals', 'event_stage', 'is_comm_event',
    'is_host_wait_event', 'STAGE_NAMES', 'COLLECTIVE_OPS',
    'SERVE_SPAN_NAMES', 'SERVE_SPAN_STAGES',
]


class TraceParseError(ValueError):
    """One trace file could not be parsed; carries the path + reason."""

    def __init__(self, path, reason):
        super().__init__(f'{path}: {reason}')
        self.path = path
        self.reason = reason


#: XLA profiler device-process naming (``/device:TPU:0``, plus the
#: ``(pid N)``-suffixed spellings some exporters use). Host processes
#: are ``/host:CPU`` or anything else.
_DEVICE_PROCESS = re.compile(r'^/device:')

#: Event names whose base opcode marks cross-device communication:
#: the shared collective table plus point-to-point send/recv (HLO
#: lowers device-to-device permute edges onto them).
_COMM_OPCODES = frozenset(COLLECTIVE_OPS) | {'send', 'recv'}

#: Host-side slices that mean "the host is blocked on the device" —
#: the host-waiting-on-device half of the gap analysis. Matched as
#: lowercase substrings of the event name (python-stack events arrive
#: as ``$file.py:123 block_until_ready``).
_HOST_WAIT_MARKERS = (
    'block_until_ready', 'blockhostuntilready', 'transferfromdevice',
    'copyfromdevice', 'device_get', 'awaitcomputation',
    'wait for completion',
)

#: args keys searched (in order) for a scope path before the event
#: name itself: device op events carry the full ``jit(f)/.../psi1/...``
#: path in their metadata, not in the short display name.
_SCOPE_ARG_KEYS = ('long_name', 'op_name', 'tf_op', 'hlo_op', 'name')


@dataclasses.dataclass
class Track:
    """All ``ph: 'X'`` slices of one ``(pid, tid)`` row.

    ``slices`` are ``(ts_us, dur_us, name, args)`` tuples sorted by
    start time; ``device`` marks tracks owned by a device process.
    """
    pid: object
    tid: object
    process: str
    thread: str
    device: bool
    slices: List[Tuple[float, float, str, dict]]

    def busy_intervals(self):
        """Merged busy intervals of this track (handles nesting)."""
        return merge_intervals([(t, t + d) for t, d, _, _ in self.slices])


def read_trace_file(path):
    """Load one Chrome-trace JSON payload (gzipped or plain).

    Returns the payload dict (must carry a ``traceEvents`` list).
    Raises :class:`TraceParseError` on unreadable files, bad gzip
    streams, truncated/corrupt JSON, or payloads without events — the
    caller records the error and degrades instead of crashing.
    """
    try:
        with open(path, 'rb') as f:
            raw = f.read()
    except OSError as e:
        raise TraceParseError(path, f'unreadable: {e}')
    if raw[:2] == b'\x1f\x8b':
        try:
            raw = gzip.decompress(raw)
        except (OSError, EOFError) as e:
            raise TraceParseError(path, f'bad gzip stream: {e}')
    try:
        payload = json.loads(raw.decode('utf-8', errors='replace'))
    except ValueError as e:
        raise TraceParseError(path, f'truncated or corrupt JSON: {e}')
    if not isinstance(payload, dict) \
            or not isinstance(payload.get('traceEvents'), list):
        raise TraceParseError(path, 'no traceEvents list in payload')
    return payload


def find_profiler_traces(profile_dir):
    """Trace-event exports under a ``--profile-dir``.

    Looks for ``<dir>/plugins/profile/<session>/*.trace.json[.gz]``
    and returns the NEWEST session's files (sorted; one per host on a
    multi-host capture). Also accepts a session directory itself, or
    any directory holding ``*.trace.json[.gz]`` files directly — so
    ``python -m dgmc_tpu.obs.attribution`` works on a copied-out
    session as well as the capture root. Returns ``[]`` when nothing
    matches (the caller decides whether that is an error).
    """
    profile_dir = os.fspath(profile_dir)

    def traces_in(d):
        return sorted(glob.glob(os.path.join(d, '*.trace.json.gz'))
                      + glob.glob(os.path.join(d, '*.trace.json')))

    direct = traces_in(profile_dir)
    if direct:
        return direct
    root = os.path.join(profile_dir, 'plugins', 'profile')
    if not os.path.isdir(root):
        return []
    sessions = sorted(d for d in glob.glob(os.path.join(root, '*'))
                      if os.path.isdir(d))
    for session in reversed(sessions):   # newest session dir first
        found = traces_in(session)
        if found:
            return found
    return []


def build_tracks(events):
    """Group trace events into per-``(pid, tid)`` :class:`Track` rows.

    Resolves ``ph: 'M'`` ``process_name``/``thread_name`` metadata,
    flags device processes, and keeps only ``ph: 'X'`` complete slices
    with a numeric ``ts`` (counter/instant/metadata events carry no
    wall-clock to attribute). Slices are sorted by start time.
    """
    process_names: Dict[object, str] = {}
    thread_names: Dict[Tuple[object, object], str] = {}
    slices: Dict[Tuple[object, object],
                 List[Tuple[float, float, str, dict]]] = {}
    for e in events:
        if not isinstance(e, dict):
            continue
        ph = e.get('ph')
        pid, tid = e.get('pid'), e.get('tid')
        if ph == 'M':
            args = e.get('args') or {}
            if e.get('name') == 'process_name':
                process_names[pid] = str(args.get('name', ''))
            elif e.get('name') == 'thread_name':
                thread_names[(pid, tid)] = str(args.get('name', ''))
            continue
        if ph != 'X':
            continue
        ts, dur = e.get('ts'), e.get('dur', 0.0)
        if not isinstance(ts, (int, float)) \
                or not isinstance(dur, (int, float)) or dur < 0:
            continue
        args = dict(e.get('args') or {})
        if e.get('cat'):
            # The top-level Chrome 'cat' rides along in args so
            # downstream classification (e.g. the host run-trace's
            # cat: 'step' spans) sees one metadata dict.
            args.setdefault('cat', e['cat'])
        slices.setdefault((pid, tid), []).append(
            (float(ts), float(dur), str(e.get('name', '')), args))
    tracks = []
    for (pid, tid), rows in sorted(slices.items(),
                                   key=lambda kv: (str(kv[0][0]),
                                                   str(kv[0][1]))):
        process = process_names.get(pid, '')
        tracks.append(Track(
            pid=pid, tid=tid, process=process,
            thread=thread_names.get((pid, tid), ''),
            device=bool(_DEVICE_PROCESS.match(process)),
            slices=sorted(rows, key=lambda s: (s[0], -s[1]))))
    return tracks


# ---------------------------------------------------------------------------
# Interval algebra (all times in the trace's microsecond clock)
# ---------------------------------------------------------------------------


def merge_intervals(intervals):
    """Union of ``(start, end)`` intervals as a sorted disjoint list.

    Overlapping and nested slices (async in-flight windows over the
    ops they cover) collapse to their cover — the reason busy time is
    computed on unions, never on raw duration sums.
    """
    ivs = sorted((s, e) for s, e in intervals if e > s)
    merged = []
    for s, e in ivs:
        if merged and s <= merged[-1][1]:
            if e > merged[-1][1]:
                merged[-1] = (merged[-1][0], e)
        else:
            merged.append((s, e))
    return merged


def sum_intervals(merged):
    """Total covered time of a merged interval list."""
    return sum(e - s for s, e in merged)


def intersect_intervals(a, b):
    """Merged intersection of two MERGED interval lists (two-pointer
    sweep) — the measured-overlap primitive: comm busy ∩ compute busy."""
    out = []
    i = j = 0
    while i < len(a) and j < len(b):
        s = max(a[i][0], b[j][0])
        e = min(a[i][1], b[j][1])
        if e > s:
            out.append((s, e))
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    return out


# ---------------------------------------------------------------------------
# Event classification (shared vocabulary with the static models)
# ---------------------------------------------------------------------------


def _opcode_of(name):
    """Base HLO opcode of an op-event display name:
    ``all-reduce-start.3`` -> ``all-reduce``; ``fusion.12`` ->
    ``fusion``. Strips the ``.N`` instance suffix and async
    ``-start``/``-done`` halves (an async pair's in-flight window is
    the same communication)."""
    base = name.strip().lstrip('%').split('(')[0].strip()
    base = re.sub(r'\.\d+$', '', base)
    for suffix in ('-start', '-done'):
        if base.endswith(suffix):
            base = base[:-len(suffix)]
    return base


def event_stage(name, args):
    """Pipeline stage of one trace event, via the SAME
    :func:`~dgmc_tpu.analysis.hlo_comm.stage_of` scope-path rule the
    static cost model applies to lowered HLO. Device op events carry
    the full scope path in their args metadata (``long_name`` /
    ``op_name`` / ``tf_op``); the display name is the fallback.
    Returns ``'other'`` when no stage scope matches."""
    for key in _SCOPE_ARG_KEYS:
        v = args.get(key)
        if isinstance(v, str) and v:
            s = stage_of(v)
            if s != 'other':
                return s
    return stage_of(name)


def is_comm_event(name, args):
    """True when the event is cross-device communication: its base
    opcode is in the shared collective table (plus send/recv), or its
    exporter category says so."""
    if _opcode_of(name) in _COMM_OPCODES:
        return True
    for key in ('hlo_category', 'category'):
        v = args.get(key)
        if isinstance(v, str) and 'collective' in v.lower():
            return True
    return False


def is_host_wait_event(name):
    """True when a host-track slice means the host is blocked on the
    device (fetches, ``block_until_ready``, transfer waits) — the
    host-waiting-on-device half of the idle/gap analysis."""
    low = name.lower()
    return any(marker in low for marker in _HOST_WAIT_MARKERS)
