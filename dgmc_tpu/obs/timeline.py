"""Longitudinal bench trajectory: the committed rounds as one table.

``obs.diff`` compares exactly two runs; the repo's performance HISTORY
lives in the committed round records — ``BENCH_r*.json`` (single-chip),
``MULTICHIP_r*.json`` (sharded), ``SCALE_r*.json`` (streamed
million-entity), ``SERVE_r*.json`` (the online matching service's load
rounds: query-latency p50/p95, QPS, restart count and the warm
restart-to-first-answer beside the training families' columns) — and
has so far been invisible except by opening each file.
This CLI walks one or more directories, parses every round record
it finds (both the modern structured schema of r06+ and the legacy
``{'cmd', 'rc', 'tail', 'parsed'}`` driver capture of r01–r05), and
renders the trajectory per family::

    python -m dgmc_tpu.obs.timeline benchmarks/          # table
    python -m dgmc_tpu.obs.timeline benchmarks/ --json   # machine-readable

Since PR 14 every committed round lives under ``benchmarks/`` (the
legacy root-level r01–r05 driver captures moved there), so the single
``benchmarks/`` invocation covers the whole trajectory.

Columns are the headline series the ROADMAP tracks: throughput
(pairs/s), step p50, MFU, modeled overlap fraction, skew, device count,
and the round's outcome (``rc:124`` rounds — the silent-hang era — show
up as exactly that). SCALE rows additionally carry the ``offload``
column (prefetch-ring depth + host-resident corpus bytes) so an r07→r08
jump in rows reads as the layout change it is — the corpus moved to
host RAM — not a regression in what fits on device. Like every other
obs reader, this module has **no jax import**: it renders committed
evidence on any box.
"""

import argparse
import json
import os
import re
import sys

from dgmc_tpu.obs.observe import fmt_seconds

__all__ = ['collect_rounds', 'parse_round', 'render', 'trend',
           'render_trend', 'main']

_ROUND_FILE = re.compile(r'^(BENCH|MULTICHIP|SCALE|SERVE)_r(\d+)\.json$')
#: Family render order (matches the chronology: single-chip first).
_FAMILIES = ('BENCH', 'MULTICHIP', 'SCALE', 'SERVE')


def _get(d, *path):
    for key in path:
        if not isinstance(d, dict):
            return None
        d = d.get(key)
    return d


def _first(*vals):
    for v in vals:
        if v is not None:
            return v
    return None


def parse_round(family, number, path):
    """One normalized row from a round record (any schema vintage).

    Returns ``{'family', 'round', 'file', 'outcome', 'devices',
    'pairs_per_sec', 'step_p50_ms', 'mfu', 'overlap', 'skew',
    'device'}`` — absent measurements are ``None``, never guessed.
    """
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError) as e:
        return {'family': family, 'round': number,
                'file': os.path.basename(path),
                'outcome': f'unreadable ({type(e).__name__})'}
    # r01-r05 driver captures keep the measurement under 'parsed';
    # r06+ structured records keep it under 'result' (BENCH) or at the
    # top level (MULTICHIP/SCALE).
    parsed = d.get('parsed') or {}
    result = d.get('result') or {}
    rc = d.get('rc')
    outcome = _first(_get(d, 'supervision', 'outcome'),
                     _get(d, 'supervision', 'outcome_8dev'),
                     d.get('outcome'))
    if outcome is None:
        if rc == 0 or d.get('ok'):
            outcome = 'completed'
        elif d.get('skipped'):
            outcome = 'skipped'
        elif rc is not None:
            outcome = f'rc:{rc}'
        else:
            outcome = '?'
    restarts = _first(_get(d, 'supervision', 'restarts'),
                      _get(d, 'supervision', 'restarts_8dev'))
    if restarts and family != 'SERVE':
        # SERVE rows carry restarts as their own column (the chaos kill
        # is part of the round's protocol, not an anomaly to flag).
        outcome = f'{outcome} ({restarts} restarts)'
    row = {
        'family': family,
        'round': number,
        'file': os.path.basename(path),
        'outcome': outcome,
        'devices': d.get('n_devices'),
        'device': _first(result.get('device'), parsed.get('device'),
                         _get(d, 'environment', 'platform')),
        'pairs_per_sec': _first(
            result.get('value') if result.get('metric')
            == 'train_pairs_per_sec' else None,
            parsed.get('value') if parsed.get('metric')
            == 'train_pairs_per_sec' else None),
        'step_p50_ms': _first(
            _get(d, 'timing', 'step_p50_ms_8dev'),
            _get(d, 'timing', 'step_p50_ms'),
            _get(result, 'sparse_dbp15k', 'f32', 'step_ms'),
            _get(result, 'sparse_dbp15k', 'step_ms'),
            _get(parsed, 'sparse_dbp15k', 'step_ms')),
        'mfu': _first(_get(result, 'dense_perf', 'mfu'),
                      _get(parsed, 'dense_perf', 'mfu'),
                      d.get('mfu')),
        'overlap': _first(
            _get(d, 'analysis_fields', 'overlap_fraction'),
            _get(result, 'dense_perf', 'overlap_fraction'),
            d.get('overlap_fraction'),
            _get(d, 'timing', 'overlap_fraction')),
        'skew': _get(d, 'timing', 'per_device_step_skew_ratio'),
        # Quality plane (PR 17+): rounds carrying a 'quality' block get
        # accuracy columns; older rounds simply render '-'.
        'hits1': _first(_get(d, 'quality', 'hits1'),
                        d.get('hits_at_1')),
    }
    off = d.get('offload') or {}
    if off:
        row['offload'] = {
            'rows': off.get('rows'),
            'prefetch_depth': off.get('prefetch_depth'),
            'host_resident_bytes': off.get('host_resident_bytes'),
            'outcome': off.get('outcome'),
        }
    if family == 'SERVE':
        # The serving rounds' headline series: per-query latency, QPS
        # under concurrent load, and how many supervised restarts the
        # round survived (the mid-run SIGKILL is part of the protocol —
        # 1 restart is the healthy shape, not a regression).
        lat = d.get('latency') or {}
        restart = d.get('restart') or {}
        # r02+ rounds carry a per-query trace account (obs.qtrace):
        # p99 and the stage the p95−p50 gap attributes to. Older
        # rounds simply lack the block — the columns render '-'.
        qt = d.get('qtrace') or {}
        # r03+ rounds add the quality account: per-query confidence
        # and the shadow audit's worst-case shortlist recall.
        quality = d.get('quality') or {}
        audit = quality.get('audit') or {}
        # r04+ rounds add the capacity/goodput account (obs.capacity /
        # obs.goodput): serve-path goodput ratio and the Little's-law
        # utilization ρ. Older rounds lack both blocks — the columns
        # render '-'.
        row.update({
            'audit_recall': audit.get('recall_min'),
            'saturated_frac': quality.get('saturated_frac'),
            'goodput': _first(
                _get(d, 'goodput', 'serve', 'goodput_ratio'),
                _get(d, 'goodput', 'goodput_ratio')),
            'utilization': _get(d, 'capacity', 'utilization'),
            'latency_p50_ms': _first(lat.get('server_p50_ms'),
                                     lat.get('client_p50_ms')),
            'latency_p95_ms': _first(lat.get('server_p95_ms'),
                                     lat.get('client_p95_ms')),
            'latency_p99_ms': qt.get('p99_ms'),
            'dominant_stage': qt.get('dominant_stage'),
            'qps': d.get('qps'),
            'clients': d.get('clients'),
            'restarts': _first(_get(d, 'supervision', 'restarts'), 0),
            'warm_restart_s': restart.get('warm_first_answer_s'),
        })
    # Truncate the long prose device/platform strings to their lead.
    if isinstance(row['device'], str):
        row['device'] = row['device'].split('(')[0].strip() or None
    return row


def collect_rounds(paths):
    """All round rows under ``paths`` (files or directories, searched
    non-recursively), sorted by (family, round). Duplicate
    family/round pairs keep every file (distinct directories can
    legitimately both hold a round — the table shows the file)."""
    rows = []
    for p in paths:
        if os.path.isfile(p):
            m = _ROUND_FILE.match(os.path.basename(p))
            if m:
                rows.append(parse_round(m.group(1), int(m.group(2)), p))
            continue
        try:
            names = sorted(os.listdir(p))
        except OSError:
            continue
        for name in names:
            m = _ROUND_FILE.match(name)
            if m:
                rows.append(parse_round(m.group(1), int(m.group(2)),
                                        os.path.join(p, name)))
    fam_rank = {f: i for i, f in enumerate(_FAMILIES)}
    rows.sort(key=lambda r: (fam_rank.get(r['family'], len(fam_rank)),
                             r['round'], r['file']))
    return rows


def _fmt(v, spec='{:.4g}'):
    return '-' if v is None else spec.format(v)


def _fmt_offload(off):
    """``d<depth>/<host GiB>`` — the ring depth and where the corpus
    lives; '-' for rows without an offload tier."""
    if not off:
        return '-'
    depth = off.get('prefetch_depth')
    host = off.get('host_resident_bytes')
    host = f'{host / 2**30:.1f}G' if host else '?'
    return f'd{depth if depth is not None else "?"}/{host}'


def _render_serve(fam_rows, lines):
    """SERVE rows carry a different headline set than the training
    families: per-query latency p50/p95/p99, sustained QPS, concurrent
    clients, warm restart-to-first-answer, restart count, and the
    stage the tail gap attributes to (``obs.qtrace``; rounds predating
    the trace account render '-')."""
    lines.append('== SERVE trajectory ==')
    lines.append(f'  {"round":>5} {"p50":>9} {"p95":>9} {"p99":>9} '
                 f'{"QPS":>7} {"clients":>7} {"warm rta":>9} '
                 f'{"restarts":>8} {"tail stage":>16} '
                 f'{"hits@1":>7} {"audit":>7} '
                 f'{"goodput":>7} {"util":>6}  outcome')
    for r in fam_rows:
        p50 = r.get('latency_p50_ms')
        p95 = r.get('latency_p95_ms')
        p99 = r.get('latency_p99_ms')
        lines.append(
            f'  {r["round"]:>5} '
            f'{fmt_seconds(p50 / 1e3) if p50 is not None else "-":>9} '
            f'{fmt_seconds(p95 / 1e3) if p95 is not None else "-":>9} '
            f'{fmt_seconds(p99 / 1e3) if p99 is not None else "-":>9} '
            f'{_fmt(r.get("qps")):>7} '
            f'{_fmt(r.get("clients"), "{:d}"):>7} '
            f'{_fmt(r.get("warm_restart_s"), "{:.2f}s"):>9} '
            f'{_fmt(r.get("restarts"), "{:d}"):>8} '
            f'{r.get("dominant_stage") or "-":>16} '
            f'{_fmt(r.get("hits1"), "{:.4f}"):>7} '
            f'{_fmt(r.get("audit_recall"), "{:.2f}"):>7} '
            f'{_fmt(r.get("goodput"), "{:.3f}"):>7} '
            f'{_fmt(r.get("utilization"), "{:.3f}"):>6}'
            f'  {r.get("outcome", "?")}')


def render(rows):
    lines = []
    for family in _FAMILIES:
        fam_rows = [r for r in rows if r['family'] == family]
        if not fam_rows:
            continue
        if family == 'SERVE':
            _render_serve(fam_rows, lines)
            continue
        offload_col = any(r.get('offload') for r in fam_rows)
        hits1_col = any(r.get('hits1') is not None for r in fam_rows)
        lines.append(f'== {family} trajectory ==')
        lines.append(f'  {"round":>5} {"pairs/s":>9} {"step p50":>11} '
                     f'{"MFU":>8} {"overlap":>8} {"skew":>7} '
                     f'{"dev":>4}'
                     + (f' {"offload":>9}' if offload_col else '')
                     + (f' {"hits@1":>7}' if hits1_col else '')
                     + '  outcome')
        for r in fam_rows:
            p50 = r.get('step_p50_ms')
            p50 = fmt_seconds(p50 / 1e3) if p50 is not None else '-'
            mfu = r.get('mfu')
            mfu = f'{mfu:.2%}' if mfu is not None else '-'
            lines.append(
                f'  {r["round"]:>5} {_fmt(r.get("pairs_per_sec")):>9} '
                f'{p50:>11} {mfu:>8} {_fmt(r.get("overlap")):>8} '
                f'{_fmt(r.get("skew"), "{:.3f}x"):>7} '
                f'{_fmt(r.get("devices"), "{:d}"):>4}'
                + (f' {_fmt_offload(r.get("offload")):>9}'
                   if offload_col else '')
                + (f' {_fmt(r.get("hits1"), "{:.4f}"):>7}'
                   if hits1_col else '')
                + f'  {r.get("outcome", "?")}')
    if not lines:
        lines.append('(no BENCH_r*/MULTICHIP_r*/SCALE_r*.json rounds '
                     'found)')
    return '\n'.join(lines)


#: Headline series the --trend changepoint scan walks per family.
_TREND_METRICS = {
    'BENCH': ('pairs_per_sec', 'step_p50_ms', 'mfu', 'overlap',
              'hits1'),
    'MULTICHIP': ('pairs_per_sec', 'step_p50_ms', 'mfu', 'overlap',
                  'skew'),
    'SCALE': ('pairs_per_sec', 'step_p50_ms', 'mfu'),
    'SERVE': ('latency_p50_ms', 'latency_p95_ms', 'qps', 'hits1',
              'goodput', 'utilization', 'warm_restart_s'),
}


def trend(rows):
    """CUSUM changepoints over each family's committed headline series
    (:func:`dgmc_tpu.obs.anomaly.changepoints` — the offline form of
    the live watch). Returns ``[{'family', 'metric', 'rounds',
    'changepoints': [{'round', 'direction', 'value'}]}, ...]`` for
    every series with enough measured rounds to have a baseline; the
    changepoint index maps back to the ROUND NUMBER so "p95 shifted up
    at r04" reads straight off the table."""
    from dgmc_tpu.obs.anomaly import changepoints
    out = []
    for family in _FAMILIES:
        fam_rows = [r for r in rows if r['family'] == family]
        if not fam_rows:
            continue
        for metric in _TREND_METRICS.get(family, ()):
            series = [r.get(metric) for r in fam_rows]
            measured = sum(1 for v in series if v is not None)
            if measured < 4:
                continue  # 3 baseline rounds + 1 to judge, minimum
            cps = changepoints(series)
            out.append({
                'family': family,
                'metric': metric,
                'rounds': measured,
                'changepoints': [
                    {'round': fam_rows[cp['index']]['round'],
                     'direction': cp['direction'],
                     'value': cp['value']}
                    for cp in cps],
            })
    return out


def render_trend(trends):
    lines = ['== trend changepoints (CUSUM over committed rounds) ==']
    if not trends:
        lines.append('  (no series with enough measured rounds — need '
                     '4+ per family/metric)')
        return '\n'.join(lines)
    shifted = [t for t in trends if t['changepoints']]
    for t in shifted:
        marks = ', '.join(
            f'r{cp["round"]:02d} {cp["direction"]} '
            f'(to {_fmt(cp["value"])})'
            for cp in t['changepoints'])
        lines.append(f'  {t["family"]:<9} {t["metric"]:<16} {marks}')
    stable = [t for t in trends if not t['changepoints']]
    if stable:
        lines.append(
            '  stable: ' + ', '.join(
                f'{t["family"]}.{t["metric"]}' for t in stable))
    return '\n'.join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='python -m dgmc_tpu.obs.timeline',
        description='Render the longitudinal trajectory of committed '
                    'bench rounds (BENCH_r*/MULTICHIP_r*/SCALE_r*.json) '
                    'across directories.')
    parser.add_argument('paths', nargs='*', default=None,
                        help='directories (or round files) to scan; '
                             'default: benchmarks/ and the current '
                             'directory')
    parser.add_argument('--json', action='store_true',
                        help='print the machine-readable rows')
    parser.add_argument('--trend', action='store_true',
                        help='append the CUSUM changepoint view: which '
                             'round each headline series shifted at '
                             '(obs.anomaly.changepoints over the '
                             'committed trajectory)')
    args = parser.parse_args(argv)

    paths = args.paths or ['benchmarks', '.']
    rows = collect_rounds(paths)
    if args.json:
        payload = ({'rows': rows, 'trend': trend(rows)}
                   if args.trend else rows)
        print(json.dumps(payload, indent=1))
    else:
        print(render(rows))
        if args.trend:
            print(render_trend(trend(rows)))
    if not rows:
        print(f'timeline: no round records under {paths}',
              file=sys.stderr)
        return 2
    return 0


if __name__ == '__main__':
    sys.exit(main())
