"""Trace export: run telemetry as Chrome-trace/Perfetto JSON, and the
``--profile-dir`` profiler flag.

Two complementary trace sources:

1. **Host-side run trace** (:func:`export_chrome_trace`): the telemetry
   the :class:`~dgmc_tpu.obs.run.RunObserver` already collects — step
   spans, compile events, benchmark sections, probe events — serialized
   in the Chrome trace-event format. Open ``<obs_dir>/trace.json`` in
   `Perfetto <https://ui.perfetto.dev>`_ (or ``chrome://tracing``): steps
   render as duration slices, XLA compiles as slices on their own track,
   and every numeric probe (``corr_entropy``, ``consensus_delta``,
   ``grad_norm``, ...) as a counter track — the sharpening curve drawn
   over the run's real timeline. No jax import needed; this works on a
   box that only has the artifacts.
2. **Device-side profiler trace** (:func:`profile_span` behind
   ``--profile-dir`` on the experiment CLIs and ``bench.py``):
   ``jax.profiler.trace`` over the whole run, viewable in
   TensorBoard/Perfetto, where the model's ``jax.named_scope`` stage
   annotations (``psi1`` -> ``initial_corr``/``topk`` ->
   ``consensus_iter``/``psi2``) label the XLA ops. This is the
   MXU-idle/HBM-stall view; the run trace above is the what-did-the-host
   -do view.

The trace-event records follow the documented Chrome format: ``ph: 'X'``
complete events with microsecond ``ts``/``dur``, ``ph: 'C'`` counters,
``ph: 'i'`` instants.
"""

import atexit
import contextlib
import json
import math
import os

#: Track ids inside the single "dgmc run" process row.
_TID_STEPS = 1
_TID_COMPILE = 2
_TID_SECTIONS = 3
_PID = 1


def _us(t, origin):
    return round((t - origin) * 1e6, 1)


def chrome_events(step_spans=(), probe_records=(), compile_events=(),
                  sections=(), device_fences=()):
    """Build the ``traceEvents`` list from host telemetry.

    Args:
        step_spans: ``(epoch_start_s, duration_s)`` pairs
            (:attr:`StepTimer.spans <dgmc_tpu.obs.observe.StepTimer>`).
        probe_records: probe record dicts (``probe``/``value``/``time``
            plus optional ``stage``/``iteration``), as delivered by
            :mod:`dgmc_tpu.obs.probes` sinks.
        compile_events: :class:`~dgmc_tpu.obs.registry.CompileWatcher`
            event dicts (``time`` is the event's END; ``duration_s``,
            ``kind``, ``label``).
        sections: ``(name, epoch_start_s, duration_s)`` triples (e.g.
            bench.py's section ledger).
        device_fences: ``(epoch_time_s, {device_id: completion_s})``
            pairs (``RunObserver.fence_devices``) — one counter track
            per device, so a straggler draws as the visibly-higher
            line.
    """
    starts = ([t for t, _ in step_spans]
              + [r['time'] for r in probe_records]
              + [e['time'] - e.get('duration_s', 0.0)
                 for e in compile_events]
              + [t for _, t, _ in sections]
              + [t for t, _ in device_fences])
    if not starts:
        return []
    origin = min(starts)

    events = [
        {'ph': 'M', 'pid': _PID, 'name': 'process_name',
         'args': {'name': 'dgmc run'}},
        {'ph': 'M', 'pid': _PID, 'tid': _TID_STEPS, 'name': 'thread_name',
         'args': {'name': 'steps'}},
        {'ph': 'M', 'pid': _PID, 'tid': _TID_COMPILE, 'name': 'thread_name',
         'args': {'name': 'xla compile'}},
    ]
    if sections:
        events.append({'ph': 'M', 'pid': _PID, 'tid': _TID_SECTIONS,
                       'name': 'thread_name', 'args': {'name': 'sections'}})

    for i, (t0, dur) in enumerate(step_spans):
        events.append({'ph': 'X', 'pid': _PID, 'tid': _TID_STEPS,
                       'name': f'step {i}', 'cat': 'step',
                       'ts': _us(t0, origin), 'dur': round(dur * 1e6, 1)})

    for e in compile_events:
        dur = e.get('duration_s', 0.0)
        events.append({'ph': 'X', 'pid': _PID, 'tid': _TID_COMPILE,
                       'name': e.get('kind', 'compile'), 'cat': 'compile',
                       'ts': _us(e['time'] - dur, origin),
                       'dur': round(dur * 1e6, 1),
                       'args': {'label': e.get('label', '')}})

    for name, t0, dur in sections:
        events.append({'ph': 'X', 'pid': _PID, 'tid': _TID_SECTIONS,
                       'name': name, 'cat': 'section',
                       'ts': _us(t0, origin), 'dur': round(dur * 1e6, 1)})

    for t, per_device in device_fences:
        for dev, dt in sorted(per_device.items()):
            events.append({'ph': 'C', 'pid': _PID,
                           'name': f'device_step[{dev}]', 'cat': 'fence',
                           'ts': _us(t, origin),
                           'args': {'completion_ms': round(dt * 1e3, 3)}})

    for r in probe_records:
        name = r.get('probe', '?')
        if name == 'nonfinite':
            # Only actual failures are trace-worthy; the all-finite checks
            # would bury the timeline under no-op instants.
            if r.get('value'):
                events.append({'ph': 'i', 'pid': _PID, 'tid': _TID_STEPS,
                               'name': f'nonfinite@{r.get("stage", "?")}',
                               'cat': 'probe', 's': 'p',
                               'ts': _us(r['time'], origin)})
            continue
        v = r.get('value')
        if not isinstance(v, (int, float)) or not math.isfinite(v):
            # NaN/inf are not valid JSON and would make the whole trace
            # unreadable in Perfetto — the very run worth reading. The
            # nonfinite instants above already mark the failure.
            continue
        track = name if 'stage' not in r else f'{name}[{r["stage"]}]'
        events.append({'ph': 'C', 'pid': _PID, 'name': track,
                       'cat': 'probe', 'ts': _us(r['time'], origin),
                       'args': {'value': v}})
    return events


def export_chrome_trace(path, step_spans=(), probe_records=(),
                        compile_events=(), sections=(), device_fences=(),
                        metadata=None):
    """Write a Chrome-trace JSON file; returns the number of events.

    Atomic (tmp + rename) so a run killed mid-flush leaves the previous
    complete trace, matching the other obs artifacts' contract.
    """
    events = chrome_events(step_spans=step_spans,
                           probe_records=probe_records,
                           compile_events=compile_events,
                           sections=sections,
                           device_fences=device_fences)
    payload = {'traceEvents': events, 'displayTimeUnit': 'ms'}
    if metadata:
        payload['otherData'] = metadata
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return len(events)


def add_profile_flag(parser):
    """Register the standard ``--profile-dir`` flag on an argparse
    parser (the whole-run ``jax.profiler.trace`` switch)."""
    parser.add_argument(
        '--profile-dir', '--profile_dir', dest='profile_dir', type=str,
        default=None,
        help='capture a jax.profiler trace of the whole run into this '
             'directory (open in TensorBoard or ui.perfetto.dev; the '
             'psi1/initial_corr/topk/consensus_iter/psi2 named scopes '
             'label the pipeline stages)')
    return parser


@contextlib.contextmanager
def profile_span(profile_dir):
    """``jax.profiler.trace`` over the enclosed region; no-op when
    ``profile_dir`` is falsy. The device-side counterpart of the
    host-side run trace — unlike the one-step ``--profile`` flag some
    CLIs keep, this covers everything inside the block. Host tracing
    instruments every dispatched op, so wrap SHORT runs; on
    syscall-filtered sandboxes the per-step overhead reaches orders of
    magnitude."""
    from dgmc_tpu.obs.observe import trace
    with trace(profile_dir):
        yield


def start_profile(profile_dir):
    """CLI-shaped :func:`profile_span`: enter the span now, return a
    handle whose ``close()`` ends it — and finalize at process exit if
    the run dies first (an exception mid-training must still leave a
    readable trace; that failing run is exactly the one worth
    profiling). ``close()`` is idempotent, so the success path's
    explicit call and the ``atexit`` hook coexist."""
    stack = contextlib.ExitStack()
    stack.enter_context(profile_span(profile_dir))
    atexit.register(stack.close)
    return stack
