"""Trace export: run telemetry as Chrome-trace/Perfetto JSON, and the
``--profile-dir`` profiler flag.

Two complementary trace sources:

1. **Host-side run trace** (:func:`export_chrome_trace`): the telemetry
   the :class:`~dgmc_tpu.obs.run.RunObserver` already collects — step
   spans, compile events, benchmark sections, probe events — serialized
   in the Chrome trace-event format. Open ``<obs_dir>/trace.json`` in
   `Perfetto <https://ui.perfetto.dev>`_ (or ``chrome://tracing``): steps
   render as duration slices, XLA compiles as slices on their own track,
   and every numeric probe (``corr_entropy``, ``consensus_delta``,
   ``grad_norm``, ...) as a counter track — the sharpening curve drawn
   over the run's real timeline. No jax import needed; this works on a
   box that only has the artifacts.
2. **Device-side profiler trace** (:func:`profile_span` behind
   ``--profile-dir`` on the experiment CLIs and ``bench.py``):
   ``jax.profiler.trace`` over the whole run, viewable in
   TensorBoard/Perfetto, where the model's ``jax.named_scope`` stage
   annotations (``psi1`` -> ``initial_corr``/``topk`` ->
   ``consensus_iter``/``psi2``) label the XLA ops. This is the
   MXU-idle/HBM-stall view; the run trace above is the what-did-the-host
   -do view.

The trace-event records follow the documented Chrome format: ``ph: 'X'``
complete events with microsecond ``ts``/``dur``, ``ph: 'C'`` counters,
``ph: 'i'`` instants.
"""

import argparse
import atexit
import contextlib
import json
import math
import os
import re
import sys

#: Track ids inside the single "dgmc run" process row.
_TID_STEPS = 1
_TID_COMPILE = 2
_TID_SECTIONS = 3
_PID = 1


def _us(t, origin):
    return round((t - origin) * 1e6, 1)


def chrome_events(step_spans=(), probe_records=(), compile_events=(),
                  sections=(), device_fences=()):
    """Build the ``traceEvents`` list from host telemetry.

    Args:
        step_spans: ``(epoch_start_s, duration_s)`` pairs
            (:attr:`StepTimer.spans <dgmc_tpu.obs.observe.StepTimer>`).
        probe_records: probe record dicts (``probe``/``value``/``time``
            plus optional ``stage``/``iteration``), as delivered by
            :mod:`dgmc_tpu.obs.probes` sinks.
        compile_events: :class:`~dgmc_tpu.obs.registry.CompileWatcher`
            event dicts (``time`` is the event's END; ``duration_s``,
            ``kind``, ``label``).
        sections: ``(name, epoch_start_s, duration_s)`` triples (e.g.
            bench.py's section ledger).
        device_fences: ``(epoch_time_s, {device_id: completion_s})``
            pairs (``RunObserver.fence_devices``) — one counter track
            per device, so a straggler draws as the visibly-higher
            line.
    """
    starts = ([t for t, _ in step_spans]
              + [r['time'] for r in probe_records]
              + [e['time'] - e.get('duration_s', 0.0)
                 for e in compile_events]
              + [t for _, t, _ in sections]
              + [t for t, _ in device_fences])
    if not starts:
        return []
    origin = min(starts)

    events = [
        {'ph': 'M', 'pid': _PID, 'name': 'process_name',
         'args': {'name': 'dgmc run'}},
        {'ph': 'M', 'pid': _PID, 'tid': _TID_STEPS, 'name': 'thread_name',
         'args': {'name': 'steps'}},
        {'ph': 'M', 'pid': _PID, 'tid': _TID_COMPILE, 'name': 'thread_name',
         'args': {'name': 'xla compile'}},
    ]
    if sections:
        events.append({'ph': 'M', 'pid': _PID, 'tid': _TID_SECTIONS,
                       'name': 'thread_name', 'args': {'name': 'sections'}})

    for i, (t0, dur) in enumerate(step_spans):
        events.append({'ph': 'X', 'pid': _PID, 'tid': _TID_STEPS,
                       'name': f'step {i}', 'cat': 'step',
                       'ts': _us(t0, origin), 'dur': round(dur * 1e6, 1)})

    for e in compile_events:
        dur = e.get('duration_s', 0.0)
        events.append({'ph': 'X', 'pid': _PID, 'tid': _TID_COMPILE,
                       'name': e.get('kind', 'compile'), 'cat': 'compile',
                       'ts': _us(e['time'] - dur, origin),
                       'dur': round(dur * 1e6, 1),
                       'args': {'label': e.get('label', '')}})

    for name, t0, dur in sections:
        events.append({'ph': 'X', 'pid': _PID, 'tid': _TID_SECTIONS,
                       'name': name, 'cat': 'section',
                       'ts': _us(t0, origin), 'dur': round(dur * 1e6, 1)})

    for t, per_device in device_fences:
        for dev, dt in sorted(per_device.items()):
            events.append({'ph': 'C', 'pid': _PID,
                           'name': f'device_step[{dev}]', 'cat': 'fence',
                           'ts': _us(t, origin),
                           'args': {'completion_ms': round(dt * 1e3, 3)}})

    for r in probe_records:
        name = r.get('probe', '?')
        if name == 'nonfinite':
            # Only actual failures are trace-worthy; the all-finite checks
            # would bury the timeline under no-op instants.
            if r.get('value'):
                events.append({'ph': 'i', 'pid': _PID, 'tid': _TID_STEPS,
                               'name': f'nonfinite@{r.get("stage", "?")}',
                               'cat': 'probe', 's': 'p',
                               'ts': _us(r['time'], origin)})
            continue
        v = r.get('value')
        if not isinstance(v, (int, float)) or not math.isfinite(v):
            # NaN/inf are not valid JSON and would make the whole trace
            # unreadable in Perfetto — the very run worth reading. The
            # nonfinite instants above already mark the failure.
            continue
        track = name if 'stage' not in r else f'{name}[{r["stage"]}]'
        events.append({'ph': 'C', 'pid': _PID, 'name': track,
                       'cat': 'probe', 'ts': _us(r['time'], origin),
                       'args': {'value': v}})
    return events


def export_chrome_trace(path, step_spans=(), probe_records=(),
                        compile_events=(), sections=(), device_fences=(),
                        metadata=None):
    """Write a Chrome-trace JSON file; returns the number of events.

    Atomic (tmp + rename) so a run killed mid-flush leaves the previous
    complete trace, matching the other obs artifacts' contract.
    """
    events = chrome_events(step_spans=step_spans,
                           probe_records=probe_records,
                           compile_events=compile_events,
                           sections=sections,
                           device_fences=device_fences)
    payload = {'traceEvents': events, 'displayTimeUnit': 'ms'}
    if metadata:
        payload['otherData'] = metadata
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return len(events)


def add_profile_flag(parser):
    """Register the standard ``--profile-dir`` / ``--profile-steps``
    flags on an argparse parser (the ``jax.profiler.trace`` switch:
    whole-run by default, a step window with ``--profile-steps``)."""
    parser.add_argument(
        '--profile-dir', '--profile_dir', dest='profile_dir', type=str,
        default=None,
        help='capture a jax.profiler trace into this directory (open in '
             'TensorBoard or ui.perfetto.dev, or feed it to `python -m '
             'dgmc_tpu.obs.attribution`; the psi1/initial_corr/topk/'
             'consensus_iter/psi2 named scopes label the pipeline '
             'stages). Whole-run by default; see --profile-steps')
    parser.add_argument(
        '--profile-steps', '--profile_steps', dest='profile_steps',
        type=_step_window_arg, default=None, metavar='A:B',
        help='window the --profile-dir capture to steps [A, B): the '
             'trace starts at step boundary A and stops at boundary B '
             '(whole-run traces are unboundedly large on long runs). '
             'Armed at the existing step boundaries; pick A >= 1 to '
             'keep the first step\'s JIT compile out of the window. '
             'The run ending early still finalizes a readable trace')
    return parser


def _step_window_arg(spec):
    """argparse ``type=`` wrapper: a typo'd window must fail at PARSE
    time with the parser's usage message, not minutes later when
    ``start_profile`` runs after dataset load and the first lowering."""
    try:
        return parse_step_window(spec)
    except ValueError as e:
        raise argparse.ArgumentTypeError(str(e))


def parse_step_window(spec):
    """``'A:B'`` -> ``(A, B)``, the half-open step window ``[A, B)``
    (python-slice convention: ``0:4`` captures the first four steps).
    Raises ``ValueError`` on malformed or empty windows — a typo'd
    window must fail the CLI at parse time, not silently profile
    nothing."""
    m = re.fullmatch(r'(\d+):(\d+)', str(spec).strip())
    if not m:
        raise ValueError(
            f'--profile-steps expects A:B step indices (e.g. 10:14), '
            f'got {spec!r}')
    a, b = int(m.group(1)), int(m.group(2))
    if b <= a:
        raise ValueError(f'--profile-steps window [{a}, {b}) is empty')
    return a, b


@contextlib.contextmanager
def profile_span(profile_dir):
    """``jax.profiler.trace`` over the enclosed region; no-op when
    ``profile_dir`` is falsy. The device-side counterpart of the
    host-side run trace — unlike the one-step ``--profile`` flag some
    CLIs keep, this covers everything inside the block. Host tracing
    instruments every dispatched op, so wrap SHORT runs; on
    syscall-filtered sandboxes the per-step overhead reaches orders of
    magnitude."""
    from dgmc_tpu.obs.observe import trace
    with trace(profile_dir):
        yield


class ProfileHandle:
    """The CLI-shaped profiler switch behind ``--profile-dir`` /
    ``--profile-steps``.

    Whole-run mode (``steps=None``, the default): the span is entered
    at construction and ``close()`` (or process exit, via ``atexit``)
    ends it — an exception mid-training must still leave a readable
    trace; that failing run is exactly the one worth profiling.

    Step-window mode (``steps='A:B'`` or ``(A, B)``): nothing starts
    at construction. :meth:`on_step` — called at every step boundary
    (``RunObserver.attach_profiler`` wires it on the experiment CLIs;
    ``bench.py`` calls it from its measured loops) — enters the span
    at boundary ``A`` and stops it at boundary ``B``, so the capture
    covers exactly the steps ``[A, B)``. Pick ``A >= 1`` to keep
    startup compiles out on CLIs whose first step JIT-compiles
    (boundary 0 opens the span *before* step 0 runs).
    A run that ends inside the window still finalizes the trace via
    ``close()``/``atexit``; a window the run never reaches records
    nothing. The window fires once — it never re-arms.

    :meth:`step_annotation` wraps a step body in
    ``jax.profiler.StepTraceAnnotation`` while the span is open, so
    the exported trace carries per-step markers
    (:data:`dgmc_tpu.obs.attribution.STEP_ANNOTATION`) the attribution
    CLI normalizes device-active time by.
    """

    def __init__(self, profile_dir, steps=None):
        self._dir = profile_dir
        if isinstance(steps, str):
            steps = parse_step_window(steps)
        self._window = steps
        if steps is not None and not profile_dir:
            print('start_profile: --profile-steps is ignored without '
                  '--profile-dir (there is no capture to window)',
                  file=sys.stderr)
            self._window = None
        self._seen = 0
        self._stack = None
        self._fired = False
        if self._dir and self._window is None:
            self._enter()
        atexit.register(self.close)

    @property
    def active(self):
        """True while the profiler span is open."""
        return self._stack is not None

    def _enter(self):
        if self._stack is None and not self._fired:
            self._fired = True
            stack = contextlib.ExitStack()
            stack.enter_context(profile_span(self._dir))
            self._stack = stack

    def _exit(self):
        if self._stack is not None:
            stack, self._stack = self._stack, None
            stack.close()

    def on_step(self):
        """Advance the step counter; open/close the windowed span at
        its boundaries (a no-op switch in whole-run mode)."""
        i = self._seen
        self._seen += 1
        if not self._dir or self._window is None:
            return
        a, b = self._window
        if i >= b:
            self._exit()
        elif i >= a:
            self._enter()

    def step_annotation(self, step=None):
        """Context manager marking one step inside an open span
        (``jax.profiler.StepTraceAnnotation``); a no-op while the
        profiler is not capturing. ``step`` defaults to the handle's
        own boundary counter."""
        if self._stack is None:
            return contextlib.nullcontext()
        if step is None:
            step = max(self._seen - 1, 0)
        import jax
        from dgmc_tpu.obs.attribution import STEP_ANNOTATION
        return jax.profiler.StepTraceAnnotation(STEP_ANNOTATION,
                                                step_num=step)

    def close(self):
        """Finalize the trace if a span is open. Idempotent, so the
        success path's explicit call and the ``atexit`` hook coexist."""
        self._exit()


def start_profile(profile_dir, steps=None):
    """Build the profiler handle for a CLI: whole-run capture when
    ``steps`` is None (the long-standing behavior), a ``[A, B)`` step
    window when ``steps`` is ``'A:B'``/``(A, B)`` — see
    :class:`ProfileHandle`."""
    return ProfileHandle(profile_dir, steps=steps)
