"""Padding-waste accounting: goodput = useful FLOPs ÷ executed FLOPs.

Every padded batch this repo builds (``utils/data.pad_graphs`` /
``pad_pair_batch``, the serve router's ``pad_query``) executes the full
bucket shape whatever the real graph sizes were — the masked rows cost
real FLOPs that no metric so far accounted for. This module turns the
validity masks the collation layer already builds (and, post-hoc, the
real-size totals the padding telemetry now records) into:

- **fill fractions** — real ÷ padded, per axis (source/target nodes and
  edges, plus the correspondence axis ``corr = node_fill_s ·
  node_fill_t``, the axis the O(N_s·N_t)-shaped stages scale on);
- a **goodput ratio** — useful FLOPs ÷ executed FLOPs, composed with
  ``obs/cost.stage_table``'s per-stage FLOP attribution when available
  (each stage discounts along the axis its cost scales with,
  :data:`STAGE_AXES`), else the conservative mask-only fallback;
- the ``goodput.json`` artifact (:func:`payload_from_rows`) rebuilt
  from any recorded obs dir's padding rows — pad waste is recomputable
  post-hoc, not just live.

Like every obs reader/writer on the artifact path, this module has
**no jax import**: it must account a dead run's padding on any box.
"""

import math

__all__ = ['STAGE_AXES', 'fill_fraction', 'mask_fills', 'pair_fills',
           'goodput_ratio', 'row_fills', 'payload_from_rows',
           'merge_real_rows']

#: Which fill axis each cost stage's FLOPs scale along
#: (``analysis/hlo_comm.STAGE_NAMES`` vocabulary — one dialect, no
#: third): the ψ nets are message passing over edges; the
#: correspondence/shortlist/consensus stages carry O(N_s·N_t)-shaped
#: work; loss reductions scale with source nodes; the optimizer touches
#: parameters only (no padding axis at all — fill 1.0).
STAGE_AXES = {
    'psi1': 'edges',
    'psi2': 'edges',
    'initial_corr': 'corr',
    'topk': 'corr',
    'consensus_iter': 'corr',
    'loss': 'nodes',
    'optimizer': 'none',
    'other': 'nodes',
}


def fill_fraction(real, padded):
    """real ÷ padded, clamped to [0, 1]; ``None`` when undefined."""
    try:
        real, padded = float(real), float(padded)
    except (TypeError, ValueError):
        return None
    if padded <= 0 or not math.isfinite(real) or not math.isfinite(padded):
        return None
    return max(0.0, min(1.0, real / padded))


def mask_fills(node_mask, edge_mask):
    """Fill account of one padded ``GraphBatch`` side from its validity
    masks (``[B, N]`` / ``[B, E]`` bool arrays — any object exposing
    ``.sum()`` and ``.size`` works; no jax import)."""
    return {
        'nodes_real': int(node_mask.sum()),
        'nodes_padded': int(node_mask.size),
        'edges_real': int(edge_mask.sum()),
        'edges_padded': int(edge_mask.size),
    }


def _axis_fills(nodes_real, nodes_padded, edges_real, edges_padded,
                node_fill_s=None, node_fill_t=None):
    fills = {
        'nodes': fill_fraction(nodes_real, nodes_padded),
        'edges': fill_fraction(edges_real, edges_padded),
    }
    if node_fill_s is not None and node_fill_t is not None:
        fills['corr'] = node_fill_s * node_fill_t
    else:
        fills['corr'] = fills['nodes']
    return fills


def pair_fills(s_account, t_account):
    """Combined fill fractions for a padded pair (two
    :func:`mask_fills` accounts): per-axis real ÷ padded over both
    sides, plus the correspondence axis ``corr`` = node fill of the
    source side × node fill of the target side."""
    nf_s = fill_fraction(s_account['nodes_real'], s_account['nodes_padded'])
    nf_t = fill_fraction(t_account['nodes_real'], t_account['nodes_padded'])
    return _axis_fills(
        s_account['nodes_real'] + t_account['nodes_real'],
        s_account['nodes_padded'] + t_account['nodes_padded'],
        s_account['edges_real'] + t_account['edges_real'],
        s_account['edges_padded'] + t_account['edges_padded'],
        node_fill_s=nf_s, node_fill_t=nf_t)


def goodput_ratio(fills, stages=None):
    """Useful FLOPs ÷ executed FLOPs for one padded execution.

    ``fills`` is an axis→fill dict (:func:`pair_fills` /
    :func:`row_fills` output). With a ``stages`` table
    (``obs/cost.stage_table``: ``{stage: {'flops', ...}}``) each
    stage's FLOPs are discounted along its :data:`STAGE_AXES` axis and
    the ratio is the FLOP-weighted mean; without one, the conservative
    fallback is the smallest defined axis fill (every stage scales
    along SOME padded axis, so no stage can be more useful than the
    emptiest axis claims).
    """
    if stages:
        useful = executed = 0.0
        for stage, row in stages.items():
            flops = float(row.get('flops') or 0) or float(
                row.get('bytes_out') or 0)
            if flops <= 0:
                continue
            axis = STAGE_AXES.get(stage, 'nodes')
            fill = 1.0 if axis == 'none' else fills.get(axis)
            if fill is None:
                fill = _fallback_fill(fills)
                if fill is None:
                    continue
            executed += flops
            useful += flops * fill
        if executed > 0:
            return useful / executed
    return _fallback_fill(fills)


def _fallback_fill(fills):
    defined = [v for v in fills.values() if v is not None]
    return min(defined) if defined else None


def _split_pair(value):
    try:
        a, b = str(value).split('x')
        return int(a), int(b)
    except (ValueError, AttributeError):
        return None, None


def row_fills(row):
    """Fill fractions recomputed from one recorded padding-bucket row
    (``registry.padding_bucket_table`` format plus the
    ``real_nodes_s/real_nodes_t/real_edges_s/real_edges_t`` totals the
    collation layer records). ``None`` when the row predates the real-
    size account — absence is honest, never guessed."""
    reals = [row.get(k) for k in ('real_nodes_s', 'real_nodes_t',
                                  'real_edges_s', 'real_edges_t')]
    if any(v is None for v in reals):
        return None
    n_s, n_t = _split_pair(row.get('nodes'))
    e_s, e_t = _split_pair(row.get('edges'))
    if None in (n_s, n_t, e_s, e_t):
        return None
    collations = int(row.get('count', 0)) * int(row.get('batch', 1) or 1)
    if collations <= 0:
        return None
    rn_s, rn_t, re_s, re_t = (int(v) for v in reals)
    nf_s = fill_fraction(rn_s, collations * n_s)
    nf_t = fill_fraction(rn_t, collations * n_t)
    return _axis_fills(rn_s + rn_t, collations * (n_s + n_t),
                       re_s + re_t, collations * (e_s + e_t),
                       node_fill_s=nf_s, node_fill_t=nf_t)


def merge_real_rows(bucket_rows, real_rows):
    """Join the real-size totals (``registry.padding_real_table`` rows:
    ``{batch, nodes, edges, axis, count}``) onto their padding-bucket
    rows as ``real_<axis>`` fields. Rows without a recorded real
    account pass through untouched — the extra FIELDS are signature-
    safe (``analysis/recompile.bucket_signature`` hashes only
    batch/nodes/edges), so the recompile lint and the serve router see
    the same bucket identity they always did."""
    reals = {}
    for r in real_rows or []:
        key = (r.get('batch'), r.get('nodes'), r.get('edges'))
        reals.setdefault(key, {})[f'real_{r.get("axis")}'] = r.get('count')
    out = []
    for row in bucket_rows or []:
        extra = reals.get((row.get('batch'), row.get('nodes'),
                           row.get('edges')))
        out.append(dict(row, **extra) if extra else dict(row))
    return out


def payload_from_rows(rows, stages=None, source='padding_bucket_table'):
    """The ``goodput.json`` body from (merged) padding rows.

    Per-bucket pad fraction + goodput ratio, and the collation-weighted
    aggregate — weighted by each bucket's executed (padded) node total,
    the closest artifact-only proxy for its executed FLOPs. ``stages``
    (``obs/cost.stage_table`` output) upgrades every ratio from the
    mask-only fallback to the FLOP-composed account. ``None`` when no
    row carries the real-size account (an old recording) — the diff
    gate's lost-account rule needs absence to stay absent.
    """
    buckets = []
    agg_useful = agg_weight = 0.0
    for row in rows or []:
        fills = row_fills(row)
        if fills is None:
            continue
        ratio = goodput_ratio(fills, stages)
        n_s, n_t = _split_pair(row.get('nodes'))
        weight = (int(row.get('count', 0))
                  * int(row.get('batch', 1) or 1)
                  * ((n_s or 0) + (n_t or 0)))
        buckets.append({
            'batch': row.get('batch'),
            'nodes': row.get('nodes'),
            'edges': row.get('edges'),
            'count': row.get('count'),
            'node_fill': _round(fills.get('nodes')),
            'edge_fill': _round(fills.get('edges')),
            'corr_fill': _round(fills.get('corr')),
            'pad_fraction': _round(1.0 - fills['nodes']
                                   if fills.get('nodes') is not None
                                   else None),
            'goodput_ratio': _round(ratio),
        })
        if ratio is not None and weight > 0:
            agg_useful += ratio * weight
            agg_weight += weight
    if not buckets:
        return None
    ratio = agg_useful / agg_weight if agg_weight > 0 else None
    pads = [b['pad_fraction'] for b in buckets
            if b['pad_fraction'] is not None]
    return {
        'source': source,
        'composed_with_stage_flops': bool(stages),
        'goodput_ratio': _round(ratio),
        'pad_fraction_max': _round(max(pads)) if pads else None,
        'buckets': buckets,
    }


def _round(v, digits=6):
    return None if v is None else round(float(v), digits)
