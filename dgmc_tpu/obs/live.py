"""Live telemetry plane: ``/healthz`` + ``/metrics`` + ``/status``, the
streaming latency histogram, and the anomaly flight recorder.

Every obs layer before this one is **post-hoc**: ``RunObserver`` writes
``metrics.jsonl``/``timings.json``, the watchdog dumps
``hang_report.json``, and the supervisor learns about a child's health
by polling heartbeat *files*. An online serving process (ROADMAP item 1)
cannot be load-tested or supervised that way — it needs a health check a
prober can hit, metrics a scraper can pull, and a "what happened in the
last N steps" record when something dies. This module is that surface,
armed via ``--obs-port`` through the same :func:`~dgmc_tpu.obs.run.
add_obs_flag` path as every other obs knob:

- ``GET /healthz`` — watchdog heartbeat age, the in-flight span, guard
  skip/consec-bad gauges, recovery/elastic state. Returns **503 when
  the heartbeat is stale** under the SAME staleness definition the run
  supervisor applies to the heartbeat file
  (:data:`STALE_AFTER_FACTOR` × the watchdog deadline), so an external
  prober and the supervisor share one notion of "wedged".
- ``GET /metrics`` — Prometheus text exposition: the step-latency
  **streaming fixed-bucket histogram** (:class:`StreamingHistogram`,
  O(1) memory instead of the unbounded per-step list), throughput,
  compile counts per label, kernel-dispatch outcome counters, probe
  gauges, and MFU / arithmetic intensity from the last efficiency
  snapshot.
- ``GET /status`` — the full live ``timings.json`` summary as JSON.

Alongside the endpoints, the **flight recorder**
(:class:`FlightRecorder`): an always-on bounded ring buffer of the last
N span completions, probe values, kernel-dispatch decisions and compile
events, dumped as ``flight.json`` on any anomaly — a watchdog deadline
trip, a fence timeout, a guard rollback, a SIGTERM/SIGKILL-adjacent
teardown, a supervisor kill. ``hang_report.json`` says where the run
*is* (stack dump); ``flight.json`` says what it *did on the way there*
(trailing context) — the two halves of a post-mortem.

This module deliberately has **no jax import** (stdlib plus the
equally import-light ``utils.io`` atomic writer): the server thread
must answer while jax is wedged — that is exactly when the probe
matters — and the supervisor/aggregate scrape helpers run in jax-free
monitor processes.
"""

import bisect
import collections
import http.server
import inspect
import json
import math
import os
import sys
import threading
import time

from dgmc_tpu.utils.io import write_json_atomic

__all__ = ['StreamingHistogram', 'FlightRecorder', 'TelemetryServer',
           'prometheus_exposition', 'probe_healthz',
           'DEFAULT_LATENCY_BOUNDS', 'DEFAULT_FLIGHT_CAPACITY',
           'STALE_AFTER_FACTOR']

#: One health definition for everyone: a heartbeat older than
#: ``STALE_AFTER_FACTOR x the watchdog deadline`` means "wedged". The
#: in-process ``/healthz`` handler and the out-of-process supervisor's
#: heartbeat-file watch (``resilience/supervisor.py``) both apply it,
#: so a 503 and a ``heartbeat-stale`` kill are the same verdict reached
#: from two vantage points.
STALE_AFTER_FACTOR = 2.0

#: Step-latency histogram bounds (seconds): powers of two from 1 ms to
#: ~35 min. Steps on this codebase genuinely span that range — sub-ms
#: CPU smoke steps to the 412 s streamed million-entity steps of
#: ``SCALE_r07.json`` — and exponential buckets keep the relative
#: error of any quantile estimate bounded by the factor-of-2 spacing.
DEFAULT_LATENCY_BOUNDS = tuple(0.001 * 2 ** i for i in range(22))

#: Flight-recorder ring capacity. At one span pair per step plus a
#: handful of probe/dispatch/compile events, 1024 events cover the
#: last few hundred steps — the trailing context a hang report lacks —
#: in a few hundred KiB of memory, always-on.
DEFAULT_FLIGHT_CAPACITY = 1024


class StreamingHistogram:
    """Fixed-bucket streaming histogram with O(1) memory.

    The per-step latency list ``StepTimer`` keeps grows without bound —
    fine for a 200-epoch training run, wrong for a serving process that
    must hold its p95 account over millions of queries. This histogram
    is the O(1) replacement: ``len(bounds)+1`` integer counters, a sum
    and a count, observed in O(log buckets) per event, rendered as a
    standard Prometheus cumulative histogram.

    Bucket semantics match Prometheus: bucket ``le=B`` counts
    observations ``<= B``; the implicit last bucket is ``+Inf``.
    """

    def __init__(self, bounds=DEFAULT_LATENCY_BOUNDS):
        bounds = tuple(float(b) for b in bounds)
        if not bounds:
            raise ValueError('histogram needs at least one bucket bound')
        if any(b2 <= b1 for b1, b2 in zip(bounds, bounds[1:])):
            raise ValueError('bucket bounds must be strictly increasing: '
                             f'{bounds}')
        if not all(math.isfinite(b) for b in bounds):
            raise ValueError('bucket bounds must be finite '
                             '(+Inf is implicit)')
        self.bounds = bounds
        self._counts = [0] * (len(bounds) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value):
        value = float(value)
        # First bound >= value, i.e. the smallest bucket whose
        # ``le`` covers it (Prometheus ``<=`` semantics).
        i = bisect.bisect_left(self.bounds, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def snapshot(self):
        """``{'buckets': [(le, cumulative_count), ...], 'sum', 'count'}``
        with the final ``+Inf`` bucket equal to ``count`` — the exact
        shape the exposition renders."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
            acc = self._sum
        buckets, cum = [], 0
        for bound, c in zip(self.bounds, counts):
            cum += c
            buckets.append((bound, cum))
        buckets.append((math.inf, total))
        return {'buckets': buckets, 'sum': acc, 'count': total}

    def quantile(self, q):
        """Upper bucket edge containing the q-quantile (``None`` when
        empty) — a conservative estimate whose error is bounded by the
        bucket spacing, cross-checked against the exact
        :func:`~dgmc_tpu.obs.observe.percentile` in tests."""
        if not 0.0 <= q <= 1.0:
            raise ValueError(f'quantile must be in [0, 1], got {q}')
        snap = self.snapshot()
        if not snap['count']:
            return None
        rank = q * snap['count']
        for bound, cum in snap['buckets']:
            if cum >= rank:
                return bound
        return math.inf


def _json_safe(obj):
    """Copy with non-finite floats replaced by ``None``: NaN/inf are not
    valid JSON and one poisoned probe value must not make the whole
    flight record unparseable — the poisoned run is the one worth
    reading (same contract as ``MetricLogger``)."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _json_safe(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_json_safe(v) for v in obj]
    return obj


class FlightRecorder:
    """Bounded ring buffer of run events, dumped on anomaly.

    :meth:`record` is cheap (one dict build + deque append under a
    lock) so it stays on for the whole run; the ring keeps the LAST
    ``capacity`` events and counts what it evicted — a dump over a
    clipped window says so (``events_truncated``), never silently.

    :meth:`dump` is deliberately **lock-free** (snapshot reads only):
    it is called from the watchdog's signal path, where the interrupted
    main thread may hold any lock, including this recorder's. The
    record side takes the lock; the dump side never does.
    """

    def __init__(self, path=None, capacity=DEFAULT_FLIGHT_CAPACITY):
        self.path = path
        self.capacity = int(capacity)
        if self.capacity <= 0:
            raise ValueError(f'capacity must be positive: {capacity}')
        self._events = collections.deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.seen = 0
        self.dump_count = 0
        self.last_reason = None

    def record(self, kind, **fields):
        rec = {'time': time.time(), 'kind': kind}
        rec.update(fields)
        with self._lock:
            self._events.append(rec)
            self.seen += 1

    @property
    def recorded(self):
        return len(self._events)

    @property
    def truncated(self):
        """Events evicted by the ring cap (seen - kept)."""
        return max(0, self.seen - len(self._events))

    def snapshot(self):
        return list(self._events)

    def counters(self):
        return {'events_seen': self.seen,
                'events_recorded': self.recorded,
                'events_truncated': self.truncated,
                'dumps': self.dump_count}

    def dump(self, reason, extra=None, path=None):
        """Write ``flight.json`` now; returns the path (``None`` when
        no path is configured or the write failed — a recorder must
        never raise into the run it records). Lock-free: safe from the
        signal path."""
        path = path or self.path
        if not path:
            return None
        # list(deque) without the lock: atomic enough in CPython, and
        # the signal path must not block on a lock the interrupted
        # thread may hold mid-record.
        events = list(self._events)
        payload = {
            'reason': reason,
            'time': time.time(),
            'pid': os.getpid(),
            'argv': sys.argv,
            'capacity': self.capacity,
            'events_seen': self.seen,
            'events_recorded': len(events),
            'events_truncated': max(0, self.seen - len(events)),
            'events': _json_safe(events),
        }
        if extra:
            payload.update(_json_safe(dict(extra)))
        if not write_json_atomic(path, payload, indent=1, quiet=True,
                                 default=str):
            return None
        self.dump_count += 1
        self.last_reason = reason
        return path


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

def _metric_name(name):
    """Sanitize to the metric-name grammar
    ``[a-zA-Z_:][a-zA-Z0-9_:]*`` (invalid chars become ``_``)."""
    out = ''.join(c if c.isascii() and (c.isalnum() or c in '_:')
                  else '_' for c in str(name))
    if not out or not (out[0].isalpha() or out[0] in '_:'):
        out = '_' + out
    return out


def _label_name(name):
    out = ''.join(c if c.isascii() and (c.isalnum() or c == '_')
                  else '_' for c in str(name))
    if not out or not (out[0].isalpha() or out[0] == '_'):
        out = '_' + out
    return out


def _escape_label_value(value):
    return (str(value).replace('\\', r'\\').replace('"', r'\"')
            .replace('\n', r'\n'))


def _escape_help(text):
    return str(text).replace('\\', r'\\').replace('\n', r'\n')


def _fmt_value(v):
    if isinstance(v, bool):
        return '1' if v else '0'
    if isinstance(v, int):
        return str(v)
    v = float(v)
    if math.isnan(v):
        return 'NaN'
    if math.isinf(v):
        return '+Inf' if v > 0 else '-Inf'
    return repr(v)


def _sample(name, labels, value):
    if not labels:
        return f'{name} {_fmt_value(value)}'
    inner = ','.join(
        f'{_label_name(k)}="{_escape_label_value(v)}"'
        for k, v in labels.items())
    return f'{name}{{{inner}}} {_fmt_value(value)}'


def prometheus_exposition(families):
    """Render metric families as the Prometheus text format (0.0.4).

    ``families`` is an iterable of ``(name, type, help, samples)`` where
    ``samples`` is a list of ``(suffix, labels_dict, value)`` — suffix
    is appended to the family name (``_bucket``/``_sum``/``_count`` for
    histograms, ``''`` otherwise). Names and label names are sanitized
    to the exposition grammar; label values and help text are escaped.
    Ends with the mandatory trailing newline.
    """
    lines = []
    for name, mtype, help_text, samples in families:
        name = _metric_name(name)
        if help_text:
            lines.append(f'# HELP {name} {_escape_help(help_text)}')
        lines.append(f'# TYPE {name} {mtype}')
        for suffix, labels, value in samples:
            lines.append(_sample(name + suffix, labels or {}, value))
    return '\n'.join(lines) + '\n'


def histogram_family(name, help_text, hist_snapshot):
    """One histogram family from a :meth:`StreamingHistogram.snapshot`
    (the ``le`` label rendering, ``+Inf`` spelling included)."""
    samples = []
    for bound, cum in hist_snapshot['buckets']:
        le = '+Inf' if math.isinf(bound) else _fmt_value(float(bound))
        samples.append(('_bucket', {'le': le}, cum))
    samples.append(('_sum', {}, hist_snapshot['sum']))
    samples.append(('_count', {}, hist_snapshot['count']))
    return (name, 'histogram', help_text, samples)


# ---------------------------------------------------------------------------
# HTTP plane
# ---------------------------------------------------------------------------

def _accepts_headers(handler):
    """Whether a route handler declares the optional third positional
    parameter (request headers). Decided ONCE at mount time from the
    signature — never by catching ``TypeError`` at call time, which
    would mask genuine arity bugs inside the handler."""
    try:
        sig = inspect.signature(handler)
    except (TypeError, ValueError):
        return True
    params = list(sig.parameters.values())
    if any(p.kind == p.VAR_POSITIONAL for p in params):
        return True
    positional = [p for p in params
                  if p.kind in (p.POSITIONAL_ONLY,
                                p.POSITIONAL_OR_KEYWORD)]
    return len(positional) >= 3


class TelemetryServer:
    """Threaded HTTP server for the three endpoints (plus app routes).

    Args:
        port: TCP port (0 = pick a free one; the chosen port is in
            :attr:`port` after :meth:`start` and is what the observer
            advertises in ``heartbeat.json``).
        health_fn: 0-arg callable returning the ``/healthz`` JSON dict;
            a falsy ``'healthy'`` key turns the response into a 503.
        metrics_fn: 0-arg callable returning the ``/metrics`` exposition
            text.
        status_fn: 0-arg callable returning the ``/status`` JSON dict.
        host: bind address (default all interfaces — an external
            prober/scraper is the point of the plane).
        routes: optional ``{path: handler}`` application endpoints
            mounted BESIDE the telemetry trio (the serving subsystem's
            ``/match`` joins ``/healthz``/``/metrics``/``/status`` on
            one port this way). A handler is called as
            ``handler(method, body_bytes)`` — GET arrives with
            ``body_bytes=b''`` — and returns ``(status_code,
            payload_dict)``; the payload is rendered as JSON. Returning
            a 4xx/5xx code is the structured-error path (the router's
            unknown-bucket 400). A handler that *raises* still yields
            the generic 500, like the telemetry callbacks.

            A handler that declares a THIRD positional parameter is
            additionally passed the request headers as a lowercase-keyed
            dict (``handler(method, body_bytes, headers)``) — how the
            serve plane receives ``traceparent`` — and any handler may
            return a 3-tuple ``(status_code, payload_dict,
            response_headers_dict)`` to attach extra response headers
            (the trace-context echo). Two-argument handlers and
            2-tuple returns keep working unchanged.

    A callback that raises yields a 500 carrying the error text; the
    serving thread itself must survive anything the callbacks do.
    """

    def __init__(self, port, health_fn=None, metrics_fn=None,
                 status_fn=None, host='', routes=None):
        self._requested_port = int(port)
        self._host = host
        self._health_fn = health_fn
        self._metrics_fn = metrics_fn
        self._status_fn = status_fn
        self._routes = dict(routes or {})
        self._route_takes_headers = {
            path: _accepts_headers(fn) for path, fn in self._routes.items()}
        self._server = None
        self._thread = None
        self.port = None

    def start(self):
        plane = self

        class Handler(http.server.BaseHTTPRequestHandler):
            server_version = 'dgmc-obs'
            protocol_version = 'HTTP/1.1'

            def log_message(self, *args):   # no stderr chatter per scrape
                pass

            def _respond(self, code, body, ctype, extra_headers=None):
                data = body.encode('utf-8')
                self.send_response(code)
                self.send_header('Content-Type', ctype)
                self.send_header('Content-Length', str(len(data)))
                for k, v in (extra_headers or {}).items():
                    self.send_header(str(k), str(v))
                self.end_headers()
                self.wfile.write(data)

            def _json(self, code, payload, extra_headers=None):
                self._respond(code, json.dumps(_json_safe(payload),
                                               indent=1),
                              'application/json; charset=utf-8',
                              extra_headers)

            def _endpoints(self):
                return (['/healthz', '/metrics', '/status']
                        + sorted(plane._routes))

            def _dispatch(self, method):
                path = self.path.split('?', 1)[0].rstrip('/') or '/'
                try:
                    if path in plane._routes:
                        n = int(self.headers.get('Content-Length') or 0)
                        body = self.rfile.read(n) if n else b''
                        handler = plane._routes[path]
                        if plane._route_takes_headers.get(path):
                            hdrs = {k.lower(): v
                                    for k, v in self.headers.items()}
                            out = handler(method, body, hdrs)
                        else:
                            out = handler(method, body)
                        if len(out) == 3:
                            code, payload, resp_hdrs = out
                        else:
                            code, payload = out
                            resp_hdrs = None
                        self._json(code, payload, resp_hdrs)
                    elif method != 'GET':
                        self._json(405, {
                            'error': f'{method} not supported on {path}',
                            'endpoints': self._endpoints()})
                    elif path == '/healthz' and plane._health_fn:
                        payload = plane._health_fn()
                        code = 200 if payload.get('healthy', True) \
                            else 503
                        self._json(code, payload)
                    elif path == '/metrics' and plane._metrics_fn:
                        self._respond(
                            200, plane._metrics_fn(),
                            'text/plain; version=0.0.4; charset=utf-8')
                    elif path == '/status' and plane._status_fn:
                        self._json(200, plane._status_fn())
                    else:
                        self._json(404, {
                            'error': f'no such endpoint: {path}',
                            'endpoints': self._endpoints()})
                except BrokenPipeError:
                    pass      # scraper went away mid-response
                except Exception as e:
                    try:
                        self._json(500, {
                            'error': f'{type(e).__name__}: {e}'})
                    except Exception:
                        pass

            def do_GET(self):
                self._dispatch('GET')

            def do_POST(self):
                self._dispatch('POST')

        self._server = http.server.ThreadingHTTPServer(
            (self._host, self._requested_port), Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            name='dgmc-telemetry', daemon=True)
        self._thread.start()
        return self

    def close(self):
        if self._server is not None:
            self._server.shutdown()
            self._server.server_close()
            self._server = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()


def probe_healthz(port, host='127.0.0.1', timeout_s=2.0):
    """Scrape one ``/healthz`` endpoint.

    Returns ``(status_code, payload_dict)`` — 503 responses included,
    their JSON body intact — or ``None`` when the endpoint is
    unreachable (connection refused, timeout, non-JSON garbage): the
    caller falls back to file heartbeats, it does not condemn the run
    on a failed scrape. Shared by the run supervisor and
    ``obs.aggregate`` so both apply the same scrape semantics.
    """
    import urllib.error
    import urllib.request
    url = f'http://{host}:{int(port)}/healthz'
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            code = resp.status
            body = resp.read()
    except urllib.error.HTTPError as e:
        code = e.code
        try:
            body = e.read()
        except Exception:
            return None
    except Exception:
        return None
    try:
        payload = json.loads(body.decode('utf-8'))
    except (ValueError, UnicodeDecodeError):
        return None
    if not isinstance(payload, dict):
        return None
    return code, payload
