"""Run-health watchdog: turn a silent hang into a ``hang_report.json``.

Every multichip benchmark attempt so far (``MULTICHIP_r01-r05.json``,
``BENCH_r05.json``) ended ``rc: 124`` with an empty tail: the run hung,
the external ``timeout(1)`` killed it, and the post-hoc telemetry said
nothing about *where*. The watchdog closes that gap with a heartbeat
thread armed by :class:`~dgmc_tpu.obs.run.RunObserver`:

- Call sites **beat** (:meth:`Watchdog.beat`) when an activity starts —
  a training step, a labelled compile region, a bench section — and
  **complete** (:meth:`Watchdog.done`) when it finishes.
- The daemon thread watches staleness. When no beat/complete lands for
  ``deadline_s`` seconds, it dumps ``hang_report.json``: all-thread
  Python tracebacks (``sys._current_frames``), the in-flight activity,
  the last-completed one, and whatever run context the owner supplies
  (step count, pending compile labels, the kernel-dispatch tail).
- Optionally it also arms **signal handlers** (SIGTERM/SIGALRM — what
  ``timeout(1)`` sends) that write the same report before chaining to
  the previously-installed handler, so an externally-killed run leaves
  evidence even when it was *not* stale yet.

Why a thread and not just signals: a process hung inside one XLA call
never returns to the Python interpreter, so a Python-level signal
handler never runs — but a separate thread still gets scheduled and
``sys._current_frames()`` still shows where every thread (including the
stuck one) is. The signal path complements it for responsive processes.

Lock discipline: the *thread* path may take ordinary locks (the main
thread is hung in C, not suspended mid-critical-section). The *signal*
path runs with the main thread interrupted at an arbitrary bytecode, so
it must not acquire any lock the main thread could hold — it therefore
uses only the context snapshot the thread cached on its last poll, plus
``sys._current_frames()`` (no Python locks) and a direct file write.

This module deliberately has **no jax import**: arming a watchdog must
work in any process, and the report must be writable while jax is wedged.
"""

import json
import os
import signal
import sys
import threading
import time
import traceback

__all__ = ['Watchdog', 'DEFAULT_SIGNALS', 'thread_stacks']

#: Signals the watchdog arms by default: what ``timeout(1)`` (SIGTERM)
#: and ``timeout -s ALRM`` / alarm-based harnesses deliver. Callers that
#: use SIGALRM themselves (bench.py's per-section budgets) pass an
#: explicit subset.
DEFAULT_SIGNALS = (signal.SIGTERM, signal.SIGALRM)


def thread_stacks(names=None):
    """All-thread Python tracebacks, JSON-ready.

    ``sys._current_frames`` is a C-level snapshot needing no Python
    locks, but resolving thread NAMES via ``threading.enumerate()``
    takes threading's internal registry lock — which the interrupted
    main thread may hold (e.g. inside ``Thread.start()``). Signal-path
    callers therefore pass a pre-cached ``{ident: (name, daemon)}``
    mapping (see :class:`Watchdog`); only thread-context callers let
    this default to a live ``enumerate()``.
    """
    if names is None:
        names = {t.ident: (t.name, bool(t.daemon))
                 for t in threading.enumerate()}
    out = []
    for ident, frame in sorted(sys._current_frames().items()):
        name, daemon = names.get(ident, ('?', None))
        out.append({
            'ident': ident,
            'name': name,
            'daemon': daemon,
            'stack': [ln.rstrip('\n') for ln in
                      traceback.format_stack(frame)],
        })
    return out


class Watchdog:
    """Heartbeat-armed hang reporter writing ``report_path`` on stall.

    Args:
        report_path: where ``hang_report.json`` goes (written atomically;
            a re-dump replaces it).
        deadline_s: staleness budget — seconds without a :meth:`beat` /
            :meth:`done` before the thread dumps. ``None``/``0`` disables
            the deadline (signal dumps still work).
        context_fn: 0-arg callable returning a JSON-able dict of run
            state (steps completed, sections, pending compiles, dispatch
            tail). Called from the watchdog thread under normal locking
            rules; its latest result is cached for the lock-free signal
            path.
        signals: iterable of signal numbers to arm (empty = none). The
            previous handler of each is chained after the dump and
            restored by :meth:`close`.
        poll_s: thread poll interval (default: ``deadline_s / 4`` clamped
            to [0.05, 1.0]).
        heartbeat_path: when set, the watchdog thread writes a small
            liveness file there on every poll (atomic tmp+rename):
            ``{time, pid, last_event, in_flight, steps_completed}``. An
            OUT-of-process monitor (the run supervisor,
            ``dgmc_tpu/resilience/supervisor.py``) watches its age — a
            process too wedged to run even this thread goes stale and
            gets killed, the layer below the in-process deadline dump.
        advertise: extra keys merged into every heartbeat payload —
            how the run advertises its live-telemetry ``port``
            (``--obs-port``) so the supervisor and ``obs.aggregate``
            can discover per-attempt endpoints from the heartbeat file
            alone, without out-of-band configuration.
        on_dump: callable ``(reason)`` invoked after every hang-report
            dump (deadline and signal paths alike) — the flight
            recorder's anomaly trigger. Runs on the dumping thread,
            possibly the lock-free signal path, so it must not take
            locks the main thread could hold; exceptions are swallowed.
    """

    def __init__(self, report_path, deadline_s=None, context_fn=None,
                 signals=(), poll_s=None, heartbeat_path=None,
                 advertise=None, on_dump=None):
        self.report_path = report_path
        self.heartbeat_path = heartbeat_path
        self.advertise = dict(advertise or {})
        self._on_dump = on_dump
        self.deadline_s = deadline_s or None
        self._context_fn = context_fn
        self._signals = tuple(signals)
        if poll_s is None:
            poll_s = min(1.0, max(0.05, (deadline_s or 4.0) / 4.0))
        self._poll_s = poll_s
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self._prev_handlers = {}
        t = time.time()
        self._in_flight = {'phase': 'startup', 'name': None, 'since': t}
        self._last_completed = None
        self._last_event = t
        self._dumped_this_stall = False
        self._cached_context = {}
        self._cached_thread_names = {}
        self.dump_count = 0

    # -- heartbeat ---------------------------------------------------------

    def beat(self, phase, name=None):
        """Record the start of an activity (a step, a compile label, a
        bench section). Resets the staleness clock and re-arms the
        once-per-stall dump."""
        now = time.time()
        with self._lock:
            self._in_flight = {'phase': phase, 'name': name, 'since': now}
            self._last_event = now
            self._dumped_this_stall = False

    def done(self):
        """Record completion of the in-flight activity. A completion of
        the idle phase (nested beat/done pairs unwind through it) is a
        heartbeat only — it must not overwrite the last-completed span a
        hang report names."""
        now = time.time()
        with self._lock:
            fin = self._in_flight
            if fin['phase'] != 'idle':
                self._last_completed = {
                    'phase': fin['phase'], 'name': fin['name'],
                    'duration_s': round(now - fin['since'], 3)}
            self._in_flight = {'phase': 'idle', 'name': None, 'since': now}
            self._last_event = now
            self._dumped_this_stall = False

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        """Arm: install signal handlers (main thread only; skipped
        silently elsewhere) and start the heartbeat thread."""
        # Seed the name cache here (safe context) so a signal arriving
        # before the first poll still labels the threads it can.
        self._refresh_thread_names()
        for sig in self._signals:
            try:
                self._prev_handlers[sig] = signal.signal(
                    sig, self._on_signal)
            except ValueError:  # not the main thread
                break
        # First heartbeat immediately: the supervisor's staleness watch
        # starts from the moment the file exists, so it must exist as
        # soon as the watchdog is armed, not one poll later.
        self._write_heartbeat()
        if self.deadline_s or self.heartbeat_path:
            self._thread = threading.Thread(
                target=self._watch, name='dgmc-watchdog', daemon=True)
            self._thread.start()
        return self

    def close(self):
        """Disarm: stop the thread and restore the signal handlers."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._poll_s * 4 + 1.0)
            self._thread = None
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except ValueError:
                break
        self._prev_handlers.clear()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.close()

    # -- dumping -----------------------------------------------------------

    def _refresh_thread_names(self):
        try:
            self._cached_thread_names = {
                t.ident: (t.name, bool(t.daemon))
                for t in threading.enumerate()}
        except Exception:
            pass

    def _write_heartbeat(self):
        """Liveness file for the out-of-process supervisor (thread path
        only; best-effort, never raises)."""
        if not self.heartbeat_path:
            return
        try:
            with self._lock:
                payload = {
                    'time': time.time(),
                    'pid': os.getpid(),
                    'last_event': self._last_event,
                    'in_flight': dict(self._in_flight),
                }
            ctx = self._cached_context or {}
            if 'steps_completed' in ctx:
                payload['steps_completed'] = ctx['steps_completed']
            if self.advertise:
                # The live-plane port (and anything else the owner
                # advertises): endpoint discovery rides the existing
                # liveness file instead of a side channel.
                payload.update(self.advertise)
            from dgmc_tpu.utils.io import write_json_atomic
            write_json_atomic(self.heartbeat_path, payload, quiet=True)
        except Exception:
            pass

    def _watch(self):
        while not self._stop.wait(self._poll_s):
            # Refresh the context + thread-name caches for the lock-free
            # signal path while everything is healthy (ordinary locks
            # are fine here).
            self._refresh_thread_names()
            if self._context_fn is not None:
                try:
                    self._cached_context = self._context_fn()
                except Exception:
                    pass
            self._write_heartbeat()
            if not self.deadline_s:
                continue
            with self._lock:
                stale = time.time() - self._last_event
                should = (stale > self.deadline_s
                          and not self._dumped_this_stall)
                if should:
                    self._dumped_this_stall = True
            if should:
                self.dump('deadline', use_locks=True)

    def _on_signal(self, signum, frame):
        try:
            name = signal.Signals(signum).name
        except ValueError:
            name = str(signum)
        # Cached context only: the main thread is interrupted at an
        # arbitrary bytecode and may hold any lock (see module docstring).
        self.dump(f'signal:{name}', use_locks=False)
        prev = self._prev_handlers.get(signum)
        if callable(prev):
            prev(signum, frame)
        elif prev == signal.SIG_DFL:
            # Re-deliver with the default disposition so the exit status
            # says "killed by signal", as it would have without us.
            signal.signal(signum, signal.SIG_DFL)
            os.kill(os.getpid(), signum)

    def dump(self, reason, extra=None, use_locks=True):
        """Write ``hang_report.json`` now; returns the path (or ``None``
        if even the write failed — a watchdog must never raise into the
        run it observes)."""
        now = time.time()
        if use_locks:
            with self._lock:
                in_flight = dict(self._in_flight)
                last_completed = self._last_completed
                last_event = self._last_event
            context = self._cached_context
            if self._context_fn is not None:
                try:
                    context = self._context_fn()
                except Exception:
                    pass
        else:
            in_flight = dict(self._in_flight)      # dict reads are atomic
            last_completed = self._last_completed  # enough for a dump
            last_event = self._last_event
            context = self._cached_context
        in_flight['since_s'] = round(now - in_flight.pop('since'), 3)
        # Signal path: cached thread names only — threading.enumerate()
        # takes the registry lock the interrupted main thread may hold.
        names = None if use_locks else dict(self._cached_thread_names)
        report = {
            'reason': reason,
            'time': now,
            'pid': os.getpid(),
            'argv': sys.argv,
            'deadline_s': self.deadline_s,
            'stalled_for_s': round(now - last_event, 3),
            'in_flight': in_flight,
            'last_completed': last_completed,
            'context': context or {},
            'threads': thread_stacks(names),
        }
        if extra:
            report.update(extra)
        path = None
        try:
            tmp = f'{self.report_path}.tmp.{os.getpid()}'
            with open(tmp, 'w') as f:
                json.dump(report, f, indent=1, default=str)
            os.replace(tmp, self.report_path)
            path = self.report_path
            self.dump_count += 1
        except Exception:
            pass
        if self._on_dump is not None:
            # Anomaly fan-out (the flight recorder): fires even when
            # the report write itself failed — the trailing-context
            # record is independent evidence, and on the signal path
            # the callee must already be lock-free by contract.
            try:
                self._on_dump(reason)
            except Exception:
                pass
        return path
