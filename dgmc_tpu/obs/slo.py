"""Declarative SLOs, error budgets and multi-window burn rates.

PRs 12-18 built the measurement planes — latency histograms, quality
headlines, goodput ratios — but nothing *judged* them: the serve plane
had percentiles and no objective, so "is the service healthy" was a
human squinting at ``/status``. This module closes that loop with the
standard SRE vocabulary:

- an **SLO spec** is a small JSON file (``--slo <file>`` on
  ``dgmc_tpu.serve`` and the experiment CLIs) declaring an availability
  objective, latency objectives (end-to-end and per serve stage, over
  the SAME span vocabulary qtrace records), and optional absolute
  floors on the quality plane's Hits@1 headline and the goodput ratio;
- the **error budget** of an objective is ``1 - objective``; budget
  *consumption* over the compliance window is
  ``bad_fraction / (1 - objective)`` — 1.0 means the window's budget is
  exactly spent;
- the **burn rate** over a window is the same ratio computed over that
  window: burn 1.0 spends the budget exactly at the sustainable rate,
  burn 14.4 spends a 30-day budget in 2 days. Alerts use the
  multi-window form (Google SRE workbook ch.5): a *fast* pair (long +
  short window, high threshold — pages on sudden cliffs without
  flapping) and a *slow* pair (longer windows, low threshold — catches
  quiet budget leaks).

Events stream into O(1)-memory time-bucketed rings
(:class:`WindowedRatio`): per objective, two counters per bucket,
ring length fixed by the longest configured window. No per-event
storage — the tracker holds its account over millions of queries.

Wiring (see :meth:`dgmc_tpu.obs.run.RunObserver.attach_slo`): the
tracker joins ``/metrics`` as the ``dgmc_slo_*`` families
(strict-parser pinned in CI), joins ``/status`` as the ``slo`` section,
is flushed to ``slo.json`` by every ``RunObserver.flush``, and dumps
the flight recorder through ``on_breach`` when a budget exhausts or a
fast-burn alert fires — the trailing context is on disk before a human
looks.

jax-free (stdlib only): the tracker runs in serving workers and the
report path without a backend bring-up.
"""

import json
import math
import threading
import time

__all__ = ['SloSpec', 'SloTracker', 'WindowedRatio', 'load_slo_spec',
           'DEFAULT_BURN_WINDOWS', 'DEFAULT_SERVE_SPEC',
           'SLO_SCHEMA_VERSION']

SLO_SCHEMA_VERSION = 1

#: The multi-window multi-burn-rate alert pairs (SRE workbook ch.5
#: shape, scaled to this repo's minutes-long CI rounds rather than
#: 30-day product windows): the FAST pair pages on a cliff — budget
#: burning >= ``threshold``x sustainable over both the long leg and the
#: recent short leg (the short leg stops a recovered incident from
#: alerting for the rest of the hour); the SLOW pair catches a quiet
#: leak the fast thresholds ignore.
DEFAULT_BURN_WINDOWS = {
    'fast': {'long_s': 3600.0, 'short_s': 300.0, 'threshold': 14.4},
    'slow': {'long_s': 21600.0, 'short_s': 1800.0, 'threshold': 6.0},
}

#: The serving default the bench driver writes when no spec file is
#: given explicitly: availability 99.9%, an end-to-end latency
#: objective, and a device_execute stage objective over the qtrace
#: span vocabulary. Floors are deliberately absent here — they are
#: deployment-specific pins, not defaults.
DEFAULT_SERVE_SPEC = {
    'name': 'serve-default',
    'window_s': 3600.0,
    'availability': {'objective': 0.999},
    'latency': [
        {'name': 'query', 'threshold_ms': 1000.0, 'objective': 0.95},
        {'name': 'device_execute', 'stage': 'device_execute',
         'threshold_ms': 500.0, 'objective': 0.95},
    ],
    'burn_windows': DEFAULT_BURN_WINDOWS,
}


class WindowedRatio:
    """Good/total event counts over trailing windows, O(1) memory.

    A fixed ring of time buckets (``bucket_s`` wide, enough buckets to
    cover ``horizon_s``); :meth:`add` increments the current bucket,
    :meth:`ratio` sums the buckets covering a trailing window. Buckets
    older than the horizon are overwritten in place — the ring never
    grows (the CON505 discipline), and there is no per-event storage.
    Thread-safe: serve handler threads add concurrently.
    """

    def __init__(self, horizon_s, bucket_s=None, time_fn=time.time):
        if bucket_s is None:
            # <= 64 buckets over the horizon, floor 1s: coarse enough
            # to stay O(1)-small, fine enough that a window quantizes
            # to within ~2% of its nominal span. Callers whose SHORTEST
            # window is much smaller than the horizon must pass a
            # matching bucket_s (SloTracker does).
            bucket_s = max(1.0, float(horizon_s) / 64.0)
        self.bucket_s = float(bucket_s)
        self.horizon_s = float(horizon_s)
        self._n = max(2, int(math.ceil(horizon_s / bucket_s)) + 1)
        self._bad = [0] * self._n
        self._total = [0] * self._n
        self._epoch = [None] * self._n  # bucket index each slot holds
        self._time = time_fn
        self._lock = threading.Lock()

    def _slot(self, now):
        """Ring slot for ``now``, clearing a stale slot on reuse."""
        epoch = int(now // self.bucket_s)
        i = epoch % self._n
        if self._epoch[i] != epoch:
            self._epoch[i] = epoch
            self._bad[i] = 0
            self._total[i] = 0
        return i

    def add(self, ok, now=None):
        now = self._time() if now is None else now
        with self._lock:
            i = self._slot(now)
            self._total[i] += 1
            if not ok:
                self._bad[i] += 1

    def counts(self, window_s, now=None):
        """``(bad, total)`` over the trailing ``window_s``."""
        now = self._time() if now is None else now
        window_s = min(float(window_s), self.horizon_s)
        oldest = int((now - window_s) // self.bucket_s)
        newest = int(now // self.bucket_s)
        bad = total = 0
        with self._lock:
            for epoch in range(max(oldest + 1, newest - self._n + 1),
                               newest + 1):
                i = epoch % self._n
                if self._epoch[i] == epoch:
                    bad += self._bad[i]
                    total += self._total[i]
        return bad, total

    def bad_fraction(self, window_s, now=None):
        """Bad/total over the window; ``None`` with no events (an
        empty window has no failure rate, not a zero one)."""
        bad, total = self.counts(window_s, now=now)
        if not total:
            return None
        return bad / total


def _require_fraction(value, what):
    try:
        v = float(value)
    except (TypeError, ValueError):
        raise ValueError(f'slo spec: {what} must be a number, '
                         f'got {value!r}')
    if not 0.0 < v < 1.0:
        raise ValueError(f'slo spec: {what} must be in (0, 1), got {v}')
    return v


class SloSpec:
    """One validated SLO spec (see :func:`load_slo_spec` for the file
    form). Objectives carry a stable ``name`` used in metric labels,
    ``slo.json`` keys and breach reasons."""

    def __init__(self, raw):
        if not isinstance(raw, dict):
            raise ValueError(f'slo spec: expected an object, '
                             f'got {type(raw).__name__}')
        self.raw = raw
        self.name = str(raw.get('name') or 'slo')
        self.window_s = float(raw.get('window_s') or 3600.0)
        if self.window_s <= 0:
            raise ValueError('slo spec: window_s must be positive')
        self.bucket_s = raw.get('bucket_s')

        self.objectives = []  # (name, kind, objective, threshold_s, stage)
        avail = raw.get('availability')
        if avail is not None:
            self.objectives.append({
                'name': 'availability', 'kind': 'availability',
                'objective': _require_fraction(
                    avail.get('objective'), 'availability.objective'),
                'threshold_s': None, 'stage': None})
        for i, lat in enumerate(raw.get('latency') or ()):
            stage = lat.get('stage')
            name = str(lat.get('name') or stage or f'latency_{i}')
            thr_ms = lat.get('threshold_ms')
            if not isinstance(thr_ms, (int, float)) or thr_ms <= 0:
                raise ValueError(f'slo spec: latency[{i}].threshold_ms '
                                 f'must be a positive number, '
                                 f'got {thr_ms!r}')
            self.objectives.append({
                'name': name, 'kind': 'latency',
                'objective': _require_fraction(
                    lat.get('objective'), f'latency[{i}].objective'),
                'threshold_s': float(thr_ms) / 1e3,
                'stage': str(stage) if stage else None})
        if not self.objectives:
            raise ValueError('slo spec: no objectives (need '
                             '"availability" and/or "latency")')
        names = [o['name'] for o in self.objectives]
        if len(set(names)) != len(names):
            raise ValueError(f'slo spec: duplicate objective names '
                             f'{names}')

        self.burn_windows = {}
        for wname, w in (raw.get('burn_windows')
                         or DEFAULT_BURN_WINDOWS).items():
            long_s, short_s = float(w['long_s']), float(w['short_s'])
            if not 0 < short_s <= long_s:
                raise ValueError(f'slo spec: burn window {wname!r} '
                                 f'needs 0 < short_s <= long_s')
            self.burn_windows[str(wname)] = {
                'long_s': long_s, 'short_s': short_s,
                'threshold': float(w['threshold'])}

        #: Absolute floors on plane headlines (gauges, not event
        #: streams): breaching is reported, and counts as a breach
        #: event, but consumes no latency/availability budget.
        self.floors = {}
        for key in ('hits1_floor', 'goodput_floor'):
            if raw.get(key) is not None:
                self.floors[key[:-len('_floor')]] = float(raw[key])

    @property
    def horizon_s(self):
        longest = max([self.window_s]
                      + [w['long_s'] for w in self.burn_windows.values()])
        return longest

    @property
    def ring_bucket_s(self):
        """Bucket width for the shared rings: explicit ``bucket_s``
        if the spec pins one, else sized so the SHORTEST configured
        window spans >= 6 buckets (quantization error <= ~17% of the
        short burn leg, not 100% of it), floored at 1s."""
        if self.bucket_s is not None:
            return float(self.bucket_s)
        shortest = min([self.window_s]
                       + [w['short_s'] for w in self.burn_windows.values()])
        return max(1.0, shortest / 6.0)

    def describe(self):
        """The spec back as plain data (what ``slo.json`` embeds)."""
        return {
            'name': self.name,
            'window_s': self.window_s,
            'objectives': [dict(o) for o in self.objectives],
            'burn_windows': dict(self.burn_windows),
            'floors': dict(self.floors),
        }


def load_slo_spec(path):
    """Parse + validate an SLO spec file. Raises ``ValueError`` with
    the offending field named — a malformed SLO must fail the CLI at
    startup, not silently judge nothing."""
    try:
        with open(path) as f:
            raw = json.load(f)
    except OSError as e:
        raise ValueError(f'slo spec: cannot read {path}: {e}')
    except json.JSONDecodeError as e:
        raise ValueError(f'slo spec: {path} is not valid JSON: {e}')
    return SloSpec(raw)


class SloTracker:
    """Live error-budget accounting for one :class:`SloSpec`.

    :meth:`record` feeds one event (a served query, or a training
    step): availability counts ``ok``; each latency objective counts
    the event's latency — end-to-end by default, or the named qtrace
    stage from ``stages_ms``. :meth:`check` (called at every observer
    flush) evaluates budgets and burn windows and fires ``on_breach``
    — rate-limited per breach kind — on budget exhaustion or a burning
    alert pair. All reads come from the same rings the exports read:
    ``/metrics``, ``/status`` and ``slo.json`` can never disagree.
    """

    #: Seconds between repeated ``on_breach`` calls for the same kind:
    #: the flight recorder needs the FIRST trailing context, not one
    #: dump per flush while the budget stays exhausted.
    BREACH_COOLDOWN_S = 60.0

    def __init__(self, spec, time_fn=time.time, on_breach=None):
        self.spec = spec
        self._time = time_fn
        self._on_breach = on_breach
        self._rings = {
            o['name']: WindowedRatio(spec.horizon_s,
                                     bucket_s=spec.ring_bucket_s,
                                     time_fn=time_fn)
            for o in spec.objectives}
        self._lock = threading.Lock()
        self._good = {o['name']: 0 for o in spec.objectives}
        self._bad = {o['name']: 0 for o in spec.objectives}
        self._gauges = {}          # hits1 / goodput headline values
        self._breach_counts = {}   # kind -> count
        self._breach_last = {}     # kind -> unix time of last on_breach
        self._last_breach = None

    # -- event intake ------------------------------------------------------

    def record(self, ok, latency_s=None, stages_ms=None, now=None):
        """One event: ``ok`` feeds availability; ``latency_s`` (and the
        per-stage ``stages_ms`` mapping, qtrace vocabulary) feed the
        latency objectives. A failed event with no latency counts as
        bad for every latency objective too — an error is not a fast
        success."""
        now = self._time() if now is None else now
        for o in self.spec.objectives:
            name = o['name']
            if o['kind'] == 'availability':
                good = bool(ok)
            else:
                if not ok:
                    good = False
                else:
                    if o['stage'] is not None:
                        val_ms = (stages_ms or {}).get(o['stage'])
                        val = None if val_ms is None else val_ms / 1e3
                    else:
                        val = latency_s
                    if val is None:
                        continue  # unmeasured: no evidence either way
                    good = val <= o['threshold_s']
            self._rings[name].add(good, now=now)
            with self._lock:
                if good:
                    self._good[name] += 1
                else:
                    self._bad[name] += 1

    def update_gauges(self, **values):
        """Refresh the floor-checked plane headlines (``hits1=``,
        ``goodput=``); ``None`` values clear — absence stays absent."""
        with self._lock:
            for key, val in values.items():
                if val is None:
                    self._gauges.pop(key, None)
                else:
                    self._gauges[key] = float(val)

    # -- judgment ----------------------------------------------------------

    def _objective_state(self, o, now):
        name = o['name']
        ring = self._rings[name]
        budget = 1.0 - o['objective']
        frac = ring.bad_fraction(self.spec.window_s, now=now)
        consumed = None if frac is None else frac / budget
        burn = {}
        for wname, w in self.spec.burn_windows.items():
            fl = ring.bad_fraction(w['long_s'], now=now)
            fs = ring.bad_fraction(w['short_s'], now=now)
            bl = None if fl is None else fl / budget
            bs = None if fs is None else fs / budget
            burn[wname] = {
                'long': bl, 'short': bs,
                'threshold': w['threshold'],
                # The multi-window AND: both legs over threshold. An
                # unmeasured leg cannot alert — no evidence, no page.
                'alerting': (bl is not None and bs is not None
                             and bl >= w['threshold']
                             and bs >= w['threshold']),
            }
        with self._lock:
            good, bad = self._good[name], self._bad[name]
        return {
            'kind': o['kind'],
            'objective': o['objective'],
            'threshold_ms': (None if o['threshold_s'] is None
                             else o['threshold_s'] * 1e3),
            'stage': o['stage'],
            'events': good + bad,
            'bad': bad,
            'window_bad_fraction': frac,
            'budget_consumed': consumed,
            'burn': burn,
        }

    def _breach(self, kind, detail, now):
        with self._lock:
            self._breach_counts[kind] = \
                self._breach_counts.get(kind, 0) + 1
            self._last_breach = {'kind': kind, 'time': now,
                                 'detail': detail}
            last = self._breach_last.get(kind)
            fire = last is None or now - last >= self.BREACH_COOLDOWN_S
            if fire:
                self._breach_last[kind] = now
        if fire and self._on_breach is not None:
            try:
                self._on_breach(kind, detail)
            except Exception:
                pass  # judging must never take the service down

    def check(self, now=None):
        """Evaluate every objective; fire breaches. Returns the full
        state dict (the ``slo.json`` / ``/status`` body)."""
        now = self._time() if now is None else now
        objectives = {}
        for o in self.spec.objectives:
            state = self._objective_state(o, now)
            objectives[o['name']] = state
            consumed = state['budget_consumed']
            if consumed is not None and consumed >= 1.0:
                self._breach(
                    f'budget-exhausted:{o["name"]}',
                    {'objective': o['name'],
                     'budget_consumed': round(consumed, 4),
                     'window_s': self.spec.window_s}, now)
            for wname, b in state['burn'].items():
                if b['alerting']:
                    self._breach(
                        f'burn:{wname}:{o["name"]}',
                        {'objective': o['name'], 'window': wname,
                         'burn_long': round(b['long'], 4),
                         'burn_short': round(b['short'], 4),
                         'threshold': b['threshold']}, now)

        floors = {}
        with self._lock:
            gauges = dict(self._gauges)
        for key, floor in self.spec.floors.items():
            value = gauges.get(key)
            breached = value is not None and value < floor
            floors[key] = {'floor': floor, 'value': value,
                           'breached': breached}
            if breached:
                self._breach(f'floor:{key}',
                             {'floor': floor, 'value': value}, now)

        with self._lock:
            breaches = {'counts': dict(self._breach_counts),
                        'last': (dict(self._last_breach)
                                 if self._last_breach else None)}
        return {
            'version': SLO_SCHEMA_VERSION,
            'slo': self.spec.name,
            'time': now,
            'spec': self.spec.describe(),
            'objectives': objectives,
            'floors': floors,
            'breaches': breaches,
        }

    # -- exports -----------------------------------------------------------

    def snapshot(self):
        """The ``slo.json`` body (alias of :meth:`check`: flushing IS
        a judgment pass, so a breach can never be newer than the
        artifact that records it)."""
        return self.check()

    def status(self):
        """The ``/status`` ``slo`` section: :meth:`check` without the
        spec echo (the scrape stays small; the spec is in slo.json)."""
        out = self.check()
        out.pop('spec', None)
        return out

    def metric_families(self):
        """The ``dgmc_slo_*`` families for ``/metrics``."""
        state = self.check()
        slo = self.spec.name
        consumed, burn, events, alerting = [], [], [], []
        for name, o in sorted(state['objectives'].items()):
            lbl = {'slo': slo, 'objective': name}
            if o['budget_consumed'] is not None:
                consumed.append(('', lbl, round(o['budget_consumed'], 6)))
            events.append(('', dict(lbl, outcome='good'),
                           o['events'] - o['bad']))
            events.append(('', dict(lbl, outcome='bad'), o['bad']))
            for wname, b in sorted(o['burn'].items()):
                for leg in ('long', 'short'):
                    if b[leg] is not None:
                        burn.append(
                            ('', dict(lbl, window=wname, leg=leg),
                             round(b[leg], 6)))
                alerting.append(('', dict(lbl, window=wname),
                                 1 if b['alerting'] else 0))
        families = [
            ('dgmc_slo_error_budget_consumed', 'gauge',
             'Error-budget consumption over the SLO compliance window '
             '(1.0 = spent).', consumed),
            ('dgmc_slo_burn_rate', 'gauge',
             'Error-budget burn rate per alert window leg '
             '(1.0 = sustainable).', burn),
            ('dgmc_slo_burn_alerting', 'gauge',
             'Multi-window burn alert state (both legs over '
             'threshold).', alerting),
            ('dgmc_slo_events_total', 'counter',
             'SLO events by objective and outcome.', events),
            ('dgmc_slo_breaches_total', 'counter',
             'Breach events (budget exhaustion, burn alerts, floor '
             'violations) by kind.',
             [('', {'slo': slo, 'kind': kind}, count)
              for kind, count in
              sorted(state['breaches']['counts'].items())] or
             [('', {'slo': slo, 'kind': 'none'}, 0)]),
        ]
        floors = [
            ('', {'slo': slo, 'floor': key},
             1 if f['breached'] else 0)
            for key, f in sorted(state['floors'].items())
            if f['value'] is not None]
        if floors:
            families.append(
                ('dgmc_slo_floor_breached', 'gauge',
                 'Plane-headline floor state (hits1/goodput below its '
                 'configured absolute floor).', floors))
        return families
