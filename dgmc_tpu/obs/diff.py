"""Cross-run regression diff: compare two ``--obs-dir`` runs, gate CI.

Usage::

    python -m dgmc_tpu.obs.diff BASELINE CANDIDATE            # table + rc
    python -m dgmc_tpu.obs.diff A B --json                   # machine-readable
    python -m dgmc_tpu.obs.diff A B --max-step-p50-regression 0.5

Every perf claim in this repo rests on its own measurements (the
reference publishes no wall-clock numbers), so "measurably faster" needs
a tool that can say *measurably slower* with a nonzero exit code. The
diff compares the summaries :mod:`dgmc_tpu.obs.report` builds —
throughput, step p50/p95, recompile count, memory peak, the
kernel-dispatch table, probe aggregates — against configurable
regression thresholds:

- **step p50 / p95** — relative increase above
  ``--max-step-p50-regression`` / ``--max-step-p95-regression`` fails.
- **throughput** — relative decrease above
  ``--max-throughput-regression`` fails.
- **compile events** — more than ``--max-new-compile-events`` extra
  events fails (padding-bucket churn shows up here).
- **memory peak** — relative increase above
  ``--max-memory-regression`` fails (only when both runs report the
  same source: device peaks and host RSS are not comparable).
- **kernel dispatch** — a kernel that ran Pallas in the baseline but
  only fell back in the candidate fails (``--allow-kernel-fallback``
  downgrades this to a note).
- **probes** — a candidate run that recorded a non-finite stage fails;
  numeric probe aggregates (entropy, consensus delta, grad norm) are
  reported as informational drift rows.
- **hang reports** — a candidate that left a ``hang_report.json`` the
  baseline did not have fails unconditionally: a run that hung must
  never diff as "fewer metrics, pass" (the MULTICHIP rc:124 failure
  mode). Both-hung compares the rest and notes it; baseline-only-hung
  is the fix, not a regression.
- **restarts** — a supervised candidate (``recovery.json``, see
  ``dgmc_tpu.resilience.supervisor``) that needed more restarts than the
  baseline plus ``--max-restarts-regression`` fails — a newly flaky path
  is a regression even when the final attempt's metrics look fine — and
  a candidate whose supervisor **gave up** fails unconditionally.
- **elastic shrinks** — a candidate whose supervisor performed more
  elastic mesh shrinks than the baseline fails: the run survived, but
  on fewer devices than it asked for, which invalidates every scaling
  number the surviving metrics report.
- **MFU** — relative decrease of the headline MFU
  (``efficiency.json``) above ``--max-mfu-regression`` fails, as does
  an MFU the baseline had but the candidate lost.
- **arithmetic intensity** — relative decrease of the headline achieved
  FLOPs/byte (``efficiency.json``) above
  ``--max-intensity-regression`` fails (a program that got
  byte-heavier per FLOP slid down the roofline even if wall-clock
  noise hides it); lost-from-candidate fails like MFU.
- **collective overlap** — the headline modeled overlap fraction
  (``efficiency.json``, from the schedule model
  ``analysis/hlo_sched.py``) dropping below the ``--min-overlap``
  floor fails: the chunk loop serialized, whatever wall-clock noise
  says. An absolute floor, not a ratio — 0.0 is a meaningful value
  and ratios against it are not. Lost-from-candidate fails.
- **static peak bytes** — relative increase of the liveness model's
  static peak-live bound (``efficiency.json``) above
  ``--max-peak-regression`` fails; unlike the runtime memory row it
  needs no matching measurement source, because the bound is computed
  from the compiled program alone. Lost-from-candidate fails.
- **measured overlap** — the *measured* comm/compute overlap fraction
  (``efficiency.json``, from the profiler-trace attribution
  ``obs.attribution``) dropping below the ``--min-measured-overlap``
  floor fails. Same absolute-floor / lost-account semantics as
  ``--min-overlap``: this is the runtime truth the static model only
  bounds — a candidate that lost the measurement the baseline had
  fails, and the floor only gates when configured (device-less CPU
  captures have no measured overlap to gate).
- **idle fraction** — the attribution plane's idle headline
  (``efficiency.json``: device idle inside the profiled window, or
  host idle on device-less captures) growing past
  ``--max-idle-regression`` fails; like the memory row, the two runs
  must report the same ``idle_source`` (device idle and host idle are
  not comparable). Lost-from-candidate fails; a zero-idle baseline
  gates the candidate's absolute idle fraction against the threshold
  directly (a ratio against 0 is undefined, and "we used to have no
  idle" is exactly the baseline worth defending).

- **skew** — the device step-time skew ratio (``aggregate.json``, see
  ``obs.aggregate``) growing past ``--max-skew-regression`` fails;
  runs without aggregation skip the row (the artifact is produced by a
  separate tool, so absence is not evidence of regression).
- **serve stage p95** — each serve stage's p95 latency
  (``qtrace_summary.json``, see ``obs.qtrace``) growing past
  ``--max-stage-p95-regression`` fails. Off unless configured (like
  ``--min-overlap``): training runs carry no qtrace account. When on,
  a serving candidate that LOST the per-stage account the baseline had
  fails — tail-latency attribution is itself a gated artifact.
- **goodput ratio** — the padding-waste account's useful-over-executed
  FLOPs ratio (``goodput.json``, see ``obs.goodput``) dropping below
  the ``--min-goodput`` floor fails. Absolute floor with
  ``--min-overlap`` semantics: a candidate that lost the goodput
  account the baseline carried fails unconditionally (the batcher that
  silently stopped accounting its padding must never read as a pass);
  the floor itself only gates when configured.
- **pad fraction** — the worst-bucket pad fraction (``goodput.json``)
  growing by more than ``--max-pad-regression`` fails. An ABSOLUTE
  increase bound, not a ratio: a 0.0 baseline (perfectly-filled
  buckets) is a meaningful value and exactly the one worth defending,
  and a ratio against it is undefined. Lost-from-candidate fails.
- **utilization** — the serve path's Little's-law ρ
  (``capacity.json``, see ``obs.capacity``: arrival rate × mean
  service time) exceeding the ``--max-utilization`` ceiling fails —
  a candidate running hotter than the ceiling has no headroom before
  the queue grows without bound, whatever its latency quantiles say.
  Absolute ceiling, off unless configured (training runs carry no
  capacity account); lost-from-candidate fails.

When a gated key is absent from one side, the row's note names WHICH
run lacks it and lists the gated keys that run *does* carry, so a CI
failure is diagnosable from the log alone (is the artifact missing, or
just this account?).

``--calibration <calibration.json>`` (see :mod:`dgmc_tpu.obs.calibrate`)
rescales the RELATIVE thresholds above to ``z * rel_sigma`` of each
metric's fitted run-to-run noise floor (``--calibration-z``, default 3):
the gate fires on a shift three noise floors deep instead of a
hand-picked fraction. Pinned fallbacks: metrics the calibration file
does not cover (or covers with too few samples) keep their fixed
thresholds unchanged, absolute floors/ceilings (``--min-*``,
``--max-utilization``, compile/restart counts) are never rescaled, and
every lost-account rule applies exactly as before — calibration
adjusts gate WIDTH, never gate existence. Each rescaled gate is
reported as a ``calibrated:`` info row naming the noise floor it was
judged by.

Exit codes: 0 = no regression, 1 = regression, 2 = usage/missing input.
Like the report CLI, this module has **no jax import** — it must gate CI
from artifacts alone.
"""

import argparse
import json
import os
import sys

from dgmc_tpu.obs.report import load_run, summarize

#: Default fractional/absolute thresholds; CLI flags override.
DEFAULT_THRESHOLDS = {
    'step_p50': 0.25,
    'step_p95': 0.40,
    'throughput': 0.25,
    'memory': 0.15,
    'new_compile_events': 5,
    'mfu': 0.25,
    'intensity': 0.40,
    'skew': 0.50,
    'restarts': 0,
    #: Absolute overlap-fraction floor; None = gate off unless asked
    #: (a run whose programs legitimately model 0.0 must not fail by
    #: default).
    'min_overlap': None,
    'static_peak': 0.25,
    #: Absolute measured-overlap floor (obs.attribution); None = gate
    #: off unless asked, same contract as min_overlap.
    'min_measured_overlap': None,
    #: Serve per-stage p95 regression (qtrace_summary.json); None =
    #: gate off unless asked — training runs carry no qtrace account.
    'stage_p95': None,
    #: Relative Hits@1 regression bound (quality.json headline); None =
    #: gate off unless asked. The lost-account rule still applies
    #: unconditionally: a candidate that stopped reporting the quality
    #: account the baseline had fails.
    'hits1': None,
    #: Absolute Hits@1 floor; None = gate off unless asked
    #: (min_overlap semantics — ROADMAP item 2's paper-parity pin).
    'min_hits1': None,
    'idle': 0.25,
    #: Absolute goodput-ratio floor (goodput.json); None = gate off
    #: unless asked, min_overlap semantics (lost account still fails).
    'min_goodput': None,
    #: Allowed ABSOLUTE increase of the worst-bucket pad fraction
    #: (goodput.json); None = gate off unless asked. Absolute, not a
    #: ratio: a zero-pad baseline is the one worth defending.
    'pad_regression': None,
    #: Absolute ceiling on the serve path's Little's-law utilization ρ
    #: (capacity.json); None = gate off unless asked — training runs
    #: carry no capacity account.
    'max_utilization': None,
    #: Logged metrics whose FINAL values must be exactly equal between
    #: the runs (tuple of keys; empty = gate off). The
    #: streamed-vs-offloaded equivalence gate: two layouts of the same
    #: forward must log the same loss/Hits, bit for bit.
    'require_equal': (),
}

#: Keys the gates read from a run summary — listed in missing-metric
#: notes so a failing CI log names what the lacking run DID record.
GATED_KEYS = (
    'step_p50_s', 'step_p95_s', 'steps_per_sec', 'compile_events',
    'peak_memory_bytes', 'mfu', 'arith_intensity', 'overlap_fraction',
    'static_peak_bytes', 'measured_overlap_fraction', 'idle_fraction',
    'hits1', 'goodput_ratio', 'pad_fraction', 'utilization',
)


def _missing_note(side, summary):
    """``'missing from candidate; candidate has: mfu, step_p50_s'`` —
    the diagnosable form of a lost-account failure: which side lacks
    the gated key, and which gated keys that run does carry."""
    have = [k for k in GATED_KEYS if summary.get(k) is not None]
    return (f'missing from {side}; {side} has: '
            + (', '.join(have) if have else 'no gated metrics at all'))


def _rel(a, b):
    """(b - a) / a — the signed fractional change, None if undefined."""
    if a is None or b is None or not a:
        return None
    return (b - a) / a


def _row(metric, a, b, delta, limit, status, note=''):
    return {'metric': metric, 'a': a, 'b': b, 'delta': delta,
            'limit': limit, 'status': status, 'note': note}


def _dispatch_outcomes(summary):
    """{kernel: set(outcomes with count > 0)} from a run summary."""
    out = {}
    for r in summary.get('dispatch', []):
        if r.get('count', 0) > 0 and 'kernel' in r:
            out.setdefault(r['kernel'], set()).add(r.get('outcome'))
    return out


def diff_runs(a, b, thresholds=None, allow_kernel_fallback=False):
    """Compare two run summaries (:func:`dgmc_tpu.obs.report.summarize`
    outputs). Returns ``(rows, regressions)`` — all comparison rows, and
    the subset that breached a threshold."""
    thr = dict(DEFAULT_THRESHOLDS, **(thresholds or {}))
    rows = []

    def gate(metric, va, vb, delta, limit, worse, note=''):
        status = 'REGRESSION' if worse else 'ok'
        rows.append(_row(metric, va, vb, delta, limit, status, note))

    # -- step timing ------------------------------------------------------
    # Asymmetric absence handling, matching the dispatch section below: a
    # metric the BASELINE recorded but the candidate lost (broken timer,
    # run died before its first flush) is a regression — a gate that
    # exits 0 because the numbers it gates on vanished is no gate.
    def timing(key, thr_key, worse_when):
        va, vb = a.get(key), b.get(key)
        if va is None:
            rows.append(_row(key, va, vb, None, thr[thr_key], 'skipped',
                             _missing_note('baseline', a)))
            return
        if vb is None:
            rows.append(_row(key, va, vb, None, thr[thr_key], 'REGRESSION',
                             _missing_note('candidate', b)))
            return
        d = _rel(va, vb)
        if d is None:  # zero baseline: no meaningful ratio
            rows.append(_row(key, va, vb, None, thr[thr_key], 'skipped',
                             'zero baseline'))
            return
        gate(key, va, vb, round(d, 4), thr[thr_key], worse_when(d))

    timing('step_p50_s', 'step_p50', lambda d: d > thr['step_p50'])
    timing('step_p95_s', 'step_p95', lambda d: d > thr['step_p95'])
    timing('steps_per_sec', 'throughput',
           lambda d: -d > thr['throughput'])

    # -- hang reports -----------------------------------------------------
    # Checked before everything else conceptually gates: a hung candidate
    # must fail even when every surviving metric looks fine (a hang
    # truncates the run, which usually *improves* the aggregates).
    ha, hb = a.get('hang_report'), b.get('hang_report')
    if hb is not None:
        inf = hb.get('in_flight') or {}
        status = 'note' if ha is not None else 'REGRESSION'
        note = (f'candidate hung ({hb.get("reason")}) in '
                f'{inf.get("phase")}:{inf.get("name")}'
                + ('; baseline hung too' if ha is not None else ''))
        rows.append(_row('hang_report', 'absent' if ha is None else
                         ha.get('reason'), hb.get('reason'), None, None,
                         status, note))
    elif ha is not None:
        rows.append(_row('hang_report', ha.get('reason'), 'absent', None,
                         None, 'ok', 'baseline hung; candidate did not'))

    # -- supervised-run recovery ------------------------------------------
    # A candidate that needed MORE restarts than the baseline (plus the
    # allowed slack) is a newly flaky path even when its final attempt's
    # metrics look fine; a candidate whose supervisor gave up failed
    # outright, whatever the surviving artifacts say. An unsupervised
    # baseline counts as 0 restarts; an unsupervised candidate skips the
    # row (supervision is opt-in — absence is not evidence).
    ra = a.get('recovery') or {}
    rb = b.get('recovery')
    if rb is not None:
        if rb.get('outcome') == 'gave-up':
            rows.append(_row('recovery', ra.get('outcome') or 'absent',
                             'gave-up', None, None, 'REGRESSION',
                             'candidate supervisor exhausted its '
                             'restart budget'))
        base_r = ra.get('restarts', 0)
        cand_r = rb.get('restarts', 0)
        extra = cand_r - base_r
        gate('restarts', base_r, cand_r, extra, thr['restarts'],
             extra > thr['restarts'],
             ('degraded: ' + ','.join(rb['degradations'])
              if rb.get('degradations') else ''))
        # Elastic-event gate: a candidate whose supervisor had to SHRINK
        # THE MESH survived, but on fewer devices than the run asked for
        # — throughput, memory headroom and every scaling claim changed
        # out from under the surviving metrics. More shrinks than the
        # baseline fails (0 for an un-shrunk baseline).
        ea = len(ra.get('elastic') or [])
        eb = len(rb.get('elastic') or [])
        if ea or eb:
            detail = '; '.join(e.get('detail') or '?'
                               for e in (rb.get('elastic') or []))
            gate('elastic_shrinks', ea, eb, eb - ea, 0, eb > ea,
                 detail or 'baseline shrank; candidate did not')
    elif ra:
        rows.append(_row('restarts', ra.get('restarts', 0), None, None,
                         thr['restarts'], 'skipped',
                         'candidate unsupervised'))

    # -- required-equal logged metrics ------------------------------------
    # The layout-equivalence gate (streamed vs offloaded forward): the
    # named metrics' final logged values must match EXACTLY — a layout
    # change is pure scheduling, so any numeric drift is a bug, not
    # noise. Asymmetric on absence like every other gate: a key the
    # baseline logged but the candidate lost fails.
    la, lb = a.get('last_metrics') or {}, b.get('last_metrics') or {}
    for key in thr.get('require_equal') or ():
        va, vb = la.get(key), lb.get(key)
        if va is None and vb is None:
            rows.append(_row(f'equal:{key}', None, None, None, 0,
                             'REGRESSION',
                             'neither run logged the required metric'))
        elif va is None or vb is None:
            rows.append(_row(f'equal:{key}', va, vb, None, 0,
                             'REGRESSION',
                             _missing_note(
                                 'baseline' if va is None else 'candidate',
                                 a if va is None else b)))
        else:
            # Values may be non-numeric (metrics.jsonl carries e.g.
            # 'event' strings): the gate is pure equality; the delta
            # column is numeric-only garnish.
            delta = (abs(va - vb)
                     if va != vb
                     and isinstance(va, (int, float))
                     and isinstance(vb, (int, float))
                     and not isinstance(va, bool)
                     and not isinstance(vb, bool) else None)
            gate(f'equal:{key}', va, vb, delta, 0, va != vb,
                 '' if va == vb else 'required exactly equal')

    # -- MFU --------------------------------------------------------------
    # Asymmetric like the timings: efficiency the baseline accounted for
    # but the candidate lost (cost recording broke, run died first) is a
    # regression, not a skip.
    mfu_a, mfu_b = a.get('mfu'), b.get('mfu')
    if mfu_a is not None and mfu_b is None:
        rows.append(_row('mfu', mfu_a, mfu_b, None, thr['mfu'],
                         'REGRESSION', _missing_note('candidate', b)))
    elif mfu_a is None and mfu_b is not None:
        rows.append(_row('mfu', mfu_a, mfu_b, None, thr['mfu'], 'skipped',
                         _missing_note('baseline', a)))
    elif mfu_a is not None:
        d = _rel(mfu_a, mfu_b)
        if d is None:
            rows.append(_row('mfu', mfu_a, mfu_b, None, thr['mfu'],
                             'skipped', 'zero baseline'))
        else:
            gate('mfu', mfu_a, mfu_b, round(d, 4), thr['mfu'],
                 -d > thr['mfu'])

    # -- achieved arithmetic intensity ------------------------------------
    # Same asymmetry as MFU: an intensity account the baseline had but
    # the candidate lost is a broken gate input, not a skip.
    ai_a, ai_b = a.get('arith_intensity'), b.get('arith_intensity')
    if ai_a is not None and ai_b is None:
        rows.append(_row('arith_intensity', ai_a, ai_b, None,
                         thr['intensity'], 'REGRESSION',
                         _missing_note('candidate', b)))
    elif ai_a is None and ai_b is not None:
        rows.append(_row('arith_intensity', ai_a, ai_b, None,
                         thr['intensity'], 'skipped',
                         _missing_note('baseline', a)))
    elif ai_a is not None:
        d = _rel(ai_a, ai_b)
        if d is None:
            rows.append(_row('arith_intensity', ai_a, ai_b, None,
                             thr['intensity'], 'skipped', 'zero baseline'))
        else:
            gate('arith_intensity', ai_a, ai_b, round(d, 4),
                 thr['intensity'], -d > thr['intensity'])

    # -- modeled collective overlap ---------------------------------------
    # An ABSOLUTE floor, not a ratio gate: 0.0 overlap is a meaningful
    # value (a fully serial program) and fractional change against it is
    # undefined. A candidate that lost the account the baseline had
    # fails like MFU; the floor itself only gates when configured.
    ov_a, ov_b = a.get('overlap_fraction'), b.get('overlap_fraction')
    floor = thr.get('min_overlap')
    if ov_a is not None and ov_b is None:
        rows.append(_row('overlap_fraction', ov_a, ov_b, None, floor,
                         'REGRESSION', _missing_note('candidate', b)))
    elif ov_b is not None and floor is not None:
        gate('overlap_fraction', ov_a, ov_b,
             None if ov_a is None else round(ov_b - ov_a, 4), floor,
             ov_b < floor,
             'chunk loop serialized below the floor'
             if ov_b < floor else '')
    elif ov_a is not None or ov_b is not None:
        rows.append(_row('overlap_fraction', ov_a, ov_b,
                         None if None in (ov_a, ov_b)
                         else round(ov_b - ov_a, 4), floor, 'info',
                         'no --min-overlap floor configured'))

    # -- Hits@1 (quality plane) -------------------------------------------
    # The paper's headline metric, gated both ways:
    # --max-hits1-regression bounds the RELATIVE drop against the
    # baseline; --min-hits1 is an absolute floor (min_overlap
    # semantics). Either way, a candidate that lost the quality account
    # the baseline carried FAILS unconditionally — an eval loop that
    # silently stopped reporting accuracy must read as a regression,
    # never as a pass.
    h_a, h_b = a.get('hits1'), b.get('hits1')
    h_lim = thr.get('hits1')
    h_floor = thr.get('min_hits1')
    if h_a is not None and h_b is None:
        rows.append(_row('hits1', h_a, h_b, None, h_lim, 'REGRESSION',
                         _missing_note('candidate', b)))
    else:
        if h_lim is not None and h_a is None and h_b is not None:
            rows.append(_row('hits1', h_a, h_b, None, h_lim, 'skipped',
                             _missing_note('baseline', a)))
        elif h_lim is not None and h_a is not None and h_b is not None:
            d = _rel(h_a, h_b)
            if d is None:
                rows.append(_row('hits1', h_a, h_b, None, h_lim,
                                 'skipped', 'zero baseline'))
            else:
                gate('hits1', h_a, h_b, round(d, 4), h_lim, -d > h_lim)
        if h_floor is not None and h_b is not None:
            gate('min_hits1', h_a, h_b,
                 None if h_a is None else round(h_b - h_a, 4), h_floor,
                 h_b < h_floor,
                 'Hits@1 under the absolute floor'
                 if h_b < h_floor else '')
        if h_b is not None and h_lim is None and h_floor is None:
            rows.append(_row(
                'hits1', h_a, h_b,
                None if h_a is None else round(h_b - h_a, 4), None,
                'info',
                'no --max-hits1-regression / --min-hits1 configured'))

    # -- measured comm/compute overlap ------------------------------------
    # The profiler-trace counterpart of the modeled floor above, same
    # semantics: absolute floor (0.0 = genuinely serialized hardware),
    # lost-account fails, floor gates only when configured.
    mo_a = a.get('measured_overlap_fraction')
    mo_b = b.get('measured_overlap_fraction')
    mfloor = thr.get('min_measured_overlap')
    if mo_a is not None and mo_b is None:
        rows.append(_row('measured_overlap_fraction', mo_a, mo_b, None,
                         mfloor, 'REGRESSION',
                         _missing_note('candidate', b)))
    elif mo_b is not None and mfloor is not None:
        gate('measured_overlap_fraction', mo_a, mo_b,
             None if mo_a is None else round(mo_b - mo_a, 4), mfloor,
             mo_b < mfloor,
             'hardware ran the chunk loop below the measured floor'
             if mo_b < mfloor else '')
    elif mo_a is not None or mo_b is not None:
        rows.append(_row('measured_overlap_fraction', mo_a, mo_b,
                         None if None in (mo_a, mo_b)
                         else round(mo_b - mo_a, 4), mfloor, 'info',
                         'no --min-measured-overlap floor configured'))

    # -- idle fraction (measured attribution) ------------------------------
    # Source-matched like the memory row: device idle and host idle are
    # different quantities. A zero-idle baseline gates the candidate's
    # ABSOLUTE idle against the threshold (no ratio exists against 0,
    # and a perfectly-fed baseline is the one worth defending).
    id_a, id_b = a.get('idle_fraction'), b.get('idle_fraction')
    isrc_a, isrc_b = a.get('idle_source'), b.get('idle_source')
    if id_a is not None and id_b is None:
        rows.append(_row('idle_fraction', id_a, id_b, None, thr['idle'],
                         'REGRESSION', _missing_note('candidate', b)))
    elif id_a is None and id_b is not None:
        rows.append(_row('idle_fraction', id_a, id_b, None, thr['idle'],
                         'skipped', _missing_note('baseline', a)))
    elif id_a is not None:
        if isrc_a != isrc_b:
            rows.append(_row('idle_fraction', id_a, id_b, None,
                             thr['idle'], 'skipped',
                             f'sources differ ({isrc_a} vs {isrc_b})'))
        else:
            d = _rel(id_a, id_b)
            if d is not None:
                gate('idle_fraction', id_a, id_b, round(d, 4),
                     thr['idle'], d > thr['idle'],
                     f'source={isrc_a}')
            else:
                gate('idle_fraction', id_a, id_b, round(id_b, 4),
                     thr['idle'], id_b > thr['idle'],
                     f'zero-idle baseline: absolute gate, '
                     f'source={isrc_a}')

    # -- static peak-live bytes -------------------------------------------
    # The liveness model's bound needs no matching measurement source
    # (it is computed from the compiled program alone), so unlike the
    # runtime memory row it always compares when both runs carry it.
    pk_a, pk_b = a.get('static_peak_bytes'), b.get('static_peak_bytes')
    if pk_a is not None and pk_b is None:
        rows.append(_row('static_peak_bytes', pk_a, pk_b, None,
                         thr['static_peak'], 'REGRESSION',
                         _missing_note('candidate', b)))
    elif pk_a is None and pk_b is not None:
        rows.append(_row('static_peak_bytes', pk_a, pk_b, None,
                         thr['static_peak'], 'skipped',
                         _missing_note('baseline', a)))
    elif pk_a is not None:
        d = _rel(pk_a, pk_b)
        if d is None:
            rows.append(_row('static_peak_bytes', pk_a, pk_b, None,
                             thr['static_peak'], 'skipped',
                             'zero baseline'))
        else:
            gate('static_peak_bytes', pk_a, pk_b, round(d, 4),
                 thr['static_peak'], d > thr['static_peak'])

    # -- multi-device skew ------------------------------------------------
    sk_a = (a.get('skew') or {}).get('step_time_ratio')
    sk_b = (b.get('skew') or {}).get('step_time_ratio')
    if sk_a is not None and sk_b is not None:
        d = _rel(sk_a, sk_b)
        gate('skew_step_time_ratio', sk_a, sk_b,
             None if d is None else round(d, 4), thr['skew'],
             d is not None and d > thr['skew'])
    elif sk_a is not None or sk_b is not None:
        rows.append(_row('skew_step_time_ratio', sk_a, sk_b, None,
                         thr['skew'], 'skipped',
                         'aggregation missing from one run'))

    # -- compiles ---------------------------------------------------------
    ca, cb = a.get('compile_events', 0), b.get('compile_events', 0)
    extra = cb - ca
    gate('compile_events', ca, cb, extra, thr['new_compile_events'],
         extra > thr['new_compile_events'])

    # -- memory -----------------------------------------------------------
    ma, mb = a.get('peak_memory_bytes'), b.get('peak_memory_bytes')
    src_a, src_b = (a.get('peak_memory_source'), b.get('peak_memory_source'))
    if ma is not None and mb is None:
        rows.append(_row('peak_memory_bytes', ma, mb, None, thr['memory'],
                         'REGRESSION', _missing_note('candidate', b)))
    elif ma is None or mb is None:
        rows.append(_row('peak_memory_bytes', ma, mb, None, thr['memory'],
                         'skipped', _missing_note('baseline', a)))
    elif src_a != src_b:
        rows.append(_row('peak_memory_bytes', ma, mb, None, thr['memory'],
                         'skipped',
                         f'sources differ ({src_a} vs {src_b})'))
    else:
        d = _rel(ma, mb)
        gate('peak_memory_bytes', ma, mb, round(d, 4), thr['memory'],
             d > thr['memory'], f'source={src_a}')

    # -- kernel dispatch --------------------------------------------------
    da, db = _dispatch_outcomes(a), _dispatch_outcomes(b)
    for kernel, outcomes_a in sorted(da.items()):
        if 'pallas' not in outcomes_a:
            continue
        outcomes_b = db.get(kernel, set())
        # Absent counts as lost too: a candidate that never reached the
        # decision site stopped exercising the Pallas path just as
        # surely as one that fell back.
        lost = 'pallas' not in outcomes_b
        status = ('note' if allow_kernel_fallback else 'REGRESSION') \
            if lost else 'ok'
        note = '' if not lost else (
            'kernel fell back to XLA' if outcomes_b
            else 'kernel decision absent from candidate')
        rows.append(_row(f'dispatch[{kernel}]', 'pallas',
                         ','.join(sorted(x for x in outcomes_b if x))
                         or 'absent',
                         None, None, status, note))

    # -- serve per-stage latency (qtrace) ---------------------------------
    # Gate only when configured (like min_overlap): training runs have
    # no qtrace summary, and a default-on gate would spuriously skip or
    # fail every non-serving diff. When on, the lost-account rule
    # applies: a serving candidate that stopped producing the per-stage
    # account the baseline had fails — the attribution layer is itself
    # a gated artifact.
    sthr = thr.get('stage_p95')
    if sthr is not None:
        qa = a.get('qtrace_stages') or {}
        qb = b.get('qtrace_stages') or {}
        if not qa:
            rows.append(_row('qtrace_stages', None, len(qb) or None,
                             None, sthr, 'skipped',
                             'baseline has no qtrace stage account'))
        elif not qb:
            rows.append(_row('qtrace_stages', len(qa), None, None, sthr,
                             'REGRESSION',
                             'candidate lost the qtrace stage account '
                             'the baseline had'))
        else:
            for stage in sorted(qa):
                pa95 = (qa[stage] or {}).get('p95_ms')
                sb = qb.get(stage) or {}
                pb95 = sb.get('p95_ms')
                key = f'qtrace[{stage}].p95_ms'
                if pa95 is None:
                    continue
                if pb95 is None:
                    rows.append(_row(key, pa95, None, None, sthr,
                                     'REGRESSION',
                                     'stage account missing from '
                                     'candidate'))
                    continue
                d = _rel(pa95, pb95)
                if d is None:
                    rows.append(_row(key, pa95, pb95, None, sthr,
                                     'skipped', 'zero baseline'))
                    continue
                gate(key, pa95, pb95, round(d, 4), sthr, d > sthr)

    # -- goodput ratio (padding-waste account) ----------------------------
    # min_overlap semantics: absolute floor (0.0 goodput — every FLOP
    # spent on padding — is a meaningful value, and a ratio against it
    # is not), lost-account fails unconditionally, the floor only
    # gates when configured.
    gp_a, gp_b = a.get('goodput_ratio'), b.get('goodput_ratio')
    gfloor = thr.get('min_goodput')
    if gp_a is not None and gp_b is None:
        rows.append(_row('goodput_ratio', gp_a, gp_b, None, gfloor,
                         'REGRESSION', _missing_note('candidate', b)))
    elif gp_b is not None and gfloor is not None:
        gate('goodput_ratio', gp_a, gp_b,
             None if gp_a is None else round(gp_b - gp_a, 4), gfloor,
             gp_b < gfloor,
             'padding waste pushed useful FLOPs below the floor'
             if gp_b < gfloor else '')
    elif gp_a is not None or gp_b is not None:
        rows.append(_row('goodput_ratio', gp_a, gp_b,
                         None if None in (gp_a, gp_b)
                         else round(gp_b - gp_a, 4), gfloor, 'info',
                         'no --min-goodput floor configured'))

    # -- pad fraction (worst bucket) --------------------------------------
    # An ABSOLUTE increase bound: the gate fires on pad_b - pad_a >
    # threshold. Not a ratio — a 0.0 baseline (perfectly-filled
    # buckets) is exactly the baseline worth defending, and fractional
    # change against it is undefined.
    pf_a, pf_b = a.get('pad_fraction'), b.get('pad_fraction')
    plim = thr.get('pad_regression')
    if pf_a is not None and pf_b is None:
        rows.append(_row('pad_fraction', pf_a, pf_b, None, plim,
                         'REGRESSION', _missing_note('candidate', b)))
    elif plim is not None and pf_a is None and pf_b is not None:
        rows.append(_row('pad_fraction', pf_a, pf_b, None, plim,
                         'skipped', _missing_note('baseline', a)))
    elif plim is not None and pf_a is not None and pf_b is not None:
        d = round(pf_b - pf_a, 4)
        gate('pad_fraction', pf_a, pf_b, d, plim, d > plim,
             'worst-bucket padding grew past the allowed increase'
             if d > plim else '')
    elif pf_a is not None or pf_b is not None:
        rows.append(_row('pad_fraction', pf_a, pf_b,
                         None if None in (pf_a, pf_b)
                         else round(pf_b - pf_a, 4), plim, 'info',
                         'no --max-pad-regression bound configured'))

    # -- serve utilization (capacity model) -------------------------------
    # Absolute ceiling on the candidate's Little's-law ρ: a serve run
    # hotter than the ceiling has no headroom before the queue grows
    # without bound, whatever its latency quantiles say. Off unless
    # configured (training runs carry no capacity account);
    # lost-from-candidate fails.
    ut_a, ut_b = a.get('utilization'), b.get('utilization')
    uceil = thr.get('max_utilization')
    if ut_a is not None and ut_b is None:
        rows.append(_row('utilization', ut_a, ut_b, None, uceil,
                         'REGRESSION', _missing_note('candidate', b)))
    elif ut_b is not None and uceil is not None:
        gate('utilization', ut_a, ut_b,
             None if ut_a is None else round(ut_b - ut_a, 4), uceil,
             ut_b > uceil,
             'serve path over the utilization ceiling (no headroom)'
             if ut_b > uceil else '')
    elif ut_a is not None or ut_b is not None:
        rows.append(_row('utilization', ut_a, ut_b,
                         None if None in (ut_a, ut_b)
                         else round(ut_b - ut_a, 4), uceil, 'info',
                         'no --max-utilization ceiling configured'))

    # -- probes -----------------------------------------------------------
    fn = b.get('first_nonfinite')
    if fn:
        rows.append(_row('first_nonfinite', a.get('first_nonfinite'), fn,
                         None, None, 'REGRESSION',
                         f'candidate went non-finite at step '
                         f'{fn.get("step")} stage {fn.get("stage")!r}'))
    pa, pb = a.get('probes') or {}, b.get('probes') or {}
    for name in sorted(set(pa) | set(pb)):
        if name == 'nonfinite':
            continue
        mean_a = (pa.get(name) or {}).get('mean')
        mean_b = (pb.get(name) or {}).get('mean')
        rows.append(_row(f'probe[{name}].mean', mean_a, mean_b,
                         _rel(mean_a, mean_b), None, 'info',
                         'informational drift'))

    regressions = [r for r in rows if r['status'] == 'REGRESSION']
    return rows, regressions


def _fmt(v):
    if v is None:
        return '-'
    if isinstance(v, float):
        return f'{v:.6g}'
    return str(v)


def render_diff(a_path, b_path, rows, regressions):
    lines = [f'== run diff: {a_path} (baseline) vs {b_path} (candidate) ==',
             f'  {"metric":<28} {"baseline":>12} {"candidate":>12} '
             f'{"delta":>9} {"limit":>7}  status']
    for r in rows:
        delta = f'{r["delta"]:+.1%}' if isinstance(r['delta'], float) \
            else _fmt(r['delta'])
        limit = _fmt(r['limit'])
        note = f'  ({r["note"]})' if r['note'] else ''
        lines.append(f'  {r["metric"]:<28} {_fmt(r["a"]):>12} '
                     f'{_fmt(r["b"]):>12} {delta:>9} {limit:>7}  '
                     f'{r["status"]}{note}')
    lines.append(f'  => {len(regressions)} regression(s)')
    return '\n'.join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='python -m dgmc_tpu.obs.diff',
        description='Compare two --obs-dir runs; exit nonzero on '
                    'threshold regression (the CI perf gate).')
    parser.add_argument('baseline', help='obs dir of the baseline run')
    parser.add_argument('candidate', help='obs dir of the candidate run')
    parser.add_argument('--json', action='store_true',
                        help='print the machine-readable diff object')
    parser.add_argument('--max-step-p50-regression', type=float,
                        default=DEFAULT_THRESHOLDS['step_p50'],
                        metavar='FRAC',
                        help='allowed fractional p50 step-time increase '
                             '(default %(default)s)')
    parser.add_argument('--max-step-p95-regression', type=float,
                        default=DEFAULT_THRESHOLDS['step_p95'],
                        metavar='FRAC',
                        help='allowed fractional p95 step-time increase '
                             '(default %(default)s)')
    parser.add_argument('--max-throughput-regression', type=float,
                        default=DEFAULT_THRESHOLDS['throughput'],
                        metavar='FRAC',
                        help='allowed fractional steps/sec decrease '
                             '(default %(default)s)')
    parser.add_argument('--max-memory-regression', type=float,
                        default=DEFAULT_THRESHOLDS['memory'],
                        metavar='FRAC',
                        help='allowed fractional peak-memory increase '
                             '(default %(default)s)')
    parser.add_argument('--max-new-compile-events', type=int,
                        default=DEFAULT_THRESHOLDS['new_compile_events'],
                        metavar='N',
                        help='allowed extra compile events in the '
                             'candidate (default %(default)s)')
    parser.add_argument('--max-mfu-regression', type=float,
                        default=DEFAULT_THRESHOLDS['mfu'],
                        metavar='FRAC',
                        help='allowed fractional headline-MFU decrease '
                             '(efficiency.json; default %(default)s)')
    parser.add_argument('--max-intensity-regression', type=float,
                        default=DEFAULT_THRESHOLDS['intensity'],
                        metavar='FRAC',
                        help='allowed fractional decrease of the headline '
                             'achieved arithmetic intensity (FLOPs/byte, '
                             'efficiency.json; default %(default)s)')
    parser.add_argument('--min-overlap', type=float, default=None,
                        metavar='FRAC',
                        help='absolute floor on the headline modeled '
                             'collective overlap fraction '
                             '(efficiency.json, analysis/hlo_sched.py); '
                             'a candidate below it serialized the chunk '
                             'loop (default: floor off; a lost overlap '
                             'account still fails)')
    parser.add_argument('--min-measured-overlap', type=float,
                        default=None, metavar='FRAC',
                        help='absolute floor on the MEASURED '
                             'comm/compute overlap fraction '
                             '(efficiency.json, from the profiler-'
                             'trace attribution obs.attribution); '
                             'same lost-account semantics as '
                             '--min-overlap (default: floor off)')
    parser.add_argument('--max-idle-regression', type=float,
                        default=DEFAULT_THRESHOLDS['idle'],
                        metavar='FRAC',
                        help='allowed fractional increase of the '
                             'measured idle fraction (efficiency.json, '
                             'obs.attribution; device idle when the '
                             'capture has device tracks, host idle '
                             'otherwise — sources must match to '
                             'compare; default %(default)s)')
    parser.add_argument('--max-peak-regression', type=float,
                        default=DEFAULT_THRESHOLDS['static_peak'],
                        metavar='FRAC',
                        help='allowed fractional increase of the static '
                             'peak-live-bytes bound (efficiency.json, '
                             'analysis/hlo_liveness.py; '
                             'default %(default)s)')
    parser.add_argument('--max-skew-regression', type=float,
                        default=DEFAULT_THRESHOLDS['skew'],
                        metavar='FRAC',
                        help='allowed fractional increase of the device '
                             'step-time skew ratio (aggregate.json; '
                             'default %(default)s)')
    parser.add_argument('--max-restarts-regression', type=int,
                        default=DEFAULT_THRESHOLDS['restarts'],
                        metavar='N',
                        help='allowed extra supervisor restarts in the '
                             'candidate over the baseline '
                             '(recovery.json; a candidate whose '
                             'supervisor gave up fails unconditionally; '
                             'default %(default)s)')
    parser.add_argument('--max-stage-p95-regression', type=float,
                        default=DEFAULT_THRESHOLDS['stage_p95'],
                        metavar='FRAC',
                        help='allowed fractional increase of each serve '
                             'stage\'s p95 latency '
                             '(qtrace_summary.json; off unless set — '
                             'training runs carry no qtrace account; a '
                             'serving candidate that lost a stage '
                             'account the baseline had fails)')
    parser.add_argument('--max-hits1-regression', type=float,
                        default=DEFAULT_THRESHOLDS['hits1'],
                        metavar='FRAC',
                        help='allowed fractional Hits@1 decrease '
                             '(quality.json headline; off unless set — '
                             'a candidate that lost the quality account '
                             'the baseline had fails unconditionally)')
    parser.add_argument('--min-hits1', type=float,
                        default=DEFAULT_THRESHOLDS['min_hits1'],
                        metavar='FRAC',
                        help='absolute Hits@1 floor (quality.json '
                             'headline; the paper-parity pin — same '
                             'lost-account semantics as --min-overlap; '
                             'default: floor off)')
    parser.add_argument('--min-goodput', type=float,
                        default=DEFAULT_THRESHOLDS['min_goodput'],
                        metavar='FRAC',
                        help='absolute floor on the goodput ratio '
                             '(useful/executed FLOPs, goodput.json; '
                             'same lost-account semantics as '
                             '--min-overlap; default: floor off)')
    parser.add_argument('--max-pad-regression', type=float,
                        default=DEFAULT_THRESHOLDS['pad_regression'],
                        metavar='FRAC',
                        help='allowed ABSOLUTE increase of the worst-'
                             'bucket pad fraction (goodput.json; '
                             'absolute, not a ratio — a zero-pad '
                             'baseline gates directly; off unless set; '
                             'a candidate that lost the account the '
                             'baseline had fails unconditionally)')
    parser.add_argument('--max-utilization', type=float,
                        default=DEFAULT_THRESHOLDS['max_utilization'],
                        metavar='RHO',
                        help='absolute ceiling on the serve path\'s '
                             'Little\'s-law utilization (capacity.json; '
                             'off unless set — training runs carry no '
                             'capacity account; lost-from-candidate '
                             'fails)')
    parser.add_argument('--require-equal', type=str, default=None,
                        metavar='KEY[,KEY...]',
                        help='comma-separated logged-metric keys whose '
                             'FINAL values must be exactly equal in '
                             'both runs (the streamed-vs-offloaded '
                             'layout-equivalence gate: e.g. '
                             '--require-equal loss,hits1); a key '
                             'either run failed to log fails')
    parser.add_argument('--calibration', type=str, default=None,
                        metavar='FILE',
                        help='calibration.json (dgmc_tpu.obs.calibrate): '
                             'rescale the relative regression thresholds '
                             'to z * rel_sigma of each metric\'s fitted '
                             'noise floor; uncalibrated metrics keep '
                             'their fixed thresholds, absolute floors '
                             'and lost-account rules are untouched')
    parser.add_argument('--calibration-z', type=float, default=3.0,
                        metavar='Z',
                        help='significance multiple for calibrated gates '
                             '(default %(default)s noise floors)')
    parser.add_argument('--allow-kernel-fallback', action='store_true',
                        help='downgrade pallas->fallback dispatch changes '
                             'from regression to note')
    args = parser.parse_args(argv)

    for p in (args.baseline, args.candidate):
        if not os.path.isdir(p):
            print(f'diff: no such obs dir: {p}', file=sys.stderr)
            return 2

    a = summarize(load_run(args.baseline))
    b = summarize(load_run(args.candidate))
    if not a.get('metrics_records') and not a.get('steps'):
        print(f'diff: {args.baseline} holds no telemetry', file=sys.stderr)
        return 2
    if not b.get('metrics_records') and not b.get('steps'):
        print(f'diff: {args.candidate} holds no telemetry', file=sys.stderr)
        return 2

    thresholds = {
            'step_p50': args.max_step_p50_regression,
            'step_p95': args.max_step_p95_regression,
            'throughput': args.max_throughput_regression,
            'memory': args.max_memory_regression,
            'new_compile_events': args.max_new_compile_events,
            'mfu': args.max_mfu_regression,
            'intensity': args.max_intensity_regression,
            'skew': args.max_skew_regression,
            'restarts': args.max_restarts_regression,
            'min_overlap': args.min_overlap,
            'static_peak': args.max_peak_regression,
            'min_measured_overlap': args.min_measured_overlap,
            'stage_p95': args.max_stage_p95_regression,
            'hits1': args.max_hits1_regression,
            'min_hits1': args.min_hits1,
            'idle': args.max_idle_regression,
            'min_goodput': args.min_goodput,
            'pad_regression': args.max_pad_regression,
            'max_utilization': args.max_utilization,
            'require_equal': tuple(
                k.strip() for k in (args.require_equal or '').split(',')
                if k.strip()),
        }

    calibration_notes = []
    if args.calibration:
        from dgmc_tpu.obs.calibrate import (apply_calibration,
                                            load_calibration)
        try:
            cal = load_calibration(args.calibration)
        except ValueError as e:
            print(f'diff: {e}', file=sys.stderr)
            return 2
        thresholds, calibration_notes = apply_calibration(
            thresholds, cal, z=args.calibration_z)

    rows, regressions = diff_runs(
        a, b, thresholds=thresholds,
        allow_kernel_fallback=args.allow_kernel_fallback)
    for n in calibration_notes:
        # One info row per rescaled gate: a calibrated verdict must
        # say what it was judged by, in the same table it judged.
        rows.append(_row(
            f'calibrated:{n["gate"]}', n['fixed'],
            round(n['calibrated'], 4), None, round(n['calibrated'], 4),
            'info',
            f'{n["metric"]}: z={n["z"]:g} x rel_sigma='
            f'{n["rel_sigma"]:.4f} over n={n["n"]} samples'))

    if args.json:
        print(json.dumps({'baseline': args.baseline,
                          'candidate': args.candidate,
                          'rows': rows,
                          'calibration': calibration_notes or None,
                          'regressions': len(regressions),
                          'ok': not regressions}, indent=1))
    else:
        print(render_diff(args.baseline, args.candidate, rows, regressions))
    return 1 if regressions else 0


if __name__ == '__main__':
    sys.exit(main())
