"""Process-wide telemetry registry: counters, gauges, kernel-dispatch
outcomes, and jit compile events.

Everything here is host-side and cheap (a dict increment under a lock), so
it is always on — there is no "enabled" switch to forget. The registry is
the source of truth that :class:`~dgmc_tpu.obs.run.RunObserver` snapshots
into ``dispatch.json`` / ``timings.json``.

Counting semantics worth knowing:

- **Dispatch counters** (:func:`record_dispatch`) increment when a kernel
  *decision* is made. Auto decisions are resolved in un-jitted wrappers or
  at module trace time (see ``ops/topk.chunked_topk``,
  ``models/dgmc.py``), so each count corresponds to one traced program,
  not one executed device step — exactly the granularity at which the
  decision can change (a recompile). A program that traces once and runs
  10k steps contributes one count per decision site.
- **Compile events** (:class:`CompileWatcher`) come from
  ``jax.monitoring``: one event per XLA backend compile
  (``backend_compile_duration``) or per persistent-cache hit (a hit still
  builds a new executable from the cached binary). Repeated same-shape
  calls of a jitted function produce zero further events; a new padding
  bucket produces one — which makes recompile churn from unstable batch
  shapes directly visible.
"""

import contextlib
import threading
import time


class Registry:
    """Thread-safe labelled counters and gauges."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters = {}
        self._gauges = {}

    @staticmethod
    def _key(name, labels):
        return (name, tuple(sorted(labels.items())))

    def inc(self, name, value=1, **labels):
        with self._lock:
            k = self._key(name, labels)
            self._counters[k] = self._counters.get(k, 0) + value

    def gauge(self, name, value, **labels):
        with self._lock:
            self._gauges[self._key(name, labels)] = value

    def counter_value(self, name, **labels):
        with self._lock:
            return self._counters.get(self._key(name, labels), 0)

    def total(self, name):
        """Sum of a counter over all label combinations."""
        with self._lock:
            return sum(v for (n, _), v in self._counters.items()
                       if n == name)

    def snapshot(self):
        """JSON-ready dump: ``{'counters': [...], 'gauges': [...]}``."""
        with self._lock:
            return {
                'counters': [
                    {'name': n, 'labels': dict(ls), 'value': v}
                    for (n, ls), v in sorted(self._counters.items())],
                'gauges': [
                    {'name': n, 'labels': dict(ls), 'value': v}
                    for (n, ls), v in sorted(self._gauges.items())],
            }

    def reset(self):
        with self._lock:
            self._counters.clear()
            self._gauges.clear()


#: The process-wide registry every call site records into.
REGISTRY = Registry()


# ---------------------------------------------------------------------------
# Kernel-dispatch outcomes
# ---------------------------------------------------------------------------

DISPATCH_COUNTER = 'pallas_dispatch'

#: Live dispatch sinks: callables ``(kernel, outcome, reason)`` invoked
#: on every decision — how the flight recorder sees dispatch events as
#: they happen rather than as post-hoc table deltas. Guarded by its own
#: lock; a raising sink is dropped from the event, never from the run.
_dispatch_lock = threading.Lock()
_dispatch_sinks = []


def add_dispatch_sink(fn):
    with _dispatch_lock:
        _dispatch_sinks.append(fn)


def remove_dispatch_sink(fn):
    with _dispatch_lock:
        if fn in _dispatch_sinks:
            _dispatch_sinks.remove(fn)


def record_dispatch(kernel, outcome, reason):
    """Record one kernel-dispatch decision.

    Args:
        kernel: decision site, e.g. ``'topk'``, ``'dense_consensus'``,
            ``'sparse_consensus'``, ``'spline_route'``.
        outcome: ``'pallas'`` (fused kernel taken) or ``'fallback'``
            (XLA path taken).
        reason: why, e.g. ``'auto-tpu'``, ``'backend=cpu'``,
            ``'gspmd-silenced'``, ``'explicit'``, ``'size'``,
            ``'default-off'``.
    """
    REGISTRY.inc(DISPATCH_COUNTER, kernel=kernel, outcome=outcome,
                 reason=reason)
    with _dispatch_lock:
        sinks = tuple(_dispatch_sinks)
    for fn in sinks:
        try:
            fn(kernel, outcome, reason)
        except Exception:
            pass


def dispatch_table():
    """Dispatch counts as sorted rows of
    ``{'kernel', 'outcome', 'reason', 'count'}``."""
    rows = []
    for rec in REGISTRY.snapshot()['counters']:
        if rec['name'] != DISPATCH_COUNTER:
            continue
        rows.append({**rec['labels'], 'count': rec['value']})
    return sorted(rows, key=lambda r: (r.get('kernel', ''),
                                       r.get('outcome', ''),
                                       r.get('reason', '')))


def padding_bucket_table():
    """Padding-bucket collation counts (``utils.data.pad_pair_batch``):
    one row per distinct (batch, nodes, edges) padding — more rows means
    more XLA programs for the consuming step function."""
    rows = [dict(rec['labels'], count=rec['value'])
            for rec in REGISTRY.snapshot()['counters']
            if rec['name'] == 'padding_bucket']
    return sorted(rows, key=lambda r: -r['count'])


#: Real-size axes the collation layer accumulates per padding bucket:
#: pre-padding node/edge totals for each pair side. A separate counter
#: family, NOT extra ``padding_bucket`` labels — the full label set is a
#: counter's identity, and the recompile lint's
#: ``analysis/recompile.bucket_signature`` hashes the bucket rows, so
#: the real-size account must ride beside the bucket counter, never
#: fragment it.
PADDING_REAL_AXES = ('nodes_s', 'nodes_t', 'edges_s', 'edges_t')


def record_padding(batch, nodes, edges, real=None):
    """Count one collation into its padding bucket, optionally with the
    batch's REAL (pre-padding) per-axis totals — what
    ``obs.goodput`` recomputes pad waste from in any recorded obs dir.

    ``real`` maps :data:`PADDING_REAL_AXES` names to this collation's
    summed real sizes (e.g. total source nodes across the batch).
    """
    labels = {'batch': batch, 'nodes': nodes, 'edges': edges}
    REGISTRY.inc('padding_bucket', **labels)
    for axis, value in (real or {}).items():
        if axis in PADDING_REAL_AXES and value is not None:
            REGISTRY.inc('padding_real', value=int(value), axis=axis,
                         **labels)


def padding_real_table():
    """Accumulated real-size totals per padding bucket and axis: rows of
    ``{'batch', 'nodes', 'edges', 'axis', 'count'}`` (``count`` is the
    summed real sizes, a monotonic counter like every registry value —
    delta-friendly for :meth:`RunObserver` baselines)."""
    rows = [dict(rec['labels'], count=rec['value'])
            for rec in REGISTRY.snapshot()['counters']
            if rec['name'] == 'padding_real']
    return sorted(rows, key=lambda r: (str(r.get('nodes')),
                                       str(r.get('edges')),
                                       r.get('axis', '')))


# ---------------------------------------------------------------------------
# Compile events (jax.monitoring)
# ---------------------------------------------------------------------------

# jax.monitoring has no unregister API, so ONE module-level listener is
# installed on first use and fans out to the registry + every live watcher.
_listener_lock = threading.Lock()
_listener_installed = False
_watchers = []
_COMPILE_DURATION_EVENT = '/jax/core/compile/backend_compile_duration'
_CACHE_HIT_EVENT = '/jax/compilation_cache/cache_hits'


def _on_event_duration(event, duration, **kw):
    if event != _COMPILE_DURATION_EVENT:
        return
    REGISTRY.inc('compile_events')
    REGISTRY.inc('compile_seconds', value=duration)
    rec = {'time': time.time(), 'kind': 'backend_compile',
           'duration_s': round(duration, 4)}
    with _listener_lock:
        for w in _watchers:
            w._record(rec)


def _on_event(event, **kw):
    # A persistent-cache hit skips backend_compile but still builds a new
    # executable — count it as a compile event so the churn signal does
    # not vanish when the on-disk cache is warm.
    if event != _CACHE_HIT_EVENT:
        return
    REGISTRY.inc('compile_events')
    REGISTRY.inc('compile_cache_hits')
    rec = {'time': time.time(), 'kind': 'cache_hit', 'duration_s': 0.0}
    with _listener_lock:
        for w in _watchers:
            w._record(rec)


def _ensure_listener():
    global _listener_installed
    with _listener_lock:
        if _listener_installed:
            return
        import jax
        jax.monitoring.register_event_duration_secs_listener(
            _on_event_duration)
        jax.monitoring.register_event_listener(_on_event)
        _listener_installed = True


def compile_event_count():
    """Process-lifetime compile-event count (compiles + cache hits) seen
    since the first watcher/observer was installed."""
    return REGISTRY.total('compile_events')


class CompileWatcher:
    """Scoped view over compile events, with optional phase labels.

    ``jax.monitoring`` reports compile durations without attribution, so a
    watcher lets the caller bracket regions (``with w.label('phase2')``)
    and attributes every event inside the bracket to that label — the
    per-step-function attribution the events themselves lack.

    Use as a context manager; events are collected between ``__enter__``
    and ``close()``/``__exit__`` (the module listener stays installed —
    there is no unregister API — but a closed watcher stops recording).

    ``on_event`` (optional) is called with each labelled event record
    as it lands — the flight recorder's live view of compile activity.
    It runs under the listener lock, so it must be cheap and must not
    re-enter this module; a raising callback is swallowed.
    """

    def __init__(self, on_event=None):
        self._events = []
        self._label = 'run'
        self._open = False
        self._on_event = on_event

    # -- listener callback (under _listener_lock) --
    def _record(self, rec):
        if self._open:
            rec = dict(rec, label=self._label)
            self._events.append(rec)
            if self._on_event is not None:
                try:
                    self._on_event(rec)
                except Exception:
                    pass

    def __enter__(self):
        _ensure_listener()
        with _listener_lock:
            self._open = True
            _watchers.append(self)
        return self

    def close(self):
        with _listener_lock:
            self._open = False
            if self in _watchers:
                _watchers.remove(self)

    def __exit__(self, *exc):
        self.close()

    @contextlib.contextmanager
    def label(self, name):
        """Attribute compile events inside the block to ``name``."""
        prev, self._label = self._label, name
        try:
            yield
        finally:
            self._label = prev

    @property
    def events(self):
        with _listener_lock:
            return list(self._events)

    def count(self):
        return len(self.events)

    def summary(self):
        """``{'events', 'compile_s', 'cache_hits', 'by_label'}`` for
        ``timings.json``."""
        evs = self.events
        by_label = {}
        for e in evs:
            d = by_label.setdefault(e['label'], {'events': 0,
                                                 'compile_s': 0.0})
            d['events'] += 1
            d['compile_s'] = round(d['compile_s'] + e['duration_s'], 4)
        return {
            'events': len(evs),
            'compile_s': round(sum(e['duration_s'] for e in evs), 4),
            'cache_hits': sum(e['kind'] == 'cache_hit' for e in evs),
            'by_label': by_label,
        }
