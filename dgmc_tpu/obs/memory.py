"""Memory telemetry: per-device allocator snapshots with a host fallback.

``device.memory_stats()`` is the live HBM allocator view on real TPUs, but
it returns ``None``/``{}`` on the CPU backend and on some tunneled TPU
platforms (bench.py's notes). Observability must not silently go dark
there, so every snapshot also records host process memory from
``/proc/self/status`` (VmRSS/VmHWM) — on CPU runs the "HBM" *is* host
memory, and on a starved tunneled platform the host numbers still bound
the process. Peak extraction in ``obs.report`` prefers device peaks and
falls back to the host high-water mark.
"""

import time


def _device_stats():
    """Per-device allocator stats; entries are ``None`` where the platform
    publishes nothing."""
    import jax
    out = []
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        rec = {'id': d.id, 'kind': d.device_kind, 'platform': d.platform}
        if stats:
            rec['bytes_in_use'] = int(stats.get('bytes_in_use', 0))
            peak = stats.get('peak_bytes_in_use')
            if peak is not None:
                rec['peak_bytes_in_use'] = int(peak)
            limit = stats.get('bytes_limit')
            if limit is not None:
                rec['bytes_limit'] = int(limit)
        else:
            rec['stats'] = None
        out.append(rec)
    return out


def _host_stats():
    """Host process RSS and high-water mark, in bytes."""
    out = {}
    try:
        with open('/proc/self/status') as f:
            for line in f:
                if line.startswith('VmRSS:'):
                    out['rss_bytes'] = int(line.split()[1]) * 1024
                elif line.startswith('VmHWM:'):
                    out['peak_rss_bytes'] = int(line.split()[1]) * 1024
    except OSError:
        pass
    if 'peak_rss_bytes' not in out:
        try:
            import resource
            ru = resource.getrusage(resource.RUSAGE_SELF)
            out['peak_rss_bytes'] = ru.ru_maxrss * 1024  # KiB on Linux
        except Exception:
            pass
    return out


def memory_snapshot(tag=''):
    """One labelled memory snapshot: device allocator stats + host RSS."""
    return {'tag': tag, 'time': time.time(),
            'devices': _device_stats(), 'host': _host_stats()}


def compiled_memory(compiled):
    """Static peak-HBM bound of one compiled executable
    (``memory_analysis``): argument + output + temp bytes. Works even
    where the live allocator publishes nothing. Returns ``{}`` if the
    platform refuses."""
    try:
        ma = compiled.memory_analysis()
        return {
            'argument_bytes': int(ma.argument_size_in_bytes),
            'output_bytes': int(ma.output_size_in_bytes),
            'temp_bytes': int(ma.temp_size_in_bytes),
            'total_bytes': int(ma.argument_size_in_bytes +
                               ma.output_size_in_bytes +
                               ma.temp_size_in_bytes),
        }
    except Exception:
        return {}
