"""Cost & efficiency attribution: FLOPs/bytes per pipeline stage, MFU.

"As fast as the hardware allows" (ROADMAP) is only checkable against an
account of what the hardware allows. This module produces that account,
in the roofline spirit of the scaling literature (Megatron-LM's
model-FLOPs utilization):

- **Program totals** from XLA's own cost model:
  ``Lowered.cost_analysis()`` / ``Compiled.cost_analysis()`` — FLOPs and
  bytes accessed per executed step.
- **Per-stage attribution** from the PR-1 ``jax.named_scope`` spans:
  the lowered MLIR keeps every op's scope path (``.../psi1/...``,
  ``.../consensus_iter/psi2/...``) in its ``loc`` metadata, so walking
  the module attributes analytic dot-FLOPs and result bytes to the
  pipeline stages (``psi1``, ``initial_corr``, ``topk``,
  ``consensus_iter``, ``psi2``, plus the train step's ``loss`` and
  ``optimizer`` scopes). Backward-pass ops inherit their primal scope
  through jax's transpose naming, so each stage's number covers forward
  + backward.
- **Collectives** in sharded programs: all-reduce / all-gather /
  reduce-scatter / all-to-all / collective-permute op counts and result
  bytes, from compiled HLO (post-GSPMD) or manual-collective StableHLO —
  counted by the shared HLO walker
  (:mod:`dgmc_tpu.analysis.hlo_comm`), the same parser the lint SHD
  tier builds its collective schedules on, so the cost account and the
  lint rules can never disagree about what a program moves.
- **MFU / roofline utilization**: ``flops / (step_time * peak_flops)``
  against a per-backend peak table (:data:`PEAK_FLOPS`, moved here from
  ``bench.py``) with an explicit CPU fallback entry, so smoke runs on
  the CI host report a small-but-comparable figure instead of nothing.

Two entry points:

- :func:`cost_summary` — one program (a jitted callable + args, a
  ``Lowered``, or a ``Compiled``); what
  :meth:`RunObserver.record_cost <dgmc_tpu.obs.run.RunObserver>` calls.
  The result lands in the run's ``efficiency.json`` artifact.
- ``python -m dgmc_tpu.obs.cost`` — the registered hot-specimen table
  (:mod:`dgmc_tpu.analysis.registry`), fully compiled, with
  ``Compiled.cost_analysis()`` totals; ``--obs-dir`` merges the rows
  into that run's ``efficiency.json`` under ``specimen.<name>`` keys.

jax is imported lazily (inside functions): the parsing helpers run on
saved text anywhere, and importing this module must never bring up a
backend.
"""

import argparse
import json
import math
import re
import sys

# The collective accounting (op table, byte counting, dtype widths) and
# the stage bucketing live in the shared walker; re-exported here so
# existing callers of ``cost.collective_table`` / ``cost.STAGE_NAMES``
# / ``cost.stage_of`` keep working.
from dgmc_tpu.analysis.hlo_comm import (COLLECTIVE_OPS,  # noqa: F401
                                        DTYPE_BYTES as _DTYPE_BYTES,
                                        STAGE_NAMES, collective_table,
                                        hlo_shape_bytes, mlir_tensor_info,
                                        stage_of)

__all__ = [
    'PEAK_FLOPS', 'CPU_PEAK_FLOPS', 'STAGE_NAMES', 'COLLECTIVE_OPS',
    'peak_flops_entry', 'stage_table', 'collective_table',
    'analysis_totals', 'cost_summary', 'efficiency_payload',
    'headline_of', 'specimen_costs', 'main',
]


def headline_of(payload, key):
    """The efficiency payload's headline value for one per-program
    ``key`` (``arith_intensity``, ``overlap_fraction``,
    ``static_peak_bytes``, ``flops``, ...): the ``train_step``
    program's when present, else the first program carrying one. The
    ONE convention ``obs.report``, ``obs.diff`` (via the summary) and
    ``obs.attribution``'s reconciliation share — so the static and
    measured sides of a comparison always pick the same program."""
    programs = (payload or {}).get('programs') or {}
    ts = programs.get('train_step') or {}
    if ts.get(key) is not None:
        return ts[key]
    for p in programs.values():
        if p.get(key) is not None:
            return p[key]
    return None

#: Documented dense-matmul peak FLOP/s per chip (bf16, public TPU spec
#: sheets). MFU = flops / (step_time * peak) is an honest ceiling ratio:
#: f32 HIGHEST-precision matmuls can at best reach ~1/6 of the bf16
#: peak, so these numbers understate kernel quality but are comparable
#: round over round and across chips. (Moved from bench.py, which now
#: imports it from here.)
PEAK_FLOPS = {
    'TPU v4': 275e12,
    'TPU v5 lite': 197e12,   # v5e
    'TPU v5e': 197e12,
    'TPU v5': 459e12,        # v5p
    'TPU v5p': 459e12,
    'TPU v6 lite': 918e12,   # v6e / Trillium
}

#: CPU fallback peak: one core x ~3 GHz x 16 f32 FLOP/cycle (AVX2 FMA) —
#: a nominal single-core roofline anchor so CPU smoke runs report an MFU
#: that is tiny but nonzero and comparable run over run, which is all
#: ``obs.diff``'s MFU gate needs.
CPU_PEAK_FLOPS = 48e9

def peak_flops_entry(device=None):
    """``{'peak_flops', 'ref', 'source'}`` for ``device`` (default: the
    first jax device). ``source`` is ``'table'`` for known accelerators,
    ``'cpu-fallback'`` for the nominal CPU entry, ``'unknown'`` (with
    ``peak_flops: None``) for an accelerator missing from the table —
    callers omit MFU rather than fabricate one."""
    if device is None:
        import jax
        device = jax.devices()[0]
    kind = getattr(device, 'device_kind', str(device))
    platform = getattr(device, 'platform', '')
    peak = PEAK_FLOPS.get(kind)
    if peak:
        return {'peak_flops': peak, 'ref': f'{kind} bf16', 'source': 'table'}
    if platform == 'cpu':
        return {'peak_flops': CPU_PEAK_FLOPS,
                'ref': 'cpu nominal (1 core x 3 GHz x 16 f32 FLOP/cycle)',
                'source': 'cpu-fallback'}
    return {'peak_flops': None, 'ref': kind, 'source': 'unknown'}


# ---------------------------------------------------------------------------
# MLIR (lowered StableHLO) parsing: stage attribution
# ---------------------------------------------------------------------------

# `#loc42 = loc("jit(f)/jit(main)/psi1/dot_general"(#loc9))` — the quoted
# string is the op-name scope path. Plain file locs have no '/' path.
_LOC_DEF = re.compile(r'^#loc(\d+) = loc\("([^"]*)"')
_LOC_REF = re.compile(r'loc\(#loc(\d+)\)')
_LOC_INLINE = re.compile(r'loc\("([^"]*)"')
_TENSOR = re.compile(r'tensor<(?:([0-9x?]*)x)?([a-z][a-z0-9]*)>')
_CONTRACT = re.compile(r'contracting_dims\s*=\s*\[([0-9, ]*)\]'
                       r'\s*x\s*\[[0-9, ]*\]')
_CONTRACT_ATTR = re.compile(r'lhs_contracting_dimensions\s*=\s*'
                            r'\[([0-9, ]*)\]')


def _tensor_info(dims, dtype):
    """(element_count, bytes) for one parsed ``tensor<...>`` type —
    the shared walker's MLIR-type accounting."""
    return mlir_tensor_info(dims or '', dtype)


def _loc_names(asm):
    """{loc_id: op_name} for every loc definition that carries a scope
    path (a '/'-separated op name, not a bare file location)."""
    names = {}
    for line in asm.splitlines():
        m = _LOC_DEF.match(line)
        if m and '/' in m.group(2):
            names[m.group(1)] = m.group(2)
    return names


def _op_name_of(line, loc_names):
    m = _LOC_REF.search(line)
    if m:
        return loc_names.get(m.group(1), '')
    m = _LOC_INLINE.search(line)
    return m.group(1) if m and '/' in m.group(1) else ''


def _dot_flops(line):
    """Analytic FLOPs of one ``stablehlo.dot_general`` asm line:
    ``2 * prod(result dims) * prod(contracted dims)``. Returns 0 when
    the line cannot be parsed (never raises on odd syntax)."""
    tensors = _TENSOR.findall(line)
    if len(tensors) < 3:
        return 0
    lhs, out = tensors[0], tensors[-1]
    m = _CONTRACT.search(line) or _CONTRACT_ATTR.search(line)
    if not m:
        return 0
    lhs_dims = [d for d in (lhs[0].split('x') if lhs[0] else []) if d]
    k = 1
    try:
        for idx in (int(s) for s in m.group(1).replace(' ', '').split(',')
                    if s):
            k *= int(lhs_dims[idx])
    except (IndexError, ValueError):
        return 0
    out_n, _ = _tensor_info(out[0], out[1])
    return 2 * out_n * k


def stage_table(asm):
    """Per-stage op/FLOP/byte attribution from lowered MLIR asm (as
    produced by ``lowered.compiler_ir().operation.get_asm(
    enable_debug_info=True)``).

    Returns ``{stage: {'ops', 'dot_ops', 'flops', 'bytes_out'}}`` where
    ``flops`` is the analytic dot-general count (the MXU work) and
    ``bytes_out`` sums every op's result-tensor bytes (a proxy for the
    stage's memory traffic). Stages follow :data:`STAGE_NAMES` plus
    ``'other'`` for unscoped ops.
    """
    loc_names = _loc_names(asm)
    table = {}
    for line in asm.splitlines():
        stripped = line.lstrip()
        if not (stripped.startswith('%') and '= ' in stripped):
            continue
        tensors = _TENSOR.findall(line)
        if not tensors:
            continue
        name = _op_name_of(line, loc_names)
        stage = stage_of(name) if name else 'other'
        row = table.setdefault(stage, {'ops': 0, 'dot_ops': 0, 'flops': 0,
                                       'bytes_out': 0})
        row['ops'] += 1
        # Result type: the tensor after '->' when present (functions /
        # dot_general), else the trailing type annotation.
        arrow = line.rfind('->')
        res_match = None
        for m in _TENSOR.finditer(line):
            if arrow < 0 or m.start() > arrow:
                res_match = m
        if res_match is not None:
            _, nbytes = _tensor_info(res_match.group(1) or '',
                                     res_match.group(2))
            row['bytes_out'] += nbytes
        if 'dot_general' in line:
            row['dot_ops'] += 1
            row['flops'] += _dot_flops(line)
    return table


# ---------------------------------------------------------------------------
# Program summaries
# ---------------------------------------------------------------------------


def analysis_totals(target):
    """``{'flops', 'bytes'}`` from ``target.cost_analysis()`` (a
    ``Lowered`` or ``Compiled``); ``{}`` when the platform refuses."""
    try:
        ca = target.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        out = {}
        flops = float(ca.get('flops', 0.0) or 0.0)
        if flops > 0 and math.isfinite(flops):
            out['flops'] = flops
        nbytes = float(ca.get('bytes accessed', 0.0) or 0.0)
        if nbytes > 0 and math.isfinite(nbytes):
            out['bytes'] = nbytes
        return out
    except Exception:
        return {}


def cost_summary(target, *args, step_time_s=None):
    """Cost account of one program.

    ``target`` may be a jitted callable (``*args`` are its example
    arguments; the function is **lowered once, not compiled** — cheap
    enough to run inside a training CLI), a ``jax.stages.Lowered``, or a
    ``jax.stages.Compiled`` (bench.py's AOT path — exact post-
    optimization totals and post-GSPMD collectives).

    Returns ``{'flops', 'bytes', 'arith_intensity', 'stages',
    'collectives', 'source', ['step_time_s']}`` — any field the target
    cannot provide is omitted rather than guessed.
    """
    lowered = compiled = None
    if hasattr(target, 'lower'):
        lowered = target.lower(*args)
    elif hasattr(target, 'compiler_ir'):
        lowered = target
    else:
        compiled = target

    out = {}
    if lowered is not None:
        out['source'] = 'lowered'
        out.update(analysis_totals(lowered))
        try:
            asm = lowered.compiler_ir().operation.get_asm(
                enable_debug_info=True)
        except Exception:
            asm = ''
        if asm:
            stages = stage_table(asm)
            if stages:
                out['stages'] = stages
            coll = collective_table(asm)
            if coll['ops']:
                out['collectives'] = coll
    else:
        out['source'] = 'compiled'
        out.update(analysis_totals(compiled))
        try:
            text = compiled.as_text()
        except Exception:
            text = ''
        if text:
            out['collectives'] = collective_table(text)
            stages = _compiled_stage_bytes(text)
            if stages:
                out['stages'] = stages
            out.update(_schedule_fields(text))
    if out.get('flops') and out.get('bytes'):
        out['arith_intensity'] = round(out['flops'] / out['bytes'], 3)
    if step_time_s:
        out['step_time_s'] = step_time_s
    return out


def _schedule_fields(hlo_text):
    """Schedule/liveness account of one compiled (post-GSPMD) program:
    ``overlap_fraction`` (payload-weighted modeled collective overlap,
    omitted when the program moves nothing), ``critical_path_share``,
    and ``static_peak_bytes`` (the liveness model's per-device bound) —
    the same models the SCH/MEM lint tier gates on
    (:mod:`dgmc_tpu.analysis.hlo_sched` /
    :mod:`dgmc_tpu.analysis.hlo_liveness`), so ``efficiency.json`` and
    the lint can never disagree about what a program overlaps or
    holds."""
    out = {}
    try:
        from dgmc_tpu.analysis.hlo_comm import parse_hlo_module
        from dgmc_tpu.analysis.hlo_liveness import peak_summary
        from dgmc_tpu.analysis.hlo_sched import schedule_summary
        module = parse_hlo_module(hlo_text)   # ONE parse for both models
    except Exception as e:
        # A model error must leave a breadcrumb, not a bare missing
        # field: obs.diff reports a vanished account as REGRESSION, and
        # "missing from candidate" with no cause is undiagnosable.
        return {'schedule_error': f'{type(e).__name__}: {e}'}
    try:
        sched = schedule_summary(module)
        if sched.get('overlap_fraction') is not None:
            out['overlap_fraction'] = sched['overlap_fraction']
        if sched.get('critical_path_share') is not None:
            out['critical_path_share'] = sched['critical_path_share']
    except Exception as e:
        out['schedule_error'] = f'{type(e).__name__}: {e}'
    try:
        # Independent of the schedule model: a failure in one must not
        # discard the other's already-computed account.
        peak = peak_summary(module)
        if peak.get('static_peak_bytes'):
            out['static_peak_bytes'] = peak['static_peak_bytes']
    except Exception as e:
        out['liveness_error'] = f'{type(e).__name__}: {e}'
    return out


_HLO_OPNAME = re.compile(r'op_name="([^"]*)"')


def _compiled_stage_bytes(hlo_text):
    """Per-stage op counts/result bytes from compiled HLO metadata.
    Fusion hides individual dots, so no analytic FLOPs here — bytes and
    op counts still localize where the program's work sits."""
    table = {}
    for line in hlo_text.splitlines():
        m = _HLO_OPNAME.search(line)
        if not m or '=' not in line:
            continue
        stage = stage_of(m.group(1))
        row = table.setdefault(stage, {'ops': 0, 'bytes_out': 0})
        row['ops'] += 1
        head = line.split('=', 1)[0] + '=' + \
            line.split('=', 1)[1].split('(', 1)[0]
        row['bytes_out'] += hlo_shape_bytes(head)
    return table


def efficiency_payload(programs, fallback_step_time_s=None, device=None):
    """Assemble the ``efficiency.json`` artifact from named
    :func:`cost_summary` results.

    MFU is computed per program from its own ``step_time_s`` when the
    caller measured one (bench sections), else from
    ``fallback_step_time_s`` (the run's observed step p50, marked
    ``step_time_source: 'observed_p50'``). The headline ``mfu`` is the
    ``train_step`` program's when present, else the first program with
    one.
    """
    peak = peak_flops_entry(device)
    if device is None:
        try:
            import jax
            device = jax.devices()[0]
        except Exception:
            device = None
    out = {
        'device_kind': getattr(device, 'device_kind', None),
        'platform': getattr(device, 'platform', None),
        'peak_flops': peak['peak_flops'],
        'peak_flops_ref': peak['ref'],
        'peak_flops_source': peak['source'],
        'programs': {},
    }
    for name, summary in programs.items():
        entry = dict(summary)
        flops = entry.get('flops')
        step_s = entry.get('step_time_s')
        if step_s is None and fallback_step_time_s:
            step_s = fallback_step_time_s
            entry['step_time_s'] = round(step_s, 6)
            entry['step_time_source'] = 'observed_p50'
        if flops and step_s and peak['peak_flops']:
            # 4 significant digits, not fixed decimals: a tiny smoke-run
            # MFU must stay nonzero for the diff gate to compare.
            entry['mfu'] = float(
                f'{flops / (step_s * peak["peak_flops"]):.4g}')
        out['programs'][name] = entry
    headline = None
    if 'train_step' in out['programs']:
        headline = out['programs']['train_step'].get('mfu')
    if headline is None:
        for entry in out['programs'].values():
            if entry.get('mfu') is not None:
                headline = entry['mfu']
                break
    if headline is not None:
        out['mfu'] = headline
    return out


# ---------------------------------------------------------------------------
# Specimen mode (the analysis registry's hot-function table, compiled)
# ---------------------------------------------------------------------------


def _compile_specimen(spec):
    """Build + AOT-compile one registry specimen (probes forced off —
    the registry's contract, enforced by the shared
    :class:`~dgmc_tpu.analysis.registry.SpecimenArtifacts` this rides
    on); returns ``(lowered, compiled)`` from ONE trace."""
    from dgmc_tpu.analysis.registry import SpecimenArtifacts
    art = SpecimenArtifacts(spec)
    return art.lowered(), art.compiled()


def specimen_costs(names=None, on_progress=None):
    """``{specimen_name: cost_summary}`` over the registered hot
    functions (:func:`dgmc_tpu.analysis.registry.default_specimens`),
    each **fully compiled** so the totals are ``Compiled.cost_analysis``
    numbers and sharded specimens expose their post-GSPMD collectives.
    Probes are forced off (the registry's contract) so the programs
    measured are the production ones. Mesh specimens are skipped below
    their device count; a specimen that fails to build is reported as an
    ``{'error': ...}`` row instead of killing the table."""
    import jax
    from dgmc_tpu.analysis.registry import default_specimens
    out = {}
    n_dev = len(jax.devices())
    for spec in default_specimens():
        if names is not None and spec.name not in names:
            continue
        if spec.min_devices and n_dev < spec.min_devices:
            if on_progress:
                on_progress(f'skip {spec.name} (needs >= '
                            f'{spec.min_devices} devices, have {n_dev})')
            continue
        if on_progress:
            on_progress(f'compile {spec.name}')
        try:
            lowered, compiled = _compile_specimen(spec)
            summary = cost_summary(compiled)
            # The compiled view loses per-dot FLOP attribution to
            # fusion; graft the lowered view's stage table in (same
            # program, pre-optimization).
            low_stages = cost_summary(lowered).get('stages')
            if low_stages:
                summary['stages'] = low_stages
            out[spec.name] = summary
        except Exception as e:
            out[spec.name] = {'error': f'{type(e).__name__}: {e}'}
    return out


def _fmt_num(v):
    from dgmc_tpu.obs.observe import fmt_si
    return fmt_si(v)


def render_costs(payload):
    lines = ['== cost / efficiency ==',
             f'  device           {payload.get("device_kind")} '
             f'({payload.get("platform")})',
             f'  peak flops       {_fmt_num(payload.get("peak_flops"))} '
             f'[{payload.get("peak_flops_source")}: '
             f'{payload.get("peak_flops_ref")}]']
    if payload.get('mfu') is not None:
        lines.append(f'  MFU              {payload["mfu"]:.4%}')
    for name, p in payload.get('programs', {}).items():
        if 'error' in p:
            lines.append(f'  -- {name}: ERROR {p["error"]}')
            continue
        lines.append(f'  -- {name} --')
        lines.append(f'    flops / bytes / AI   '
                     f'{_fmt_num(p.get("flops"))} / '
                     f'{_fmt_num(p.get("bytes"))} / '
                     f'{p.get("arith_intensity", "-")}')
        if p.get('mfu') is not None:
            st = p.get('step_time_s')
            lines.append(f'    MFU                  {p["mfu"]:.4%} '
                         f'(step {st * 1e3:.3f} ms)' if st else
                         f'    MFU                  {p["mfu"]:.4%}')
        if p.get('overlap_fraction') is not None:
            lines.append(f'    overlap / cp-share   '
                         f'{p["overlap_fraction"]:.4f} / '
                         f'{p.get("critical_path_share", 0):.4f}')
        if p.get('static_peak_bytes') is not None:
            lines.append(f'    static peak          '
                         f'{_fmt_num(p["static_peak_bytes"])}B')
        for stage, row in (p.get('stages') or {}).items():
            lines.append(f'    stage {stage:<15} '
                         f'flops {_fmt_num(row.get("flops")):>8}  '
                         f'bytes {_fmt_num(row.get("bytes_out")):>8}  '
                         f'ops {row.get("ops", 0)}')
        coll = p.get('collectives') or {}
        if coll.get('ops'):
            for cname, row in coll['ops'].items():
                lines.append(f'    collective {cname:<15} '
                             f'x{row["count"]}  '
                             f'{_fmt_num(row["bytes"])}B')
    return '\n'.join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='python -m dgmc_tpu.obs.cost',
        description='FLOPs/bytes/MFU attribution over the registered '
                    'hot specimens; --obs-dir merges the rows into that '
                    "run's efficiency.json.")
    parser.add_argument('--specimens', default=None,
                        help='comma-separated specimen names '
                             '(default: all runnable)')
    parser.add_argument('--obs-dir', '--obs_dir', dest='obs_dir',
                        default=None,
                        help='obs run directory whose efficiency.json '
                             'receives the specimen rows (created if '
                             'absent; run rows are preserved)')
    parser.add_argument('--json', action='store_true',
                        help='print the machine-readable payload')
    args = parser.parse_args(argv)

    quiet = args.json

    def progress(msg):
        if not quiet:
            print(f'[obs.cost] {msg}', file=sys.stderr)

    names = (set(n.strip() for n in args.specimens.split(',') if n.strip())
             if args.specimens else None)
    costs = specimen_costs(names=names, on_progress=progress)
    if not costs:
        print('obs.cost: no runnable specimens matched', file=sys.stderr)
        return 2

    local = efficiency_payload({f'specimen.{k}': v
                                for k, v in costs.items()})
    if args.obs_dir:
        import os
        path = os.path.join(args.obs_dir, 'efficiency.json')
        existing = {}
        try:
            with open(path) as f:
                existing = json.load(f)
        except (OSError, ValueError):
            pass
        if existing:
            # Preserve the recording machine's account VERBATIM: the
            # run rows' MFU, device identity and headline were measured
            # there — re-deriving them against THIS machine's peak
            # table would corrupt them when the merge runs on a
            # different box (TPU run analyzed on a CPU workstation).
            # Only the freshly-compiled specimen rows are local facts.
            payload = dict(existing)
            programs = dict(existing.get('programs', {}))
            # Specimen rows are namespaced, so a rerun replaces them
            # idempotently without touching run rows.
            programs.update(local['programs'])
            payload['programs'] = programs
            if payload.get('device_kind') is None:
                for key in ('device_kind', 'platform', 'peak_flops',
                            'peak_flops_ref', 'peak_flops_source'):
                    payload[key] = local.get(key)
        else:
            payload = local
        os.makedirs(args.obs_dir, exist_ok=True)
        tmp = path + '.tmp'
        with open(tmp, 'w') as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)
    else:
        payload = local

    if args.json:
        print(json.dumps(payload, indent=1, sort_keys=True))
    else:
        print(render_costs(payload))
    return 0


if __name__ == '__main__':
    sys.exit(main())
