"""Variance calibration: noise floors for the regression gates.

Every ``obs.diff`` gate so far compared two single runs against a
hand-picked fixed threshold — so the load-bearing accuracy gates sat
at deliberately vacuous values (``--min-hits1 0.0``) because nothing
modeled run-to-run noise: a 3% hits@1 wobble is a fact of small-batch
eval, not a regression, and a gate that cannot tell the difference is
either mute or flaky. This module measures the difference:

- :func:`fit_calibration` fits per-metric **noise floors** — median
  and MAD (median absolute deviation), the robust pair that one bad
  run cannot drag — from two evidence sources: N repeat obs dirs of
  the same workload (``--obs-dir``, repeatable; metrics keyed by the
  ``obs.report`` summary vocabulary: ``step_p50_s``, ``hits1``, ...)
  and the committed longitudinal rounds (``--rounds benchmarks/``;
  keyed ``FAMILY.metric``: ``SERVE.hits1``, ``BENCH.step_p50_ms``).
  The robust sigma is ``1.4826 * MAD`` (normal-consistent), and
  ``rel_sigma = sigma / |median|`` is the unit the gates consume.
- :func:`apply_calibration` rescales ``obs.diff``'s RELATIVE
  regression thresholds to ``z * rel_sigma`` (z defaults to 3: a
  gate fires only on a shift three noise floors deep). Metrics
  without calibration (or with fewer than ``min_samples`` samples)
  keep their fixed threshold unchanged — pinned behavior, a thin
  calibration file must never silently widen every gate. Absolute
  floors (``--min-hits1`` etc.) stay explicit CLI values: deriving
  them from this file is a human step recorded in the CI workflow
  comments, because a floor is a product decision, not a noise
  estimate.

CLI::

    python -m dgmc_tpu.obs.calibrate \
        --obs-dir runs/rep1 --obs-dir runs/rep2 --obs-dir runs/rep3 \
        --rounds benchmarks/ --out benchmarks/calibration.json

jax-free (stdlib + the obs readers only).
"""

import argparse
import json
import os
import sys

__all__ = ['fit_samples', 'fit_calibration', 'apply_calibration',
           'collect_obs_metrics', 'collect_round_metrics',
           'CALIBRATED_GATES', 'CALIBRATION_SCHEMA_VERSION', 'main']

CALIBRATION_SCHEMA_VERSION = 1

#: diff threshold key -> obs.report summary metric that calibrates it.
#: RELATIVE gates only — each of these thresholds is a fraction of the
#: baseline value, the same unit as ``rel_sigma``. Absolute gates
#: (compile-event counts, restart counts, min_* floors) are outside
#: calibration's writ by design.
CALIBRATED_GATES = {
    'step_p50': 'step_p50_s',
    'step_p95': 'step_p95_s',
    'throughput': 'steps_per_sec',
    'memory': 'peak_memory_bytes',
    'mfu': 'mfu',
    'intensity': 'arith_intensity',
    'static_peak': 'static_peak_bytes',
    'idle': 'idle_fraction',
    'hits1': 'hits1',
}


def fit_samples(values):
    """Robust location/scale for one metric's samples.

    Returns ``{'n', 'median', 'mad', 'sigma', 'rel_sigma', 'min',
    'max'}``; ``sigma = 1.4826 * MAD`` (consistent for normal noise),
    ``rel_sigma = sigma / |median|`` or ``None`` at median 0 (no
    relative scale exists there).
    """
    vals = sorted(float(v) for v in values)
    n = len(vals)
    if n == 0:
        raise ValueError('fit_samples: no samples')

    def _median(sorted_vals):
        m = len(sorted_vals)
        mid = m // 2
        if m % 2:
            return sorted_vals[mid]
        return 0.5 * (sorted_vals[mid - 1] + sorted_vals[mid])

    median = _median(vals)
    mad = _median(sorted(abs(v - median) for v in vals))
    sigma = 1.4826 * mad
    rel_sigma = None if median == 0 else sigma / abs(median)
    return {'n': n, 'median': median, 'mad': mad,
            'sigma': sigma, 'rel_sigma': rel_sigma,
            'min': vals[0], 'max': vals[-1]}


def _numeric_items(mapping):
    for key, val in mapping.items():
        if isinstance(val, bool):
            continue
        if isinstance(val, (int, float)):
            yield key, float(val)


def collect_obs_metrics(obs_dirs):
    """``{metric: [value, ...]}`` across repeat obs dirs, keyed by the
    ``obs.report`` summary vocabulary (every numeric scalar the
    summary emits, plus per-stage qtrace p95s as
    ``qtrace_stage.<name>.p95_ms``)."""
    from dgmc_tpu.obs.report import load_run, summarize
    metrics = {}
    for d in obs_dirs:
        summary = summarize(load_run(d))
        flat = dict(_numeric_items(summary))
        for name, q in (summary.get('qtrace_stages') or {}).items():
            if isinstance(q, dict) and q.get('p95_ms') is not None:
                flat[f'qtrace_stage.{name}.p95_ms'] = float(q['p95_ms'])
        for key, val in flat.items():
            metrics.setdefault(key, []).append(val)
    return metrics


def collect_round_metrics(paths):
    """``{'FAMILY.metric': [value, ...]}`` across the committed round
    records (``obs.timeline``'s normalized rows; numeric scalars
    only — the round number itself is an index, not a metric)."""
    from dgmc_tpu.obs.timeline import collect_rounds
    metrics = {}
    for row in collect_rounds(paths):
        family = row.get('family') or '?'
        for key, val in _numeric_items(row):
            if key == 'round':
                continue
            metrics.setdefault(f'{family}.{key}', []).append(val)
    return metrics


def fit_calibration(obs_dirs=(), round_paths=(), min_samples=2):
    """The ``calibration.json`` body: per-metric fits from both
    evidence sources. Metrics with fewer than ``min_samples`` samples
    are dropped — one observation has no spread."""
    samples = {}
    if obs_dirs:
        samples.update(collect_obs_metrics(obs_dirs))
    if round_paths:
        samples.update(collect_round_metrics(round_paths))
    fitted = {key: fit_samples(vals)
              for key, vals in sorted(samples.items())
              if len(vals) >= min_samples}
    return {
        'version': CALIBRATION_SCHEMA_VERSION,
        'generated_by': 'python -m dgmc_tpu.obs.calibrate',
        'sources': {'obs_dirs': [os.path.normpath(d) for d in obs_dirs],
                    'rounds': [os.path.normpath(p)
                               for p in round_paths]},
        'min_samples': min_samples,
        'metrics': fitted,
    }


def load_calibration(path):
    """Parse + validate a calibration file; raises ``ValueError`` (a
    malformed calibration must fail the diff at startup, not silently
    judge with fixed thresholds)."""
    try:
        with open(path) as f:
            cal = json.load(f)
    except OSError as e:
        raise ValueError(f'calibration: cannot read {path}: {e}')
    except json.JSONDecodeError as e:
        raise ValueError(f'calibration: {path} is not valid JSON: {e}')
    if not isinstance(cal, dict) or not isinstance(
            cal.get('metrics'), dict):
        raise ValueError(f'calibration: {path} has no "metrics" object')
    return cal


def apply_calibration(thresholds, calibration, z=3.0, min_samples=3,
                      floor=0.01):
    """Rescale the relative gates to ``z * rel_sigma``.

    Returns ``(new_thresholds, notes)``; ``notes`` is one record per
    rescaled gate (for the diff's table — a calibrated verdict must
    say what it was judged by). Pinned fallbacks: a gate whose metric
    is uncalibrated, under-sampled, or scale-free (``rel_sigma``
    ``None``) keeps its fixed threshold; a calibrated threshold is
    floored at ``floor`` (a dead-flat repeat set must not produce a
    zero-width gate that fails on the next run's least significant
    digit).
    """
    metrics = calibration.get('metrics') or {}
    out = dict(thresholds)
    notes = []
    for gate, metric in CALIBRATED_GATES.items():
        if out.get(gate) is None:
            continue  # gate not armed: calibration must not arm it
        stats = metrics.get(metric)
        if not stats:
            continue
        if stats.get('n', 0) < min_samples:
            continue
        rel_sigma = stats.get('rel_sigma')
        if rel_sigma is None:
            continue
        calibrated = max(z * float(rel_sigma), floor)
        notes.append({'gate': gate, 'metric': metric,
                      'fixed': out[gate], 'calibrated': calibrated,
                      'rel_sigma': float(rel_sigma),
                      'n': stats['n'], 'z': z})
        out[gate] = calibrated
    return out, notes


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='python -m dgmc_tpu.obs.calibrate',
        description='Fit per-metric noise floors (median/MAD) from '
                    'repeat obs dirs and committed benchmark rounds; '
                    'write calibration.json for obs.diff '
                    '--calibration.')
    parser.add_argument('--obs-dir', action='append', default=[],
                        metavar='DIR',
                        help='one repeat-run obs dir (repeatable); '
                             'metrics keyed by the obs.report summary '
                             'vocabulary')
    parser.add_argument('--rounds', action='append', default=[],
                        metavar='DIR',
                        help='directory of committed *_r*.json rounds '
                             '(repeatable); metrics keyed '
                             'FAMILY.metric')
    parser.add_argument('--out', default='calibration.json',
                        help='output path (default: %(default)s)')
    parser.add_argument('--min-samples', type=int, default=2,
                        help='drop metrics with fewer samples '
                             '(default: %(default)s)')
    args = parser.parse_args(argv)

    if not args.obs_dir and not args.rounds:
        parser.error('need at least one --obs-dir or --rounds')
    cal = fit_calibration(obs_dirs=args.obs_dir,
                          round_paths=args.rounds,
                          min_samples=args.min_samples)
    if not cal['metrics']:
        print('calibrate: no metric reached --min-samples '
              f'{args.min_samples}; nothing to write', file=sys.stderr)
        return 2
    tmp = args.out + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(cal, f, indent=1, sort_keys=True)
        f.write('\n')
    os.replace(tmp, args.out)
    gates = sorted(m for m in CALIBRATED_GATES.values()
                   if m in cal['metrics'])
    print(f'calibrate: {len(cal["metrics"])} metrics fitted '
          f'({len(gates)} feed diff gates: {", ".join(gates)}) '
          f'-> {args.out}')
    for key in gates:
        s = cal['metrics'][key]
        rel = ('n/a' if s['rel_sigma'] is None
               else f'{s["rel_sigma"]:.4f}')
        print(f'  {key}: n={s["n"]} median={s["median"]:.6g} '
              f'rel_sigma={rel}')
    return 0


if __name__ == '__main__':
    sys.exit(main())
