"""Quality plane: the accuracy counterpart of the latency account.

The obs plane can attribute a 152 ms tail to ``admission_queue_wait``
but, before this module, could not say whether the matcher's *answers*
got worse — Hits@1 (the paper's headline metric) was computed in the
eval loops and discarded. :class:`QualityTracker` is the missing
instrument, one tracker per :class:`~dgmc_tpu.obs.run.RunObserver`,
fed from three directions:

* **Eval accounting** — the experiment CLIs push their per-epoch
  summaries (the :func:`dgmc_tpu.models.evalsum.eval_summary` dict:
  Hits@1/@k, MRR, loss) through :meth:`QualityTracker.observe_eval`;
  the tracker keeps first/last/best per scenario and a run-level
  headline (the last eval observed).
* **Consensus convergence** — ``consensus_delta`` probe records (the
  per-iteration ``delta_norm`` emitted inside ``DGMC.__call__``) feed
  :meth:`observe_consensus`; the tracker derives iterations-to-converge
  (first refinement iteration whose mean correction fell under
  ``tol`` × the first iteration's).
* **Serve-side confidence** — the engine's cheap in-graph per-query
  proxies (row entropy, top-1/top-2 margin, final correction norm,
  shortlist saturation) land in streaming histograms exported as
  ``dgmc_query_quality{signal=...}``, beside the low-confidence breach
  counter and the shadow audit's recall account.

``RunObserver.flush`` writes :meth:`payload` as ``quality.json`` — a
schema-pinned artifact ``obs.report`` renders, ``obs.timeline`` grows
columns from, and ``obs.diff`` gates with ``--max-hits1-regression`` /
``--min-hits1``.

Like every obs reader, this module has **no jax import**.
"""

import hashlib
import math
import threading

from dgmc_tpu.obs.live import StreamingHistogram

__all__ = ['QUALITY_SCHEMA_VERSION', 'QUALITY_SIGNALS', 'QUALITY_BOUNDS',
           'audit_keep', 'QualityTracker']

#: Bumped whenever quality.json's keyset changes; readers check it
#: before trusting field semantics.
QUALITY_SCHEMA_VERSION = 1

#: The per-query confidence proxies the serve engine computes in-graph.
QUALITY_SIGNALS = ('entropy', 'margin', 'correction', 'saturation')

#: Geometric bucket bounds for the quality histograms: the signals are
#: unitless and span entropy ~ln(k) down to correction norms ~1e-3, so
#: the grid runs 1e-3 .. ~1.2e3 at 25% resolution.
QUALITY_BOUNDS = tuple(0.001 * 1.25 ** i for i in range(64))

#: Cap on the audited-trace-id list carried in quality.json — the ids
#: pin sampling determinism in tests without growing the artifact
#: unboundedly on long-lived services.
AUDIT_TRACE_ID_CAP = 256

#: Convergence tolerance: the consensus loop counts as converged at the
#: first iteration whose mean ``delta_norm`` is under this fraction of
#: the first iteration's.
CONVERGE_TOL = 0.05


def audit_keep(seed, trace_id, rate):
    """Deterministic keep decision for the shadow audit — the qtrace
    retention discipline: a seeded hash of the trace id mapped to
    [0, 1) and compared against the sample rate, so the audited set is
    a pure function of (seed, trace ids) and byte-identical across
    runs, restarts and replicas."""
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    digest = hashlib.sha256(f'{seed}:audit:{trace_id}'.encode()).digest()
    return int.from_bytes(digest[:8], 'big') / 2.0 ** 64 < rate


def _finite(v):
    try:
        v = float(v)
    except (TypeError, ValueError):
        return None
    return v if math.isfinite(v) else None


class QualityTracker:
    """Run-level accuracy accounting; all methods thread-safe (probe
    callbacks, handler threads, the audit thread and the flush loop all
    feed one tracker)."""

    def __init__(self):
        self._lock = threading.Lock()
        # --- eval side -------------------------------------------------
        self._scenarios = {}
        self._headline = {'scenario': None, 'step': None, 'metrics': {}}
        # --- consensus side --------------------------------------------
        self._consensus = {}   # iteration -> [count, total, last]
        self._consensus_events = 0
        # --- serve side ------------------------------------------------
        self._hists = {s: StreamingHistogram(QUALITY_BOUNDS)
                       for s in QUALITY_SIGNALS}
        self._queries = 0
        self._saturated_queries = 0
        self._low_confidence = 0
        self._audit_rate = None
        self._audit_seed = None
        self._audited = 0
        self._audit_exact = 0
        self._audit_recall_sum = 0.0
        self._audit_recall_min = None
        self._audit_trace_ids = []
        self._audit_truncated = 0

    # --- eval accounting ----------------------------------------------

    def observe_eval(self, scenario, summary, step=None):
        """One eval-split summary (the ``eval_summary`` dict: ``count``
        plus named fractions / ``loss``). Keeps first/last/best per
        metric per scenario; the LAST call run-wide becomes the
        headline ``obs.report`` summarizes and ``obs.diff`` gates."""
        metrics = {k: _finite(v) for k, v in summary.items()
                   if k != 'count' and _finite(v) is not None}
        count = _finite(summary.get('count'))
        with self._lock:
            sc = self._scenarios.setdefault(
                scenario, {'evals': 0, 'count': None, 'step': None,
                           'metrics': {}})
            sc['evals'] += 1
            if count is not None:
                sc['count'] = count
            if step is not None:
                sc['step'] = step
            for name, v in metrics.items():
                m = sc['metrics'].setdefault(
                    name, {'first': v, 'last': v, 'best': v})
                m['last'] = v
                # 'best' is metric-aware: loss improves downward.
                m['best'] = (min(m['best'], v) if name == 'loss'
                             else max(m['best'], v))
            self._headline = {'scenario': scenario, 'step': step,
                              'metrics': dict(metrics)}

    # --- consensus convergence ----------------------------------------

    def observe_consensus(self, iteration, value):
        """One ``consensus_delta`` probe record: the mean row-wise
        correction norm at refinement ``iteration``."""
        v = _finite(value)
        if v is None or iteration is None:
            return
        i = int(iteration)
        with self._lock:
            self._consensus_events += 1
            slot = self._consensus.setdefault(i, [0, 0.0, 0.0])
            slot[0] += 1
            slot[1] += v
            slot[2] = v

    # --- serve-side confidence -----------------------------------------

    def observe_query(self, signals):
        """Per-query confidence proxies from the engine's answer
        (``signals`` carries the :data:`QUALITY_SIGNALS` scalars plus
        ``saturated_frac``)."""
        with self._lock:
            self._queries += 1
            for name in QUALITY_SIGNALS:
                v = _finite(signals.get(name))
                if v is not None:
                    self._hists[name].observe(v)
            sat = _finite(signals.get('saturated_frac'))
            if sat is not None and sat > 0:
                self._saturated_queries += 1

    def record_low_confidence(self):
        """A served answer fell under the ``--min-margin`` floor."""
        with self._lock:
            self._low_confidence += 1
            return self._low_confidence

    # --- shadow audit ---------------------------------------------------

    def set_audit_params(self, rate, seed):
        with self._lock:
            self._audit_rate = float(rate)
            self._audit_seed = int(seed)

    def observe_audit(self, trace_id, recall, exact):
        """One shadow-audited query: shortlist recall@k of the served
        answer against the exhaustive corpus scan."""
        r = _finite(recall)
        with self._lock:
            self._audited += 1
            if exact:
                self._audit_exact += 1
            if r is not None:
                self._audit_recall_sum += r
                self._audit_recall_min = (
                    r if self._audit_recall_min is None
                    else min(self._audit_recall_min, r))
            if len(self._audit_trace_ids) < AUDIT_TRACE_ID_CAP:
                self._audit_trace_ids.append(trace_id)
            else:
                self._audit_truncated += 1

    # --- artifact + exposition -----------------------------------------

    def payload(self):
        """The ``quality.json`` payload. The keyset is PINNED by
        ``tests/obs/test_quality.py`` — additions bump
        :data:`QUALITY_SCHEMA_VERSION`."""
        with self._lock:
            per_iter = {
                str(i): {'count': slot[0],
                         'mean': slot[1] / max(slot[0], 1),
                         'last': slot[2]}
                for i, slot in sorted(self._consensus.items())}
            first_mean = (per_iter[str(min(self._consensus))]['mean']
                          if self._consensus else None)
            final_mean = (per_iter[str(max(self._consensus))]['mean']
                          if self._consensus else None)
            converged_at = None
            if first_mean is not None and first_mean > 0:
                for i in sorted(self._consensus):
                    if per_iter[str(i)]['mean'] <= CONVERGE_TOL * first_mean:
                        converged_at = i
                        break
            signals = {}
            for name in QUALITY_SIGNALS:
                h = self._hists[name]
                signals[name] = (None if not h.count else {
                    'count': h.count,
                    'mean': h.sum / h.count,
                    'p50': h.quantile(0.5),
                    'p95': h.quantile(0.95)})
            return {
                'schema': QUALITY_SCHEMA_VERSION,
                'headline': {'scenario': self._headline['scenario'],
                             'step': self._headline['step'],
                             'metrics': dict(self._headline['metrics'])},
                'scenarios': {
                    name: {'evals': sc['evals'], 'count': sc['count'],
                           'step': sc['step'],
                           'metrics': {m: dict(v) for m, v
                                       in sc['metrics'].items()}}
                    for name, sc in self._scenarios.items()},
                'consensus': {
                    'events': self._consensus_events,
                    'iterations': len(self._consensus),
                    'per_iteration': per_iter,
                    'tol': CONVERGE_TOL,
                    'converged_at': converged_at,
                    'first_mean': first_mean,
                    'final_mean': final_mean,
                },
                'serve': {
                    'queries': self._queries,
                    'low_confidence': self._low_confidence,
                    'saturated_queries': self._saturated_queries,
                    'signals': signals,
                    'audit': {
                        'sample_rate': self._audit_rate,
                        'seed': self._audit_seed,
                        'audited': self._audited,
                        'exact': self._audit_exact,
                        'recall_mean': (
                            self._audit_recall_sum / self._audited
                            if self._audited else None),
                        'recall_min': self._audit_recall_min,
                        'trace_ids': list(self._audit_trace_ids),
                        'truncated': self._audit_truncated,
                    },
                },
            }

    def metric_families(self):
        """Metric families for ``/metrics``: the per-signal
        ``dgmc_query_quality`` histograms plus the breach and audit
        counters. Plugged into ``RunObserver.add_metrics_provider``."""
        with self._lock:
            snaps = {name: self._hists[name].snapshot()
                     for name in QUALITY_SIGNALS
                     if self._hists[name].count}
            low = self._low_confidence
            audited = self._audited
            exact = self._audit_exact
            recall_min = self._audit_recall_min
        samples = []
        for name in QUALITY_SIGNALS:
            snap = snaps.get(name)
            if snap is None:
                continue
            for bound, cum in snap['buckets']:
                le = '+Inf' if math.isinf(bound) else repr(float(bound))
                samples.append(
                    ('_bucket', {'signal': name, 'le': le}, cum))
            samples.append(('_sum', {'signal': name}, snap['sum']))
            samples.append(('_count', {'signal': name}, snap['count']))
        fams = [
            ('dgmc_query_quality', 'histogram',
             'Per-query answer-confidence proxies by signal (entropy, '
             'margin, correction, saturation).', samples),
            ('dgmc_quality_low_confidence_total', 'counter',
             'Served answers under the --min-margin confidence floor.',
             [('', {}, low)]),
            ('dgmc_quality_audited_total', 'counter',
             'Live queries re-scored by the shadow audit.',
             [('', {}, audited)]),
            ('dgmc_quality_audit_exact_total', 'counter',
             'Shadow-audited queries whose served shortlist matched the '
             'exhaustive scan exactly (recall 1.0).',
             [('', {}, exact)]),
        ]
        if recall_min is not None:
            fams.append(
                ('dgmc_quality_audit_recall_min', 'gauge',
                 'Worst shortlist recall@k the shadow audit has seen.',
                 [('', {}, recall_min)]))
        return fams
