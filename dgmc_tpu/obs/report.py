"""Run-report CLI: render ``--obs-dir`` telemetry as a table + JSON.

Usage::

    python -m dgmc_tpu.obs.report <obs_dir>            # human table
    python -m dgmc_tpu.obs.report <obs_dir> --json     # summary JSON only
    python -m dgmc_tpu.obs.report run1/ run2/          # several runs
    python -m dgmc_tpu.obs.report metrics.jsonl        # bare metric files

The table shows throughput, step-time percentiles, recompile counts and
time, HBM (or host-RSS) peaks, and the kernel-dispatch outcome table. The
``--json`` form emits one machine-readable summary object per input (a
JSON list when given several) — what CI asserts on.

This module deliberately has **no jax import**: it must render telemetry
from a dead run on any box.
"""

import argparse
import json
import os
import sys


from dgmc_tpu.obs.observe import read_json_artifact as _read_json


def _read_jsonl(path):
    recs = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    recs.append(json.loads(line))
                except ValueError:
                    recs.append({'_unparsed': line[:200]})
    except OSError:
        pass
    return recs


#: Artifacts written AT a root dir by their tools (specimen-merged
#: efficiency.json, aggregate.json, recovery.json, the attribution
#: CLI's attribution.json) that must outrank the subdir's copies when a
#: root loads as one of its subruns.
_ROOT_ARTIFACTS = ('recovery', 'aggregate', 'efficiency', 'attribution')


def _load_as_subrun(run, root_path, subdir):
    """Load ``subdir`` as the run while keeping ``root_path`` as its
    identity and the root-level :data:`_ROOT_ARTIFACTS` on top."""
    root = {k: run[k] for k in _ROOT_ARTIFACTS}
    sub = load_run(os.path.join(root_path, subdir))
    sub['path'] = root_path
    for k in _ROOT_ARTIFACTS:
        sub[k] = root[k] or sub.get(k)
    return sub


def load_run(path):
    """Load one obs dir (or one bare JSONL file) into a run dict.

    A multi-host root (no artifacts of its own but ``host_<k>/``
    subdirectories — see :mod:`dgmc_tpu.obs.aggregate`) loads as its
    ``host_0`` run, tagged with ``multi_host`` and the root's
    ``aggregate.json`` so summaries still carry the cross-host skew.

    A supervised root (``recovery.json`` + ``attempt_<k>/`` subdirs —
    see :mod:`dgmc_tpu.resilience.supervisor`) loads as its LAST
    attempt's run, tagged with ``recovery``/``attempts``: the final
    attempt is the run's outcome, and earlier attempts' telemetry
    (including their hang reports) is recovery *history* the timeline
    renders, not the final state — a supervised run whose last attempt
    completed clean must not diff as hung.
    """
    if os.path.isdir(path):
        run = {
            'path': path,
            'metrics': _read_jsonl(os.path.join(path, 'metrics.jsonl')),
            'timings': _read_json(os.path.join(path, 'timings.json')),
            'memory': _read_json(os.path.join(path, 'memory.json')),
            'dispatch': _read_json(os.path.join(path, 'dispatch.json')),
            'efficiency': _read_json(os.path.join(path, 'efficiency.json')),
            'aggregate': _read_json(os.path.join(path, 'aggregate.json')),
            'hang': _read_json(os.path.join(path, 'hang_report.json')),
            'recovery': _read_json(os.path.join(path, 'recovery.json')),
            'flight': _read_json(os.path.join(path, 'flight.json')),
            'attribution': _read_json(
                os.path.join(path, 'attribution.json')),
            'qtrace': _read_json(
                os.path.join(path, 'qtrace_summary.json')),
            'quality': _read_json(os.path.join(path, 'quality.json')),
            'goodput': _read_json(os.path.join(path, 'goodput.json')),
            'capacity': _read_json(os.path.join(path, 'capacity.json')),
            'slo': _read_json(os.path.join(path, 'slo.json')),
            'anomalies': _read_json(os.path.join(path,
                                                 'anomalies.json')),
        }
        if run['timings'] is None and not run['metrics']:
            from dgmc_tpu.resilience.supervisor import (ATTEMPT_PREFIX,
                                                        is_attempt_dirname)
            attempts = sorted(
                (d for d in os.listdir(path)
                 if is_attempt_dirname(d)
                 and os.path.isdir(os.path.join(path, d))),
                key=lambda d: int(d[len(ATTEMPT_PREFIX):]))
            if attempts:
                run = _load_as_subrun(run, path, attempts[-1])
                run['attempts'] = len(attempts)
                return run
            hosts = sorted(
                d for d in os.listdir(path)
                if d.startswith('host_')
                and os.path.isdir(os.path.join(path, d)))
            if hosts:
                run = _load_as_subrun(run, path, hosts[0])
                run['multi_host'] = len(hosts)
                # A hang ANYWHERE is the run's hang: the straggling
                # non-coordinator host is precisely the evidence the
                # per-host layout exists for, and the diff gate's
                # "hung candidate always fails" must see it even when
                # host_0 finished clean.
                hung = []
                for h in hosts:
                    rep = _read_json(os.path.join(path, h,
                                                  'hang_report.json'))
                    if rep is not None:
                        hung.append(h)
                        if run['hang'] is None:
                            run['hang'] = dict(rep, host=h)
                if hung:
                    run['hung_hosts'] = hung
        return run
    return {'path': path, 'metrics': _read_jsonl(path), 'timings': None,
            'memory': None, 'dispatch': None, 'efficiency': None,
            'aggregate': None, 'hang': None, 'recovery': None,
            'flight': None, 'attribution': None, 'qtrace': None,
            'quality': None, 'goodput': None, 'capacity': None,
            'slo': None, 'anomalies': None}


def peak_memory(memory):
    """(bytes, source) — the maximum device allocator peak across all
    snapshots, else the host RSS high-water mark."""
    if not memory:
        return None, None
    dev_peak = host_peak = 0
    for snap in memory.get('snapshots', []):
        for d in snap.get('devices', []):
            dev_peak = max(dev_peak, d.get('peak_bytes_in_use', 0),
                           d.get('bytes_in_use', 0))
        host_peak = max(host_peak,
                        snap.get('host', {}).get('peak_rss_bytes', 0),
                        snap.get('host', {}).get('rss_bytes', 0))
    if dev_peak:
        return dev_peak, 'device'
    if host_peak:
        return host_peak, 'host'
    return None, None


def probe_aggregates_from_metrics(metrics):
    """Rebuild per-probe aggregates from the raw ``metrics.jsonl`` series
    — the fallback when ``timings.json`` predates the probe layer or only
    a bare metrics file was given. Uses the same accumulator the live
    sink does (``obs.probes.Aggregator``; jax-free)."""
    from dgmc_tpu.obs.probes import Aggregator
    agg = Aggregator()
    for rec in metrics or []:
        name = rec.get('probe')
        # 'nonfinite' is skipped by construction: only FIRING checks
        # reach metrics.jsonl, so a rebuild would see a different
        # population than the live sink's full-check statistics.
        if not name or name == 'nonfinite':
            continue
        v = rec.get('value')
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            agg.add(name, v)
        elif v is None and 'value' in rec:
            # MetricLogger writes non-finite values as null (NaN is not
            # valid JSON): feed NaN back so the rebuilt count and the
            # 'nonfinite_values' marker match the live sink's.
            agg.add(name, float('nan'))
    return agg.summary()


def summarize(run):
    """One machine-readable summary object for a loaded run."""
    out = {'path': run['path'],
           'metrics_records': len(run['metrics'] or [])}
    if run['metrics']:
        last = run['metrics'][-1]
        out['last_metrics'] = {k: v for k, v in last.items()
                               if k != '_unparsed'}
    t = run['timings'] or {}
    steps = t.get('steps') or {}
    if steps:
        out['steps'] = steps.get('steps')
        out['step_mean_s'] = round(steps.get('mean_s', 0.0), 6)
        out['step_p50_s'] = round(steps.get('p50_s', 0.0), 6)
        out['step_p95_s'] = round(steps.get('p95_s', 0.0), 6)
        out['step_max_s'] = round(steps.get('max_s', 0.0), 6)
        if steps.get('mean_s'):
            out['steps_per_sec'] = round(1.0 / steps['mean_s'], 3)
    if t.get('wall_s') is not None:
        out['wall_s'] = t['wall_s']
    comp = t.get('compile') or {}
    out['compile_events'] = comp.get('events', 0)
    out['compile_s'] = comp.get('compile_s', 0.0)
    if comp.get('by_label'):
        out['compile_by_label'] = comp['by_label']
    buckets = t.get('padding_buckets') or []
    if buckets:
        out['padding_buckets'] = len(buckets)
        out['padding_bucket_rows'] = buckets

    if t.get('device_steps'):
        out['device_steps'] = t['device_steps']

    probes = t.get('probes') or probe_aggregates_from_metrics(run['metrics'])
    if probes:
        out['probes'] = probes
    if t.get('first_nonfinite'):
        out['first_nonfinite'] = t['first_nonfinite']

    eff = run.get('efficiency') or {}
    if eff:
        if eff.get('mfu') is not None:
            out['mfu'] = eff['mfu']
        out['efficiency'] = {
            'peak_flops': eff.get('peak_flops'),
            'peak_flops_ref': eff.get('peak_flops_ref'),
            'peak_flops_source': eff.get('peak_flops_source'),
            'programs': eff.get('programs', {}),
        }
        ts = eff.get('programs', {}).get('train_step', {})
        if ts.get('flops'):
            out['flops_per_step'] = ts['flops']
        # Headline per-program fields (arithmetic intensity, the
        # modeled overlap fraction, the static peak-live bound): one
        # shared picking convention (cost.headline_of — train_step
        # first) so obs.diff and the attribution reconciliation can
        # never gate on different programs than this summary reports.
        from dgmc_tpu.obs.cost import headline_of
        for key in ('arith_intensity', 'overlap_fraction',
                    'static_peak_bytes'):
            val = headline_of(eff, key)
            if val is not None:
                out[key] = val
        # Measured headline (obs.attribution's efficiency merge): the
        # profiler-trace truth next to the static models, so obs.diff
        # can gate measured overlap and idle growth from artifacts.
        # TOP-LEVEL keys only, deliberately: the merge pops a headline
        # whose measurement vanished, and falling back into the
        # `measured` block here would resurrect the stale value and
        # silence the diff's lost-account rule.
        for key in ('measured_overlap_fraction', 'measured_mfu',
                    'device_idle_fraction', 'idle_fraction',
                    'idle_source'):
            if eff.get(key) is not None:
                out[key] = eff[key]
        meas = eff.get('measured') or {}
        if meas:
            out['measured_device_available'] = meas.get(
                'device_available')

    qtrace = run.get('qtrace')
    if qtrace:
        # The serve plane's per-query account: per-stage quantiles for
        # the diff's --max-stage-p95-regression gate, plus the gap
        # attribution headline the timeline's SERVE rows render.
        out['qtrace_queries'] = qtrace.get('queries')
        out['qtrace_errors'] = qtrace.get('errors')
        e2e = qtrace.get('end_to_end') or {}
        for key in ('p50_ms', 'p95_ms', 'p99_ms'):
            if e2e.get(key) is not None:
                out[f'qtrace_{key}'] = e2e[key]
        stages = qtrace.get('stages') or {}
        if stages:
            out['qtrace_stages'] = {
                name: {k: q.get(k) for k in
                       ('count', 'p50_ms', 'p95_ms', 'p99_ms')}
                for name, q in stages.items()}
        gap = qtrace.get('gap_attribution') or {}
        if gap.get('dominant_stage'):
            out['qtrace_dominant_stage'] = gap['dominant_stage']
        if gap.get('p95_minus_p50_ms') is not None:
            out['qtrace_gap_ms'] = gap['p95_minus_p50_ms']

    quality = run.get('quality')
    if quality:
        # The quality plane (quality.json): the run's headline eval
        # metrics become FLAT summary keys — hits1/hits10/mrr/loss are
        # what obs.diff's --max-hits1-regression / --min-hits1 gates
        # read, and a run that stopped emitting them must LOSE the keys
        # (lost-account-fails), never inherit stale ones.
        headline = (quality.get('headline') or {}).get('metrics') or {}
        for key, val in headline.items():
            if val is not None:
                out[key] = val
        scenarios = quality.get('scenarios') or {}
        if scenarios:
            out['quality_scenarios'] = {
                name: {m: v.get('last')
                       for m, v in (sc.get('metrics') or {}).items()}
                for name, sc in scenarios.items()}
        consensus = quality.get('consensus') or {}
        if consensus.get('iterations'):
            out['consensus_iterations'] = consensus['iterations']
            out['consensus_converged_at'] = consensus.get('converged_at')
        serve_q = quality.get('serve') or {}
        if serve_q.get('queries'):
            out['quality_queries'] = serve_q['queries']
            out['quality_low_confidence'] = serve_q.get('low_confidence')
            out['quality_saturated_queries'] = serve_q.get(
                'saturated_queries')
        audit = serve_q.get('audit') or {}
        if audit.get('audited'):
            out['audit_queries'] = audit['audited']
            out['audit_recall_mean'] = audit.get('recall_mean')
            out['audit_recall_min'] = audit.get('recall_min')
            out['audit_exact'] = audit.get('exact')

    goodput = run.get('goodput')
    if goodput:
        # The capacity/goodput plane (goodput.json): flat keys so
        # obs.diff's --min-goodput / --max-pad-regression gates read the
        # same artifact the observer recorded — a run that stopped
        # writing the account loses the keys (lost-account-fails).
        if goodput.get('goodput_ratio') is not None:
            out['goodput_ratio'] = goodput['goodput_ratio']
        if goodput.get('pad_fraction_max') is not None:
            out['pad_fraction'] = goodput['pad_fraction_max']
        if goodput.get('buckets'):
            out['goodput_buckets'] = len(goodput['buckets'])
        if goodput.get('composed_with_stage_flops') is not None:
            out['goodput_composed'] = goodput['composed_with_stage_flops']

    capacity = run.get('capacity')
    if capacity:
        # The serve-side capacity model (capacity.json): Little's-law
        # utilization and the measured saturation ceiling, plus the
        # lock split the qtrace admission span reconciles against.
        for key in ('utilization', 'saturation_qps', 'arrival_qps',
                    'inflight', 'mean_service_ms', 'projected_wait_ms'):
            if capacity.get(key) is not None:
                out[f'capacity_{key}' if key != 'utilization'
                    else 'utilization'] = capacity[key]
        for side in ('lock_wait_ms', 'lock_hold_ms'):
            hist = capacity.get(side) or {}
            if hist.get('p95_ms') is not None:
                out[f'capacity_{side[:-3]}_p95_ms'] = hist['p95_ms']

    slo = run.get('slo')
    if slo:
        # The SLO plane (slo.json): the judged account — worst budget
        # consumption across objectives, any alerting burn windows and
        # the breach counts. Headline-sized; the full per-window burn
        # detail stays in the artifact.
        objectives = slo.get('objectives') or {}
        consumed = {name: o.get('budget_consumed')
                    for name, o in objectives.items()
                    if o.get('budget_consumed') is not None}
        out['slo'] = {
            'name': slo.get('slo'),
            'budget_consumed': consumed,
            'worst_budget_consumed': (round(max(consumed.values()), 6)
                                      if consumed else None),
            'alerting': sorted(
                f'{name}:{wname}'
                for name, o in objectives.items()
                for wname, b in (o.get('burn') or {}).items()
                if b.get('alerting')),
            'breaches': (slo.get('breaches') or {}).get('counts') or {},
        }

    anomalies = run.get('anomalies')
    if anomalies:
        # The anomaly watch (anomalies.json): totals plus only the
        # signals that actually fired — a quiet run summarizes quiet.
        sig = anomalies.get('signals') or {}
        out['anomaly'] = {
            'events': len(anomalies.get('events') or []),
            'truncated': anomalies.get('truncated', 0),
            'spikes': sum(s.get('spikes', 0) for s in sig.values()),
            'shifts': sum(s.get('shifts', 0) for s in sig.values()),
            'fired': {name: {'spikes': s.get('spikes', 0),
                             'shifts': s.get('shifts', 0)}
                      for name, s in sorted(sig.items())
                      if s.get('spikes') or s.get('shifts')},
        }

    flight = run.get('flight')
    if flight:
        out['flight'] = {
            'reason': flight.get('reason'),
            'events_recorded': flight.get('events_recorded'),
            'events_truncated': flight.get('events_truncated'),
        }
        events = flight.get('events') or []
        if events:
            out['flight']['last_event'] = events[-1]
            spans = [e for e in events
                     if str(e.get('kind', '')).startswith('span')]
            if spans:
                out['flight']['last_span'] = spans[-1]

    hang = run.get('hang')
    if hang:
        out['hang_report'] = {
            'reason': hang.get('reason'),
            'stalled_for_s': hang.get('stalled_for_s'),
            'in_flight': hang.get('in_flight'),
            'last_completed': hang.get('last_completed'),
        }
        if hang.get('host'):
            out['hang_report']['host'] = hang['host']
    if run.get('hung_hosts'):
        out['hung_hosts'] = run['hung_hosts']

    rec = run.get('recovery')
    if rec:
        out['recovery'] = {
            'outcome': rec.get('outcome'),
            'restarts': rec.get('restarts', 0),
            'degradations': [d.get('rung')
                             for d in rec.get('degradations', [])],
            'elastic': rec.get('elastic', []),
            'attempts': [
                {'attempt': at.get('attempt'),
                 'reason': at.get('reason'),
                 'rc': at.get('rc'),
                 'steps_completed': at.get('steps_completed'),
                 'duration_s': (
                     round(at['end_time'] - at['start_time'], 1)
                     if at.get('end_time') and at.get('start_time')
                     else None)}
                for at in rec.get('attempts', [])],
        }

    agg = run.get('aggregate')
    if agg and agg.get('skew'):
        out['skew'] = agg['skew']
        out['hosts'] = agg.get('hosts')
    if run.get('multi_host'):
        out['hosts'] = run['multi_host']

    peak, source = peak_memory(run['memory'])
    if peak is not None:
        out['peak_memory_bytes'] = peak
        out['peak_memory_gib'] = round(peak / 2 ** 30, 3)
        out['peak_memory_source'] = source

    rows = (run['dispatch'] or {}).get('counts', [])
    if rows:
        out['dispatch'] = rows
        out['dispatch_pallas'] = sum(r['count'] for r in rows
                                     if r.get('outcome') == 'pallas')
        out['dispatch_fallback'] = sum(r['count'] for r in rows
                                       if r.get('outcome') == 'fallback')
    return out


def _fmt_bytes(n):
    if n is None:
        return '-'
    for unit in ('B', 'KiB', 'MiB', 'GiB', 'TiB'):
        if n < 1024 or unit == 'TiB':
            return f'{n:.2f} {unit}' if unit != 'B' else f'{n} B'
        n /= 1024


def _fmt_s(v):
    from dgmc_tpu.obs.observe import fmt_seconds
    return fmt_seconds(v)


def _fmt_count(n):
    from dgmc_tpu.obs.observe import fmt_si
    return fmt_si(n)


def render(run):
    """Human-readable report for one loaded run."""
    s = summarize(run)
    lines = [f'== run report: {run["path"]} ==']
    if s.get('hang_report'):
        h = s['hang_report']
        inf = h.get('in_flight') or {}
        lines.append(f'  ** RUN HUNG: {h.get("reason")} after '
                     f'{h.get("stalled_for_s")}s in '
                     f'{inf.get("phase")}:{inf.get("name")} '
                     f'(last completed: {h.get("last_completed")}) — '
                     f'see hang_report.json **')

    if s.get('recovery'):
        rec = s['recovery']
        lines.append('-- recovery timeline (supervised run) --')
        lines.append(f'  outcome          {rec.get("outcome")}   '
                     f'restarts: {rec.get("restarts", 0)}')
        if rec.get('degradations'):
            lines.append('  degradations     '
                         + ' -> '.join(rec['degradations']))
        for ev in rec.get('elastic') or []:
            lines.append(f'  elastic shrink   {ev.get("detail")} '
                         f'after {ev.get("reason")} '
                         f'(attempt {ev.get("attempt")})')
        for at in rec.get('attempts', []):
            dur = at.get('duration_s')
            steps_done = at.get('steps_completed')
            lines.append(
                f'  attempt {at.get("attempt")}: '
                f'{at.get("reason", "?")}'
                + (f' after {steps_done} step(s)'
                   if steps_done is not None else '')
                + (f' ({dur}s)' if dur is not None else ''))

    flight = run.get('flight')
    if flight:
        lines.append('-- flight recorder (trailing context) --')
        lines.append(
            f'  dumped on        {flight.get("reason")}   '
            f'({flight.get("events_recorded", 0)} events kept, '
            f'{flight.get("events_truncated", 0)} evicted by the ring)')
        events = flight.get('events') or []
        t_end = events[-1].get('time', 0.0) if events else 0.0
        for ev in events[-12:]:
            dt = (ev.get('time') or t_end) - t_end
            detail = ' '.join(
                f'{k}={v}' for k, v in ev.items()
                if k not in ('time', 'kind') and v is not None)
            lines.append(f'  {dt:+9.3f}s  {ev.get("kind", "?"):<10} '
                         f'{detail}'.rstrip())

    steps = s.get('steps')
    lines.append('-- step timing --')
    if steps:
        lines.append(f'  steps            {steps}')
        lines.append(f'  throughput       '
                     f'{s.get("steps_per_sec", "-")} steps/s')
        lines.append(f'  mean / p50 / p95 / max   '
                     f'{_fmt_s(s["step_mean_s"])} / '
                     f'{_fmt_s(s["step_p50_s"])} / '
                     f'{_fmt_s(s["step_p95_s"])} / '
                     f'{_fmt_s(s["step_max_s"])}')
    else:
        lines.append('  (no step timings recorded)')
    if 'wall_s' in s:
        lines.append(f'  run wall-clock   {_fmt_s(s["wall_s"])}')

    lines.append('-- compiles --')
    lines.append(f'  compile events   {s["compile_events"]}'
                 f'   (total {_fmt_s(s["compile_s"])})')
    for label, d in (s.get('compile_by_label') or {}).items():
        lines.append(f'    {label:<16} {d["events"]} events, '
                     f'{_fmt_s(d["compile_s"])}')
    if s.get('padding_buckets'):
        lines.append(f'  padding buckets  {s["padding_buckets"]} distinct')
        for b in s['padding_bucket_rows'][:5]:
            lines.append(f'    batch={b.get("batch")} '
                         f'nodes={b.get("nodes")} edges={b.get("edges")} '
                         f'x{b.get("count")}')

    lines.append('-- memory --')
    if 'peak_memory_bytes' in s:
        lines.append(f'  peak ({s["peak_memory_source"]})    '
                     f'{_fmt_bytes(s["peak_memory_bytes"])}')
    else:
        lines.append('  (no memory snapshots recorded)')

    if s.get('efficiency'):
        eff = s['efficiency']
        lines.append('-- cost / efficiency --')
        lines.append(f'  peak flops       '
                     f'{_fmt_count(eff.get("peak_flops"))}FLOP/s '
                     f'[{eff.get("peak_flops_source")}: '
                     f'{eff.get("peak_flops_ref")}]')
        if s.get('mfu') is not None:
            lines.append(f'  MFU              {s["mfu"]:.4%}')
        if s.get('overlap_fraction') is not None:
            lines.append(f'  overlap          '
                         f'{s["overlap_fraction"]:.4f} (modeled '
                         f'collective overlap)')
        if s.get('static_peak_bytes') is not None:
            lines.append(f'  static peak      '
                         f'{_fmt_bytes(s["static_peak_bytes"])} '
                         f'(liveness bound)')
        for name, p in eff.get('programs', {}).items():
            if 'error' in p:
                lines.append(f'  {name}: cost unavailable ({p["error"]})')
                continue
            mfu = f'  MFU {p["mfu"]:.4%}' if p.get('mfu') is not None \
                else ''
            lines.append(f'  {name}: {_fmt_count(p.get("flops"))}FLOP, '
                         f'{_fmt_bytes(p.get("bytes"))} accessed'
                         f'{mfu}')
            for stage, row in (p.get('stages') or {}).items():
                lines.append(
                    f'    {stage:<16} flops '
                    f'{_fmt_count(row.get("flops")):>9}  bytes '
                    f'{_fmt_bytes(row.get("bytes_out")):>11}  '
                    f'ops {row.get("ops", 0)}')
            coll = (p.get('collectives') or {}).get('ops') or {}
            for cname, row in coll.items():
                lines.append(f'    collective {cname:<14} x{row["count"]} '
                             f'{_fmt_bytes(row["bytes"])}')

    attribution = run.get('attribution')
    if attribution:
        # The measured account (profiler trace): the attribution CLI's
        # renderer, indented into the run report so the stage table,
        # occupancy and static-vs-measured reconciliation appear next
        # to the static cost/efficiency block they reconcile against.
        from dgmc_tpu.obs.attribution import render_attribution
        lines.append('-- measured attribution (profiler trace) --')
        lines.extend(render_attribution(attribution).splitlines()[1:])

    if s.get('device_steps'):
        lines.append('-- per-device step completion --')
        lines.append(f'  {"device":>6} {"count":>6} {"mean":>12} '
                     f'{"p50":>12} {"max":>12}')
        for dev, a in s['device_steps'].items():
            lines.append(f'  {dev:>6} {a["count"]:>6} '
                         f'{_fmt_s(a["mean_s"]):>12} '
                         f'{_fmt_s(a["p50_s"]):>12} '
                         f'{_fmt_s(a["max_s"]):>12}')

    if s.get('skew'):
        sk = s['skew']
        lines.append('-- multi-device skew --')
        if s.get('hosts'):
            lines.append(f'  hosts            {s["hosts"]}')
        for key, label in (('step_time_ratio', 'step-time max/median'),
                           ('memory_ratio', 'memory max/median'),
                           ('wall_ratio', 'wall-clock max/median')):
            if sk.get(key) is not None:
                lines.append(f'  {label:<22} {sk[key]:.3f}x')

    lines.append('-- kernel dispatch --')
    rows = s.get('dispatch', [])
    if rows:
        lines.append(f'  {"kernel":<20} {"outcome":<10} {"reason":<18} '
                     f'{"count":>6}')
        for r in rows:
            lines.append(f'  {r.get("kernel", "?"):<20} '
                         f'{r.get("outcome", "?"):<10} '
                         f'{r.get("reason", "?"):<18} '
                         f'{r.get("count", 0):>6}')
        lines.append(f'  pallas taken: {s.get("dispatch_pallas", 0)}   '
                     f'fallback: {s.get("dispatch_fallback", 0)}')
    else:
        lines.append('  (no dispatch decisions recorded)')

    if s.get('probes'):
        lines.append('-- probes --')
        lines.append(f'  {"probe":<18} {"count":>6} {"mean":>12} '
                     f'{"last":>12} {"min":>12} {"max":>12}')

        def g(v):
            return '-' if v is None else f'{v:.6g}'

        for name, a in s['probes'].items():
            nf = (f'  ({a["nonfinite_values"]} non-finite)'
                  if a.get('nonfinite_values') else '')
            lines.append(f'  {name:<18} {a["count"]:>6} {g(a["mean"]):>12} '
                         f'{g(a["last"]):>12} {g(a["min"]):>12} '
                         f'{g(a["max"]):>12}{nf}')
        if s.get('first_nonfinite'):
            fn = s['first_nonfinite']
            lines.append(f'  FIRST NON-FINITE at step {fn.get("step")} '
                         f'stage {fn.get("stage")!r}')

    quality = run.get('quality')
    if quality and (s.get('quality_scenarios') or s.get('quality_queries')
                    or s.get('consensus_iterations')):
        lines.append('-- quality plane --')
        for name, mets in (s.get('quality_scenarios') or {}).items():
            rendered = '  '.join(
                f'{m}={v:.4f}' for m, v in sorted(mets.items())
                if isinstance(v, (int, float)))
            lines.append(f'  {name:<16} {rendered}')
        if s.get('consensus_iterations'):
            conv = s.get('consensus_converged_at')
            lines.append(
                f'  consensus        {s["consensus_iterations"]} '
                f'iterations, converged at '
                f'{conv if conv is not None else "never (tol)"}')
        if s.get('quality_queries'):
            lines.append(
                f'  serve confidence {s["quality_queries"]} queries, '
                f'{s.get("quality_low_confidence", 0)} low-confidence, '
                f'{s.get("quality_saturated_queries", 0)} shortlist-'
                f'saturated')
        if s.get('audit_queries'):
            rmin = s.get('audit_recall_min')
            lines.append(
                f'  shadow audit     {s["audit_queries"]} audited, '
                f'{s.get("audit_exact", 0)} exact, recall min '
                f'{rmin if rmin is not None else "-"}')

    goodput = run.get('goodput')
    capacity = run.get('capacity')
    if goodput or capacity:
        lines.append('-- capacity / goodput plane --')
        if s.get('goodput_ratio') is not None:
            composed = ('FLOP-weighted' if s.get('goodput_composed')
                        else 'mask-only')
            lines.append(f'  goodput ratio    {s["goodput_ratio"]:.4f} '
                         f'(useful/executed FLOPs, {composed})')
        if s.get('pad_fraction') is not None:
            lines.append(f'  pad fraction     {s["pad_fraction"]:.4f} '
                         f'(worst bucket)')
        for b in (goodput or {}).get('buckets', [])[:5]:
            gr = b.get('goodput_ratio')
            lines.append(
                f'    batch={b.get("batch")} nodes={b.get("nodes")} '
                f'edges={b.get("edges")} x{b.get("count")}  '
                f'pad={b.get("pad_fraction", 0.0):.3f}'
                + (f'  goodput={gr:.3f}' if gr is not None else ''))
        if capacity:
            if s.get('utilization') is not None:
                lines.append(f'  utilization ρ    {s["utilization"]:.4f} '
                             f'(Little\'s law: arrival x service)')
            if s.get('capacity_saturation_qps') is not None:
                lines.append(f'  saturation QPS   '
                             f'{s["capacity_saturation_qps"]:.2f} '
                             f'(1 / mean service time)')
            if s.get('capacity_arrival_qps') is not None:
                lines.append(f'  arrival QPS      '
                             f'{s["capacity_arrival_qps"]:.2f}')
            wait = s.get('capacity_lock_wait_p95_ms')
            hold = s.get('capacity_lock_hold_p95_ms')
            if wait is not None or hold is not None:
                lines.append(f'  engine lock p95  '
                             f'wait {wait if wait is not None else "-"}ms / '
                             f'hold {hold if hold is not None else "-"}ms')
            rec_adm = capacity.get('admission_reconciliation')
            if rec_adm:
                lines.append(
                    f'  admission recon  qtrace '
                    f'{rec_adm.get("qtrace_count")}x '
                    f'p95={rec_adm.get("qtrace_p95_ms")}ms vs engine '
                    f'{rec_adm.get("engine_count")}x '
                    f'p95={rec_adm.get("engine_p95_ms")}ms')

    if s.get('slo') or s.get('anomaly'):
        lines.append('-- slo / anomaly plane --')
        slo_s = s.get('slo')
        if slo_s:
            worst = slo_s.get('worst_budget_consumed')
            lines.append(
                f'  slo {slo_s.get("name", "?"):<12} worst budget '
                f'consumed '
                f'{f"{worst:.4f}" if worst is not None else "-"}'
                + (f'  ALERTING: {", ".join(slo_s["alerting"])}'
                   if slo_s.get('alerting') else ''))
            for name, c in sorted(
                    (slo_s.get('budget_consumed') or {}).items()):
                lines.append(f'    {name:<16} budget {c:.4f}')
            if slo_s.get('breaches'):
                rendered = '  '.join(
                    f'{k}={v}' for k, v in
                    sorted(slo_s['breaches'].items()))
                lines.append(f'  breaches         {rendered}')
        an = s.get('anomaly')
        if an:
            lines.append(
                f'  anomalies        {an["events"]} in ring '
                f'({an["truncated"]} truncated), '
                f'{an["spikes"]} spikes / {an["shifts"]} shifts'
                + ('' if not an.get('fired') else '  ['
                   + ', '.join(
                       f'{name}: {f["spikes"]}s/{f["shifts"]}c'
                       for name, f in sorted(an['fired'].items()))
                   + ']'))

    lines.append('-- metrics --')
    lines.append(f'  records          {s["metrics_records"]}')
    if s.get('last_metrics'):
        lines.append(f'  last             '
                     f'{json.dumps(s["last_metrics"], sort_keys=True)}')
    return '\n'.join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='python -m dgmc_tpu.obs.report',
        description='Render --obs-dir telemetry (or bare metric JSONL '
                    'files) as a report.')
    parser.add_argument('paths', nargs='+',
                        help='obs directories or metrics JSONL files')
    parser.add_argument('--json', action='store_true',
                        help='print only the machine-readable summary')
    args = parser.parse_args(argv)

    runs = []
    for p in args.paths:
        if not os.path.exists(p):
            print(f'report: no such path: {p}', file=sys.stderr)
            return 2
        runs.append(load_run(p))

    if args.json:
        summaries = [summarize(r) for r in runs]
        print(json.dumps(summaries[0] if len(summaries) == 1
                         else summaries, indent=1))
    else:
        for r in runs:
            print(render(r))
    return 0


if __name__ == '__main__':
    sys.exit(main())
