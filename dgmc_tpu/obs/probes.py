"""In-graph numerics probes: per-step model internals without host syncs.

DGMC's accuracy hinges on dynamics invisible from outside ``jit``: how
fast the softmax correspondence sharpens over the L consensus iterations,
how much probability mass the top-k candidate set keeps, and how large
the per-iteration corrections the consensus MLP applies are (Algorithm 1
of Fey et al., ICLR 2020). This module streams those diagnostics out of
compiled programs via ``jax.debug.callback`` — the host receives small
scalars as the step executes, with no extra device->host fences in the
training loop.

Design contract (the zero-overhead guarantee):

- The enable switch is a **Python bool read at trace time**. Probe call
  sites pass their metric as a 0-arg thunk, so with probes disabled
  (default) neither the metric computation nor the callback is ever
  traced — the lowered HLO is byte-identical to a build without probe
  call sites (pinned by ``tests/obs/test_probes.py``).
- Because the switch is trace-time, it must be flipped **before the
  first execution of a jitted step** (tracing happens at first call); a
  step traced while probes were off keeps running probe-free until it is
  retraced. :class:`~dgmc_tpu.obs.run.RunObserver` enables probes in its
  constructor, which every CLI creates before its first step.

Probes emitted by the model/train-step integration:

``corr_entropy``
    Mean per-row entropy of the soft correspondence (dense: over targets;
    sparse: over candidate slots), for ``S^0``/``S^L`` (``stage``) and
    per consensus iteration (``iteration``) — the sharpening curve.
``topk_mass``
    Mean probability mass of each row's ``k`` largest entries — how much
    mass a top-k sparsification keeps (dense), or how concentrated the
    kept candidate set already is (sparse).
``consensus_delta``
    Per-iteration correction norm ``‖S_{l+1} - S_l‖`` (masked Frobenius
    norm, mean over the batch) — Algorithm 1's fixed-point residual.
``grad_norm``
    Global gradient norm of the train step.
``nonfinite``
    1.0 when a pipeline stage produced a non-finite value, with the
    offending ``stage`` name — first-offender attribution is done by the
    sink (:class:`~dgmc_tpu.obs.run.RunObserver` records the first).

Host-side delivery: callbacks fan out to registered sinks (callables
receiving one record dict). Records carry ``probe``, ``value``, ``time``
and any static metadata the call site attached (``stage``,
``iteration``). With JAX's async dispatch the arrival time is when the
device computation actually runs, so step attribution by a host-side
counter is approximate within the dispatch pipeline depth — exact
enough for per-step series. Callbacks are UNordered (``ordered=True``
does not compose with every transform), so nothing may depend on
arrival order within a step; the first-nonfinite attribution sorts on
each check's static ``order`` (pipeline position) instead.
"""

import contextlib
import math
import threading
import time

__all__ = [
    'enabled', 'enable', 'disable', 'add_sink', 'remove_sink',
    'activated', 'ProbeLog', 'emit', 'check_finite',
    'entropy', 'topk_mass', 'delta_norm',
]

_lock = threading.Lock()
_enabled = False
_sinks = []


def enabled():
    """Trace-time probe switch (a plain Python bool)."""
    return _enabled


def enable(sink=None):
    """Turn probes on (idempotent); optionally register ``sink``.

    Must run before the first execution of any jitted step that should
    carry probes (the switch is read when the step is traced).
    """
    global _enabled
    with _lock:
        _enabled = True
        if sink is not None and sink not in _sinks:
            _sinks.append(sink)


def disable(sink=None):
    """Turn probes off for subsequently-traced programs; optionally
    unregister ``sink``. Already-traced programs keep their callbacks —
    with no sinks registered those dispatch to nothing."""
    global _enabled
    with _lock:
        _enabled = False
        if sink is not None and sink in _sinks:
            _sinks.remove(sink)


def add_sink(fn):
    with _lock:
        if fn not in _sinks:
            _sinks.append(fn)


def remove_sink(fn):
    with _lock:
        if fn in _sinks:
            _sinks.remove(fn)


class ProbeLog:
    """Minimal list sink: ``ProbeLog()`` collects records for tests."""

    def __init__(self):
        self.records = []

    def __call__(self, rec):
        self.records.append(rec)

    def by_name(self, name):
        return [r for r in self.records if r['probe'] == name]


class Aggregator:
    """Streaming per-probe aggregates (count/mean/last/min/max).

    The ONE accumulation shared by the live sink (RunObserver) and the
    offline rebuild (``obs.report`` over a raw ``metrics.jsonl``) so the
    statistics themselves cannot drift. (The ``nonfinite`` probe is the
    exception by construction: only FIRING checks reach metrics.jsonl,
    so a rebuild sees a different population than the live sink — the
    rebuild therefore skips it.) Pure Python — no jax — so the
    report/diff CLIs stay importable anywhere.

    Non-finite values are counted (``nonfinite_values``) but kept out of
    mean/min/max/last: one NaN must not poison the whole run's
    statistics, and NaN is not representable in strict JSON anyway."""

    def __init__(self):
        self._agg = {}

    def add(self, name, value):
        a = self._agg.setdefault(
            name, {'count': 0, 'finite': 0, 'sum': 0.0, 'min': None,
                   'max': None, 'last': None, 'nonfinite': 0})
        a['count'] += 1
        if math.isfinite(value):
            a['finite'] += 1
            a['sum'] += value
            a['min'] = value if a['min'] is None else min(a['min'], value)
            a['max'] = value if a['max'] is None else max(a['max'], value)
            a['last'] = value
        else:
            a['nonfinite'] += 1

    def __bool__(self):
        return bool(self._agg)

    def summary(self):
        out = {}
        for name, a in sorted(self._agg.items()):
            r = lambda v: None if v is None else round(v, 6)  # noqa: E731
            s = {'count': a['count'],
                 'mean': r(a['sum'] / a['finite']) if a['finite'] else None,
                 'last': r(a['last']),
                 'min': r(a['min']),
                 'max': r(a['max'])}
            if a['nonfinite']:
                s['nonfinite_values'] = a['nonfinite']
            out[name] = s
        return out


@contextlib.contextmanager
def activated(sink=None):
    """Scoped enable for tests: probes on (with ``sink``) inside the
    block, prior switch state restored after."""
    global _enabled
    prev = _enabled
    enable(sink)
    try:
        yield sink
    finally:
        with _lock:
            _enabled = prev
            if sink is not None and sink in _sinks:
                _sinks.remove(sink)


def _dispatch(rec):
    with _lock:
        sinks = list(_sinks)
    for s in sinks:
        try:
            s(rec)
        except Exception:
            # A broken sink must never take down the training step that
            # happens to be streaming diagnostics through it.
            pass


def emit(name, value, **meta):
    """Stream one scalar probe out of the running computation.

    Args:
        name: probe name (``corr_entropy``, ``grad_norm``, ...).
        value: a scalar array, or a **0-arg callable** returning one —
            pass a thunk so the metric computation itself is skipped
            (never traced) when probes are disabled.
        **meta: static Python metadata attached to the record
            (``stage=...``, ``iteration=...``).
    """
    if not _enabled:
        return
    import jax
    import jax.numpy as jnp
    v = jnp.asarray(value() if callable(value) else value, jnp.float32)

    def _cb(x, _name=name, _meta=meta):
        _dispatch({'probe': _name, 'value': float(x), 'time': time.time(),
                   **_meta})

    jax.debug.callback(_cb, v)


def check_finite(stage, *arrays, order=0, **meta):
    """Emit a ``nonfinite`` probe (0.0/1.0) for ``stage`` covering
    ``arrays``. ``order`` is the stage's static position in the pipeline
    (psi1 < initial_corr < consensus_iter[i] < loss < grad): the
    callbacks are unordered, so first-offender attribution must NOT
    trust host arrival order — the sink picks the firing check with the
    lowest ``(step, order)`` instead."""
    if not _enabled:
        return
    import jax.numpy as jnp
    bad = jnp.zeros((), bool)
    for a in arrays:
        bad = bad | ~jnp.all(jnp.isfinite(jnp.asarray(a)))
    emit('nonfinite', bad.astype(jnp.float32), stage=stage, order=order,
         **meta)


# ---------------------------------------------------------------------------
# In-graph metric helpers (call only inside an emit thunk / enabled branch)
# ---------------------------------------------------------------------------

_EPS = 1e-12


def _row_mean(per_row, row_mask):
    import jax.numpy as jnp
    if row_mask is None:
        return jnp.mean(per_row)
    m = row_mask.astype(per_row.dtype)
    return jnp.sum(per_row * m) / jnp.maximum(jnp.sum(m), 1.0)


def entropy(S, row_mask=None):
    """Mean per-row entropy of a probability tensor ``[..., rows, C]``
    (zero entries contribute zero; ``row_mask`` selects valid rows)."""
    import jax.numpy as jnp
    S = S.astype(jnp.float32)
    h = -jnp.sum(jnp.where(S > 0, S * jnp.log(jnp.maximum(S, _EPS)), 0.0),
                 axis=-1)
    return _row_mean(h, row_mask)


def topk_mass(S, k, row_mask=None):
    """Mean per-row probability mass of the ``k`` largest entries."""
    import jax.lax
    import jax.numpy as jnp
    S = S.astype(jnp.float32)
    k = max(1, min(int(k), S.shape[-1]))
    top, _ = jax.lax.top_k(S, k)
    return _row_mean(jnp.sum(top, axis=-1), row_mask)


def delta_norm(S_new, S_old, row_mask=None):
    """Mean-over-batch Frobenius norm of ``S_new - S_old`` (rows outside
    ``row_mask`` zeroed): Algorithm 1's per-iteration correction size."""
    import jax.numpy as jnp
    d = (S_new - S_old).astype(jnp.float32)
    if row_mask is not None:
        d = d * row_mask[..., None].astype(d.dtype)
    axes = tuple(range(1, d.ndim))
    return jnp.mean(jnp.sqrt(jnp.sum(d * d, axis=axes)))
