"""Multi-device / multi-host obs aggregation: stragglers and skew.

A multi-process run writes one obs subdirectory per host
(``obs-dir/host_<k>/`` — see
:func:`dgmc_tpu.parallel.distributed.host_obs_dir`), each holding the
standard artifacts for that process plus per-device step-completion
series (``RunObserver.fence_devices``) and per-device memory snapshots.
This module merges them::

    python -m dgmc_tpu.obs.aggregate <obs_dir>         # table + artifact
    python -m dgmc_tpu.obs.aggregate <obs_dir> --json  # machine-readable

producing a straggler/skew summary — max/median device step-time ratio,
per-device memory-peak spread, per-host wall-clock spread — that
``obs.report`` and ``obs.diff`` consume (``aggregate.json`` is written
next to the host subdirectories). A single-host obs dir is treated as
``host_0``, so an 8-device single-process run still gets its per-device
skew table.

Skew semantics: the step-time ratio is ``max / median`` over the mean
per-device step-completion time (1.0 = perfectly balanced). The
completion series are cumulative-drain measurements — each device's
time is measured by fetching its shard of the step output, in device
order, so a straggler inflates the recorded time of every device
fetched after it; the MAX (the straggler itself) is exact, the median
is an upper bound, making the reported ratio a *lower* bound on the
true skew. Memory spread is ``max / median`` over per-device allocator
peaks (device source only; host-RSS fallbacks compare across hosts
instead).

Like ``obs.report`` / ``obs.diff``, this module has **no jax import**:
it must merge artifacts from a dead run on any box.
"""

import argparse
import json
import os
import re
import sys

from dgmc_tpu.obs.observe import fmt_seconds as _fmt_s
from dgmc_tpu.obs.observe import percentile
from dgmc_tpu.obs.report import load_run, summarize

_HOST_DIR = re.compile(r'^host_(\d+)$')


def find_host_dirs(root):
    """``[(host_name, path)]`` — the ``host_<k>/`` subdirectories of
    ``root`` (sorted by host index), else ``root`` itself as ``host_0``
    when it holds run artifacts directly. Empty when neither."""
    hosts = []
    try:
        entries = sorted(os.listdir(root))
    except OSError:
        return []
    for name in entries:
        m = _HOST_DIR.match(name)
        d = os.path.join(root, name)
        if m and os.path.isdir(d) and _has_artifacts(d):
            hosts.append((int(m.group(1)), name, d))
    if hosts:
        return [(name, d) for _, name, d in sorted(hosts)]
    if _has_artifacts(root):
        return [('host_0', root)]
    return []


def _has_artifacts(d):
    return (os.path.exists(os.path.join(d, 'timings.json'))
            or os.path.exists(os.path.join(d, 'metrics.jsonl')))


def _median(values):
    return percentile(sorted(values), 0.5) if values else None


def _ratio(mx, med):
    if mx is None or not med:
        return None
    return round(mx / med, 4)


def _spread(rows, key):
    """{'max', 'median', 'ratio_max_over_median', 'worst'} over
    ``rows`` (dicts carrying ``key`` plus identity fields)."""
    vals = [r[key] for r in rows if r.get(key)]
    if not vals:
        return None
    mx = max(vals)
    med = _median(vals)
    worst = max((r for r in rows if r.get(key)), key=lambda r: r[key])
    return {'max': mx, 'median': med,
            'ratio_max_over_median': _ratio(mx, med),
            'worst': {k: worst[k] for k in ('host', 'device') if k in worst}}


#: A heartbeat older than this with an unreachable endpoint reads as
#: "the run ENDED" (heartbeats refresh every watchdog poll, seconds
#: apart, while the process lives) — the post-hoc artifacts are then
#: authoritative and the host is NOT flagged live-unhealthy. A FRESH
#: heartbeat with a dead endpoint is the live anomaly.
ENDED_HEARTBEAT_AGE_S = 60.0


def _scrape_host(host_dir):
    """Live-endpoint probe for one host dir: the advertised host+port
    are read from the heartbeat file the watchdog already writes
    (``heartbeat.json`` carries them when ``--obs-port`` is armed),
    then ``/healthz`` is scraped. Returns ``None`` when the host never
    advertised a port; otherwise ``{'port', 'pid', 'healthy',
    'heartbeat_age_s', ...}``. An endpoint that does not answer (or
    answers without a verdict — an errored handler's 500) is
    ``'unreachable'`` only while the heartbeat is fresh; with a stale
    heartbeat it is ``'ended'`` — a completed run's leftover
    advertisement, not a live anomaly."""
    try:
        with open(os.path.join(host_dir, 'heartbeat.json')) as f:
            hb = json.load(f)
    except (OSError, ValueError):
        return None
    port = hb.get('port')
    if not port:
        return None
    out = {'port': port, 'pid': hb.get('pid')}
    from dgmc_tpu.obs.live import probe_healthz
    res = probe_healthz(port, host=hb.get('host') or '127.0.0.1')
    verdict = None
    if res is not None:
        code, payload = res
        if 'healthy' in payload:
            verdict = bool(payload['healthy'])
        elif code == 200:
            verdict = True
        else:
            out['scrape_error'] = code
    if verdict is None:
        import time
        if time.time() - hb.get('time', 0) > ENDED_HEARTBEAT_AGE_S:
            out['ended'] = True
        else:
            out['unreachable'] = True
        return out
    out['healthy'] = verdict
    for k in ('heartbeat_age_s', 'steps_completed', 'in_flight',
              'gauges'):
        if res[1].get(k) is not None:
            out[k] = res[1][k]
    return out


def aggregate(root, scrape=False):
    """Merge ``root``'s host subdirectories into one skew summary.

    Returns ``None`` when ``root`` holds no run artifacts at all;
    otherwise a dict with ``hosts``, ``per_host``, ``devices`` (one row
    per (host, device) with mean step-completion time and memory peak),
    ``step_time``, ``memory``, ``wall`` spreads and the condensed
    ``skew`` block the report/diff layers read.

    ``scrape=True`` additionally probes each host's LIVE ``/healthz``
    endpoint (port discovered from its ``heartbeat.json``) — the
    distributed-run view of a run still in flight: per-host
    ``live`` blocks plus top-level ``live_unhealthy_hosts``.
    """
    hosts = find_host_dirs(root)
    if not hosts:
        return None

    per_host = {}
    device_rows = []
    mem_rows = []
    host_rows = []
    for name, d in hosts:
        run = load_run(d)
        s = summarize(run)
        per_host[name] = {k: s[k] for k in
                          ('steps', 'step_p50_s', 'step_p95_s', 'wall_s',
                           'steps_per_sec', 'compile_events',
                           'peak_memory_bytes', 'peak_memory_source',
                           'metrics_records')
                          if k in s}
        if s.get('hang_report'):
            per_host[name]['hang_report'] = s['hang_report']
        if scrape:
            live = _scrape_host(d)
            if live is not None:
                per_host[name]['live'] = live
        host_rows.append({'host': name,
                          'step_p50_s': s.get('step_p50_s'),
                          'wall_s': s.get('wall_s')})
        for dev_id, agg in (s.get('device_steps') or {}).items():
            device_rows.append({'host': name, 'device': dev_id,
                                'mean_step_s': agg.get('mean_s'),
                                'steps': agg.get('count')})
        mem_rows.extend(_device_memory_peaks(name, run['memory']))

    # Device step-time spread; when no per-device series exists (the
    # run predates fence_devices or never called it), fall back to the
    # per-host p50s so multi-host runs still get a straggler signal.
    step_spread = _spread(device_rows, 'mean_step_s')
    step_source = 'device_series'
    if step_spread is None:
        step_spread = _spread(
            [{'host': r['host'], 'mean_step_s': r['step_p50_s']}
             for r in host_rows], 'mean_step_s')
        step_source = 'host_p50'

    mem_spread = _spread(mem_rows, 'peak_bytes')
    mem_source = 'device'
    if mem_spread is None:
        host_mem = [{'host': name,
                     'peak_bytes': per_host[name].get('peak_memory_bytes')}
                    for name, _ in hosts]
        mem_spread = _spread(host_mem, 'peak_bytes')
        mem_source = 'host'

    wall_spread = _spread(
        [{'host': r['host'], 'wall_s': r['wall_s']} for r in host_rows],
        'wall_s')

    out = {
        'root': root,
        'hosts': len(hosts),
        'per_host': per_host,
        'devices': device_rows,
        'step_time': dict(step_spread or {}, source=step_source)
        if step_spread else None,
        'memory': dict(mem_spread or {}, source=mem_source)
        if mem_spread else None,
        'wall': wall_spread,
        'hung_hosts': [name for name, p in per_host.items()
                       if 'hang_report' in p],
    }
    if scrape:
        out['live_unhealthy_hosts'] = [
            name for name, p in per_host.items()
            if 'live' in p and (p['live'].get('unreachable')
                                or p['live'].get('healthy') is False)]
    attribution = {
        name: _attribute_hang(root, name, per_host[name]['hang_report'])
        for name in out['hung_hosts']}
    if attribution:
        out['hang_attribution'] = attribution
    out['skew'] = {
        'step_time_ratio': (step_spread or {}).get('ratio_max_over_median'),
        'memory_ratio': (mem_spread or {}).get('ratio_max_over_median'),
        'wall_ratio': (wall_spread or {}).get('ratio_max_over_median'),
    }
    return out


def _attribute_hang(root, host_name, hang_summary):
    """Attribute a hung host to its last completed fence/phase.

    "Hung" alone is not actionable; the attribution names (a) what the
    host was inside when it stalled (the hang report's in-flight span —
    for a fence-deadline report that names the fence phase/step and the
    missing peers), (b) the last span it COMPLETED, and (c) its last
    completed collective fence from the control-plane heartbeat
    (``<root>/control/host_<i>.json``) when one exists — the phase every
    surviving peer agrees this host reached.
    """
    out = {'reason': hang_summary.get('reason')}
    inf = hang_summary.get('in_flight') or {}
    if inf:
        out['in_flight'] = {k: inf.get(k) for k in ('phase', 'name')
                            if inf.get(k) is not None}
    if hang_summary.get('last_completed'):
        out['last_completed'] = hang_summary['last_completed']
    m = _HOST_DIR.match(host_name)
    if m is not None:
        # jax-free on purpose (module contract): read the control file
        # directly rather than through the resilience channel object.
        path = os.path.join(root, 'control', f'host_{m.group(1)}.json')
        try:
            with open(path) as f:
                beat = json.load(f)
        except (OSError, ValueError):
            beat = None
        if beat:
            out['last_heartbeat'] = {
                k: beat.get(k) for k in ('phase', 'step', 'time')
                if beat.get(k) is not None}
            if beat.get('last_fence'):
                out['last_fence'] = beat['last_fence']
    return out


def _device_memory_peaks(host, memory):
    """Per-device allocator peaks across a host's snapshots (device
    source only — host RSS is compared per host, not per device)."""
    peaks = {}
    for snap in (memory or {}).get('snapshots', []):
        for d in snap.get('devices', []):
            peak = max(d.get('peak_bytes_in_use', 0),
                       d.get('bytes_in_use', 0))
            if peak:
                did = str(d.get('id', '?'))
                peaks[did] = max(peaks.get(did, 0), peak)
    return [{'host': host, 'device': did, 'peak_bytes': v}
            for did, v in sorted(peaks.items())]


def write_aggregate(root, summary):
    path = os.path.join(root, 'aggregate.json')
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(summary, f, indent=1)
    os.replace(tmp, path)
    return path


def _fmt_ratio(v):
    return '-' if v is None else f'{v:.3f}x'


def render(summary):
    lines = [f'== obs aggregate: {summary["root"]} '
             f'({summary["hosts"]} host(s)) ==']
    lines.append(f'  {"host":<10} {"steps":>6} {"p50":>10} {"wall":>10} '
                 f'{"peak mem":>12}')
    for name, p in summary['per_host'].items():
        peak = p.get('peak_memory_bytes')
        peak = f'{peak / 2**30:.3f} GiB' if peak else '-'
        hang = '  ** HUNG **' if 'hang_report' in p else ''
        live = p.get('live')
        if live:
            if live.get('ended'):
                hang += f'  [live :{live["port"]} ended]'
            elif live.get('unreachable'):
                hang += f'  [live :{live["port"]} UNREACHABLE]'
            else:
                state = 'ok' if live.get('healthy') else 'STALE'
                hang += f'  [live :{live["port"]} {state}]'
        lines.append(f'  {name:<10} {p.get("steps", "-"):>6} '
                     f'{_fmt_s(p.get("step_p50_s")):>10} '
                     f'{_fmt_s(p.get("wall_s")):>10} {peak:>12}{hang}')
    st = summary.get('step_time')
    lines.append('-- step-time skew --')
    if st:
        lines.append(f'  max / median     {_fmt_s(st["max"])} / '
                     f'{_fmt_s(st["median"])}   '
                     f'ratio {_fmt_ratio(st["ratio_max_over_median"])} '
                     f'[{st["source"]}]')
        if st.get('worst'):
            lines.append(f'  straggler        {st["worst"]}')
    else:
        lines.append('  (no step series recorded)')
    if summary.get('devices'):
        lines.append(f'  {"host":<10} {"device":>6} {"mean step":>12} '
                     f'{"steps":>6}')
        for r in summary['devices']:
            lines.append(f'  {r["host"]:<10} {r["device"]:>6} '
                         f'{_fmt_s(r.get("mean_step_s")):>12} '
                         f'{r.get("steps", "-"):>6}')
    mem = summary.get('memory')
    lines.append('-- memory skew --')
    if mem:
        lines.append(f'  max / median     {mem["max"] / 2**30:.3f} GiB / '
                     f'{mem["median"] / 2**30:.3f} GiB   '
                     f'ratio {_fmt_ratio(mem["ratio_max_over_median"])} '
                     f'[{mem["source"]}]')
    else:
        lines.append('  (no memory peaks recorded)')
    if summary.get('live_unhealthy_hosts'):
        lines.append(f'  LIVE-UNHEALTHY HOSTS: '
                     f'{summary["live_unhealthy_hosts"]} '
                     f'(/healthz 503 or unreachable)')
    if summary.get('hung_hosts'):
        lines.append(f'  HUNG HOSTS: {summary["hung_hosts"]} '
                     f'(see their hang_report.json)')
        for name, att in (summary.get('hang_attribution') or {}).items():
            inf = att.get('in_flight') or {}
            fence = att.get('last_fence') or {}
            done = att.get('last_completed') or {}
            lines.append(
                f'    {name}: stuck in '
                f'{inf.get("phase", "?")}:{inf.get("name", "?")}'
                + (f', last completed '
                   f'{done.get("phase")}:{done.get("name")}'
                   if done else '')
                + (f', last fence {fence.get("phase")}@{fence.get("step")}'
                   if fence else ''))
    return '\n'.join(lines)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='python -m dgmc_tpu.obs.aggregate',
        description='Merge per-host obs subdirectories into a '
                    'straggler/skew summary (writes aggregate.json).')
    parser.add_argument('root', help='obs directory (holding host_<k>/ '
                                     'subdirs, or artifacts directly)')
    parser.add_argument('--json', action='store_true',
                        help='print the machine-readable summary')
    parser.add_argument('--no-write', action='store_true',
                        help="don't write <root>/aggregate.json")
    parser.add_argument('--scrape', action='store_true',
                        help='also probe each host\'s live /healthz '
                             'endpoint (port discovered from its '
                             'heartbeat.json — the --obs-port '
                             'advertisement) and report per-host live '
                             'health for a run still in flight')
    args = parser.parse_args(argv)

    summary = aggregate(args.root, scrape=args.scrape)
    if summary is None:
        print(f'aggregate: no obs artifacts under {args.root}',
              file=sys.stderr)
        return 2
    if not args.no_write:
        write_aggregate(args.root, summary)
    if args.json:
        print(json.dumps(summary, indent=1))
    else:
        print(render(summary))
    return 0


if __name__ == '__main__':
    sys.exit(main())
