"""Capacity model: saturation QPS, Little's-law utilization, headroom.

Usage::

    python -m dgmc_tpu.obs.capacity <obs_dir | round.json> ...
    python -m dgmc_tpu.obs.capacity benchmarks/SERVE_r04.json --json
    python -m dgmc_tpu.obs.capacity obs/ --target-qps 50 \
        benchmarks/BENCH_r06.json

The serving executor is serialized (one engine lock — see
``serve/engine.py``), so its capacity model is the single-server queue:

- **saturation QPS** = 1 / E[service time] — the ceiling the measured
  service-time distribution (the engine's lock-HOLD histogram, or
  qtrace's ``device_execute`` account) permits, whatever concurrency
  clients offer;
- **utilization** ρ = arrival rate × E[service time] (Little's law) —
  how much of that ceiling the observed arrival rate consumes;
- **projected wait** ≈ ρ/(1−ρ) × E[service] (M/M/1) — what the
  admission queue charges as ρ → 1, the model behind SERVE_r02's
  measured `admission_queue_wait` tail;
- **knee** of a measured QPS-vs-concurrency ramp (serve_bench's
  1→2→4→8 leg): the last concurrency whose marginal QPS gain still
  cleared the floor — added clients past it buy queueing, not
  throughput;
- **batching headroom** from bench ``pairs_sweep``'s measured
  ``step_ms_per_pair``: projected QPS(B) = 1000 / step_ms_per_pair(B),
  and the smallest bucket batch that hits a target QPS.

Inputs are committed artifacts (round JSONs, obs dirs) — like every obs
reader this module has **no jax import**; it models capacity from
evidence on any box.
"""

import argparse
import json
import math
import os
import sys

__all__ = ['saturation_qps', 'utilization', 'mm1_wait_s', 'knee_of',
           'hist_mean_s', 'hist_quantile_s', 'batching_headroom',
           'live_summary', 'analyze_paths', 'render', 'main']


def saturation_qps(mean_service_s):
    """The serialized executor's throughput ceiling: 1 / E[service]."""
    if not mean_service_s or mean_service_s <= 0:
        return None
    return 1.0 / float(mean_service_s)


def utilization(arrival_qps, mean_service_s):
    """Little's-law utilization ρ = λ × E[service] (may exceed 1 when
    the measured arrival rate outruns the ceiling — that IS the
    saturation signal, not an error)."""
    if arrival_qps is None or not mean_service_s or mean_service_s <= 0:
        return None
    return float(arrival_qps) * float(mean_service_s)


def mm1_wait_s(arrival_qps, mean_service_s):
    """Projected queue wait ρ/(1−ρ)·E[service] (M/M/1); ``None`` at or
    past saturation — an unstable queue has no stationary wait."""
    rho = utilization(arrival_qps, mean_service_s)
    if rho is None or rho >= 1.0:
        return None
    return rho / (1.0 - rho) * float(mean_service_s)


def hist_mean_s(snapshot):
    """Mean from a :meth:`StreamingHistogram.snapshot` dict."""
    if not snapshot or not snapshot.get('count'):
        return None
    return float(snapshot['sum']) / float(snapshot['count'])


def hist_quantile_s(snapshot, q):
    """Quantile from a histogram SNAPSHOT (cumulative ``buckets``
    rows) — the artifact-side twin of ``StreamingHistogram.quantile``,
    upper-bound convention: the smallest bucket bound whose cumulative
    count covers the rank."""
    if not snapshot or not snapshot.get('count'):
        return None
    rank = q * snapshot['count']
    prev_bound = 0.0
    for bound, cum in snapshot['buckets']:
        if cum >= rank:
            return float(bound) if math.isfinite(bound) else prev_bound
        if math.isfinite(bound):
            prev_bound = float(bound)
    return prev_bound


def knee_of(ramp, min_gain=0.10):
    """The measured saturation knee of a QPS-vs-concurrency ramp.

    ``ramp`` is a list of ``{'clients', 'qps'}`` rows (any order).
    Walking in increasing concurrency, the knee is the last level whose
    QPS still improved on the previous level by at least ``min_gain``
    (fractional); every level past it bought queueing, not throughput.
    ``saturated`` is False when the ramp never stopped scaling — the
    knee lies beyond the measured range.
    """
    rows = sorted((r for r in ramp or []
                   if r.get('clients') and r.get('qps') is not None),
                  key=lambda r: r['clients'])
    if not rows:
        return None
    knee = rows[0]
    saturated = False
    for prev, cur in zip(rows, rows[1:]):
        if prev['qps'] > 0 and \
                (cur['qps'] - prev['qps']) / prev['qps'] >= min_gain:
            knee = cur
        else:
            saturated = True
            break
    return {'clients': knee['clients'], 'qps': knee['qps'],
            'saturated': saturated, 'min_gain': min_gain}


def batching_headroom(step_ms_per_pair_by_b, target_qps=None):
    """Projected QPS per bucket batch size from bench ``pairs_sweep``'s
    measured per-pair step time, plus the smallest batch hitting
    ``target_qps`` (``None`` when out of reach — honesty over hope)."""
    per_batch = {}
    for b, ms in (step_ms_per_pair_by_b or {}).items():
        try:
            b = int(b)
            ms = float(ms)
        except (TypeError, ValueError):
            continue
        if ms > 0:
            per_batch[b] = round(1000.0 / ms, 3)
    if not per_batch:
        return None
    out = {
        'projected_qps_per_batch': {str(b): per_batch[b]
                                    for b in sorted(per_batch)},
        'best_batch': max(per_batch, key=per_batch.get),
        'best_qps': max(per_batch.values()),
    }
    if target_qps:
        out['target_qps'] = float(target_qps)
        fits = [b for b in sorted(per_batch)
                if per_batch[b] >= float(target_qps)]
        out['recommended_batch'] = fits[0] if fits else None
    return out


def live_summary(cap_stats, qtrace_summary=None):
    """The `/status` ``capacity`` section: the engine's
    :meth:`~dgmc_tpu.serve.engine.MatchEngine.capacity_stats` account
    reduced to the queueing model, with the engine's lock-wait
    distribution reconciled against qtrace's ``admission_queue_wait``
    stage (same measured region, two recorders — the reconciliation
    block proves the two dialects agree)."""
    hold = cap_stats.get('lock_hold') or {}
    wait = cap_stats.get('lock_wait') or {}
    mean_service = hist_mean_s(hold)
    window = cap_stats.get('window_s')
    queries = cap_stats.get('queries') or 0
    arrival = (queries - 1) / window if window and queries > 1 else None
    out = {
        'inflight': cap_stats.get('inflight'),
        'queries': queries,
        'arrival_qps': round(arrival, 3) if arrival else None,
        'mean_service_ms': (round(mean_service * 1e3, 4)
                            if mean_service else None),
        'saturation_qps': _round3(saturation_qps(mean_service)),
        'utilization': _round3(utilization(arrival, mean_service)),
        'projected_wait_ms': _ms(mm1_wait_s(arrival, mean_service)),
        'lock_wait_ms': _hist_ms(wait),
        'lock_hold_ms': _hist_ms(hold),
        'pad_fraction': cap_stats.get('pad_fraction'),
        'goodput_ratio': cap_stats.get('goodput_ratio'),
        'buckets': cap_stats.get('buckets'),
    }
    stage = ((qtrace_summary or {}).get('stages') or {}).get(
        'admission_queue_wait')
    if stage:
        engine_p95 = hist_quantile_s(wait, 0.95)
        out['admission_reconciliation'] = {
            'qtrace_count': stage.get('count'),
            'qtrace_p95_ms': stage.get('p95_ms'),
            'engine_count': wait.get('count'),
            'engine_p95_ms': (round(engine_p95 * 1e3, 4)
                              if engine_p95 is not None else None),
            'note': 'same measured region (the engine lock acquire); '
                    'qtrace counts traced queries only, the engine '
                    'histogram counts all',
        }
    return out


def _round3(v):
    return None if v is None else round(v, 3)


def _ms(v):
    return None if v is None else round(v * 1e3, 4)


def _hist_ms(snapshot):
    if not snapshot or not snapshot.get('count'):
        return None
    return {
        'count': snapshot['count'],
        'mean_ms': _ms(hist_mean_s(snapshot)),
        'p50_ms': _ms(hist_quantile_s(snapshot, 0.50)),
        'p95_ms': _ms(hist_quantile_s(snapshot, 0.95)),
        'p99_ms': _ms(hist_quantile_s(snapshot, 0.99)),
    }


# ---------------------------------------------------------------------------
# Artifact-side analysis (the CLI)
# ---------------------------------------------------------------------------

def _read_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _from_obs_dir(path, out):
    qtrace = _read_json(os.path.join(path, 'qtrace_summary.json'))
    if qtrace:
        e2e = qtrace.get('end_to_end') or {}
        count = e2e.get('count')
        mean_s = (e2e['sum_ms'] / count / 1e3
                  if count and e2e.get('sum_ms') else None)
        out['service_time'] = {
            'source': f'{path}/qtrace_summary.json end_to_end',
            'queries': count,
            'mean_ms': round(mean_s * 1e3, 4) if mean_s else None,
            'saturation_qps': _round3(saturation_qps(mean_s)),
        }
    goodput = _read_json(os.path.join(path, 'goodput.json'))
    if goodput:
        out['goodput'] = {'source': f'{path}/goodput.json',
                          'goodput_ratio': goodput.get('goodput_ratio'),
                          'pad_fraction_max':
                              goodput.get('pad_fraction_max')}


def _from_round(path, d, out, target_qps):
    cap = d.get('capacity') or {}
    ramp = (d.get('ramp') or {}).get('levels') or d.get('ramp')
    if isinstance(ramp, list) and ramp:
        out['ramp'] = {'source': os.path.basename(path),
                       'levels': ramp,
                       'knee': knee_of(ramp)}
    if cap:
        out['serve_capacity'] = dict(cap, source=os.path.basename(path))
    if d.get('goodput'):
        out.setdefault('goodput', {})
        out['goodput'].update(dict(d['goodput'],
                                   source=os.path.basename(path)))
    sweep = _pairs_sweep_of(d)
    if sweep:
        per_b = {b: v.get('step_ms_per_pair')
                 for b, v in sweep.items()
                 if isinstance(v, dict) and v.get('step_ms_per_pair')}
        headroom = batching_headroom(per_b, target_qps)
        if headroom:
            out['batching_headroom'] = dict(
                headroom, source=os.path.basename(path))


def _pairs_sweep_of(d):
    for holder in (d.get('result') or {}, d):
        for key in ('sparse_dbp15k', 'sparse'):
            sweep = (holder.get(key) or {}).get('pairs_sweep') \
                if isinstance(holder.get(key), dict) else None
            if sweep:
                return sweep
    return (d.get('result') or {}).get('pairs_sweep') \
        or d.get('pairs_sweep')


def analyze_paths(paths, target_qps=None):
    """One capacity report object from committed evidence: obs dirs
    (service-time distribution, goodput artifact) and/or round JSONs
    (serve rounds' ramp + capacity blocks, bench rounds'
    ``pairs_sweep`` for batching headroom)."""
    out = {'inputs': list(paths)}
    if target_qps:
        out['target_qps'] = float(target_qps)
    for p in paths:
        if os.path.isdir(p):
            _from_obs_dir(p, out)
            continue
        d = _read_json(p)
        if d is None:
            out.setdefault('unreadable', []).append(p)
            continue
        _from_round(p, d, out, target_qps)
    return out


def render(report):
    lines = ['== capacity model ==']
    st = report.get('service_time')
    if st:
        lines.append(f'  service time     mean {st.get("mean_ms")} ms '
                     f'over {st.get("queries")} queries '
                     f'[{st.get("source")}]')
        lines.append(f'  saturation QPS   {st.get("saturation_qps")}')
    cap = report.get('serve_capacity')
    if cap:
        lines.append(f'  serve capacity   [{cap.get("source")}]')
        for key in ('saturation_qps', 'utilization', 'arrival_qps',
                    'mean_service_ms', 'projected_wait_ms'):
            if cap.get(key) is not None:
                lines.append(f'    {key:<18} {cap[key]}')
    ramp = report.get('ramp')
    if ramp:
        lines.append(f'  concurrency ramp [{ramp.get("source")}]')
        lines.append(f'    {"clients":>7} {"QPS":>8} {"p50 ms":>9} '
                     f'{"p95 ms":>9}')
        for row in ramp['levels']:
            lines.append(f'    {row.get("clients", "-"):>7} '
                         f'{_f(row.get("qps")):>8} '
                         f'{_f(row.get("p50_ms")):>9} '
                         f'{_f(row.get("p95_ms")):>9}')
        knee = ramp.get('knee')
        if knee:
            beyond = '' if knee['saturated'] else \
                ' (beyond the measured range)'
            lines.append(f'    knee: {knee["clients"]} clients @ '
                         f'{knee["qps"]} QPS{beyond}')
    good = report.get('goodput')
    if good:
        lines.append(f'  goodput          ratio '
                     f'{good.get("goodput_ratio")}, max pad fraction '
                     f'{good.get("pad_fraction_max")} '
                     f'[{good.get("source", "?")}]')
    hr = report.get('batching_headroom')
    if hr:
        lines.append(f'  batching headroom [{hr.get("source")}]')
        for b, qps in hr['projected_qps_per_batch'].items():
            lines.append(f'    B={b:<3} projected {qps} QPS')
        if hr.get('target_qps'):
            rec = hr.get('recommended_batch')
            lines.append(f'    target {hr["target_qps"]} QPS -> '
                         + (f'B={rec}' if rec is not None
                            else 'out of reach at measured rates'))
    if len(lines) == 1:
        lines.append('  (no capacity evidence in the given paths)')
    return '\n'.join(lines)


def _f(v):
    return '-' if v is None else f'{v:.4g}'


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog='python -m dgmc_tpu.obs.capacity',
        description='Model serving capacity from committed evidence: '
                    'saturation QPS, Little\'s-law utilization, the '
                    'measured concurrency knee, and bench-seeded '
                    'batching headroom.')
    parser.add_argument('paths', nargs='+',
                        help='obs dirs and/or round JSONs '
                             '(SERVE_r*.json ramps, BENCH_r*.json '
                             'pairs_sweep)')
    parser.add_argument('--target-qps', type=float, default=None,
                        help='QPS target for the batching-headroom '
                             'recommendation')
    parser.add_argument('--json', action='store_true',
                        help='print the machine-readable report')
    args = parser.parse_args(argv)

    for p in args.paths:
        if not os.path.exists(p):
            print(f'capacity: no such path: {p}', file=sys.stderr)
            return 2
    report = analyze_paths(args.paths, target_qps=args.target_qps)
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(render(report))
    return 0


if __name__ == '__main__':
    sys.exit(main())
