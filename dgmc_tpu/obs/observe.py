"""Observability primitives: profiler traces, per-step timing, metric
logging.

The reference has no tracing, timing, or metric sink of any kind — training
progress is bare ``print()`` lines (SURVEY.md §5: reference
``examples/dbp15k.py:75-76``, ``examples/pascal.py:109-110``). Here these
are first-class:

- :func:`trace` — a ``jax.profiler`` trace of a step window, viewable in
  TensorBoard/Perfetto, for finding MXU idle time and HBM stalls. Model
  code carries ``jax.named_scope`` stage annotations (``psi1``, ``topk``,
  ``consensus_iter``, ``psi2``; see ``models/dgmc.py``), so the trace
  shows the matching pipeline's stages rather than anonymous XLA ops.
- :class:`StepTimer` — wall-clock per-step timing with a device fence, so
  the numbers measure execution rather than dispatch.
- :class:`MetricLogger` — JSONL metric sink alongside (not replacing) the
  reference-parity stdout prints.

Formerly ``dgmc_tpu.train.observe``; that module remains as a deprecated
alias of this one.
"""

import contextlib
import json
import math
import os
import time


@contextlib.contextmanager
def trace(log_dir):
    """Profile the enclosed steps into ``log_dir`` (no-op if ``log_dir`` is
    falsy). The trace captures XLA device activity on the real TPU and
    host-side dispatch everywhere."""
    if not log_dir:
        yield
        return
    # Lazy: this module must import without jax so the report CLI can
    # render telemetry from a dead run on any box.
    import jax
    os.makedirs(log_dir, exist_ok=True)
    with jax.profiler.trace(log_dir):
        yield


def read_json_artifact(path):
    """Best-effort obs-artifact read: the parsed JSON, or ``None`` on a
    missing/torn/unparsable file — the shared contract of every
    artifact consumer (report, diff, attribution): absence is data,
    never an exception. jax-free."""
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def fmt_seconds(v):
    """``41.2 ms`` / ``3.100 s`` / ``-`` — the one duration formatter
    shared by the report, aggregate and cost renderers (jax-free)."""
    if v is None:
        return '-'
    if v >= 1.0:
        return f'{v:.3f} s'
    return f'{v * 1e3:.2f} ms'


def fmt_si(v):
    """``60.5 M``-style SI scaling (no unit suffix), shared by the
    report and cost renderers; ``-`` for None."""
    if v is None:
        return '-'
    for unit in ('', ' K', ' M', ' G', ' T', ' P'):
        if abs(v) < 1000 or unit == ' P':
            return f'{v:.3g}{unit}'
        v /= 1000


def percentile(sorted_times, q):
    """Linear-interpolated percentile (``q`` in [0, 1]) of an already
    sorted list — numpy's default 'linear' rule, so the p50 of an
    even-length window is the mean of the two middle elements rather than
    the upper one."""
    if not sorted_times:
        raise ValueError('percentile of an empty window')
    pos = q * (len(sorted_times) - 1)
    lo = int(pos)
    hi = min(lo + 1, len(sorted_times) - 1)
    return sorted_times[lo] + (sorted_times[hi] - sorted_times[lo]) * (
        pos - lo)


class StepTimer:
    """Accumulates fenced per-step wall-clock times.

    ``fence`` should be a device scalar from the step's outputs (e.g. the
    loss); fetching it to host guarantees the step actually finished before
    the clock stops. Without a fence the recorded time is host-observed
    dispatch+wait, which still averages to true step time over a window
    that ends in a host fetch.
    """

    def __init__(self):
        self.times = []
        #: Wall-clock step spans ``(epoch_start_s, duration_s)`` — the
        #: timeline view of ``times``, consumed by the Chrome-trace
        #: exporter (:func:`dgmc_tpu.obs.trace.export_chrome_trace`).
        self.spans = []
        #: ``perf_counter`` of the most recent :meth:`start`, kept after
        #: :meth:`stop` — the reference point for per-device completion
        #: probes (``RunObserver.fence_devices``) that run right after
        #: the timed block.
        self.last_start = None
        self._t0 = None
        self._wall0 = None

    def start(self):
        self._wall0 = time.time()
        self._t0 = self.last_start = time.perf_counter()

    def stop(self, fence=None):
        if self._t0 is None:
            raise RuntimeError(
                'StepTimer.stop() called without a matching start(); call '
                'start() before each timed step')
        if fence is not None:
            float(fence)
        self.times.append(time.perf_counter() - self._t0)
        self.spans.append((self._wall0, self.times[-1]))
        self._t0 = self._wall0 = None
        return self.times[-1]

    @property
    def mean(self):
        return sum(self.times) / max(len(self.times), 1)

    def summary(self):
        if not self.times:
            return {}
        ts = sorted(self.times)
        return {
            'steps': len(ts),
            'mean_s': self.mean,
            'p50_s': percentile(ts, 0.5),
            'p95_s': percentile(ts, 0.95),
            'max_s': ts[-1],
            'total_s': sum(ts),
        }


class MetricLogger:
    """Append-only JSONL metric sink (one object per ``log`` call).

    Cheap enough to leave on: one ``json.dumps`` + buffered write per step.
    Pass ``path=None`` to disable (all calls become no-ops). ``mode='a'``
    (default) appends across invocations — the standalone ``--metrics_log``
    contract; :class:`~dgmc_tpu.obs.run.RunObserver` passes ``'w'`` so a
    reused ``--obs-dir`` holds ONE run, consistent with the other
    artifacts it rewrites.
    """

    def __init__(self, path, mode='a'):
        self.path = path
        self._fh = None
        if path:
            os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
            self._fh = open(path, mode)

    def log(self, step, **metrics):
        if self._fh is None:
            return
        rec = {'step': step, 'time': time.time()}
        for k, v in metrics.items():
            # Device scalars / numpy types to float; bools and Python ints
            # (e.g. a probe's static `iteration` tag) keep their type.
            coerce = (hasattr(v, '__float__')
                      and not isinstance(v, (bool, int)))
            v = float(v) if coerce else v
            if isinstance(v, float) and not math.isfinite(v):
                # NaN/inf are not valid JSON (json.dumps would emit a
                # bare NaN token that strict parsers reject) — null
                # records "this value went non-finite" in a file that
                # stays loadable, which is exactly when it matters.
                v = None
            rec[k] = v
        self._fh.write(json.dumps(rec) + '\n')
        self._fh.flush()

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
