"""Observability subsystem: profiler spans, telemetry registry, run
artifacts, and the report CLI.

The reference logs training with bare ``print()`` lines and publishes no
wall-clock numbers (SURVEY.md §5), so every perf claim this repo makes
rests on its own measurements. This package is the single place those
measurements come from:

- :mod:`~dgmc_tpu.obs.observe` — staged profiler traces
  (:func:`trace`), fenced per-step wall-clock timing (:class:`StepTimer`),
  and the JSONL metric sink (:class:`MetricLogger`). Formerly
  ``dgmc_tpu.train.observe``; the old import path remains as a deprecated
  alias.
- :mod:`~dgmc_tpu.obs.registry` — process-wide counter/gauge registry:
  jit compile events (padding-bucket recompile churn), kernel-dispatch
  outcomes (Pallas-taken vs XLA-fallback vs GSPMD-silenced, with reason).
- :mod:`~dgmc_tpu.obs.memory` — per-device ``memory_stats()`` snapshots
  with a host-RSS fallback for platforms (CPU, tunneled TPU) where the
  allocator publishes nothing.
- :mod:`~dgmc_tpu.obs.run` — the :class:`RunObserver` facade behind the
  ``--obs-dir`` flag of every experiment CLI and ``bench.py``: one
  directory holding ``metrics.jsonl``, ``timings.json``, ``memory.json``
  and ``dispatch.json``.
- :mod:`~dgmc_tpu.obs.report` — ``python -m dgmc_tpu.obs.report <dir>``:
  renders throughput, step-time percentiles, recompile counts, HBM peaks
  and the kernel-dispatch table from those artifacts.

Model code carries :func:`jax.named_scope` annotations for the matching
pipeline's stages (``psi1``, ``initial_corr``, ``topk``,
``consensus_iter``, ``psi2``) so Perfetto/TensorBoard traces and lowered
HLO show the algorithm's structure instead of anonymous XLA ops.
"""

from dgmc_tpu.obs.observe import MetricLogger, StepTimer, trace
from dgmc_tpu.obs.registry import (REGISTRY, CompileWatcher, Registry,
                                   compile_event_count, dispatch_table,
                                   record_dispatch)
from dgmc_tpu.obs.memory import memory_snapshot
from dgmc_tpu.obs.run import RunObserver, add_obs_flag

__all__ = [
    'MetricLogger',
    'StepTimer',
    'trace',
    'Registry',
    'REGISTRY',
    'CompileWatcher',
    'compile_event_count',
    'record_dispatch',
    'dispatch_table',
    'memory_snapshot',
    'RunObserver',
    'add_obs_flag',
]
