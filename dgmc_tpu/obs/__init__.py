"""Observability subsystem: profiler spans, telemetry registry, run
artifacts, and the report CLI.

The reference logs training with bare ``print()`` lines and publishes no
wall-clock numbers (SURVEY.md §5), so every perf claim this repo makes
rests on its own measurements. This package is the single place those
measurements come from:

- :mod:`~dgmc_tpu.obs.observe` — staged profiler traces
  (:func:`trace`), fenced per-step wall-clock timing (:class:`StepTimer`),
  and the JSONL metric sink (:class:`MetricLogger`). Formerly
  ``dgmc_tpu.train.observe``; the old import path remains as a deprecated
  alias.
- :mod:`~dgmc_tpu.obs.registry` — process-wide counter/gauge registry:
  jit compile events (padding-bucket recompile churn), kernel-dispatch
  outcomes (Pallas-taken vs XLA-fallback vs GSPMD-silenced, with reason).
- :mod:`~dgmc_tpu.obs.memory` — per-device ``memory_stats()`` snapshots
  with a host-RSS fallback for platforms (CPU, tunneled TPU) where the
  allocator publishes nothing.
- :mod:`~dgmc_tpu.obs.run` — the :class:`RunObserver` facade behind the
  ``--obs-dir`` flag of every experiment CLI and ``bench.py``: one
  directory holding ``metrics.jsonl``, ``timings.json``, ``memory.json``
  and ``dispatch.json``.
- :mod:`~dgmc_tpu.obs.probes` — in-graph numerics probes
  (``jax.debug.callback`` streams): correspondence entropy, top-k mass,
  per-consensus-iteration correction norms, gradient global-norm, and
  non-finite detection with first-offending-stage attribution. A Python
  bool at trace time — disabled, the lowered HLO is byte-identical to a
  probe-free build.
- :mod:`~dgmc_tpu.obs.trace` — Chrome-trace/Perfetto export of the run
  timeline (steps, compiles, probe series) plus the whole-run
  ``--profile-dir`` ``jax.profiler.trace`` flag.
- :mod:`~dgmc_tpu.obs.report` — ``python -m dgmc_tpu.obs.report <dir>``:
  renders throughput, step-time percentiles, recompile counts, HBM peaks,
  probe aggregates and the kernel-dispatch table from those artifacts.
- :mod:`~dgmc_tpu.obs.diff` — ``python -m dgmc_tpu.obs.diff A B``:
  cross-run regression diff with configurable thresholds and a nonzero
  exit code — the CI perf gate. A candidate that hung (left a
  ``hang_report.json``) or whose MFU dropped past threshold fails.
- :mod:`~dgmc_tpu.obs.watchdog` — run-health watchdog: a heartbeat
  thread (armed by :class:`RunObserver` via ``--watchdog-deadline``)
  that dumps ``hang_report.json`` — all-thread tracebacks, the
  in-flight activity, the last-completed span, pending compile labels,
  the kernel-dispatch tail — when the run stalls or receives
  SIGTERM/SIGALRM, so an ``rc: 124`` run is diagnosable.
- :mod:`~dgmc_tpu.obs.cost` — cost & efficiency attribution:
  ``cost_analysis`` FLOPs/bytes, per-pipeline-stage attribution via the
  ``named_scope`` spans in lowered HLO, collective-op accounting in
  sharded programs, and step-level MFU against a per-backend peak-FLOPs
  table (CPU fallback included) — the ``efficiency.json`` artifact and
  the ``python -m dgmc_tpu.obs.cost`` specimen CLI.
- :mod:`~dgmc_tpu.obs.aggregate` — multi-device/host aggregation:
  merges per-host obs subdirectories (``obs-dir/host_<k>/``) into a
  straggler/skew summary (max/median device step-time ratio, per-device
  memory-peak spread) via ``python -m dgmc_tpu.obs.aggregate``; with
  ``--scrape`` it also probes each host's live ``/healthz`` endpoint.
- :mod:`~dgmc_tpu.obs.live` — the live telemetry plane behind
  ``--obs-port``: ``/healthz`` (503 on a stale watchdog heartbeat,
  the supervisor's own staleness definition), ``/metrics`` (Prometheus
  text exposition with an O(1)-memory streaming step-latency
  histogram), ``/status`` (live timings), and the always-on anomaly
  **flight recorder** whose ring buffer is dumped as ``flight.json``
  on any watchdog trip, fence timeout, guard rollback or signal
  teardown.
- :mod:`~dgmc_tpu.obs.timeline` — longitudinal bench trajectory:
  ``python -m dgmc_tpu.obs.timeline benchmarks/`` renders the
  committed ``BENCH_r*``/``MULTICHIP_r*``/``SCALE_r*.json`` rounds as
  one throughput/p50/MFU/overlap table (``--json`` for rows).
- :mod:`~dgmc_tpu.obs.trace_events` — jax-free parser for the
  profiler's trace-event exports (``--profile-dir``'s
  ``plugins/profile/*/*.trace.json.gz``): device/host track
  classification, busy-interval algebra, stage/collective event
  classification shared with the static models.
- :mod:`~dgmc_tpu.obs.attribution` — measured-runtime attribution:
  ``python -m dgmc_tpu.obs.attribution <profile-dir|obs-dir>`` (also
  ``dgmc-obs-attribution``) turns a captured profiler trace into
  per-stage device wall-clock, comm/compute occupancy with a
  *measured* overlap fraction, idle/gap analysis, and a
  static-vs-measured reconciliation (measured MFU vs ``obs/cost``'s,
  measured overlap vs ``hlo_sched``'s model) — the
  ``attribution.json`` artifact, with headline fields merged into
  ``efficiency.json`` for the report and the diff gates
  (``--min-measured-overlap``, ``--max-idle-regression``). Device-less
  captures degrade to host-track attribution with device fields
  marked unavailable.
- :mod:`~dgmc_tpu.obs.qtrace` — per-query tracing for the serve path:
  W3C ``traceparent`` adoption/minting, span trees over a fixed stage
  vocabulary shared with the static/measured planes, deterministic
  bounded retention (slowest-K reservoir + every error + seeded
  sample) into ``qtrace.jsonl``, per-stage ``/metrics`` histograms,
  and ``python -m dgmc_tpu.obs.qtrace <obs-dir>`` attributing the
  serve p95−p50 tail gap to a named stage (``--chrome`` exports span
  trees beside profiler captures; ``obs.diff`` gates per-stage p95
  via ``--max-stage-p95-regression``).

Model code carries :func:`jax.named_scope` annotations for the matching
pipeline's stages (``psi1``, ``initial_corr``, ``topk``,
``consensus_iter``, ``psi2``) so Perfetto/TensorBoard traces and lowered
HLO show the algorithm's structure instead of anonymous XLA ops.
"""

from dgmc_tpu.obs import probes
from dgmc_tpu.obs.registry import (REGISTRY, CompileWatcher, Registry,
                                   compile_event_count, dispatch_table,
                                   record_dispatch)
from dgmc_tpu.obs.memory import memory_snapshot
from dgmc_tpu.obs.watchdog import Watchdog
from dgmc_tpu.obs.run import RunObserver, add_obs_flag
from dgmc_tpu.obs.trace import (ProfileHandle, add_profile_flag,
                                export_chrome_trace, parse_step_window,
                                profile_span, start_profile)
# Imported LAST: binding the trace() *function* must win over the package
# attribute the `dgmc_tpu.obs.trace` submodule import set just above —
# `from dgmc_tpu.obs import trace` is the long-standing profiler-context
# API (re-exported by dgmc_tpu.train). Reach the submodule with
# `from dgmc_tpu.obs.trace import ...` (resolved via sys.modules).
from dgmc_tpu.obs.observe import MetricLogger, StepTimer, trace

__all__ = [
    'MetricLogger',
    'StepTimer',
    'trace',
    'Registry',
    'REGISTRY',
    'CompileWatcher',
    'compile_event_count',
    'record_dispatch',
    'dispatch_table',
    'memory_snapshot',
    'RunObserver',
    'add_obs_flag',
    'Watchdog',
    'probes',
    'add_profile_flag',
    'export_chrome_trace',
    'profile_span',
    'start_profile',
    'ProfileHandle',
    'parse_step_window',
]
