"""The ``--obs-dir`` facade: one flag, four artifacts.

:class:`RunObserver` bundles the metric sink, the step timer, the compile
watcher, memory snapshots and the dispatch-counter snapshot behind a
single directory:

- ``metrics.jsonl`` — one record per :meth:`RunObserver.log` call, plus
  (with probes on) one record per in-graph probe event.
- ``timings.json``  — step-time percentiles + compile-event summary +
  run wall-clock + per-probe aggregates.
- ``memory.json``   — labelled device/host memory snapshots + the peak.
- ``dispatch.json`` — the kernel-dispatch outcome table.
- ``trace.json``    — Chrome-trace/Perfetto timeline of steps, compiles
  and probe series (:mod:`dgmc_tpu.obs.trace`).
- ``efficiency.json`` — FLOPs/bytes/per-stage attribution and MFU for
  the programs the run registered via :meth:`RunObserver.record_cost`
  (:mod:`dgmc_tpu.obs.cost`).
- ``hang_report.json`` — written only when the run stalls past the
  watchdog deadline or dies to SIGTERM/SIGALRM
  (:mod:`dgmc_tpu.obs.watchdog`).
- ``flight.json`` — the anomaly flight recorder's dump
  (:mod:`dgmc_tpu.obs.live`): the last N span completions, probe
  values, dispatch decisions and compile events, written on any
  anomaly (watchdog trip, fence timeout, guard rollback, signal
  teardown) — the trailing-context record ``hang_report.json``'s
  stack dump lacks.

With ``--obs-port`` the observer additionally serves the live
telemetry plane (``/healthz`` + ``/metrics`` + ``/status``, see
:mod:`dgmc_tpu.obs.live`) and advertises the bound port in
``heartbeat.json``.

Every method is a no-op when constructed with a falsy directory, so CLIs
call the observer unconditionally::

    obs = RunObserver(args.obs_dir)      # None => disabled
    with obs:
        for batch in loader:
            with obs.step():
                state, out = step(state, batch, key)
        obs.log(epoch, loss=loss)
        obs.snapshot_memory(f'epoch{epoch}')

Artifacts are rewritten on every :meth:`flush` (each ``log`` /
``snapshot_memory`` flushes), so a run killed by a timeout still leaves
analyzable telemetry on disk — the failure mode ``BENCH_r05.json``
(``rc: 124``, no evidence) exposed.
"""

import collections
import contextlib
import json
import os
import sys
import threading
import time

# Safe despite the package-cycle shape: importing ANY obs submodule runs
# the package __init__ first, and that imports probes before run.
from dgmc_tpu.obs import probes as probes_mod
from dgmc_tpu.obs.memory import memory_snapshot
from dgmc_tpu.obs.observe import MetricLogger, StepTimer
from dgmc_tpu.obs.registry import (CompileWatcher, dispatch_table,
                                   padding_bucket_table)


def add_obs_flag(parser):
    """Register the standard ``--obs-dir`` / ``--probes`` flags on an
    argparse parser."""
    parser.add_argument(
        '--obs-dir', '--obs_dir', dest='obs_dir', type=str, default=None,
        help='write run telemetry (metrics.jsonl, timings.json, '
             'memory.json, dispatch.json, trace.json) into this '
             'directory; render it with `python -m dgmc_tpu.obs.report '
             '<dir>`, compare two runs with `python -m dgmc_tpu.obs.diff '
             'A B`')
    parser.add_argument(
        '--probes', action='store_true',
        help='stream in-graph numerics probes (correspondence entropy, '
             'top-k mass, consensus-delta norm, grad norm, non-finite '
             'detection) into the --obs-dir artifacts; off = the lowered '
             'step is byte-identical to a probe-free build')
    parser.add_argument(
        '--watchdog-deadline', '--watchdog_deadline',
        dest='watchdog_deadline', type=float, default=None, metavar='SEC',
        help='arm the run-health watchdog: if no step/compile/section '
             'completes for SEC seconds, or the process receives '
             'SIGTERM/SIGALRM, dump <obs-dir>/hang_report.json '
             '(all-thread tracebacks, the in-flight activity, the last-'
             'completed span) so an rc:124 run is diagnosable')
    parser.add_argument(
        '--fence-deadline', '--fence_deadline',
        dest='fence_deadline', type=float, default=None, metavar='SEC',
        help='deadline on each collective device fence (the epoch-'
             'boundary per-device completion probe): a fence that does '
             'not complete within SEC seconds dumps '
             '<obs-dir>/hang_report.json naming the fence phase/step '
             'and the hosts that never reached it, then exits with '
             'rc 67 (FENCE_TIMEOUT_RC) so the supervisor restarts '
             'elastically instead of the run hanging to rc:124. '
             '--supervise arms it automatically; 0 opts out')
    parser.add_argument(
        '--obs-port', '--obs_port', dest='obs_port', type=int,
        default=None, metavar='PORT',
        help='serve the live telemetry plane on this port '
             '(dgmc_tpu/obs/live.py): GET /healthz (200, or 503 when '
             'the watchdog heartbeat is stale — the same staleness '
             'definition the supervisor applies), GET /metrics '
             '(Prometheus text exposition: streaming step-latency '
             'histogram, throughput, per-label compile counters, '
             'kernel-dispatch outcomes, probe gauges, MFU/intensity '
             'from the last efficiency snapshot), GET /status (the '
             'live timings.json summary). 0 picks a free port; the '
             'chosen port is advertised in heartbeat.json so the '
             'supervisor and obs.aggregate can discover it')
    parser.add_argument(
        '--slo', dest='slo', type=str, default=None, metavar='FILE',
        help='judge the run against a declarative SLO spec (JSON: '
             'availability/latency objectives, optional hits@1 and '
             'goodput floors — see dgmc_tpu/obs/slo.py): error-budget '
             'consumption and multi-window burn rates are computed '
             'live, exported as dgmc_slo_* in /metrics and the slo '
             'section of /status, flushed to <obs-dir>/slo.json, and '
             'a budget exhaustion or fast-burn breach dumps the '
             'flight recorder; requires --obs-dir')
    return parser


#: Probe records kept in memory for the trace timeline; past this the
#: oldest fall off (deque maxlen — metrics.jsonl still holds the full
#: series, and the aggregates cover every event).
MAX_TRACE_PROBES = 20000


class RunObserver:
    """Facade collecting one run's telemetry into ``obs_dir``.

    ``probes=True`` additionally turns on the in-graph numerics probes
    (:mod:`dgmc_tpu.obs.probes`) and streams their records into
    ``metrics.jsonl`` (tagged with the observer's step counter),
    per-probe aggregates into ``timings.json``, and the series timeline
    into ``trace.json``. The observer is constructed before the first
    jitted step runs, which is exactly when the trace-time probe switch
    must be set. The switch is flipped even when ``obs_dir`` is falsy
    (only the SINK needs an artifact dir): in a multi-process run the
    coordinator-gated observers must still trace the SAME program on
    every process — a probe-carrying step on process 0 against a
    probe-free step on process 1 would break SPMD lockstep.
    """

    def __init__(self, obs_dir, probes=False, watchdog_deadline_s=None,
                 watchdog_signals=None, fence_deadline_s=None,
                 host_channel=None, obs_port=None, routes=None):
        self.dir = obs_dir
        self.enabled = bool(obs_dir)
        #: Collective-fence deadline (``--fence-deadline``): every
        #: :meth:`fence_devices` fetch runs under a
        #: :class:`~dgmc_tpu.resilience.distributed_guard.FenceGuard`
        #: that converts a wedged fence into hang_report.json + a
        #: FENCE_TIMEOUT_RC exit instead of an rc:124 hang.
        self.fence_deadline_s = fence_deadline_s or None
        #: Optional :class:`~dgmc_tpu.resilience.distributed_guard.
        #: HostChannel`: completed fences are recorded on it (the
        #: attribution a peer's hang report needs) and its peer table
        #: names the missing hosts when THIS host's fence times out.
        self.host_channel = host_channel
        #: Optional hook called inside the fence guard with the fence's
        #: tag — the injection point of the ``collective-stall@N``
        #: fault (``FaultPlan.before_fence``), kept as a plain callable
        #: so obs does not import the resilience package.
        self.fence_hook = None
        self.timer = StepTimer()
        self._t_start = time.time()
        self._snapshots = []
        self._watcher = None
        self._sections = []
        self._step_index = 0
        self._costs = {}
        self._device_times = {}
        self._fence_records = []
        self._pending_compiles = []
        self.watchdog = None
        self._probe_sink = None
        # _probe_lock: _on_probe runs on jax's host-callback thread while
        # the main thread logs/flushes — both touch the records/aggregates
        # and the metrics file handle.
        self._probe_lock = threading.Lock()
        self._probe_agg = probes_mod.Aggregator()
        self._probe_records = collections.deque(maxlen=MAX_TRACE_PROBES)
        #: Probe records DELIVERED (vs kept in the bounded timeline
        #: deque): `timings.json`/`trace.json` publish the difference
        #: as ``probes_truncated`` so an aggregate over a clipped
        #: window is visibly partial, never silently so.
        self._probe_seen = 0
        self.first_nonfinite = None
        self._probes_enabled_by_me = False
        #: Live plane state: the always-on flight recorder + streaming
        #: latency histogram (both O(1)-memory, armed with the obs
        #: dir), and the optional HTTP endpoints (armed by --obs-port).
        self.flight = None
        self.live_port = None
        self._live_hist = None
        self._server = None
        self._live_gauges = {}
        self._metrics_providers = []
        self._status_sections = {}
        #: Quality plane: the run-level accuracy account (Hits@k / MRR /
        #: loss per scenario, consensus convergence, serve confidence),
        #: flushed as quality.json beside the latency artifacts.
        self.quality = None
        #: SLO/anomaly planes (attach_slo / attach_anomaly): the
        #: error-budget tracker judging this run and the streaming
        #: detector watch arming the flight recorder. Both optional —
        #: absence stays absent in the artifacts.
        self.slo = None
        self.anomaly = None
        self._anomaly_compiles_seen = 0
        self._anomaly_skips_seen = 0
        self._last_efficiency = None
        self._last_activity = time.time()
        self._dispatch_sink = None
        self._compile_sink = None
        self._profiler = None
        if probes:
            self._probes_enabled_by_me = not probes_mod.enabled()
            if self.enabled:
                self._probe_sink = self._on_probe
            probes_mod.enable(self._probe_sink)
        if watchdog_deadline_s and not self.enabled:
            # The hang report needs a directory to land in; accepting
            # the flag silently would reproduce the evidence-free rc:124
            # death the watchdog exists to prevent.
            print('RunObserver: --watchdog-deadline is ignored without '
                  '--obs-dir (hang_report.json needs an obs directory)',
                  file=sys.stderr)
        if obs_port is not None and not self.enabled:
            # Same contract: the flight recorder and the /status
            # endpoint are views over the artifact state an obs dir
            # holds — serving a plane with nothing behind it would
            # report an empty run as healthy forever.
            print('RunObserver: --obs-port is ignored without '
                  '--obs-dir (the live plane serves the obs-dir '
                  'telemetry)', file=sys.stderr)
        # mode='w': an obs dir describes ONE run — a reused --obs-dir must
        # not append a second run's metrics to artifacts the observer
        # rewrites from scratch.
        self._metrics = MetricLogger(
            os.path.join(obs_dir, 'metrics.jsonl') if self.enabled else None,
            mode='w')
        if self.enabled:
            os.makedirs(obs_dir, exist_ok=True)
            from dgmc_tpu.obs import live as live_mod
            from dgmc_tpu.obs import quality as quality_mod
            self._live_mod = live_mod
            self.quality = quality_mod.QualityTracker()
            # Always-on: the ring buffer is O(capacity) memory and a
            # record is one deque append — the trailing context must
            # exist BEFORE anyone knows an anomaly is coming.
            self.flight = live_mod.FlightRecorder(
                os.path.join(obs_dir, 'flight.json'))
            self._live_hist = live_mod.StreamingHistogram()
            # Registry counters are process-lifetime; baseline them here so
            # the artifacts attribute only THIS run's activity (the same
            # scoping CompileWatcher gives compile events).
            self._dispatch_base = self._count_index(dispatch_table())
            self._buckets_base = self._count_index(padding_bucket_table())
            from dgmc_tpu.obs.registry import padding_real_table
            self._real_base = self._count_index(padding_real_table())
            self._watcher = CompileWatcher(
                on_event=self._on_compile_event).__enter__()
            self._dispatch_sink = self._on_dispatch
            from dgmc_tpu.obs.registry import add_dispatch_sink
            add_dispatch_sink(self._dispatch_sink)
            if obs_port is not None:
                # Started BEFORE the watchdog so the bound port can be
                # advertised in every heartbeat from the first poll on.
                def bind(port):
                    return live_mod.TelemetryServer(
                        port, health_fn=self.health,
                        metrics_fn=self.prometheus_metrics,
                        status_fn=self.status, routes=routes,
                        # All interfaces by default (external probers
                        # are the point); DGMC_TPU_OBS_BIND narrows it
                        # (e.g. 127.0.0.1 on multi-tenant machines).
                        host=os.environ.get('DGMC_TPU_OBS_BIND',
                                            '')).start()

                # A failed bind on a FIXED port (already taken — two
                # host processes given the same --obs-port, or a
                # restarted serving worker whose predecessor's socket
                # lingers in TIME_WAIT) retries on an ephemeral port:
                # the plane MOVES instead of dying, and the chosen port
                # is re-advertised through heartbeat.json so the
                # supervisor's /healthz scrape and any endpoint
                # discovery follow it. Only a failed ephemeral bind
                # (the port-0 retry itself refused) degrades to no
                # plane — telemetry must never take the run down.
                try:
                    self._server = bind(obs_port)
                    self.live_port = self._server.port
                except OSError as e:
                    if obs_port:
                        try:
                            self._server = bind(0)
                            self.live_port = self._server.port
                            print(f'RunObserver: port {obs_port} is '
                                  f'taken ({e}); live telemetry plane '
                                  f'moved to ephemeral port '
                                  f'{self.live_port} (advertised in '
                                  f'heartbeat.json)', file=sys.stderr)
                        except OSError as e2:
                            e = e2
                    if self._server is None:
                        print(f'RunObserver: could not bind the live '
                              f'telemetry plane on port {obs_port} '
                              f'({e}); continuing without it',
                              file=sys.stderr)
            if watchdog_deadline_s:
                from dgmc_tpu.obs.watchdog import DEFAULT_SIGNALS, Watchdog
                self.watchdog = Watchdog(
                    os.path.join(obs_dir, 'hang_report.json'),
                    deadline_s=watchdog_deadline_s,
                    context_fn=self._watchdog_context,
                    signals=(DEFAULT_SIGNALS if watchdog_signals is None
                             else watchdog_signals),
                    # Liveness file for the out-of-process run
                    # supervisor (resilience/supervisor.py).
                    heartbeat_path=os.path.join(
                        obs_dir, 'heartbeat.json'),
                    # Endpoint discovery: the supervisor/aggregate read
                    # the host+port from the heartbeat they already
                    # watch. The hostname matters on shared obs
                    # filesystems: a scraper on another machine must
                    # not probe 127.0.0.1 and mistake its OWN local
                    # plane for this host's.
                    advertise=({'port': self.live_port,
                                'host': self._advertise_host()}
                               if self.live_port else None),
                    # Anomaly trigger: every hang-report dump (deadline
                    # or signal path) also dumps the flight recorder.
                    on_dump=self.flight_dump).start()
            self.snapshot_memory('start')

    # -- collection --------------------------------------------------------

    def attach_profiler(self, profiler):
        """Drive a :class:`~dgmc_tpu.obs.trace.ProfileHandle` from this
        observer's step boundaries: each :meth:`step` entry calls
        ``profiler.on_step()`` (arming/stopping a ``--profile-steps``
        window) and the step body runs under
        ``profiler.step_annotation()`` so the exported trace carries
        per-step markers the attribution CLI can normalize by. Works
        even when the observer itself is disabled (profiling does not
        require ``--obs-dir``)."""
        self._profiler = profiler
        return profiler

    @contextlib.contextmanager
    def step(self, fence=None):
        """Time one training/eval step (host-observed; pass ``fence`` a
        device scalar to time actual execution)."""
        prof = self._profiler
        if prof is not None:
            # Window boundary FIRST (it may stop the span), then the
            # annotation (which is a no-op outside an open span).
            prof.on_step()
        ann = (prof.step_annotation(None if not self.enabled
                                    else self._step_index)
               if prof is not None else contextlib.nullcontext())
        if not self.enabled:
            with ann:
                yield
            return
        if self.watchdog is not None:
            self.watchdog.beat('step', self._step_index)
        if self.flight is not None:
            self.flight.record('span-start', phase='step',
                               step=self._step_index)
        self.timer.start()
        try:
            with ann:
                yield
        finally:
            dur = self.timer.stop(fence=fence)
            if self.flight is not None:
                self.flight.record('span-end', phase='step',
                                   step=self._step_index,
                                   duration_s=round(dur, 6))
            if self._live_hist is not None:
                # O(1)-memory latency account for /metrics — the
                # serving-scale counterpart of the timer's full list.
                self._live_hist.observe(dur)
            if self.anomaly is not None:
                self.anomaly.observe('step_latency_s', dur)
            if self.slo is not None:
                # A completed step is an available event; its duration
                # feeds any end-to-end latency objective. Serve paths
                # record their own per-query events instead.
                self.slo.record(True, latency_s=dur)
            self._last_activity = time.time()
            # Probe records are attributed to this counter; with async
            # dispatch the attribution is approximate within the dispatch
            # pipeline depth (see obs/probes.py).
            self._step_index += 1
            if self.watchdog is not None:
                self.watchdog.done()

    def fence_devices(self, value, tag=None, phase='epoch-fence'):
        """Per-device step-completion probe for straggler/skew analysis.

        ``value`` is a jax array from the step's outputs (typically the
        loss — replicated or sharded, its addressable shards cover the
        participating local devices). Each shard is fetched in device
        order; the elapsed time from the most recent step start to each
        fetch completing is that device's cumulative-drain measurement.
        A straggler device records a visibly larger time; devices
        fetched after it inherit its wait (the recorded skew is a lower
        bound — see :mod:`dgmc_tpu.obs.aggregate`). Per-device
        aggregates land in ``timings.json`` (``device_steps``) and one
        record per fence in ``metrics.jsonl``.

        This is also the run's **collective fence**: in a sharded
        program the fetch drains cross-device collectives, so a dead or
        wedged peer blocks it forever — the rc:124 shape. With
        ``fence_deadline_s`` armed the fetch runs under a
        :class:`~dgmc_tpu.resilience.distributed_guard.FenceGuard`
        (miss → ``hang_report.json`` naming this fence and the missing
        hosts → exit ``FENCE_TIMEOUT_RC``), and a completed fence is
        recorded on the host channel so *peers'* reports can name this
        host as arrived. ``tag`` labels the fence (the CLI's epoch
        counter; defaults to the observer's step index).

        Each fetch is a device->host round trip, so call this where the
        loop already fetches (an epoch/eval boundary), not every step on
        a tunneled platform.
        """
        if not self.enabled:
            return None
        import numpy as np
        tag = self._step_index if tag is None else tag
        t0 = self.timer.last_start
        if t0 is None:
            t0 = time.perf_counter()
        times = {}
        try:
            shards = sorted(value.addressable_shards,
                            key=lambda s: s.device.id)
        except AttributeError:   # non-jax input: nothing to fence
            return None
        if self.watchdog is not None:
            self.watchdog.beat('fence', f'{phase}@{tag}')
        if self.flight is not None:
            self.flight.record('span-start', phase='fence',
                               name=f'{phase}@{tag}')
        guard = contextlib.nullcontext()
        if self.fence_deadline_s:
            from dgmc_tpu.resilience.distributed_guard import FenceGuard
            guard = FenceGuard(
                os.path.join(self.dir, 'hang_report.json'),
                self.fence_deadline_s, phase=phase, step=tag,
                channel=self.host_channel,
                context_fn=self._watchdog_context,
                # A fence timeout is an anomaly: dump the flight
                # recorder's trailing context before the rc-67 exit.
                on_dump=self.flight_dump)
        with guard:
            if self.fence_hook is not None:
                # collective-stall@N injection point: the stall happens
                # INSIDE the deadline guard, exactly like a wedged
                # collective would.
                self.fence_hook(tag)
            for shard in shards:
                np.asarray(shard.data)  # blocks until device is done
                times[str(shard.device.id)] = round(
                    time.perf_counter() - t0, 6)
        if self.host_channel is not None:
            self.host_channel.record_fence(phase, tag)
        if self.flight is not None:
            self.flight.record('span-end', phase='fence',
                               name=f'{phase}@{tag}',
                               duration_s=round(
                                   max(times.values(), default=0.0), 6))
        self._last_activity = time.time()
        for dev, dt in times.items():
            self._device_times.setdefault(dev, []).append(dt)
        self._fence_records.append((time.time(), times))
        with self._probe_lock:
            self._metrics.log(self._step_index, device_fence=times)
        if self.watchdog is not None:
            self.watchdog.done()
            self.watchdog.beat('idle')
        return times

    def record_cost(self, name, target, *args, step_time_s=None):
        """Register one program's cost account (``efficiency.json``).

        ``target`` is a jitted callable (with its example ``*args`` —
        lowered once, **not** compiled: one extra trace, no extra XLA
        compile), a ``Lowered``, or a ``Compiled`` (bench.py's AOT path,
        which also yields post-GSPMD collective counts). MFU is derived
        at flush time from ``step_time_s`` when given, else from the
        run's observed step p50. See :mod:`dgmc_tpu.obs.cost`.
        """
        if not self.enabled:
            return None
        from dgmc_tpu.obs import cost as cost_mod
        if self.watchdog is not None:
            self.watchdog.beat('cost', name)
        try:
            summary = cost_mod.cost_summary(target, *args,
                                            step_time_s=step_time_s)
        except Exception as e:
            # A platform that refuses cost analysis must not kill the
            # run being observed; record the refusal instead.
            summary = {'error': f'{type(e).__name__}: {e}'}
        self._costs[name] = summary
        if self.watchdog is not None:
            self.watchdog.done()
        self.flush()
        return summary

    def _on_probe(self, rec):
        """Probe sink (runs on jax's host-callback thread): series ->
        metrics.jsonl, aggregates -> timings.json, timeline ->
        trace.json. Nonfinite checks only hit metrics.jsonl when they
        actually fire (the all-finite flood stays out)."""
        name = rec['probe']
        value = rec['value']
        with self._probe_lock:
            self._probe_agg.add(name, value)
            meta = {k: v for k, v in rec.items()
                    if k not in ('probe', 'value', 'time')}
            if name == 'nonfinite':
                if value:
                    # Callbacks are unordered: attribute the FIRST
                    # offender by (step, static pipeline order), not by
                    # host arrival order.
                    cand = {'step': self._step_index,
                            'stage': rec.get('stage', '?'),
                            'order': rec.get('order', 1 << 30)}
                    cur = self.first_nonfinite
                    if cur is None or ((cand['step'], cand['order'])
                                       < (cur['step'],
                                          cur.get('order', 1 << 30))):
                        self.first_nonfinite = cand
                else:
                    return
            # deque(maxlen=...): O(1) eviction once the timeline cap is
            # hit (metrics.jsonl still holds the full series, and the
            # _probe_seen counter makes the eviction visible as
            # `probes_truncated` in timings.json / trace.json).
            self._probe_records.append(rec)
            self._probe_seen += 1
            self._metrics.log(self._step_index, probe=name, value=value,
                              **meta)
        if name == 'consensus_delta' and self.quality is not None:
            # The refinement loop's per-iteration correction norm feeds
            # the quality plane's iterations-to-converge account (the
            # probe's `step` meta is the consensus iteration index).
            self.quality.observe_consensus(meta.get('step'), value)
        if self.flight is not None:
            try:
                fval = float(value)
            except (TypeError, ValueError):
                fval = value
            self.flight.record('probe', name=name, value=fval, **meta)

    def record_section(self, name, start_s, duration_s):
        """Register one labelled wall-clock span (e.g. a bench section)
        for the ``trace.json`` timeline."""
        if self.enabled:
            self._sections.append((name, start_s, duration_s))
            if self.flight is not None:
                self.flight.record('section', name=name,
                                   duration_s=round(duration_s, 6))
            self._last_activity = time.time()
            if self.watchdog is not None:
                # A completed section is both a heartbeat and the
                # last-completed span a hang report should name.
                self.watchdog.beat('section', name)
                self.watchdog.done()

    def log(self, step, **metrics):
        """Append one record to ``metrics.jsonl`` and refresh the derived
        artifacts."""
        if not self.enabled:
            return
        # Same lock as the probe sink: both sides write the one
        # metrics.jsonl handle, and late probe callbacks can still be
        # draining on jax's host-callback thread while the main thread
        # logs its epoch record.
        with self._probe_lock:
            self._metrics.log(step, **metrics)
        self._last_activity = time.time()
        if self.watchdog is not None:
            # Epoch-boundary host work (eval loops, checkpointing) beats
            # through its log calls, so only genuine stalls trip the
            # deadline.
            self.watchdog.beat('idle')
        self.flush()

    @contextlib.contextmanager
    def compile_label(self, name):
        """Attribute compile events inside the block to ``name`` in
        ``timings.json``'s ``by_label`` breakdown."""
        if not self.enabled:
            yield
            return
        if self.watchdog is not None:
            self.watchdog.beat('compile', name)
        self._pending_compiles.append(name)
        try:
            with self._watcher.label(name):
                yield
        finally:
            if name in self._pending_compiles:
                self._pending_compiles.remove(name)
            if self.watchdog is not None:
                self.watchdog.done()

    def snapshot_memory(self, tag=''):
        """Record a labelled device/host memory snapshot."""
        if not self.enabled:
            return None
        snap = memory_snapshot(tag)
        self._snapshots.append(snap)
        self.flush()
        return snap

    # -- artifacts ---------------------------------------------------------

    @staticmethod
    def _count_index(rows):
        return {tuple(sorted((k, v) for k, v in r.items() if k != 'count')):
                r['count'] for r in rows}

    @staticmethod
    def _since(rows, base):
        """Rows with the baseline counts subtracted (drop zero rows)."""
        out = []
        for r in rows:
            key = tuple(sorted((k, v) for k, v in r.items()
                               if k != 'count'))
            delta = r['count'] - base.get(key, 0)
            if delta > 0:
                out.append(dict(r, count=delta))
        return out

    def _write(self, name, payload):
        path = os.path.join(self.dir, name)
        tmp = path + '.tmp'
        with open(tmp, 'w') as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)

    def write_artifact(self, name, payload):
        """Write one extra JSON artifact into the obs dir (atomic, like
        every built-in artifact). The subsystem hook behind e.g. the
        serving worker's ``capacity.json`` — artifacts the observer does
        not itself compute but that belong in the recorded run."""
        if self.enabled:
            self._write(name, payload)

    def probe_summary(self):
        """Per-probe aggregates ``{name: {count, mean, last, min, max}}``
        (+ ``first_nonfinite`` when a stage went non-finite)."""
        with self._probe_lock:
            return self._probe_agg.summary()

    def device_step_summary(self):
        """Per-device completion aggregates from :meth:`fence_devices`:
        ``{device_id: {count, mean_s, p50_s, max_s, last_s}}``."""
        from dgmc_tpu.obs.observe import percentile
        out = {}
        for dev, times in sorted(self._device_times.items()):
            ts = sorted(times)
            out[dev] = {
                'count': len(ts),
                'mean_s': round(sum(ts) / len(ts), 6),
                'p50_s': round(percentile(ts, 0.5), 6),
                'max_s': round(ts[-1], 6),
                'last_s': round(times[-1], 6),
            }
        return out

    # -- live plane --------------------------------------------------------

    @staticmethod
    def _advertise_host():
        """Hostname peers should scrape this plane at (loopback when
        the hostname cannot be determined — the single-host case)."""
        import socket
        try:
            return socket.gethostname() or '127.0.0.1'
        except OSError:
            return '127.0.0.1'

    def _on_dispatch(self, kernel, outcome, reason):
        """Registry dispatch sink: every kernel decision lands in the
        flight recorder as it happens."""
        if self.flight is not None:
            self.flight.record('dispatch', kernel=kernel,
                               outcome=outcome, reason=reason)

    def _on_compile_event(self, rec):
        """CompileWatcher event sink (runs under the listener lock:
        keep it to one ring append)."""
        if self.flight is not None:
            self.flight.record('compile', compile_kind=rec.get('kind'),
                               duration_s=rec.get('duration_s'),
                               label=rec.get('label'))

    def set_gauge(self, name, value):
        """Publish one named live gauge (e.g. the guard's
        ``skip_count``/``consec_bad`` counters fetched at the CLI's
        print boundary): shown in ``/healthz`` and exported as
        ``dgmc_<name>`` in ``/metrics``."""
        if not self.enabled:
            return
        self._live_gauges[str(name)] = value

    def flight_dump(self, reason, extra=None):
        """Dump the flight recorder now (``flight.json``); the anomaly
        trigger shared by the watchdog, the fence guard and the
        rollback guard. No-op (returns ``None``) when disabled; never
        raises (and must not take locks — the watchdog may call it on
        the signal path)."""
        if self.flight is None:
            return None
        return self.flight.dump(reason, extra=extra)

    def attach_slo(self, spec_or_path):
        """Arm the SLO plane (:mod:`dgmc_tpu.obs.slo`): accepts a spec
        file path (the ``--slo`` flag's value), a raw spec dict, or a
        built :class:`~dgmc_tpu.obs.slo.SloSpec`. The tracker joins
        ``/metrics`` (``dgmc_slo_*``), ``/status`` (``slo`` section),
        is flushed to ``slo.json`` by every :meth:`flush`, and dumps
        the flight recorder on budget exhaustion / burn alerts / floor
        breaches. ``None`` input or a disabled observer is a no-op —
        the experiment CLIs pass ``args.slo`` through unconditionally.
        Raises ``ValueError`` on a malformed spec (a CLI given a bad
        SLO must fail at startup, not judge nothing)."""
        if spec_or_path is None or not self.enabled:
            return None
        from dgmc_tpu.obs.slo import SloSpec, SloTracker, load_slo_spec
        if isinstance(spec_or_path, SloSpec):
            spec = spec_or_path
        elif isinstance(spec_or_path, dict):
            spec = SloSpec(spec_or_path)
        else:
            spec = load_slo_spec(spec_or_path)
        self.slo = SloTracker(spec, on_breach=self._on_slo_breach)
        self.add_metrics_provider(self.slo.metric_families)
        self.add_status_section('slo', self.slo.status)
        return self.slo

    def _on_slo_breach(self, kind, detail):
        """SLO breach hook: capture the trailing context the moment
        the budget dies (rate-limited by the tracker)."""
        self.flight_dump(f'slo:{kind}', extra=detail)

    def attach_anomaly(self, capacity=256):
        """Arm the streaming anomaly watch
        (:mod:`dgmc_tpu.obs.anomaly`): :meth:`step` feeds
        ``step_latency_s``, :meth:`flush` feeds per-flush compile-event
        deltas and writes ``anomalies.json``; subsystems feed their own
        signals through ``observer.anomaly.observe``. A detected spike
        or sustained shift dumps the flight recorder (rate-limited per
        signal) — the trailing context of a silent degradation is
        captured before anyone asks."""
        if not self.enabled:
            return None
        from dgmc_tpu.obs.anomaly import AnomalyWatch
        self.anomaly = AnomalyWatch(capacity=capacity,
                                    on_anomaly=self._on_anomaly)
        self.add_metrics_provider(self.anomaly.metric_families)
        self.add_status_section('anomaly', self.anomaly.counters)
        return self.anomaly

    def _on_anomaly(self, event):
        """Anomaly hook: one flight dump per excursion (the watch
        rate-limits per signal)."""
        self.flight_dump(f'anomaly:{event["signal"]}', extra=event)

    def _recovery_summary(self):
        """Condensed supervisor state for ``/healthz``: a supervised
        child's obs dir is ``<root>/attempt_<k>[/host_<i>]`` and
        ``recovery.json`` lives at the root — walk up only through
        those supervisor-named levels so an unrelated file is never
        picked up."""
        cur = os.path.abspath(self.dir)
        for _ in range(3):
            path = os.path.join(cur, 'recovery.json')
            try:
                with open(path) as f:
                    rec = json.load(f)
            except (OSError, ValueError):
                rec = None
            if rec:
                return {'outcome': rec.get('outcome'),
                        'restarts': rec.get('restarts'),
                        'degradations': len(rec.get('degradations', [])),
                        'elastic': len(rec.get('elastic', []))}
            name = os.path.basename(cur)
            if not (name.startswith('attempt_')
                    or name.startswith('host_')):
                break
            cur = os.path.dirname(cur)
        return None

    def health(self):
        """The ``/healthz`` payload. ``healthy`` goes false — the
        endpoint answers 503 — when the watchdog heartbeat is older
        than ``STALE_AFTER_FACTOR x deadline``, the SAME definition
        the supervisor applies to the heartbeat file: one health
        verdict, two vantage points. Without an armed deadline there
        is no staleness definition and the plane reports healthy."""
        now = time.time()
        wd = self.watchdog
        deadline = wd.deadline_s if wd is not None else None
        last = wd._last_event if wd is not None else self._last_activity
        age = now - last
        stale_after = (self._live_mod.STALE_AFTER_FACTOR * deadline
                       if deadline else None)
        out = {
            'healthy': stale_after is None or age <= stale_after,
            'time': now,
            'pid': os.getpid(),
            'port': self.live_port,
            'heartbeat_age_s': round(age, 3),
            'stale_after_s': stale_after,
            'steps_completed': self._step_index,
        }
        if wd is not None:
            in_flight = dict(wd._in_flight)
            in_flight['since_s'] = round(
                now - in_flight.pop('since'), 3)
            out['in_flight'] = in_flight
            out['watchdog_deadline_s'] = deadline
            out['hang_dumps'] = wd.dump_count
        if self._live_gauges:
            out['gauges'] = dict(self._live_gauges)
        if self.flight is not None:
            out['flight'] = self.flight.counters()
        recovery = self._recovery_summary()
        if recovery:
            out['recovery'] = recovery
        return out

    def _efficiency_headline(self):
        """(mfu, arith_intensity) from the last flushed efficiency
        snapshot, the same headline convention ``obs.report`` uses."""
        eff = self._last_efficiency or {}
        mfu = eff.get('mfu')
        intensity = None
        programs = eff.get('programs', {})
        for name in ('train_step', *sorted(programs)):
            ai = programs.get(name, {}).get('arith_intensity')
            if ai is not None:
                intensity = ai
                break
        return mfu, intensity

    def prometheus_metrics(self):
        """The ``/metrics`` exposition text (Prometheus 0.0.4)."""
        live = self._live_mod
        steps = self.timer.summary()
        health = self.health()
        families = [
            ('dgmc_up', 'gauge', 'Run observer alive.', [('', {}, 1)]),
            ('dgmc_healthy', 'gauge',
             'Health verdict (the /healthz 200-vs-503 bit).',
             [('', {}, 1 if health['healthy'] else 0)]),
            ('dgmc_heartbeat_age_seconds', 'gauge',
             'Seconds since the last watchdog heartbeat event.',
             [('', {}, health['heartbeat_age_s'])]),
            ('dgmc_steps_total', 'counter', 'Completed steps.',
             [('', {}, self._step_index)]),
            live.histogram_family(
                'dgmc_step_latency_seconds',
                'Step wall-clock latency (streaming fixed buckets).',
                self._live_hist.snapshot()),
        ]
        if steps.get('mean_s'):
            families.append((
                'dgmc_step_throughput_steps_per_sec', 'gauge',
                'Reciprocal mean step time over the run.',
                [('', {}, 1.0 / steps['mean_s'])]))
        comp = self._watcher.summary() if self._watcher else {}
        by_label = comp.get('by_label') or {}
        if by_label:
            families.append((
                'dgmc_compile_events_total', 'counter',
                'XLA compile events (incl. cache hits) per label.',
                [('', {'label': lb}, d['events'])
                 for lb, d in sorted(by_label.items())]))
            families.append((
                'dgmc_compile_seconds_total', 'counter',
                'XLA compile seconds per label.',
                [('', {'label': lb}, d['compile_s'])
                 for lb, d in sorted(by_label.items())]))
        rows = self._since(dispatch_table(), self._dispatch_base)
        if rows:
            families.append((
                'dgmc_kernel_dispatch_total', 'counter',
                'Kernel-dispatch decisions by site/outcome/reason.',
                [('', {'kernel': r.get('kernel', '?'),
                       'outcome': r.get('outcome', '?'),
                       'reason': r.get('reason', '?')}, r['count'])
                 for r in rows]))
        probe_summary = self.probe_summary()
        if probe_summary:
            last_samples, count_samples = [], []
            for name, agg in sorted(probe_summary.items()):
                count_samples.append(
                    ('', {'probe': name}, agg.get('count', 0)))
                if isinstance(agg.get('last'), (int, float)):
                    last_samples.append(
                        ('', {'probe': name}, agg['last']))
            families.append((
                'dgmc_probe_events_total', 'counter',
                'In-graph probe events per probe.', count_samples))
            if last_samples:
                families.append((
                    'dgmc_probe_last', 'gauge',
                    'Most recent value per in-graph probe.',
                    last_samples))
        mfu, intensity = self._efficiency_headline()
        if mfu is not None:
            families.append((
                'dgmc_mfu', 'gauge',
                'Model FLOPs utilization (last efficiency snapshot).',
                [('', {}, mfu)]))
        if intensity is not None:
            families.append((
                'dgmc_arith_intensity', 'gauge',
                'Achieved arithmetic intensity, FLOPs/byte (last '
                'efficiency snapshot).', [('', {}, intensity)]))
        if self.flight is not None:
            counters = self.flight.counters()
            families.append((
                'dgmc_flight_events_total', 'counter',
                'Events recorded by the flight recorder.',
                [('', {}, counters['events_seen'])]))
            families.append((
                'dgmc_flight_events_dropped_total', 'counter',
                'Flight-recorder events evicted by the ring cap.',
                [('', {}, counters['events_truncated'])]))
            families.append((
                'dgmc_flight_dumps_total', 'counter',
                'flight.json anomaly dumps.',
                [('', {}, counters['dumps'])]))
        for name, value in sorted(self._live_gauges.items()):
            if isinstance(value, (int, float)):
                families.append((
                    f'dgmc_{name}', 'gauge',
                    f'Run-published gauge {name}.', [('', {}, value)]))
        for provider in self._metrics_providers:
            families.extend(provider() or [])
        return live.prometheus_exposition(families)

    def add_metrics_provider(self, provider):
        """Register a 0-arg callable returning extra metric families
        (the ``prometheus_exposition`` ``(name, type, help, samples)``
        shape) appended to every ``/metrics`` scrape — how subsystems
        with their own labelled counters (the serve plane's per-class
        query errors and per-stage qtrace histograms) join the
        exposition without the observer knowing their schema. A
        provider that raises fails the scrape with the generic 500,
        exactly like the built-in callbacks."""
        if not callable(provider):
            raise TypeError(f'metrics provider must be callable: '
                            f'{provider!r}')
        self._metrics_providers.append(provider)
        return self

    def add_status_section(self, name, fn):
        """Register a 0-arg callable whose payload joins every
        ``/status`` scrape under ``name`` — how the serve plane folds
        the ``qtrace_summary.json`` block into the same response as the
        timing account ("how fast AND how good" in one scrape). A
        section that raises degrades to an ``{'error': ...}`` stub
        instead of failing the whole status page."""
        if not callable(fn):
            raise TypeError(f'status section must be callable: {fn!r}')
        self._status_sections[name] = fn
        return self

    def quality_eval(self, scenario, summary=None, step=None, **metrics):
        """Record one eval summary on the quality plane (no-op without
        an obs dir). Accepts either the ``eval_summary`` dict or named
        fractions directly."""
        if self.quality is None:
            return
        payload = dict(summary) if summary else {}
        payload.update(metrics)
        self.quality.observe_eval(scenario, payload, step=step)

    def status(self):
        """The ``/status`` payload: the timing account at the top level
        (scrape compatibility — ``compile``/``steps``/... keep their
        place) plus the quality block and any registered sections."""
        out = self.timings()
        if self.quality is not None:
            out['quality'] = self.quality.payload()
        for name, fn in self._status_sections.items():
            try:
                out[name] = fn()
            except Exception as e:  # degrade, don't 500 the scrape
                out[name] = {'error': f'{type(e).__name__}: {e}'}
        return out

    def _watchdog_context(self):
        """Run-state snapshot for the hang report (called from the
        watchdog thread; cached there for the lock-free signal path)."""
        ctx = {
            'steps_completed': self._step_index,
            'steps': self.timer.summary(),
            'pending_compiles': list(self._pending_compiles),
            'compile_events': (self._watcher.count()
                               if self._watcher else 0),
            'dispatch_tail': self._since(dispatch_table(),
                                         self._dispatch_base)[-8:],
        }
        if self.timer.spans:
            t0, dur = self.timer.spans[-1]
            ctx['last_step_span'] = {'start': t0,
                                     'duration_s': round(dur, 6)}
        if self._sections:
            ctx['sections'] = [
                {'name': n, 'start': t0, 'duration_s': round(d, 3)}
                for n, t0, d in self._sections[-8:]]
        return ctx

    def _padding_rows(self):
        """This run's padding-bucket rows with the real (pre-padding)
        totals merged in (``real_nodes_s`` etc.) — the delta baselines
        are applied per family FIRST, then joined, so the run-scoped
        counts and the run-scoped real totals describe the same
        collations."""
        from dgmc_tpu.obs import goodput as goodput_mod
        from dgmc_tpu.obs.registry import padding_real_table
        return goodput_mod.merge_real_rows(
            self._since(padding_bucket_table(), self._buckets_base),
            self._since(padding_real_table(), self._real_base))

    def goodput_payload(self):
        """The ``goodput.json`` body for this run: pad waste + goodput
        ratio from the merged padding rows, composed with the last
        efficiency snapshot's per-stage FLOPs (``train_step``-first
        headline convention) when the run recorded a cost account.
        ``None`` when nothing recorded a real-size account."""
        from dgmc_tpu.obs import goodput as goodput_mod
        stages = None
        programs = (self._last_efficiency or {}).get('programs') or {}
        ts = programs.get('train_step') or {}
        stages = ts.get('stages')
        if not stages:
            for p in programs.values():
                if p.get('stages'):
                    stages = p['stages']
                    break
        return goodput_mod.payload_from_rows(self._padding_rows(),
                                             stages=stages)

    def timings(self):
        out = {
            'wall_s': round(time.time() - self._t_start, 3),
            'argv': sys.argv,
            'steps': self.timer.summary(),
            'compile': self._watcher.summary() if self._watcher else {},
            'padding_buckets': self._padding_rows(),
        }
        if self._device_times:
            out['device_steps'] = self.device_step_summary()
        if self._probe_agg:
            out['probes'] = self.probe_summary()
            # The trace timeline keeps a bounded window of the probe
            # series (MAX_TRACE_PROBES); publish how much the window
            # clipped so a consumer of trace.json knows the timeline
            # is partial (the aggregates above still cover everything).
            with self._probe_lock:
                out['probes_truncated'] = max(
                    0, self._probe_seen - len(self._probe_records))
        if self.flight is not None:
            # Same silent-cap contract for the flight ring: the counts
            # make an evicted window visible in timings.json.
            counters = self.flight.counters()
            out['flight'] = counters
            out['events_truncated'] = counters['events_truncated']
        if self.first_nonfinite is not None:
            out['first_nonfinite'] = self.first_nonfinite
        return out

    def flush(self):
        """Rewrite ``timings.json`` / ``memory.json`` / ``dispatch.json``
        / ``trace.json`` from current state (atomic per file)."""
        if not self.enabled:
            return
        self._write('timings.json', self.timings())
        quality_payload = None
        if self.quality is not None:
            quality_payload = self.quality.payload()
            self._write('quality.json', quality_payload)
        self._write('memory.json', {'snapshots': self._snapshots})
        self._write('dispatch.json', {'counts': self._since(
            dispatch_table(), self._dispatch_base)})
        if self._costs:
            from dgmc_tpu.obs import cost as cost_mod
            steps = self.timer.summary()
            payload = cost_mod.efficiency_payload(
                self._costs, fallback_step_time_s=steps.get('p50_s'))
            # The live plane's "last efficiency snapshot": /metrics
            # serves MFU/intensity from exactly what efficiency.json
            # last said.
            self._last_efficiency = payload
            self._write('efficiency.json', payload)
        # After the efficiency write so the goodput ratio composes with
        # the freshest per-stage FLOP attribution. Absence stays absent:
        # a run with no real-size padding account writes no goodput.json
        # (the diff's lost-account rule needs that honesty).
        goodput = self.goodput_payload()
        if goodput is not None:
            self._write('goodput.json', goodput)
        if self.anomaly is not None:
            # Per-flush compile-event delta: 0 once warm, so a mid-run
            # recompile burst (padding-bucket churn) standardizes into
            # an obvious spike against the quiet history.
            events = self._watcher.count() if self._watcher else 0
            self.anomaly.observe(
                'compile_events', events - self._anomaly_compiles_seen)
            self._anomaly_compiles_seen = events
            # Guard skips (the rollback guard's published gauge, when
            # the CLI publishes one): per-flush delta — a burst of
            # skipped steps is a numerics event worth a flight dump.
            skips = self._live_gauges.get('guard_skip_count')
            if isinstance(skips, (int, float)):
                self.anomaly.observe(
                    'guard_skips', skips - self._anomaly_skips_seen)
                self._anomaly_skips_seen = skips
        if self.slo is not None:
            # Floor gauges track the freshest plane headlines; a plane
            # that stopped reporting CLEARS its gauge (absence stays
            # absent — a floor cannot pass on a stale value). Flushing
            # is also a judgment pass: the snapshot runs check(), so
            # breach hooks fire at flush cadence even when nothing
            # scrapes /metrics.
            headline = ((quality_payload or {}).get('headline')
                        or {}).get('metrics') or {}
            self.slo.update_gauges(
                hits1=headline.get('hits1'),
                goodput=(goodput or {}).get('goodput_ratio'))
            self._write('slo.json', self.slo.snapshot())
        if self.anomaly is not None:
            self._write('anomalies.json', self.anomaly.snapshot())
        from dgmc_tpu.obs.trace import export_chrome_trace
        with self._probe_lock:
            # Snapshot: the deque may receive callback-thread appends
            # while the exporter iterates.
            probe_records = list(self._probe_records)
            probes_truncated = max(
                0, self._probe_seen - len(probe_records))
        export_chrome_trace(
            os.path.join(self.dir, 'trace.json'),
            step_spans=self.timer.spans,
            probe_records=probe_records,
            compile_events=self._watcher.events if self._watcher else (),
            sections=self._sections,
            device_fences=self._fence_records,
            # The timeline is a bounded window over the probe series;
            # the count makes the clipping visible to trace consumers.
            metadata={'argv': sys.argv,
                      'probes_truncated': probes_truncated})

    def close(self):
        # Probe teardown first, and independent of `enabled`: a
        # coordinator-gated observer (obs_dir=None) still flipped the
        # global switch in __init__ and must restore it.
        if self._probe_sink is not None or self._probes_enabled_by_me:
            # Drain in-flight debug callbacks BEFORE detaching the sink:
            # on async-dispatch backends the last step's probe records
            # (possibly including the run's only non-finite) are still
            # queued on jax's host-callback thread when the training
            # loop returns.
            try:
                import jax
                jax.effects_barrier()
            except Exception:
                pass
        if self._probe_sink is not None:
            probes_mod.remove_sink(self._probe_sink)
            self._probe_sink = None
        if self._probes_enabled_by_me:
            probes_mod.disable()
            self._probes_enabled_by_me = False
        if not self.enabled:
            return
        if self.watchdog is not None:
            self.watchdog.close()
            self.watchdog = None
        if self._dispatch_sink is not None:
            from dgmc_tpu.obs.registry import remove_dispatch_sink
            remove_dispatch_sink(self._dispatch_sink)
            self._dispatch_sink = None
        self.snapshot_memory('end')
        self.flush()
        self._metrics.close()
        self._watcher.close()
        if self._server is not None:
            # Last: the plane keeps answering through the final flush,
            # so a prober never sees the port die before the artifacts
            # settle.
            self._server.close()
            self._server = None
        self.enabled = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
