"""The ``--obs-dir`` facade: one flag, four artifacts.

:class:`RunObserver` bundles the metric sink, the step timer, the compile
watcher, memory snapshots and the dispatch-counter snapshot behind a
single directory:

- ``metrics.jsonl`` — one record per :meth:`RunObserver.log` call, plus
  (with probes on) one record per in-graph probe event.
- ``timings.json``  — step-time percentiles + compile-event summary +
  run wall-clock + per-probe aggregates.
- ``memory.json``   — labelled device/host memory snapshots + the peak.
- ``dispatch.json`` — the kernel-dispatch outcome table.
- ``trace.json``    — Chrome-trace/Perfetto timeline of steps, compiles
  and probe series (:mod:`dgmc_tpu.obs.trace`).
- ``efficiency.json`` — FLOPs/bytes/per-stage attribution and MFU for
  the programs the run registered via :meth:`RunObserver.record_cost`
  (:mod:`dgmc_tpu.obs.cost`).
- ``hang_report.json`` — written only when the run stalls past the
  watchdog deadline or dies to SIGTERM/SIGALRM
  (:mod:`dgmc_tpu.obs.watchdog`).

Every method is a no-op when constructed with a falsy directory, so CLIs
call the observer unconditionally::

    obs = RunObserver(args.obs_dir)      # None => disabled
    with obs:
        for batch in loader:
            with obs.step():
                state, out = step(state, batch, key)
        obs.log(epoch, loss=loss)
        obs.snapshot_memory(f'epoch{epoch}')

Artifacts are rewritten on every :meth:`flush` (each ``log`` /
``snapshot_memory`` flushes), so a run killed by a timeout still leaves
analyzable telemetry on disk — the failure mode ``BENCH_r05.json``
(``rc: 124``, no evidence) exposed.
"""

import collections
import contextlib
import json
import os
import sys
import threading
import time

# Safe despite the package-cycle shape: importing ANY obs submodule runs
# the package __init__ first, and that imports probes before run.
from dgmc_tpu.obs import probes as probes_mod
from dgmc_tpu.obs.memory import memory_snapshot
from dgmc_tpu.obs.observe import MetricLogger, StepTimer
from dgmc_tpu.obs.registry import (CompileWatcher, dispatch_table,
                                   padding_bucket_table)


def add_obs_flag(parser):
    """Register the standard ``--obs-dir`` / ``--probes`` flags on an
    argparse parser."""
    parser.add_argument(
        '--obs-dir', '--obs_dir', dest='obs_dir', type=str, default=None,
        help='write run telemetry (metrics.jsonl, timings.json, '
             'memory.json, dispatch.json, trace.json) into this '
             'directory; render it with `python -m dgmc_tpu.obs.report '
             '<dir>`, compare two runs with `python -m dgmc_tpu.obs.diff '
             'A B`')
    parser.add_argument(
        '--probes', action='store_true',
        help='stream in-graph numerics probes (correspondence entropy, '
             'top-k mass, consensus-delta norm, grad norm, non-finite '
             'detection) into the --obs-dir artifacts; off = the lowered '
             'step is byte-identical to a probe-free build')
    parser.add_argument(
        '--watchdog-deadline', '--watchdog_deadline',
        dest='watchdog_deadline', type=float, default=None, metavar='SEC',
        help='arm the run-health watchdog: if no step/compile/section '
             'completes for SEC seconds, or the process receives '
             'SIGTERM/SIGALRM, dump <obs-dir>/hang_report.json '
             '(all-thread tracebacks, the in-flight activity, the last-'
             'completed span) so an rc:124 run is diagnosable')
    parser.add_argument(
        '--fence-deadline', '--fence_deadline',
        dest='fence_deadline', type=float, default=None, metavar='SEC',
        help='deadline on each collective device fence (the epoch-'
             'boundary per-device completion probe): a fence that does '
             'not complete within SEC seconds dumps '
             '<obs-dir>/hang_report.json naming the fence phase/step '
             'and the hosts that never reached it, then exits with '
             'rc 67 (FENCE_TIMEOUT_RC) so the supervisor restarts '
             'elastically instead of the run hanging to rc:124. '
             '--supervise arms it automatically; 0 opts out')
    return parser


#: Probe records kept in memory for the trace timeline; past this the
#: oldest fall off (deque maxlen — metrics.jsonl still holds the full
#: series, and the aggregates cover every event).
MAX_TRACE_PROBES = 20000


class RunObserver:
    """Facade collecting one run's telemetry into ``obs_dir``.

    ``probes=True`` additionally turns on the in-graph numerics probes
    (:mod:`dgmc_tpu.obs.probes`) and streams their records into
    ``metrics.jsonl`` (tagged with the observer's step counter),
    per-probe aggregates into ``timings.json``, and the series timeline
    into ``trace.json``. The observer is constructed before the first
    jitted step runs, which is exactly when the trace-time probe switch
    must be set. The switch is flipped even when ``obs_dir`` is falsy
    (only the SINK needs an artifact dir): in a multi-process run the
    coordinator-gated observers must still trace the SAME program on
    every process — a probe-carrying step on process 0 against a
    probe-free step on process 1 would break SPMD lockstep.
    """

    def __init__(self, obs_dir, probes=False, watchdog_deadline_s=None,
                 watchdog_signals=None, fence_deadline_s=None,
                 host_channel=None):
        self.dir = obs_dir
        self.enabled = bool(obs_dir)
        #: Collective-fence deadline (``--fence-deadline``): every
        #: :meth:`fence_devices` fetch runs under a
        #: :class:`~dgmc_tpu.resilience.distributed_guard.FenceGuard`
        #: that converts a wedged fence into hang_report.json + a
        #: FENCE_TIMEOUT_RC exit instead of an rc:124 hang.
        self.fence_deadline_s = fence_deadline_s or None
        #: Optional :class:`~dgmc_tpu.resilience.distributed_guard.
        #: HostChannel`: completed fences are recorded on it (the
        #: attribution a peer's hang report needs) and its peer table
        #: names the missing hosts when THIS host's fence times out.
        self.host_channel = host_channel
        #: Optional hook called inside the fence guard with the fence's
        #: tag — the injection point of the ``collective-stall@N``
        #: fault (``FaultPlan.before_fence``), kept as a plain callable
        #: so obs does not import the resilience package.
        self.fence_hook = None
        self.timer = StepTimer()
        self._t_start = time.time()
        self._snapshots = []
        self._watcher = None
        self._sections = []
        self._step_index = 0
        self._costs = {}
        self._device_times = {}
        self._fence_records = []
        self._pending_compiles = []
        self.watchdog = None
        self._probe_sink = None
        # _probe_lock: _on_probe runs on jax's host-callback thread while
        # the main thread logs/flushes — both touch the records/aggregates
        # and the metrics file handle.
        self._probe_lock = threading.Lock()
        self._probe_agg = probes_mod.Aggregator()
        self._probe_records = collections.deque(maxlen=MAX_TRACE_PROBES)
        self.first_nonfinite = None
        self._probes_enabled_by_me = False
        if probes:
            self._probes_enabled_by_me = not probes_mod.enabled()
            if self.enabled:
                self._probe_sink = self._on_probe
            probes_mod.enable(self._probe_sink)
        if watchdog_deadline_s and not self.enabled:
            # The hang report needs a directory to land in; accepting
            # the flag silently would reproduce the evidence-free rc:124
            # death the watchdog exists to prevent.
            print('RunObserver: --watchdog-deadline is ignored without '
                  '--obs-dir (hang_report.json needs an obs directory)',
                  file=sys.stderr)
        # mode='w': an obs dir describes ONE run — a reused --obs-dir must
        # not append a second run's metrics to artifacts the observer
        # rewrites from scratch.
        self._metrics = MetricLogger(
            os.path.join(obs_dir, 'metrics.jsonl') if self.enabled else None,
            mode='w')
        if self.enabled:
            os.makedirs(obs_dir, exist_ok=True)
            # Registry counters are process-lifetime; baseline them here so
            # the artifacts attribute only THIS run's activity (the same
            # scoping CompileWatcher gives compile events).
            self._dispatch_base = self._count_index(dispatch_table())
            self._buckets_base = self._count_index(padding_bucket_table())
            self._watcher = CompileWatcher().__enter__()
            if watchdog_deadline_s:
                from dgmc_tpu.obs.watchdog import DEFAULT_SIGNALS, Watchdog
                self.watchdog = Watchdog(
                    os.path.join(obs_dir, 'hang_report.json'),
                    deadline_s=watchdog_deadline_s,
                    context_fn=self._watchdog_context,
                    signals=(DEFAULT_SIGNALS if watchdog_signals is None
                             else watchdog_signals),
                    # Liveness file for the out-of-process run
                    # supervisor (resilience/supervisor.py).
                    heartbeat_path=os.path.join(
                        obs_dir, 'heartbeat.json')).start()
            self.snapshot_memory('start')

    # -- collection --------------------------------------------------------

    @contextlib.contextmanager
    def step(self, fence=None):
        """Time one training/eval step (host-observed; pass ``fence`` a
        device scalar to time actual execution)."""
        if not self.enabled:
            yield
            return
        if self.watchdog is not None:
            self.watchdog.beat('step', self._step_index)
        self.timer.start()
        try:
            yield
        finally:
            self.timer.stop(fence=fence)
            # Probe records are attributed to this counter; with async
            # dispatch the attribution is approximate within the dispatch
            # pipeline depth (see obs/probes.py).
            self._step_index += 1
            if self.watchdog is not None:
                self.watchdog.done()

    def fence_devices(self, value, tag=None, phase='epoch-fence'):
        """Per-device step-completion probe for straggler/skew analysis.

        ``value`` is a jax array from the step's outputs (typically the
        loss — replicated or sharded, its addressable shards cover the
        participating local devices). Each shard is fetched in device
        order; the elapsed time from the most recent step start to each
        fetch completing is that device's cumulative-drain measurement.
        A straggler device records a visibly larger time; devices
        fetched after it inherit its wait (the recorded skew is a lower
        bound — see :mod:`dgmc_tpu.obs.aggregate`). Per-device
        aggregates land in ``timings.json`` (``device_steps``) and one
        record per fence in ``metrics.jsonl``.

        This is also the run's **collective fence**: in a sharded
        program the fetch drains cross-device collectives, so a dead or
        wedged peer blocks it forever — the rc:124 shape. With
        ``fence_deadline_s`` armed the fetch runs under a
        :class:`~dgmc_tpu.resilience.distributed_guard.FenceGuard`
        (miss → ``hang_report.json`` naming this fence and the missing
        hosts → exit ``FENCE_TIMEOUT_RC``), and a completed fence is
        recorded on the host channel so *peers'* reports can name this
        host as arrived. ``tag`` labels the fence (the CLI's epoch
        counter; defaults to the observer's step index).

        Each fetch is a device->host round trip, so call this where the
        loop already fetches (an epoch/eval boundary), not every step on
        a tunneled platform.
        """
        if not self.enabled:
            return None
        import numpy as np
        tag = self._step_index if tag is None else tag
        t0 = self.timer.last_start
        if t0 is None:
            t0 = time.perf_counter()
        times = {}
        try:
            shards = sorted(value.addressable_shards,
                            key=lambda s: s.device.id)
        except AttributeError:   # non-jax input: nothing to fence
            return None
        if self.watchdog is not None:
            self.watchdog.beat('fence', f'{phase}@{tag}')
        guard = contextlib.nullcontext()
        if self.fence_deadline_s:
            from dgmc_tpu.resilience.distributed_guard import FenceGuard
            guard = FenceGuard(
                os.path.join(self.dir, 'hang_report.json'),
                self.fence_deadline_s, phase=phase, step=tag,
                channel=self.host_channel,
                context_fn=self._watchdog_context)
        with guard:
            if self.fence_hook is not None:
                # collective-stall@N injection point: the stall happens
                # INSIDE the deadline guard, exactly like a wedged
                # collective would.
                self.fence_hook(tag)
            for shard in shards:
                np.asarray(shard.data)  # blocks until device is done
                times[str(shard.device.id)] = round(
                    time.perf_counter() - t0, 6)
        if self.host_channel is not None:
            self.host_channel.record_fence(phase, tag)
        for dev, dt in times.items():
            self._device_times.setdefault(dev, []).append(dt)
        self._fence_records.append((time.time(), times))
        with self._probe_lock:
            self._metrics.log(self._step_index, device_fence=times)
        if self.watchdog is not None:
            self.watchdog.done()
            self.watchdog.beat('idle')
        return times

    def record_cost(self, name, target, *args, step_time_s=None):
        """Register one program's cost account (``efficiency.json``).

        ``target`` is a jitted callable (with its example ``*args`` —
        lowered once, **not** compiled: one extra trace, no extra XLA
        compile), a ``Lowered``, or a ``Compiled`` (bench.py's AOT path,
        which also yields post-GSPMD collective counts). MFU is derived
        at flush time from ``step_time_s`` when given, else from the
        run's observed step p50. See :mod:`dgmc_tpu.obs.cost`.
        """
        if not self.enabled:
            return None
        from dgmc_tpu.obs import cost as cost_mod
        if self.watchdog is not None:
            self.watchdog.beat('cost', name)
        try:
            summary = cost_mod.cost_summary(target, *args,
                                            step_time_s=step_time_s)
        except Exception as e:
            # A platform that refuses cost analysis must not kill the
            # run being observed; record the refusal instead.
            summary = {'error': f'{type(e).__name__}: {e}'}
        self._costs[name] = summary
        if self.watchdog is not None:
            self.watchdog.done()
        self.flush()
        return summary

    def _on_probe(self, rec):
        """Probe sink (runs on jax's host-callback thread): series ->
        metrics.jsonl, aggregates -> timings.json, timeline ->
        trace.json. Nonfinite checks only hit metrics.jsonl when they
        actually fire (the all-finite flood stays out)."""
        name = rec['probe']
        value = rec['value']
        with self._probe_lock:
            self._probe_agg.add(name, value)
            meta = {k: v for k, v in rec.items()
                    if k not in ('probe', 'value', 'time')}
            if name == 'nonfinite':
                if value:
                    # Callbacks are unordered: attribute the FIRST
                    # offender by (step, static pipeline order), not by
                    # host arrival order.
                    cand = {'step': self._step_index,
                            'stage': rec.get('stage', '?'),
                            'order': rec.get('order', 1 << 30)}
                    cur = self.first_nonfinite
                    if cur is None or ((cand['step'], cand['order'])
                                       < (cur['step'],
                                          cur.get('order', 1 << 30))):
                        self.first_nonfinite = cand
                else:
                    return
            # deque(maxlen=...): O(1) eviction once the timeline cap is
            # hit (metrics.jsonl still holds the full series).
            self._probe_records.append(rec)
            self._metrics.log(self._step_index, probe=name, value=value,
                              **meta)

    def record_section(self, name, start_s, duration_s):
        """Register one labelled wall-clock span (e.g. a bench section)
        for the ``trace.json`` timeline."""
        if self.enabled:
            self._sections.append((name, start_s, duration_s))
            if self.watchdog is not None:
                # A completed section is both a heartbeat and the
                # last-completed span a hang report should name.
                self.watchdog.beat('section', name)
                self.watchdog.done()

    def log(self, step, **metrics):
        """Append one record to ``metrics.jsonl`` and refresh the derived
        artifacts."""
        if not self.enabled:
            return
        # Same lock as the probe sink: both sides write the one
        # metrics.jsonl handle, and late probe callbacks can still be
        # draining on jax's host-callback thread while the main thread
        # logs its epoch record.
        with self._probe_lock:
            self._metrics.log(step, **metrics)
        if self.watchdog is not None:
            # Epoch-boundary host work (eval loops, checkpointing) beats
            # through its log calls, so only genuine stalls trip the
            # deadline.
            self.watchdog.beat('idle')
        self.flush()

    @contextlib.contextmanager
    def compile_label(self, name):
        """Attribute compile events inside the block to ``name`` in
        ``timings.json``'s ``by_label`` breakdown."""
        if not self.enabled:
            yield
            return
        if self.watchdog is not None:
            self.watchdog.beat('compile', name)
        self._pending_compiles.append(name)
        try:
            with self._watcher.label(name):
                yield
        finally:
            if name in self._pending_compiles:
                self._pending_compiles.remove(name)
            if self.watchdog is not None:
                self.watchdog.done()

    def snapshot_memory(self, tag=''):
        """Record a labelled device/host memory snapshot."""
        if not self.enabled:
            return None
        snap = memory_snapshot(tag)
        self._snapshots.append(snap)
        self.flush()
        return snap

    # -- artifacts ---------------------------------------------------------

    @staticmethod
    def _count_index(rows):
        return {tuple(sorted((k, v) for k, v in r.items() if k != 'count')):
                r['count'] for r in rows}

    @staticmethod
    def _since(rows, base):
        """Rows with the baseline counts subtracted (drop zero rows)."""
        out = []
        for r in rows:
            key = tuple(sorted((k, v) for k, v in r.items()
                               if k != 'count'))
            delta = r['count'] - base.get(key, 0)
            if delta > 0:
                out.append(dict(r, count=delta))
        return out

    def _write(self, name, payload):
        path = os.path.join(self.dir, name)
        tmp = path + '.tmp'
        with open(tmp, 'w') as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)

    def probe_summary(self):
        """Per-probe aggregates ``{name: {count, mean, last, min, max}}``
        (+ ``first_nonfinite`` when a stage went non-finite)."""
        with self._probe_lock:
            return self._probe_agg.summary()

    def device_step_summary(self):
        """Per-device completion aggregates from :meth:`fence_devices`:
        ``{device_id: {count, mean_s, p50_s, max_s, last_s}}``."""
        from dgmc_tpu.obs.observe import percentile
        out = {}
        for dev, times in sorted(self._device_times.items()):
            ts = sorted(times)
            out[dev] = {
                'count': len(ts),
                'mean_s': round(sum(ts) / len(ts), 6),
                'p50_s': round(percentile(ts, 0.5), 6),
                'max_s': round(ts[-1], 6),
                'last_s': round(times[-1], 6),
            }
        return out

    def _watchdog_context(self):
        """Run-state snapshot for the hang report (called from the
        watchdog thread; cached there for the lock-free signal path)."""
        ctx = {
            'steps_completed': self._step_index,
            'steps': self.timer.summary(),
            'pending_compiles': list(self._pending_compiles),
            'compile_events': (self._watcher.count()
                               if self._watcher else 0),
            'dispatch_tail': self._since(dispatch_table(),
                                         self._dispatch_base)[-8:],
        }
        if self.timer.spans:
            t0, dur = self.timer.spans[-1]
            ctx['last_step_span'] = {'start': t0,
                                     'duration_s': round(dur, 6)}
        if self._sections:
            ctx['sections'] = [
                {'name': n, 'start': t0, 'duration_s': round(d, 3)}
                for n, t0, d in self._sections[-8:]]
        return ctx

    def timings(self):
        out = {
            'wall_s': round(time.time() - self._t_start, 3),
            'argv': sys.argv,
            'steps': self.timer.summary(),
            'compile': self._watcher.summary() if self._watcher else {},
            'padding_buckets': self._since(padding_bucket_table(),
                                           self._buckets_base),
        }
        if self._device_times:
            out['device_steps'] = self.device_step_summary()
        if self._probe_agg:
            out['probes'] = self.probe_summary()
        if self.first_nonfinite is not None:
            out['first_nonfinite'] = self.first_nonfinite
        return out

    def flush(self):
        """Rewrite ``timings.json`` / ``memory.json`` / ``dispatch.json``
        / ``trace.json`` from current state (atomic per file)."""
        if not self.enabled:
            return
        self._write('timings.json', self.timings())
        self._write('memory.json', {'snapshots': self._snapshots})
        self._write('dispatch.json', {'counts': self._since(
            dispatch_table(), self._dispatch_base)})
        if self._costs:
            from dgmc_tpu.obs import cost as cost_mod
            steps = self.timer.summary()
            self._write('efficiency.json', cost_mod.efficiency_payload(
                self._costs, fallback_step_time_s=steps.get('p50_s')))
        from dgmc_tpu.obs.trace import export_chrome_trace
        with self._probe_lock:
            # Snapshot: the deque may receive callback-thread appends
            # while the exporter iterates.
            probe_records = list(self._probe_records)
        export_chrome_trace(
            os.path.join(self.dir, 'trace.json'),
            step_spans=self.timer.spans,
            probe_records=probe_records,
            compile_events=self._watcher.events if self._watcher else (),
            sections=self._sections,
            device_fences=self._fence_records,
            metadata={'argv': sys.argv})

    def close(self):
        # Probe teardown first, and independent of `enabled`: a
        # coordinator-gated observer (obs_dir=None) still flipped the
        # global switch in __init__ and must restore it.
        if self._probe_sink is not None or self._probes_enabled_by_me:
            # Drain in-flight debug callbacks BEFORE detaching the sink:
            # on async-dispatch backends the last step's probe records
            # (possibly including the run's only non-finite) are still
            # queued on jax's host-callback thread when the training
            # loop returns.
            try:
                import jax
                jax.effects_barrier()
            except Exception:
                pass
        if self._probe_sink is not None:
            probes_mod.remove_sink(self._probe_sink)
            self._probe_sink = None
        if self._probes_enabled_by_me:
            probes_mod.disable()
            self._probes_enabled_by_me = False
        if not self.enabled:
            return
        if self.watchdog is not None:
            self.watchdog.close()
            self.watchdog = None
        self.snapshot_memory('end')
        self.flush()
        self._metrics.close()
        self._watcher.close()
        self.enabled = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
