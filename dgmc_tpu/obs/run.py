"""The ``--obs-dir`` facade: one flag, four artifacts.

:class:`RunObserver` bundles the metric sink, the step timer, the compile
watcher, memory snapshots and the dispatch-counter snapshot behind a
single directory:

- ``metrics.jsonl`` — one record per :meth:`RunObserver.log` call.
- ``timings.json``  — step-time percentiles + compile-event summary +
  run wall-clock.
- ``memory.json``   — labelled device/host memory snapshots + the peak.
- ``dispatch.json`` — the kernel-dispatch outcome table.

Every method is a no-op when constructed with a falsy directory, so CLIs
call the observer unconditionally::

    obs = RunObserver(args.obs_dir)      # None => disabled
    with obs:
        for batch in loader:
            with obs.step():
                state, out = step(state, batch, key)
        obs.log(epoch, loss=loss)
        obs.snapshot_memory(f'epoch{epoch}')

Artifacts are rewritten on every :meth:`flush` (each ``log`` /
``snapshot_memory`` flushes), so a run killed by a timeout still leaves
analyzable telemetry on disk — the failure mode ``BENCH_r05.json``
(``rc: 124``, no evidence) exposed.
"""

import contextlib
import json
import os
import sys
import time

from dgmc_tpu.obs.memory import memory_snapshot
from dgmc_tpu.obs.observe import MetricLogger, StepTimer
from dgmc_tpu.obs.registry import (CompileWatcher, dispatch_table,
                                   padding_bucket_table)


def add_obs_flag(parser):
    """Register the standard ``--obs-dir`` flag on an argparse parser."""
    parser.add_argument(
        '--obs-dir', '--obs_dir', dest='obs_dir', type=str, default=None,
        help='write run telemetry (metrics.jsonl, timings.json, '
             'memory.json, dispatch.json) into this directory; render it '
             'with `python -m dgmc_tpu.obs.report <dir>`')
    return parser


class RunObserver:
    """Facade collecting one run's telemetry into ``obs_dir``."""

    def __init__(self, obs_dir):
        self.dir = obs_dir
        self.enabled = bool(obs_dir)
        self.timer = StepTimer()
        self._t_start = time.time()
        self._snapshots = []
        self._watcher = None
        # mode='w': an obs dir describes ONE run — a reused --obs-dir must
        # not append a second run's metrics to artifacts the observer
        # rewrites from scratch.
        self._metrics = MetricLogger(
            os.path.join(obs_dir, 'metrics.jsonl') if self.enabled else None,
            mode='w')
        if self.enabled:
            os.makedirs(obs_dir, exist_ok=True)
            # Registry counters are process-lifetime; baseline them here so
            # the artifacts attribute only THIS run's activity (the same
            # scoping CompileWatcher gives compile events).
            self._dispatch_base = self._count_index(dispatch_table())
            self._buckets_base = self._count_index(padding_bucket_table())
            self._watcher = CompileWatcher().__enter__()
            self.snapshot_memory('start')

    # -- collection --------------------------------------------------------

    @contextlib.contextmanager
    def step(self, fence=None):
        """Time one training/eval step (host-observed; pass ``fence`` a
        device scalar to time actual execution)."""
        if not self.enabled:
            yield
            return
        self.timer.start()
        try:
            yield
        finally:
            self.timer.stop(fence=fence)

    def log(self, step, **metrics):
        """Append one record to ``metrics.jsonl`` and refresh the derived
        artifacts."""
        if not self.enabled:
            return
        self._metrics.log(step, **metrics)
        self.flush()

    @contextlib.contextmanager
    def compile_label(self, name):
        """Attribute compile events inside the block to ``name`` in
        ``timings.json``'s ``by_label`` breakdown."""
        if not self.enabled:
            yield
            return
        with self._watcher.label(name):
            yield

    def snapshot_memory(self, tag=''):
        """Record a labelled device/host memory snapshot."""
        if not self.enabled:
            return None
        snap = memory_snapshot(tag)
        self._snapshots.append(snap)
        self.flush()
        return snap

    # -- artifacts ---------------------------------------------------------

    @staticmethod
    def _count_index(rows):
        return {tuple(sorted((k, v) for k, v in r.items() if k != 'count')):
                r['count'] for r in rows}

    @staticmethod
    def _since(rows, base):
        """Rows with the baseline counts subtracted (drop zero rows)."""
        out = []
        for r in rows:
            key = tuple(sorted((k, v) for k, v in r.items()
                               if k != 'count'))
            delta = r['count'] - base.get(key, 0)
            if delta > 0:
                out.append(dict(r, count=delta))
        return out

    def _write(self, name, payload):
        path = os.path.join(self.dir, name)
        tmp = path + '.tmp'
        with open(tmp, 'w') as f:
            json.dump(payload, f, indent=1)
        os.replace(tmp, path)

    def timings(self):
        return {
            'wall_s': round(time.time() - self._t_start, 3),
            'argv': sys.argv,
            'steps': self.timer.summary(),
            'compile': self._watcher.summary() if self._watcher else {},
            'padding_buckets': self._since(padding_bucket_table(),
                                           self._buckets_base),
        }

    def flush(self):
        """Rewrite ``timings.json`` / ``memory.json`` / ``dispatch.json``
        from current state (atomic per file)."""
        if not self.enabled:
            return
        self._write('timings.json', self.timings())
        self._write('memory.json', {'snapshots': self._snapshots})
        self._write('dispatch.json', {'counts': self._since(
            dispatch_table(), self._dispatch_base)})

    def close(self):
        if not self.enabled:
            return
        self.snapshot_memory('end')
        self.flush()
        self._metrics.close()
        self._watcher.close()
        self.enabled = False

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
