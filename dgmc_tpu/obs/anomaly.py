"""Streaming anomaly watch: EWMA z-scores + CUSUM changepoints, O(1) memory.

The flight recorder (PR 12) captures trailing context *when asked* —
a watchdog fires, an SLO budget exhausts, a human hits ``/debug``. A
silent degradation (a slow drift in step latency, a quiet jump in
guard skips after a deploy) never asks. This module is the asking:
constant-memory detectors watch the signals the planes already
measure and arm the flight recorder the moment a signal leaves its
own recent history.

Two detectors run per signal, catching different shapes:

- **EWMA z-score**: exponentially-weighted running mean and variance
  (West 1979 incremental form); a sample more than ``z_threshold``
  robust deviations from the running mean flags a *spike*. Catches
  cliffs; forgets them at rate ``alpha``.
- **CUSUM** (Page 1954) on the standardized residuals:
  ``s+ = max(0, s+ + z - k)`` and the mirrored ``s-``; crossing ``h``
  flags a sustained *shift* — a mean change too small for any single
  sample to look odd. The classic tuning ``k = 0.5`` (sensitive to
  ~1-sigma shifts) with ``h = 5`` gives an in-control average run
  length of ~930 samples, i.e. under one false positive per thousand
  white-noise samples.

Events land in a **bounded** ring (explicit length check + oldest
eviction, ``truncated`` counter — the CON505 discipline) flushed as
``anomalies.json`` by every RunObserver flush, and the rate-limited
``on_anomaly`` callback feeds ``RunObserver.flight_dump`` so the
trailing context of the FIRST excursion is on disk before anyone
looks.

Signal vocabulary (what the wiring feeds — the watch itself accepts
any name): ``step_latency_s``, ``query_latency_s``, ``qps``,
``compile_events``, ``guard_skips``, ``quality_margin``.

:func:`changepoints` is the same CUSUM run offline over a short
committed series — ``obs.timeline --trend`` uses it to mark the
round where a longitudinal metric shifted.

jax-free (stdlib only).
"""

import math
import threading
import time

__all__ = ['EwmaDetector', 'CusumDetector', 'AnomalyWatch',
           'changepoints', 'ANOMALY_SCHEMA_VERSION', 'WATCHED_SIGNALS']

ANOMALY_SCHEMA_VERSION = 1

#: The signals the standard wiring feeds (documentation + the
#: serve-bench boundedness gate iterates it); the watch accepts any
#: signal name.
WATCHED_SIGNALS = ('step_latency_s', 'query_latency_s', 'qps',
                   'compile_events', 'guard_skips', 'quality_margin')


class EwmaDetector:
    """Exponentially-weighted mean/variance with z-score spike checks.

    ``observe`` returns the standardized residual z of the sample
    against the *pre-update* state (a spike must not first inflate the
    variance it is judged by), then folds the sample in. The first
    ``warmup`` samples only train — cold stats flag everything.
    """

    def __init__(self, alpha=0.1, z_threshold=4.0, warmup=10,
                 min_sigma=1e-9):
        if not 0.0 < alpha <= 1.0:
            raise ValueError(f'alpha must be in (0, 1], got {alpha}')
        self.alpha = float(alpha)
        self.z_threshold = float(z_threshold)
        self.warmup = int(warmup)
        self.min_sigma = float(min_sigma)
        self.count = 0
        self.mean = 0.0
        self.var = 0.0

    def observe(self, value):
        """Fold in ``value``; return ``(z, spiked)`` — ``z`` is
        ``None`` during warmup."""
        value = float(value)
        z = None
        if self.count >= self.warmup:
            sigma = math.sqrt(max(self.var, 0.0))
            # A dead-flat history (constant signal) gets a floor
            # rather than an infinite z on the first wiggle.
            sigma = max(sigma, self.min_sigma,
                        abs(self.mean) * 1e-6)
            z = (value - self.mean) / sigma
        if self.count == 0:
            self.mean = value
        else:
            delta = value - self.mean
            self.mean += self.alpha * delta
            # West-style EWMA variance of the residuals.
            self.var = (1.0 - self.alpha) * (self.var
                                             + self.alpha * delta * delta)
        self.count += 1
        spiked = z is not None and abs(z) >= self.z_threshold
        return z, spiked


class CusumDetector:
    """Two-sided CUSUM on standardized residuals.

    ``observe(z)`` accumulates ``s+ = max(0, s+ + z - k)`` and
    ``s- = max(0, s- - z - k)``; either crossing ``h`` signals a
    sustained shift, after which both sums reset (one changepoint per
    excursion, not one per sample while shifted).
    """

    def __init__(self, k=0.5, h=5.0):
        self.k = float(k)
        self.h = float(h)
        self.s_pos = 0.0
        self.s_neg = 0.0

    def observe(self, z):
        """Accumulate one standardized residual; return ``(shifted,
        direction)`` where direction is ``'up'``/``'down'``/``None``."""
        z = float(z)
        self.s_pos = max(0.0, self.s_pos + z - self.k)
        self.s_neg = max(0.0, self.s_neg - z - self.k)
        if self.s_pos >= self.h:
            self.s_pos = self.s_neg = 0.0
            return True, 'up'
        if self.s_neg >= self.h:
            self.s_pos = self.s_neg = 0.0
            return True, 'down'
        return False, None


class _SignalWatch:
    """One signal's detector pair + counters (internal)."""

    def __init__(self, alpha, z_threshold, warmup, k, h):
        self.ewma = EwmaDetector(alpha=alpha, z_threshold=z_threshold,
                                 warmup=warmup)
        self.cusum = CusumDetector(k=k, h=h)
        self.samples = 0
        self.spikes = 0
        self.shifts = 0
        self.last_value = None
        self.last_z = None


class AnomalyWatch:
    """The per-run anomaly account: many signals, one bounded ring.

    ``observe(signal, value)`` runs the detector pair and, on a spike
    or shift, appends an event to the ring (evicting the oldest past
    ``capacity`` and counting the truncation) and fires the
    rate-limited ``on_anomaly`` callback. Thread-safe — serve handler
    threads and the flush loop feed it concurrently.
    """

    #: Seconds between ``on_anomaly`` callbacks per signal: the flight
    #: recorder wants the FIRST excursion's trailing context, not a
    #: dump per sample while the signal stays strange.
    CALLBACK_COOLDOWN_S = 60.0

    def __init__(self, capacity=256, alpha=0.1, z_threshold=4.0,
                 warmup=10, cusum_k=0.5, cusum_h=5.0,
                 time_fn=time.time, on_anomaly=None):
        if capacity < 1:
            raise ValueError(f'capacity must be >= 1, got {capacity}')
        self.capacity = int(capacity)
        self._params = (float(alpha), float(z_threshold), int(warmup),
                        float(cusum_k), float(cusum_h))
        self._time = time_fn
        self._on_anomaly = on_anomaly
        self._lock = threading.Lock()
        self._signals = {}
        self._ring = []        # bounded: len() check + eviction below
        self._truncated = 0
        self._callback_last = {}

    def observe(self, signal, value, now=None):
        """Feed one sample; returns the event dict if it anomaled,
        else ``None``."""
        now = self._time() if now is None else now
        fire = None
        with self._lock:
            w = self._signals.get(signal)
            if w is None:
                w = self._signals[signal] = _SignalWatch(*self._params)
            z, spiked = w.ewma.observe(value)
            shifted, direction = (False, None)
            if z is not None:
                shifted, direction = w.cusum.observe(z)
            w.samples += 1
            w.last_value = float(value)
            w.last_z = z
            if not (spiked or shifted):
                return None
            kinds = []
            if spiked:
                w.spikes += 1
                kinds.append('spike')
            if shifted:
                w.shifts += 1
                kinds.append('shift')
            event = {
                'signal': signal,
                'kinds': kinds,
                'direction': (direction if shifted
                              else ('up' if z >= 0 else 'down')),
                'value': float(value),
                'z': round(z, 4),
                'mean': round(w.ewma.mean, 6),
                'sample': w.samples,
                'time': now,
            }
            # Bounded ring (CON505): evict the oldest past capacity
            # and account for the loss — the artifact says how much
            # history it dropped, never silently.
            self._ring.append(event)
            if len(self._ring) > self.capacity:
                del self._ring[0]
                self._truncated += 1
            last = self._callback_last.get(signal)
            if last is None or now - last >= self.CALLBACK_COOLDOWN_S:
                self._callback_last[signal] = now
                fire = event
        if fire is not None and self._on_anomaly is not None:
            try:
                self._on_anomaly(fire)
            except Exception:
                pass  # watching must never take the service down
        return event

    # -- exports -----------------------------------------------------------

    def counters(self):
        """Small per-signal account (the ``/status`` body)."""
        with self._lock:
            return {
                'signals': {
                    name: {'samples': w.samples, 'spikes': w.spikes,
                           'shifts': w.shifts,
                           'last_value': w.last_value,
                           'last_z': (None if w.last_z is None
                                      else round(w.last_z, 4))}
                    for name, w in sorted(self._signals.items())},
                'events': len(self._ring),
                'truncated': self._truncated,
            }

    def snapshot(self):
        """The ``anomalies.json`` body: bounded event ring + account."""
        with self._lock:
            return {
                'version': ANOMALY_SCHEMA_VERSION,
                'capacity': self.capacity,
                'truncated': self._truncated,
                'signals': {
                    name: {'samples': w.samples, 'spikes': w.spikes,
                           'shifts': w.shifts}
                    for name, w in sorted(self._signals.items())},
                'events': [dict(e) for e in self._ring],
            }

    def metric_families(self):
        """The ``dgmc_anomaly_*`` families for ``/metrics``."""
        with self._lock:
            spikes = [('', {'signal': name}, w.spikes)
                      for name, w in sorted(self._signals.items())]
            shifts = [('', {'signal': name}, w.shifts)
                      for name, w in sorted(self._signals.items())]
            truncated = self._truncated
        return [
            ('dgmc_anomaly_spikes_total', 'counter',
             'EWMA z-score spike detections by signal.',
             spikes or [('', {'signal': 'none'}, 0)]),
            ('dgmc_anomaly_shifts_total', 'counter',
             'CUSUM sustained-shift detections by signal.',
             shifts or [('', {'signal': 'none'}, 0)]),
            ('dgmc_anomaly_ring_truncated_total', 'counter',
             'Anomaly events evicted from the bounded ring.',
             [('', {}, truncated)]),
        ]


def changepoints(series, k=0.5, h=4.0, warmup=3):
    """Offline CUSUM over a short committed series (timeline rounds).

    Standardizes against the median and the MAD-derived robust sigma
    of the first ``warmup`` values (the baseline the trend is judged
    FROM — a late regression must not inflate the scale it is judged
    by), then runs the same two-sided CUSUM the live watch uses.
    Returns ``[{'index', 'direction', 'value'}, ...]``; ``None``
    entries in ``series`` are skipped without breaking the
    accumulation. Tuned looser than the live watch (``h=4``,
    ``warmup=3``) because committed rounds are few and each point is
    already an aggregate.
    """
    vals = [(i, float(v)) for i, v in enumerate(series) if v is not None]
    if len(vals) <= warmup:
        return []
    base = sorted(v for _, v in vals[:warmup])
    n = len(base)
    median = (base[n // 2] if n % 2 else
              0.5 * (base[n // 2 - 1] + base[n // 2]))
    abs_dev = sorted(abs(v - median) for v in base)
    mad = (abs_dev[n // 2] if n % 2 else
           0.5 * (abs_dev[n // 2 - 1] + abs_dev[n // 2]))
    sigma = 1.4826 * mad
    # A flat baseline (common with 3 rounds of a stable metric) gets
    # a relative floor so real shifts still standardize finitely.
    sigma = max(sigma, abs(median) * 0.01, 1e-12)
    det = CusumDetector(k=k, h=h)
    out = []
    for i, v in vals:
        shifted, direction = det.observe((v - median) / sigma)
        if shifted:
            out.append({'index': i, 'direction': direction, 'value': v})
            # Re-baseline at the new level: a sustained shift is ONE
            # changepoint, not one per subsequent round that stays
            # there (the CUSUM reset alone is not enough — the old
            # median would re-accumulate immediately).
            median = v
    return out
