"""Benchmark: flagship DGMC training throughput (pairs/sec) on one chip.

Workload: the pascal_pf-shaped dense matcher (SplineCNN ψ₁/ψ₂, 10 consensus
steps — the reference's headline keypoint configuration, reference
``examples/pascal_pf.py:81-83`` / ``examples/pascal.py:46-50``) training on
synthetic geometric pairs padded to 64 nodes, batch 128. The reference
publishes no wall-clock numbers (BASELINE.md), so the recorded first-round
throughput (``BENCH_BASELINE.json``, written on first run) is the baseline
later rounds must beat; ``vs_baseline`` is the ratio against it.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
"""

import json
import os
import time

import jax
import numpy as np

BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             'BENCH_BASELINE.json')

BATCH = 128
NUM_NODES = 64
NUM_EDGES = 512
NUM_STEPS = 10
WARMUP = 3
ITERS = 20


def build():
    from dgmc_tpu.data import (Cartesian, Compose, Constant, KNNGraph,
                               RandomGraphPairs)
    from dgmc_tpu.models import DGMC, SplineCNN
    from dgmc_tpu.train import create_train_state, make_train_step
    from dgmc_tpu.utils import PairLoader

    transform = Compose([Constant(), KNNGraph(k=8), Cartesian()])
    ds = RandomGraphPairs(min_inliers=30, max_inliers=60, min_outliers=0,
                          max_outliers=4, transform=transform, length=BATCH,
                          seed=0)
    loader = PairLoader(ds, BATCH, shuffle=False, num_nodes=NUM_NODES,
                        num_edges=NUM_EDGES)
    batch = next(iter(loader))

    psi_1 = SplineCNN(1, 256, dim=2, num_layers=2, cat=False, lin=True,
                      dropout=0.0)
    psi_2 = SplineCNN(64, 64, dim=2, num_layers=2, cat=True, lin=True)
    model = DGMC(psi_1, psi_2, num_steps=NUM_STEPS, k=-1)
    state = create_train_state(model, jax.random.key(0), batch,
                               learning_rate=1e-3)
    step = make_train_step(model, loss_on_s0=True)
    return state, step, batch


def main():
    state, step, batch = build()
    key = jax.random.key(1)

    for _ in range(WARMUP):
        key, sub = jax.random.split(key)
        state, out = step(state, batch, sub)
    jax.block_until_ready(out['loss'])

    t0 = time.perf_counter()
    for _ in range(ITERS):
        key, sub = jax.random.split(key)
        state, out = step(state, batch, sub)
    jax.block_until_ready(out['loss'])
    dt = time.perf_counter() - t0

    pairs_per_sec = BATCH * ITERS / dt
    assert np.isfinite(float(out['loss']))

    platform = str(jax.devices()[0].platform)
    baseline = None
    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            stored = json.load(f)
        # A baseline recorded on another platform (e.g. CPU smoke run) would
        # make vs_baseline meaningless — re-seed it instead.
        if stored.get('device') == platform:
            baseline = stored['value']
    if baseline is None:
        baseline = pairs_per_sec
        with open(BASELINE_FILE, 'w') as f:
            json.dump({'metric': 'train_pairs_per_sec',
                       'value': pairs_per_sec,
                       'device': platform}, f)

    print(json.dumps({
        'metric': 'train_pairs_per_sec',
        'value': round(pairs_per_sec, 2),
        'unit': 'pairs/sec',
        'vs_baseline': round(pairs_per_sec / baseline, 4),
    }))


if __name__ == '__main__':
    main()
