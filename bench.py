"""Benchmark: flagship DGMC throughput on one chip, dense AND sparse.

Two workloads:

1. **Dense keypoint matching** (the primary metric): the pascal_pf-shaped
   dense matcher (SplineCNN ψ₁/ψ₂, 10 consensus steps — the reference's
   headline keypoint configuration, reference ``examples/pascal_pf.py:81-83``
   / ``examples/pascal.py:46-50``) training on synthetic geometric pairs
   padded to 64 nodes, batch 128. Reported as training pairs/sec.
2. **DBP15K-scale sparse matching** (the ``sparse_dbp15k`` extras): the
   sparse top-k matcher at genuine knowledge-graph scale — B=1,
   N_s=15000, N_t=20000, k=10, RelCNN backbones with the reference's
   DBP15K dimensions (reference ``examples/dbp15k.py:29-32``), random
   features — one full training step (ψ₁ + chunked top-k + negatives/GT
   injection + 10 consensus iterations + backward + Adam), plus the
   standalone chunked-top-k sweep across block sizes. This is the workload
   the sparse path and the sharded design exist for; it must fit and run
   on a single chip.

The reference publishes no wall-clock numbers (BASELINE.md), so the recorded
first-round numbers (``BENCH_BASELINE.json``, written on first run per
platform) are the baseline later rounds must beat; ``vs_baseline`` is the
ratio against them (>1 is better for pairs/sec; for the sparse step the
ratio is baseline_ms/current_ms so >1 is also better). ``vs_baseline``
compares against THIS REPO's own protocol-v2 first measurement on this
chip — the reference publishes no numbers and no cross-hardware (A100)
anchor exists in-repo, so it is a self-relative progress ratio, nothing
more.

Both workloads report the f32 policy AND the bf16 compute policy. The
dense primary metric stays f32 (baseline-comparable) with a
``dense_bf16`` extra. The sparse FLAGSHIP leg is the bf16 policy as of
round 5 — the library-default precision since round 6, with full-scale
quality evidence committed (``runs/dbp15k_syn_bf16.jsonl``: phase-2
+12.8 pt Hits@1, within 0.3 pt of f32 at every recorded epoch;
EXPERIMENTS.md) — and, as of round 6, runs ``SP_PAIRS`` pair-replicas
per step (the DBP15K CLI's ``--pairs-per-step``): B=1 starves the MXU,
so the flagship batches the hot loop and reports
``sparse_dbp15k.step_ms`` (total) plus ``step_ms_per_pair``, with
``flagship: 'bf16'`` and ``pairs_per_step`` marked explicitly. The f32
leg stays B=1 as the ``sparse_dbp15k.f32`` extra with its own
``vs_baseline`` — it is also what seeds the stored baseline. The stored
baseline (671 ms) was measured under the f32 policy at B=1; the
flagship competes against it on ``step_ms_per_pair`` — per-unit-work
normalization, like the dense metric's pairs/sec, not a protocol change
(the timed region is identical).

Every section also records its roofline position next to MFU:
``flops_per_step`` / ``bytes_per_step`` from the compiled executable's
cost analysis (the same ``obs/cost.py`` attribution behind
``efficiency.json``) and their ratio ``arith_intensity`` (achieved
FLOPs/byte) — low intensity at low MFU reads bandwidth-bound, high
intensity at low MFU reads dispatch/latency-bound.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", extras...}.

Timeout diagnosability: every section reports into a host-side progress
ledger (and a one-line JSON progress record per completed section on
stderr), and a SIGTERM/SIGALRM (what ``timeout(1)`` sends) makes the
process print a PARTIAL JSON line — sections completed, per-section
elapsed, the section in flight — before exiting 124, instead of dying
silently like ``BENCH_r05.json`` (``rc: 124, parsed: null``). With
``--obs-dir`` the run additionally leaves the standard telemetry
artifacts (``python -m dgmc_tpu.obs.report <dir>``), flushed after every
section so they survive a kill too, and ``--watchdog-deadline SEC`` arms
the run-health watchdog (``hang_report.json`` on stall or SIGTERM —
``dgmc_tpu/obs/watchdog.py``).

``--section-timeout SEC`` gives every section its own deadline budget
(``signal.setitimer``): a section exceeding it is recorded as
``{'ok': False, 'timeout': True}`` and the run MOVES ON to the next
section, so one stuck section no longer consumes the whole run and the
final JSON line still carries every completed section's numbers (the
BENCH_r05/MULTICHIP failure mode left ``parsed: null`` for everything).
Caveat: the timeout interrupts at the next Python bytecode — a hang
inside one C-level XLA call still needs the external ``timeout(1)``,
which the partial-line handler and the watchdog then make diagnosable.
"""

import argparse
import contextlib
import json
import os
import signal
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

BASELINE_FILE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             'BENCH_BASELINE.json')

# Measurement-protocol version: bump when the harness itself changes what is
# inside the timed region (e.g. v2 moved the batch device-side before the
# loop), so vs_baseline never credits a measurement change as a speedup —
# a protocol mismatch reseeds the baseline instead.
PROTOCOL = 2

# Dense workload shape.
BATCH = 128
NUM_NODES = 64
NUM_EDGES = 512
NUM_STEPS = 10
WARMUP = 3
ITERS = 20

# Sparse workload shape (DBP15K zh_en scale).
SP_N_S, SP_N_T = 15000, 20000
SP_E_S, SP_E_T = 100000, 120000
SP_DIM = 300
SP_K = 10
# Flagship batch axis: replicas of the pair per step (--pairs-per-step in
# the DBP15K CLI — independent per-pair indicator noise / negatives, one
# averaged gradient). B=1 starves the MXU (r04 flagship MFU 0.0165 with
# the chip ~98% idle); batching amortizes the per-kernel dispatch floor
# and widens every GEMM. The flagship's vs_baseline is computed on
# step_ms_per_pair (= step_ms / pairs) so the per-unit-work metric stays
# comparable with the stored B=1 baseline; the f32 leg stays B=1 as the
# baseline-seeding anchor.
SP_PAIRS = 2
# The ONE measured candidate-search block default (within noise of
# 1024/4096 in the r03 sweep — 18.19/18.09/18.12 ms; the Pallas kernel
# ignores the knob entirely; lowest peak tile memory on the scan paths).
# Threaded from ops/topk.DEFAULT_BLOCK — the same constant the
# partition-rule config (parallel/rules.DEFAULT_TOPK_BLOCK) hands every
# sharded callsite — so the bench measures the shipped default, not a
# bench-local literal (benchmarks/DISPATCH_DEFAULTS.md, block-size
# section).
from dgmc_tpu.ops.topk import DEFAULT_BLOCK as SP_TOPK_BLOCK  # noqa: E402
SP_ITERS = 10
TOPK_ITERS = 10


# Peak-FLOPs accounting moved to dgmc_tpu/obs/cost.py (one table for
# bench, the efficiency.json artifact, and the report/diff layers); the
# alias keeps this module's historical surface. MFU remains
# flops / (step_time * peak) against the bf16 peak — see obs/cost.py for
# the honest-ceiling caveats — and now also resolves on CPU via the
# nominal fallback entry.
from dgmc_tpu.obs.cost import (PEAK_FLOPS,  # noqa: E402,F401  (re-export)
                               peak_flops_entry)


# ---------------------------------------------------------------------------
# Section progress + partial-result emission on timeout
# ---------------------------------------------------------------------------

_PROGRESS = {'sections': {}, 'current': None, 'current_t0': None,
             'in_body': False, 'start': time.time()}
_OBS = None  # RunObserver when --obs-dir is set
_PROF = None  # ProfileHandle when --profile-dir is set


def _prof_step():
    """One measured-step boundary: advances the ``--profile-steps``
    window (bench has no RunObserver.step loop, so the measured
    iterations themselves are the boundaries) and returns the per-step
    trace annotation — a null context while the profiler is not
    capturing. Warmup iterations count as boundaries too, but no
    compile can land in-window: every wrapped step is AOT-compiled in
    its build step, and the topk variants compile on the unwrapped
    first fence."""
    if _PROF is None:
        return contextlib.nullcontext()
    _PROF.on_step()
    return _PROF.step_annotation()
#: Per-section deadline budget in seconds (0 = off); set by
#: --section-timeout. While a section runs with a budget, SIGALRM means
#: "this section blew its budget" and raises SectionTimeout into the
#: section body instead of killing the run.
_SECTION_TIMEOUT = {'seconds': 0.0}


class SectionTimeout(Exception):
    """Raised (from the SIGALRM handler) into a section body that
    exceeded its ``--section-timeout`` budget."""


@contextlib.contextmanager
def _section(name):
    """Track one benchmark section in the progress ledger (and in the
    --obs-dir artifacts), so a timeout mid-run still reports which
    sections finished and where time went.

    With ``--section-timeout``, arms a per-section ``setitimer`` budget;
    a :class:`SectionTimeout` is recorded (``'timeout': True``) and
    SWALLOWED — the caller's leg variables keep their pre-section
    values (``None``) and the run proceeds to the next section. Real
    exceptions still propagate. Every completed section also emits one
    JSON progress line on stderr (stdout stays the one-line protocol).
    """
    # t0 before name: a signal between the two assignments must never see
    # current set with current_t0 still None (the handler reads both).
    wall0 = time.time()
    t0 = _PROGRESS['current_t0'] = time.perf_counter()
    _PROGRESS['current'] = name
    budget = _SECTION_TIMEOUT['seconds']
    if budget > 0:
        signal.setitimer(signal.ITIMER_REAL, budget)
    timed_out = False
    try:
        _PROGRESS['in_body'] = True
        yield
        # Body done: a budget alarm delivered from here on is moot (the
        # section DID finish) — the handler checks in_body and ignores
        # it instead of raising into bookkeeping or, worse, out of the
        # finally block after the itimer-cancel point.
        _PROGRESS['in_body'] = False
        _PROGRESS['sections'][name] = {
            'ok': True, 'elapsed_s': round(time.perf_counter() - t0, 3)}
    except SectionTimeout:
        timed_out = True
        _PROGRESS['sections'][name] = {
            'ok': False, 'timeout': True,
            'elapsed_s': round(time.perf_counter() - t0, 3),
            'error': f'section exceeded --section-timeout {budget}s'}
    except Exception as e:
        _PROGRESS['sections'][name] = {
            'ok': False, 'elapsed_s': round(time.perf_counter() - t0, 3),
            'error': f'{type(e).__name__}: {e}'}
        raise
    finally:
        _PROGRESS['in_body'] = False
        if budget > 0:
            signal.setitimer(signal.ITIMER_REAL, 0)
        _PROGRESS['current'] = _PROGRESS['current_t0'] = None
        rec = _PROGRESS['sections'].get(name, {})
        print(json.dumps({'section': name, **rec}), file=sys.stderr,
              flush=True)
        if _OBS is not None:
            _OBS.record_section(name, wall0, time.perf_counter() - t0)
            _OBS.log(name, **rec)
            _OBS.snapshot_memory(name)
    if timed_out and _OBS is not None:
        # The stuck section is worth a hang report even though the run
        # survives: the all-thread stacks say WHERE the budget went.
        if _OBS.watchdog is not None:
            _OBS.watchdog.dump(f'section-timeout:{name}')


def _emit_partial(signum, frame):
    """Signal handler: print a partial JSON line and exit 124 (the
    timeout(1) convention) instead of dying with no evidence."""
    current, t0 = _PROGRESS['current'], _PROGRESS['current_t0']
    rec = {
        'metric': 'train_pairs_per_sec',
        'value': None,
        'partial': True,
        'signal': signal.Signals(signum).name,
        'elapsed_s': round(time.time() - _PROGRESS['start'], 3),
        'sections': _PROGRESS['sections'],
        'current': None if current is None or t0 is None else {
            'name': current,
            'elapsed_s': round(time.perf_counter() - t0, 3)},
    }
    # No _OBS.flush() here: flush() snapshots the registry under
    # non-reentrant locks the interrupted main thread may already hold
    # (REGISTRY._lock, the compile-listener lock) — a blocked acquire in a
    # signal handler would hang the process, the very failure mode this
    # handler exists to fix. The obs artifacts are already on disk: every
    # completed section flushed them.
    print(json.dumps(rec), flush=True)
    os._exit(124)


def _on_signal(signum, frame):
    """SIGTERM/SIGALRM dispatcher.

    A SIGALRM is the section's OWN budget expiry only when a budgeted
    section is current AND its elapsed time has actually reached the
    budget — an external SIGALRM (``timeout -s ALRM``) landing mid-body
    before that must still kill the run with the partial line, not be
    swallowed as a fake section timeout. An own-budget alarm delivered
    in the section's bookkeeping (body finished within epsilon of the
    budget) is moot and ignored — raising there would escape the
    context manager's except scope and kill the run without its JSON
    line. Everything else is the external kill: emit the partial line
    and exit 124."""
    budget = _SECTION_TIMEOUT['seconds']
    t0 = _PROGRESS['current_t0']
    if (signum == signal.SIGALRM and budget > 0
            and _PROGRESS['current'] is not None and t0 is not None
            and time.perf_counter() - t0 >= budget - 0.05):
        if _PROGRESS['in_body']:
            raise SectionTimeout(_PROGRESS['current'])
        return
    _emit_partial(signum, frame)


def _install_signal_handlers():
    for sig in (signal.SIGTERM, signal.SIGALRM):
        signal.signal(sig, _on_signal)


def _aot_compile(jitted, *args, attempts=3):
    """Ahead-of-time compile a jitted step once; the returned executable is
    used for BOTH the timed loop and the cost/memory accounting, so the
    expensive XLA compile happens exactly once per leg. The tunneled
    platform's remote-compile endpoint fails transiently — retry."""
    for i in range(attempts):
        try:
            return jitted.lower(*args).compile()
        except Exception as e:
            if i == attempts - 1:
                raise
            print(f'# compile attempt {i + 1} failed '
                  f'({type(e).__name__}: {str(e)[:120]}); retrying',
                  file=sys.stderr)
            time.sleep(5)


def _perf_stats(compiled, step_seconds):
    """Absolute performance accounting for one compiled step.

    Uses the compiled executable's ``cost_analysis`` (XLA's FLOP + bytes-
    accessed counts, via ``obs.cost.analysis_totals`` — the same
    attribution the ``efficiency.json`` artifact records) and
    ``memory_analysis`` (argument/output/temp bytes — a static peak-HBM
    bound that works even where ``device.memory_stats()`` is empty, as on
    the tunneled platform here). Emits the section's roofline position
    next to MFU: ``bytes_per_step`` and ``arith_intensity`` (FLOPs/byte
    *achieved* by the program — low intensity at low MFU says
    bandwidth-bound, high intensity at low MFU says dispatch/latency-
    bound). Returns {} if the platform refuses.
    """
    from dgmc_tpu.obs.cost import analysis_totals
    out = {}
    try:
        totals = analysis_totals(compiled)
        flops = totals.get('flops', 0.0)
        if totals.get('bytes'):
            out['bytes_per_step'] = totals['bytes']
        if flops > 0:
            out['flops_per_step'] = flops
            if totals.get('bytes'):
                out['arith_intensity'] = round(flops / totals['bytes'], 3)
            peak = peak_flops_entry(jax.devices()[0])
            if peak['peak_flops'] and step_seconds:
                out['mfu'] = round(
                    flops / (step_seconds * peak['peak_flops']), 6)
                out['mfu_peak_ref'] = (f'{peak["ref"]} '
                                       f'{peak["peak_flops"]:.0f}')
    except Exception:
        pass
    from dgmc_tpu.obs.memory import compiled_memory
    cm = compiled_memory(compiled)
    if cm:
        out['peak_hbm_gib'] = round(cm['total_bytes'] / 2**30, 3)
    return out


def _obs_cost(name, compiled, step_seconds):
    """Register one AOT-compiled leg in the --obs-dir efficiency.json
    (exact Compiled.cost_analysis totals + post-GSPMD collectives)."""
    if _OBS is not None:
        _OBS.record_cost(name, compiled, step_time_s=step_seconds)


def _best_of(run_window, windows=3):
    """Minimum wall-clock seconds of ``run_window()`` over several windows.

    The tunneled chip is shared, so effective speed varies with external
    load; the minimum is the least-contended estimate.
    """
    best = float('inf')
    for _ in range(windows):
        t0 = time.perf_counter()
        run_window()
        best = min(best, time.perf_counter() - t0)
    return best


def _fence(scalar):
    """Force completion by fetching a scalar to host.

    ``block_until_ready`` is the natural fence, but on the tunneled TPU
    platform used here it intermittently returns before the computation has
    actually run, producing absurd timings (sub-ms for a 15k x 20k training
    step). A device-to-host fetch of one element cannot lie.
    """
    return float(scalar)


def build_dense(bf16=False):
    from dgmc_tpu.data import (Cartesian, Compose, Constant, KNNGraph,
                               RandomGraphPairs)
    from dgmc_tpu.models import DGMC, SplineCNN
    from dgmc_tpu.train import create_train_state, make_train_step
    from dgmc_tpu.utils import PairLoader

    transform = Compose([Constant(), KNNGraph(k=8), Cartesian()])
    ds = RandomGraphPairs(min_inliers=30, max_inliers=60, min_outliers=0,
                          max_outliers=4, transform=transform, length=BATCH,
                          seed=0)
    loader = PairLoader(ds, BATCH, shuffle=False, num_nodes=NUM_NODES,
                        num_edges=NUM_EDGES)
    batch = jax.device_put(next(iter(loader)))

    from dgmc_tpu.models.precision import get as get_precision
    dt = get_precision('bf16' if bf16 else 'f32').compute_dtype
    psi_1 = SplineCNN(1, 256, dim=2, num_layers=2, cat=False, lin=True,
                      dropout=0.0, dtype=dt)
    psi_2 = SplineCNN(64, 64, dim=2, num_layers=2, cat=True, lin=True,
                      dtype=dt)
    model = DGMC(psi_1, psi_2, num_steps=NUM_STEPS, k=-1, dtype=dt)
    state = create_train_state(model, jax.random.key(0), batch,
                               learning_rate=1e-3)
    step = make_train_step(model, loss_on_s0=True)
    step = _aot_compile(step, state, batch, jax.random.key(1))
    return state, step, batch


def bench_dense(bf16=False):
    state, step, batch = build_dense(bf16=bf16)
    key = jax.random.key(1)

    for _ in range(WARMUP):
        key, sub = jax.random.split(key)
        with _prof_step():
            state, out = step(state, batch, sub)
    _fence(out['loss'])

    loss = np.nan

    def window():
        nonlocal state, key, loss
        for _ in range(ITERS):
            key, sub = jax.random.split(key)
            with _prof_step():
                state, out = step(state, batch, sub)
        loss = _fence(out['loss'])

    dt = _best_of(window)
    assert np.isfinite(loss)
    _obs_cost('dense_bf16' if bf16 else 'dense_f32', step, dt / ITERS)
    return BATCH * ITERS / dt, _perf_stats(step, dt / ITERS)


def _kg_side(n, e, dim, rng, gather_dtype=None, reps=1):
    from dgmc_tpu.ops import GraphBatch
    from dgmc_tpu.ops.blocked import attach_blocks, repeat_graph

    # gather_dtype is pinned explicitly per leg: None for the f32 leg,
    # 'bfloat16' for the bf16-policy leg (matching experiments/dbp15k.py),
    # so what each recorded number measures never depends on a library
    # default. Blocked once at B=1; pair replicas are tiled.
    side = attach_blocks(GraphBatch(
        x=rng.randn(1, n, dim).astype(np.float32),
        senders=rng.randint(0, n, (1, e)).astype(np.int32),
        receivers=rng.randint(0, n, (1, e)).astype(np.int32),
        node_mask=np.ones((1, n), bool),
        edge_mask=np.ones((1, e), bool),
        edge_attr=None), gather_dtype=gather_dtype)
    return repeat_graph(side, reps)


def _bench_sparse_leg(bf16, pairs=1):
    """One DBP15K-scale sparse training step under one precision policy,
    ``pairs`` pair-replicas per step (the CLI's --pairs-per-step; each
    replica draws independent per-pair indicator noise / negatives)."""
    from dgmc_tpu.models import DGMC, RelCNN
    from dgmc_tpu.models.precision import get as get_precision
    from dgmc_tpu.train import create_train_state, make_train_step
    from dgmc_tpu.utils.data import PairBatch

    prec = get_precision('bf16' if bf16 else 'f32')
    gd = prec.gather_dtype
    dt = prec.compute_dtype
    rng = np.random.RandomState(0)
    s = _kg_side(SP_N_S, SP_E_S, SP_DIM, rng, gather_dtype=gd, reps=pairs)
    t = _kg_side(SP_N_T, SP_E_T, SP_DIM, rng, gather_dtype=gd, reps=pairs)
    y = np.full((1, SP_N_S), -1, np.int32)
    train_n = int(0.3 * SP_N_S)   # the reference's 30% seed alignment split
    y[0, :train_n] = rng.permutation(SP_N_T)[:train_n]
    y = np.repeat(y, pairs, axis=0)
    batch = jax.device_put(PairBatch(s=s, t=t, y=y, y_mask=y >= 0))
    jax.block_until_ready(batch)

    psi_1 = RelCNN(SP_DIM, 256, num_layers=3, dropout=0.5, dtype=dt)
    psi_2 = RelCNN(32, 32, num_layers=3, dtype=dt)
    model = DGMC(psi_1, psi_2, num_steps=NUM_STEPS, k=SP_K,
                 topk_block=SP_TOPK_BLOCK, dtype=dt)

    # Params are independent of graph size: init on a tiny batch to avoid
    # compiling the init program at 20k-node scale.
    tiny = PairBatch(s=_kg_side(32, 64, SP_DIM, rng),
                     t=_kg_side(32, 64, SP_DIM, rng),
                     y=np.zeros((1, 32), np.int32),
                     y_mask=np.ones((1, 32), bool))
    state = create_train_state(model, jax.random.key(0), tiny,
                               learning_rate=1e-3)
    step = make_train_step(model, loss_on_s0=False)
    step = _aot_compile(step, state, batch, jax.random.key(1))

    key = jax.random.key(1)
    for _ in range(2):
        key, sub = jax.random.split(key)
        with _prof_step():
            state, out = step(state, batch, sub)
    _fence(out['loss'])

    loss = np.nan

    def window():
        nonlocal state, key, loss
        for _ in range(SP_ITERS):
            key, sub = jax.random.split(key)
            with _prof_step():
                state, out = step(state, batch, sub)
        loss = _fence(out['loss'])

    step_ms = _best_of(window) / SP_ITERS * 1e3
    assert np.isfinite(loss)
    _obs_cost('sparse_bf16' if bf16 else 'sparse_f32', step, step_ms / 1e3)
    perf = _perf_stats(step, step_ms / 1e3)
    if pairs > 1:
        perf['pairs_per_step'] = pairs
        perf['step_ms_per_pair'] = round(step_ms / pairs, 1)
    # Padding-waste account (obs.goodput): MEASURED from the validity
    # masks, not assumed — these synthetic legs build exactly-sized
    # graphs (all-true masks) so the honest ratio is 1.0, and a future
    # bucketed bench that pads will show its real waste on this axis.
    from dgmc_tpu.obs import goodput as goodput_mod
    gr = goodput_mod.goodput_ratio(goodput_mod.pair_fills(
        goodput_mod.mask_fills(s.node_mask, s.edge_mask),
        goodput_mod.mask_fills(t.node_mask, t.edge_mask)))
    if gr is not None:
        perf['goodput_ratio'] = round(gr, 4)
    # Live allocator peak is PROCESS-LIFETIME: only the first (f32) leg
    # can attribute it; later legs would just echo the earlier maximum,
    # so they keep the per-executable static bound from memory_analysis.
    if not bf16:
        mem = jax.local_devices()[0].memory_stats() or {}
        peak = mem.get('peak_bytes_in_use')
        if peak:
            perf['peak_hbm_gib'] = round(peak / 2**30, 3)
    return step_ms, perf


def bench_sparse():
    """DBP15K-scale sparse training step, both precision policies, the
    standalone candidate-search comparison (Pallas kernel vs the jnp
    scan fallback — the kernel ignores tile-size knobs, so a block sweep
    of it would measure the same kernel repeatedly; r03's did), and the
    ``--pairs-per-step`` batch-scaling sweep (B ∈ {1,2,4,8} on the
    flagship policy, ``step_ms_per_pair`` per point, fault-tolerant
    per-variant)."""
    from dgmc_tpu.ops.topk import chunked_topk

    # Legs pre-initialize to None so a --section-timeout'd section
    # (SectionTimeout swallowed by _section) degrades to a missing leg
    # in the result instead of an unbound variable.
    f32_ms = f32_perf = step_ms = perf = None
    with _section('sparse_f32'):
        f32_ms, f32_perf = _bench_sparse_leg(bf16=False)
    with _section('sparse_bf16'):
        # Flagship: bf16 policy at SP_PAIRS pair-replicas per step (see
        # the SP_PAIRS note; per-pair normalization keeps vs_baseline
        # comparable with the stored B=1 baseline).
        step_ms, perf = _bench_sparse_leg(bf16=True, pairs=SP_PAIRS)

    rng = np.random.RandomState(0)
    h_s = jnp.asarray(rng.randn(1, SP_N_S, 256).astype(np.float32))
    h_t = jnp.asarray(rng.randn(1, SP_N_T, 256).astype(np.float32))

    from dgmc_tpu.parallel import make_mesh
    from dgmc_tpu.parallel.topk import sharded_topk_rows
    mesh1 = make_mesh(data=1, model=1)
    runners = (
        ('pallas', jax.jit(lambda a, b: chunked_topk(a, b, SP_K,
                                                     pallas=True))),
        # The scan's best-known tiling is block=1024 (topk_tpu.json: 86 ms
        # vs 211 ms for the sort form); block=256 suits only the Pallas
        # path's fallbacks elsewhere.
        ('scan', jax.jit(lambda a, b: chunked_topk(
            a, b, SP_K, pallas=False, block=1024))),
        # Kernel inside shard_map manual mode (1-chip mesh): proves the
        # sharded path runs at kernel speed, not the silenced fallback.
        ('shard_map', jax.jit(lambda a, b: sharded_topk_rows(
            mesh1, a, b, SP_K))),
    )
    topk_ms = {}
    for name, f in runners:
        # One failing variant (e.g. the Pallas kernel on a CPU-only
        # container: "Only interpret mode is supported") must not
        # destroy the MEASURED step legs above it — record the error
        # under the variant, like the section ledger does, and sweep
        # on. A SectionTimeout is already swallowed by _section.
        try:
            with _section(f'topk_{name}'):
                _fence(f(h_s, h_t)[0, 0, 0])

                def window(f=f):
                    for _ in range(TOPK_ITERS):
                        with _prof_step():
                            out = f(h_s, h_t)
                    _fence(out[0, 0, 0])

                topk_ms[name] = round(
                    _best_of(window) / TOPK_ITERS * 1e3, 2)
        except Exception as e:   # SectionTimeout never escapes _section
            topk_ms[name] = {'error': f'{type(e).__name__}: {e}'}

    # --pairs-per-step batch-scaling sweep (ROADMAP item 3's owed leg):
    # step_ms_per_pair across B ∈ {1, 2, 4, 8} on the sparse flagship
    # policy — the curve that says where batching stops buying MXU
    # utilization. The flagship's own B=SP_PAIRS measurement anchors its
    # point (no duplicate run); every other point is fault-tolerant
    # per-variant exactly like the top-k sweep above — one timed-out or
    # OOM'd batch size is recorded as such and the sweep moves on.
    pairs_sweep = {}
    if step_ms is not None:
        pairs_sweep[str(SP_PAIRS)] = {
            'step_ms': round(step_ms, 1),
            'step_ms_per_pair': perf.get('step_ms_per_pair',
                                         round(step_ms / SP_PAIRS, 1)),
            'goodput_ratio': perf.get('goodput_ratio'),
            'source': 'flagship'}
    for b in (p for p in (1, 2, 4, 8) if str(p) not in pairs_sweep):
        res = None
        try:
            with _section(f'pairs_b{b}'):
                b_ms, b_perf = _bench_sparse_leg(bf16=True, pairs=b)
                res = {'step_ms': round(b_ms, 1),
                       'step_ms_per_pair': b_perf.get(
                           'step_ms_per_pair', round(b_ms / b, 1)),
                       **{k: b_perf[k] for k in
                          ('mfu', 'arith_intensity', 'goodput_ratio')
                          if k in b_perf}}
        except Exception as e:   # SectionTimeout never escapes _section
            res = {'error': f'{type(e).__name__}: {e}'}
        if res is None:
            res = {'error': 'timeout'}
        pairs_sweep[str(b)] = res

    out = {'shape': f'{SP_N_S}x{SP_N_T} k={SP_K} steps={NUM_STEPS}',
           'topk_ms': topk_ms,
           'pairs_sweep': pairs_sweep}
    # Batching-headroom estimate (obs.capacity): projected QPS per batch
    # size from the sweep's measured per-pair step time — what seeds the
    # serve rounds' capacity model.
    from dgmc_tpu.obs.capacity import batching_headroom
    per_pair = {b: leg['step_ms_per_pair'] for b, leg in pairs_sweep.items()
                if isinstance(leg, dict)
                and leg.get('step_ms_per_pair') is not None}
    if per_pair:
        out['batching_headroom'] = batching_headroom(per_pair)
    if step_ms is not None:
        # Flagship leg: the bf16 compute policy (quality-gated; see
        # module docstring) at SP_PAIRS pairs per step.
        out.update(step_ms=round(step_ms, 1), flagship='bf16', **perf)
    if f32_ms is not None:
        out['f32'] = {'step_ms': round(f32_ms, 1), **f32_perf}
    return out


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    from dgmc_tpu.obs import (RunObserver, add_obs_flag, add_profile_flag,
                              start_profile)
    add_obs_flag(parser)
    add_profile_flag(parser)
    parser.add_argument(
        '--section-timeout', '--section_timeout', dest='section_timeout',
        type=float, default=0.0, metavar='SEC',
        help='per-section deadline budget: a section exceeding SEC '
             'seconds is recorded as timed out and the run moves on, so '
             'one stuck section cannot consume the whole run (0 = off)')
    from dgmc_tpu.resilience import add_supervisor_args
    add_supervisor_args(parser)
    args = parser.parse_args(argv)
    if args.supervise:
        # Crash/hang recovery loop (dgmc_tpu/resilience/supervisor.py):
        # the bench re-runs whole (no checkpoint), so a wedged or killed
        # attempt is retried with backoff; repeated same-point failures
        # degrade to the XLA fallbacks via DGMC_TPU_DISABLE_FUSED. The
        # child is this script, not a -m module.
        from dgmc_tpu.resilience.supervisor import supervise_cli
        sys.exit(supervise_cli(
            None, args, argv, ladder=('disable-fused',),
            cmd=[sys.executable, os.path.abspath(__file__)]))
    _SECTION_TIMEOUT['seconds'] = max(0.0, args.section_timeout)
    # Bench's own handlers FIRST, then the observer: the watchdog chains
    # to whatever was installed before it, so a SIGTERM dumps
    # hang_report.json and THEN prints the partial line + exit 124.
    _install_signal_handlers()
    global _OBS
    if args.obs_dir or args.probes or args.watchdog_deadline \
            or args.obs_port is not None:
        # --probes without --obs-dir still flips the trace-time probe
        # switch (a disabled observer carries no sink) so a probe-overhead
        # bench run measures what it claims to — same contract as the
        # experiment CLIs, which construct their observer unconditionally.
        # SIGALRM stays bench's alone (--section-timeout budgets); the
        # watchdog arms SIGTERM only.
        _OBS = RunObserver(args.obs_dir, probes=args.probes,
                           watchdog_deadline_s=args.watchdog_deadline,
                           fence_deadline_s=args.fence_deadline,
                           watchdog_signals=(signal.SIGTERM,),
                           obs_port=args.obs_port)
    global _PROF
    _PROF = prof = start_profile(args.profile_dir,
                                 steps=args.profile_steps)

    # Sparse first: the allocator's peak_bytes_in_use is process-lifetime,
    # so the sparse leg must run before anything else allocates if its
    # reported peak is to be attributable to the DBP15K workload.
    try:
        sparse = bench_sparse()
    except Exception as e:  # never let the sparse leg kill the primary line
        sparse = {'error': f'{type(e).__name__}: {e}'}
    pairs_per_sec, dense_stats = None, {}
    with _section('dense_f32'):
        pairs_per_sec, dense_stats = bench_dense()
    try:
        with _section('dense_bf16'):
            bf16_pps, bf16_stats = bench_dense(bf16=True)
        dense_bf16 = ({'pairs_per_sec': round(bf16_pps, 2), **bf16_stats}
                      if not _PROGRESS['sections'].get(
                          'dense_bf16', {}).get('timeout')
                      else {'error': 'timeout'})
    except Exception as e:
        dense_bf16 = {'error': f'{type(e).__name__}: {e}'}

    platform = str(jax.devices()[0].platform)
    stored = {}
    if os.path.exists(BASELINE_FILE):
        with open(BASELINE_FILE) as f:
            stored = json.load(f)
        # A baseline recorded on another platform (e.g. CPU smoke run) or
        # under a different measurement protocol would make vs_baseline
        # meaningless — re-seed it instead.
        if (stored.get('device') != platform or
                stored.get('protocol') != PROTOCOL):
            stored = {}

    baseline = stored.get('value')
    sparse_baseline_ms = stored.get('sparse_step_ms')
    reseed = not stored
    if baseline is None and pairs_per_sec is not None:
        baseline = pairs_per_sec
        reseed = True
    if sparse_baseline_ms is None and 'f32' in sparse:
        # Seed the sparse baseline from the F32 leg ONLY: the baseline
        # contract (module docstring) is an f32-policy number, so a
        # fresh environment pins the same policy the shipped baseline
        # used — otherwise the bf16 flagship would seed itself and read
        # 1.0 forever while the f32 extra read as a fake regression.
        # No fallback to the bf16 step_ms: with --section-timeout the
        # f32 leg can now be missing while bf16 completed, and seeding
        # the f32-policy baseline from a bf16 measurement would fake a
        # permanent regression on every later full run. Leave the
        # baseline unseeded; the next run with a complete f32 leg
        # seeds it.
        sparse_baseline_ms = sparse['f32']['step_ms']
        reseed = True
    if reseed and baseline is not None:
        with open(BASELINE_FILE, 'w') as f:
            json.dump({'metric': 'train_pairs_per_sec', 'value': baseline,
                       'sparse_step_ms': sparse_baseline_ms,
                       'device': platform, 'protocol': PROTOCOL}, f)

    if 'step_ms' in sparse and sparse_baseline_ms:
        # Per-pair normalization: a batched flagship step does
        # pairs_per_step pairs of work, so the unit the baseline prices
        # (one pair-step) is step_ms / pairs (step_ms_per_pair).
        per_pair = sparse.get('step_ms_per_pair', sparse['step_ms'])
        sparse['vs_baseline'] = round(sparse_baseline_ms / per_pair, 4)
        if 'f32' in sparse:
            sparse['f32']['vs_baseline'] = round(
                sparse_baseline_ms / sparse['f32']['step_ms'], 4)
    rec = {
        'metric': 'train_pairs_per_sec',
        'value': None if pairs_per_sec is None else round(pairs_per_sec, 2),
        'unit': 'pairs/sec',
        'device': str(jax.devices()[0].device_kind),
        'dense_perf': dense_stats,
        'dense_bf16': dense_bf16,
        'sparse_dbp15k': sparse,
        'sections': _PROGRESS['sections'],
    }
    if pairs_per_sec is not None and baseline:
        rec['vs_baseline'] = round(pairs_per_sec / baseline, 4)
    if any(s.get('timeout') for s in _PROGRESS['sections'].values()):
        # Some section blew its --section-timeout budget: the line is
        # still parseable, with every completed section's numbers.
        rec['partial'] = True
    print(json.dumps(rec))
    prof.close()
    if _OBS is not None:
        _OBS.close()


if __name__ == '__main__':
    main()
