"""End-to-end smoke runs of every experiment script on tiny fixture data —
the four workloads of SURVEY.md §2.2, exercised through their CLIs."""

import jax
import json

import numpy as np
import pytest


@pytest.fixture
def dbp_root(tmp_path):
    from tests.helpers import make_tiny_dbp15k
    return make_tiny_dbp15k(tmp_path)


@pytest.fixture
def voc_root(tmp_path):
    from dgmc_tpu.datasets.pascal_voc import CATEGORIES
    rng = np.random.RandomState(1)
    kp_names = ['a', 'b', 'c', 'd', 'e', 'f']
    for cat in CATEGORIES:
        ann = tmp_path / 'annotations' / cat
        ann.mkdir(parents=True)
        for i in range(4):
            pts = rng.rand(len(kp_names), 2) * 80 + 10
            kps = '\n'.join(
                f'<keypoint name="{n}" x="{pts[j, 0]:.1f}" '
                f'y="{pts[j, 1]:.1f}" visible="1"/>'
                for j, n in enumerate(kp_names))
            (ann / f'{2008 + i}_{i:04d}.xml').write_text(
                f'<annotation><image>im_{cat}_{i}</image>'
                f'<visible_bounds xmin="0" ymin="0" xmax="100" ymax="100"/>'
                f'<keypoints>{kps}</keypoints></annotation>')
    return tmp_path


@pytest.fixture
def willow_root(tmp_path):
    from PIL import Image
    from scipy.io import savemat
    from dgmc_tpu.datasets.willow import _DIRNAMES
    rng = np.random.RandomState(2)
    for dirname in _DIRNAMES.values():
        base = tmp_path / 'WILLOW-ObjectClass' / dirname
        base.mkdir(parents=True)
        for i in range(22):
            savemat(str(base / f'im{i:03d}.mat'),
                    {'pts_coord': rng.rand(2, 10) * 100})
            if i == 0:  # one real image is enough; the rest fall back
                Image.fromarray(rng.randint(
                    0, 255, (32, 32, 3), dtype=np.uint8)).save(
                        str(base / f'im{i:03d}.png'))
    return tmp_path


def test_pascal_pf_runs(capsys, tmp_path):
    from examples import pascal_pf
    obs_dir = str(tmp_path / 'obs')
    state = pascal_pf.main([
        '--epochs', '1', '--batch_size', '8', '--dim', '16',
        '--rnd_dim', '8', '--num_steps', '1', '--synthetic_eval', '8',
        '--data_root', '/nonexistent', '--obs-dir', obs_dir])
    assert state is not None
    # The held-out synthetic eval (the offline stand-in for the real
    # PascalPF zero-shot eval) must have run and printed a number.
    assert 'Held-out synthetic:' in capsys.readouterr().out

    # --obs-dir produced all four telemetry artifacts, and the report
    # summary carries step percentiles, a compile count, a memory peak
    # and the CPU-forced kernel fallbacks (ISSUE acceptance contract).
    import os
    for name in ('metrics.jsonl', 'timings.json', 'memory.json',
                 'dispatch.json'):
        assert os.path.exists(os.path.join(obs_dir, name)), name
    from dgmc_tpu.obs import report
    s = report.summarize(report.load_run(obs_dir))
    assert s['steps'] > 0 and s['step_p50_s'] > 0
    assert s['compile_events'] >= 1
    assert s['peak_memory_bytes'] > 0
    assert s['dispatch_fallback'] >= 1


def test_dbp15k_runs(dbp_root):
    from examples import dbp15k
    state = dbp15k.main([
        '--category', 'zh_en', '--data_root', str(dbp_root),
        '--dim', '8', '--rnd_dim', '4', '--num_layers', '1',
        '--num_steps', '1', '--k', '2', '--epochs', '4',
        '--phase1_epochs', '2'])
    assert state is not None


def test_pascal_runs(voc_root):
    from examples import pascal
    state = pascal.main([
        '--data_root', str(voc_root), '--vgg_weights', 'none',
        '--dim', '8', '--rnd_dim', '4', '--num_layers', '1',
        '--num_steps', '1', '--batch_size', '8', '--epochs', '1',
        '--test_samples', '8'])
    assert state is not None


# Willow repeats the keypoint-CLI shape pascal already smokes in
# tier-1, at ~21s for the two-run transfer protocol; tier-2 keeps it.
@pytest.mark.slow
def test_willow_runs(voc_root, willow_root):
    from examples import willow
    accs = willow.main([
        '--voc_root', str(voc_root), '--willow_root', str(willow_root),
        '--vgg_weights', 'none', '--dim', '8', '--rnd_dim', '4',
        '--num_layers', '1', '--num_steps', '1', '--batch_size', '8',
        '--pre_epochs', '1', '--epochs', '1', '--runs', '2',
        '--test_samples', '8'])
    assert accs.shape == (2, 5)
    assert np.isfinite(accs).all()


# A second full dbp15k CLI run on top of test_dbp15k_runs (~22s);
# the resume path itself is covered by the checkpoint-manager tests.
@pytest.mark.slow
def test_dbp15k_resumes_mid_schedule(dbp_root, tmp_path, capsys):
    """Kill/restart lands in the right phase with the right step: run the
    two-phase schedule to completion once, then restart from the epoch-2
    checkpoint and check the resumed run crosses into phase 2 and matches
    the uninterrupted run's final params exactly (same PRNG stream)."""
    from examples import dbp15k
    ckpt = str(tmp_path / 'ckpt')
    args = ['--category', 'zh_en', '--data_root', str(dbp_root),
            '--dim', '8', '--rnd_dim', '4', '--num_layers', '1',
            '--num_steps', '1', '--k', '2', '--epochs', '4',
            '--phase1_epochs', '2', '--ckpt_every', '2',
            '--metrics_log', str(tmp_path / 'metrics.jsonl')]
    full = dbp15k.main(args + ['--ckpt_dir', ckpt + '_full'])

    # Simulate a crash after epoch 2 (phase 1): a fresh directory seeded
    # with only the epoch-2 checkpoint.
    import orbax.checkpoint as ocp
    mgr = ocp.CheckpointManager(ckpt)
    mgr.save(2, args=ocp.args.StandardSave(
        dbp15k.main(args[:-2] + ['--epochs', '2'])))
    mgr.wait_until_finished()
    mgr.close()

    resumed = dbp15k.main(args + ['--ckpt_dir', ckpt])
    out = capsys.readouterr().out
    assert 'Resumed from' in out
    assert 'Refine correspondence matrix...' in out  # crossed into phase 2
    for a, b in zip(jax.tree.leaves(full.params),
                    jax.tree.leaves(resumed.params)):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)

    lines = (tmp_path / 'metrics.jsonl').read_text().splitlines()
    assert any(json.loads(ln).get('phase') == 2 for ln in lines)


@pytest.mark.slow
def test_dbp15k_model_shards_cli(dbp_root):
    """The --model_shards flag drives the GSPMD corr-sharded path (the
    scale-out axis the reference lacks); on the virtual 8-device CPU
    platform two model shards must train and evaluate end to end.
    Tier-2: the sharded-corr parity itself is pinned by
    tests/parallel/test_sharding.py in tier-1; this adds the CLI
    wiring on top (~12s)."""
    from examples import dbp15k
    state = dbp15k.main([
        '--category', 'zh_en', '--data_root', str(dbp_root),
        '--dim', '8', '--rnd_dim', '4', '--num_layers', '1',
        '--num_steps', '1', '--k', '2', '--epochs', '2',
        '--phase1_epochs', '1', '--model_shards', '2'])
    assert state is not None
