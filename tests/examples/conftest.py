"""De-flake fixture: the example-CLI smoke tests must not read the
persistent XLA compilation cache.

Same bug family tests/parallel/conftest.py root-caused on this
container's jax 0.4.37: executables with donated inputs round-trip
through the persistent compilation cache with broken input-output
aliasing. Here the trigger is the dbp15k resume test — three
``dbp15k.main`` invocations compile the SAME donating train step, so
from the second one on every compile is a persistent-cache HIT; the
deserialized executable releases the donated state buffers and then
reads them, which segfaults the whole pytest process (observed
deterministically with a warm ``tests/.jax_compile_cache``; a cold
cache run passes and then poisons the next). A fresh in-process compile
of the same program is always correct.

Scoped to this package like the tests/parallel fixture: these tests run
full CLI mains whose train steps donate. ``is_cache_used`` latches
process-wide on first use, so the fixture resets the cache on both
transitions — flipping the config flag alone is silently ignored.
"""

import jax
import pytest


@pytest.fixture(autouse=True)
def _no_persistent_compile_cache():
    from jax._src import compilation_cache

    prev = jax.config.jax_enable_compilation_cache
    jax.config.update('jax_enable_compilation_cache', False)
    compilation_cache.reset_cache()  # un-latch is_cache_used
    try:
        yield
    finally:
        jax.config.update('jax_enable_compilation_cache', prev)
        compilation_cache.reset_cache()
