"""OS-level fault injection: SIGKILL a training run, restart, verify
recovery (SURVEY.md §5 failure-detection/elastic row).

The in-process resume test (`test_examples_smoke.py`) checks restore
*logic*; this one checks the actual crash path: a subprocess running the
DBP15K two-phase schedule is killed with SIGKILL the moment its first
checkpoint lands on disk (so partial writes, unflushed logs and an
optimizer mid-step are all in play), then the identical command reruns
and must auto-resume past the killed epoch and finish the schedule.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

ARGS = ['--category', 'zh_en', '--dim', '8', '--rnd_dim', '4',
        '--num_layers', '1', '--num_steps', '1', '--k', '2',
        '--epochs', '6', '--phase1_epochs', '3', '--ckpt_every', '1']

WORKER = r'''
import sys
import jax
jax.config.update('jax_platforms', 'cpu')
sys.path.insert(0, {repo!r})
from dgmc_tpu.experiments import dbp15k
dbp15k.main({args!r})
print('RUN COMPLETE', flush=True)
'''


def _spawn(repo, args, out_path):
    # stdout goes to a FILE, not a pipe: an undrained pipe can block a
    # chatty worker before its first checkpoint and deadlock the test.
    fh = open(out_path, 'w')
    proc = subprocess.Popen(
        [sys.executable, '-c', WORKER.format(repo=repo, args=args)],
        stdout=fh, stderr=subprocess.STDOUT, text=True, cwd=repo)
    proc._out_fh = fh
    return proc


def _finish(proc):
    proc._out_fh.close()


@pytest.mark.slow
def test_sigkill_and_resume(tmp_path):
    from tests.helpers import make_tiny_dbp15k
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    data = make_tiny_dbp15k(tmp_path / 'data')
    ckpt = str(tmp_path / 'ckpt')
    log = str(tmp_path / 'metrics.jsonl')
    args = ARGS + ['--data_root', data, '--ckpt_dir', ckpt,
                   '--metrics_log', log]
    v_out, s_out = str(tmp_path / 'victim.log'), str(tmp_path / 'surv.log')

    victim = _spawn(repo, args, v_out)
    survivor = None
    try:
        # Kill as soon as any checkpoint step directory exists.
        deadline = time.time() + 300
        killed_after = None
        while time.time() < deadline:
            if victim.poll() is not None:  # finished before we could kill
                break
            steps = [int(p) for p in os.listdir(ckpt)
                     if os.path.isdir(os.path.join(ckpt, p)) and p.isdigit()
                     ] if os.path.isdir(ckpt) else []
            if steps:
                killed_after = max(steps)
                victim.send_signal(signal.SIGKILL)
                victim.wait(timeout=60)
                break
            time.sleep(0.2)
        assert killed_after is not None, (
            'no checkpoint appeared in time; victim output:\n'
            + open(v_out).read()[-2000:])

        survivor = _spawn(repo, args, s_out)
        survivor.wait(timeout=600)
    finally:
        for p in (victim, survivor):
            if p is not None and p.poll() is None:
                p.kill()
                p.wait()
            if p is not None:
                _finish(p)

    out = open(s_out).read()
    assert survivor.returncode == 0, out[-3000:]
    assert 'Resumed from' in out, out[-3000:]
    assert 'RUN COMPLETE' in out
    # The resumed run crossed into phase 2 and reached the final epoch.
    with open(log) as f:
        events = [json.loads(line) for line in f]
    assert any(e.get('event') == 'resume' for e in events)
    assert any(e.get('phase') == 2 and e.get('step') == 6 for e in events)