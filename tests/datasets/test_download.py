"""Dataset acquisition plumbing (fetch/extract) — tested with file:// URLs
since this environment has no network egress. Loader default behavior
(raise-with-instructions, no download attempted) is also pinned."""

import os
import tarfile
import zipfile

import pytest

from dgmc_tpu.datasets import download as dl


def test_fetch_file_url(tmp_path):
    src = tmp_path / 'payload.bin'
    src.write_bytes(b'hello dataset')
    dest = tmp_path / 'out' / 'payload.bin'
    dl.fetch(src.as_uri(), str(dest))
    assert dest.read_bytes() == b'hello dataset'


def test_fetch_failure_cleans_up_and_instructs(tmp_path):
    dest = tmp_path / 'missing.bin'
    with pytest.raises(RuntimeError, match='manually'):
        dl.fetch((tmp_path / 'nope.bin').as_uri(), str(dest))
    assert not dest.exists()
    assert not (tmp_path / 'missing.bin.part').exists()


@pytest.mark.parametrize('kind', ['zip', 'tar'])
def test_download_and_extract_roundtrip(tmp_path, monkeypatch, kind):
    inner = tmp_path / 'build' / 'DATA' / 'f.txt'
    inner.parent.mkdir(parents=True)
    inner.write_text('contents')
    if kind == 'zip':
        archive = tmp_path / 'data.zip'
        with zipfile.ZipFile(archive, 'w') as z:
            z.write(inner, 'DATA/f.txt')
    else:
        archive = tmp_path / 'data.tar.gz'
        with tarfile.open(archive, 'w:gz') as t:
            t.add(inner, 'DATA/f.txt')
    monkeypatch.setitem(dl.URLS, 'fake', archive.as_uri())

    root = tmp_path / 'root'
    dl.download_and_extract('fake', str(root))
    assert (root / 'DATA' / 'f.txt').read_text() == 'contents'
    # archive removed by default
    assert not (root / archive.name).exists()


def test_loaders_stay_offline_by_default(tmp_path):
    from dgmc_tpu.datasets import DBP15K, PascalPF
    with pytest.raises(FileNotFoundError, match='download=True'):
        DBP15K(str(tmp_path), 'zh_en')
    with pytest.raises(FileNotFoundError, match='download=True'):
        PascalPF(str(tmp_path), 'aeroplane')