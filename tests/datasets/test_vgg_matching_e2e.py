"""Converted-VGG parity pipeline, executed end to end: a
torchvision-layout checkpoint -> ``convert_state_dict`` -> ``.npz`` ->
``VGG16Features`` -> PascalVOC keypoint dataset (real images) -> DGMC
training step. The reference's pascal/willow numbers ride on pretrained
VGG16 features (reference ``examples/pascal.py:5``, ``willow.py:7-8``);
the pretrained file cannot ship in this sandbox, so this test proves the
whole conversion-to-matching path is EXECUTED code on a synthesized
checkpoint with the exact torchvision key/shape layout (VERDICT r4
missing-item 2 / next-round item 7).
"""

import numpy as np
import pytest

pytest.importorskip('PIL')
pytest.importorskip('scipy')

import jax  # noqa: E402

from dgmc_tpu.data import Cartesian, Compose, Delaunay, FaceToEdge  # noqa: E402
from dgmc_tpu.datasets import VGG16Features  # noqa: E402
from dgmc_tpu.datasets.convert_vgg import convert_state_dict  # noqa: E402
from dgmc_tpu.datasets.pascal_voc import PascalVOCKeypoints  # noqa: E402
from dgmc_tpu.models import DGMC, SplineCNN  # noqa: E402
from dgmc_tpu.train import create_train_state, make_train_step  # noqa: E402
from dgmc_tpu.utils import PairLoader, ValidPairDataset  # noqa: E402


def _synthetic_checkpoint(seed=0):
    """Torchvision-VGG16-layout state dict: same keys and shapes, random
    values (no torch needed — the converter takes any array mapping)."""
    from dgmc_tpu.datasets.convert_vgg import CONV_INDICES, CONV_SHAPES
    rng = np.random.RandomState(seed)
    sd = {}
    for idx, (c_out, c_in) in zip(CONV_INDICES, CONV_SHAPES):
        sd[f'features.{idx}.weight'] = (
            rng.randn(c_out, c_in, 3, 3) * np.sqrt(2.0 / (9 * c_in))
        ).astype(np.float32)
        sd[f'features.{idx}.bias'] = (rng.randn(c_out) * 0.01
                                      ).astype(np.float32)
    return sd


def _voc_category_root(tmp_path, category='aeroplane', n=4):
    """One category of Berkeley-style annotations WITH images, so the VGG
    forward actually runs on pixels (the smoke fixtures omit images and
    fall back to zeros)."""
    from PIL import Image
    rng = np.random.RandomState(3)
    ann = tmp_path / 'annotations' / category
    images = tmp_path / 'images'
    ann.mkdir(parents=True)
    images.mkdir()
    kp_names = ['a', 'b', 'c', 'd', 'e']
    for i in range(n):
        pts = rng.rand(len(kp_names), 2) * 80 + 10
        kps = '\n'.join(
            f'<keypoint name="{nm}" x="{pts[j, 0]:.1f}" '
            f'y="{pts[j, 1]:.1f}" visible="1"/>'
            for j, nm in enumerate(kp_names))
        (ann / f'{2008 + i}_{i:04d}.xml').write_text(
            f'<annotation><image>im_{i}</image>'
            f'<visible_bounds xmin="0" ymin="0" xmax="100" ymax="100"/>'
            f'<keypoints>{kps}</keypoints></annotation>')
        Image.fromarray(rng.randint(0, 255, (100, 100, 3),
                                    dtype=np.uint8)).save(
            str(images / f'im_{i}.png'))
    return tmp_path


def test_checkpoint_to_features_to_matching(tmp_path):
    # 1. Checkpoint -> converter -> npz (the dgmc-convert-vgg16 layout).
    npz_path = tmp_path / 'vgg16.npz'
    np.savez(npz_path, **convert_state_dict(_synthetic_checkpoint()))

    # 2. npz -> extractor (small input_size keeps the 13-conv forward
    #    test-cheap; taps and sampling are size-agnostic).
    features = VGG16Features(weights=str(npz_path), input_size=64)
    assert features.tag == 'vgg16'

    # 3. Extractor -> dataset: per-keypoint features must come from the
    #    converted weights (non-zero, unlike the 'none' fallback).
    root = _voc_category_root(tmp_path)
    transform = Compose([Delaunay(), FaceToEdge(), Cartesian()])
    ds = PascalVOCKeypoints(str(root), 'aeroplane', train=True,
                            transform=transform, features=features)
    assert len(ds) > 0
    assert ds.num_node_features == 1024  # relu4_2 (512) + relu5_1 (512)
    assert any(float(np.abs(g.x).max()) > 0 for g in ds)

    # 4. Dataset -> matching: one DGMC training step on VGG features.
    pairs = ValidPairDataset(ds, ds, sample=True, seed=0)
    batch = next(iter(PairLoader(pairs, 4, shuffle=False, num_nodes=8,
                                 num_edges=32)))
    psi_1 = SplineCNN(ds.num_node_features, 16, dim=2, num_layers=2,
                      cat=False, lin=True)
    psi_2 = SplineCNN(8, 8, dim=2, num_layers=2, cat=True, lin=True)
    model = DGMC(psi_1, psi_2, num_steps=2, k=-1)
    state = create_train_state(model, jax.random.key(0), batch,
                               learning_rate=1e-3)
    step = make_train_step(model, loss_on_s0=True)
    state, out = step(state, batch, jax.random.key(1))
    assert np.isfinite(float(out['loss']))
