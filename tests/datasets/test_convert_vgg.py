"""VGG16 weight conversion: torchvision checkpoint -> npz -> extractor
activation parity against an independent torch forward of the same weights.
"""

import numpy as np
import pytest

torch = pytest.importorskip('torch')  # CI's [test] extra has no torch
import torch.nn.functional as F  # noqa: E402

from dgmc_tpu.datasets import VGG16Features, convert_checkpoint
from dgmc_tpu.datasets.convert_vgg import (CONV_INDICES, CONV_SHAPES,
                                           convert_state_dict)
from dgmc_tpu.datasets.features import (IMAGENET_MEAN, IMAGENET_STD,
                                        TAP_RELU4_2, TAP_RELU5_1, VGG_CFG)


def synthetic_state_dict(seed=0):
    """A torchvision-VGG16-shaped state dict with small random weights
    (plus classifier entries the converter must ignore)."""
    rng = np.random.RandomState(seed)
    sd = {}
    for idx, (c_out, c_in) in zip(CONV_INDICES, CONV_SHAPES):
        sd[f'features.{idx}.weight'] = torch.tensor(
            (rng.randn(c_out, c_in, 3, 3)
             * np.sqrt(2.0 / (9 * c_in))).astype(np.float32))
        sd[f'features.{idx}.bias'] = torch.tensor(
            (rng.randn(c_out) * 0.01).astype(np.float32))
    sd['classifier.0.weight'] = torch.zeros(8, 8)
    return sd


def torch_taps(sd, img01):
    """Independent torch forward of the conv stack: img01 [H, W, 3] in
    [0, 1] -> (relu4_2, relu5_1) activation maps [h, w, C]."""
    x = (img01 - IMAGENET_MEAN) / IMAGENET_STD
    x = torch.tensor(x.transpose(2, 0, 1)[None])
    taps, ci = [], 0
    for c in VGG_CFG:
        if c == 'M':
            x = F.max_pool2d(x, 2)
            continue
        idx = CONV_INDICES[ci]
        x = F.relu(F.conv2d(x, sd[f'features.{idx}.weight'],
                            sd[f'features.{idx}.bias'], padding=1))
        if ci in (TAP_RELU4_2, TAP_RELU5_1):
            taps.append(x[0].numpy().transpose(1, 2, 0))
        if ci == TAP_RELU5_1:
            break
        ci += 1
    return taps


def bilinear(fmap, coords01):
    """The extractor's sampling formula, independently in numpy."""
    h, w = fmap.shape[:2]
    xf = coords01[:, 0] * (w - 1)
    yf = coords01[:, 1] * (h - 1)
    x0 = np.clip(np.floor(xf).astype(int), 0, w - 2)
    y0 = np.clip(np.floor(yf).astype(int), 0, h - 2)
    dx = (xf - x0)[:, None]
    dy = (yf - y0)[:, None]
    return ((1 - dy) * ((1 - dx) * fmap[y0, x0] + dx * fmap[y0, x0 + 1]) +
            dy * ((1 - dx) * fmap[y0 + 1, x0] + dx * fmap[y0 + 1, x0 + 1]))


def test_convert_and_activation_parity(tmp_path):
    sd = synthetic_state_dict()
    src = tmp_path / 'vgg16.pth'
    torch.save(sd, str(src))
    out = convert_checkpoint(str(src), str(tmp_path / 'vgg16.npz'))

    npz = np.load(out)
    assert len(npz.files) == 26  # 13 convs x (weight, bias), head dropped
    np.testing.assert_array_equal(npz['features.0.weight'],
                                  sd['features.0.weight'].numpy())

    rng = np.random.RandomState(1)
    image = rng.randint(0, 255, (48, 64, 3)).astype(np.uint8)
    kps = np.array([[5.0, 7.0], [40.0, 30.0], [63.0, 47.0]], np.float32)

    extractor = VGG16Features(weights=out, input_size=64)
    got = extractor(image, kps)
    assert got.shape == (3, 1024)

    # Expected: PIL resize to 64x64 (as the extractor does), torch convs,
    # numpy bilinear taps.
    from PIL import Image
    img01 = np.asarray(
        Image.fromarray(image).resize((64, 64)), np.float32) / 255.0
    t4, t5 = torch_taps(sd, img01)
    coords = kps / np.array([63.0, 47.0], np.float32)
    want = np.concatenate([bilinear(t4, coords), bilinear(t5, coords)], -1)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_convert_rejects_non_vgg(tmp_path):
    sd = synthetic_state_dict()
    del sd['features.28.bias']
    with pytest.raises(KeyError, match='features.28.bias'):
        convert_state_dict(sd)

    sd = synthetic_state_dict()
    sd['features.0.weight'] = torch.zeros(64, 3, 5, 5)
    with pytest.raises(ValueError, match='shape'):
        convert_state_dict(sd)


def test_convert_cli(tmp_path):
    from dgmc_tpu.datasets import convert_vgg
    src = tmp_path / 'vgg16.pth'
    torch.save(synthetic_state_dict(), str(src))
    convert_vgg.main([str(src), str(tmp_path / 'out.npz')])
    assert VGG16Features(weights=str(tmp_path / 'out.npz')).tag == 'out'
