"""Dataset parser tests against small on-disk fixtures (the raw-file formats
of DBP15K / PascalPF / WILLOW / PascalVOC-Berkeley; no network access)."""

import json

import numpy as np
import pytest

from dgmc_tpu.datasets import (DBP15K, PascalPF, PascalVOCKeypoints,
                               VGG16Features, WILLOWObjectClass)


# ---------------------------------------------------------------------------
# Fixtures
# ---------------------------------------------------------------------------


@pytest.fixture
def dbp_root(tmp_path):
    d = tmp_path / 'zh_en'
    d.mkdir()
    # Graph 1: global entity ids 10, 11, 12; graph 2: 20, 21, 22, 23.
    (d / 'ent_ids_1').write_text('10\te1\n11\te2\n12\te3\n')
    (d / 'ent_ids_2').write_text('20\tf1\n21\tf2\n22\tf3\n23\tf4\n')
    (d / 'triples_1').write_text('10\t0\t11\n11\t1\t12\n')
    (d / 'triples_2').write_text('20\t0\t21\n21\t0\t22\n22\t1\t23\n')
    (d / 'sup_pairs').write_text('10\t20\n11\t21\n')
    (d / 'ref_pairs').write_text('12\t22\n')
    vecs = [[float(i)] * 4 for i in range(30)]
    (d / 'zh_vectorList.json').write_text(json.dumps(vecs))
    (d / 'en_vectorList.json').write_text(json.dumps(vecs))
    return tmp_path


@pytest.fixture
def pf_root(tmp_path):
    from scipy.io import savemat
    ann = tmp_path / 'PF-dataset-PASCAL' / 'Annotations' / 'car'
    ann.mkdir(parents=True)
    rng = np.random.RandomState(0)
    for name in ['2008_a', '2008_b', '2008_c']:
        kps = rng.rand(8, 2) * 100
        savemat(str(ann / f'{name}.mat'), {'kps': kps})
    return tmp_path


@pytest.fixture
def willow_root(tmp_path):
    from PIL import Image
    from scipy.io import savemat
    base = tmp_path / 'WILLOW-ObjectClass' / 'Car'
    base.mkdir(parents=True)
    rng = np.random.RandomState(0)
    for name in ['img1', 'img2']:
        pts = rng.rand(2, 10) * 200
        savemat(str(base / f'{name}.mat'), {'pts_coord': pts})
        Image.fromarray(
            rng.randint(0, 255, (64, 80, 3), dtype=np.uint8)).save(
                str(base / f'{name}.png'))
    return tmp_path


@pytest.fixture
def voc_root(tmp_path):
    ann = tmp_path / 'annotations' / 'car'
    ann.mkdir(parents=True)
    kp_names = ['wheel_f', 'wheel_b', 'light', 'mirror']
    rng = np.random.RandomState(0)
    for i in range(4):
        kps = '\n'.join(
            f'<keypoint name="{n}" x="{10 + 5 * j + i}" y="{20 + 3 * j}" '
            f'visible="1"/>'
            for j, n in enumerate(kp_names))
        (ann / f'inst_{i}.xml').write_text(f'''<annotation>
  <image>2009_{i:04d}</image>
  <visible_bounds xmin="5" ymin="10" xmax="120" ymax="90"/>
  <keypoints>{kps}</keypoints>
</annotation>''')
    return tmp_path


# ---------------------------------------------------------------------------
# Tests
# ---------------------------------------------------------------------------


def test_dbp15k_parses(dbp_root):
    ds = DBP15K(str(dbp_root), 'zh_en')
    assert ds.num_nodes1 == 3 and ds.num_nodes2 == 4
    assert ds.edge_index1.shape == (2, 2)
    assert ds.edge_index2.shape == (2, 3)
    # Local indices: global 10->0, 20->0 etc.
    np.testing.assert_array_equal(ds.train_y, [[0, 1], [0, 1]])
    np.testing.assert_array_equal(ds.test_y, [[2], [2]])
    g1, g2 = ds.graphs()
    assert g1.x.shape == (3, 4)       # W=1 word summed away
    assert g1.x.dtype == np.float32
    # Feature of entity with global id 11 (row 11 of vectorList) = 11.0.
    np.testing.assert_allclose(g1.x[1], 11.0)


def test_dbp15k_missing_raises(tmp_path):
    with pytest.raises(FileNotFoundError):
        DBP15K(str(tmp_path), 'ja_en')


def test_pascal_pf_parses(pf_root):
    ds = PascalPF(str(pf_root), 'car')
    assert len(ds) == 3
    # No parsePascalVOC.mat -> consecutive fallback pairs.
    assert len(ds.pairs) == 2
    for g_s, g_t, y in ds.pair_graphs():
        assert g_s.pos.shape[1] == 2
        assert np.abs(g_s.pos).max() <= 1.0 + 1e-6
        np.testing.assert_array_equal(y, np.arange(8))


def test_willow_parses(willow_root):
    ds = WILLOWObjectClass(str(willow_root), 'car',
                           features=VGG16Features(weights='none'))
    assert len(ds) == 2
    g = ds[0]
    assert g.x.shape == (10, 1024)
    assert g.pos.shape == (10, 2)
    np.testing.assert_allclose(g.pos.mean(axis=0), 0.0, atol=1e-4)
    np.testing.assert_array_equal(g.y, np.arange(10))
    train, test = ds.shuffled_split(1, seed=0)
    assert len(train) == 1 and len(test) == 1


def test_voc_parses_and_valid_pairs(voc_root):
    # No split lists on disk -> fraction fallback, with an explicit warning
    # that this is not the official protocol.
    with pytest.warns(UserWarning, match='fraction split'):
        ds = PascalVOCKeypoints(str(voc_root), 'car', train=True,
                                features=VGG16Features(weights='none'))
    assert len(ds) == 3          # 80% of 4
    g = ds[0]
    assert g.x.shape == (4, 1024)
    assert sorted(g.y) == [0, 1, 2, 3]

    from dgmc_tpu.utils import ValidPairDataset
    pairs = ValidPairDataset(ds, ds)
    assert len(pairs) == 9
    p = pairs[1]
    # Ground truth maps each source node to the target node of equal class.
    assert (p.t.y[p.y_col] == p.s.y).all()


def test_voc_official_split_lists(voc_root):
    # With official VOC ImageSets lists present, the split follows the
    # lists exactly (train ids in _train.txt; val ids in _val.txt with the
    # -1 "category absent" rows excluded) and no fallback warning fires.
    import warnings
    sets = voc_root / 'ImageSets' / 'Main'
    sets.mkdir(parents=True)
    (sets / 'car_train.txt').write_text('2009_0000  1\n2009_0001  1\n')
    (sets / 'car_val.txt').write_text('2009_0002  1\n2009_0003 -1\n')
    with warnings.catch_warnings():
        warnings.simplefilter('error')
        train = PascalVOCKeypoints(str(voc_root), 'car', train=True,
                                   features=VGG16Features(weights='none'))
        val = PascalVOCKeypoints(str(voc_root), 'car', train=False,
                                 features=VGG16Features(weights='none'))
    assert len(train) == 2
    assert {g.name for g in train._graphs} == {'inst_0', 'inst_1'}
    assert len(val) == 1
    assert val[0].name == 'inst_2'


def test_vgg_random_features_deterministic(willow_root):
    f1 = VGG16Features(weights='random', input_size=64)
    f2 = VGG16Features(weights='random', input_size=64)
    img = np.random.RandomState(3).randint(0, 255, (50, 60, 3),
                                           dtype=np.uint8)
    kps = np.array([[5.0, 5.0], [30.0, 20.0]])
    a, b = f1(img, kps), f2(img, kps)
    assert a.shape == (2, 1024)
    np.testing.assert_allclose(a, b)
    assert np.isfinite(a).all() and np.abs(a).sum() > 0
