"""``--pairs-per-step`` batching contract.

A batched step over pairs ``[0, N)`` must be *per-pair equivalent* to N
independent ``B=1`` steps: the per-pair RNG folding in
:meth:`DGMC.__call__` (``fold_in(stream_key, pair_offset + b)``) makes
pair ``b`` of a batched call draw exactly the indicator noise and
negative samples of a ``B=1`` call at ``pair_offset=b`` with the same
stream keys, so ``loss_per_pair[b]`` from the batched step matches that
pair's own ``B=1`` loss. (Dropout is the one coupler the contract
excludes — a batched mask draw is not per-pair foldable — so the pinned
models run dropout-free, as DGMC's ψ₂ does in every shipped config.)

Also covers the collation half: ``pad_pair_batch(pairs_per_step=N)``
tiles the pair list along the batch axis.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgmc_tpu.models import DGMC, RelCNN
from dgmc_tpu.train import create_train_state, make_train_step
from dgmc_tpu.utils.data import Graph, GraphPair, pad_pair_batch

N_NODES, N_EDGES, C = 12, 30, 16


def _pair(seed, n=N_NODES):
    r = np.random.RandomState(seed)

    def g():
        return Graph(edge_index=r.randint(0, n, (2, N_EDGES)),
                     x=r.randn(n, C).astype(np.float32))

    y = r.permutation(n).astype(np.int64)
    return GraphPair(s=g(), t=g(), y_col=y)


def _model(k):
    # Dropout-free on BOTH backbones: the per-pair equivalence contract
    # covers the noise/negatives streams (see module docstring).
    return DGMC(RelCNN(C, 12, num_layers=2, dropout=0.0),
                RelCNN(8, 8, num_layers=2, dropout=0.0),
                num_steps=2, k=k)


# The dense (-1) arm repeats the batched-vs-independent parity at the
# heaviest workload (~22s); tier-1 keeps the top-k arm.
@pytest.mark.parametrize('k', [pytest.param(-1, marks=pytest.mark.slow),
                               4])
def test_batched_losses_match_independent_steps(k):
    pairs = [_pair(s) for s in (1, 2, 3)]
    batched = pad_pair_batch(pairs, N_NODES, N_EDGES)
    model = _model(k)
    state = create_train_state(model, jax.random.key(0), batched,
                               learning_rate=1e-2)
    key = jax.random.key(7)

    step = make_train_step(model, jit=False)
    _, out = step(state, batched, key)
    assert out['loss_per_pair'].shape == (3,)

    for i, p in enumerate(pairs):
        single = pad_pair_batch([p], N_NODES, N_EDGES)
        step_i = make_train_step(model, jit=False, pair_offset=i)
        _, out_i = step_i(state, single, key)
        np.testing.assert_allclose(
            np.asarray(out['loss_per_pair'][i]),
            np.asarray(out_i['loss']), rtol=1e-5, atol=1e-6,
            err_msg=f'pair {i} (k={k})')


def test_combined_loss_is_valid_correspondence_mean():
    """The scalar trained on is the masked mean over every valid
    correspondence of the batch — with equal per-pair counts, the mean
    of the per-pair losses."""
    pairs = [_pair(s) for s in (4, 5)]
    batched = pad_pair_batch(pairs, N_NODES, N_EDGES)
    model = _model(4)
    state = create_train_state(model, jax.random.key(0), batched,
                               learning_rate=1e-2)
    _, out = make_train_step(model, jit=False)(
        state, batched, jax.random.key(3))
    np.testing.assert_allclose(np.asarray(out['loss']),
                               np.asarray(out['loss_per_pair']).mean(),
                               rtol=1e-6)


def test_pad_pair_batch_pairs_per_step_tiles():
    p = _pair(9)
    b = pad_pair_batch([p], N_NODES, N_EDGES, pairs_per_step=3)
    assert b.s.x.shape[0] == 3 and b.y.shape == (3, N_NODES)
    np.testing.assert_array_equal(b.y[0], b.y[2])
    np.testing.assert_array_equal(np.asarray(b.s.x[0]),
                                  np.asarray(b.s.x[1]))


def test_repeat_graph_matches_per_replica_blocking():
    """repeat_graph (block once, tile the index tensors) aggregates
    identically to blocking the tiled batch from scratch."""
    from dgmc_tpu.ops.blocked import (adj_matmul, attach_blocks,
                                      repeat_graph)
    from dgmc_tpu.ops.graph import GraphBatch
    r = np.random.RandomState(0)
    n, e, c = 1200, 4000, 32
    arrays = dict(
        x=r.randn(1, n, c).astype(np.float32),
        senders=r.randint(0, n, (1, e)).astype(np.int32),
        receivers=r.randint(0, n, (1, e)).astype(np.int32),
        node_mask=np.ones((1, n), bool),
        edge_mask=np.ones((1, e), bool), edge_attr=None)
    tiled = repeat_graph(attach_blocks(GraphBatch(**arrays)), 3)
    naive = attach_blocks(GraphBatch(**{
        k: (None if v is None else np.repeat(v, 3, axis=0))
        for k, v in arrays.items()}))
    out_t = adj_matmul(jnp.asarray(tiled.x), tiled.blocks_in,
                       tiled.blocks_out)
    out_n = adj_matmul(jnp.asarray(naive.x), naive.blocks_in,
                       naive.blocks_out)
    np.testing.assert_array_equal(np.asarray(out_t), np.asarray(out_n))


def test_replicated_pairs_draw_independent_noise():
    """Replicas of one pair must NOT be redundant: each batch element
    folds its own RNG, so a replicated sparse training batch samples
    different negatives per element (the variance-reduction the DBP15K
    --pairs-per-step mode exists for)."""
    p = _pair(11)
    batched = pad_pair_batch([p], N_NODES, N_EDGES, pairs_per_step=2)
    model = _model(4)
    variables = model.init(
        {'params': jax.random.key(0), 'noise': jax.random.key(1),
         'negatives': jax.random.key(2), 'dropout': jax.random.key(3)},
        batched.s, batched.t)
    (S_0, S_L) = model.apply(
        variables, batched.s, batched.t, y=batched.y,
        y_mask=batched.y_mask, train=True,
        rngs={'noise': jax.random.key(5), 'negatives': jax.random.key(6),
              'dropout': jax.random.key(8)})
    # Same graphs, same params — only the per-pair RNG distinguishes the
    # elements; the refined correspondences must differ.
    assert not np.allclose(np.asarray(S_L.val[0]), np.asarray(S_L.val[1]))
