"""Mixed-precision (bf16) policy contract tests.

The ``dtype=jnp.bfloat16`` policy (VERDICT r3 item 3) must keep the
matching semantics: dense and sparse(k=N) still agree (to bf16
tolerance), correspondence logits/probabilities and parameters stay
float32, and a training step produces finite f32 grads/params. The
end-to-end quality evidence lives in the two-phase gate's bf16 variant
(tests/models/test_two_phase_quality.py).
"""

import jax
import jax.numpy as jnp
import numpy as np

from dgmc_tpu.models import DGMC, GIN, RelCNN
from dgmc_tpu.train import create_train_state, make_train_step
from dgmc_tpu.utils.data import PairBatch
from dgmc_tpu.ops.graph import GraphBatch

from tests.helpers import path_graph

N, C = 8, 32
BF16 = jnp.bfloat16


def build(k=-1, num_steps=2, dtype=None):
    psi_1 = GIN(C, 16, num_layers=2, dtype=dtype)
    psi_2 = GIN(8, 8, num_layers=2, dtype=dtype)
    return DGMC(psi_1, psi_2, num_steps=num_steps, k=k, dtype=dtype)


def run(model, g_s, g_t, variables=None, y=None, seed=7):
    rngs = {'noise': jax.random.PRNGKey(seed),
            'negatives': jax.random.PRNGKey(seed + 1),
            'dropout': jax.random.PRNGKey(seed + 2)}
    if variables is None:
        variables = model.init({'params': jax.random.PRNGKey(0), **rngs},
                               g_s, g_t)
    out = model.apply(variables, g_s, g_t, y=y, train=False, rngs=rngs)
    return out, variables


def test_bf16_outputs_stay_f32():
    g = path_graph(n=N, c=C)
    (S_0, S_L), variables = run(build(dtype=BF16), g, g)
    assert S_0.val.dtype == jnp.float32
    assert S_L.val.dtype == jnp.float32
    for leaf in jax.tree.leaves(variables['params']):
        assert leaf.dtype == jnp.float32


def test_bf16_dense_sparse_equivalence():
    """The dense==sparse(k=N) behavioral contract holds under the bf16
    policy, to bf16 tolerance (both paths round identically only where
    they share ops, so allow a loose-but-meaningful bound)."""
    g = path_graph(n=N, c=C)
    y = jnp.arange(N)[None]
    dense = build(k=-1, dtype=BF16)
    (S1_0, S1_L), variables = run(dense, g, g)
    sparse = build(k=N, dtype=BF16)
    (S2_0, S2_L), _ = run(sparse, g, g, variables=variables, y=y)
    np.testing.assert_allclose(S1_0.val, S2_0.to_dense(), atol=2e-2)
    np.testing.assert_allclose(S1_L.val, S2_L.to_dense(), atol=2e-2)


def test_bf16_close_to_f32():
    """bf16 predictions agree with f32 (probabilities can diverge through
    a sharp softmax, the hard assignment must not)."""
    g = path_graph(n=N, c=C)
    (A_0, A_L), variables = run(build(dtype=None), g, g)
    (B_0, B_L), _ = run(build(dtype=BF16), g, g, variables=variables)
    agree = np.mean(np.argmax(A_L.val, -1) == np.argmax(B_L.val, -1))
    assert agree == 1.0, agree


def test_bf16_sparse_train_step_finite():
    rng = np.random.RandomState(0)
    n, e, c = 32, 96, 16

    def side(seed):
        r = np.random.RandomState(seed)
        return GraphBatch(
            x=r.randn(1, n, c).astype(np.float32),
            senders=r.randint(0, n, (1, e)).astype(np.int32),
            receivers=r.randint(0, n, (1, e)).astype(np.int32),
            node_mask=np.ones((1, n), bool),
            edge_mask=np.ones((1, e), bool), edge_attr=None)

    y = rng.permutation(n).astype(np.int32)[None]
    batch = PairBatch(s=side(1), t=side(2), y=y, y_mask=y >= 0)
    model = DGMC(RelCNN(c, 16, num_layers=2, dtype=BF16),
                 RelCNN(8, 8, num_layers=2, dtype=BF16),
                 num_steps=2, k=4, dtype=BF16)
    state = create_train_state(model, jax.random.key(0), batch,
                               learning_rate=1e-2)
    step = make_train_step(model)
    losses = []
    key = jax.random.key(1)
    for _ in range(5):
        key, sub = jax.random.split(key)
        state, out = step(state, batch, sub)
        losses.append(float(out['loss']))
    assert all(np.isfinite(losses)), losses
    for leaf in jax.tree.leaves(state.params):
        assert leaf.dtype == jnp.float32
        assert np.all(np.isfinite(np.asarray(leaf)))
    assert losses[-1] < losses[0], losses
