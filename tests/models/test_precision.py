"""Mixed-precision policy contract tests.

bf16 compute / f32 accumulation is the DEFAULT policy
(``dgmc_tpu/models/precision.py``); these tests pin its three contracts:

1. **Semantics** — dense and sparse(k=N) still agree (to bf16
   tolerance), correspondence logits/probabilities and parameters stay
   float32, a training step produces finite f32 grads/params, and the
   policy object routes through every consumer (models, blocked
   aggregation, CLI flags).
2. **f32 accumulation** — the reductions that feed logits/grads
   (segment sums, blocked one-hot contractions, the fused kernels'
   ``d_o_t`` reduction) accumulate in float32 even with bf16 operands.
   The tests are built so a bf16 RUNNING SUM cannot represent the true
   total (addends below the bf16 spacing at the accumulated magnitude):
   an accumulation-dtype regression fails a test here, not a bench.
3. **Tolerance** — bf16-default forward/backward matches f32 within the
   documented bounds on dense and sparse paths. The end-to-end quality
   evidence lives in the two-phase gate's bf16 variant
   (tests/models/test_two_phase_quality.py).
"""

import jax
import jax.numpy as jnp
import numpy as np

from dgmc_tpu.models import DGMC, GIN, RelCNN, precision
from dgmc_tpu.train import create_train_state, make_train_step
from dgmc_tpu.utils.data import PairBatch
from dgmc_tpu.ops.graph import GraphBatch

from tests.helpers import path_graph

N, C = 8, 32
BF16 = jnp.bfloat16


def build(k=-1, num_steps=2, dtype=None):
    psi_1 = GIN(C, 16, num_layers=2, dtype=dtype)
    psi_2 = GIN(8, 8, num_layers=2, dtype=dtype)
    return DGMC(psi_1, psi_2, num_steps=num_steps, k=k, dtype=dtype)


def run(model, g_s, g_t, variables=None, y=None, seed=7):
    rngs = {'noise': jax.random.PRNGKey(seed),
            'negatives': jax.random.PRNGKey(seed + 1),
            'dropout': jax.random.PRNGKey(seed + 2)}
    if variables is None:
        variables = model.init({'params': jax.random.PRNGKey(0), **rngs},
                               g_s, g_t)
    out = model.apply(variables, g_s, g_t, y=y, train=False, rngs=rngs)
    return out, variables


def test_bf16_outputs_stay_f32():
    g = path_graph(n=N, c=C)
    (S_0, S_L), variables = run(build(dtype=BF16), g, g)
    assert S_0.val.dtype == jnp.float32
    assert S_L.val.dtype == jnp.float32
    for leaf in jax.tree.leaves(variables['params']):
        assert leaf.dtype == jnp.float32


def test_bf16_dense_sparse_equivalence():
    """The dense==sparse(k=N) behavioral contract holds under the bf16
    policy, to bf16 tolerance (both paths round identically only where
    they share ops, so allow a loose-but-meaningful bound)."""
    g = path_graph(n=N, c=C)
    y = jnp.arange(N)[None]
    dense = build(k=-1, dtype=BF16)
    (S1_0, S1_L), variables = run(dense, g, g)
    sparse = build(k=N, dtype=BF16)
    (S2_0, S2_L), _ = run(sparse, g, g, variables=variables, y=y)
    np.testing.assert_allclose(S1_0.val, S2_0.to_dense(), atol=2e-2)
    np.testing.assert_allclose(S1_L.val, S2_L.to_dense(), atol=2e-2)


def test_bf16_close_to_f32():
    """bf16 predictions agree with f32 (probabilities can diverge through
    a sharp softmax, the hard assignment must not)."""
    g = path_graph(n=N, c=C)
    (A_0, A_L), variables = run(build(dtype=None), g, g)
    (B_0, B_L), _ = run(build(dtype=BF16), g, g, variables=variables)
    agree = np.mean(np.argmax(A_L.val, -1) == np.argmax(B_L.val, -1))
    assert agree == 1.0, agree


def test_policy_object():
    bf16 = precision.get('bf16')
    assert bf16.compute_dtype == jnp.bfloat16
    assert bf16.gather_dtype == 'bfloat16'
    assert bf16.is_mixed
    f32 = precision.get('f32')
    assert f32.compute_dtype is None and f32.gather_dtype is None
    assert precision.get(None) is precision.F32
    assert precision.get(bf16) is bf16
    assert precision.get(jnp.bfloat16).name == 'bf16'
    # Models accept a policy wherever they accept a dtype.
    assert precision.compute_dtype_of(bf16) == jnp.bfloat16
    assert precision.compute_dtype_of(jnp.bfloat16) == jnp.bfloat16
    assert precision.compute_dtype_of(None) is None
    assert precision.gather_dtype_of(bf16) == 'bfloat16'
    assert precision.gather_dtype_of('bfloat16') == 'bfloat16'
    assert precision.gather_dtype_of(None) is None


def test_policy_cli_flags():
    """bf16 is the default on the shared CLI flags; --f32 is the opt-out
    and --bf16 the legacy alias."""
    import argparse
    for argv, want in (([], 'bf16'), (['--f32'], 'f32'),
                       (['--bf16'], 'bf16'),
                       (['--precision', 'f32'], 'f32')):
        parser = argparse.ArgumentParser()
        precision.add_precision_args(parser)
        args = parser.parse_args(argv)
        assert precision.from_args(args).name == want, (argv, want)


def test_policy_accepted_by_models():
    """A Precision object in a module's dtype field behaves exactly like
    the raw compute dtype."""
    g = path_graph(n=N, c=C)
    pol = precision.get('bf16')
    (A_0, A_L), variables = run(build(dtype=BF16), g, g)
    (B_0, B_L), _ = run(build(dtype=pol), g, g, variables=variables)
    np.testing.assert_array_equal(np.asarray(A_L.val), np.asarray(B_L.val))


def _one_hot_sum_graph(e=1024, n=4, c=8):
    """All ``e`` edges point at node 0 with message 0.5: the true sum is
    e/2, unreachable by a bf16 running sum (0.5 is below the bf16
    spacing of 2.0 once the accumulator passes 256)."""
    return GraphBatch(
        x=np.full((1, n, c), 0.5, np.float32),
        senders=np.zeros((1, e), np.int32),
        receivers=np.zeros((1, e), np.int32),
        node_mask=np.ones((1, n), bool),
        edge_mask=np.ones((1, e), bool), edge_attr=None)


def test_segment_sum_accumulates_f32_under_bf16():
    """scatter_to_nodes with bf16 messages must reach the exact total —
    a bf16 running sum stalls at 256 and fails this."""
    from dgmc_tpu.ops.graph import scatter_to_nodes
    g = _one_hot_sum_graph()
    msgs = jnp.full((1, 1024, 8), 0.5, BF16)
    out = scatter_to_nodes(msgs, g.receivers, g.edge_mask, 4, aggr='sum')
    np.testing.assert_array_equal(np.asarray(out[0, 0], np.float32),
                                  np.full(8, 512.0, np.float32))


def test_blocked_aggregation_accumulates_f32_under_bf16():
    """The blocked one-hot contraction (ops/blocked.py) under
    gather_dtype='bfloat16' keeps the f32-accumulation contract: wide
    bf16 rows (>= 512 B, so the narrow-row guard does NOT upcast) summed
    past the bf16 stall point."""
    from dgmc_tpu.ops.blocked import adj_matmul, attach_blocks
    c = 256  # 512-byte bf16 rows: stays bf16 through the gather
    e, n = 2048, 1500  # >= min_nodes so attach_blocks engages
    g = GraphBatch(
        x=np.full((1, n, c), 0.5, np.float32),
        senders=np.zeros((1, e), np.int32),
        receivers=np.zeros((1, e), np.int32),
        node_mask=np.ones((1, n), bool),
        edge_mask=np.ones((1, e), bool), edge_attr=None)
    g = attach_blocks(g, gather_dtype=precision.get('bf16'))
    assert g.blocks_in is not None
    assert g.blocks_in.gather_dtype == 'bfloat16'
    out = adj_matmul(jnp.asarray(g.x), g.blocks_in, g.blocks_out)
    assert out.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(out[0, 0]),
                                  np.full(c, 1024.0, np.float32))


def test_fused_kernel_d_o_t_accumulates_f32():
    """The widened round-trip kernel's backward reduces 2048 candidate
    cotangents of 0.5 into one target row: exactly -1024 under the f32
    contract; a bf16 running sum would stall at -256."""
    from dgmc_tpu.ops.pallas.sparse_consensus import fused_candidate_delta
    R, N_s = 8, 2048
    o_s = jnp.zeros((1, N_s, R), BF16)
    o_t = jnp.zeros((1, 4, R), BF16)
    S_idx = jnp.zeros((1, N_s, 1), jnp.int32)
    w1 = jnp.eye(R, dtype=BF16)
    b1 = jnp.ones((R,), BF16)          # pre-activation 1 > 0 everywhere
    w2 = jnp.ones((R, 1), BF16)
    b2 = jnp.zeros((1,), BF16)

    d_o_t = jax.grad(
        lambda t: 0.5 * jnp.sum(fused_candidate_delta(
            o_s, t, S_idx, w1, b1, w2, b2, True)))(o_t)
    # d_cand per entry = -(g * w2ᵀ) @ w1ᵀ = -0.5 per channel; 2048 of
    # them land on target row 0.
    np.testing.assert_array_equal(np.asarray(d_o_t[0, 0], np.float32),
                                  np.full(R, -1024.0, np.float32))
    np.testing.assert_array_equal(np.asarray(d_o_t[0, 1], np.float32),
                                  np.zeros(R, np.float32))


def test_bf16_sparse_close_to_f32():
    """Sparse-path bf16 predictions agree with f32 on the hard
    assignment (the dense-path twin of test_bf16_close_to_f32)."""
    g = path_graph(n=N, c=C)
    y = jnp.arange(N)[None]
    (A_0, A_L), variables = run(build(k=N, dtype=None), g, g, y=y)
    (B_0, B_L), _ = run(build(k=N, dtype=BF16), g, g, variables=variables,
                        y=y)
    agree = np.mean(np.argmax(A_L.val, -1) == np.argmax(B_L.val, -1))
    assert agree == 1.0, agree


def test_bf16_sparse_train_step_finite():
    rng = np.random.RandomState(0)
    n, e, c = 32, 96, 16

    def side(seed):
        r = np.random.RandomState(seed)
        return GraphBatch(
            x=r.randn(1, n, c).astype(np.float32),
            senders=r.randint(0, n, (1, e)).astype(np.int32),
            receivers=r.randint(0, n, (1, e)).astype(np.int32),
            node_mask=np.ones((1, n), bool),
            edge_mask=np.ones((1, e), bool), edge_attr=None)

    y = rng.permutation(n).astype(np.int32)[None]
    batch = PairBatch(s=side(1), t=side(2), y=y, y_mask=y >= 0)
    model = DGMC(RelCNN(c, 16, num_layers=2, dtype=BF16),
                 RelCNN(8, 8, num_layers=2, dtype=BF16),
                 num_steps=2, k=4, dtype=BF16)
    state = create_train_state(model, jax.random.key(0), batch,
                               learning_rate=1e-2)
    step = make_train_step(model)
    losses = []
    key = jax.random.key(1)
    for _ in range(5):
        key, sub = jax.random.split(key)
        state, out = step(state, batch, sub)
        losses.append(float(out['loss']))
    assert all(np.isfinite(losses)), losses
    for leaf in jax.tree.leaves(state.params):
        assert leaf.dtype == jnp.float32
        assert np.all(np.isfinite(np.asarray(leaf)))
    assert losses[-1] < losses[0], losses
