"""Sparse two-phase matching-quality gate (VERDICT round-2 item 3).

The DBP15K protocol's core claim (the paper's, reproduced by reference
``examples/dbp15k.py:63-69``) is that (a) sparse top-k feature matching
with GT injection learns alignments from a 30% seed set, and (b) the
detached consensus-refinement phase IMPROVES on feature-only matching.
Nothing in the plumbing tests checks matching *quality*; this does, on a
synthetic knowledge-graph alignment built like DBP15K in miniature:
a random directed graph, a permuted copy with noisy features and 15%
rewired edges, 30% training seeds, sparse k=8 with random negatives + GT
injection, trained through the real two-phase compiled-step schedule
(phase 1 ``num_steps=0``; phase 2 ``num_steps=5, detach=True``).

Runs on the blocked-adjacency path (ops/blocked.py), so it also gates
that the scatter-free MXU aggregation actually *trains*, not merely
matches forward values. Parametrized over the bf16 compute policy
(``dtype=jnp.bfloat16``) — the end-to-end quality evidence that
reduced-precision matmuls and message gathers still learn alignments
(ADVICE r3; tests/models/test_precision.py covers only contracts).

Calibration at the time of writing (CPU, seeds 0-3, 50+25 epochs):
phase 1 lands at 0.51-0.61 test Hits@1, phase 2 at 0.64-0.80 (f32) /
0.68-0.87 (bf16), improvement >= +0.11 everywhere; chance is 1/300.
Floors of 0.60 and +0.05 improvement sit well inside the band but far
above any broken-wiring outcome. (Round 3 ran 80+40 epochs with a 0.65
floor; trimmed per VERDICT r3 item 7 with floors recalibrated.)
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgmc_tpu.models import DGMC, RelCNN
from dgmc_tpu.ops import GraphBatch
from dgmc_tpu.ops.blocked import attach_blocks
from dgmc_tpu.train import (create_train_state, make_eval_step,
                            make_train_step)
from dgmc_tpu.utils.data import PairBatch

N, E, C = 300, 1500, 24


def build_alignment_problem(seed=0):
    rng = np.random.RandomState(seed)
    x_s = rng.randn(N, C).astype(np.float32)
    snd = rng.randint(0, N, E).astype(np.int32)
    rcv = rng.randint(0, N, E).astype(np.int32)

    # Target KG: permuted entities, noisy embeddings, 85% shared edges.
    perm = rng.permutation(N).astype(np.int32)
    x_t = np.zeros_like(x_s)
    x_t[perm] = x_s + 0.9 * rng.randn(N, C).astype(np.float32)
    keep = rng.rand(E) < 0.85
    snd_t = np.where(keep, perm[snd], rng.randint(0, N, E)).astype(np.int32)
    rcv_t = np.where(keep, perm[rcv], rng.randint(0, N, E)).astype(np.int32)

    def side(x, s, r):
        g = GraphBatch(x=x[None], senders=s[None], receivers=r[None],
                       node_mask=np.ones((1, N), bool),
                       edge_mask=np.ones((1, E), bool), edge_attr=None)
        return attach_blocks(g, rows=64, block_edges=128, min_nodes=1,
                             gather_dtype=None)

    g_s, g_t = side(x_s, snd, rcv), side(x_t, snd_t, rcv_t)
    train_mask = np.zeros(N, bool)
    train_mask[:int(0.3 * N)] = True      # the reference's 30% seed split
    y_train = np.where(train_mask, perm, -1).astype(np.int32)[None]
    y_test = np.where(~train_mask, perm, -1).astype(np.int32)[None]
    return (PairBatch(s=g_s, t=g_t, y=y_train, y_mask=y_train >= 0),
            PairBatch(s=g_s, t=g_t, y=y_test, y_mask=y_test >= 0))


# The bf16 arm repeats the full two-phase training run (~18s) purely
# for the dtype parity; tier-1 keeps the f32 arm.
@pytest.mark.parametrize(
    'dtype',
    [None, pytest.param(jnp.bfloat16, marks=pytest.mark.slow)],
    ids=['f32', 'bf16'])
def test_two_phase_schedule_matching_quality(dtype):
    batch, test_batch = build_alignment_problem(seed=0)
    model = DGMC(RelCNN(C, 64, num_layers=2, dropout=0.3, dtype=dtype),
                 RelCNN(16, 16, num_layers=2, dtype=dtype),
                 num_steps=0, k=8, dtype=dtype)
    state = create_train_state(model, jax.random.key(0), batch,
                               learning_rate=1e-2)

    p1_train = make_train_step(model, num_steps=0)
    p2_train = make_train_step(model, num_steps=5, detach=True)
    p1_eval = make_eval_step(model, num_steps=0)
    p2_eval = make_eval_step(model, num_steps=5)

    def test_hits1(state, eval_step, key):
        out = eval_step(state, test_batch, key)
        return float(out['correct']) / float(out['count'])

    key = jax.random.key(1)
    for _ in range(50):
        key, sub = jax.random.split(key)
        state, _ = p1_train(state, batch, sub)
    key, sub = jax.random.split(key)
    h1 = test_hits1(state, p1_eval, sub)

    for _ in range(25):
        key, sub = jax.random.split(key)
        state, _ = p2_train(state, batch, sub)
    key, sub = jax.random.split(key)
    h2 = test_hits1(state, p2_eval, sub)

    assert h2 >= 0.60, f'two-phase matching quality regressed: {h2:.3f}'
    assert h2 >= h1 + 0.05, (
        f'consensus refinement no longer improves on feature matching: '
        f'phase1={h1:.3f} phase2={h2:.3f}')
