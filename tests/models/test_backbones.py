"""Backbone contract tests, mirroring the reference's per-backbone suites
(reference ``test/models/test_{gin,rel,spline,mlp}.py``): for every
(cat, lin) combination the output width equals ``model.out_channels``, which
is ``16 + num_layers * 32`` exactly when ``cat and not lin`` else 32."""

import itertools

import jax
import jax.numpy as jnp
import pytest

from dgmc_tpu.models import MLP, GIN, RelCNN, SplineCNN

from tests.helpers import path_graph

KEY = jax.random.PRNGKey(0)


def _init_apply(model, *args, **kwargs):
    variables = model.init({'params': KEY}, *args, **kwargs)
    return model.apply(variables, *args, **kwargs)


def test_mlp_shapes_and_repr():
    g = path_graph(n=4, c=16)
    model = MLP(16, 32, num_layers=2, batch_norm=True)
    out = _init_apply(model, g.x, g.node_mask)
    assert out.shape == (1, 4, 32)
    assert repr(model) == ('MLP(16, 32, num_layers=2, batch_norm=True, '
                           'dropout=0.0)')


@pytest.mark.parametrize('cat,lin', itertools.product([False, True], repeat=2))
def test_gin_out_channels_contract(cat, lin):
    g = path_graph(n=4, c=16)
    model = GIN(16, 32, num_layers=2, cat=cat, lin=lin)
    expected = 16 + 2 * 32 if cat and not lin else 32
    assert model.out_channels == expected
    out = _init_apply(model, g.x, g)
    assert out.shape == (1, 4, expected)


@pytest.mark.parametrize('cat,lin', itertools.product([False, True], repeat=2))
def test_rel_out_channels_contract(cat, lin):
    g = path_graph(n=4, c=16)
    model = RelCNN(16, 32, num_layers=2, cat=cat, lin=lin, dropout=0.5)
    expected = 16 + 2 * 32 if cat and not lin else 32
    assert model.out_channels == expected
    out = _init_apply(model, g.x, g)
    assert out.shape == (1, 4, expected)


@pytest.mark.parametrize('cat,lin', itertools.product([False, True], repeat=2))
def test_spline_out_channels_contract(cat, lin):
    import numpy as np
    rng = np.random.RandomState(1)
    from tests.helpers import graph_from_edges
    edges = [(0, 1), (1, 0), (1, 2), (2, 1), (2, 3), (3, 2)]
    g = graph_from_edges(rng.randn(4, 16), edges,
                         edge_attr=rng.rand(6, 3))
    model = SplineCNN(16, 32, dim=3, num_layers=2, cat=cat, lin=lin,
                      dropout=0.5)
    expected = 16 + 2 * 32 if cat and not lin else 32
    assert model.out_channels == expected
    out = _init_apply(model, g.x, g)
    assert out.shape == (1, 4, expected)


def test_repr_formats():
    assert repr(GIN(16, 32, num_layers=2)) == (
        'GIN(16, 32, num_layers=2, batch_norm=False, cat=True, lin=True)')
    assert repr(RelCNN(16, 32, num_layers=2, dropout=0.5)) == (
        'RelCNN(16, 32, num_layers=2, batch_norm=False, cat=True, lin=True, '
        'dropout=0.5)')
    assert repr(SplineCNN(16, 32, dim=2, num_layers=2)) == (
        'SplineCNN(16, 32, dim=2, num_layers=2, cat=True, lin=True, '
        'dropout=0.0)')


def test_dropout_requires_rng_only_in_train():
    g = path_graph(n=4, c=16)
    model = RelCNN(16, 32, num_layers=2, dropout=0.5)
    variables = model.init({'params': KEY}, g.x, g)
    out_eval = model.apply(variables, g.x, g, train=False)
    out_train = model.apply(variables, g.x, g, train=True,
                            rngs={'dropout': jax.random.PRNGKey(1)})
    assert out_eval.shape == out_train.shape
    assert not jnp.allclose(out_eval, out_train)


def test_masked_nodes_do_not_leak():
    """A padded node with junk features must not affect valid nodes."""
    g1 = path_graph(n=4, c=8)
    # Same graph padded to 6 nodes with junk in the pad slots.
    import numpy as np
    from tests.helpers import graph_from_edges
    rng = np.random.RandomState(0)
    x = np.zeros((6, 8), np.float32)
    x[:4] = np.asarray(g1.x[0])
    x[4:] = 1e3
    edges = [(0, 1), (1, 0), (1, 2), (2, 1), (2, 3), (3, 2)]
    g2 = graph_from_edges(x, edges, num_valid_nodes=4)

    model = GIN(8, 16, num_layers=2)
    variables = model.init({'params': KEY}, g1.x, g1)
    out1 = model.apply(variables, g1.x, g1)
    out2 = model.apply(variables, g2.x, g2)
    assert jnp.allclose(out1[0, :4], out2[0, :4], atol=1e-5)


def test_relcnn_streams_rejects_active_dropout():
    """A channel-packed (streams>1) evaluation draws ONE dropout mask
    across the packed groups — silently coupling what should be
    independent consensus iterations. The backbone must reject it loudly
    (DGMC.prefetch_source already skips packing in this case)."""
    g = path_graph(n=4, c=16)
    model = RelCNN(16, 32, num_layers=1, dropout=0.5)
    x2 = jnp.concatenate([g.x, g.x], axis=-1)
    with pytest.raises(ValueError, match='dropout'):
        model.init({'params': KEY, 'dropout': KEY}, x2, g,
                   train=True, streams=2)
    # Inactive dropout (eval) stays fine.
    variables = model.init({'params': KEY}, x2, g, train=False, streams=2)
    out = model.apply(variables, x2, g, train=False, streams=2)
    assert out.shape == (1, 4, 2 * 32)
