"""DGMC behavioral-contract tests, mirroring the reference suite
(reference ``test/models/test_dgmc.py``): dense and sparse variants with
``k = N`` must produce identical ``S_0``/``S_L``/loss/metrics under shared
PRNG keys; ``include_gt`` overwrites only the last slot and only where the
ground truth is missing; hits@all is exactly 1.0."""

import jax
import jax.numpy as jnp
import numpy as np

from dgmc_tpu.models import DGMC, GIN
from dgmc_tpu.models.dgmc import include_gt

from tests.helpers import path_graph, stack_graphs

N, C = 4, 32


def build(k=-1, num_steps=1):
    psi_1 = GIN(C, 16, num_layers=2)
    psi_2 = GIN(8, 8, num_layers=2)
    return DGMC(psi_1, psi_2, num_steps=num_steps, k=k)


def run(model, g_s, g_t, variables=None, y=None, y_mask=None, train=False,
        seed=7):
    rngs = {'noise': jax.random.PRNGKey(seed),
            'negatives': jax.random.PRNGKey(seed + 1),
            'dropout': jax.random.PRNGKey(seed + 2)}
    if variables is None:
        variables = model.init({'params': jax.random.PRNGKey(0), **rngs},
                               g_s, g_t)
    out = model.apply(variables, g_s, g_t, y=y, y_mask=y_mask, train=train,
                      rngs=rngs)
    return out, variables


def test_repr():
    model = build()
    assert repr(model) == (
        'DGMC(\n'
        '    psi_1=GIN(32, 16, num_layers=2, batch_norm=False, cat=True, '
        'lin=True),\n'
        '    psi_2=GIN(8, 8, num_layers=2, batch_norm=False, cat=True, '
        'lin=True),\n'
        '    num_steps=1, k=-1\n)')


def test_dense_sparse_equivalence_single_graph():
    g = path_graph(n=N, c=C)
    y = jnp.arange(N)[None]

    dense = build(k=-1)
    (S1_0, S1_L), variables = run(dense, g, g)

    sparse = build(k=N)
    (S2_0, S2_L), _ = run(sparse, g, g, variables=variables, y=y)

    assert S1_0.val.shape == (1, N, N)
    np.testing.assert_allclose(S1_0.val, S2_0.to_dense(), atol=1e-6)
    np.testing.assert_allclose(S1_L.val, S2_L.to_dense(), atol=1e-6)

    loss1 = DGMC.loss(S1_0, y)
    loss2 = DGMC.loss(S2_0, y)
    # atol matters: on a near-uniform 4-node toy problem the NLL sits at
    # ~4e-4, where a handful of f32 ulps from two different reduction
    # orders (dense einsum vs sparse gather+einsum) already exceeds a
    # bare rtol=1e-5. The equivalence being pinned is behavioral, not
    # bit-exact accumulation order.
    np.testing.assert_allclose(loss1, loss2, rtol=1e-5, atol=1e-6)

    acc1, acc2 = DGMC.acc(S1_0, y), DGMC.acc(S2_0, y)
    h1_1 = DGMC.hits_at_k(1, S1_0, y)
    h2_1 = DGMC.hits_at_k(1, S2_0, y)
    h1_10 = DGMC.hits_at_k(10, S1_0, y)
    h2_10 = DGMC.hits_at_k(10, S2_0, y)
    h1_all = DGMC.hits_at_k(N, S1_0, y)
    h2_all = DGMC.hits_at_k(N, S2_0, y)

    assert acc1 == acc2 == h1_1 == h2_1
    assert h1_1 <= h1_10
    assert h1_10 == h2_10
    assert h1_10 <= h1_all
    assert h1_all == h2_all == 1.0


def test_dense_sparse_equivalence_batched():
    g = path_graph(n=N, c=C)
    gb = stack_graphs(g, g)

    dense = build(k=-1)
    (S1_0, S1_L), variables = run(dense, gb, gb)
    assert S1_0.val.shape == (2, N, N)

    sparse = build(k=N)
    (S2_0, S2_L), _ = run(sparse, gb, gb, variables=variables)

    np.testing.assert_allclose(S1_0.val, S2_0.to_dense(), atol=1e-6)
    np.testing.assert_allclose(S1_L.val, S2_L.to_dense(), atol=1e-6)


def test_gradients_flow_both_variants():
    g = path_graph(n=N, c=C)
    y = jnp.arange(N)[None]
    rngs = {'noise': jax.random.PRNGKey(7),
            'negatives': jax.random.PRNGKey(8)}

    for k in (-1, N):
        model = build(k=k)
        variables = model.init({'params': jax.random.PRNGKey(0), **rngs},
                               g, g)

        def loss_fn(params):
            S_0, S_L = model.apply({'params': params}, g, g, y=y,
                                   train=True, rngs=rngs)
            return DGMC.loss(S_0, y) + DGMC.loss(S_L, y)

        grads = jax.grad(loss_fn)(variables['params'])
        flat = jax.tree_util.tree_leaves(grads)
        assert all(jnp.isfinite(g_).all() for g_ in flat)
        assert any(jnp.abs(g_).max() > 0 for g_ in flat)


def test_detach_cuts_psi1_gradients():
    g = path_graph(n=N, c=C)
    y = jnp.arange(N)[None]
    rngs = {'noise': jax.random.PRNGKey(7)}
    model = build(k=-1, num_steps=2)
    variables = model.init({'params': jax.random.PRNGKey(0), **rngs}, g, g)

    def loss_fn(params):
        _, S_L = model.apply({'params': params}, g, g, detach=True,
                             rngs=rngs)
        return DGMC.loss(S_L, y)

    grads = jax.grad(loss_fn)(variables['params'])
    psi1_grads = jax.tree_util.tree_leaves(grads['psi_1'])
    assert all(jnp.abs(g_).max() == 0 for g_ in psi1_grads)
    psi2_grads = jax.tree_util.tree_leaves(grads['psi_2'])
    assert any(jnp.abs(g_).max() > 0 for g_ in psi2_grads)


def test_num_steps_zero_skips_consensus():
    g = path_graph(n=N, c=C)
    model = build(k=-1, num_steps=0)
    (S_0, S_L), _ = run(model, g, g)
    np.testing.assert_allclose(S_0.val, S_L.val)


def test_include_gt():
    # Hand-written case adapted from the reference's 2x2x2 unit test
    # (reference test/models/test_dgmc.py:87-95), expressed with padded
    # per-row ground truth: rows with a valid GT absent from their candidate
    # list get it written into the LAST slot only.
    S_idx = jnp.array([[[0, 1], [1, 2]], [[1, 2], [0, 1]]])
    y = jnp.array([[1, 0], [0, 0]])
    y_mask = jnp.array([[True, False], [True, True]])

    out = include_gt(S_idx, y, y_mask)
    assert out.tolist() == [[[0, 1], [1, 2]], [[1, 0], [0, 1]]]


def test_sparse_train_injects_gt_and_negatives():
    g = path_graph(n=N, c=C)
    big = stack_graphs(g, g)  # B=2
    y = jnp.array([[3, 2, 1, 0], [0, 1, 2, 3]])
    model = build(k=1, num_steps=1)
    (S_0, S_L), _ = run(model, big, big, y=y, train=True)
    # k=1 plus min(1, N-1)=1 negative plus GT overwrite => K=2 candidates.
    assert S_0.idx.shape == (2, N, 2)
    # GT present in every row's candidate list.
    assert bool((S_0.idx == y[..., None]).any(-1).all())
    # Loss is finite and positive.
    loss = DGMC.loss(S_L, y)
    assert jnp.isfinite(loss) and loss > 0


def test_metrics_reductions():
    g = path_graph(n=N, c=C)
    y = jnp.arange(N)[None]
    model = build(k=-1)
    (S_0, _), _ = run(model, g, g)
    s = DGMC.loss(S_0, y, reduction='sum')
    m = DGMC.loss(S_0, y, reduction='mean')
    n = DGMC.loss(S_0, y, reduction='none')
    np.testing.assert_allclose(s, n.sum(), rtol=1e-6)
    np.testing.assert_allclose(m, s / N, rtol=1e-6)
    assert DGMC.acc(S_0, y, reduction='sum') <= N
