"""Golden-value quality gates (SURVEY.md §4 plan; VERDICT round-1 item 5).

Two guarantees the finiteness/shape smoke tests cannot give:

1. **Hand-computed consensus iteration** — with trivial injected backbones
   (identity ψ₁, a degree-colouring ψ₂ that ignores its input, hand-set
   consensus-MLP parameters), one dense consensus step has a closed-form
   numpy value. Any rewiring of the update (softmax → project → ψ₂ → D →
   MLP → additive logit update → softmax; reference
   ``dgmc/models/dgmc.py:167-179``) changes these numbers.

2. **Matching-quality floor** — the pascal_pf-style synthetic protocol
   (train on random geometric pairs, evaluate on unseen pairs; reference
   ``examples/pascal_pf.py:115-123``) must reach a recorded Hits@1
   threshold in a fixed training budget. Fails if matching quality (not
   just plumbing) regresses.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from dgmc_tpu.models import DGMC
from dgmc_tpu.ops import GraphBatch


class IdentityPsi1(nn.Module):
    """ψ₁ that emits the node features unchanged."""
    in_channels: int
    out_channels: int

    @nn.compact
    def __call__(self, x, graph, train=False):
        return x


class DegreePsi2(nn.Module):
    """ψ₂ that ignores its input and colours node ``i`` with its in-degree,
    broadcast over ``out_channels`` — a fixed, hand-computable colouring, so
    the consensus update is deterministic (the random indicator functions
    cancel out of the expectation entirely)."""
    in_channels: int
    out_channels: int

    @nn.compact
    def __call__(self, x, graph, train=False):
        ones = jnp.where(graph.edge_mask, 1.0, 0.0)
        deg = jax.vmap(
            lambda w, r: jax.ops.segment_sum(
                w, r, num_segments=graph.node_mask.shape[1]))(
                    ones, graph.receivers)
        return jnp.broadcast_to(deg[..., None],
                                deg.shape + (self.out_channels,))


def line_graph(n, feats):
    """Directed path 0→1→…→n-1 with the given node features, B=1."""
    senders = np.arange(n - 1, dtype=np.int32)[None]
    receivers = np.arange(1, n, dtype=np.int32)[None]
    return GraphBatch(
        x=np.asarray(feats, np.float32)[None],
        senders=senders, receivers=receivers,
        node_mask=np.ones((1, n), bool),
        edge_mask=np.ones((1, n - 1), bool),
        edge_attr=None)


def softmax(z):
    e = np.exp(z - z.max(axis=-1, keepdims=True))
    return e / e.sum(axis=-1, keepdims=True)


def test_consensus_iteration_golden():
    R = 3
    # Source: path of 3 nodes, features chosen so S_hat is asymmetric.
    x_s = [[1.0, 0.0], [0.0, 1.0], [1.0, 1.0]]
    x_t = [[1.0, 1.0], [1.0, 0.0], [0.0, 2.0]]
    g_s, g_t = line_graph(3, x_s), line_graph(3, x_t)

    model = DGMC(IdentityPsi1(2, 2), DegreePsi2(R, R), num_steps=1, k=-1)
    # Hand-set consensus MLP: hidden = relu(d * I + 0), out = mean over R.
    variables = {'params': {
        'mlp_hidden_kernel': jnp.eye(R),
        'mlp_hidden_bias': jnp.zeros((R,)),
        'mlp_out_kernel': jnp.full((R, 1), 1.0 / R),
        'mlp_out_bias': jnp.zeros((1,)),
    }}
    S_0, S_L = model.apply(variables, g_s, g_t,
                           rngs={'noise': jax.random.PRNGKey(0)})

    # ---- The same computation by hand ----
    H_s, H_t = np.asarray(x_s), np.asarray(x_t)
    S_hat0 = H_s @ H_t.T
    want_S0 = softmax(S_hat0)
    # In-degrees of the directed 3-path: node 0 has none.
    deg = np.array([0.0, 1.0, 1.0])
    # o = deg broadcast to R dims; D[i, j] = o_s[i] - o_t[j] (all R equal).
    d = deg[:, None] - deg[None, :]               # [N_s, N_t]
    delta = np.maximum(d, 0.0)                    # relu, then mean over R
    want_SL = softmax(S_hat0 + delta)

    np.testing.assert_allclose(np.asarray(S_0.val[0]), want_S0, atol=1e-6)
    np.testing.assert_allclose(np.asarray(S_L.val[0]), want_SL, atol=1e-6)
    # The golden values themselves, pinned (recomputed above for clarity):
    np.testing.assert_allclose(
        want_SL[1], [0.46831053, 0.06337894, 0.46831053], atol=1e-6)


def test_consensus_iteration_golden_sparse_matches():
    """The sparse path with k=N must land on the same golden values."""
    R = 3
    x_s = [[1.0, 0.0], [0.0, 1.0], [1.0, 1.0]]
    x_t = [[1.0, 1.0], [1.0, 0.0], [0.0, 2.0]]
    g_s, g_t = line_graph(3, x_s), line_graph(3, x_t)
    variables = {'params': {
        'mlp_hidden_kernel': jnp.eye(R),
        'mlp_hidden_bias': jnp.zeros((R,)),
        'mlp_out_kernel': jnp.full((R, 1), 1.0 / R),
        'mlp_out_bias': jnp.zeros((1,)),
    }}
    model = DGMC(IdentityPsi1(2, 2), DegreePsi2(R, R), num_steps=1, k=3)
    _, S_L = model.apply(variables, g_s, g_t,
                         rngs={'noise': jax.random.PRNGKey(0)})
    dense = np.asarray(S_L.to_dense()[0])
    np.testing.assert_allclose(
        dense[1], [0.46831053, 0.06337894, 0.46831053], atol=1e-6)
    np.testing.assert_allclose(
        dense[2], [0.66524096, 0.09003057, 0.24472847], atol=1e-6)


# A 100-step CPU training run (~47s); the consensus-iteration goldens
# above pin the numerics in tier-1, the quality floor is tier-2.
@pytest.mark.slow
def test_synthetic_matching_quality_floor():
    """Train the flagship dense matcher on synthetic geometric pairs for a
    fixed 100-step budget; unseen-pair Hits@1 must stay ≥ 0.6.

    Recorded calibration at the time of writing (CPU, this exact config):
    trained ≈ 0.68, untrained ≈ 0.07, and a longer budget plateaus ≈ 0.7 —
    so 0.6 is a tight floor for a one-minute test, far above any broken
    consensus/matching wiring."""
    from dgmc_tpu.data import (Cartesian, Compose, Constant, KNNGraph,
                               RandomGraphPairs)
    from dgmc_tpu.models import SplineCNN
    from dgmc_tpu.train import (create_train_state, make_eval_step,
                                make_train_step)
    from dgmc_tpu.utils import PairLoader

    transform = Compose([Constant(), KNNGraph(k=8), Cartesian()])
    ds = RandomGraphPairs(min_inliers=20, max_inliers=40, min_outliers=0,
                          max_outliers=4, transform=transform, length=64,
                          seed=0)
    loader = PairLoader(ds, 16, shuffle=True, seed=0,
                        num_nodes=48, num_edges=400)
    eval_ds = RandomGraphPairs(min_inliers=20, max_inliers=40,
                               min_outliers=0, max_outliers=4,
                               transform=transform, length=32, seed=99)
    eval_loader = PairLoader(eval_ds, 16, shuffle=False,
                             num_nodes=48, num_edges=400)

    psi_1 = SplineCNN(1, 128, dim=2, num_layers=2, cat=False, dropout=0.0)
    psi_2 = SplineCNN(32, 32, dim=2, num_layers=2, cat=True)
    model = DGMC(psi_1, psi_2, num_steps=3, k=-1)

    batch0 = next(iter(loader))
    state = create_train_state(model, jax.random.key(0), batch0,
                               learning_rate=1e-3)
    step = make_train_step(model, loss_on_s0=True)
    eval_step = make_eval_step(model)

    key = jax.random.key(1)
    for epoch in range(25):  # 25 epochs x 4 batches = 100 steps
        ds.set_epoch(epoch)  # fresh pairs per epoch, as pascal_pf trains
        for batch in loader:
            key, sub = jax.random.split(key)
            state, out = step(state, batch, sub)

    correct = count = 0.0
    for batch in eval_loader:
        key, sub = jax.random.split(key)
        ev = eval_step(state, batch, sub)
        correct += float(ev['correct'])
        count += float(ev['count'])
    acc = correct / count
    assert acc >= 0.6, f'matching quality regressed: Hits@1={acc:.3f}'
