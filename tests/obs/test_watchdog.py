"""Run-health watchdog contract (obs/watchdog.py).

The acceptance pin for the injected-stall scenario: a train loop with a
deliberately blocked step, run under the watchdog with a short deadline,
must produce a valid ``hang_report.json`` naming the last-completed
stage span — the diagnosability the rc:124 MULTICHIP/BENCH rounds
lacked. Plus the signal path (dump + chain to the previous handler) and
the disarm-on-close hygiene.
"""

import json
import os
import signal
import time

import pytest

from dgmc_tpu.obs.watchdog import Watchdog, thread_stacks


def _read(path):
    with open(path) as f:
        return json.load(f)


def test_thread_stacks_cover_current_thread():
    stacks = thread_stacks()
    assert any('test_thread_stacks_cover_current_thread' in ln
               for t in stacks for ln in t['stack'])
    assert all('name' in t and 'stack' in t for t in stacks)


def test_deadline_dump_on_blocked_step(tmp_path):
    """The injected-stall scenario: beats for fast steps, then a blocked
    one; the dump must name the stall and the last-completed span."""
    path = str(tmp_path / 'hang_report.json')
    wd = Watchdog(path, deadline_s=0.25, poll_s=0.05).start()
    try:
        for i in range(3):
            wd.beat('step', i)
            time.sleep(0.01)
            wd.done()
        wd.beat('step', 3)          # the deliberately blocked step
        time.sleep(0.9)             # > deadline: the thread dumps
        assert os.path.exists(path)
        rep = _read(path)
        assert rep['reason'] == 'deadline'
        assert rep['in_flight']['phase'] == 'step'
        assert rep['in_flight']['name'] == 3
        assert rep['in_flight']['since_s'] >= 0.25
        assert rep['stalled_for_s'] >= 0.25
        assert rep['last_completed']['phase'] == 'step'
        assert rep['last_completed']['name'] == 2
        # All-thread tracebacks include this (stalled) main thread.
        assert any('time.sleep' in ln or 'sleep' in ln
                   for t in rep['threads'] for ln in t['stack'])
        assert rep['deadline_s'] == 0.25
    finally:
        wd.close()


def test_no_dump_while_beaten(tmp_path):
    path = str(tmp_path / 'hang_report.json')
    wd = Watchdog(path, deadline_s=0.3, poll_s=0.05).start()
    try:
        for i in range(10):
            wd.beat('step', i)
            time.sleep(0.05)
            wd.done()
    finally:
        wd.close()
    assert not os.path.exists(path)


def test_dump_once_per_stall_then_rearms(tmp_path):
    path = str(tmp_path / 'hang_report.json')
    wd = Watchdog(path, deadline_s=0.15, poll_s=0.03).start()
    try:
        wd.beat('step', 0)
        time.sleep(0.5)
        assert wd.dump_count == 1      # one stall, one dump — no spam
        wd.beat('step', 1)             # recovery re-arms
        time.sleep(0.5)
        assert wd.dump_count == 2
    finally:
        wd.close()


def test_context_fn_lands_in_report(tmp_path):
    path = str(tmp_path / 'hang_report.json')
    wd = Watchdog(path, deadline_s=0.15, poll_s=0.03,
                  context_fn=lambda: {'steps_completed': 41}).start()
    try:
        wd.beat('step', 41)
        time.sleep(0.5)
        assert _read(path)['context']['steps_completed'] == 41
    finally:
        wd.close()


def test_signal_dump_chains_to_previous_handler(tmp_path):
    """A SIGTERM dump must not swallow the previously-installed handler
    (bench.py's partial-line emitter chains after the report)."""
    seen = []
    prev = signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
    path = str(tmp_path / 'hang_report.json')
    wd = Watchdog(path, deadline_s=None,
                  signals=(signal.SIGTERM,)).start()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        # Delivery is synchronous for a self-signal on the main thread.
        assert seen == [signal.SIGTERM]
        rep = _read(path)
        assert rep['reason'] == 'signal:SIGTERM'
    finally:
        wd.close()
        signal.signal(signal.SIGTERM, prev)


def test_close_restores_signal_handlers(tmp_path):
    marker = lambda s, f: None                                # noqa: E731
    prev = signal.signal(signal.SIGTERM, marker)
    try:
        wd = Watchdog(str(tmp_path / 'h.json'),
                      signals=(signal.SIGTERM,)).start()
        assert signal.getsignal(signal.SIGTERM) is not marker
        wd.close()
        assert signal.getsignal(signal.SIGTERM) is marker
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_dump_never_raises_on_unwritable_path():
    wd = Watchdog('/nonexistent-dir/sub/hang.json', deadline_s=None)
    assert wd.dump('deadline') is None      # returns None, no raise


# ---------------------------------------------------------------------------
# RunObserver integration (the --watchdog-deadline wiring)
# ---------------------------------------------------------------------------


def test_runobserver_blocked_step_produces_hang_report(tmp_path):
    from dgmc_tpu.obs import RunObserver
    d = str(tmp_path / 'obs')
    obs = RunObserver(d, watchdog_deadline_s=0.25,
                      watchdog_signals=())
    try:
        for _ in range(2):
            with obs.step():
                time.sleep(0.01)
        obs.log(1, loss=1.0)
        with obs.step():               # the deliberately blocked step
            time.sleep(0.9)
    finally:
        obs.close()
    rep = _read(os.path.join(d, 'hang_report.json'))
    assert rep['reason'] == 'deadline'
    assert rep['in_flight']['phase'] == 'step'
    assert rep['in_flight']['name'] == 2
    ctx = rep['context']
    assert ctx['steps_completed'] == 2
    assert 'last_step_span' in ctx
    assert ctx['steps']['steps'] == 2


def test_runobserver_healthy_run_leaves_no_report(tmp_path):
    from dgmc_tpu.obs import RunObserver
    d = str(tmp_path / 'obs')
    with RunObserver(d, watchdog_deadline_s=30.0,
                     watchdog_signals=()) as obs:
        with obs.step():
            pass
        obs.log(1, loss=0.5)
    assert not os.path.exists(os.path.join(d, 'hang_report.json'))
    assert obs.watchdog is None            # close() disarmed it


def test_runobserver_without_deadline_has_no_watchdog(tmp_path):
    from dgmc_tpu.obs import RunObserver
    with RunObserver(str(tmp_path / 'obs')) as obs:
        assert obs.watchdog is None


@pytest.mark.parametrize('deadline', [0.25])
def test_runobserver_pending_compile_label_in_report(tmp_path, deadline):
    """A stall inside a labelled compile region names that label as
    pending — the 'which compile wedged' question MULTICHIP_r0x left
    open."""
    from dgmc_tpu.obs import RunObserver
    d = str(tmp_path / 'obs')
    obs = RunObserver(d, watchdog_deadline_s=deadline,
                      watchdog_signals=())
    try:
        with obs.compile_label('phase2'):
            time.sleep(0.9)
    finally:
        obs.close()
    rep = _read(os.path.join(d, 'hang_report.json'))
    assert rep['in_flight'] == {'phase': 'compile', 'name': 'phase2',
                                'since_s': rep['in_flight']['since_s']}
    assert rep['context']['pending_compiles'] == ['phase2']
