"""Per-query trace retention contract (``obs.qtrace``): the stage
vocabulary is pinned to the shared model-stage dialect, sampling is
deterministic and bounded, the slowest-K reservoir holds exactly the K
slowest, errors are never sampled out, and the exports (Chrome trace,
/metrics exposition, report CLI) strict-parse."""

import json

import pytest

from dgmc_tpu.analysis.hlo_comm import STAGE_NAMES
from dgmc_tpu.obs import qtrace as qt
from dgmc_tpu.obs import trace_events
from dgmc_tpu.obs.live import prometheus_exposition
from tests.obs.test_live import parse_exposition


def make_trace(tracer, total_s, spans=None, traceparent=None):
    """One synthetic closed trace: pre-timed spans + a forced total."""
    trace = tracer.start(traceparent)
    for name, start_s, dur_s in spans or [
            ('bucket_resolve', 0.0, 0.001),
            ('device_execute', 0.001, total_s * 0.8),
            ('serialize', 0.001 + total_s * 0.8, 0.001)]:
        trace.record(name, start_s, dur_s)
    return trace


# ---------------------------------------------------------------------------
# Stage vocabulary: one dialect, enforced at record time
# ---------------------------------------------------------------------------

def test_stage_vocabulary_identity():
    """The serve span vocabulary IS the one the static/measured planes
    share: defined once in analysis.hlo_comm, re-exported verbatim, and
    every device-facing span maps onto STAGE_NAMES members only."""
    from dgmc_tpu.analysis import hlo_comm
    from dgmc_tpu.obs import trace_events as te
    assert qt.SERVE_SPAN_NAMES == hlo_comm.SERVE_SPAN_NAMES
    assert qt.SERVE_SPAN_NAMES == te.SERVE_SPAN_NAMES
    assert qt.SERVE_SPAN_STAGES is hlo_comm.SERVE_SPAN_STAGES
    assert set(qt.SERVE_SPAN_STAGES) == set(qt.SERVE_SPAN_NAMES)
    assert qt.SERVE_SPAN_NAMES == (
        'admission_queue_wait', 'bucket_resolve', 'pad_and_stage',
        'device_execute', 'shortlist_merge', 'consensus_rerank',
        'serialize')
    for name, stages in qt.SERVE_SPAN_STAGES.items():
        assert set(stages) <= set(STAGE_NAMES), name


def test_unknown_span_name_raises():
    tracer = qt.QueryTracer(path=None)
    trace = tracer.start()
    with pytest.raises(ValueError, match='unknown serve span'):
        with trace.span('made_up_stage'):
            pass
    with pytest.raises(ValueError, match='unknown serve span'):
        trace.record('psi1', 0.0, 0.001)   # model stage != span name


# ---------------------------------------------------------------------------
# traceparent: adopt when valid, mint deterministically otherwise
# ---------------------------------------------------------------------------

def test_traceparent_parse_and_format():
    tid, sid = 'ab' * 16, 'cd' * 8
    assert qt.parse_traceparent(f'00-{tid}-{sid}-01') == (tid, sid)
    assert qt.format_traceparent(tid, sid) == f'00-{tid}-{sid}-01'
    for bad in (None, '', 'garbage', f'00-{tid}-{sid}',
                f'00-{"0" * 32}-{sid}-01',     # all-zero trace id
                f'00-{tid}-{"0" * 16}-01',     # all-zero parent
                f'00-{tid[:-2]}-{sid}-01'):    # short trace id
        assert qt.parse_traceparent(bad) is None, bad


def test_start_adopts_or_mints():
    tracer = qt.QueryTracer(path=None, seed=7)
    tid, sid = '12' * 16, '34' * 8
    adopted = tracer.start(f'00-{tid}-{sid}-01')
    assert adopted.trace_id == tid
    assert adopted.parent_id == sid
    minted = tracer.start('not-a-traceparent')
    assert minted.parent_id is None
    assert len(minted.trace_id) == 32
    int(minted.trace_id, 16)
    # Minting is a pure function of (seed, seq): same worker replay
    # mints the same ids.
    again = qt.QueryTracer(path=None, seed=7)
    again.start()
    assert again.start().trace_id == minted.trace_id


# ---------------------------------------------------------------------------
# Retention: deterministic sample, exact slowest-K, errors never lost
# ---------------------------------------------------------------------------

def run_load(tracer, n=60, error_every=None):
    """Feed ``n`` synthetic queries with distinct totals (ms == seq+1);
    every ``error_every``-th finishes as a 500."""
    for i in range(n):
        trace = make_trace(tracer, (i + 1) * 1e-3)
        is_err = error_every is not None and i % error_every == 0
        tracer.finish(trace, status=500 if is_err else 200,
                      bucket='16x48',
                      error='engine-fault' if is_err else None,
                      total_s=(i + 1) * 1e-3)


def kept_ids(path):
    with open(path) as f:
        return [(json.loads(line)['trace_id'],
                 tuple(json.loads(line)['kept']))
                for line in f if line.strip()]


def test_sampling_deterministic_and_bounded(tmp_path):
    """Same seed -> byte-identical kept-set across two independent
    tracers; the file never exceeds capacity+error_capacity+slowest_k."""
    paths = [str(tmp_path / f'{i}' / 'qtrace.jsonl') for i in (0, 1)]
    kept = []
    for path in paths:
        tracer = qt.QueryTracer(path=path, sample_rate=0.3, slowest_k=4,
                                capacity=16, error_capacity=8, seed=42)
        run_load(tracer, n=80, error_every=9)
        assert tracer.flush()
        kept.append(kept_ids(path))
    assert kept[0] == kept[1]
    assert len(kept[0]) <= 16 + 8 + 4
    reasons = {r for _tid, rs in kept[0] for r in rs}
    assert reasons <= {'sampled', 'slowest', 'error'}
    assert 'sampled' in reasons and 'slowest' in reasons \
        and 'error' in reasons
    # A different seed keeps a different sampled subset (the decision
    # hashes the seed, not just the trace id).
    other = qt.QueryTracer(path=str(tmp_path / 'other.jsonl'),
                           sample_rate=0.3, slowest_k=4, capacity=16,
                           error_capacity=8, seed=43)
    run_load(other, n=80, error_every=9)
    other.flush()
    assert kept_ids(str(tmp_path / 'other.jsonl')) != kept[0]


def test_slowest_k_reservoir_exact(tmp_path):
    """sample_rate 0 isolates the reservoir: exactly K records, and
    they are exactly the K slowest queries."""
    path = str(tmp_path / 'qtrace.jsonl')
    tracer = qt.QueryTracer(path=path, sample_rate=0.0, slowest_k=5,
                            capacity=64, seed=0)
    run_load(tracer, n=40)
    tracer.flush()
    records = [json.loads(line) for line in open(path) if line.strip()]
    assert len(records) == 5
    assert all(r['kept'] == ['slowest'] for r in records)
    # run_load's totals are (seq+1) ms: the slowest five are seqs 35-39.
    assert sorted(r['seq'] for r in records) == [35, 36, 37, 38, 39]


def test_errors_never_sampled_out(tmp_path):
    """Every error is kept while the ring has room; past the bound the
    OLDEST are evicted and the truncation is counted, never silent."""
    path = str(tmp_path / 'qtrace.jsonl')
    tracer = qt.QueryTracer(path=path, sample_rate=0.0, slowest_k=0,
                            capacity=0, error_capacity=10, seed=0)
    run_load(tracer, n=30, error_every=1)    # 30 errors, ring of 10
    tracer.flush()
    records = [json.loads(line) for line in open(path) if line.strip()]
    assert len(records) == 10
    assert all(r['kept'] == ['error'] for r in records)
    assert [r['seq'] for r in records] == list(range(20, 30))
    summary = tracer.summary()
    assert summary['errors'] == 30
    assert summary['errors_truncated'] == 20
    # Below the bound nothing is lost.
    t2 = qt.QueryTracer(path=None, sample_rate=0.0, slowest_k=0,
                        capacity=0, error_capacity=10)
    run_load(t2, n=8, error_every=1)
    assert t2.summary()['errors'] == 8
    assert t2.summary()['errors_truncated'] == 0


def test_slo_breach_hook_fires_with_record():
    breached = []
    tracer = qt.QueryTracer(path=None, slo_s=0.010,
                            on_breach=breached.append)
    run_load(tracer, n=20)                  # totals 1..20 ms, slo 10 ms
    assert tracer.summary()['slo_breaches'] == 10
    assert len(breached) == 10
    assert all(r['total_ms'] > 10.0 for r in breached)
    assert all(r['spans'] for r in breached)


# ---------------------------------------------------------------------------
# Summaries and exports
# ---------------------------------------------------------------------------

def test_summary_gap_attribution(tmp_path):
    path = str(tmp_path / 'qtrace.jsonl')
    tracer = qt.QueryTracer(path=path, sample_rate=1.0, slowest_k=2,
                            seed=0)
    run_load(tracer, n=50)
    tracer.flush()
    summary = json.load(open(tracer.summary_path))
    assert summary['queries'] == 50
    assert summary['stage_vocabulary'] == list(qt.SERVE_SPAN_NAMES)
    e2e = summary['end_to_end']
    # Histogram quantiles on the x1.25 ladder: within 25% of exact.
    assert e2e['count'] == 50
    assert abs(e2e['p50_ms'] - 25.5) / 25.5 < 0.25
    gap = summary['gap_attribution']
    # run_load puts 80% of each total in device_execute: the spread
    # must attribute there.
    assert gap['dominant_stage'] == 'device_execute'
    assert gap['p95_minus_p50_ms'] > 0
    # Exact (kept-set) attribution agrees on the dominant stage.
    records, loaded_summary, _ = qt.load_records(str(tmp_path))
    assert loaded_summary['queries'] == 50
    pct = qt.stage_percentiles(records)
    attr = qt.gap_attribution(pct)
    assert attr['dominant_stage'] == 'device_execute'
    assert 0 < attr['dominant_share'] <= 1.0


def test_chrome_export_parses_through_trace_events(tmp_path):
    tracer = qt.QueryTracer(path=None, sample_rate=1.0)
    records = [tracer.finish(make_trace(tracer, 0.02), total_s=0.02)
               for _ in range(3)]
    payload = qt.chrome_trace_events(records)
    path = tmp_path / 'qtrace.trace.json'
    path.write_text(json.dumps(payload))
    loaded = trace_events.read_trace_file(str(path))
    tracks = trace_events.build_tracks(loaded['traceEvents'])
    assert len(tracks) == 3                  # one thread row per query
    for track in tracks:
        assert track.process == 'dgmc-qtrace'
        assert track.thread.startswith('query ')
        names = {name for _ts, _dur, name, _args in track.slices}
        assert names <= set(qt.SERVE_SPAN_NAMES)
        for _ts, _dur, name, args in track.slices:
            assert args['stages'] == list(qt.SERVE_SPAN_STAGES[name])


def test_metric_families_strict_exposition():
    tracer = qt.QueryTracer(path=None, sample_rate=1.0, slo_s=0.010)
    run_load(tracer, n=20, error_every=7)
    text = prometheus_exposition(tracer.metric_families())
    families = parse_exposition(text)
    stage_fam = families['dgmc_query_stage_seconds']
    assert stage_fam['type'] == 'histogram'
    counts = {s[1]['stage']: s[2] for s in stage_fam['samples']
              if s[0].endswith('_count')}
    assert set(counts) == set(qt.SERVE_SPAN_NAMES)
    assert counts['device_execute'] == 20
    assert counts['shortlist_merge'] == 0    # unexercised stage: 0, not
    assert families['dgmc_query_trace_seconds']['type'] == 'histogram'
    kept = {s[1]['reason']: s[2]
            for s in families['dgmc_qtrace_kept_total']['samples']}
    assert set(kept) == {'sampled', 'slowest', 'error'}
    assert kept['error'] == 3
    [(_, _, n_q)] = families['dgmc_qtrace_queries_total']['samples']
    assert n_q == 20
    [(_, _, n_b)] = \
        families['dgmc_qtrace_slo_breaches_total']['samples']
    assert n_b == 10


def test_report_cli(tmp_path, capsys):
    obs = tmp_path / 'obs'
    tracer = qt.QueryTracer(path=str(obs / 'qtrace.jsonl'),
                            sample_rate=1.0, slowest_k=2, seed=0)
    run_load(tracer, n=12, error_every=5)
    tracer.flush()
    chrome_out = str(tmp_path / 'qtrace.chrome.json')
    assert qt.main([str(obs), '--slowest', '2',
                    '--chrome', chrome_out]) == 0
    out = capsys.readouterr().out
    assert 'dominant stage: device_execute' in out
    assert 'trace ' in out                   # a span tree was printed
    trace_events.read_trace_file(chrome_out)
    assert qt.main([str(obs), '--json']) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload['gap_attribution']['dominant_stage'] \
        == 'device_execute'
    # Supervised layout: the dir resolves to the LAST attempt.
    sup = tmp_path / 'sup'
    for attempt, n in (('attempt_0', 3), ('attempt_1', 7)):
        t = qt.QueryTracer(path=str(sup / attempt / 'qtrace.jsonl'),
                           sample_rate=1.0, seed=0)
        run_load(t, n=n)
        t.flush()
    records, summary, resolved = qt.load_records(str(sup))
    assert 'attempt_1' in resolved
    assert summary['queries'] == 7
    # Missing account: a clear error, not a traceback.
    assert qt.main([str(tmp_path / 'nowhere')]) == 1
