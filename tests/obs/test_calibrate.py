"""obs.calibrate: median/MAD noise-floor fits, the fit CLI, and the
round-trip through ``obs.diff --calibration`` — a within-noise delta
that the fixed threshold failed must pass the calibrated gate, a
genuine regression must still fail, and a tight calibration must be
able to FAIL a delta the fixed threshold waved through."""

import copy
import json
import os

import pytest

from dgmc_tpu.obs import calibrate as cal_mod
from dgmc_tpu.obs import diff as diff_mod
from tests.obs.test_diff import BASE_TIMINGS, write_run


def test_fit_samples_golden():
    s = cal_mod.fit_samples([1.0, 2.0, 3.0, 4.0, 100.0])
    assert s['n'] == 5
    assert s['median'] == 3.0
    assert s['mad'] == 1.0  # the outlier does not move the MAD
    assert s['sigma'] == pytest.approx(1.4826)
    assert s['rel_sigma'] == pytest.approx(1.4826 / 3.0)
    assert (s['min'], s['max']) == (1.0, 100.0)
    with pytest.raises(ValueError):
        cal_mod.fit_samples([])


def test_fit_samples_zero_median_has_no_rel_sigma():
    s = cal_mod.fit_samples([-1.0, 0.0, 1.0])
    assert s['median'] == 0.0
    assert s['rel_sigma'] is None


def _repeat_runs(tmp_path, p50s):
    dirs = []
    for i, p50 in enumerate(p50s):
        t = copy.deepcopy(BASE_TIMINGS)
        t['steps']['p50_s'] = p50
        dirs.append(write_run(tmp_path, f'rep{i}', timings=t))
    return dirs


def test_fit_calibration_from_obs_dirs(tmp_path):
    dirs = _repeat_runs(tmp_path, [0.10, 0.11, 0.12])
    cal = cal_mod.fit_calibration(obs_dirs=dirs)
    m = cal['metrics']['step_p50_s']
    assert m['n'] == 3
    assert m['median'] == 0.11
    assert m['rel_sigma'] == pytest.approx(1.4826 * 0.01 / 0.11)
    # Repeat-identical metrics fit a zero noise floor, not a crash.
    assert cal['metrics']['compile_events']['rel_sigma'] == 0.0
    assert cal['version'] == cal_mod.CALIBRATION_SCHEMA_VERSION


def test_fit_calibration_from_round_files(tmp_path):
    for i, qps in enumerate([20.0, 22.0, 21.0], start=1):
        p = tmp_path / f'SERVE_r0{i}.json'
        p.write_text(json.dumps({
            'family': 'SERVE', 'round': i, 'qps': qps,
            'clients': 4, 'hits_at_1': 0.19,
            'latency': {'client_p50_ms': 150.0}}))
    cal = cal_mod.fit_calibration(round_paths=[str(tmp_path)])
    assert cal['metrics']['SERVE.qps']['n'] == 3
    assert cal['metrics']['SERVE.qps']['median'] == 21.0
    assert 'round' not in {k.split('.')[1]
                           for k in cal['metrics']}


def test_fit_cli_writes_calibration(tmp_path, capsys):
    dirs = _repeat_runs(tmp_path, [0.10, 0.11, 0.12])
    out = str(tmp_path / 'calibration.json')
    rc = cal_mod.main(['--obs-dir', dirs[0], '--obs-dir', dirs[1],
                       '--obs-dir', dirs[2], '--out', out])
    assert rc == 0
    with open(out) as f:
        cal = json.load(f)
    assert cal['metrics']['step_p50_s']['n'] == 3
    assert 'step_p50_s' in capsys.readouterr().out


def test_fit_cli_usage_and_undersampled(tmp_path):
    # No sources at all: usage error (argparse exits 2).
    with pytest.raises(SystemExit) as exc:
        cal_mod.main(['--out', str(tmp_path / 'c.json')])
    assert exc.value.code == 2
    # One repeat cannot calibrate anything at min-samples 2.
    d = _repeat_runs(tmp_path, [0.10])
    assert cal_mod.main(['--obs-dir', d[0],
                         '--out', str(tmp_path / 'c.json')]) == 2


CAL = {
    'version': 1,
    'min_samples': 2,
    'metrics': {
        # A noisy step-time floor: rel_sigma 0.15 -> 3-sigma gate 0.45.
        'step_p50_s': {'n': 5, 'median': 0.1, 'mad': 0.0101,
                       'sigma': 0.015, 'rel_sigma': 0.15,
                       'min': 0.08, 'max': 0.13},
    },
}


def _write_cal(tmp_path, cal):
    p = tmp_path / 'calibration.json'
    p.write_text(json.dumps(cal))
    return str(p)


def test_apply_calibration_scales_armed_gates():
    thresholds = {'step_p50': 0.25, 'step_p95': 0.40, 'min_hits1': None}
    out, notes = cal_mod.apply_calibration(thresholds, CAL)
    assert out['step_p50'] == pytest.approx(0.45)  # 3 x 0.15
    assert out['step_p95'] == 0.40      # no stats: fixed kept
    assert out['min_hits1'] is None     # unarmed gates stay unarmed
    assert len(notes) == 1
    n = notes[0]
    assert n['gate'] == 'step_p50' and n['metric'] == 'step_p50_s'
    assert n['fixed'] == 0.25 and n['calibrated'] == pytest.approx(0.45)


def test_apply_calibration_guards():
    # Under-sampled stats are ignored (min_samples=3 at apply time).
    thin = {'version': 1, 'metrics': {
        'step_p50_s': dict(CAL['metrics']['step_p50_s'], n=2)}}
    out, notes = cal_mod.apply_calibration({'step_p50': 0.25}, thin)
    assert out['step_p50'] == 0.25 and notes == []
    # A dead-flat repeat set floors at 0.01, never a zero-width gate.
    flat = {'version': 1, 'metrics': {
        'step_p50_s': dict(CAL['metrics']['step_p50_s'],
                           rel_sigma=0.0)}}
    out, _ = cal_mod.apply_calibration({'step_p50': 0.25}, flat)
    assert out['step_p50'] == 0.01
    # rel_sigma None (zero median) cannot scale a relative gate.
    nocal = {'version': 1, 'metrics': {
        'step_p50_s': dict(CAL['metrics']['step_p50_s'],
                           rel_sigma=None)}}
    out, notes = cal_mod.apply_calibration({'step_p50': 0.25}, nocal)
    assert out['step_p50'] == 0.25 and notes == []


def test_load_calibration_errors(tmp_path):
    with pytest.raises(ValueError):
        cal_mod.load_calibration(str(tmp_path / 'absent.json'))
    bad = tmp_path / 'bad.json'
    bad.write_text(json.dumps({'no_metrics': True}))
    with pytest.raises(ValueError):
        cal_mod.load_calibration(str(bad))


def _p50_run(tmp_path, name, p50):
    t = copy.deepcopy(BASE_TIMINGS)
    t['steps'] = dict(t['steps'], p50_s=p50)
    return write_run(tmp_path, name, timings=t)


def test_diff_calibration_loosens_within_noise_delta(tmp_path, capsys):
    """The tentpole round-trip: +30% p50 fails the fixed 25% gate but
    is within 3 sigma of a 15% noise floor — the calibrated gate must
    pass it, and say so in an info row."""
    a = _p50_run(tmp_path, 'a', 0.10)
    b = _p50_run(tmp_path, 'b', 0.13)
    cal = _write_cal(tmp_path, CAL)
    assert diff_mod.main([a, b]) == 1          # fixed: REGRESSION
    capsys.readouterr()
    assert diff_mod.main([a, b, '--calibration', cal]) == 0
    out = capsys.readouterr().out
    assert 'calibrated:step_p50' in out
    assert 'rel_sigma' in out


def test_diff_calibration_still_fails_genuine_regression(tmp_path):
    a = _p50_run(tmp_path, 'a', 0.10)
    b = _p50_run(tmp_path, 'b', 0.20)  # +100% >> 3 x 0.15
    cal = _write_cal(tmp_path, CAL)
    assert diff_mod.main([a, b, '--calibration', cal]) == 1


def test_diff_calibration_tightens_quiet_metric(tmp_path, capsys):
    """The other direction: a +10% delta the fixed 25% gate waves
    through FAILS once calibration says the metric repeats within
    2%."""
    a = _p50_run(tmp_path, 'a', 0.10)
    b = _p50_run(tmp_path, 'b', 0.11)
    quiet = {'version': 1, 'metrics': {
        'step_p50_s': dict(CAL['metrics']['step_p50_s'],
                           rel_sigma=0.02)}}
    cal = _write_cal(tmp_path, quiet)
    assert diff_mod.main([a, b]) == 0          # fixed: passes
    capsys.readouterr()
    assert diff_mod.main([a, b, '--calibration', cal]) == 1
    assert 'REGRESSION' in capsys.readouterr().out


def test_diff_calibration_z_flag(tmp_path):
    a = _p50_run(tmp_path, 'a', 0.10)
    b = _p50_run(tmp_path, 'b', 0.13)
    cal = _write_cal(tmp_path, CAL)
    # z=1: gate 0.15 < 0.30 delta -> fail; default z=3 passes above.
    assert diff_mod.main([a, b, '--calibration', cal,
                          '--calibration-z', '1.0']) == 1


def test_diff_calibration_preserves_lost_account_rule(tmp_path, capsys):
    """Calibration widens gates; it must never un-fail a vanished
    metric (the lost-account asymmetry is not noise)."""
    a = write_run(tmp_path, 'a')
    timerless = copy.deepcopy(BASE_TIMINGS)
    timerless['steps'] = {}
    b = write_run(tmp_path, 'b', timings=timerless)
    cal = _write_cal(tmp_path, CAL)
    assert diff_mod.main([a, b, '--calibration', cal]) == 1
    assert 'missing from candidate' in capsys.readouterr().out


def test_diff_calibration_unreadable_is_usage_error(tmp_path, capsys):
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    assert diff_mod.main([a, b, '--calibration',
                          str(tmp_path / 'absent.json')]) == 2
    assert 'calibration' in capsys.readouterr().err


def test_diff_json_carries_calibration_notes(tmp_path, capsys):
    a = _p50_run(tmp_path, 'a', 0.10)
    b = _p50_run(tmp_path, 'b', 0.13)
    cal = _write_cal(tmp_path, CAL)
    assert diff_mod.main([a, b, '--calibration', cal, '--json']) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload['calibration'][0]['gate'] == 'step_p50'
    uncal = json.loads('null')
    assert uncal is None  # sanity for the next assertion's shape
    capsys.readouterr()
    assert diff_mod.main([a, a, '--json']) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload['calibration'] is None


def test_timeline_trend_marks_shift_round(tmp_path, capsys):
    """obs.timeline --trend: a qps collapse at r05 reads as one
    changepoint labeled with the ROUND, not the list index."""
    from dgmc_tpu.obs import timeline as tl
    for i, qps in enumerate([20.0, 21.0, 20.5, 20.8, 5.0], start=1):
        p = tmp_path / f'SERVE_r0{i}.json'
        p.write_text(json.dumps({
            'family': 'SERVE', 'round': i, 'qps': qps, 'clients': 4,
            'latency': {'client_p50_ms': 150.0,
                        'client_p95_ms': 300.0}}))
    rows = tl.collect_rounds([str(tmp_path)])
    trends = tl.trend(rows)
    qps_t = next(t for t in trends if t['metric'] == 'qps')
    assert qps_t['changepoints'] == [
        {'round': 5, 'direction': 'down', 'value': 5.0}]
    # Stable series stay quiet.
    p50_t = next(t for t in trends
                 if t['metric'] == 'latency_p50_ms')
    assert p50_t['changepoints'] == []
    assert tl.main([str(tmp_path), '--trend']) == 0
    out = capsys.readouterr().out
    assert 'trend changepoints' in out
    assert 'r05 down' in out
