"""Multi-device/host aggregation contract (obs/aggregate.py).

Synthesized host subdirectories pin the skew arithmetic (a 2x straggler
device must read as ratio 2.0 against the median); a real 8-virtual-
device fenced run pins the device-series plumbing end to end; the
report/diff layers must consume the artifact.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgmc_tpu.obs import aggregate as agg_mod
from dgmc_tpu.obs import report


def _host(root, name, device_means=(), p50=0.1, wall=10.0,
          dev_peaks=(), host_peak=None, steps=8, hang=None):
    d = os.path.join(str(root), name) if name else str(root)
    os.makedirs(d, exist_ok=True)
    timings = {
        'wall_s': wall,
        'steps': {'steps': steps, 'mean_s': p50, 'p50_s': p50,
                  'p95_s': p50 * 1.2, 'max_s': p50 * 2,
                  'total_s': p50 * steps},
        'compile': {'events': 1, 'compile_s': 1.0},
    }
    if device_means:
        timings['device_steps'] = {
            str(i): {'count': steps, 'mean_s': m, 'p50_s': m,
                     'max_s': m * 1.1, 'last_s': m}
            for i, m in enumerate(device_means)}
    with open(os.path.join(d, 'timings.json'), 'w') as f:
        json.dump(timings, f)
    devices = [{'id': i, 'peak_bytes_in_use': p}
               for i, p in enumerate(dev_peaks)]
    host = {'peak_rss_bytes': host_peak} if host_peak else {}
    with open(os.path.join(d, 'memory.json'), 'w') as f:
        json.dump({'snapshots': [{'tag': 'end', 'devices': devices,
                                  'host': host}]}, f)
    with open(os.path.join(d, 'metrics.jsonl'), 'w') as f:
        f.write(json.dumps({'step': 1, 'loss': 1.0}) + '\n')
    if hang:
        with open(os.path.join(d, 'hang_report.json'), 'w') as f:
            json.dump(hang, f)
    return d


def test_single_dir_acts_as_host0(tmp_path):
    _host(tmp_path, None, device_means=(0.1, 0.1, 0.2, 0.1))
    s = agg_mod.aggregate(str(tmp_path))
    assert s['hosts'] == 1
    assert list(s['per_host']) == ['host_0']
    # devices 0,1,3 at 100ms, device 2 at 200ms: median 100ms, max 200ms.
    assert s['skew']['step_time_ratio'] == pytest.approx(2.0)
    assert s['step_time']['worst'] == {'host': 'host_0', 'device': '2'}
    assert s['step_time']['source'] == 'device_series'


def test_multi_host_merge_and_memory_spread(tmp_path):
    _host(tmp_path, 'host_0', device_means=(0.1, 0.1),
          dev_peaks=(1 << 30, 1 << 30), wall=10.0)
    _host(tmp_path, 'host_1', device_means=(0.1, 0.3),
          dev_peaks=(1 << 30, 3 << 30), wall=14.0)
    s = agg_mod.aggregate(str(tmp_path))
    assert s['hosts'] == 2
    assert len(s['devices']) == 4
    assert s['skew']['step_time_ratio'] == pytest.approx(3.0)
    assert s['step_time']['worst'] == {'host': 'host_1', 'device': '1'}
    assert s['skew']['memory_ratio'] == pytest.approx(3.0)
    assert s['memory']['source'] == 'device'
    assert s['skew']['wall_ratio'] == pytest.approx(14.0 / 12.0,
                                                    abs=1e-3)


def test_host_p50_fallback_when_no_device_series(tmp_path):
    _host(tmp_path, 'host_0', p50=0.1)
    _host(tmp_path, 'host_1', p50=0.4)
    s = agg_mod.aggregate(str(tmp_path))
    assert s['step_time']['source'] == 'host_p50'
    assert s['skew']['step_time_ratio'] == pytest.approx(0.4 / 0.25)


def test_hung_host_is_flagged(tmp_path):
    _host(tmp_path, 'host_0')
    _host(tmp_path, 'host_1',
          hang={'reason': 'deadline', 'stalled_for_s': 99.0,
                'in_flight': {'phase': 'step', 'name': 7}})
    s = agg_mod.aggregate(str(tmp_path))
    assert s['hung_hosts'] == ['host_1']
    assert 'hang_report' in s['per_host']['host_1']


def test_hung_host_attributed_to_fence_and_phase(tmp_path):
    """'Hung' alone is not actionable: the aggregate must say what the
    host was inside (the hang report's in-flight span), what it last
    completed, and — from the control-plane heartbeat — the last fence
    every peer agrees it reached."""
    _host(tmp_path, 'host_0', device_means=(0.1,))
    _host(tmp_path, 'host_1', device_means=(0.1,),
          hang={'reason': 'fence-deadline: epoch-fence incomplete '
                          'after 30.0s',
                'in_flight': {'phase': 'fence', 'name': 'epoch-fence'},
                'last_completed': {'phase': 'step', 'name': 11,
                                   'duration_s': 0.4},
                'stalled_for_s': 31.0})
    cdir = os.path.join(str(tmp_path), 'control')
    os.makedirs(cdir)
    with open(os.path.join(cdir, 'host_1.json'), 'w') as f:
        json.dump({'host': 1, 'time': 123.0, 'phase': 'epoch',
                   'step': 12,
                   'last_fence': {'phase': 'epoch-fence', 'step': 10,
                                  'time': 120.0}}, f)
    s = agg_mod.aggregate(str(tmp_path))
    assert s['hung_hosts'] == ['host_1']
    att = s['hang_attribution']['host_1']
    assert att['reason'].startswith('fence-deadline')
    assert att['in_flight'] == {'phase': 'fence', 'name': 'epoch-fence'}
    assert att['last_completed']['name'] == 11
    assert att['last_fence'] == {'phase': 'epoch-fence', 'step': 10,
                                 'time': 120.0}
    assert att['last_heartbeat']['step'] == 12
    text = agg_mod.render(s)
    assert 'stuck in fence:epoch-fence' in text
    assert 'last fence epoch-fence@10' in text


def test_non_coordinator_hang_reaches_root_summary_and_diff(tmp_path):
    """A hang on host_2 with a clean host_0 must surface as the ROOT
    run's hang (and therefore fail the diff's hung-candidate gate) —
    the straggling non-coordinator host is the whole point of per-host
    obs dirs."""
    from dgmc_tpu.obs import diff as diff_mod
    clean = str(tmp_path / 'clean')
    _host(clean, 'host_0')
    _host(clean, 'host_1')
    hung = str(tmp_path / 'hung')
    _host(hung, 'host_0')
    _host(hung, 'host_1',
          hang={'reason': 'deadline', 'stalled_for_s': 77.0,
                'in_flight': {'phase': 'step', 'name': 9}})
    s = report.summarize(report.load_run(hung))
    assert s['hang_report']['reason'] == 'deadline'
    assert s['hang_report']['host'] == 'host_1'
    assert s['hung_hosts'] == ['host_1']
    assert diff_mod.main([clean, hung]) == 1


def test_empty_root_returns_none_and_cli_errors(tmp_path):
    assert agg_mod.aggregate(str(tmp_path)) is None
    assert agg_mod.main([str(tmp_path)]) == 2


def test_cli_writes_aggregate_json_and_renders(tmp_path, capsys):
    _host(tmp_path, 'host_0', device_means=(0.1, 0.2))
    assert agg_mod.main([str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert 'step-time skew' in out and 'host_0' in out
    on_disk = json.load(open(tmp_path / 'aggregate.json'))
    assert on_disk['skew']['step_time_ratio'] == pytest.approx(
        0.2 / 0.15, abs=1e-3)


def test_report_consumes_multi_host_root(tmp_path, capsys):
    """A multi-host root (no artifacts of its own) reports as host_0
    plus the aggregate skew block."""
    _host(tmp_path, 'host_0', device_means=(0.1, 0.1))
    _host(tmp_path, 'host_1', device_means=(0.1, 0.2))
    assert agg_mod.main([str(tmp_path)]) == 0
    capsys.readouterr()
    assert report.main([str(tmp_path), '--json']) == 0
    s = json.loads(capsys.readouterr().out)
    assert s['hosts'] == 2
    assert s['skew']['step_time_ratio'] == pytest.approx(2.0)
    assert s['steps'] == 8                      # host_0's run summary
    # Root-level efficiency.json (e.g. obs.cost --obs-dir <root>) must
    # survive the host_0 rebind into the root summary.
    with open(tmp_path / 'efficiency.json', 'w') as f:
        json.dump({'mfu': 0.25, 'programs': {}}, f)
    s = report.summarize(report.load_run(str(tmp_path)))
    assert s['mfu'] == 0.25


def test_fence_devices_series_feeds_aggregate(tmp_path):
    """End to end on the real 8-virtual-device platform: a fenced run's
    per-device series lands in timings.json and aggregates to a skew
    row per device."""
    from dgmc_tpu.obs import RunObserver
    d = str(tmp_path / 'obs')
    n_dev = len(jax.devices())
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    mesh = Mesh(np.array(jax.devices()), ('data',))
    f = jax.jit(lambda x: jnp.sum(x * 2.0))
    x = jax.device_put(
        np.random.randn(n_dev * 2, 4).astype(np.float32),
        NamedSharding(mesh, P('data')))
    with RunObserver(d) as obs:
        for _ in range(3):
            with obs.step():
                out = f(x)
            times = obs.fence_devices(out)
        assert sorted(times) == sorted(str(dev.id)
                                       for dev in jax.devices())
        obs.log(1, loss=1.0)
    t = json.load(open(os.path.join(d, 'timings.json')))
    assert len(t['device_steps']) == n_dev
    for a in t['device_steps'].values():
        assert a['count'] == 3 and a['mean_s'] > 0
    s = agg_mod.aggregate(d)
    assert len(s['devices']) == n_dev
    assert s['skew']['step_time_ratio'] >= 1.0
    # The fences also render as per-device Perfetto counter tracks.
    trace = json.load(open(os.path.join(d, 'trace.json')))
    fence_tracks = {e['name'] for e in trace['traceEvents']
                    if e.get('cat') == 'fence'}
    assert len(fence_tracks) == n_dev
    assert f'device_step[{jax.devices()[0].id}]' in fence_tracks


def test_fence_devices_noops(tmp_path):
    from dgmc_tpu.obs import RunObserver
    disabled = RunObserver(None)
    assert disabled.fence_devices(jnp.ones(())) is None
    with RunObserver(str(tmp_path / 'obs')) as obs:
        assert obs.fence_devices(3.5) is None       # non-jax input


def test_scrape_probes_advertised_endpoints(tmp_path):
    """--scrape: per-host live /healthz verdicts discovered from the
    port each heartbeat.json advertises; an unreachable endpoint is
    flagged, a host without a port is untouched."""
    from dgmc_tpu.obs.live import TelemetryServer
    h0 = _host(tmp_path, 'host_0', device_means=(0.1,))
    h1 = _host(tmp_path, 'host_1', device_means=(0.1,))
    _host(tmp_path, 'host_2', device_means=(0.1,))
    srv_ok = TelemetryServer(
        0, health_fn=lambda: {'healthy': True,
                              'heartbeat_age_s': 0.5}).start()
    srv_bad = TelemetryServer(
        0, health_fn=lambda: {'healthy': False}).start()
    dead_port = srv_bad.port
    try:
        json.dump({'time': 1.0, 'pid': 1, 'port': srv_ok.port},
                  open(os.path.join(h0, 'heartbeat.json'), 'w'))
        json.dump({'time': 1.0, 'pid': 2, 'port': srv_bad.port},
                  open(os.path.join(h1, 'heartbeat.json'), 'w'))
        s = agg_mod.aggregate(str(tmp_path), scrape=True)
        live0 = s['per_host']['host_0']['live']
        assert live0['healthy'] is True
        assert live0['heartbeat_age_s'] == 0.5
        live1 = s['per_host']['host_1']['live']
        assert live1['healthy'] is False
        assert 'live' not in s['per_host']['host_2']
        assert s['live_unhealthy_hosts'] == ['host_1']
        text = agg_mod.render(s)
        assert 'LIVE-UNHEALTHY HOSTS' in text
        assert f':{srv_ok.port} ok' in text
    finally:
        srv_ok.close()
        srv_bad.close()
    # Endpoint gone with a FRESH heartbeat -> a live anomaly
    # (unreachable); with a STALE heartbeat -> the run simply ended
    # (leftover advertisement), NOT flagged live-unhealthy.
    import time as _time
    json.dump({'time': _time.time(), 'pid': 2, 'port': dead_port},
              open(os.path.join(h1, 'heartbeat.json'), 'w'))
    s = agg_mod.aggregate(str(tmp_path), scrape=True)
    live1 = s['per_host']['host_1']['live']
    assert live1.get('unreachable') is True
    assert live1['port'] == dead_port
    assert 'host_1' in s['live_unhealthy_hosts']
    json.dump({'time': 1.0, 'pid': 2, 'port': dead_port},
              open(os.path.join(h1, 'heartbeat.json'), 'w'))
    s = agg_mod.aggregate(str(tmp_path), scrape=True)
    live1 = s['per_host']['host_1']['live']
    assert live1.get('ended') is True
    assert 'host_1' not in s['live_unhealthy_hosts']
    assert f':{dead_port} ended' in agg_mod.render(s)


def test_without_scrape_no_live_blocks(tmp_path):
    h0 = _host(tmp_path, 'host_0', device_means=(0.1,))
    json.dump({'time': 1.0, 'pid': 1, 'port': 1},
              open(os.path.join(h0, 'heartbeat.json'), 'w'))
    s = agg_mod.aggregate(str(tmp_path))
    assert 'live' not in s['per_host']['host_0']
    assert 'live_unhealthy_hosts' not in s
