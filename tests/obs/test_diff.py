"""Cross-run diff contract: equal runs exit 0; an injected 2x step-time
regression (and friends) exits nonzero — the CI perf gate's teeth."""

import copy
import json
import os

import pytest

from dgmc_tpu.obs import diff as diff_mod

BASE_TIMINGS = {
    'wall_s': 10.0,
    'steps': {'steps': 50, 'mean_s': 0.1, 'p50_s': 0.1, 'p95_s': 0.12,
              'max_s': 0.2, 'total_s': 5.0},
    'compile': {'events': 3, 'compile_s': 2.0, 'cache_hits': 0,
                'by_label': {}},
    'probes': {'corr_entropy': {'count': 10, 'mean': 3.0, 'last': 2.5,
                                'min': 2.0, 'max': 4.0}},
}
BASE_MEMORY = {'snapshots': [
    {'tag': 'end', 'devices': [{'id': 0, 'peak_bytes_in_use': 1 << 30}],
     'host': {}}]}
BASE_DISPATCH = {'counts': [
    {'kernel': 'topk', 'outcome': 'pallas', 'reason': 'auto-tpu',
     'count': 1}]}


def write_run(root, name, timings=None, memory=None, dispatch=None,
              efficiency=None, hang=None, aggregate=None):
    d = os.path.join(str(root), name)
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, 'timings.json'), 'w') as f:
        json.dump(timings or BASE_TIMINGS, f)
    with open(os.path.join(d, 'memory.json'), 'w') as f:
        json.dump(memory or BASE_MEMORY, f)
    with open(os.path.join(d, 'dispatch.json'), 'w') as f:
        json.dump(dispatch or BASE_DISPATCH, f)
    with open(os.path.join(d, 'metrics.jsonl'), 'w') as f:
        f.write(json.dumps({'step': 1, 'loss': 1.0}) + '\n')
    for fname, payload in (('efficiency.json', efficiency),
                           ('hang_report.json', hang),
                           ('aggregate.json', aggregate)):
        if payload is not None:
            with open(os.path.join(d, fname), 'w') as f:
                json.dump(payload, f)
    return d


def test_equal_runs_exit_zero(tmp_path, capsys):
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    assert diff_mod.main([a, b]) == 0
    out = capsys.readouterr().out
    assert '0 regression(s)' in out


def test_step_time_regression_exits_nonzero(tmp_path, capsys):
    a = write_run(tmp_path, 'a')
    slow = copy.deepcopy(BASE_TIMINGS)
    for k in ('mean_s', 'p50_s', 'p95_s', 'max_s'):
        slow['steps'][k] *= 2  # the synthetic 2x step-time regression
    b = write_run(tmp_path, 'b', timings=slow)
    rc = diff_mod.main([a, b])
    assert rc == 1
    out = capsys.readouterr().out
    assert 'REGRESSION' in out
    # ...and the same pair passes with an explicitly relaxed threshold.
    assert diff_mod.main([a, b, '--max-step-p50-regression', '1.5',
                          '--max-step-p95-regression', '1.5',
                          '--max-throughput-regression', '0.9']) == 0


def test_compile_churn_regression(tmp_path):
    a = write_run(tmp_path, 'a')
    churny = copy.deepcopy(BASE_TIMINGS)
    churny['compile']['events'] = 30
    b = write_run(tmp_path, 'b', timings=churny)
    assert diff_mod.main([a, b]) == 1
    assert diff_mod.main([a, b, '--max-new-compile-events', '50']) == 0


def test_memory_regression_and_source_mismatch(tmp_path):
    a = write_run(tmp_path, 'a')
    big = {'snapshots': [
        {'tag': 'end', 'devices': [{'id': 0,
                                    'peak_bytes_in_use': 2 << 30}],
         'host': {}}]}
    b = write_run(tmp_path, 'b', memory=big)
    assert diff_mod.main([a, b]) == 1
    # Host-RSS vs device peaks are not comparable: skipped, not failed.
    host_only = {'snapshots': [
        {'tag': 'end', 'devices': [],
         'host': {'peak_rss_bytes': 3 << 30}}]}
    c = write_run(tmp_path, 'c', memory=host_only)
    assert diff_mod.main([a, c]) == 0


def test_kernel_fallback_regression(tmp_path, capsys):
    a = write_run(tmp_path, 'a')
    fb = {'counts': [{'kernel': 'topk', 'outcome': 'fallback',
                      'reason': 'size', 'count': 1}]}
    b = write_run(tmp_path, 'b', dispatch=fb)
    assert diff_mod.main([a, b]) == 1
    assert 'fell back' in capsys.readouterr().out
    assert diff_mod.main([a, b, '--allow-kernel-fallback']) == 0


def test_candidate_missing_step_metrics_is_regression(tmp_path, capsys):
    """A candidate whose step timings vanished (broken timer, died before
    first flush) must FAIL the gate, not pass it vacuously."""
    a = write_run(tmp_path, 'a')
    timerless = copy.deepcopy(BASE_TIMINGS)
    timerless['steps'] = {}
    b = write_run(tmp_path, 'b', timings=timerless)
    assert diff_mod.main([a, b]) == 1
    assert 'missing from candidate' in capsys.readouterr().out
    # The reverse (baseline never had the metric) stays a skip.
    assert diff_mod.main([b, b]) == 0


def test_kernel_absent_from_candidate_is_regression(tmp_path, capsys):
    """A candidate that never reached the kernel's decision site lost
    the Pallas path just as surely as one that fell back."""
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b', dispatch={'counts': []})
    assert diff_mod.main([a, b]) == 1
    assert 'absent' in capsys.readouterr().out
    assert diff_mod.main([a, b, '--allow-kernel-fallback']) == 0


def test_nonfinite_candidate_fails(tmp_path):
    a = write_run(tmp_path, 'a')
    poisoned = copy.deepcopy(BASE_TIMINGS)
    poisoned['first_nonfinite'] = {'step': 7, 'stage': 'psi1'}
    b = write_run(tmp_path, 'b', timings=poisoned)
    assert diff_mod.main([a, b]) == 1


def test_json_output(tmp_path, capsys):
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    assert diff_mod.main([a, b, '--json']) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload['ok'] and payload['regressions'] == 0
    metrics = {r['metric'] for r in payload['rows']}
    assert {'step_p50_s', 'step_p95_s', 'steps_per_sec', 'compile_events',
            'peak_memory_bytes', 'probe[corr_entropy].mean'} <= metrics


def test_missing_dir_is_usage_error(tmp_path):
    a = write_run(tmp_path, 'a')
    assert diff_mod.main([a, str(tmp_path / 'nope')]) == 2


def test_empty_dir_is_usage_error(tmp_path):
    a = write_run(tmp_path, 'a')
    empty = tmp_path / 'empty'
    empty.mkdir()
    assert diff_mod.main([a, str(empty)]) == 2


def test_hung_candidate_is_regression(tmp_path, capsys):
    """Satellite pin: a candidate that left a hang_report.json must NOT
    diff as 'fewer metrics, pass' — a hang truncates the run, which
    usually improves every surviving aggregate."""
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b',
                  hang={'reason': 'deadline', 'stalled_for_s': 120.0,
                        'in_flight': {'phase': 'step', 'name': 7}})
    assert diff_mod.main([a, b]) == 1
    out = capsys.readouterr().out
    assert 'hang_report' in out and 'candidate hung' in out
    # The fix direction (baseline hung, candidate clean) passes.
    assert diff_mod.main([b, a]) == 0
    # Both hung: noted, and the remaining metrics still gate (equal
    # here, so rc 0).
    assert diff_mod.main([b, b]) == 0
    assert 'baseline hung too' in capsys.readouterr().out


EFF = {'mfu': 0.5, 'peak_flops': 1e12, 'peak_flops_source': 'table',
       'programs': {'train_step': {'flops': 1e9, 'mfu': 0.5}}}


def test_mfu_regression_gates(tmp_path, capsys):
    a = write_run(tmp_path, 'a', efficiency=EFF)
    dropped = dict(EFF, mfu=0.3)
    b = write_run(tmp_path, 'b', efficiency=dropped)
    assert diff_mod.main([a, b]) == 1          # -40% > default 25%
    assert 'mfu' in capsys.readouterr().out
    assert diff_mod.main([a, b, '--max-mfu-regression', '0.5']) == 0
    # Improvement direction passes by default.
    assert diff_mod.main([b, a]) == 0


def test_mfu_missing_from_candidate_is_regression(tmp_path, capsys):
    a = write_run(tmp_path, 'a', efficiency=EFF)
    b = write_run(tmp_path, 'b')
    assert diff_mod.main([a, b]) == 1
    assert 'missing from candidate' in capsys.readouterr().out
    # Baseline never had it: skip, not fail.
    assert diff_mod.main([b, a]) == 0


AI_EFF = {'mfu': 0.5, 'peak_flops': 1e12, 'peak_flops_source': 'table',
          'programs': {'train_step': {'flops': 1e9, 'bytes': 1e8,
                                      'arith_intensity': 10.0,
                                      'mfu': 0.5}}}


def test_intensity_regression_gates(tmp_path, capsys):
    a = write_run(tmp_path, 'a', efficiency=AI_EFF)
    slid = dict(AI_EFF)
    slid['programs'] = {'train_step': dict(AI_EFF['programs']['train_step'],
                                           arith_intensity=4.0)}
    b = write_run(tmp_path, 'b', efficiency=slid)
    assert diff_mod.main([a, b]) == 1          # -60% > default 40%
    assert 'arith_intensity' in capsys.readouterr().out
    assert diff_mod.main([a, b, '--max-intensity-regression', '0.7']) == 0
    # Improvement direction passes by default.
    assert diff_mod.main([b, a]) == 0


def test_intensity_missing_from_candidate_is_regression(tmp_path, capsys):
    a = write_run(tmp_path, 'a', efficiency=AI_EFF)
    no_ai = dict(AI_EFF)
    no_ai['programs'] = {'train_step': {'flops': 1e9, 'mfu': 0.5}}
    b = write_run(tmp_path, 'b', efficiency=no_ai)
    assert diff_mod.main([a, b]) == 1
    assert 'missing from candidate' in capsys.readouterr().out
    # Baseline never had it: skip, not fail.
    assert diff_mod.main([b, a]) == 0


def test_skew_regression_gates(tmp_path, capsys):
    agg = {'skew': {'step_time_ratio': 1.1}}
    worse = {'skew': {'step_time_ratio': 2.2}}
    a = write_run(tmp_path, 'a', aggregate=agg)
    b = write_run(tmp_path, 'b', aggregate=worse)
    assert diff_mod.main([a, b]) == 1          # 2x growth > default 50%
    assert 'skew_step_time_ratio' in capsys.readouterr().out
    assert diff_mod.main([a, b, '--max-skew-regression', '1.5']) == 0
    # Aggregation absent from one side: skipped, not a regression.
    c = write_run(tmp_path, 'c')
    assert diff_mod.main([a, c]) == 0


@pytest.mark.parametrize('probe_fallback', [True, False])
def test_probe_aggregates_from_metrics_fallback(tmp_path, probe_fallback):
    """Probe aggregates reach the diff even when timings.json predates
    the probe layer (rebuilt from the metrics.jsonl series)."""
    from dgmc_tpu.obs.report import load_run, summarize
    t = copy.deepcopy(BASE_TIMINGS)
    if probe_fallback:
        del t['probes']
    d = write_run(tmp_path, 'x', timings=t)
    if probe_fallback:
        with open(os.path.join(d, 'metrics.jsonl'), 'a') as f:
            f.write(json.dumps({'step': 1, 'probe': 'corr_entropy',
                                'value': 3.0}) + '\n')
    s = summarize(load_run(d))
    assert 'corr_entropy' in s['probes']


SCHED_EFF = {'mfu': 0.5, 'peak_flops': 1e12, 'peak_flops_source': 'table',
             'programs': {'train_step': {'flops': 1e9, 'mfu': 0.5,
                                         'overlap_fraction': 0.4,
                                         'static_peak_bytes': 1 << 20}}}


def test_min_overlap_floor_gates(tmp_path, capsys):
    """--min-overlap is an ABSOLUTE floor on the modeled collective
    overlap: a candidate under it serialized the chunk loop."""
    a = write_run(tmp_path, 'a', efficiency=SCHED_EFF)
    serial = dict(SCHED_EFF)
    serial['programs'] = {'train_step': dict(
        SCHED_EFF['programs']['train_step'], overlap_fraction=0.05)}
    b = write_run(tmp_path, 'b', efficiency=serial)
    # Floor off by default: informational only.
    assert diff_mod.main([a, b]) == 0
    assert diff_mod.main([a, b, '--min-overlap', '0.2']) == 1
    assert 'serialized below the floor' in capsys.readouterr().out
    assert diff_mod.main([a, b, '--min-overlap', '0.01']) == 0
    # The healthy run clears the same floor.
    assert diff_mod.main([b, a, '--min-overlap', '0.2']) == 0


def test_overlap_missing_from_candidate_is_regression(tmp_path, capsys):
    a = write_run(tmp_path, 'a', efficiency=SCHED_EFF)
    lost = dict(SCHED_EFF)
    lost['programs'] = {'train_step': {'flops': 1e9, 'mfu': 0.5}}
    b = write_run(tmp_path, 'b', efficiency=lost)
    assert diff_mod.main([a, b]) == 1
    assert 'missing from candidate' in capsys.readouterr().out
    # Baseline never had the account: nothing to lose.
    assert diff_mod.main([b, a]) == 0


def test_static_peak_regression_gates(tmp_path, capsys):
    a = write_run(tmp_path, 'a', efficiency=SCHED_EFF)
    fat = dict(SCHED_EFF)
    fat['programs'] = {'train_step': dict(
        SCHED_EFF['programs']['train_step'],
        static_peak_bytes=2 << 20)}
    b = write_run(tmp_path, 'b', efficiency=fat)
    assert diff_mod.main([a, b]) == 1          # +100% > default 25%
    assert 'static_peak_bytes' in capsys.readouterr().out
    assert diff_mod.main([a, b, '--max-peak-regression', '1.5']) == 0
    # Shrinking the bound passes.
    assert diff_mod.main([b, a]) == 0


# ---------------------------------------------------------------------------
# Measured-attribution gates (--min-measured-overlap, --max-idle-regression)
# ---------------------------------------------------------------------------

def _eff_measured(overlap=None, idle=None, idle_source='device'):
    """efficiency.json carrying the attribution plane's merged
    headline (obs.attribution.merge_into_efficiency shape)."""
    eff = {'mfu': 0.02, 'programs': {'train_step': {'flops': 1e9}},
           'measured': {'device_available': idle_source == 'device'}}
    if overlap is not None:
        eff['measured_overlap_fraction'] = overlap
    if idle is not None:
        eff['idle_fraction'] = idle
        eff['idle_source'] = idle_source
    return eff


def test_measured_overlap_floor(tmp_path, capsys):
    a = write_run(tmp_path, 'a', efficiency=_eff_measured(overlap=0.5))
    b = write_run(tmp_path, 'b', efficiency=_eff_measured(overlap=0.1))
    # No floor configured: informational only.
    assert diff_mod.main([a, b]) == 0
    assert diff_mod.main([a, b, '--min-measured-overlap', '0.3']) == 1
    out = capsys.readouterr().out
    assert 'below the measured floor' in out
    assert diff_mod.main([a, b, '--min-measured-overlap', '0.05']) == 0
    # Warn-level wiring (floor 0.0) can never fail a present value.
    assert diff_mod.main([a, b, '--min-measured-overlap', '0.0']) == 0


def test_measured_overlap_lost_account_fails(tmp_path, capsys):
    a = write_run(tmp_path, 'a', efficiency=_eff_measured(overlap=0.5))
    b = write_run(tmp_path, 'b', efficiency=_eff_measured())
    assert diff_mod.main([a, b]) == 1
    assert 'missing from candidate' in capsys.readouterr().out
    # The reverse (baseline never measured) reports info, not failure.
    assert diff_mod.main([b, a]) == 0


def test_idle_regression_gate(tmp_path, capsys):
    a = write_run(tmp_path, 'a', efficiency=_eff_measured(idle=0.1))
    worse = write_run(tmp_path, 'b',
                      efficiency=_eff_measured(idle=0.2))
    assert diff_mod.main([a, worse]) == 1            # +100% > 25%
    assert 'source=device' in capsys.readouterr().out
    assert diff_mod.main([a, worse,
                          '--max-idle-regression', '1.5']) == 0
    # Lost idle account fails; note names the side and its keys.
    lost = write_run(tmp_path, 'c', efficiency=_eff_measured())
    assert diff_mod.main([a, lost]) == 1
    out = capsys.readouterr().out
    assert 'missing from candidate; candidate has:' in out
    assert 'mfu' in out


def test_idle_sources_do_not_compare(tmp_path, capsys):
    a = write_run(tmp_path, 'a',
                  efficiency=_eff_measured(idle=0.0,
                                           idle_source='host-trace'))
    b = write_run(tmp_path, 'b', efficiency=_eff_measured(idle=0.9))
    assert diff_mod.main([a, b]) == 0
    assert 'sources differ' in capsys.readouterr().out


def test_idle_zero_baseline_gates_absolute(tmp_path, capsys):
    """A zero-idle baseline has no ratio; the candidate's ABSOLUTE
    idle gates against the threshold instead of skipping."""
    a = write_run(tmp_path, 'a', efficiency=_eff_measured(idle=0.0))
    b = write_run(tmp_path, 'b', efficiency=_eff_measured(idle=0.5))
    assert diff_mod.main([a, b]) == 1
    assert 'zero-idle baseline' in capsys.readouterr().out
    ok = write_run(tmp_path, 'c', efficiency=_eff_measured(idle=0.2))
    assert diff_mod.main([a, ok]) == 0


def test_missing_note_lists_available_keys(tmp_path, capsys):
    """The missing-metric UX fix: a lost-account failure names which
    side lacks the key AND lists the gated keys that run does have,
    so the CI log alone answers 'artifact gone, or just this row?'."""
    a = write_run(tmp_path, 'a', efficiency=_eff_measured(overlap=0.5))
    timerless = copy.deepcopy(BASE_TIMINGS)
    timerless['steps'] = {}
    b = write_run(tmp_path, 'b', timings=timerless)
    assert diff_mod.main([a, b]) == 1
    out = capsys.readouterr().out
    assert 'missing from candidate; candidate has:' in out
    # The candidate did keep compile counts + memory: both listed.
    assert 'compile_events' in out and 'peak_memory_bytes' in out


def _write_metrics(run_dir, record):
    with open(os.path.join(run_dir, 'metrics.jsonl'), 'w') as f:
        f.write(json.dumps({'step': 1, 'loss': 9.9}) + '\n')
        f.write(json.dumps(record) + '\n')


def test_require_equal_passes_on_exact_match(tmp_path):
    """The streamed-vs-offloaded layout-equivalence gate: identical
    final logged metrics pass at delta 0."""
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    final = {'step': 4, 'loss': 1.25, 'hits1': 0.5, 'hits10': 0.75}
    _write_metrics(a, final)
    _write_metrics(b, dict(final, offload_equal=1.0))
    assert diff_mod.main([a, b, '--require-equal',
                          'loss,hits1,hits10']) == 0


def test_require_equal_fails_on_any_drift(tmp_path, capsys):
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    _write_metrics(a, {'step': 4, 'loss': 1.25, 'hits1': 0.5})
    _write_metrics(b, {'step': 4, 'loss': 1.2500001, 'hits1': 0.5})
    assert diff_mod.main([a, b, '--require-equal', 'loss,hits1']) == 1
    out = capsys.readouterr().out
    assert 'equal:loss' in out


def test_require_equal_missing_key_fails_either_side(tmp_path, capsys):
    """A key either run failed to log fails — a gate that exits 0
    because the numbers vanished is no gate."""
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    _write_metrics(a, {'step': 4, 'loss': 1.25, 'hits1': 0.5})
    _write_metrics(b, {'step': 4, 'loss': 1.25})
    assert diff_mod.main([a, b, '--require-equal', 'loss,hits1']) == 1
    out = capsys.readouterr().out
    assert 'equal:hits1' in out


def _write_qtrace(run_dir, stages, queries=100):
    payload = {
        'queries': queries, 'errors': 0,
        'stage_vocabulary': sorted(stages),
        'end_to_end': {'count': queries, 'p50_ms': 10.0,
                       'p95_ms': 20.0, 'p99_ms': 30.0},
        'stages': {name: {'count': queries, 'p50_ms': p50,
                          'p95_ms': p95, 'p99_ms': p95 * 1.2}
                   for name, (p50, p95) in stages.items()},
    }
    with open(os.path.join(run_dir, 'qtrace_summary.json'), 'w') as f:
        json.dump(payload, f)


def test_stage_p95_gate_off_by_default(tmp_path):
    """Without --max-stage-p95-regression the qtrace account is not
    gated at all: a serving regression pair still exits 0, and a pair
    of training runs (no qtrace file) is untouched."""
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    _write_qtrace(a, {'device_execute': (10.0, 20.0)})
    _write_qtrace(b, {'device_execute': (10.0, 200.0)})
    assert diff_mod.main([a, b]) == 0


def test_stage_p95_gate_fires_when_configured(tmp_path, capsys):
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    _write_qtrace(a, {'device_execute': (10.0, 20.0),
                      'serialize': (0.1, 0.2)})
    _write_qtrace(b, {'device_execute': (10.0, 31.0),   # +55% p95
                      'serialize': (0.1, 0.2)})
    assert diff_mod.main([a, b,
                          '--max-stage-p95-regression', '0.5']) == 1
    out = capsys.readouterr().out
    assert 'qtrace[device_execute].p95_ms' in out
    # The same pair passes under a looser bound; the untouched stage
    # never fires.
    assert diff_mod.main([a, b,
                          '--max-stage-p95-regression', '0.6']) == 0


def test_stage_p95_lost_account_is_regression(tmp_path, capsys):
    """A candidate that stopped producing the per-stage account the
    baseline had fails when the gate is on; a baseline without one
    skips (first traced round has nothing to compare against)."""
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    _write_qtrace(a, {'device_execute': (10.0, 20.0)})
    assert diff_mod.main([a, b,
                          '--max-stage-p95-regression', '0.5']) == 1
    assert 'lost the qtrace stage account' in capsys.readouterr().out
    # Stage present in baseline but missing from candidate: same rule.
    _write_qtrace(b, {'serialize': (0.1, 0.2)})
    assert diff_mod.main([a, b,
                          '--max-stage-p95-regression', '0.5']) == 1
    # No baseline account: skipped, not failed.
    c = write_run(tmp_path, 'c')
    d = write_run(tmp_path, 'd')
    _write_qtrace(d, {'device_execute': (10.0, 20.0)})
    assert diff_mod.main([c, d,
                          '--max-stage-p95-regression', '0.5']) == 0


def _write_plane(run_dir, goodput=None, cap=None):
    """Drop the capacity/goodput plane's artifacts into a run dir."""
    if goodput is not None:
        with open(os.path.join(run_dir, 'goodput.json'), 'w') as f:
            json.dump(goodput, f)
    if cap is not None:
        with open(os.path.join(run_dir, 'capacity.json'), 'w') as f:
            json.dump(cap, f)


def test_goodput_floor_gate(tmp_path, capsys):
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    _write_plane(a, goodput={'goodput_ratio': 0.9})
    _write_plane(b, goodput={'goodput_ratio': 0.6})
    # Off by default: a 0.9 -> 0.6 drop is an info row, not a failure.
    assert diff_mod.main([a, b]) == 0
    assert 'no --min-goodput floor configured' in capsys.readouterr().out
    assert diff_mod.main([a, b, '--min-goodput', '0.8']) == 1
    assert 'below the floor' in capsys.readouterr().out
    assert diff_mod.main([a, b, '--min-goodput', '0.5']) == 0


def test_goodput_lost_account_fails(tmp_path, capsys):
    """A candidate that stopped recording the padding-waste account the
    baseline had fails UNCONDITIONALLY (min_overlap semantics) — a
    vanished account must never read as a pass."""
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    _write_plane(a, goodput={'goodput_ratio': 0.9})
    assert diff_mod.main([a, b]) == 1
    assert 'missing from candidate' in capsys.readouterr().out
    # The reverse (baseline never measured goodput) gates the candidate
    # against the floor alone.
    assert diff_mod.main([b, a, '--min-goodput', '0.5']) == 0
    assert diff_mod.main([b, a, '--min-goodput', '0.95']) == 1


def test_pad_fraction_absolute_increase_gate(tmp_path, capsys):
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    _write_plane(a, goodput={'goodput_ratio': 0.9,
                             'pad_fraction_max': 0.1})
    _write_plane(b, goodput={'goodput_ratio': 0.9,
                             'pad_fraction_max': 0.35})
    # +0.25 absolute: over a 0.2 allowance, within a 0.3 one.
    assert diff_mod.main([a, b]) == 0  # off by default
    assert diff_mod.main([a, b, '--max-pad-regression', '0.2']) == 1
    assert 'padding grew past the allowed increase' \
        in capsys.readouterr().out
    assert diff_mod.main([a, b, '--max-pad-regression', '0.3']) == 0


def test_pad_fraction_zero_baseline_gates_directly(tmp_path):
    """Absolute (not ratio) semantics: a perfectly-filled 0.0 baseline
    is a meaningful value and any growth past the allowance fires."""
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    _write_plane(a, goodput={'goodput_ratio': 1.0,
                             'pad_fraction_max': 0.0})
    _write_plane(b, goodput={'goodput_ratio': 0.95,
                             'pad_fraction_max': 0.05})
    assert diff_mod.main([a, b, '--max-pad-regression', '0.01']) == 1
    assert diff_mod.main([a, b, '--max-pad-regression', '0.1']) == 0


def test_pad_fraction_lost_and_baseline_missing(tmp_path, capsys):
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    _write_plane(a, goodput={'goodput_ratio': 0.9,
                             'pad_fraction_max': 0.1})
    _write_plane(b, goodput={'goodput_ratio': 0.9})
    # Candidate lost the pad account the baseline had: unconditional.
    assert diff_mod.main([a, b]) == 1
    assert 'missing from candidate' in capsys.readouterr().out
    # Baseline without the account: skipped (first measured round has
    # nothing to compare against), not failed.
    assert diff_mod.main([b, a, '--max-pad-regression', '0.05']) == 0
    assert 'skipped' in capsys.readouterr().out


def test_utilization_ceiling_gate(tmp_path, capsys):
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    _write_plane(a, cap={'utilization': 0.5})
    _write_plane(b, cap={'utilization': 0.95})
    # Off by default — training runs carry no capacity account.
    assert diff_mod.main([a, b]) == 0
    assert 'no --max-utilization ceiling configured' \
        in capsys.readouterr().out
    assert diff_mod.main([a, b, '--max-utilization', '0.9']) == 1
    assert 'over the utilization ceiling' in capsys.readouterr().out
    assert diff_mod.main([a, b, '--max-utilization', '0.99']) == 0


def test_utilization_lost_account_fails(tmp_path, capsys):
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    _write_plane(a, cap={'utilization': 0.5})
    assert diff_mod.main([a, b]) == 1
    assert 'missing from candidate' in capsys.readouterr().out
    # Ceiling configured but baseline never served: candidate still
    # gates against the absolute ceiling.
    assert diff_mod.main([b, a, '--max-utilization', '0.4']) == 1
    assert diff_mod.main([b, a, '--max-utilization', '0.9']) == 0
