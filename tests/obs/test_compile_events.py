"""Compile-event telemetry: one event per new program shape, none for
cache-hit repeats — recompile churn from unstable padding buckets becomes
a visible counter."""

import jax
import jax.numpy as jnp

from dgmc_tpu.obs import CompileWatcher


def test_one_compile_per_padding_bucket():
    # Materialize both "padding buckets" BEFORE the watcher opens so the
    # array-creation programs don't pollute the step-function counts.
    small = jax.block_until_ready(jnp.ones((4, 4)))
    large = jax.block_until_ready(jnp.ones((8, 8)))

    def step(a):
        return (a * 2.0).sum()

    step = jax.jit(step)
    with CompileWatcher() as w:
        jax.block_until_ready(step(small))
        first = w.count()
        assert first == 1   # exactly one compile for the first shape

        for _ in range(3):  # same shape: served from the jit cache
            jax.block_until_ready(step(small))
        assert w.count() == first

        jax.block_until_ready(step(large))   # new padding bucket
        assert w.count() == first + 1


def test_labels_attribute_compiles():
    x = jax.block_until_ready(jnp.ones((5, 5)))
    y = jax.block_until_ready(jnp.ones((6, 6)))
    f = jax.jit(lambda a: a.sum() * 3.0)
    with CompileWatcher() as w:
        with w.label('phase1'):
            jax.block_until_ready(f(x))
        with w.label('phase2'):
            jax.block_until_ready(f(y))
    s = w.summary()
    assert s['events'] == 2
    assert s['by_label']['phase1']['events'] == 1
    assert s['by_label']['phase2']['events'] == 1
    assert s['compile_s'] >= 0.0


def test_closed_watcher_stops_recording():
    x = jax.block_until_ready(jnp.ones((7, 3)))
    f = jax.jit(lambda a: (a + 1.0).sum())
    w = CompileWatcher().__enter__()
    w.close()
    jax.block_until_ready(f(x))
    assert w.count() == 0
