"""Live telemetry plane: streaming histogram vs the exact percentile,
Prometheus exposition pinned by a strict line-grammar parser (not a
substring check), /healthz 200→503 on a stalled heartbeat, heartbeat
port advertisement, and the flight recorder's ring/dump semantics.
jax-free except the RunObserver integration tests (which run no jitted
code — the plane is host-side by construction).
"""

import json
import math
import os
import random
import re
import time
import urllib.error
import urllib.request

import pytest

from dgmc_tpu.obs.live import (DEFAULT_LATENCY_BOUNDS, FlightRecorder,
                               STALE_AFTER_FACTOR, StreamingHistogram,
                               TelemetryServer, histogram_family,
                               probe_healthz, prometheus_exposition)
from dgmc_tpu.obs.observe import percentile

# ---------------------------------------------------------------------------
# Strict Prometheus text-format parser (the 0.0.4 line grammar). Every
# line must be a comment, blank, or a sample matching the grammar —
# anything else raises. This is the pin the acceptance criteria ask
# for: /metrics output must PARSE, not merely contain substrings.
# ---------------------------------------------------------------------------

_METRIC_RE = re.compile(r'^[a-zA-Z_:][a-zA-Z0-9_:]*$')
_LABEL_RE = re.compile(r'^[a-zA-Z_][a-zA-Z0-9_]*$')
_VALUE_RE = re.compile(
    r'^(?:[+-]?Inf|NaN|[+-]?(?:\d+\.?\d*|\.\d+)(?:[eE][+-]?\d+)?)$')


def _parse_labels(text):
    """Parse ``{k="v",...}`` handling escapes; returns (labels, rest)."""
    assert text.startswith('{'), text
    i, labels = 1, {}
    while True:
        if text[i] == '}':
            return labels, text[i + 1:]
        m = re.match(r'[a-zA-Z_][a-zA-Z0-9_]*', text[i:])
        assert m, f'bad label name at {text[i:]!r}'
        name = m.group(0)
        assert _LABEL_RE.match(name)
        i += len(name)
        assert text[i] == '=', text[i:]
        assert text[i + 1] == '"', text[i:]
        i += 2
        val = []
        while text[i] != '"':
            if text[i] == '\\':
                esc = text[i + 1]
                assert esc in ('\\', '"', 'n'), f'bad escape \\{esc}'
                val.append({'\\': '\\', '"': '"', 'n': '\n'}[esc])
                i += 2
            else:
                assert text[i] != '\n'
                val.append(text[i])
                i += 1
        i += 1
        labels[name] = ''.join(val)
        if text[i] == ',':
            i += 1


def parse_exposition(text):
    """{metric_base: {'type', 'help', 'samples': [(name, labels, value)]}}
    — raises AssertionError on any line violating the grammar."""
    assert text.endswith('\n'), 'exposition must end with a newline'
    families = {}
    current = None
    for line in text.split('\n')[:-1]:
        if not line:
            continue
        if line.startswith('# HELP '):
            rest = line[len('# HELP '):]
            name, _, help_text = rest.partition(' ')
            assert _METRIC_RE.match(name), name
            current = families.setdefault(
                name, {'type': None, 'help': None, 'samples': []})
            current['help'] = help_text
            continue
        if line.startswith('# TYPE '):
            parts = line.split(' ')
            assert len(parts) == 4, line
            name, mtype = parts[2], parts[3]
            assert _METRIC_RE.match(name), name
            assert mtype in ('counter', 'gauge', 'histogram', 'summary',
                             'untyped'), mtype
            current = families.setdefault(
                name, {'type': None, 'help': None, 'samples': []})
            current['type'] = mtype
            continue
        assert not line.startswith('#'), f'unknown comment: {line!r}'
        m = re.match(r'[a-zA-Z_:][a-zA-Z0-9_:]*', line)
        assert m, f'bad sample line: {line!r}'
        name = m.group(0)
        rest = line[len(name):]
        labels = {}
        if rest.startswith('{'):
            labels, rest = _parse_labels(rest)
        assert rest.startswith(' '), f'bad sample line: {line!r}'
        value = rest[1:]
        assert _VALUE_RE.match(value), f'bad value: {value!r} in {line!r}'
        base = re.sub(r'_(bucket|sum|count)$', '', name)
        fam = families.get(base) or families.get(name)
        assert fam is not None, f'sample {name} without TYPE/HELP'
        fam['samples'].append((name, labels, float(value)))
    return families


# ---------------------------------------------------------------------------
# StreamingHistogram
# ---------------------------------------------------------------------------

def test_histogram_bucket_counts_are_exact():
    bounds = (0.1, 1.0, 10.0)
    h = StreamingHistogram(bounds)
    values = [0.05, 0.1, 0.3, 1.0, 5.0, 50.0]
    for v in values:
        h.observe(v)
    snap = h.snapshot()
    # Prometheus le semantics: count of values <= bound, cumulative.
    assert snap['buckets'] == [
        (0.1, 2),          # 0.05, 0.1 (le is inclusive)
        (1.0, 4),          # + 0.3, 1.0
        (10.0, 5),         # + 5.0
        (math.inf, 6),     # everything
    ]
    assert snap['count'] == 6
    assert snap['sum'] == pytest.approx(sum(values))


def test_histogram_thread_safety_hammer():
    """The serving contract: observe() is called from concurrent
    handler threads while snapshot()/quantile() scrape — counts must be
    EXACT (a lost increment means an unlocked read-modify-write) and
    every mid-hammer snapshot internally consistent (+Inf bucket ==
    count; cumulative counts monotone). The CON501 lint pins the lock
    statically; this pins it dynamically."""
    import threading
    h = StreamingHistogram((0.1, 1.0, 10.0))
    n_threads, per_thread = 8, 2000
    start = threading.Barrier(n_threads + 1)
    inconsistent = []

    def writer(seed):
        start.wait()
        for i in range(per_thread):
            h.observe((seed + i) % 20)

    def scraper():
        start.wait()
        while h.count < n_threads * per_thread:
            snap = h.snapshot()
            cums = [c for _, c in snap['buckets']]
            if snap['buckets'][-1][1] != snap['count'] \
                    or cums != sorted(cums):
                inconsistent.append(snap)
                return

    threads = [threading.Thread(target=writer, args=(s,))
               for s in range(n_threads)]
    scr = threading.Thread(target=scraper)
    for t in threads + [scr]:
        t.start()
    for t in threads + [scr]:
        t.join(timeout=60)
    assert not inconsistent, f'torn snapshot: {inconsistent[0]}'
    assert h.count == n_threads * per_thread        # no lost increments
    snap = h.snapshot()
    assert snap['buckets'][-1][1] == n_threads * per_thread
    expect_sum = sum((s + i) % 20 for s in range(n_threads)
                     for i in range(per_thread))
    assert snap['sum'] == pytest.approx(expect_sum)


def test_histogram_is_the_con501_clean_control():
    """The concurrency lint tier stays SILENT on obs/live.py: the
    locked observe()/snapshot() above is the in-repo positive model
    CON501 cites in its fix text."""
    import dgmc_tpu.obs.live as live_mod
    from dgmc_tpu.analysis.con_rules import lint_concurrency_file
    findings = lint_concurrency_file(live_mod.__file__,
                                     rel='dgmc_tpu/obs/live.py')
    assert not any(f.rule in ('CON501', 'CON505') for f in findings), \
        [f.to_json() for f in findings]


def test_histogram_rejects_bad_bounds():
    with pytest.raises(ValueError):
        StreamingHistogram(())
    with pytest.raises(ValueError):
        StreamingHistogram((1.0, 1.0))
    with pytest.raises(ValueError):
        StreamingHistogram((1.0, 0.5))
    with pytest.raises(ValueError):
        StreamingHistogram((1.0, math.inf))


def test_histogram_matches_exact_percentile_on_same_series():
    """The O(1) histogram against observe.percentile on the identical
    series: every cumulative bucket count must equal the exact count of
    values <= the bound, and the histogram quantile (a bucket upper
    edge) must bracket the exact percentile from below-neighbor to
    itself — the resolution contract of fixed buckets."""
    rng = random.Random(7)
    values = [rng.lognormvariate(-2.0, 2.0) for _ in range(500)]
    h = StreamingHistogram()
    for v in values:
        h.observe(v)
    snap = h.snapshot()
    for bound, cum in snap['buckets'][:-1]:
        assert cum == sum(v <= bound for v in values), bound
    ordered = sorted(values)
    bounds = (0.0,) + DEFAULT_LATENCY_BOUNDS
    for q in (0.5, 0.9, 0.95, 0.99):
        exact = percentile(ordered, q)
        upper = h.quantile(q)
        assert exact <= upper
        lower = max((b for b in bounds if b < upper), default=0.0)
        # The exact percentile sits in the quantile's bucket (strictly
        # above its lower edge unless ties straddle the boundary).
        assert exact > lower or exact == upper


def test_histogram_count_equals_step_count():
    h = StreamingHistogram()
    for i in range(37):
        h.observe(0.01 * (i + 1))
    assert h.count == 37
    assert h.snapshot()['buckets'][-1][1] == 37


# ---------------------------------------------------------------------------
# Exposition rendering
# ---------------------------------------------------------------------------

def test_exposition_parses_and_escapes_labels():
    nasty = 'quo"te back\\slash new\nline'
    text = prometheus_exposition([
        ('dgmc_test_gauge', 'gauge', 'help with "quotes" and \\ stuff',
         [('', {'label': nasty, 'other': 'plain'}, 1.5)]),
        ('dgmc_test_total', 'counter', None, [('', {}, 7)]),
    ])
    fams = parse_exposition(text)
    g = fams['dgmc_test_gauge']
    assert g['type'] == 'gauge'
    (name, labels, value), = g['samples']
    assert name == 'dgmc_test_gauge'
    # Round trip: the strict parser recovers the original value.
    assert labels == {'label': nasty, 'other': 'plain'}
    assert value == 1.5
    assert fams['dgmc_test_total']['samples'] == [
        ('dgmc_test_total', {}, 7.0)]


def test_exposition_sanitizes_bad_metric_and_label_names():
    text = prometheus_exposition([
        ('bad-metric.name', 'gauge', None,
         [('', {'bad-label.name': 'v', '0numeric': 'w'}, 1)])])
    fams = parse_exposition(text)    # must not raise
    (name, labels, _), = fams['bad_metric_name']['samples']
    assert name == 'bad_metric_name'
    assert set(labels) == {'bad_label_name', '_0numeric'}


def test_exposition_histogram_family_shape():
    h = StreamingHistogram((0.5, 2.0))
    for v in (0.1, 1.0, 10.0):
        h.observe(v)
    text = prometheus_exposition(
        [histogram_family('dgmc_lat_seconds', 'latency', h.snapshot())])
    fams = parse_exposition(text)
    fam = fams['dgmc_lat_seconds']
    assert fam['type'] == 'histogram'
    buckets = [(labels['le'], v) for name, labels, v in fam['samples']
               if name.endswith('_bucket')]
    assert buckets == [('0.5', 1.0), ('2.0', 2.0), ('+Inf', 3.0)]
    by_name = {name: v for name, labels, v in fam['samples']
               if not name.endswith('_bucket')}
    assert by_name['dgmc_lat_seconds_count'] == 3.0
    assert by_name['dgmc_lat_seconds_sum'] == pytest.approx(11.1)
    # Cumulative counts are monotone and end at _count.
    vals = [v for _, v in buckets]
    assert vals == sorted(vals) and vals[-1] == 3.0


# ---------------------------------------------------------------------------
# FlightRecorder
# ---------------------------------------------------------------------------

def test_flight_ring_evicts_and_counts(tmp_path):
    path = str(tmp_path / 'flight.json')
    fr = FlightRecorder(path, capacity=8)
    for i in range(20):
        fr.record('step', step=i)
    assert fr.seen == 20
    assert fr.recorded == 8
    assert fr.truncated == 12
    out = fr.dump('test-anomaly', extra={'detail': 'x'})
    assert out == path
    payload = json.load(open(path))
    assert payload['reason'] == 'test-anomaly'
    assert payload['events_seen'] == 20
    assert payload['events_recorded'] == 8
    assert payload['events_truncated'] == 12
    assert payload['detail'] == 'x'
    # The ring kept the LAST events — trailing context, not leading.
    assert [e['step'] for e in payload['events']] == list(range(12, 20))


def test_flight_dump_sanitizes_nonfinite(tmp_path):
    fr = FlightRecorder(str(tmp_path / 'flight.json'))
    fr.record('probe', name='grad_norm', value=float('nan'))
    fr.record('probe', name='loss', value=float('inf'))
    payload = json.load(open(fr.dump('nan-probe')))   # strict parse
    assert payload['events'][0]['value'] is None
    assert payload['events'][1]['value'] is None


def test_flight_dump_without_path_is_noop():
    fr = FlightRecorder(None)
    fr.record('x')
    assert fr.dump('r') is None
    assert fr.dump_count == 0


# ---------------------------------------------------------------------------
# TelemetryServer + probe_healthz
# ---------------------------------------------------------------------------

def test_server_endpoints_and_health_codes():
    state = {'healthy': True, 'detail': 'fine'}
    srv = TelemetryServer(
        0, health_fn=lambda: dict(state),
        metrics_fn=lambda: prometheus_exposition(
            [('dgmc_up', 'gauge', None, [('', {}, 1)])]),
        status_fn=lambda: {'steps': 3}).start()
    try:
        code, payload = probe_healthz(srv.port)
        assert code == 200 and payload['healthy'] is True
        state['healthy'] = False
        code, payload = probe_healthz(srv.port)
        assert code == 503 and payload['healthy'] is False
        resp = urllib.request.urlopen(
            f'http://127.0.0.1:{srv.port}/metrics')
        assert resp.headers['Content-Type'].startswith(
            'text/plain; version=0.0.4')
        parse_exposition(resp.read().decode())
        status = json.loads(urllib.request.urlopen(
            f'http://127.0.0.1:{srv.port}/status').read())
        assert status == {'steps': 3}
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(f'http://127.0.0.1:{srv.port}/nope')
        assert err.value.code == 404
    finally:
        srv.close()
    assert probe_healthz(srv.port) is None


def test_server_callback_error_is_a_500_not_a_crash():
    def broken():
        raise RuntimeError('boom')
    srv = TelemetryServer(0, health_fn=broken,
                          status_fn=lambda: {'ok': 1}).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as err:
            urllib.request.urlopen(
                f'http://127.0.0.1:{srv.port}/healthz')
        assert err.value.code == 500
        # The server survives and keeps answering.
        status = json.loads(urllib.request.urlopen(
            f'http://127.0.0.1:{srv.port}/status').read())
        assert status == {'ok': 1}
    finally:
        srv.close()


# ---------------------------------------------------------------------------
# RunObserver integration (host-side only; no jitted code)
# ---------------------------------------------------------------------------

def _observer(tmp_path, **kw):
    from dgmc_tpu.obs.run import RunObserver
    return RunObserver(str(tmp_path / 'obs'), **kw)


def test_observer_serves_live_plane(tmp_path):
    obs = _observer(tmp_path, obs_port=0)
    try:
        assert obs.live_port
        for _ in range(3):
            with obs.step():
                time.sleep(0.002)
        obs.set_gauge('guard_skip_count', 2)
        obs.log(1, loss=0.25)
        code, hz = probe_healthz(obs.live_port)
        assert code == 200 and hz['healthy']
        assert hz['steps_completed'] == 3
        assert hz['gauges'] == {'guard_skip_count': 2}
        assert hz['flight']['events_seen'] >= 6   # 3 span pairs
        text = urllib.request.urlopen(
            f'http://127.0.0.1:{obs.live_port}/metrics').read().decode()
        fams = parse_exposition(text)
        assert fams['dgmc_steps_total']['samples'] == [
            ('dgmc_steps_total', {}, 3.0)]
        hist = fams['dgmc_step_latency_seconds']
        count = [v for n, _, v in hist['samples']
                 if n.endswith('_count')]
        assert count == [3.0]
        assert fams['dgmc_guard_skip_count']['samples'][0][2] == 2.0
        assert fams['dgmc_healthy']['samples'][0][2] == 1.0
        status = json.loads(urllib.request.urlopen(
            f'http://127.0.0.1:{obs.live_port}/status').read())
        assert status['steps']['steps'] == 3
        assert status['flight']['events_recorded'] >= 6
        port = obs.live_port
    finally:
        obs.close()
    # The plane dies with the observer.
    assert probe_healthz(port) is None


def test_healthz_goes_503_on_stalled_heartbeat_and_dumps_flight(
        tmp_path):
    """The acceptance transition: 200 while beating, 503 once the
    heartbeat is older than STALE_AFTER_FACTOR x deadline — and the
    deadline trip dumps flight.json whose trailing events are the
    run's last spans."""
    deadline = 0.2
    obs = _observer(tmp_path, obs_port=0,
                    watchdog_deadline_s=deadline, watchdog_signals=())
    try:
        with obs.step():
            time.sleep(0.002)
        code, hz = probe_healthz(obs.live_port)
        assert code == 200 and hz['healthy']
        assert hz['stale_after_s'] == pytest.approx(
            STALE_AFTER_FACTOR * deadline)
        # Stall: no beats. Wait past the stale bound (and the dump).
        deadline_hit = time.time() + 10.0
        while time.time() < deadline_hit:
            code, hz = probe_healthz(obs.live_port)
            if code == 503:
                break
            time.sleep(0.05)
        assert code == 503 and not hz['healthy'], hz
        flight_path = os.path.join(obs.dir, 'flight.json')
        for _ in range(100):          # the watchdog thread dumps async
            if os.path.exists(flight_path):
                break
            time.sleep(0.05)
        flight = json.load(open(flight_path))
        assert flight['reason'] == 'deadline'
        kinds = [e['kind'] for e in flight['events']]
        assert 'span-start' in kinds and 'span-end' in kinds
        assert os.path.exists(os.path.join(obs.dir, 'hang_report.json'))
    finally:
        obs.close()


def test_heartbeat_advertises_port_and_pid(tmp_path):
    obs = _observer(tmp_path, obs_port=0, watchdog_deadline_s=60.0,
                    watchdog_signals=())
    try:
        hb_path = os.path.join(obs.dir, 'heartbeat.json')
        for _ in range(100):
            if os.path.exists(hb_path):
                break
            time.sleep(0.02)
        hb = json.load(open(hb_path))
        assert hb['port'] == obs.live_port
        assert hb['pid'] == os.getpid()
        # The scrape address for peers on shared obs filesystems: a
        # remote aggregate/supervisor must not probe 127.0.0.1 and
        # find its own plane.
        assert hb['host']
    finally:
        obs.close()


def test_truncation_counters_reach_timings_and_trace(tmp_path,
                                                     monkeypatch):
    """Satellite: the bounded probe timeline and the flight ring must
    record how much they clipped — aggregates over a partial window
    are visibly partial."""
    import dgmc_tpu.obs.run as run_mod
    monkeypatch.setattr(run_mod, 'MAX_TRACE_PROBES', 4)
    obs = _observer(tmp_path)
    try:
        import collections
        obs._probe_records = collections.deque(maxlen=4)
        for i in range(10):
            obs._on_probe({'probe': 'corr_entropy', 'value': float(i),
                           'time': time.time()})
        t = obs.timings()
        assert t['probes_truncated'] == 6
        assert t['flight']['events_seen'] == 10
        obs.flush()
        trace = json.load(open(os.path.join(obs.dir, 'trace.json')))
        assert trace['otherData']['probes_truncated'] == 6
        timings = json.load(open(os.path.join(obs.dir,
                                              'timings.json')))
        assert timings['probes_truncated'] == 6
        assert timings['events_truncated'] == 0
    finally:
        obs.close()


def test_flight_records_dispatch_decisions(tmp_path):
    from dgmc_tpu.obs.registry import record_dispatch
    obs = _observer(tmp_path)
    try:
        record_dispatch('topk', 'fallback', 'backend=cpu')
        events = obs.flight.snapshot()
        assert {'kind': 'dispatch', 'kernel': 'topk',
                'outcome': 'fallback', 'reason': 'backend=cpu'} == {
                    k: v for k, v in events[-1].items() if k != 'time'}
    finally:
        obs.close()
    # Closed observer detaches its sink: no more flight growth.
    seen = obs.flight.seen
    record_dispatch('topk', 'fallback', 'backend=cpu')
    assert obs.flight.seen == seen


def test_flight_dump_on_observer_api(tmp_path):
    obs = _observer(tmp_path)
    try:
        with obs.step():
            pass
        path = obs.flight_dump('guard-rollback',
                               extra={'consec_bad': 3})
        payload = json.load(open(path))
        assert payload['reason'] == 'guard-rollback'
        assert payload['consec_bad'] == 3
    finally:
        obs.close()


def test_port_collision_moves_to_ephemeral(tmp_path):
    """Two processes handed the same fixed --obs-port must not die —
    and since the serve subsystem, the loser's plane MOVES to an
    ephemeral port instead of dropping (tests/obs/test_port_retry.py
    pins the heartbeat re-advertisement half of the story)."""
    a = _observer(tmp_path / 'a', obs_port=0)
    try:
        b = _observer(tmp_path / 'b', obs_port=a.live_port)
        try:
            assert b.live_port is not None
            assert b.live_port != a.live_port
            assert b.enabled
            with b.step():
                pass                      # still fully functional
        finally:
            b.close()
    finally:
        a.close()


def test_disabled_observer_flight_dump_is_noop():
    from dgmc_tpu.obs.run import RunObserver
    obs = RunObserver(None)
    assert obs.flight_dump('anything') is None
    obs.set_gauge('x', 1)    # no-op, no raise
    obs.close()


def test_rollback_guard_dumps_flight(tmp_path, monkeypatch):
    """The guard-rollback anomaly trigger: RollbackGuard reaches the
    observer's flight_dump hook (duck-typed) when it restores a
    snapshot."""
    from dgmc_tpu.resilience.guard import RollbackGuard

    class _State:
        step = 5

        def replace(self, **kw):
            return self

    import dgmc_tpu.train.checkpoint as ckpt
    monkeypatch.setattr(ckpt, 'snapshot_params', lambda s: {'p': 1})
    monkeypatch.setattr(ckpt, 'restore_params', lambda s, snap: s)
    obs = _observer(tmp_path)
    try:
        guard = RollbackGuard(2, obs=obs)
        state = _State()
        guard.note_good(state, step=3)
        _, rolled = guard.maybe_rollback(state, consec_bad=2, step=5)
        assert rolled
        flight = json.load(open(os.path.join(obs.dir, 'flight.json')))
        assert flight['reason'] == 'guard-rollback'
        assert flight['rollback_to'] == 3
    finally:
        obs.close()
