"""Chrome-trace export contract: valid trace-event JSON from host
telemetry, and the ``trace.json`` artifact of an observed run."""

import json

import jax
import numpy as np

from dgmc_tpu.obs import StepTimer, export_chrome_trace
from dgmc_tpu.obs.trace import chrome_events


def test_step_timer_records_spans():
    t = StepTimer()
    t.start()
    t.stop()
    t.start()
    t.stop()
    assert len(t.spans) == 2
    for (wall0, dur), rec in zip(t.spans, t.times):
        assert dur == rec and dur >= 0
        assert wall0 > 1e9  # epoch seconds, not perf_counter origin


def test_chrome_events_shape():
    base = 1_700_000_000.0
    evs = chrome_events(
        step_spans=[(base, 0.25), (base + 0.3, 0.2)],
        probe_records=[
            {'probe': 'corr_entropy', 'value': 3.5, 'time': base + 0.1,
             'stage': 'S0'},
            {'probe': 'grad_norm', 'value': 1.0, 'time': base + 0.2},
            {'probe': 'nonfinite', 'value': 0.0, 'time': base + 0.21,
             'stage': 'psi1'},
            {'probe': 'nonfinite', 'value': 1.0, 'time': base + 0.22,
             'stage': 'grad'},
        ],
        compile_events=[{'time': base + 0.05, 'duration_s': 0.04,
                         'kind': 'backend_compile', 'label': 'epoch1'}],
        sections=[('dense_f32', base, 0.5)])

    steps = [e for e in evs if e.get('cat') == 'step']
    assert [e['name'] for e in steps] == ['step 0', 'step 1']
    assert all(e['ph'] == 'X' and e['ts'] >= 0 and e['dur'] > 0
               for e in steps)
    counters = [e for e in evs if e['ph'] == 'C']
    assert {e['name'] for e in counters} == {'corr_entropy[S0]',
                                             'grad_norm'}
    # Only the FIRING nonfinite check becomes an instant.
    instants = [e for e in evs if e['ph'] == 'i']
    assert [e['name'] for e in instants] == ['nonfinite@grad']
    compiles = [e for e in evs if e.get('cat') == 'compile']
    assert compiles and compiles[0]['args']['label'] == 'epoch1'
    sections = [e for e in evs if e.get('cat') == 'section']
    assert sections and sections[0]['name'] == 'dense_f32'
    # ts are relative to the earliest event: none negative.
    assert min(e.get('ts', 0) for e in evs) >= 0


def test_chrome_events_empty():
    assert chrome_events() == []


def test_export_chrome_trace_file(tmp_path):
    path = str(tmp_path / 'trace.json')
    n = export_chrome_trace(path, step_spans=[(1e9, 0.1)],
                            metadata={'argv': ['x']})
    with open(path) as f:
        payload = json.load(f)
    assert len(payload['traceEvents']) == n
    assert payload['otherData'] == {'argv': ['x']}
    assert payload['displayTimeUnit'] == 'ms'


def test_run_observer_writes_trace_artifact(tmp_path):
    """An observed run leaves a loadable trace.json holding its steps
    and probe counters alongside the other artifacts."""
    from dgmc_tpu.obs import RunObserver

    obs = RunObserver(str(tmp_path / 'obs'), probes=True)
    with obs:
        from dgmc_tpu.obs import probes as probes_mod

        @jax.jit
        def f(x):
            probes_mod.emit('corr_entropy', x.sum(), stage='S0')
            return x * 2

        with obs.step():
            jax.block_until_ready(f(np.ones(4, np.float32)))
        obs.log(0, loss=1.0)
    with open(tmp_path / 'obs' / 'trace.json') as f:
        payload = json.load(f)
    cats = {e.get('cat') for e in payload['traceEvents']}
    assert 'step' in cats
    assert any(e['ph'] == 'C' and e['name'] == 'corr_entropy[S0]'
               for e in payload['traceEvents'])
    # Probe aggregates surfaced in timings.json for report/diff.
    with open(tmp_path / 'obs' / 'timings.json') as f:
        timings = json.load(f)
    assert timings['probes']['corr_entropy']['count'] == 1


def test_profile_span_noop_without_dir():
    from dgmc_tpu.obs import profile_span
    with profile_span(None):
        pass


# ---------------------------------------------------------------------------
# --profile-steps step-window profiling
# ---------------------------------------------------------------------------


def test_parse_step_window():
    import pytest

    from dgmc_tpu.obs.trace import parse_step_window

    assert parse_step_window('0:4') == (0, 4)
    assert parse_step_window(' 10:14 ') == (10, 14)
    for bad in ('4', '4:', ':4', 'a:b', '3:3', '5:2', '-1:4', '1:2:3'):
        with pytest.raises(ValueError):
            parse_step_window(bad)


def test_profile_steps_without_dir_warns_and_disarms(capsys):
    from dgmc_tpu.obs.trace import start_profile

    prof = start_profile(None, steps='0:2')
    assert '--profile-steps is ignored' in capsys.readouterr().err
    for _ in range(4):
        prof.on_step()       # must be a cheap no-op, never start jax
    assert not prof.active
    prof.close()


def test_windowed_profile_captures_only_the_window(tmp_path):
    """steps='1:3' captures step boundaries [1, 3): the span opens at
    boundary 1, closes at boundary 3, and the exported trace carries
    the per-step annotations the attribution CLI normalizes by."""
    from dgmc_tpu.obs.attribution import STEP_ANNOTATION
    from dgmc_tpu.obs.trace import start_profile
    from dgmc_tpu.obs.trace_events import (find_profiler_traces,
                                           read_trace_file)

    d = str(tmp_path / 'prof')
    f = jax.jit(lambda x: (x * x).sum())
    x = jax.numpy.ones((8, 8))
    float(f(x))                     # compile OUTSIDE any window
    prof = start_profile(d, steps='1:3')
    assert not prof.active          # windowed: nothing starts yet
    actives = []
    for _ in range(5):
        prof.on_step()
        actives.append(prof.active)
        with prof.step_annotation():
            float(f(x))
    prof.close()
    assert actives == [False, True, True, False, False]
    traces = find_profiler_traces(d)
    assert traces, 'windowed capture left no trace export'
    events = read_trace_file(traces[0])['traceEvents']
    steps = [e for e in events
             if e.get('ph') == 'X' and e.get('name') == STEP_ANNOTATION]
    nums = sorted(int(e['args']['step_num']) for e in steps)
    # Exactly the window's boundaries, numbered by the handle counter.
    assert nums == [1, 2], nums


def test_window_never_reached_records_nothing(tmp_path):
    from dgmc_tpu.obs.trace import start_profile
    from dgmc_tpu.obs.trace_events import find_profiler_traces

    d = str(tmp_path / 'prof')
    prof = start_profile(d, steps='10:12')
    for _ in range(3):
        prof.on_step()
    prof.close()                    # idempotent; nothing was started
    prof.close()
    assert find_profiler_traces(d) == []


def test_run_observer_drives_attached_profiler(tmp_path):
    """RunObserver.step() advances the profiler window and wraps the
    body in its annotation — including when the observer itself is
    DISABLED (profiling does not require --obs-dir)."""
    import contextlib

    from dgmc_tpu.obs import RunObserver

    class FakeProf:
        def __init__(self):
            self.boundaries = 0
            self.annotated = 0

        def on_step(self):
            self.boundaries += 1

        def step_annotation(self, step=None):
            self.annotated += 1
            return contextlib.nullcontext()

    for obs_dir in (None, str(tmp_path / 'obs')):
        obs = RunObserver(obs_dir)
        prof = obs.attach_profiler(FakeProf())
        for _ in range(3):
            with obs.step():
                pass
        obs.close()
        assert prof.boundaries == 3, obs_dir
        assert prof.annotated == 3, obs_dir


def test_profile_steps_rejected_at_argparse_time(capsys):
    """A typo'd window fails the CLI at PARSE time (usage message),
    not minutes later when start_profile runs after dataset load."""
    import argparse

    import pytest

    from dgmc_tpu.obs.trace import add_profile_flag

    parser = add_profile_flag(argparse.ArgumentParser())
    with pytest.raises(SystemExit):
        parser.parse_args(['--profile-steps', '5:2'])
    assert 'window [5, 2) is empty' in capsys.readouterr().err
    args = parser.parse_args(['--profile-steps', '2:5'])
    assert args.profile_steps == (2, 5)   # pre-parsed for start_profile
