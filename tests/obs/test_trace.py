"""Chrome-trace export contract: valid trace-event JSON from host
telemetry, and the ``trace.json`` artifact of an observed run."""

import json

import jax
import numpy as np

from dgmc_tpu.obs import StepTimer, export_chrome_trace
from dgmc_tpu.obs.trace import chrome_events


def test_step_timer_records_spans():
    t = StepTimer()
    t.start()
    t.stop()
    t.start()
    t.stop()
    assert len(t.spans) == 2
    for (wall0, dur), rec in zip(t.spans, t.times):
        assert dur == rec and dur >= 0
        assert wall0 > 1e9  # epoch seconds, not perf_counter origin


def test_chrome_events_shape():
    base = 1_700_000_000.0
    evs = chrome_events(
        step_spans=[(base, 0.25), (base + 0.3, 0.2)],
        probe_records=[
            {'probe': 'corr_entropy', 'value': 3.5, 'time': base + 0.1,
             'stage': 'S0'},
            {'probe': 'grad_norm', 'value': 1.0, 'time': base + 0.2},
            {'probe': 'nonfinite', 'value': 0.0, 'time': base + 0.21,
             'stage': 'psi1'},
            {'probe': 'nonfinite', 'value': 1.0, 'time': base + 0.22,
             'stage': 'grad'},
        ],
        compile_events=[{'time': base + 0.05, 'duration_s': 0.04,
                         'kind': 'backend_compile', 'label': 'epoch1'}],
        sections=[('dense_f32', base, 0.5)])

    steps = [e for e in evs if e.get('cat') == 'step']
    assert [e['name'] for e in steps] == ['step 0', 'step 1']
    assert all(e['ph'] == 'X' and e['ts'] >= 0 and e['dur'] > 0
               for e in steps)
    counters = [e for e in evs if e['ph'] == 'C']
    assert {e['name'] for e in counters} == {'corr_entropy[S0]',
                                             'grad_norm'}
    # Only the FIRING nonfinite check becomes an instant.
    instants = [e for e in evs if e['ph'] == 'i']
    assert [e['name'] for e in instants] == ['nonfinite@grad']
    compiles = [e for e in evs if e.get('cat') == 'compile']
    assert compiles and compiles[0]['args']['label'] == 'epoch1'
    sections = [e for e in evs if e.get('cat') == 'section']
    assert sections and sections[0]['name'] == 'dense_f32'
    # ts are relative to the earliest event: none negative.
    assert min(e.get('ts', 0) for e in evs) >= 0


def test_chrome_events_empty():
    assert chrome_events() == []


def test_export_chrome_trace_file(tmp_path):
    path = str(tmp_path / 'trace.json')
    n = export_chrome_trace(path, step_spans=[(1e9, 0.1)],
                            metadata={'argv': ['x']})
    with open(path) as f:
        payload = json.load(f)
    assert len(payload['traceEvents']) == n
    assert payload['otherData'] == {'argv': ['x']}
    assert payload['displayTimeUnit'] == 'ms'


def test_run_observer_writes_trace_artifact(tmp_path):
    """An observed run leaves a loadable trace.json holding its steps
    and probe counters alongside the other artifacts."""
    from dgmc_tpu.obs import RunObserver

    obs = RunObserver(str(tmp_path / 'obs'), probes=True)
    with obs:
        from dgmc_tpu.obs import probes as probes_mod

        @jax.jit
        def f(x):
            probes_mod.emit('corr_entropy', x.sum(), stage='S0')
            return x * 2

        with obs.step():
            jax.block_until_ready(f(np.ones(4, np.float32)))
        obs.log(0, loss=1.0)
    with open(tmp_path / 'obs' / 'trace.json') as f:
        payload = json.load(f)
    cats = {e.get('cat') for e in payload['traceEvents']}
    assert 'step' in cats
    assert any(e['ph'] == 'C' and e['name'] == 'corr_entropy[S0]'
               for e in payload['traceEvents'])
    # Probe aggregates surfaced in timings.json for report/diff.
    with open(tmp_path / 'obs' / 'timings.json') as f:
        timings = json.load(f)
    assert timings['probes']['corr_entropy']['count'] == 1


def test_profile_span_noop_without_dir():
    from dgmc_tpu.obs import profile_span
    with profile_span(None):
        pass
