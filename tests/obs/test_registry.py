"""Telemetry registry: counters/gauges, and kernel-dispatch outcomes
recorded by the real decision sites (fused vs CPU-forced fallback vs
GSPMD-silenced)."""

import jax.numpy as jnp
import numpy as np
import pytest

from dgmc_tpu.obs import REGISTRY, dispatch_table, record_dispatch
from dgmc_tpu.obs.registry import Registry
from dgmc_tpu.ops.pallas import dispatch
from dgmc_tpu.ops.topk import chunked_topk


@pytest.fixture(autouse=True)
def clean_registry():
    REGISTRY.reset()
    yield
    REGISTRY.reset()


def test_counter_labels_and_totals():
    r = Registry()
    r.inc('x', kernel='a')
    r.inc('x', 2, kernel='a')
    r.inc('x', kernel='b')
    assert r.counter_value('x', kernel='a') == 3
    assert r.total('x') == 4
    r.gauge('g', 7.5, dev=0)
    snap = r.snapshot()
    assert {'name': 'g', 'labels': {'dev': 0}, 'value': 7.5} in snap['gauges']
    r.reset()
    assert r.snapshot() == {'counters': [], 'gauges': []}


def test_dispatch_fallback_recorded_on_cpu_auto():
    """The un-jitted auto gate in chunked_topk must record an XLA-fallback
    decision on the CPU backend (reason names the backend)."""
    h_s = jnp.asarray(np.random.RandomState(0).randn(1, 8, 4),
                      jnp.float32)
    h_t = jnp.asarray(np.random.RandomState(1).randn(1, 10, 4),
                      jnp.float32)
    chunked_topk(h_s, h_t, 3)
    rows = dispatch_table()
    assert rows == [{'kernel': 'topk', 'outcome': 'fallback',
                     'reason': 'backend=cpu', 'count': 1}]


def test_dispatch_pallas_recorded_when_gate_passes(monkeypatch):
    """When the auto gate resolves to the fused kernel, a pallas-taken
    outcome is recorded (backend faked — no kernel actually runs)."""
    monkeypatch.setattr(dispatch.jax, 'default_backend', lambda: 'tpu')
    assert dispatch.auto_fused('dense_consensus') is True
    assert REGISTRY.counter_value(
        'pallas_dispatch', kernel='dense_consensus', outcome='pallas',
        reason='auto-tpu') == 1


def test_dispatch_gspmd_silenced_recorded(monkeypatch):
    monkeypatch.setattr(dispatch.jax, 'default_backend', lambda: 'tpu')
    with dispatch.disable_fused_kernels():
        assert dispatch.auto_fused('topk') is False
    assert REGISTRY.counter_value(
        'pallas_dispatch', kernel='topk', outcome='fallback',
        reason='gspmd-silenced') == 1


def test_dispatch_size_gate_recorded(monkeypatch):
    monkeypatch.setattr(dispatch.jax, 'default_backend', lambda: 'tpu')
    assert dispatch.auto_fused('spline_route', size_ok=False,
                               size_reason='vmem') is False
    assert REGISTRY.counter_value(
        'pallas_dispatch', kernel='spline_route', outcome='fallback',
        reason='vmem') == 1


def test_explicit_false_recorded():
    h = jnp.ones((1, 4, 2))
    chunked_topk(h, h, 2, pallas=False)
    assert REGISTRY.counter_value(
        'pallas_dispatch', kernel='topk', outcome='fallback',
        reason='explicit') == 1


def test_sparse_model_trace_records_both_stages():
    """Tracing the sparse matcher on CPU records the top-k fallback AND
    the sparse-consensus default-off fallback in one table."""
    import jax
    from dgmc_tpu.models import DGMC, RelCNN
    from dgmc_tpu.ops.graph import GraphBatch

    rng = np.random.RandomState(0)

    def side(n, e):
        return GraphBatch(
            x=rng.randn(1, n, 4).astype(np.float32),
            senders=rng.randint(0, n, (1, e)).astype(np.int32),
            receivers=rng.randint(0, n, (1, e)).astype(np.int32),
            node_mask=np.ones((1, n), bool),
            edge_mask=np.ones((1, e), bool), edge_attr=None)

    model = DGMC(RelCNN(4, 8, num_layers=1), RelCNN(4, 4, num_layers=1),
                 num_steps=1, k=2)
    s, t = side(6, 12), side(8, 16)
    model.init({'params': jax.random.key(0), 'noise': jax.random.key(1)},
               s, t)
    kernels = {r['kernel']: r for r in dispatch_table()}
    assert kernels['topk']['outcome'] == 'fallback'
    assert kernels['sparse_consensus']['reason'] == 'default-off'


def test_padding_bucket_counter():
    """Every pad_pair_batch collation records its padding bucket, so
    recompile churn from unstable padding is visible next to the
    compile-event counter."""
    from dgmc_tpu.obs.registry import padding_bucket_table
    from dgmc_tpu.utils.data import Graph, GraphPair, pad_pair_batch

    g = Graph(edge_index=np.zeros((2, 0), np.int64),
              x=np.zeros((3, 2), np.float32))
    pad_pair_batch([GraphPair(s=g, t=g)], 4, 8)
    pad_pair_batch([GraphPair(s=g, t=g)], 4, 8)
    pad_pair_batch([GraphPair(s=g, t=g)], 6, 8)   # a second bucket
    rows = padding_bucket_table()
    assert len(rows) == 2
    assert rows[0]['count'] == 2 and rows[0]['nodes'] == '4x4'


def test_record_dispatch_direct():
    record_dispatch('k', 'pallas', 'explicit')
    record_dispatch('k', 'pallas', 'explicit')
    assert dispatch_table() == [{'kernel': 'k', 'outcome': 'pallas',
                                 'reason': 'explicit', 'count': 2}]
