"""Capacity model (obs.capacity): golden queueing math (saturation QPS,
Little's-law utilization, M/M/1 wait), the ramp knee, bench-seeded
batching headroom, histogram reduction, the live `/status` summary with
its qtrace reconciliation, and the artifact-side CLI."""

import json
import math
import os

import pytest

from dgmc_tpu.obs import capacity


def test_saturation_qps_is_inverse_mean_service():
    assert capacity.saturation_qps(0.05) == pytest.approx(20.0)
    assert capacity.saturation_qps(0) is None
    assert capacity.saturation_qps(None) is None


def test_utilization_littles_law_and_overload():
    assert capacity.utilization(10.0, 0.05) == pytest.approx(0.5)
    # ρ > 1 is the saturation signal, not an error.
    assert capacity.utilization(30.0, 0.05) == pytest.approx(1.5)
    assert capacity.utilization(None, 0.05) is None
    assert capacity.utilization(10.0, 0) is None


def test_mm1_wait_golden_and_unstable():
    # ρ = 0.5 → wait = 0.5/0.5 × 50 ms = 50 ms.
    assert capacity.mm1_wait_s(10.0, 0.05) == pytest.approx(0.05)
    # ρ = 0.8 → 0.8/0.2 × 50 ms = 200 ms.
    assert capacity.mm1_wait_s(16.0, 0.05) == pytest.approx(0.2)
    # At or past saturation an unstable queue has no stationary wait.
    assert capacity.mm1_wait_s(20.0, 0.05) is None
    assert capacity.mm1_wait_s(25.0, 0.05) is None


def test_hist_mean_and_quantile_upper_bound_convention():
    snap = {'count': 10, 'sum': 0.5,
            'buckets': [(0.01, 2), (0.05, 8), (0.1, 10),
                        (math.inf, 10)]}
    assert capacity.hist_mean_s(snap) == pytest.approx(0.05)
    # rank 5 lands in the ≤0.05 bucket (cum 8 ≥ 5).
    assert capacity.hist_quantile_s(snap, 0.50) == 0.05
    assert capacity.hist_quantile_s(snap, 0.95) == 0.1
    # A quantile landing in the +inf overflow bucket reports the last
    # finite bound, never infinity.
    overflow = {'count': 4, 'sum': 1.0,
                'buckets': [(0.1, 1), (math.inf, 4)]}
    assert capacity.hist_quantile_s(overflow, 0.99) == 0.1
    assert capacity.hist_mean_s({'count': 0, 'sum': 0.0}) is None
    assert capacity.hist_quantile_s(None, 0.5) is None


def test_knee_of_finds_last_scaling_level():
    ramp = [{'clients': 1, 'qps': 10.0}, {'clients': 2, 'qps': 19.0},
            {'clients': 4, 'qps': 20.0}, {'clients': 8, 'qps': 21.0}]
    knee = capacity.knee_of(ramp)
    # 1→2 nearly doubled (keeps scaling); 2→4 gained only ~5% < 10%.
    assert knee == {'clients': 2, 'qps': 19.0, 'saturated': True,
                    'min_gain': 0.10}


def test_knee_of_unsaturated_ramp_and_order_independence():
    # Still doubling at the top level: the knee lies beyond the range.
    ramp = [{'clients': 4, 'qps': 40.0}, {'clients': 1, 'qps': 10.0},
            {'clients': 2, 'qps': 20.0}]
    knee = capacity.knee_of(ramp)
    assert knee['clients'] == 4
    assert knee['saturated'] is False
    assert capacity.knee_of([]) is None


def test_batching_headroom_projection_and_recommendation():
    # str keys (JSON round-trip) must be accepted.
    hr = capacity.batching_headroom({'1': 100.0, '2': 60.0, '4': 40.0},
                                    target_qps=15.0)
    assert hr['projected_qps_per_batch'] == {'1': 10.0, '2': 16.667,
                                             '4': 25.0}
    assert hr['best_batch'] == 4
    assert hr['best_qps'] == 25.0
    # Smallest batch that clears the target.
    assert hr['recommended_batch'] == 2
    # Out-of-reach target: None, honesty over hope.
    assert capacity.batching_headroom(
        {'1': 100.0}, target_qps=99.0)['recommended_batch'] is None
    assert capacity.batching_headroom({}) is None
    assert capacity.batching_headroom({'1': 0.0}) is None


def _cap_stats():
    hold = {'count': 10, 'sum': 0.5,
            'buckets': [(0.05, 8), (0.1, 10), (math.inf, 10)]}
    wait = {'count': 10, 'sum': 1.0,
            'buckets': [(0.1, 5), (0.2, 10), (math.inf, 10)]}
    return {'inflight': 1, 'queries': 11, 'window_s': 2.0,
            'lock_hold': hold, 'lock_wait': wait,
            'pad_fraction': 0.125, 'goodput_ratio': 0.875,
            'buckets': {'8x16': {'queries': 11}}}


def test_live_summary_golden_queueing_model():
    out = capacity.live_summary(_cap_stats())
    # arrival = (11 − 1) queries / 2 s window.
    assert out['arrival_qps'] == 5.0
    # E[service] from the lock-HOLD histogram: 0.5 s / 10 = 50 ms.
    assert out['mean_service_ms'] == 50.0
    assert out['saturation_qps'] == 20.0
    # ρ = 5 × 0.05; projected wait = 0.25/0.75 × 50 ms.
    assert out['utilization'] == 0.25
    assert out['projected_wait_ms'] == pytest.approx(16.6667)
    assert out['lock_hold_ms']['p50_ms'] == 50.0
    assert out['lock_wait_ms']['p95_ms'] == 200.0
    assert out['pad_fraction'] == 0.125
    assert out['goodput_ratio'] == 0.875
    # No qtrace summary → no reconciliation block (absence is honest).
    assert 'admission_reconciliation' not in out


def test_live_summary_reconciles_lock_wait_against_qtrace():
    qtrace = {'stages': {'admission_queue_wait':
                         {'count': 7, 'p95_ms': 180.0}}}
    out = capacity.live_summary(_cap_stats(), qtrace)
    rec = out['admission_reconciliation']
    assert rec['qtrace_count'] == 7
    assert rec['qtrace_p95_ms'] == 180.0
    # Engine histogram counts ALL queries, not just traced ones.
    assert rec['engine_count'] == 10
    assert rec['engine_p95_ms'] == 200.0


def _round_json(tmp_path):
    record = {
        'ramp': {'levels': [{'clients': 1, 'qps': 10.0,
                             'p50_ms': 90.0, 'p95_ms': 100.0},
                            {'clients': 2, 'qps': 10.5,
                             'p50_ms': 170.0, 'p95_ms': 200.0}]},
        'capacity': {'saturation_qps': 12.0, 'utilization': 0.9},
        'goodput': {'serve': {'goodput_ratio': 0.97}},
        'result': {'sparse_dbp15k': {'pairs_sweep': {
            '1': {'step_ms_per_pair': 100.0},
            '4': {'step_ms_per_pair': 40.0}}}},
    }
    path = os.path.join(tmp_path, 'SERVE_r99.json')
    with open(path, 'w') as f:
        json.dump(record, f)
    return path


def test_analyze_paths_round_json(tmp_path):
    tmp_path = str(tmp_path)
    report = capacity.analyze_paths([_round_json(tmp_path)],
                                    target_qps=20.0)
    assert report['ramp']['knee']['clients'] == 1
    assert report['ramp']['knee']['saturated'] is True
    assert report['serve_capacity']['saturation_qps'] == 12.0
    hr = report['batching_headroom']
    assert hr['projected_qps_per_batch'] == {'1': 10.0, '4': 25.0}
    assert hr['recommended_batch'] == 4
    text = capacity.render(report)
    assert 'knee: 1 clients @ 10.0 QPS' in text
    assert 'batching headroom' in text


def test_analyze_paths_obs_dir(tmp_path):
    tmp_path = str(tmp_path)
    with open(os.path.join(tmp_path, 'qtrace_summary.json'), 'w') as f:
        json.dump({'end_to_end': {'count': 4, 'sum_ms': 200.0}}, f)
    with open(os.path.join(tmp_path, 'goodput.json'), 'w') as f:
        json.dump({'goodput_ratio': 0.9, 'pad_fraction_max': 0.2}, f)
    report = capacity.analyze_paths([tmp_path])
    # mean 50 ms over 4 queries → ceiling 20 QPS.
    assert report['service_time']['mean_ms'] == 50.0
    assert report['service_time']['saturation_qps'] == 20.0
    assert report['goodput']['goodput_ratio'] == 0.9
    assert 'saturation QPS   20.0' in capacity.render(report)


def test_main_cli(tmp_path, capsys):
    tmp_path = str(tmp_path)
    assert capacity.main([os.path.join(tmp_path, 'missing.json')]) == 2
    path = _round_json(tmp_path)
    assert capacity.main([path, '--json']) == 0
    report = json.loads(capsys.readouterr().out)
    assert report['ramp']['knee']['clients'] == 1
    assert capacity.main([path]) == 0
    assert '== capacity model ==' in capsys.readouterr().out


def test_capacity_module_is_jax_free():
    import dgmc_tpu.obs.capacity as mod
    assert 'import jax' not in open(mod.__file__).read()
