"""Cost & efficiency attribution contract (obs/cost.py).

Pins: per-stage FLOP/byte attribution through the named_scope spans of a
REAL lowered train step (including the ``loss``/``optimizer`` scopes
train/steps.py adds), collective accounting on a genuinely sharded
compiled executable, the MFU arithmetic against the peak table (CPU
fallback included), and the specimen-table CLI.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dgmc_tpu.obs import cost
from dgmc_tpu.models import DGMC, RelCNN
from dgmc_tpu.ops.graph import GraphBatch
from dgmc_tpu.train import create_train_state, make_train_step
from dgmc_tpu.utils.data import PairBatch


def _side(rng, n, e, c=4):
    return GraphBatch(
        x=rng.randn(1, n, c).astype(np.float32),
        senders=rng.randint(0, n, (1, e)).astype(np.int32),
        receivers=rng.randint(0, n, (1, e)).astype(np.int32),
        node_mask=np.ones((1, n), bool),
        edge_mask=np.ones((1, e), bool),
        edge_attr=None)


@pytest.fixture(scope='module')
def train_step_summary():
    rng = np.random.RandomState(0)
    batch = PairBatch(s=_side(rng, 8, 16), t=_side(rng, 10, 20),
                      y=(np.arange(8, dtype=np.int32) % 10)[None],
                      y_mask=np.ones((1, 8), bool))
    model = DGMC(RelCNN(4, 8, num_layers=1), RelCNN(4, 4, num_layers=1),
                 num_steps=2, k=3)
    state = create_train_state(model, jax.random.key(0), batch,
                               learning_rate=1e-3)
    step = make_train_step(model)
    return cost.cost_summary(step, state, batch, jax.random.key(1))


def test_train_step_totals(train_step_summary):
    s = train_step_summary
    assert s['source'] == 'lowered'
    assert s['flops'] > 0
    assert s['bytes'] > 0
    assert s['arith_intensity'] > 0


def test_train_step_stage_attribution(train_step_summary):
    """Every pipeline stage of the sparse train step — the model scopes
    AND steps.py's loss/optimizer scopes — appears with sane numbers;
    the MXU stages carry dot FLOPs."""
    stages = train_step_summary['stages']
    for stage in ('psi1', 'initial_corr', 'topk', 'consensus_iter',
                  'psi2', 'loss', 'optimizer'):
        assert stage in stages, f'missing stage {stage!r}'
        assert stages[stage]['ops'] > 0
        assert stages[stage]['bytes_out'] > 0
    for mxu_stage in ('psi1', 'initial_corr', 'consensus_iter', 'psi2'):
        assert stages[mxu_stage]['flops'] > 0, mxu_stage
        assert stages[mxu_stage]['dot_ops'] > 0, mxu_stage
    # Analytic dot FLOPs must stay below XLA's total op count estimate.
    total_stage_flops = sum(r['flops'] for r in stages.values())
    assert 0 < total_stage_flops <= train_step_summary['flops'] * 1.5


def test_stage_of_prefers_innermost_scope():
    assert cost.stage_of('jit(f)/jit(main)/consensus_iter/psi2/dot') \
        == 'psi2'
    assert cost.stage_of('jit(f)/jit(main)/consensus_iter/add') \
        == 'consensus_iter'
    assert cost.stage_of('jit(f)/transpose(jvp(psi1))/dot') == 'psi1'
    assert cost.stage_of('jit(f)/jit(main)/reduce_sum') == 'other'


def test_dot_flops_parses_stablehlo_line():
    line = ('%0 = stablehlo.dot_general %arg0, %arg1, '
            'contracting_dims = [1] x [0], '
            'precision = [DEFAULT, DEFAULT] : '
            '(tensor<8x16xf32>, tensor<16x4xf32>) -> tensor<8x4xf32> '
            'loc(#loc11)')
    assert cost._dot_flops(line) == 2 * 8 * 4 * 16


def test_collective_table_hlo_text():
    txt = ('ROOT %all-reduce = f32[128,4]{1,0} all-reduce(f32[128,4]{1,0} '
           '%fusion), channel_id=1\n'
           '%ag = f32[256]{0} all-gather(f32[32]{0} %x), channel_id=2\n'
           '%noise = f32[2]{0} add(f32[2]{0} %a, f32[2]{0} %b)\n')
    t = cost.collective_table(txt)
    assert t['ops']['all-reduce'] == {'count': 1, 'bytes': 128 * 4 * 4}
    assert t['ops']['all-gather'] == {'count': 1, 'bytes': 256 * 4}
    assert t['count'] == 2


def test_collective_table_async_start_done_pairs():
    """Real TPU executables overlap collectives with compute via the
    async -start/-done spelling; each pair counts ONCE."""
    txt = ('%ars = f32[1024]{0} all-reduce-start(f32[1024]{0} %g), '
           'channel_id=1\n'
           '%ard = f32[1024]{0} all-reduce-done(f32[1024]{0} %ars)\n'
           '%ags = f32[512]{0} all-gather-start(f32[64]{0} %x)\n'
           '%agd = f32[512]{0} all-gather-done(f32[512]{0} %ags)\n')
    t = cost.collective_table(txt)
    assert t['ops']['all-reduce'] == {'count': 1, 'bytes': 1024 * 4}
    assert t['ops']['all-gather'] == {'count': 1, 'bytes': 512 * 4}
    assert t['count'] == 2


def test_collectives_of_sharded_compiled_executable():
    """A data-parallel reduction compiled over the 8 virtual devices
    must report its all-reduce (the real GSPMD path, not fixture
    text)."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip('needs >= 2 devices')
    mesh = Mesh(np.array(devs), ('data',))
    f = jax.jit(lambda x: jnp.sum(x * 2.0))
    x = jax.device_put(np.random.randn(len(devs) * 2, 4).astype(np.float32),
                       NamedSharding(mesh, P('data')))
    compiled = f.lower(x).compile()
    s = cost.cost_summary(compiled)
    assert s['source'] == 'compiled'
    assert s['collectives']['ops'].get('all-reduce', {}).get('count', 0) >= 1
    assert s['collectives']['bytes'] >= 4


def test_compiled_summary_carries_schedule_and_liveness_fields():
    """The compiled view publishes the schedule/liveness account —
    overlap_fraction (program moves bytes), critical_path_share, and
    the static peak-live bound — the same models the SCH/MEM lint tier
    gates on, so efficiency.json and the lint cannot disagree."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    devs = jax.devices()
    if len(devs) < 2:
        pytest.skip('needs >= 2 devices')
    mesh = Mesh(np.array(devs), ('data',))
    f = jax.jit(lambda x: jnp.sum(x * 2.0))
    x = jax.device_put(np.random.randn(len(devs) * 2, 4).astype(np.float32),
                       NamedSharding(mesh, P('data')))
    s = cost.cost_summary(f.lower(x).compile())
    assert 0.0 <= s['overlap_fraction'] <= 1.0
    assert 0.0 < s['critical_path_share'] <= 1.0
    assert s['static_peak_bytes'] > 0
    # A single-device program has no collectives: the overlap field is
    # omitted, never fabricated; the liveness bound still reports.
    g = jax.jit(lambda y: jnp.sum(y * 2.0))
    y = np.random.randn(4, 4).astype(np.float32)
    s1 = cost.cost_summary(g.lower(y).compile())
    assert 'overlap_fraction' not in s1
    assert s1['static_peak_bytes'] > 0


def test_peak_flops_entries():
    class Dev:
        def __init__(self, kind, platform):
            self.device_kind = kind
            self.platform = platform

    tpu = cost.peak_flops_entry(Dev('TPU v4', 'tpu'))
    assert tpu == {'peak_flops': 275e12, 'ref': 'TPU v4 bf16',
                   'source': 'table'}
    cpu = cost.peak_flops_entry(Dev('cpu', 'cpu'))
    assert cpu['source'] == 'cpu-fallback'
    assert cpu['peak_flops'] == cost.CPU_PEAK_FLOPS
    unknown = cost.peak_flops_entry(Dev('QPU v1', 'qpu'))
    assert unknown['peak_flops'] is None
    assert unknown['source'] == 'unknown'


def test_efficiency_payload_mfu_math():
    programs = {
        'train_step': {'flops': 1e9, 'bytes': 1e8},
        'timed': {'flops': 2e9, 'bytes': 1e8, 'step_time_s': 0.5},
    }

    class Dev:
        device_kind = 'TPU v4'
        platform = 'tpu'

    p = cost.efficiency_payload(programs, fallback_step_time_s=0.1,
                                device=Dev())
    ts = p['programs']['train_step']
    assert ts['step_time_source'] == 'observed_p50'
    assert ts['mfu'] == pytest.approx(1e9 / (0.1 * 275e12), rel=1e-3)
    timed = p['programs']['timed']
    assert 'step_time_source' not in timed          # its own measurement
    assert timed['mfu'] == pytest.approx(2e9 / (0.5 * 275e12), rel=1e-3)
    assert p['mfu'] == ts['mfu']                    # headline: train_step
    assert p['peak_flops_source'] == 'table'


def test_efficiency_payload_unknown_peak_omits_mfu():
    class Dev:
        device_kind = 'QPU v1'
        platform = 'qpu'

    p = cost.efficiency_payload({'train_step': {'flops': 1e9}},
                                fallback_step_time_s=0.1, device=Dev())
    assert 'mfu' not in p['programs']['train_step']
    assert 'mfu' not in p


def test_specimen_cli_json(tmp_path, capsys):
    """The specimen mode compiles a registered hot op and reports its
    Compiled.cost_analysis totals; --obs-dir merges into
    efficiency.json without clobbering run rows."""
    d = str(tmp_path / 'obs')
    import os
    os.makedirs(d)
    # The existing artifact was recorded on ANOTHER machine (a TPU):
    # its rows, device identity and headline MFU must survive a merge
    # on this CPU box verbatim — re-deriving them against the local
    # peak table would corrupt them.
    with open(os.path.join(d, 'efficiency.json'), 'w') as f:
        json.dump({'device_kind': 'TPU v4', 'platform': 'tpu',
                   'peak_flops': 275e12, 'peak_flops_source': 'table',
                   'programs': {'train_step': {'flops': 7.0,
                                               'step_time_s': 1e-9,
                                               'mfu': 0.5}},
                   'mfu': 0.5}, f)
    assert cost.main(['--specimens', 'ops.masked_softmax',
                      '--obs-dir', d, '--json']) == 0
    payload = json.loads(capsys.readouterr().out)
    progs = payload['programs']
    assert progs['specimen.ops.masked_softmax']['flops'] > 0
    assert progs['specimen.ops.masked_softmax']['source'] == 'compiled'
    # Run rows, headline and device identity preserved VERBATIM — not
    # recomputed against this (CPU) machine's peak table.
    assert progs['train_step'] == {'flops': 7.0, 'step_time_s': 1e-9,
                                   'mfu': 0.5}
    assert payload['mfu'] == 0.5
    assert payload['device_kind'] == 'TPU v4'
    assert payload['peak_flops'] == 275e12
    on_disk = json.load(open(os.path.join(d, 'efficiency.json')))
    assert 'specimen.ops.masked_softmax' in on_disk['programs']


def test_specimen_cli_unknown_name(capsys):
    assert cost.main(['--specimens', 'nope.nothing', '--json']) == 2
