"""Measured-runtime attribution contract.

Golden trace-event fixtures pin the parser grammar the way SHD/SCH
rules pin golden HLO: a device+host capture (plain and gzipped), an
empty device track, truncated/corrupt JSON, and overlapping async
slices — each exercised through attribution with EXACT expected stage
tables. Plus a strict schema pin on ``attribution.json`` in the style
of ``test_live.py``'s Prometheus line-grammar parser: every key at
every level is enumerated, so a field can neither vanish nor appear
without this test noticing.

Everything here is jax-free (the modules under test must run on a box
that only has the artifacts).
"""

import gzip
import json
import os

import pytest

from dgmc_tpu.obs import attribution as attr_mod
from dgmc_tpu.obs import trace_events as te

# ---------------------------------------------------------------------------
# Fixture builders (times in trace microseconds)
# ---------------------------------------------------------------------------


def _x(pid, tid, ts, dur, name, args=None):
    e = {'ph': 'X', 'pid': pid, 'tid': tid, 'ts': ts, 'dur': dur,
         'name': name}
    if args:
        e['args'] = args
    return e


def _meta(pid, name, tid=None):
    e = {'ph': 'M', 'pid': pid,
         'name': 'thread_name' if tid is not None else 'process_name',
         'args': {'name': name}}
    if tid is not None:
        e['tid'] = tid
    return e


def device_host_events():
    """The canonical device+host capture:

    device ``/device:TPU:0`` (XLA Ops):
      psi1 compute        [   0, 1000)
      consensus compute   [1500, 2500)
      all-reduce comm     [2000, 3000)   (overlaps compute by 500us)
    host ``/host:CPU`` (python):
      run span            [   0, 4000)
      block_until_ready   [3000, 3500)
      dgmc_step x2        [   0, 2000), [2000, 4000)
    """
    scope = 'jit(train_step)/jit(main)/'
    return [
        _meta(1, '/device:TPU:0'),
        _meta(1, 'XLA Ops', tid=1),
        _meta(2, '/host:CPU'),
        _meta(2, 'python', tid=1),
        _x(1, 1, 0, 1000, 'fusion.1',
           {'long_name': scope + 'psi1/dot_general'}),
        _x(1, 1, 1500, 1000, 'fusion.2',
           {'long_name': scope + 'consensus_iter/add'}),
        _x(1, 1, 2000, 1000, 'all-reduce.3',
           {'hlo_category': 'collective communication'}),
        _x(2, 1, 0, 4000, '$train.py:10 run'),
        _x(2, 1, 3000, 500, '$array.py:50 block_until_ready'),
        _x(2, 1, 0, 2000, attr_mod.STEP_ANNOTATION,
           {'step_num': '0'}),
        _x(2, 1, 2000, 2000, attr_mod.STEP_ANNOTATION,
           {'step_num': '1'}),
    ]


#: The exact stage table device_host_events() must attribute to —
#: the golden pin for the grammar (scope path in args.long_name, the
#: comm op without a stage scope lands in 'other').
GOLDEN_STAGES = {
    'psi1': {'wall_s': 0.001, 'events': 1, 'share': 0.3333},
    'consensus_iter': {'wall_s': 0.001, 'events': 1, 'share': 0.3333},
    'other': {'wall_s': 0.001, 'events': 1, 'share': 0.3333},
}


def write_trace(tmp_path, events, name='host0.trace.json', gz=False,
                session='2026_01_01_00_00_00'):
    """Write a trace-event payload into the profiler's directory
    layout (``<root>/plugins/profile/<session>/``)."""
    d = os.path.join(str(tmp_path), 'plugins', 'profile', session)
    os.makedirs(d, exist_ok=True)
    payload = json.dumps({'traceEvents': events,
                          'displayTimeUnit': 'ms'}).encode()
    path = os.path.join(d, name + ('.gz' if gz else ''))
    if gz:
        with gzip.open(path, 'wb') as f:
            f.write(payload)
    else:
        with open(path, 'wb') as f:
            f.write(payload)
    return path


# ---------------------------------------------------------------------------
# Interval algebra
# ---------------------------------------------------------------------------


def test_merge_and_intersect_intervals():
    merged = te.merge_intervals([(0, 10), (5, 15), (20, 30), (30, 31),
                                 (2, 3)])
    assert merged == [(0, 15), (20, 31)]
    assert te.sum_intervals(merged) == 26
    other = te.merge_intervals([(12, 22), (25, 40)])
    inter = te.intersect_intervals(merged, other)
    assert inter == [(12, 15), (20, 22), (25, 31)]
    assert te.sum_intervals(inter) == 11
    assert te.merge_intervals([]) == []
    assert te.intersect_intervals([], merged) == []


# ---------------------------------------------------------------------------
# Golden fixtures through attribution
# ---------------------------------------------------------------------------


def test_device_host_golden_stage_table(tmp_path):
    write_trace(tmp_path, device_host_events())
    payload, _ = attr_mod.build_attribution(str(tmp_path))
    assert payload['device_available'] is True
    assert payload['stage_source'] == 'device'
    assert payload['stages'] == GOLDEN_STAGES
    occ = payload['occupancy']
    assert occ['window_s'] == 0.004
    assert occ['device_active_s'] == 0.0025
    assert occ['device_idle_s'] == 0.0015
    assert occ['device_idle_fraction'] == 0.375
    assert occ['compute_busy_s'] == 0.002
    assert occ['comm_busy_s'] == 0.001
    assert occ['overlapped_s'] == 0.0005
    assert occ['measured_overlap_fraction'] == 0.5
    assert occ['host_busy_s'] == 0.004
    assert occ['host_wait_s'] == 0.0005
    assert occ['idle_fraction'] == 0.375
    assert occ['idle_source'] == 'device'
    assert payload['steps'] == {'observed': 2, 'wall_s': 0.004,
                                'mean_s': 0.002}
    assert payload['per_step'] == {'device_active_s': 0.00125,
                                   'steps': 2}
    assert payload['unavailable'] == []
    assert payload['errors'] == []


def test_gzipped_trace_is_identical(tmp_path):
    plain = tmp_path / 'plain'
    zipped = tmp_path / 'zipped'
    write_trace(plain, device_host_events())
    write_trace(zipped, device_host_events(), gz=True)
    a, _ = attr_mod.build_attribution(str(plain))
    b, _ = attr_mod.build_attribution(str(zipped))
    assert a['stages'] == b['stages'] == GOLDEN_STAGES
    assert a['occupancy'] == b['occupancy']


def test_empty_device_track_degrades_to_host(tmp_path):
    """A device PROCESS with no slices is not a device measurement:
    the account degrades to host-track attribution with every device
    field unavailable — never fabricated zeros."""
    events = [e for e in device_host_events()
              if not (e.get('ph') == 'X' and e.get('pid') == 1)]
    write_trace(tmp_path, events)
    payload, _ = attr_mod.build_attribution(str(tmp_path))
    assert payload['device_available'] is False
    assert payload['stage_source'] == 'host'
    occ = payload['occupancy']
    for key in ('device_active_s', 'device_idle_s',
                'device_idle_fraction', 'compute_busy_s', 'comm_busy_s',
                'overlapped_s', 'measured_overlap_fraction'):
        assert occ[key] is None, key
    assert occ['idle_source'] == 'host'
    assert payload['per_step'] is None
    assert set(attr_mod._DEVICE_FIELDS) == set(payload['unavailable'])
    # Host attribution is still real: the wait slice and the run span.
    assert occ['host_busy_s'] == 0.004
    assert occ['host_wait_s'] == 0.0005


def test_truncated_json_is_a_named_error(tmp_path):
    path = write_trace(tmp_path, device_host_events())
    raw = open(path, 'rb').read()
    with open(path, 'wb') as f:
        f.write(raw[:len(raw) // 2])      # torn mid-write
    with pytest.raises(te.TraceParseError) as ei:
        te.read_trace_file(path)
    assert 'truncated or corrupt JSON' in str(ei.value)
    # The capture root holds ONLY the torn file -> build_attribution
    # refuses with the reason, it does not fabricate an account.
    with pytest.raises(te.TraceParseError):
        attr_mod.build_attribution(str(tmp_path))


def test_one_corrupt_host_does_not_discard_the_others(tmp_path):
    write_trace(tmp_path, device_host_events(), name='host0.trace.json')
    bad = write_trace(tmp_path, [], name='host1.trace.json')
    with open(bad, 'wb') as f:
        f.write(b'{"traceEvents": [')
    payload, _ = attr_mod.build_attribution(str(tmp_path))
    assert payload['stages'] == GOLDEN_STAGES
    assert len(payload['errors']) == 1
    assert 'host1.trace.json' in payload['errors'][0]


def test_bad_gzip_stream_is_a_named_error(tmp_path):
    path = os.path.join(str(tmp_path), 'x.trace.json.gz')
    with open(path, 'wb') as f:
        f.write(b'\x1f\x8b' + b'not really gzip')
    with pytest.raises(te.TraceParseError) as ei:
        te.read_trace_file(path)
    assert 'bad gzip' in str(ei.value)


def test_overlapping_async_slices_do_not_double_count(tmp_path):
    """Two overlapping in-flight comm windows union to their cover;
    nested same-stage compute slices union too — busy time is interval
    algebra, never a duration sum."""
    scope = 'jit(train_step)/jit(main)/'
    events = [
        _meta(1, '/device:TPU:0'),
        _meta(1, 'XLA Ops', tid=1),
        # comm: [0,1000) and [500,1500) -> union 1500us
        _x(1, 1, 0, 1000, 'all-reduce-start.1'),
        _x(1, 1, 500, 1000, 'collective-permute.2'),
        # compute: [200,700) nested inside [200,700)+[300,600) and
        # [1200,1400) -> union 700us
        _x(1, 1, 200, 500, 'fusion.3',
           {'long_name': scope + 'psi2/dot_general'}),
        _x(1, 1, 300, 300, 'fusion.4',
           {'long_name': scope + 'psi2/add'}),
        _x(1, 1, 1200, 200, 'fusion.5',
           {'long_name': scope + 'topk/sort'}),
    ]
    write_trace(tmp_path, events)
    payload, _ = attr_mod.build_attribution(str(tmp_path))
    occ = payload['occupancy']
    assert occ['comm_busy_s'] == 0.0015
    assert occ['compute_busy_s'] == 0.0007
    # overlap: comm [0,1500) covers all compute -> 700us / 1500us
    assert occ['overlapped_s'] == 0.0007
    assert occ['measured_overlap_fraction'] == 0.4667
    assert payload['stages'] == {
        'psi2': {'wall_s': 0.0005, 'events': 2, 'share': 0.2273},
        'topk': {'wall_s': 0.0002, 'events': 1, 'share': 0.0909},
        'other': {'wall_s': 0.0015, 'events': 2, 'share': 0.6818},
    }


def test_comm_without_collectives_has_no_overlap_fraction(tmp_path):
    """A window that moved nothing between devices has an UNDEFINED
    overlap fraction (None), not 0.0 — 0.0 would read as 'fully
    serialized'."""
    events = [
        _meta(1, '/device:TPU:0'),
        _x(1, 1, 0, 1000, 'fusion.1'),
    ]
    write_trace(tmp_path, events)
    payload, _ = attr_mod.build_attribution(str(tmp_path))
    assert payload['occupancy']['comm_busy_s'] == 0.0
    assert payload['occupancy']['measured_overlap_fraction'] is None


# ---------------------------------------------------------------------------
# attribution.json schema pin (the test_live.py style: exact grammar)
# ---------------------------------------------------------------------------

_TOP_KEYS = {
    'schema', 'source', 'errors', 'device_available', 'window_s',
    'steps', 'stages', 'stage_source', 'occupancy', 'per_step',
    'tracks', 'unavailable', 'reconciliation',
}
_SOURCE_KEYS = {'kind', 'path', 'trace_files', 'obs_dir'}
_OCC_KEYS = {
    'window_s', 'device_active_s', 'device_idle_s',
    'device_idle_fraction', 'compute_busy_s', 'comm_busy_s',
    'overlapped_s', 'measured_overlap_fraction', 'host_busy_s',
    'host_wait_s', 'host_wait_fraction', 'idle_fraction', 'idle_source',
}
_STAGE_KEYS = {'wall_s', 'events', 'share'}
_STEP_KEYS = {'observed', 'wall_s', 'mean_s'}
_PER_STEP_KEYS = {'device_active_s', 'steps'}
_TRACK_KEYS = {'process', 'thread', 'device', 'events', 'busy_s'}
_REC_KEYS = {
    'static_mfu', 'measured_mfu', 'mfu_ratio',
    'static_overlap_fraction', 'measured_overlap_fraction',
    'overlap_divergence', 'host_step_p50_s', 'device_step_active_s',
    'notes',
}


def _num_or_none(v):
    return v is None or (isinstance(v, (int, float))
                         and not isinstance(v, bool))


def check_attribution_schema(payload):
    """Strict walk: exact key sets at every level, typed leaves.
    Raises AssertionError on any drift — additive or subtractive."""
    assert set(payload) == _TOP_KEYS, set(payload) ^ _TOP_KEYS
    assert payload['schema'] == attr_mod.SCHEMA_VERSION
    src = payload['source']
    assert set(src) == _SOURCE_KEYS
    assert src['kind'] in ('profiler', 'host-trace')
    assert isinstance(src['trace_files'], list)
    assert all(isinstance(e, str) for e in payload['errors'])
    assert isinstance(payload['device_available'], bool)
    assert _num_or_none(payload['window_s'])
    if payload['steps'] is not None:
        assert set(payload['steps']) == _STEP_KEYS
        assert isinstance(payload['steps']['observed'], int)
    assert payload['stage_source'] in ('device', 'host', None)
    for stage, row in payload['stages'].items():
        assert stage in (*te.STAGE_NAMES, 'other'), stage
        assert set(row) == _STAGE_KEYS
        assert isinstance(row['events'], int)
        assert _num_or_none(row['wall_s']) and _num_or_none(row['share'])
    occ = payload['occupancy']
    assert set(occ) == _OCC_KEYS, set(occ) ^ _OCC_KEYS
    assert occ['idle_source'] in ('device', 'host', 'host-trace', None)
    for k in _OCC_KEYS - {'idle_source'}:
        assert _num_or_none(occ[k]), (k, occ[k])
    if payload['per_step'] is not None:
        assert set(payload['per_step']) == _PER_STEP_KEYS
    for t in payload['tracks']:
        assert set(t) == _TRACK_KEYS
        assert isinstance(t['device'], bool)
    assert all(isinstance(u, str) for u in payload['unavailable'])
    rec = payload['reconciliation']
    if rec is not None:
        assert set(rec) == _REC_KEYS, set(rec) ^ _REC_KEYS
        assert all(isinstance(n, str) for n in rec['notes'])
        for k in _REC_KEYS - {'notes'}:
            assert _num_or_none(rec[k]), (k, rec[k])


def test_schema_pin_device_and_degraded(tmp_path):
    full = tmp_path / 'full'
    write_trace(full, device_host_events())
    payload, _ = attr_mod.build_attribution(str(full))
    check_attribution_schema(payload)
    degraded = tmp_path / 'degraded'
    write_trace(degraded, [e for e in device_host_events()
                           if e.get('pid') != 1])
    payload, _ = attr_mod.build_attribution(str(degraded))
    check_attribution_schema(payload)
    # ...and through the CLI-written artifact byte path too.
    assert attr_mod.main([str(full), '--out',
                          str(tmp_path / 'a.json')]) == 0
    check_attribution_schema(json.load(open(tmp_path / 'a.json')))


# ---------------------------------------------------------------------------
# Reconciliation
# ---------------------------------------------------------------------------


def _obs_dir_with_static(tmp_path, mfu=0.02, overlap=0.1353,
                         flops=4.0e9, peak=197e12):
    d = str(tmp_path / 'obs')
    os.makedirs(d, exist_ok=True)
    eff = {
        'mfu': mfu,
        'peak_flops': peak,
        'peak_flops_ref': 'TPU v5e bf16',
        'peak_flops_source': 'table',
        'programs': {'train_step': {'flops': flops,
                                    'overlap_fraction': overlap}},
    }
    with open(os.path.join(d, 'efficiency.json'), 'w') as f:
        json.dump(eff, f)
    with open(os.path.join(d, 'timings.json'), 'w') as f:
        json.dump({'steps': {'steps': 2, 'p50_s': 0.002}}, f)
    return d


def test_reconciliation_measured_vs_static(tmp_path):
    write_trace(tmp_path, device_host_events())
    obs = _obs_dir_with_static(tmp_path)
    payload, obs_dir = attr_mod.build_attribution(str(tmp_path),
                                                  obs_dir=obs)
    assert obs_dir == obs
    rec = payload['reconciliation']
    # measured MFU = flops / (per-step device-active * peak)
    #             = 4e9 / (0.00125 * 197e12) = 0.01624...
    assert rec['measured_mfu'] == pytest.approx(
        4.0e9 / (0.00125 * 197e12), rel=1e-3)
    assert rec['static_mfu'] == 0.02
    assert rec['mfu_ratio'] == pytest.approx(
        rec['measured_mfu'] / 0.02, abs=1e-4)
    # overlap divergence is measured - modeled, a signed diagnostic
    assert rec['static_overlap_fraction'] == 0.1353
    assert rec['measured_overlap_fraction'] == 0.5
    assert rec['overlap_divergence'] == pytest.approx(0.3647)
    assert rec['host_step_p50_s'] == 0.002
    assert rec['device_step_active_s'] == 0.00125
    assert len(rec['notes']) == 2
    check_attribution_schema(payload)


def test_efficiency_merge_and_lost_measurement(tmp_path):
    write_trace(tmp_path, device_host_events())
    obs = _obs_dir_with_static(tmp_path)
    assert attr_mod.main([str(tmp_path), '--obs-dir', obs]) == 0
    assert os.path.exists(os.path.join(obs, 'attribution.json'))
    eff = json.load(open(os.path.join(obs, 'efficiency.json')))
    # Run rows preserved verbatim; measured block + headline merged.
    assert eff['mfu'] == 0.02
    assert eff['programs']['train_step']['flops'] == 4.0e9
    assert eff['measured']['device_available'] is True
    assert eff['measured_overlap_fraction'] == 0.5
    assert eff['device_idle_fraction'] == 0.375
    assert eff['idle_source'] == 'device'
    assert eff['measured_mfu'] > 0
    # A rerun from a DEGRADED capture must drop the stale headline:
    # absence means absence for obs.diff's lost-account rule.
    degraded = tmp_path / 'degraded'
    write_trace(degraded, [e for e in device_host_events()
                           if e.get('pid') != 1])
    assert attr_mod.main([str(degraded), '--obs-dir', obs]) == 0
    eff = json.load(open(os.path.join(obs, 'efficiency.json')))
    assert 'measured_overlap_fraction' not in eff
    assert 'device_idle_fraction' not in eff
    assert eff['measured']['device_available'] is False
    assert eff['idle_source'] == 'host'
    assert eff['mfu'] == 0.02          # static rows still untouched


# ---------------------------------------------------------------------------
# CLI contract + host-trace degradation (the CPU-container path)
# ---------------------------------------------------------------------------


def _host_trace_obs_dir(tmp_path):
    """An obs dir with only the host-side run trace (no profiler
    capture): step spans + a gap, the graceful-degradation source."""
    d = str(tmp_path / 'obsrun')
    os.makedirs(d, exist_ok=True)
    events = [
        {'ph': 'M', 'pid': 1, 'name': 'process_name',
         'args': {'name': 'dgmc run'}},
        {'ph': 'X', 'pid': 1, 'tid': 1, 'name': 'step 0', 'cat': 'step',
         'ts': 0, 'dur': 1000},
        {'ph': 'X', 'pid': 1, 'tid': 1, 'name': 'step 1', 'cat': 'step',
         'ts': 3000, 'dur': 1000},
    ]
    with open(os.path.join(d, 'trace.json'), 'w') as f:
        json.dump({'traceEvents': events}, f)
    with open(os.path.join(d, 'timings.json'), 'w') as f:
        json.dump({'steps': {'steps': 2, 'p50_s': 0.001}}, f)
    return d


def test_cli_host_trace_mode_exits_zero_and_marks_unavailable(tmp_path,
                                                              capsys):
    obs = _host_trace_obs_dir(tmp_path)
    assert attr_mod.main([obs]) == 0      # the acceptance pin: exit 0
    out = capsys.readouterr().out
    assert 'no device tracks' in out
    assert 'unavailable' in out
    payload = json.load(open(os.path.join(obs, 'attribution.json')))
    check_attribution_schema(payload)
    assert payload['source']['kind'] == 'host-trace'
    assert payload['device_available'] is False
    assert set(payload['unavailable']) == set(attr_mod._DEVICE_FIELDS)
    assert payload['steps']['observed'] == 2
    occ = payload['occupancy']
    # Two 1ms steps over a 4ms window: half the host timeline is gap.
    assert occ['idle_fraction'] == 0.5
    assert occ['idle_source'] == 'host-trace'
    assert occ['measured_overlap_fraction'] is None
    eff = json.load(open(os.path.join(obs, 'efficiency.json')))
    assert eff['measured']['device_available'] is False
    assert eff['idle_fraction'] == 0.5
    assert 'measured_overlap_fraction' not in eff


def test_cli_errors(tmp_path, capsys):
    assert attr_mod.main([str(tmp_path / 'nope')]) == 2
    empty = tmp_path / 'empty'
    empty.mkdir()
    assert attr_mod.main([str(empty)]) == 2
    err = capsys.readouterr().err
    assert 'no readable profiler trace' in err or 'no such path' in err


def test_report_loads_and_renders_attribution(tmp_path):
    from dgmc_tpu.obs.report import load_run, render, summarize

    write_trace(tmp_path, device_host_events())
    obs = _obs_dir_with_static(tmp_path)
    assert attr_mod.main([str(tmp_path), '--obs-dir', obs]) == 0
    run = load_run(obs)
    assert run['attribution'] is not None
    s = summarize(run)
    assert s['measured_overlap_fraction'] == 0.5
    assert s['idle_fraction'] == 0.375
    assert s['idle_source'] == 'device'
    assert s['device_idle_fraction'] == 0.375
    assert s['measured_mfu'] > 0
    assert s['measured_device_available'] is True
    text = render(run)
    assert 'measured attribution' in text
    assert 'psi1' in text
    assert 'static vs measured' in text
