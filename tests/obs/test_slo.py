"""obs.slo: error-budget arithmetic goldens, multi-window burn-rate
alerting, breach firing + cooldown, windowed-ring expiry, spec
validation, and the dgmc_slo_* exposition under the strict parser."""

import json

import pytest

from dgmc_tpu.obs.live import prometheus_exposition
from dgmc_tpu.obs.slo import (DEFAULT_SERVE_SPEC, SloSpec, SloTracker,
                              WindowedRatio, load_slo_spec)
from tests.obs.test_live import parse_exposition


class Clock:
    """Deterministic time_fn for golden budget numbers."""

    def __init__(self, t=1_000_000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


SPEC = {
    'name': 'test-slo',
    'window_s': 3600.0,
    'availability': {'objective': 0.999},
    'latency': [
        {'name': 'query', 'threshold_ms': 1000.0, 'objective': 0.95},
        {'name': 'device_execute', 'stage': 'device_execute',
         'threshold_ms': 500.0, 'objective': 0.95},
    ],
}


def feed(tracker, clock, n=1000, bad_every=100, pace_s=0.1,
         latency_s=0.05):
    """n events paced over n*pace_s seconds, one bad per bad_every."""
    for i in range(n):
        clock.advance(pace_s)
        tracker.record(i % bad_every != bad_every - 1,
                       latency_s=latency_s,
                       stages_ms={'device_execute': latency_s * 1e3})


def test_budget_consumption_golden():
    """1% bad against a 99.9% objective: exactly 10 budgets' worth.

    The same 1% of failed events counts bad for every latency
    objective too (an error is not a fast success), so the 95%
    objectives consume 0.01 / 0.05 = 0.2 of their budgets.
    """
    clock = Clock()
    t = SloTracker(SloSpec(SPEC), time_fn=clock)
    feed(t, clock)  # 1000 events over 100s, 10 bad, all fast
    state = t.check()
    avail = state['objectives']['availability']
    assert avail['events'] == 1000 and avail['bad'] == 10
    assert avail['window_bad_fraction'] == pytest.approx(0.01)
    assert avail['budget_consumed'] == pytest.approx(10.0)
    for name in ('query', 'device_execute'):
        lat = state['objectives'][name]
        assert lat['bad'] == 10
        assert lat['budget_consumed'] == pytest.approx(0.2)


def test_multi_window_burn_alerting():
    """Burn 10.0 pages the slow pair (threshold 6) on both legs but
    not the fast pair (threshold 14.4) — and the breach callback sees
    exactly the alerting pair, once."""
    clock = Clock()
    breaches = []
    t = SloTracker(SloSpec(SPEC), time_fn=clock,
                   on_breach=lambda kind, detail: breaches.append(kind))
    feed(t, clock)
    state = t.check()
    burn = state['objectives']['availability']['burn']
    assert burn['fast']['long'] == pytest.approx(10.0)
    assert burn['fast']['short'] == pytest.approx(10.0)
    assert not burn['fast']['alerting']
    assert burn['slow']['alerting']
    assert 'burn:slow:availability' in breaches
    assert 'budget-exhausted:availability' in breaches  # 10.0 >= 1.0
    assert not any(k.startswith('burn:fast') for k in breaches)


def test_unmeasured_short_leg_cannot_alert():
    """Events older than the short window leave that leg empty: the
    multi-window AND must read no-evidence as no-page, even with the
    long leg far over threshold."""
    clock = Clock()
    t = SloTracker(SloSpec(SPEC), time_fn=clock)
    for _ in range(100):
        clock.advance(0.1)
        t.record(False)  # a 100%-bad burst
    clock.advance(400.0)  # past fast short_s=300, inside long_s=3600
    burn = t.check()['objectives']['availability']['burn']
    assert burn['fast']['long'] is not None
    assert burn['fast']['long'] > 14.4
    assert burn['fast']['short'] is None
    assert not burn['fast']['alerting']


def test_breach_cooldown_rate_limits_callback():
    clock = Clock()
    calls = []
    t = SloTracker(SloSpec(SPEC), time_fn=clock,
                   on_breach=lambda kind, detail: calls.append(kind))
    for _ in range(10):
        clock.advance(0.1)
        t.record(False)
    t.check()
    t.check()  # same breach kinds inside the cooldown: no re-fire
    n_first = len(calls)
    assert n_first > 0
    clock.advance(SloTracker.BREACH_COOLDOWN_S + 1.0)
    t.check()
    assert len(calls) == 2 * n_first
    # ...but the COUNTS keep counting every judged breach.
    counts = t.check()['breaches']['counts']
    assert counts['budget-exhausted:availability'] >= 4


def test_floor_breach():
    spec = SloSpec(dict(SPEC, hits1_floor=0.5, goodput_floor=0.9))
    clock = Clock()
    calls = []
    t = SloTracker(spec, time_fn=clock,
                   on_breach=lambda kind, detail: calls.append(kind))
    t.record(True, latency_s=0.01)
    t.update_gauges(hits1=0.3, goodput=0.95)
    state = t.check()
    assert state['floors']['hits1']['breached']
    assert not state['floors']['goodput']['breached']
    assert calls == ['floor:hits1']
    # None clears: an absent headline is unmeasured, not breached.
    t.update_gauges(hits1=None)
    assert t.check()['floors']['hits1']['value'] is None


def test_windowed_ratio_expiry():
    clock = Clock()
    r = WindowedRatio(60.0, bucket_s=10.0, time_fn=clock)
    for _ in range(10):
        r.add(False)
    assert r.bad_fraction(60.0) == 1.0
    clock.advance(120.0)
    # Horizon passed: the ring forgot the burst entirely.
    assert r.bad_fraction(60.0) is None
    r.add(True)
    assert r.bad_fraction(60.0) == 0.0


def test_windowed_ratio_trailing_window():
    clock = Clock(1_000_000.0)
    r = WindowedRatio(100.0, bucket_s=10.0, time_fn=clock)
    r.add(False)
    clock.advance(50.0)
    r.add(True)
    assert r.counts(100.0) == (1, 2)
    assert r.counts(20.0) == (0, 1)  # the old bad event aged out


def test_ring_bucket_resolves_shortest_window():
    """The ring bucket must quantize the SHORTEST burn leg into >= 6
    buckets — a horizon-sized bucket would blind the fast-short leg."""
    spec = SloSpec(DEFAULT_SERVE_SPEC)
    assert spec.horizon_s == 21600.0
    assert spec.ring_bucket_s == 50.0  # min(3600, 300, 1800) / 6
    pinned = SloSpec(dict(SPEC, bucket_s=5.0))
    assert pinned.ring_bucket_s == 5.0


@pytest.mark.parametrize('raw, needle', [
    ({'availability': {'objective': 1.5}}, 'objective'),
    ({'availability': {'objective': 0.999},
      'latency': [{'name': 'q', 'threshold_ms': -3,
                   'objective': 0.9}]}, 'threshold_ms'),
    ({'latency': [{'name': 'q', 'threshold_ms': 10, 'objective': 0.9},
                  {'name': 'q', 'threshold_ms': 20,
                   'objective': 0.9}]}, 'duplicate'),
    ({'window_s': -1, 'availability': {'objective': 0.9}}, 'window_s'),
    ({}, 'no objectives'),
    ({'availability': {'objective': 0.9},
      'burn_windows': {'fast': {'long_s': 10.0, 'short_s': 60.0,
                                'threshold': 2.0}}}, 'short_s'),
    ([], 'object'),
])
def test_spec_validation_errors(raw, needle):
    with pytest.raises(ValueError, match=needle):
        SloSpec(raw)


def test_load_slo_spec_errors(tmp_path):
    with pytest.raises(ValueError, match='cannot read'):
        load_slo_spec(str(tmp_path / 'absent.json'))
    bad = tmp_path / 'bad.json'
    bad.write_text('{not json')
    with pytest.raises(ValueError, match='not valid JSON'):
        load_slo_spec(str(bad))
    good = tmp_path / 'good.json'
    good.write_text(json.dumps(DEFAULT_SERVE_SPEC))
    assert load_slo_spec(str(good)).name == 'serve-default'


def test_metric_families_strict_exposition():
    clock = Clock()
    spec = SloSpec(dict(SPEC, hits1_floor=0.5))
    t = SloTracker(spec, time_fn=clock)
    feed(t, clock, n=100, bad_every=10)
    t.update_gauges(hits1=0.4)
    text = prometheus_exposition(t.metric_families())
    fams = parse_exposition(text)
    for name in ('dgmc_slo_error_budget_consumed', 'dgmc_slo_burn_rate',
                 'dgmc_slo_burn_alerting', 'dgmc_slo_events_total',
                 'dgmc_slo_breaches_total', 'dgmc_slo_floor_breached'):
        assert name in fams, name
    consumed = {s[1]['objective']: s[2]
                for s in fams['dgmc_slo_error_budget_consumed']['samples']}
    assert consumed['availability'] == pytest.approx(100.0)
    events = {(s[1]['objective'], s[1]['outcome']): s[2]
              for s in fams['dgmc_slo_events_total']['samples']}
    assert events[('availability', 'bad')] == 10
    assert events[('availability', 'good')] == 90
    legs = {(s[1]['objective'], s[1]['window'], s[1]['leg'])
            for s in fams['dgmc_slo_burn_rate']['samples']}
    assert ('availability', 'fast', 'short') in legs
    floor = fams['dgmc_slo_floor_breached']['samples'][0]
    assert floor[1]['floor'] == 'hits1' and floor[2] == 1


def test_empty_tracker_exposition_parses():
    """Zero events must still render a grammatical exposition (the
    breaches family keeps a labeled zero sample)."""
    t = SloTracker(SloSpec(SPEC), time_fn=Clock())
    fams = parse_exposition(prometheus_exposition(t.metric_families()))
    kinds = [s[1]['kind']
             for s in fams['dgmc_slo_breaches_total']['samples']]
    assert kinds == ['none']
    assert fams['dgmc_slo_error_budget_consumed']['samples'] == []


def test_status_omits_spec_echo():
    t = SloTracker(SloSpec(SPEC), time_fn=Clock())
    assert 'spec' in t.snapshot()
    assert 'spec' not in t.status()


def test_stage_latency_uses_named_stage():
    """The device_execute objective judges the qtrace stage, not the
    end-to-end latency; an event without the stage is no evidence."""
    clock = Clock()
    t = SloTracker(SloSpec(SPEC), time_fn=clock)
    # Fast end-to-end, slow device stage: only the stage objective
    # should burn.
    for _ in range(20):
        clock.advance(0.1)
        t.record(True, latency_s=0.01,
                 stages_ms={'device_execute': 900.0})
    state = t.check()
    assert state['objectives']['query']['bad'] == 0
    assert state['objectives']['device_execute']['bad'] == 20
    # No stages_ms at all: the stage objective records nothing.
    t2 = SloTracker(SloSpec(SPEC), time_fn=clock)
    t2.record(True, latency_s=0.01)
    assert t2.check()['objectives']['device_execute']['events'] == 0
