"""RunObserver → obs-dir artifacts → report CLI round trip."""

import json
import os

import jax
import jax.numpy as jnp
import pytest

from dgmc_tpu.obs import REGISTRY, RunObserver, record_dispatch
from dgmc_tpu.obs import report


@pytest.fixture(autouse=True)
def clean_registry():
    REGISTRY.reset()
    yield
    REGISTRY.reset()


def _make_run(tmp_path):
    d = str(tmp_path / 'obs')
    f = jax.jit(lambda a: (a * 2.0).sum())
    with RunObserver(d) as obs:
        record_dispatch('topk', 'fallback', 'backend=cpu')
        for i in range(4):
            with obs.step():
                jax.block_until_ready(f(jnp.ones((4, 4)) * i))
        obs.log(1, loss=0.5, acc=0.25)
        obs.log(2, loss=0.4, acc=0.5)
        obs.snapshot_memory('epoch2')
    return d


def test_observer_emits_all_four_artifacts(tmp_path):
    d = _make_run(tmp_path)
    for name in ('metrics.jsonl', 'timings.json', 'memory.json',
                 'dispatch.json'):
        assert os.path.exists(os.path.join(d, name)), name


def test_report_round_trip_summary(tmp_path):
    d = _make_run(tmp_path)
    s = report.summarize(report.load_run(d))
    assert s['steps'] == 4
    assert s['step_p50_s'] > 0 and s['step_p95_s'] >= s['step_p50_s']
    assert s['compile_events'] >= 1       # the jitted step compiled
    assert s['metrics_records'] == 2
    assert s['last_metrics']['loss'] == 0.4
    assert s['peak_memory_bytes'] > 0     # host-RSS fallback on CPU
    assert s['dispatch_fallback'] >= 1


def test_report_cli_table_and_json(tmp_path, capsys):
    d = _make_run(tmp_path)
    assert report.main([d]) == 0
    out = capsys.readouterr().out
    for needle in ('step timing', 'compile events', 'kernel dispatch',
                   'topk', 'fallback'):
        assert needle in out, needle

    assert report.main([d, '--json']) == 0
    s = json.loads(capsys.readouterr().out)
    assert s['steps'] == 4 and s['compile_events'] >= 1


def test_report_reads_bare_jsonl(tmp_path, capsys):
    p = tmp_path / 'm.jsonl'
    p.write_text(json.dumps({'step': 1, 'loss': 1.0}) + '\n' +
                 json.dumps({'step': 2, 'loss': 0.5}) + '\n')
    assert report.main([str(p), '--json']) == 0
    s = json.loads(capsys.readouterr().out)
    assert s['metrics_records'] == 2
    assert s['last_metrics']['loss'] == 0.5


def test_report_missing_path_errors(capsys):
    assert report.main(['/nonexistent/obs']) == 2


def test_disabled_observer_is_noop(tmp_path):
    obs = RunObserver(None)
    with obs.step():
        pass
    obs.log(1, loss=0.1)
    obs.snapshot_memory('x')
    with obs.compile_label('y'):
        pass
    obs.close()
    assert not any(tmp_path.iterdir())


def test_obs_dir_reuse_holds_one_run(tmp_path):
    """Re-running with the same --obs-dir must not append a second run's
    metrics to artifacts the observer rewrites from scratch."""
    d = _make_run(tmp_path)
    first = report.summarize(report.load_run(d))
    d2 = _make_run(tmp_path)   # same directory, second run
    assert d2 == d
    s = report.summarize(report.load_run(d))
    assert s['metrics_records'] == first['metrics_records']
    assert s['steps'] == first['steps']


def test_artifacts_survive_midrun(tmp_path):
    """Artifacts are rewritten on every log/snapshot, so a killed run
    still leaves analyzable telemetry (the BENCH_r05 failure mode)."""
    d = str(tmp_path / 'obs')
    obs = RunObserver(d)
    with obs.step():
        pass
    obs.log(1, loss=1.0)
    # No close(): simulate a SIGKILL here.
    data = json.load(open(os.path.join(d, 'timings.json')))
    assert data['steps']['steps'] == 1
    obs.close()
