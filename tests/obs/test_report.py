"""RunObserver → obs-dir artifacts → report CLI round trip."""

import json
import os

import jax
import jax.numpy as jnp
import pytest

from dgmc_tpu.obs import REGISTRY, RunObserver, record_dispatch
from dgmc_tpu.obs import report


@pytest.fixture(autouse=True)
def clean_registry():
    REGISTRY.reset()
    yield
    REGISTRY.reset()


def _make_run(tmp_path):
    d = str(tmp_path / 'obs')
    f = jax.jit(lambda a: (a * 2.0).sum())
    with RunObserver(d) as obs:
        record_dispatch('topk', 'fallback', 'backend=cpu')
        for i in range(4):
            with obs.step():
                jax.block_until_ready(f(jnp.ones((4, 4)) * i))
        obs.log(1, loss=0.5, acc=0.25)
        obs.log(2, loss=0.4, acc=0.5)
        obs.snapshot_memory('epoch2')
    return d


def test_observer_emits_all_four_artifacts(tmp_path):
    d = _make_run(tmp_path)
    for name in ('metrics.jsonl', 'timings.json', 'memory.json',
                 'dispatch.json'):
        assert os.path.exists(os.path.join(d, name)), name


def test_report_round_trip_summary(tmp_path):
    d = _make_run(tmp_path)
    s = report.summarize(report.load_run(d))
    assert s['steps'] == 4
    assert s['step_p50_s'] > 0 and s['step_p95_s'] >= s['step_p50_s']
    assert s['compile_events'] >= 1       # the jitted step compiled
    assert s['metrics_records'] == 2
    assert s['last_metrics']['loss'] == 0.4
    assert s['peak_memory_bytes'] > 0     # host-RSS fallback on CPU
    assert s['dispatch_fallback'] >= 1


def test_report_cli_table_and_json(tmp_path, capsys):
    d = _make_run(tmp_path)
    assert report.main([d]) == 0
    out = capsys.readouterr().out
    for needle in ('step timing', 'compile events', 'kernel dispatch',
                   'topk', 'fallback'):
        assert needle in out, needle

    assert report.main([d, '--json']) == 0
    s = json.loads(capsys.readouterr().out)
    assert s['steps'] == 4 and s['compile_events'] >= 1


def test_report_reads_bare_jsonl(tmp_path, capsys):
    p = tmp_path / 'm.jsonl'
    p.write_text(json.dumps({'step': 1, 'loss': 1.0}) + '\n' +
                 json.dumps({'step': 2, 'loss': 0.5}) + '\n')
    assert report.main([str(p), '--json']) == 0
    s = json.loads(capsys.readouterr().out)
    assert s['metrics_records'] == 2
    assert s['last_metrics']['loss'] == 0.5


def test_report_missing_path_errors(capsys):
    assert report.main(['/nonexistent/obs']) == 2


def test_disabled_observer_is_noop(tmp_path):
    obs = RunObserver(None)
    with obs.step():
        pass
    obs.log(1, loss=0.1)
    obs.snapshot_memory('x')
    with obs.compile_label('y'):
        pass
    obs.close()
    assert not any(tmp_path.iterdir())


def test_obs_dir_reuse_holds_one_run(tmp_path):
    """Re-running with the same --obs-dir must not append a second run's
    metrics to artifacts the observer rewrites from scratch."""
    d = _make_run(tmp_path)
    first = report.summarize(report.load_run(d))
    d2 = _make_run(tmp_path)   # same directory, second run
    assert d2 == d
    s = report.summarize(report.load_run(d))
    assert s['metrics_records'] == first['metrics_records']
    assert s['steps'] == first['steps']


def test_report_renders_efficiency_and_hang(tmp_path, capsys):
    d = _make_run(tmp_path)
    with open(os.path.join(d, 'efficiency.json'), 'w') as f:
        json.dump({'mfu': 0.42, 'peak_flops': 1e12,
                   'peak_flops_ref': 'TPU vX bf16',
                   'peak_flops_source': 'table',
                   'programs': {'train_step': {
                       'flops': 1e9, 'bytes': 2e8, 'mfu': 0.42,
                       'stages': {'psi1': {'flops': 5e8,
                                           'bytes_out': 1e8,
                                           'ops': 10}}}}}, f)
    with open(os.path.join(d, 'hang_report.json'), 'w') as f:
        json.dump({'reason': 'deadline', 'stalled_for_s': 60.0,
                   'in_flight': {'phase': 'step', 'name': 3},
                   'last_completed': {'phase': 'step', 'name': 2}}, f)
    s = report.summarize(report.load_run(d))
    assert s['mfu'] == 0.42
    assert s['flops_per_step'] == 1e9
    assert s['hang_report']['reason'] == 'deadline'
    assert report.main([d]) == 0
    out = capsys.readouterr().out
    assert 'MFU' in out and '42' in out
    assert 'psi1' in out and 'cost / efficiency' in out
    assert 'RUN HUNG' in out


def test_probe_rebuild_matches_live_aggregates(tmp_path):
    """Satellite pin: aggregates recomputed from the raw metrics.jsonl
    series (the probe_aggregates_from_metrics fallback path) must match
    the timings.json aggregates the live sink wrote — same accumulator,
    same numbers. ('nonfinite' is exempt by construction: only FIRING
    checks reach metrics.jsonl, so the rebuild sees a different
    population than the live all-checks statistics.)"""
    import jax
    from dgmc_tpu.obs import probes

    d = str(tmp_path / 'obs')
    obs = RunObserver(d, probes=True)
    try:

        def f(x):
            probes.emit('corr_entropy', jnp.sum(x), stage='S0')
            probes.emit('consensus_delta', jnp.mean(x), iteration=0)
            probes.check_finite('psi1', x, order=1)
            return x * 2.0

        jf = jax.jit(f)
        for i in range(5):
            with obs.step():
                jax.block_until_ready(jf(jnp.ones((4,)) * i))
        jax.effects_barrier()
        obs.log(1, loss=1.0)
    finally:
        obs.close()

    timings = json.load(open(os.path.join(d, 'timings.json')))
    live = timings['probes']
    rebuilt = report.probe_aggregates_from_metrics(
        report.load_run(d)['metrics'])
    assert set(rebuilt) == set(live) - {'nonfinite'}
    for name in rebuilt:
        assert rebuilt[name] == live[name], name
    assert live['corr_entropy']['count'] == 5


def test_artifacts_survive_midrun(tmp_path):
    """Artifacts are rewritten on every log/snapshot, so a killed run
    still leaves analyzable telemetry (the BENCH_r05 failure mode)."""
    d = str(tmp_path / 'obs')
    obs = RunObserver(d)
    with obs.step():
        pass
    obs.log(1, loss=1.0)
    # No close(): simulate a SIGKILL here.
    data = json.load(open(os.path.join(d, 'timings.json')))
    assert data['steps']['steps'] == 1
    obs.close()
