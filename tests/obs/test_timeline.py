"""obs.timeline: longitudinal round trajectory over both round-record
schemas (the legacy r01–r05 driver captures and the structured r06+
records), plus the CLI contract against the repo's own committed
rounds."""

import json
import os
import subprocess
import sys

from dgmc_tpu.obs.timeline import collect_rounds, parse_round, render

REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _write(tmp_path, name, payload):
    p = tmp_path / name
    p.write_text(json.dumps(payload))
    return str(p)


def test_parses_legacy_driver_capture(tmp_path):
    _write(tmp_path, 'BENCH_r04.json', {
        'n': 4, 'cmd': 'python bench.py', 'rc': 0, 'tail': '...',
        'parsed': {'metric': 'train_pairs_per_sec', 'value': 1248.9,
                   'device': 'TPU v5 lite',
                   'dense_perf': {'mfu': 0.0194},
                   'sparse_dbp15k': {'step_ms': 306.5}}})
    _write(tmp_path, 'BENCH_r05.json', {
        'n': 5, 'cmd': 'python bench.py', 'rc': 124, 'tail': ''})
    rows = collect_rounds([str(tmp_path)])
    assert [r['round'] for r in rows] == [4, 5]
    r4, r5 = rows
    assert r4['pairs_per_sec'] == 1248.9
    assert r4['mfu'] == 0.0194
    assert r4['step_p50_ms'] == 306.5
    assert r4['outcome'] == 'completed'
    assert r5['outcome'] == 'rc:124'
    assert r5['pairs_per_sec'] is None


def test_parses_structured_rounds(tmp_path):
    _write(tmp_path, 'BENCH_r06.json', {
        'round': 6, 'rc': 0, 'ok': True,
        'supervision': {'outcome': 'completed', 'restarts': 2},
        'result': {'metric': 'train_pairs_per_sec', 'value': 16.97,
                   'device': 'cpu',
                   'dense_perf': {'mfu': 1.09},
                   'sparse_dbp15k': {'f32': {'step_ms': 11507.9}}}})
    _write(tmp_path, 'MULTICHIP_r08.json', {
        'round': 8, 'n_devices': 8, 'rc': 0, 'ok': True,
        'supervision': {'outcome': 'completed', 'restarts': 0},
        'timing': {'step_p50_ms_8dev': 659.1,
                   'per_device_step_skew_ratio': 1.0}})
    _write(tmp_path, 'SCALE_r07.json', {
        'round': 7, 'n_devices': 8,
        'supervision': {'outcome_8dev': 'completed',
                        'restarts_8dev': 0},
        'timing': {'step_p50_ms_8dev': 412275.0,
                   'per_device_step_skew_ratio': 1.0}})
    rows = collect_rounds([str(tmp_path)])
    assert [(r['family'], r['round']) for r in rows] == [
        ('BENCH', 6), ('MULTICHIP', 8), ('SCALE', 7)]
    bench, multi, scale = rows
    assert bench['pairs_per_sec'] == 16.97
    assert bench['step_p50_ms'] == 11507.9
    assert bench['outcome'] == 'completed (2 restarts)'
    assert multi['step_p50_ms'] == 659.1
    assert multi['skew'] == 1.0
    assert multi['devices'] == 8
    assert scale['step_p50_ms'] == 412275.0
    text = render(rows)
    assert 'BENCH trajectory' in text
    assert 'MULTICHIP trajectory' in text
    assert 'SCALE trajectory' in text


def test_unreadable_round_is_a_row_not_a_crash(tmp_path):
    (tmp_path / 'BENCH_r09.json').write_text('{not json')
    rows = collect_rounds([str(tmp_path)])
    assert rows[0]['outcome'].startswith('unreadable')
    render(rows)    # must not raise


def test_non_round_files_ignored(tmp_path):
    _write(tmp_path, 'BENCH_BASELINE.json', {'value': 1})
    _write(tmp_path, 'corr_shard_memory.json', {'x': 1})
    assert collect_rounds([str(tmp_path)]) == []


def test_parse_round_single_file(tmp_path):
    p = _write(tmp_path, 'MULTICHIP_r01.json', {
        'n_devices': 8, 'rc': 1, 'tail': ''})
    row = parse_round('MULTICHIP', 1, p)
    assert row['outcome'] == 'rc:1'
    assert row['devices'] == 8


def test_cli_over_committed_repo_rounds():
    """The committed evidence itself: benchmarks/ holds the WHOLE
    r01+ trajectory (the legacy root-level r01–r05 driver captures
    moved there), so one ``obs.timeline benchmarks/`` invocation
    renders every round — BENCH r06's headline throughput included."""
    out = subprocess.run(
        [sys.executable, '-m', 'dgmc_tpu.obs.timeline',
         'benchmarks', '--json'],
        cwd=REPO, capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    rows = json.loads(out.stdout)
    by_key = {(r['family'], r['round']): r for r in rows}
    assert by_key[('BENCH', 6)]['pairs_per_sec'] == 16.97
    assert by_key[('MULTICHIP', 8)]['step_p50_ms'] == 659.1
    assert by_key[('SCALE', 7)]['outcome'].startswith('completed')
    # The rc:124 era is visible, not hidden: r05 of both families.
    assert by_key[('BENCH', 5)]['outcome'] == 'rc:124'


def test_cli_empty_dir_exits_2(tmp_path):
    out = subprocess.run(
        [sys.executable, '-m', 'dgmc_tpu.obs.timeline', str(tmp_path)],
        capture_output=True, text=True, cwd=REPO)
    assert out.returncode == 2


def test_scale_offload_column(tmp_path):
    """SCALE rows carry the offload account (prefetch depth +
    host-resident corpus bytes) and the table renders the column —
    r07→r08 must read as a layout change, not a regression."""
    _write(tmp_path, 'SCALE_r08.json', {
        'round': 8, 'n_devices': 8,
        'supervision': {'outcome_8dev': 'completed',
                        'restarts_8dev': 0},
        'timing': {'step_p50_ms_8dev': 1000.0,
                   'per_device_step_skew_ratio': 1.0},
        'offload': {'rows': 1 << 23, 'prefetch_depth': 2,
                    'host_resident_bytes': 2 << 30,
                    'outcome': 'completed'}})
    _write(tmp_path, 'SCALE_r07.json', {
        'round': 7, 'n_devices': 8,
        'supervision': {'outcome_8dev': 'completed'},
        'timing': {'step_p50_ms_8dev': 2000.0}})
    rows = collect_rounds([str(tmp_path)])
    r7, r8 = rows
    assert 'offload' not in r7
    assert r8['offload']['prefetch_depth'] == 2
    assert r8['offload']['rows'] == 1 << 23
    table = render(rows)
    assert 'offload' in table
    assert 'd2/2.0G' in table
    # The offload-less r07 row renders a placeholder, not a blank.
    (line7,) = [ln for ln in table.splitlines() if ln.strip().
                startswith('7 ')]
    assert ' - ' in line7


def test_serve_family_rows(tmp_path):
    """SERVE rounds carry their own headline set: query-latency
    p50/p95, QPS under concurrent load, clients, the warm
    restart-to-first-answer and the restart count — rendered as their
    own trajectory section."""
    _write(tmp_path, 'SERVE_r01.json', {
        'round': 1,
        'supervision': {'outcome': 'completed', 'restarts': 1},
        'latency': {'server_p50_ms': 111.8, 'server_p95_ms': 134.8,
                    'client_p50_ms': 118.8},
        'qps': 28.6, 'clients': 4,
        'restart': {'cold_first_answer_s': 12.7,
                    'warm_first_answer_s': 10.8,
                    'warm_beats_cold': True}})
    rows = collect_rounds([str(tmp_path)])
    (r,) = rows
    assert r['family'] == 'SERVE'
    assert r['latency_p50_ms'] == 111.8
    assert r['latency_p95_ms'] == 134.8
    assert r['qps'] == 28.6
    assert r['clients'] == 4
    assert r['restarts'] == 1
    assert r['warm_restart_s'] == 10.8
    # The chaos kill is part of the protocol: the restart count is a
    # COLUMN, not an outcome-string warning like the training families.
    assert r['outcome'] == 'completed'
    table = render(rows)
    assert 'SERVE trajectory' in table
    assert 'restarts' in table and 'QPS' in table
    (line,) = [ln for ln in table.splitlines()
               if ln.strip().startswith('1 ')]
    assert '10.80s' in line


def test_serve_qtrace_columns(tmp_path):
    """r02+ rounds carry the qtrace attribution block: p99 and the
    dominant tail stage become columns; a pre-qtrace round renders
    '-' in both, not a crash."""
    _write(tmp_path, 'SERVE_r01.json', {
        'round': 1, 'supervision': {'outcome': 'completed',
                                    'restarts': 1},
        'latency': {'server_p50_ms': 111.8, 'server_p95_ms': 134.8},
        'qps': 28.6, 'clients': 4})
    _write(tmp_path, 'SERVE_r02.json', {
        'round': 2, 'supervision': {'outcome': 'completed',
                                    'restarts': 1},
        'latency': {'server_p50_ms': 100.0, 'server_p95_ms': 150.0},
        'qps': 30.0, 'clients': 4,
        'qtrace': {'p99_ms': 201.5,
                   'dominant_stage': 'admission_queue_wait'}})
    r1, r2 = collect_rounds([str(tmp_path)])
    assert r1['latency_p99_ms'] is None
    assert r1['dominant_stage'] is None
    assert r2['latency_p99_ms'] == 201.5
    assert r2['dominant_stage'] == 'admission_queue_wait'
    table = render([r1, r2])
    assert 'p99' in table and 'tail stage' in table
    (line1,) = [ln for ln in table.splitlines()
                if ln.strip().startswith('1 ')]
    (line2,) = [ln for ln in table.splitlines()
                if ln.strip().startswith('2 ')]
    assert 'admission_queue_wait' in line2 and '201.50 ms' in line2
    assert 'admission_queue_wait' not in line1


def test_serve_goodput_and_utilization_columns(tmp_path):
    """r04+ rounds carry the capacity/goodput plane: the serve goodput
    ratio and Little's-law utilization become columns; a pre-capacity
    round renders '-' in both, not a crash."""
    _write(tmp_path, 'SERVE_r01.json', {
        'round': 1, 'supervision': {'outcome': 'completed',
                                    'restarts': 1},
        'latency': {'server_p50_ms': 111.8, 'server_p95_ms': 134.8},
        'qps': 28.6, 'clients': 4})
    _write(tmp_path, 'SERVE_r04.json', {
        'round': 4, 'supervision': {'outcome': 'completed',
                                    'restarts': 1},
        'latency': {'server_p50_ms': 100.0, 'server_p95_ms': 150.0},
        'qps': 19.7, 'clients': 4,
        'goodput': {'serve': {'goodput_ratio': 0.987}},
        'capacity': {'utilization': 0.876}})
    r1, r4 = collect_rounds([str(tmp_path)])
    assert r1['goodput'] is None
    assert r1['utilization'] is None
    assert r4['goodput'] == 0.987
    assert r4['utilization'] == 0.876
    table = render([r1, r4])
    assert 'goodput' in table and 'util' in table
    (line1,) = [ln for ln in table.splitlines()
                if ln.strip().startswith('1 ')]
    (line4,) = [ln for ln in table.splitlines()
                if ln.strip().startswith('4 ')]
    assert '0.987' in line4 and '0.876' in line4
    assert '0.987' not in line1


def test_serve_falls_back_to_client_latency(tmp_path):
    _write(tmp_path, 'SERVE_r02.json', {
        'round': 2, 'supervision': {'outcome': 'completed',
                                    'restarts': 0},
        'latency': {'client_p50_ms': 9.0, 'client_p95_ms': 14.0},
        'qps': 100.0, 'clients': 2})
    (r,) = collect_rounds([str(tmp_path)])
    assert r['latency_p50_ms'] == 9.0
    assert r['latency_p95_ms'] == 14.0
    render(collect_rounds([str(tmp_path)]))


def test_cli_over_committed_serve_round():
    """The committed SERVE_r01 evidence: a supervised load round with
    one (deliberate) restart, zero per-query compiles, and the warm
    restart beating the cold start."""
    out = subprocess.run(
        [sys.executable, '-m', 'dgmc_tpu.obs.timeline',
         'benchmarks', '--json'],
        cwd=REPO, capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    rows = json.loads(out.stdout)
    by_key = {(r['family'], r['round']): r for r in rows}
    serve = by_key[('SERVE', 1)]
    assert serve['outcome'] == 'completed'
    assert serve['restarts'] == 1
    assert serve['clients'] >= 4
    assert serve['latency_p50_ms'] > 0
    assert serve['latency_p95_ms'] >= serve['latency_p50_ms']
    assert serve['qps'] > 0
    # The round record's own acceptance gates, re-asserted over the
    # committed file (the CI serve-evidence pin).
    with open(os.path.join(REPO, 'benchmarks', 'SERVE_r01.json')) as f:
        rec = json.load(f)
    assert rec['outcome'] == 'completed'
    assert rec['compiles']['per_query'] == 0
    assert rec['restart']['warm_beats_cold'] is True
    assert rec['restart']['warm_cache_hit'] == 1
    assert rec['restart']['cold_cache_hit'] == 0
    assert rec['queries_failed'] == 0
    # r02 adds the per-query trace account; its gates re-asserted over
    # the committed file the same way.
    serve2 = by_key[('SERVE', 2)]
    assert serve2['outcome'] == 'completed'
    assert serve2['latency_p99_ms'] >= serve2['latency_p95_ms'] > 0
    with open(os.path.join(REPO, 'benchmarks', 'SERVE_r02.json')) as f:
        rec2 = json.load(f)
    qt = rec2['qtrace']
    assert rec2['compiles']['per_query'] == 0
    assert qt['trace_adopted'] == qt['traced_queries'] > 0
    assert 0.70 <= qt['stage_sum_coverage_p50'] <= 1.02
    assert qt['overhead']['overhead_frac'] < 0.05
    assert qt['dominant_stage'] in qt['stage_p95_ms']
    assert serve2['dominant_stage'] == qt['dominant_stage']
