"""Pipeline stage scopes must survive into lowered HLO metadata.

The acceptance contract: lowering a *train step* of the sparse matcher
yields a module whose debug text names every pipeline stage — ``psi1``,
``topk``, ``consensus_iter``, ``psi2`` (plus ``initial_corr`` and the
backbone layer scopes) — so Perfetto/TensorBoard traces show the matching
algorithm's phases instead of anonymous XLA ops. Numerical equivalence is
covered by the existing model tests (named scopes change metadata only).
"""

import jax
import numpy as np
import pytest

from dgmc_tpu.models import DGMC, RelCNN, SplineCNN
from dgmc_tpu.ops.graph import GraphBatch
from dgmc_tpu.train import create_train_state, make_train_step
from dgmc_tpu.utils.data import PairBatch


def _side(rng, n, e, c=4, edge_dim=None):
    return GraphBatch(
        x=rng.randn(1, n, c).astype(np.float32),
        senders=rng.randint(0, n, (1, e)).astype(np.int32),
        receivers=rng.randint(0, n, (1, e)).astype(np.int32),
        node_mask=np.ones((1, n), bool),
        edge_mask=np.ones((1, e), bool),
        edge_attr=(rng.rand(1, e, edge_dim).astype(np.float32)
                   if edge_dim else None))


def _lowered_debug_text(model, batch):
    state = create_train_state(model, jax.random.key(0), batch,
                               learning_rate=1e-3)
    step = make_train_step(model)
    lowered = step.lower(state, batch, jax.random.key(1))
    return lowered.compiler_ir().operation.get_asm(enable_debug_info=True)


def test_sparse_train_step_contains_all_pipeline_scopes():
    rng = np.random.RandomState(0)
    batch = PairBatch(s=_side(rng, 8, 16), t=_side(rng, 10, 20),
                      y=(np.arange(8, dtype=np.int32) % 10)[None],
                      y_mask=np.ones((1, 8), bool))
    model = DGMC(RelCNN(4, 8, num_layers=2), RelCNN(4, 4, num_layers=1),
                 num_steps=2, k=3)
    asm = _lowered_debug_text(model, batch)
    for scope in ('psi1', 'topk', 'consensus_iter', 'psi2',
                  'initial_corr', 'rel_conv_0', 'rel_conv_1',
                  # train/steps.py's stages: the cost attribution
                  # (obs/cost.py) buckets the step's non-model work here.
                  'loss', 'optimizer'):
        assert scope in asm, f'missing named scope {scope!r} in HLO'


def test_dense_train_step_contains_pipeline_scopes():
    rng = np.random.RandomState(1)
    batch = PairBatch(s=_side(rng, 8, 16, c=2, edge_dim=2),
                      t=_side(rng, 8, 16, c=2, edge_dim=2),
                      y=np.arange(8, dtype=np.int32)[None],
                      y_mask=np.ones((1, 8), bool))
    model = DGMC(SplineCNN(2, 8, dim=2, num_layers=1, cat=False),
                 SplineCNN(4, 4, dim=2, num_layers=1, cat=True),
                 num_steps=1, k=-1)
    asm = _lowered_debug_text(model, batch)
    for scope in ('psi1', 'initial_corr', 'consensus_iter', 'psi2',
                  'spline_conv_0'):
        assert scope in asm, f'missing named scope {scope!r} in HLO'


def test_scopes_do_not_change_outputs():
    """Belt-and-braces on top of the existing model tests: the scoped
    model's outputs equal a plain re-execution of the same apply (scopes
    are metadata-only)."""
    rng = np.random.RandomState(2)
    s, t = _side(rng, 6, 12), _side(rng, 7, 14)
    model = DGMC(RelCNN(4, 8, num_layers=1), RelCNN(4, 4, num_layers=1),
                 num_steps=1, k=2)
    rngs = {'params': jax.random.key(0), 'noise': jax.random.key(1)}
    params = model.init(rngs, s, t)
    out1 = model.apply(params, s, t, rngs={'noise': jax.random.key(1)})
    out2 = model.apply(params, s, t, rngs={'noise': jax.random.key(1)})
    np.testing.assert_array_equal(np.asarray(out1[1].val),
                                  np.asarray(out2[1].val))


@pytest.mark.parametrize('phase_steps', [0, 2])
def test_phase_aware_sparse_lowering(phase_steps):
    """num_steps=0 (the DBP15K phase-1 step) still lowers with psi1/topk
    scopes and without consensus scopes."""
    rng = np.random.RandomState(3)
    batch = PairBatch(s=_side(rng, 8, 16), t=_side(rng, 10, 20),
                      y=(np.arange(8, dtype=np.int32) % 10)[None],
                      y_mask=np.ones((1, 8), bool))
    model = DGMC(RelCNN(4, 8, num_layers=1), RelCNN(4, 4, num_layers=1),
                 num_steps=phase_steps, k=3)
    asm = _lowered_debug_text(model, batch)
    assert 'psi1' in asm and 'topk' in asm
    assert ('consensus_iter' in asm) == (phase_steps > 0)
