"""obs.anomaly: EWMA spike + CUSUM shift detection on synthetic
step-changes, seeded white-noise silence, the bounded event ring, the
rate-limited callback, and the offline changepoints scan."""

import random

from dgmc_tpu.obs.anomaly import (AnomalyWatch, CusumDetector,
                                  EwmaDetector, changepoints)
from dgmc_tpu.obs.live import prometheus_exposition
from tests.obs.test_live import parse_exposition


class Clock:
    def __init__(self, t=1_000_000.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, s):
        self.t += s


def test_ewma_warmup_trains_silently():
    d = EwmaDetector(warmup=10)
    for i in range(10):
        z, spiked = d.observe(float(i))
        assert z is None and not spiked


def test_ewma_spikes_on_cliff():
    d = EwmaDetector(alpha=0.1, z_threshold=4.0, warmup=10)
    rng = random.Random(0)
    for _ in range(50):
        d.observe(1.0 + 0.01 * rng.gauss(0, 1))
    z, spiked = d.observe(5.0)  # a 400-sigma cliff
    assert spiked and abs(z) > 4.0


def test_ewma_flat_history_floor():
    """A dead-constant signal must not flag an infinitesimal wiggle
    with an infinite z: the sigma floor keeps z finite."""
    d = EwmaDetector(warmup=5)
    for _ in range(20):
        d.observe(100.0)
    z, spiked = d.observe(100.0 + 1e-9)
    assert z is not None and abs(z) < 1.0 and not spiked


def test_cusum_fires_on_step_change_and_resets():
    det = CusumDetector(k=0.5, h=5.0)
    fired = []
    # 1-sigma sustained shift: each sample adds z-k = 0.5 to s+.
    for i in range(30):
        shifted, direction = det.observe(1.0)
        if shifted:
            fired.append((i, direction))
    assert fired[0] == (9, 'up')  # 10 * 0.5 >= 5.0 at index 9
    assert det.s_pos < 5.0  # reset after each fire
    down = CusumDetector(k=0.5, h=5.0)
    assert any(down.observe(-1.0) == (True, 'down') for _ in range(30))


def test_watch_detects_synthetic_step_change():
    """Quiet gaussian baseline, then the mean jumps 8 sigma: the watch
    must record the excursion (spike on the cliff, CUSUM shift as it
    sustains) on exactly that signal."""
    w = AnomalyWatch(time_fn=Clock())
    rng = random.Random(1)
    for _ in range(60):
        w.observe('step_latency_s', 0.10 + 0.005 * rng.gauss(0, 1))
    for _ in range(30):
        w.observe('step_latency_s', 0.14 + 0.005 * rng.gauss(0, 1))
    c = w.counters()['signals']['step_latency_s']
    assert c['samples'] == 90
    assert c['spikes'] >= 1
    assert c['shifts'] >= 1
    events = w.snapshot()['events']
    assert events and events[0]['signal'] == 'step_latency_s'
    assert events[0]['direction'] == 'up'


def test_watch_quiet_on_white_noise():
    """Seeded white noise at the configured tuning (z=4, ARL ~930):
    the false-positive budget over 1000 samples is a handful of
    events, not a stream."""
    w = AnomalyWatch(time_fn=Clock())
    rng = random.Random(2)
    for _ in range(1000):
        w.observe('qps', 20.0 + 2.0 * rng.gauss(0, 1))
    c = w.counters()['signals']['qps']
    assert c['spikes'] + c['shifts'] <= 8  # < 1% of samples


def test_ring_bounded_with_truncation_counter():
    clock = Clock()
    w = AnomalyWatch(capacity=8, time_fn=clock)
    # Train on zeros, then feed exponentially growing magnitudes: each
    # value outpaces the EWMA's adaptation, so every sample anomales.
    for _ in range(12):
        w.observe('guard_skips', 0.0)
    fired = 0
    for i in range(20):
        clock.advance(1.0)
        if w.observe('guard_skips', 10.0 ** (i + 3)) is not None:
            fired += 1
    assert fired > 8
    snap = w.snapshot()
    assert len(snap['events']) == 8  # capacity holds
    assert snap['truncated'] == fired - 8
    assert snap['capacity'] == 8
    # The freshest events survived the eviction.
    assert snap['events'][-1]['sample'] == 32


def test_callback_rate_limited_per_signal():
    clock = Clock()
    calls = []
    w = AnomalyWatch(capacity=64, time_fn=clock,
                     on_anomaly=lambda e: calls.append(e['signal']))
    for _ in range(12):
        w.observe('qps', 1.0)
        w.observe('compile_events', 0.0)
    assert w.observe('qps', 1e9) is not None
    assert w.observe('qps', 1e12) is not None  # within the cooldown
    assert calls == ['qps']
    clock.advance(AnomalyWatch.CALLBACK_COOLDOWN_S + 1.0)
    assert w.observe('qps', 1e15) is not None
    assert calls == ['qps', 'qps']
    # Independent cooldown per signal.
    assert w.observe('compile_events', 50.0) is not None
    assert calls == ['qps', 'qps', 'compile_events']


def test_callback_exception_never_escapes():
    def boom(event):
        raise RuntimeError('observer crashed')

    w = AnomalyWatch(time_fn=Clock(), on_anomaly=boom)
    for _ in range(12):
        w.observe('qps', 1.0)
    event = w.observe('qps', 1e9)  # must not raise
    assert event is not None and 'spike' in event['kinds']


def test_metric_families_strict_exposition():
    w = AnomalyWatch(time_fn=Clock())
    for _ in range(12):
        w.observe('qps', 1.0)
    w.observe('qps', 1e9)
    fams = parse_exposition(prometheus_exposition(w.metric_families()))
    spikes = {s[1]['signal']: s[2]
              for s in fams['dgmc_anomaly_spikes_total']['samples']}
    assert spikes['qps'] >= 1
    assert fams['dgmc_anomaly_ring_truncated_total']['samples'][0][2] == 0
    # Empty watch still renders grammatically (labeled zero samples).
    empty = parse_exposition(
        prometheus_exposition(AnomalyWatch(time_fn=Clock())
                              .metric_families()))
    assert empty['dgmc_anomaly_spikes_total']['samples'][0][1] == \
        {'signal': 'none'}


def test_changepoints_one_event_per_excursion():
    """A sustained step change is ONE changepoint at the shift round —
    the re-baseline keeps the following steady rounds quiet."""
    series = [1.0] * 5 + [2.0] * 5
    cps = changepoints(series)
    assert len(cps) == 1
    assert cps[0]['index'] == 5
    assert cps[0]['direction'] == 'up'
    assert cps[0]['value'] == 2.0


def test_changepoints_down_and_none_handling():
    series = [10.0, None, 10.0, 10.0, None, 10.0, 3.0, 3.0]
    cps = changepoints(series)
    assert len(cps) == 1
    assert cps[0]['direction'] == 'down'
    assert cps[0]['index'] == 6  # index in the ORIGINAL series


def test_changepoints_stable_and_short_series():
    assert changepoints([5.0, 5.0, 5.0, 5.0, 5.0]) == []
    assert changepoints([1.0, 100.0]) == []  # under warmup: no baseline
    assert changepoints([]) == []
