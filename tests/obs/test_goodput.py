"""Padding-waste / goodput accounting (obs.goodput): golden-math fill
fractions, the FLOP-composed ratio, the recorded-row recomputation path
(padding_bucket + padding_real → goodput.json), and the lost-account
honesty rule (no real sizes recorded → no payload, never a guess)."""

import numpy as np
import pytest

from dgmc_tpu.obs import goodput


def test_fill_fraction_clamps_and_rejects():
    assert goodput.fill_fraction(3, 4) == 0.75
    assert goodput.fill_fraction(8, 4) == 1.0     # clamped, never >1
    assert goodput.fill_fraction(-1, 4) == 0.0
    assert goodput.fill_fraction(3, 0) is None    # undefined, not inf
    assert goodput.fill_fraction(None, 4) is None
    assert goodput.fill_fraction('x', 4) is None


def test_mask_fills_counts_validity_masks():
    node_mask = np.zeros((2, 8), bool)
    node_mask[0, :3] = True
    node_mask[1, :5] = True
    edge_mask = np.zeros((2, 10), bool)
    edge_mask[:, :4] = True
    acct = goodput.mask_fills(node_mask, edge_mask)
    assert acct == {'nodes_real': 8, 'nodes_padded': 16,
                    'edges_real': 8, 'edges_padded': 20}


def test_pair_fills_corr_is_product_of_side_fills():
    s = {'nodes_real': 4, 'nodes_padded': 8,
         'edges_real': 5, 'edges_padded': 10}
    t = {'nodes_real': 8, 'nodes_padded': 8,
         'edges_real': 10, 'edges_padded': 10}
    fills = goodput.pair_fills(s, t)
    assert fills['nodes'] == pytest.approx(12 / 16)
    assert fills['edges'] == pytest.approx(15 / 20)
    # corr = node fill of SOURCE × node fill of TARGET (the [N_s, N_t]
    # correspondence matrix scales multiplicatively), NOT the combined
    # node fill.
    assert fills['corr'] == pytest.approx(0.5 * 1.0)


def test_goodput_ratio_flop_weighted_golden():
    fills = {'nodes': 0.5, 'edges': 0.4, 'corr': 0.25}
    stages = {
        'psi1': {'flops': 100},           # edges axis → 0.4
        'initial_corr': {'flops': 300},   # corr axis → 0.25
        'optimizer': {'flops': 50},       # 'none' axis → always useful
    }
    # useful = 100·0.4 + 300·0.25 + 50·1.0 = 165; executed = 450.
    assert goodput.goodput_ratio(fills, stages) \
        == pytest.approx(165 / 450)


def test_goodput_ratio_fallback_is_min_fill():
    fills = {'nodes': 0.5, 'edges': 0.4, 'corr': 0.25}
    # No stage table: the conservative bound is the emptiest axis.
    assert goodput.goodput_ratio(fills) == 0.25
    assert goodput.goodput_ratio(fills, stages={}) == 0.25
    # A stage with no flops/bytes contributes nothing → fallback.
    assert goodput.goodput_ratio(fills, {'psi1': {'ops': 3}}) == 0.25


def test_goodput_ratio_unknown_stage_defaults_to_nodes_axis():
    fills = {'nodes': 0.5, 'edges': 0.9, 'corr': 0.8}
    assert goodput.goodput_ratio(fills, {'mystery': {'flops': 10}}) \
        == pytest.approx(0.5)


def test_row_fills_golden():
    row = {'batch': 2, 'nodes': '8x16', 'edges': '10x20', 'count': 3,
           'real_nodes_s': 24, 'real_nodes_t': 48,
           'real_edges_s': 30, 'real_edges_t': 60}
    fills = goodput.row_fills(row)
    # 6 collations: source nodes 24/48, target nodes 48/96 → both 0.5.
    assert fills['nodes'] == pytest.approx(72 / 144)
    assert fills['edges'] == pytest.approx(90 / 180)
    assert fills['corr'] == pytest.approx(0.5 * 0.5)


def test_row_fills_absent_real_account_is_none():
    # A row that predates the padding_real counter must yield None —
    # absence is honest, never guessed as full.
    assert goodput.row_fills({'batch': 1, 'nodes': '8x8',
                              'edges': '16x16', 'count': 2}) is None
    assert goodput.row_fills({'batch': 1, 'nodes': 'bogus',
                              'edges': '16x16', 'count': 2,
                              'real_nodes_s': 1, 'real_nodes_t': 1,
                              'real_edges_s': 1,
                              'real_edges_t': 1}) is None


def test_merge_real_rows_joins_by_bucket_identity():
    buckets = [{'batch': 1, 'nodes': '8x8', 'edges': '16x16',
                'count': 2},
               {'batch': 2, 'nodes': '4x4', 'edges': '8x8', 'count': 1}]
    reals = [{'batch': 1, 'nodes': '8x8', 'edges': '16x16',
              'axis': 'nodes_s', 'count': 10},
             {'batch': 1, 'nodes': '8x8', 'edges': '16x16',
              'axis': 'edges_t', 'count': 20}]
    merged = goodput.merge_real_rows(buckets, reals)
    assert merged[0]['real_nodes_s'] == 10
    assert merged[0]['real_edges_t'] == 20
    # The unmatched bucket passes through untouched (no real_* keys).
    assert not any(k.startswith('real_') for k in merged[1])


def test_payload_from_rows_aggregate_and_max_pad():
    rows = [
        # Full bucket: fill 1.0 everywhere.
        {'batch': 1, 'nodes': '8x8', 'edges': '4x4', 'count': 1,
         'real_nodes_s': 8, 'real_nodes_t': 8,
         'real_edges_s': 4, 'real_edges_t': 4},
        # Half-full bucket.
        {'batch': 1, 'nodes': '8x8', 'edges': '4x4', 'count': 1,
         'real_nodes_s': 4, 'real_nodes_t': 4,
         'real_edges_s': 2, 'real_edges_t': 2},
    ]
    payload = goodput.payload_from_rows(rows)
    assert payload['composed_with_stage_flops'] is False
    assert len(payload['buckets']) == 2
    assert payload['buckets'][0]['goodput_ratio'] == 1.0
    assert payload['buckets'][0]['pad_fraction'] == 0.0
    # Half-full: node fill 0.5, corr 0.25 → fallback ratio 0.25.
    assert payload['buckets'][1]['goodput_ratio'] == 0.25
    assert payload['buckets'][1]['pad_fraction'] == 0.5
    assert payload['pad_fraction_max'] == 0.5
    # Equal node weight per row → plain mean of the two ratios.
    assert payload['goodput_ratio'] == pytest.approx((1.0 + 0.25) / 2)


def test_payload_without_any_real_account_is_none():
    rows = [{'batch': 1, 'nodes': '8x8', 'edges': '16x16', 'count': 5}]
    # The diff gate's lost-account rule needs absence to STAY absent.
    assert goodput.payload_from_rows(rows) is None
    assert goodput.payload_from_rows([]) is None


def test_registry_roundtrip_recomputes_goodput(monkeypatch):
    """The satellite contract: record_padding's real= totals make pad
    waste recomputable from the recorded tables alone."""
    from dgmc_tpu.obs import registry
    monkeypatch.setattr(registry, 'REGISTRY', registry.Registry())
    registry.record_padding(batch=2, nodes='8x8', edges='4x4',
                            real={'nodes_s': 8, 'nodes_t': 16,
                                  'edges_s': 4, 'edges_t': 8})
    merged = goodput.merge_real_rows(registry.padding_bucket_table(),
                                     registry.padding_real_table())
    payload = goodput.payload_from_rows(merged)
    b = payload['buckets'][0]
    # One collation of batch 2: 16 padded source nodes, 8 real.
    assert b['node_fill'] == pytest.approx(24 / 32)
    assert b['corr_fill'] == pytest.approx(0.5 * 1.0)


def test_goodput_module_is_jax_free():
    import importlib
    import sys
    mod = importlib.import_module('dgmc_tpu.obs.goodput')
    src = open(mod.__file__).read()
    assert 'import jax' not in src
    assert sys.modules['dgmc_tpu.obs.goodput'] is mod
