"""In-graph probe contract tests.

The two halves of the probes contract (obs/probes.py):

1. **Zero overhead disabled** — with the trace-time switch off (the
   default), lowering the jitted train step must produce HLO that is
   byte-identical to a build whose probe call sites are stubbed out
   entirely, and must contain no host callbacks. The probe layer being
   *off* must be indistinguishable from it never having been written.
2. **Full series enabled** — with a sink registered, one executed train
   step streams the whole diagnostic set: correspondence entropy (S0 /
   per-iteration / SL), top-k mass, per-iteration consensus-delta norms,
   gradient global-norm, and per-stage finiteness flags with first-
   offender attribution through the RunObserver.
"""

import collections

import jax
import numpy as np
import pytest

from dgmc_tpu.models import DGMC, GIN, RelCNN
from dgmc_tpu.obs import probes
from dgmc_tpu.ops.graph import GraphBatch
from dgmc_tpu.train import create_train_state, make_train_step
from dgmc_tpu.utils.data import PairBatch


def _side(rng, n, e, c=4, nan=False):
    x = rng.randn(1, n, c).astype(np.float32)
    if nan:
        x[0, 0, 0] = np.nan
    return GraphBatch(
        x=x,
        senders=rng.randint(0, n, (1, e)).astype(np.int32),
        receivers=rng.randint(0, n, (1, e)).astype(np.int32),
        node_mask=np.ones((1, n), bool),
        edge_mask=np.ones((1, e), bool),
        edge_attr=None)


def _fixture(k, nan=False, num_steps=2):
    rng = np.random.RandomState(0)
    batch = PairBatch(s=_side(rng, 8, 16, nan=nan), t=_side(rng, 10, 20),
                      y=(np.arange(8, dtype=np.int32) % 10)[None],
                      y_mask=np.ones((1, 8), bool))
    model = DGMC(RelCNN(4, 8, num_layers=1), RelCNN(4, 4, num_layers=1),
                 num_steps=num_steps, k=k)
    state = create_train_state(model, jax.random.key(0), batch,
                               learning_rate=1e-3)
    return model, state, batch


def _lower_text(model, state, batch):
    step = make_train_step(model)
    return step.lower(state, batch, jax.random.key(1)).as_text()


@pytest.mark.parametrize('k', [-1, 3])
def test_disabled_probes_are_zero_overhead(k, monkeypatch):
    """Probes off: no host callbacks, HLO byte-identical to a build with
    every probe call site stubbed to a no-op (the no-probe baseline)."""
    assert not probes.enabled()
    model, state, batch = _fixture(k)
    off = _lower_text(model, state, batch)
    assert 'callback' not in off, 'disabled probes leaked host callbacks'

    # The no-probe baseline: emit/check_finite physically removed.
    monkeypatch.setattr(probes, 'emit', lambda *a, **kw: None)
    monkeypatch.setattr(probes, 'check_finite', lambda *a, **kw: None)
    baseline = _lower_text(model, state, batch)
    assert off == baseline, ('disabled probes changed the lowered train '
                             'step vs a probe-free build')


@pytest.mark.parametrize('k', [-1, 3])
def test_enabled_probes_lower_callbacks(k):
    model, state, batch = _fixture(k)
    with probes.activated(probes.ProbeLog()):
        on = _lower_text(model, state, batch)
    assert 'callback' in on


@pytest.mark.parametrize('k', [-1, 3])
def test_enabled_probes_stream_full_series(k):
    model, state, batch = _fixture(k)
    log = probes.ProbeLog()
    with probes.activated(log):
        step = make_train_step(model)
        _, out = step(state, batch, jax.random.key(1))
        jax.block_until_ready(out['loss'])

    names = collections.Counter(r['probe'] for r in log.records)
    # S0 + SL + one per consensus iteration.
    assert names['corr_entropy'] == 2 + model.num_steps
    assert names['topk_mass'] == 2
    assert names['consensus_delta'] == model.num_steps
    assert names['grad_norm'] == 1
    # psi1, initial_corr, one per iteration, grad, loss.
    assert names['nonfinite'] == 4 + model.num_steps

    by_iter = [r for r in log.by_name('consensus_delta')]
    assert sorted(r['iteration'] for r in by_iter) == [0, 1]
    for r in log.records:
        assert np.isfinite(r['value'])
        assert r['probe'] != 'nonfinite' or r['value'] == 0.0
    # Probabilities: mass in [0, 1], entropy bounded by log of the row
    # width (dense: N_t, sparse: candidate count).
    for r in log.by_name('topk_mass'):
        assert 0.0 <= r['value'] <= 1.0 + 1e-5
    width = 10 if k == -1 else k
    for r in log.by_name('corr_entropy'):
        assert 0.0 <= r['value'] <= np.log(width) + 1e-5


def test_nonfinite_first_stage_attribution(tmp_path):
    """A NaN in the inputs must be attributed to the FIRST stage that saw
    it (psi1), through the RunObserver's first_nonfinite record."""
    from dgmc_tpu.obs import RunObserver
    model, state, batch = _fixture(k=-1, nan=True)
    obs = RunObserver(str(tmp_path / 'obs'), probes=True)
    with obs:
        step = make_train_step(model)
        with obs.step():
            _, out = step(state, batch, jax.random.key(1))
        jax.block_until_ready(out['loss'])
    assert obs.first_nonfinite is not None
    assert obs.first_nonfinite['stage'] == 'psi1'
    assert not probes.enabled(), 'RunObserver leaked the probe switch'


def test_eval_step_emits_no_probes():
    """Probes document the TRAIN step: an eval forward (train=False) must
    stay probe-free even with the switch on — eval batches polluting the
    aggregates could trip the CI non-finite gate on an eval-only NaN."""
    from dgmc_tpu.train import make_eval_step
    model, state, batch = _fixture(k=-1)
    log = probes.ProbeLog()
    with probes.activated(log):
        eval_step = make_eval_step(model)
        out = eval_step(state, batch, jax.random.key(1))
        jax.block_until_ready(out['correct'])
    assert log.records == []


def test_nonfinite_attribution_uses_pipeline_order_not_arrival():
    """Callbacks are unordered: a later-arriving check from an EARLIER
    pipeline stage must win the first-offender slot within a step."""
    from dgmc_tpu.obs import RunObserver
    obs = RunObserver.__new__(RunObserver)
    import collections
    import threading
    obs.enabled = False
    obs._probe_lock = threading.Lock()
    obs._probe_agg = probes.Aggregator()
    obs._probe_records = collections.deque(maxlen=10)
    obs._probe_seen = 0
    obs.flight = None
    obs._step_index = 0
    obs.first_nonfinite = None
    from dgmc_tpu.obs.observe import MetricLogger
    obs._metrics = MetricLogger(None)
    # grad's callback lands first, psi1's second — psi1 must win.
    obs._on_probe({'probe': 'nonfinite', 'value': 1.0, 'time': 0.0,
                   'stage': 'grad', 'order': 1001})
    obs._on_probe({'probe': 'nonfinite', 'value': 1.0, 'time': 0.0,
                   'stage': 'psi1', 'order': 0})
    assert obs.first_nonfinite['stage'] == 'psi1'
    # ...but an earlier STEP always beats a lower order.
    obs._step_index = 3
    obs._on_probe({'probe': 'nonfinite', 'value': 1.0, 'time': 0.0,
                   'stage': 'psi1', 'order': 0})
    assert obs.first_nonfinite['step'] == 0


def test_probe_metric_helpers():
    import jax.numpy as jnp
    uniform = jnp.full((1, 4, 8), 1.0 / 8)
    np.testing.assert_allclose(float(probes.entropy(uniform)), np.log(8),
                               rtol=1e-6)
    onehot = jax.nn.one_hot(jnp.zeros((1, 4), jnp.int32), 8)
    np.testing.assert_allclose(float(probes.entropy(onehot)), 0.0,
                               atol=1e-6)
    np.testing.assert_allclose(float(probes.topk_mass(uniform, 2)), 0.25,
                               rtol=1e-6)
    np.testing.assert_allclose(float(probes.topk_mass(onehot, 2)), 1.0,
                               rtol=1e-6)
    # Row mask drops padded rows from the mean.
    mask = jnp.array([[True, True, False, False]])
    mixed = jnp.concatenate([uniform[:, :2], onehot[:, 2:]], axis=1)
    np.testing.assert_allclose(float(probes.entropy(mixed, mask)),
                               np.log(8), rtol=1e-6)
    np.testing.assert_allclose(
        float(probes.delta_norm(uniform, uniform)), 0.0, atol=1e-7)


def test_emit_thunk_not_evaluated_when_disabled():
    """The lazy-value contract: a disabled emit must not even evaluate
    its thunk (that is what keeps the metric math out of the HLO)."""
    assert not probes.enabled()
    calls = []
    probes.emit('x', lambda: calls.append(1))
    assert calls == []


def test_gin_backbone_probes_smoke():
    """Probes ride along any backbone, not just RelCNN."""
    rng = np.random.RandomState(2)
    batch = PairBatch(s=_side(rng, 6, 12), t=_side(rng, 6, 12),
                      y=np.arange(6, dtype=np.int32)[None],
                      y_mask=np.ones((1, 6), bool))
    model = DGMC(GIN(4, 8, num_layers=1), GIN(4, 4, num_layers=1),
                 num_steps=1, k=-1)
    state = create_train_state(model, jax.random.key(0), batch,
                               learning_rate=1e-3)
    log = probes.ProbeLog()
    with probes.activated(log):
        step = make_train_step(model)
        _, out = step(state, batch, jax.random.key(1))
        jax.block_until_ready(out['loss'])
    assert log.by_name('corr_entropy') and log.by_name('grad_norm')
