"""StepTimer contract: misuse raises clearly, percentiles interpolate."""

import pytest

from dgmc_tpu.obs import StepTimer
from dgmc_tpu.obs.observe import percentile


def test_stop_without_start_raises():
    t = StepTimer()
    with pytest.raises(RuntimeError, match='start'):
        t.stop()


def test_double_stop_raises():
    t = StepTimer()
    t.start()
    t.stop()
    with pytest.raises(RuntimeError, match='start'):
        t.stop()


def test_p50_interpolates_even_windows():
    t = StepTimer()
    t.times = [0.1, 0.2, 0.3, 0.4]
    s = t.summary()
    assert s['p50_s'] == pytest.approx(0.25)   # mean of the middle pair
    assert s['p95_s'] == pytest.approx(0.1 + 0.95 * 0.3)
    assert s['max_s'] == pytest.approx(0.4)
    assert s['total_s'] == pytest.approx(1.0)


def test_p50_odd_window_is_exact_middle():
    t = StepTimer()
    t.times = [0.3, 0.1, 0.2]
    assert t.summary()['p50_s'] == pytest.approx(0.2)


def test_percentile_bounds():
    ts = [1.0, 2.0, 3.0]
    assert percentile(ts, 0.0) == 1.0
    assert percentile(ts, 1.0) == 3.0
    with pytest.raises(ValueError):
        percentile([], 0.5)


def test_fence_forces_value():
    import jax.numpy as jnp
    t = StepTimer()
    t.start()
    dt = t.stop(fence=jnp.ones(()).sum())
    assert dt > 0 and t.summary()['steps'] == 1
