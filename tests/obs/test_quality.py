"""Quality plane contract: the pinned quality.json schema, deterministic
shadow-audit sampling, and the accuracy diff gates' teeth."""

import json
import os

from dgmc_tpu.models.evalsum import eval_summary
from dgmc_tpu.obs import diff as diff_mod
from dgmc_tpu.obs.live import prometheus_exposition
from dgmc_tpu.obs.quality import (AUDIT_TRACE_ID_CAP, QUALITY_SIGNALS,
                                  QualityTracker, audit_keep)
from tests.obs.test_diff import write_run
from tests.obs.test_live import parse_exposition


# ---------------------------------------------------------------------------
# eval_summary (the shared helper every experiment CLI routes through)
# ---------------------------------------------------------------------------

def test_eval_summary_normalizes_counts():
    s = eval_summary(200, loss=1.25, hits1=100, hits10=150)
    assert s == {'count': 200.0, 'loss': 1.25, 'hits1': 0.5,
                 'hits10': 0.75}


def test_eval_summary_empty_split_is_zero_not_nan():
    s = eval_summary(0, hits1=0)
    assert s['hits1'] == 0.0
    # ...but the empty account stays visible through count.
    assert s['count'] == 0.0


# ---------------------------------------------------------------------------
# quality.json schema pin
# ---------------------------------------------------------------------------

def _fed_tracker():
    t = QualityTracker()
    t.observe_eval('dbp15k', eval_summary(100, loss=2.0, hits1=40,
                                          hits10=70), step=10)
    t.observe_eval('dbp15k', eval_summary(100, loss=1.0, hits1=55,
                                          hits10=80), step=20)
    for i, v in enumerate([1.0, 0.4, 0.1, 0.01]):
        t.observe_consensus(i, v)
    t.observe_query({'entropy': 1.2, 'margin': 0.3, 'correction': 0.05,
                     'saturation': 0.1, 'saturated_frac': 0.0})
    t.record_low_confidence()
    t.set_audit_params(0.5, seed=7)
    t.observe_audit('aa' * 16, 1.0, exact=True)
    return t


def test_payload_keyset_is_pinned():
    """The exact keyset at every level of quality.json. Additions are
    fine — but they must bump QUALITY_SCHEMA_VERSION and this pin
    together, because obs.report / obs.diff / obs.timeline and the CI
    artifact uploads all parse these fields by name."""
    p = _fed_tracker().payload()
    assert set(p) == {'schema', 'headline', 'scenarios', 'consensus',
                      'serve'}
    assert set(p['headline']) == {'scenario', 'step', 'metrics'}
    assert set(p['scenarios']) == {'dbp15k'}
    sc = p['scenarios']['dbp15k']
    assert set(sc) == {'evals', 'count', 'step', 'metrics'}
    assert set(sc['metrics']) == {'loss', 'hits1', 'hits10'}
    for m in sc['metrics'].values():
        assert set(m) == {'first', 'last', 'best'}
    assert set(p['consensus']) == {'events', 'iterations',
                                   'per_iteration', 'tol',
                                   'converged_at', 'first_mean',
                                   'final_mean'}
    for slot in p['consensus']['per_iteration'].values():
        assert set(slot) == {'count', 'mean', 'last'}
    assert set(p['serve']) == {'queries', 'low_confidence',
                               'saturated_queries', 'signals', 'audit'}
    assert set(p['serve']['signals']) == set(QUALITY_SIGNALS)
    for snap in p['serve']['signals'].values():
        assert snap is None or set(snap) == {'count', 'mean', 'p50',
                                             'p95'}
    assert set(p['serve']['audit']) == {'sample_rate', 'seed',
                                        'audited', 'exact',
                                        'recall_mean', 'recall_min',
                                        'trace_ids', 'truncated'}
    json.dumps(p)  # the artifact must serialize as-is


def test_first_last_best_are_metric_aware():
    p = _fed_tracker().payload()
    m = p['scenarios']['dbp15k']['metrics']
    assert m['hits1'] == {'first': 0.4, 'last': 0.55, 'best': 0.55}
    # loss improves DOWNWARD: best is the minimum.
    assert m['loss'] == {'first': 2.0, 'last': 1.0, 'best': 1.0}
    assert p['headline']['metrics']['hits1'] == 0.55
    assert p['headline']['step'] == 20


def test_consensus_convergence_account():
    p = _fed_tracker().payload()
    c = p['consensus']
    assert c['events'] == 4 and c['iterations'] == 4
    assert c['first_mean'] == 1.0 and c['final_mean'] == 0.01
    # tol 0.05: iteration 3 (0.01 <= 0.05 * 1.0) is the first under it.
    assert c['converged_at'] == 3


def test_nonfinite_metrics_never_enter_the_account():
    t = QualityTracker()
    t.observe_eval('x', {'count': 10, 'hits1': float('nan'),
                         'loss': float('inf'), 'mrr': 0.5})
    m = t.payload()['scenarios']['x']['metrics']
    assert set(m) == {'mrr'}


# ---------------------------------------------------------------------------
# shadow-audit sampling determinism
# ---------------------------------------------------------------------------

def test_audit_keep_is_deterministic_and_seeded():
    ids = [f'{i:032x}' for i in range(400)]
    kept = [t for t in ids if audit_keep(7, t, 0.25)]
    # Byte-identical across calls: a pure function of (seed, id, rate).
    assert kept == [t for t in ids if audit_keep(7, t, 0.25)]
    # The rate actually thins (loose bounds; the hash is uniform).
    assert 0 < len(kept) < len(ids)
    # A different seed audits a DIFFERENT set — replicas can decorrelate.
    assert kept != [t for t in ids if audit_keep(8, t, 0.25)]
    # Edge rates short-circuit.
    assert all(audit_keep(7, t, 1.0) for t in ids)
    assert not any(audit_keep(7, t, 0.0) for t in ids)


def test_audit_trace_ids_are_capped():
    t = QualityTracker()
    for i in range(AUDIT_TRACE_ID_CAP + 10):
        t.observe_audit(f'{i:032x}', 1.0, exact=True)
    audit = t.payload()['serve']['audit']
    assert len(audit['trace_ids']) == AUDIT_TRACE_ID_CAP
    assert audit['truncated'] == 10
    assert audit['audited'] == AUDIT_TRACE_ID_CAP + 10
    assert audit['recall_min'] == 1.0


# ---------------------------------------------------------------------------
# RunObserver integration: quality.json + /status + exposition
# ---------------------------------------------------------------------------

def test_flush_writes_quality_json(tmp_path):
    from dgmc_tpu.obs.run import RunObserver
    obs = RunObserver(str(tmp_path / 'obs'))
    obs.quality_eval('willow', eval_summary(50, hits1=30), step=3)
    obs.quality_eval('willow', hits1=0.7, step=4)  # kwargs form
    obs.flush()
    with open(tmp_path / 'obs' / 'quality.json') as f:
        payload = json.load(f)
    m = payload['scenarios']['willow']['metrics']['hits1']
    assert m == {'first': 0.6, 'last': 0.7, 'best': 0.7}
    assert payload['headline']['metrics'] == {'hits1': 0.7}
    obs.close()


def test_disabled_observer_quality_is_noop():
    from dgmc_tpu.obs.run import RunObserver
    obs = RunObserver(None)
    assert obs.quality is None
    obs.quality_eval('x', hits1=0.5)  # must not raise
    obs.close()


def test_status_carries_quality_and_sections(tmp_path):
    from dgmc_tpu.obs.run import RunObserver
    obs = RunObserver(str(tmp_path / 'obs'))
    obs.add_status_section('qtrace', lambda: {'queries': 3})
    obs.add_status_section('broken', lambda: 1 / 0)
    st = obs.status()
    # The timing account keeps its top-level keys (scrape compat)...
    assert 'compile' in st and 'steps' in st
    # ...and the quality block plus registered sections join it.
    assert st['quality']['schema'] >= 1
    assert st['qtrace'] == {'queries': 3}
    assert 'error' in st['broken']  # degrade, don't 500 the scrape
    obs.close()


def test_metric_families_render_strict_exposition():
    fams = _fed_tracker().metric_families()
    parsed = parse_exposition(prometheus_exposition(fams))
    hist = parsed['dgmc_query_quality']
    assert hist['type'] == 'histogram'
    signals = {lbl['signal'] for _, lbl, _ in hist['samples']}
    assert signals == set(QUALITY_SIGNALS)
    assert parsed['dgmc_quality_low_confidence_total']['samples'][0][2] \
        == 1.0
    assert parsed['dgmc_quality_audited_total']['samples'][0][2] == 1.0
    assert parsed['dgmc_quality_audit_recall_min']['samples'][0][2] \
        == 1.0


# ---------------------------------------------------------------------------
# obs.diff accuracy gates
# ---------------------------------------------------------------------------

def _write_quality(run_dir, hits1=None, scenario='dbp15k'):
    t = QualityTracker()
    if hits1 is not None:
        t.observe_eval(scenario, {'count': 100, 'hits1': hits1}, step=1)
    with open(os.path.join(run_dir, 'quality.json'), 'w') as f:
        json.dump(t.payload(), f)


def test_hits1_unconfigured_is_informational(tmp_path):
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    _write_quality(a, hits1=0.9)
    _write_quality(b, hits1=0.1)  # an 89% collapse...
    # ...passes without the gates configured: quality gating is opt-in
    # per invocation, like --min-overlap.
    assert diff_mod.main([a, b]) == 0


def test_max_hits1_regression_gate_fires(tmp_path, capsys):
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    _write_quality(a, hits1=0.90)
    _write_quality(b, hits1=0.80)  # -11.1% relative
    assert diff_mod.main([a, b, '--max-hits1-regression', '0.05']) == 1
    assert 'hits1' in capsys.readouterr().out
    # The same pair clears a looser bound, and improvement passes.
    assert diff_mod.main([a, b, '--max-hits1-regression', '0.2']) == 0
    assert diff_mod.main([b, a, '--max-hits1-regression', '0.05']) == 0


def test_min_hits1_absolute_floor(tmp_path, capsys):
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    _write_quality(a, hits1=0.90)
    _write_quality(b, hits1=0.80)
    assert diff_mod.main([a, b, '--min-hits1', '0.85']) == 1
    assert 'floor' in capsys.readouterr().out
    assert diff_mod.main([a, b, '--min-hits1', '0.5']) == 0
    # The floor judges the CANDIDATE alone: even an improving run
    # under it fails (the paper-faithfulness bar is absolute).
    assert diff_mod.main([b, a, '--min-hits1', '0.95']) == 1


def test_lost_quality_account_fails(tmp_path, capsys):
    """A candidate that stopped emitting the quality account must FAIL
    the diff — vanished numbers are the easiest regression to ship."""
    a = write_run(tmp_path, 'a')
    b = write_run(tmp_path, 'b')
    _write_quality(a, hits1=0.9)
    assert diff_mod.main([a, b]) == 1
    assert 'missing from candidate' in capsys.readouterr().out
    # Baseline never measured quality: skip, not fail.
    assert diff_mod.main([b, a]) == 0
