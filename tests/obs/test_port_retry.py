"""Port-in-use degradation: the live plane MOVES to an ephemeral port
instead of dropping, re-advertises through heartbeat.json, and the
supervisor-side scrape follows the moved endpoint. Two processes: the
squatter owning the requested port is a real separate process, like the
lingering predecessor worker this bugfix exists for."""

import json
import os
import subprocess
import sys
import time

from dgmc_tpu.obs.live import probe_healthz
from dgmc_tpu.obs.run import RunObserver

#: Child that binds a port, reports it, and holds it until killed.
SQUATTER = r'''
import socket, sys, time
s = socket.socket()
s.bind(("", 0))
s.listen(1)
print(s.getsockname()[1], flush=True)
time.sleep(60)
'''


def test_plane_moves_and_heartbeat_readvertises(tmp_path):
    squatter = subprocess.Popen([sys.executable, '-c', SQUATTER],
                                stdout=subprocess.PIPE, text=True)
    try:
        taken = int(squatter.stdout.readline())
        obs = RunObserver(str(tmp_path), obs_port=taken,
                          watchdog_deadline_s=60)
        try:
            # The plane survived on a DIFFERENT (ephemeral) port.
            assert obs.live_port is not None
            assert obs.live_port != taken
            # heartbeat.json advertises the MOVED port...
            hb_path = os.path.join(str(tmp_path), 'heartbeat.json')
            deadline = time.time() + 10
            hb = {}
            while time.time() < deadline:
                try:
                    with open(hb_path) as f:
                        hb = json.load(f)
                    if hb.get('port'):
                        break
                except (OSError, ValueError):
                    pass
                time.sleep(0.1)
            assert hb.get('port') == obs.live_port
            # ...and the supervisor-style scrape at the advertised port
            # reaches a healthy plane (this is exactly the discovery
            # path Supervisor._healthz_verdict walks).
            res = probe_healthz(hb['port'])
            assert res is not None
            code, payload = res
            assert code == 200 and payload['healthy']
            assert payload['pid'] == os.getpid()
        finally:
            obs.close()
    finally:
        squatter.kill()
        squatter.wait()


def test_ephemeral_request_unaffected(tmp_path):
    obs = RunObserver(str(tmp_path), obs_port=0)
    try:
        assert obs.live_port
        assert probe_healthz(obs.live_port) is not None
    finally:
        obs.close()
