"""bench.py timeout diagnosability: the SIGTERM/SIGALRM path emits a
partial JSON line with per-section progress instead of dying silently
(the BENCH_r05 ``rc: 124, parsed: null`` failure mode)."""

import json
import os
import signal
import time

import pytest


@pytest.fixture
def bench_mod():
    import bench
    saved = dict(bench._PROGRESS)
    saved_timeout = dict(bench._SECTION_TIMEOUT)
    bench._PROGRESS.update(sections={}, current=None, current_t0=None,
                           in_body=False, start=time.time())
    bench._SECTION_TIMEOUT['seconds'] = 0.0
    yield bench
    bench._PROGRESS.clear()
    bench._PROGRESS.update(saved)
    bench._SECTION_TIMEOUT.update(saved_timeout)


def test_sections_record_success_and_failure(bench_mod):
    with bench_mod._section('good'):
        pass
    with pytest.raises(RuntimeError):
        with bench_mod._section('bad'):
            raise RuntimeError('boom')
    secs = bench_mod._PROGRESS['sections']
    assert secs['good']['ok'] is True
    assert secs['bad']['ok'] is False and 'boom' in secs['bad']['error']
    assert bench_mod._PROGRESS['current'] is None


def test_partial_line_on_sigterm(bench_mod, monkeypatch, capsys):
    exit_codes = []
    monkeypatch.setattr(os, '_exit', lambda code: exit_codes.append(code))
    with bench_mod._section('sparse_f32'):
        pass
    # Simulate the signal landing mid-section.
    bench_mod._PROGRESS['current'] = 'dense_f32'
    bench_mod._PROGRESS['current_t0'] = time.perf_counter()
    bench_mod._emit_partial(signal.SIGTERM, None)

    assert exit_codes == [124]
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec['partial'] is True
    assert rec['signal'] == 'SIGTERM'
    assert rec['value'] is None
    assert rec['sections']['sparse_f32']['ok'] is True
    assert rec['current']['name'] == 'dense_f32'
    assert rec['current']['elapsed_s'] >= 0


def test_section_timeout_swallowed_and_recorded(bench_mod, monkeypatch):
    """A section exceeding its --section-timeout budget is recorded as
    timed out and the run MOVES ON (SectionTimeout swallowed); leg
    variables keep their pre-section None, later sections still run."""
    import signal
    monkeypatch.setattr(os, '_exit',
                        lambda code: (_ for _ in ()).throw(
                            SystemExit(code)))
    prev_alrm = signal.getsignal(signal.SIGALRM)
    prev_term = signal.getsignal(signal.SIGTERM)
    bench_mod._install_signal_handlers()
    bench_mod._SECTION_TIMEOUT['seconds'] = 0.2
    try:
        result = None
        with bench_mod._section('stuck'):
            time.sleep(5)               # SIGALRM interrupts this sleep
            result = 'completed'        # never reached
        assert result is None
        rec = bench_mod._PROGRESS['sections']['stuck']
        assert rec['ok'] is False and rec['timeout'] is True
        assert 'section-timeout' in rec['error'] or 'timeout' in \
            rec['error']
        # The run proceeds: the next section completes normally.
        with bench_mod._section('next'):
            pass
        assert bench_mod._PROGRESS['sections']['next']['ok'] is True
    finally:
        signal.setitimer(signal.ITIMER_REAL, 0)
        signal.signal(signal.SIGALRM, prev_alrm)
        signal.signal(signal.SIGTERM, prev_term)


def test_external_alarm_mid_body_before_budget_kills_with_partial(
        bench_mod, monkeypatch, capsys):
    """An EXTERNAL SIGALRM (timeout -s ALRM) landing inside a budgeted
    section body BEFORE the budget elapsed must not be swallowed as a
    fake section timeout — it is the kill, with evidence."""
    import signal
    exit_codes = []
    monkeypatch.setattr(os, '_exit', lambda code: exit_codes.append(code))
    bench_mod._SECTION_TIMEOUT['seconds'] = 600.0
    bench_mod._PROGRESS['current'] = 'sparse_f32'
    bench_mod._PROGRESS['current_t0'] = time.perf_counter()  # just began
    bench_mod._PROGRESS['in_body'] = True
    bench_mod._on_signal(signal.SIGALRM, None)
    assert exit_codes == [124]
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec['partial'] is True and rec['signal'] == 'SIGALRM'


def test_alarm_outside_section_still_emits_partial(bench_mod,
                                                   monkeypatch, capsys):
    """--section-timeout must not hijack an EXTERNAL SIGALRM landing
    between sections: that is still the kill-with-evidence path."""
    import signal
    exit_codes = []
    monkeypatch.setattr(os, '_exit', lambda code: exit_codes.append(code))
    bench_mod._SECTION_TIMEOUT['seconds'] = 30.0
    with bench_mod._section('done_one'):
        pass
    bench_mod._on_signal(signal.SIGALRM, None)
    assert exit_codes == [124]
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec['partial'] is True and rec['signal'] == 'SIGALRM'
    assert rec['sections']['done_one']['ok'] is True


def test_section_emits_stderr_progress_line(bench_mod, capsys):
    with bench_mod._section('leg'):
        pass
    err = capsys.readouterr().err
    rec = json.loads([ln for ln in err.splitlines()
                      if ln.startswith('{')][-1])
    assert rec['section'] == 'leg' and rec['ok'] is True


def test_obs_section_logging(bench_mod, tmp_path, monkeypatch):
    """With --obs-dir, each finished section lands in metrics.jsonl and
    flushes the artifacts."""
    from dgmc_tpu.obs import RunObserver
    obs = RunObserver(str(tmp_path / 'obs'))
    monkeypatch.setattr(bench_mod, '_OBS', obs)
    with bench_mod._section('topk_scan'):
        pass
    obs.close()
    recs = [json.loads(ln) for ln in
            (tmp_path / 'obs' / 'metrics.jsonl').read_text().splitlines()]
    assert recs and recs[0]['step'] == 'topk_scan' and recs[0]['ok'] is True
