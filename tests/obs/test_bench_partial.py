"""bench.py timeout diagnosability: the SIGTERM/SIGALRM path emits a
partial JSON line with per-section progress instead of dying silently
(the BENCH_r05 ``rc: 124, parsed: null`` failure mode)."""

import json
import os
import signal
import time

import pytest


@pytest.fixture
def bench_mod():
    import bench
    saved = dict(bench._PROGRESS)
    bench._PROGRESS.update(sections={}, current=None, current_t0=None,
                           start=time.time())
    yield bench
    bench._PROGRESS.clear()
    bench._PROGRESS.update(saved)


def test_sections_record_success_and_failure(bench_mod):
    with bench_mod._section('good'):
        pass
    with pytest.raises(RuntimeError):
        with bench_mod._section('bad'):
            raise RuntimeError('boom')
    secs = bench_mod._PROGRESS['sections']
    assert secs['good']['ok'] is True
    assert secs['bad']['ok'] is False and 'boom' in secs['bad']['error']
    assert bench_mod._PROGRESS['current'] is None


def test_partial_line_on_sigterm(bench_mod, monkeypatch, capsys):
    exit_codes = []
    monkeypatch.setattr(os, '_exit', lambda code: exit_codes.append(code))
    with bench_mod._section('sparse_f32'):
        pass
    # Simulate the signal landing mid-section.
    bench_mod._PROGRESS['current'] = 'dense_f32'
    bench_mod._PROGRESS['current_t0'] = time.perf_counter()
    bench_mod._emit_partial(signal.SIGTERM, None)

    assert exit_codes == [124]
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec['partial'] is True
    assert rec['signal'] == 'SIGTERM'
    assert rec['value'] is None
    assert rec['sections']['sparse_f32']['ok'] is True
    assert rec['current']['name'] == 'dense_f32'
    assert rec['current']['elapsed_s'] >= 0


def test_obs_section_logging(bench_mod, tmp_path, monkeypatch):
    """With --obs-dir, each finished section lands in metrics.jsonl and
    flushes the artifacts."""
    from dgmc_tpu.obs import RunObserver
    obs = RunObserver(str(tmp_path / 'obs'))
    monkeypatch.setattr(bench_mod, '_OBS', obs)
    with bench_mod._section('topk_scan'):
        pass
    obs.close()
    recs = [json.loads(ln) for ln in
            (tmp_path / 'obs' / 'metrics.jsonl').read_text().splitlines()]
    assert recs and recs[0]['step'] == 'topk_scan' and recs[0]['ok'] is True
