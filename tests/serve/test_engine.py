"""Match engine: serving answers are bit-identical to a full in-graph
forward under the same checkpoint, across corpus-placement tiers, and
deterministic across repeats. Also pins DGMC's precomputed-table
argument contract."""

import jax
import numpy as np
import pytest

from dgmc_tpu.models import DGMC, RelCNN
from dgmc_tpu.serve.client import sample_query
from dgmc_tpu.serve.corpus import (CorpusIndex, compute_embeddings,
                                   synthetic_corpus)
from dgmc_tpu.serve.engine import MatchEngine
from dgmc_tpu.serve.router import QueryRouter

FEAT, K = 12, 5


def _setup(offload=False, num_steps=2):
    corpus = synthetic_corpus(64, 200, FEAT, seed=0)
    psi_1 = RelCNN(FEAT, 16, 2, batch_norm=False, cat=True, lin=True,
                   dropout=0.0)
    psi_2 = RelCNN(8, 8, 1, batch_norm=False, cat=True, lin=True,
                   dropout=0.0)
    model = DGMC(psi_1, psi_2, num_steps=num_steps, k=K)
    g_t = corpus.graph_batch(dummy_x=False)
    g_q, _ = sample_query(corpus.x, 6, 14, seed=1)
    from dgmc_tpu.utils.data import pad_graphs
    q = pad_graphs([g_q], 8, 16)
    key = jax.random.key(0)
    variables = model.init(
        {'params': key, 'noise': key, 'negatives': key, 'dropout': key},
        q, g_t, train=False)
    h_t = compute_embeddings(psi_1, variables['params']['psi_1'], corpus)
    index = CorpusIndex(corpus, h_t, {})
    router = QueryRouter([(8, 16)], corpus.num_nodes, corpus.num_edges)
    engine = MatchEngine(model, variables, index, router, max_results=3,
                         noise_seed=9, offload=offload, offload_chunk=16)
    engine.warm()
    return model, variables, g_t, engine, g_q


def _reference_answer(model, variables, engine, g_q):
    """The full in-graph COMPILED forward (ψ₁ both sides, in-graph
    search) at the engine's padded shape and noise key — what serving
    must equal bitwise. Jitted like the engine's executable: eager
    op-by-op dispatch reassociates float reductions differently from
    any fused program, so eager-vs-compiled is the one comparison that
    legitimately differs in the last ulp."""
    bucket = engine.router.route(g_q.num_nodes, g_q.num_edges)
    from dgmc_tpu.utils.data import pad_graphs
    q = pad_graphs([g_q], bucket.nodes, bucket.edges)
    g_t = engine.index.corpus.graph_batch(dummy_x=False)

    @jax.jit
    def full(variables, q, g_t, key):
        S_0, S_L = model.apply(variables, q, g_t, train=False,
                               rngs={'noise': key})
        v, p = jax.lax.top_k(S_L.val, 3)
        return v, jax.numpy.take_along_axis(S_L.idx, p, axis=-1)

    v, i = full(variables, q, g_t, jax.random.key(9))
    n = g_q.num_nodes
    return np.asarray(v)[0, :n], np.asarray(i)[0, :n]


@pytest.mark.parametrize('offload', [False, True])
def test_engine_equals_full_forward(offload):
    model, variables, g_t, engine, g_q = _setup(offload=offload)
    answer = engine.match(g_q)
    ref_v, ref_i = _reference_answer(model, variables, engine, g_q)
    got_i = np.array([[c[0] for c in m['candidates']]
                      for m in answer['matches']])
    got_v = np.array([[c[1] for c in m['candidates']]
                      for m in answer['matches']], np.float32)
    np.testing.assert_array_equal(got_i, ref_i)
    np.testing.assert_array_equal(got_v, ref_v.astype(np.float32))


@pytest.mark.parametrize('offload', [False, True])
def test_engine_deterministic_repeats(offload):
    _, _, _, engine, g_q = _setup(offload=offload)
    a = engine.match(g_q)
    b = engine.match(g_q)
    assert a == b
    assert engine.query_count == 2


@pytest.mark.parametrize('offload', [False, True])
def test_query_path_is_execute_only_after_warm(offload):
    """The zero-per-query-compile contract at the engine layer: after
    warm(), a query triggers NO compile event — including the offload
    tier's host-driven merge step (_corpus_merge is jitted per shape
    and must compile during warm(), not on the first live query after
    a restart)."""
    from dgmc_tpu.obs.registry import CompileWatcher
    from dgmc_tpu.ops.offload import _corpus_merge
    _corpus_merge.cache_clear()     # a prior test must not pre-warm it
    _, _, _, engine, g_q = _setup(offload=offload)
    with CompileWatcher() as w:
        engine.match(g_q)
        first = w.count()
        engine.match(g_q)
    assert first == 0, [e.get('key') for e in w.events]
    assert w.count() == 0


def test_device_and_offload_tiers_agree():
    _, _, _, dev_engine, g_q = _setup(offload=False)
    _, _, _, off_engine, _ = _setup(offload=True)
    a = dev_engine.match(g_q)
    b = off_engine.match(g_q)
    assert a['matches'] == b['matches']


def test_dense_engine_rejected():
    corpus = synthetic_corpus(16, 30, FEAT, seed=0)
    psi_1 = RelCNN(FEAT, 8, 1, batch_norm=False)
    psi_2 = RelCNN(4, 4, 1, batch_norm=False)
    model = DGMC(psi_1, psi_2, num_steps=1, k=-1)
    router = QueryRouter([(8, 16)], 16, 30)
    with pytest.raises(ValueError, match='sparse'):
        MatchEngine(model, {}, CorpusIndex(corpus, np.zeros((1, 16, 8)),
                                           {}), router)


def test_feature_width_mismatch_rejected():
    _, _, _, engine, _ = _setup()
    from dgmc_tpu.utils.data import Graph
    bad = Graph(edge_index=np.zeros((2, 0), np.int64),
                x=np.ones((4, FEAT + 1), np.float32))
    with pytest.raises(ValueError, match='feature width'):
        engine.match(bad)


def test_model_rejects_bad_precomputed_args():
    corpus = synthetic_corpus(16, 30, FEAT, seed=0)
    psi_1 = RelCNN(FEAT, 8, 1, batch_norm=False)
    psi_2 = RelCNN(4, 4, 1, batch_norm=False)
    g = corpus.graph_batch(dummy_x=False)
    key = jax.random.key(0)
    sparse = DGMC(psi_1, psi_2, num_steps=1, k=3)
    variables = sparse.init(
        {'params': key, 'noise': key, 'negatives': key, 'dropout': key},
        g, g, train=False)
    S_idx = np.zeros((1, 16, 3), np.int32)
    cand = np.zeros((1, 16, 3, 8), np.float32)
    with pytest.raises(ValueError, match='train=False'):
        sparse.apply(variables, g, g, train=True, S_idx=S_idx,
                     rngs={'noise': key, 'negatives': key,
                           'dropout': key})
    with pytest.raises(ValueError, match='meaningless without'):
        sparse.apply(variables, g, g, train=False, h_t_cand=cand,
                     rngs={'noise': key})
    with pytest.raises(ValueError, match='candidates but the model'):
        sparse.apply(variables, g, g, train=False,
                     S_idx=np.zeros((1, 16, 4), np.int32),
                     h_t_cand=np.zeros((1, 16, 4, 8), np.float32),
                     rngs={'noise': key})
    with pytest.raises(ValueError, match='sparse variant'):
        # The dense variant has no shortlist: precomputed candidate
        # args must be refused outright.
        DGMC(psi_1, psi_2, num_steps=1, k=-1).apply(
            variables, g, g, train=False, S_idx=S_idx,
            rngs={'noise': key})
