"""ShadowAuditor counter discipline — the genuine CON501 finding this
PR's concurrency lint tier surfaced, pinned as a regression test.

``audited``/``errors`` were incremented off-thread with no lock while
``dropped`` was guarded by ``_cond``; today a single audit thread made
the ``+=`` non-lossy in practice, but the counters are read from
serving/main threads (service gauges, close-time accounting) and the
moment a second audit worker lands (ROADMAP replica fleet) the unlocked
read-modify-write loses counts. All three counters now move under
``_cond``; the lint gate (CON501 at error severity over serve/) keeps
it that way."""

import threading

import numpy as np
import pytest

from dgmc_tpu.serve.audit import ShadowAuditor


class _Router:
    def route(self, num_nodes, num_edges):
        return 'bucket'

    def signature(self, bucket):
        return 'bucket'

    def pad_query(self, graph, bucket):
        return graph


class _Engine:
    """The minimal surface _audit_one touches; exhaustive_topk raises
    on marked queries to drive the errors counter."""

    def __init__(self):
        self.router = _Router()
        self._exec = {'bucket': object()}

    def exhaustive_topk(self, graph, info):
        if graph.poison:
            raise RuntimeError('audit boom')
        return np.array([[[0, 1]]])      # [1, n_real=1, k=2]


class _Graph:
    num_nodes = 1
    num_edges = 1

    def __init__(self, poison):
        self.poison = poison


class _Tracker:
    def __init__(self):
        self.calls = []
        self._lock = threading.Lock()

    def observe_audit(self, trace_id, recall, exact):
        with self._lock:
            self.calls.append((trace_id, recall, exact))


@pytest.mark.parametrize('n_ok,n_bad', [(40, 0), (25, 15)])
def test_audited_and_errors_counts_are_exact(n_ok, n_bad):
    tracker = _Tracker()
    auditor = ShadowAuditor(_Engine(), tracker, sample_rate=1.0,
                            seed=0, capacity=1024)
    try:
        info = {'shortlist_idx': [[0, 1]]}
        submitted = 0
        for i in range(n_ok + n_bad):
            ok = auditor.maybe_submit(f'q{i:03d}', _Graph(i >= n_ok),
                                      info)
            submitted += bool(ok)
        assert submitted == n_ok + n_bad     # sample_rate 1.0 keeps all
        assert auditor.drain(timeout_s=60.0)
        # Exact accounting: every submission lands in exactly one
        # counter, none lost to an unlocked increment.
        with auditor._cond:
            audited, errors, dropped = (auditor.audited, auditor.errors,
                                        auditor.dropped)
        assert audited == n_ok
        assert errors == n_bad
        assert dropped == 0
        assert len(tracker.calls) == n_ok
        assert all(recall == 1.0 and exact
                   for _, recall, exact in tracker.calls)
    finally:
        auditor.close()


def test_counter_writes_are_lock_guarded_statically():
    """serve/audit.py lints completely clean under the concurrency
    tier — the static face of this regression test."""
    import dgmc_tpu.serve.audit as audit_mod
    from dgmc_tpu.analysis.con_rules import lint_concurrency_file
    findings = lint_concurrency_file(audit_mod.__file__,
                                     rel='dgmc_tpu/serve/audit.py')
    assert findings == [], [f.to_json() for f in findings]
