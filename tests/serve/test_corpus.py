"""Corpus cache: build → persist → verified hit; stale/corrupt caches
rebuild instead of serving wrong embeddings."""

import json
import os

import jax
import numpy as np

from dgmc_tpu.models import RelCNN
from dgmc_tpu.serve.corpus import (CACHE_MANIFEST, CACHE_TABLE,
                                   load_cache, load_or_build,
                                   params_fingerprint, synthetic_corpus)


def _psi1(dim=8, feat=6, seed=0):
    psi = RelCNN(feat, dim, 1, batch_norm=False, cat=True, lin=True,
                 dropout=0.0)
    corpus = synthetic_corpus(20, 40, feat, seed=3)
    g = corpus.graph_batch(dummy_x=False)
    params = psi.init(jax.random.key(seed), g.x, g, train=False)['params']
    return psi, params, corpus


def test_build_then_hit(tmp_path):
    psi, params, corpus = _psi1()
    cache = str(tmp_path / 'cache')
    idx1, info1 = load_or_build(cache, psi, params, corpus,
                                checkpoint_step=7)
    assert info1['cache'].startswith('miss')
    assert os.path.exists(os.path.join(cache, CACHE_TABLE))
    manifest = json.load(open(os.path.join(cache, CACHE_MANIFEST)))
    assert manifest['checkpoint_step'] == 7
    assert CACHE_TABLE in manifest['files']

    idx2, info2 = load_or_build(cache, psi, params, corpus,
                                checkpoint_step=7)
    assert info2['cache'] == 'hit'
    np.testing.assert_array_equal(idx1.h_t, idx2.h_t)


def test_changed_params_rebuild(tmp_path):
    psi, params, corpus = _psi1()
    cache = str(tmp_path / 'cache')
    load_or_build(cache, psi, params, corpus)
    _, params2, _ = _psi1(seed=1)
    assert params_fingerprint(params) != params_fingerprint(params2)
    _, info = load_or_build(cache, psi, params2, corpus)
    assert info['cache'] == 'miss:params-mismatch'
    # ...and the rewritten cache now hits for the NEW params.
    _, info2 = load_or_build(cache, psi, params2, corpus)
    assert info2['cache'] == 'hit'


def test_changed_corpus_rebuild(tmp_path):
    psi, params, corpus = _psi1()
    cache = str(tmp_path / 'cache')
    load_or_build(cache, psi, params, corpus)
    corpus2 = synthetic_corpus(20, 40, corpus.feat_dim, seed=99)
    _, info = load_or_build(cache, psi, params, corpus2)
    assert info['cache'] == 'miss:corpus-mismatch'


def test_corrupt_table_rebuilds(tmp_path):
    psi, params, corpus = _psi1()
    cache = str(tmp_path / 'cache')
    idx, _ = load_or_build(cache, psi, params, corpus)
    table = os.path.join(cache, CACHE_TABLE)
    with open(table, 'r+b') as f:
        f.seek(200)
        f.write(b'\xff\xff\xff\xff')
    h, reason = load_cache(cache, corpus.fingerprint(),
                           params_fingerprint(params))
    assert h is None and reason == f'sha256-mismatch:{CACHE_TABLE}'
    idx2, info = load_or_build(cache, psi, params, corpus)
    assert info['cache'] == 'miss:' + reason
    np.testing.assert_array_equal(idx.h_t, idx2.h_t)


def test_truncated_table_rebuilds(tmp_path):
    psi, params, corpus = _psi1()
    cache = str(tmp_path / 'cache')
    load_or_build(cache, psi, params, corpus)
    table = os.path.join(cache, CACHE_TABLE)
    with open(table, 'r+b') as f:
        f.truncate(os.path.getsize(table) // 2)
    h, reason = load_cache(cache, corpus.fingerprint(),
                           params_fingerprint(params))
    assert h is None and reason == f'size-mismatch:{CACHE_TABLE}'


def test_no_cache_dir_always_builds():
    psi, params, corpus = _psi1()
    _, info = load_or_build(None, psi, params, corpus)
    assert info['cache'] == 'miss:disabled'
