"""The serving worker over real HTTP: /match answers, concurrent-query
determinism (N threaded clients == sequential, bit for bit), structured
4xx for unknown buckets and malformed queries, /metrics through the
strict Prometheus parser, and the warm-restart cache hit."""

import argparse
import concurrent.futures
import json

import numpy as np
import pytest

from dgmc_tpu.serve.client import (get_json, post_match, query_payload,
                                   sample_query)
from dgmc_tpu.serve.corpus import synthetic_corpus
from dgmc_tpu.serve.service import ServeService, add_serve_args
from tests.obs.test_live import parse_exposition

CORPUS = dict(nodes=256, edges=1024, dim=16)


def _args(tmp_path, obs='obs', **over):
    argv = [
        '--ckpt_dir', str(tmp_path / 'ckpt'), '--init-missing',
        '--corpus-nodes', str(CORPUS['nodes']),
        '--corpus-edges', str(CORPUS['edges']),
        '--corpus-dim', str(CORPUS['dim']),
        '--dim', '16', '--rnd_dim', '8', '--num_layers', '1',
        '--num_steps', '2', '--k', '5', '--buckets', '8x16',
        '--max-results', '3',
        '--obs-dir', str(tmp_path / obs), '--obs-port', '0',
    ]
    for k, v in over.items():
        argv += [k] + ([str(v)] if v is not None else [])
    parser = argparse.ArgumentParser()
    add_serve_args(parser)
    return parser.parse_args(argv)


@pytest.fixture(scope='module')
def service(tmp_path_factory):
    tmp = tmp_path_factory.mktemp('serve')
    svc = ServeService(_args(tmp)).start()
    yield svc
    svc.stop()
    svc.close()


def _query(seed):
    x = synthetic_corpus(**{'num_nodes': CORPUS['nodes'],
                            'num_edges': CORPUS['edges'],
                            'dim': CORPUS['dim']}).x
    g, gt = sample_query(x, 6, 12, seed=seed)
    return query_payload(g), gt


def test_match_answers(service):
    payload, gt = _query(0)
    code, resp = post_match(service.port, payload)
    assert code == 200
    assert resp['bucket'] == '8x16'
    assert resp['nodes'] == 6
    assert len(resp['matches']) == 6
    m = resp['matches'][0]
    assert set(m) == {'node', 'target', 'score', 'candidates', 'initial'}
    assert len(m['candidates']) == 3
    # Ranked: candidate probabilities descend.
    probs = [c[1] for c in m['candidates']]
    assert probs == sorted(probs, reverse=True)
    assert 0 <= m['target'] < CORPUS['nodes']
    assert resp['latency_ms'] > 0


def test_concurrent_equals_sequential(service):
    """The determinism satellite: N threaded clients firing the same
    query set get answers bit-identical (ties, candidate order, scores
    — everything but the latency stamp) to the same queries issued
    sequentially."""
    queries = [_query(seed)[0] for seed in range(6)]

    def strip(resp):
        resp = dict(resp)
        for key in ('latency_ms', 'client_ms', 'trace_id', 'trace_ms',
                    'stages_ms', 'server_traceparent'):
            resp.pop(key, None)
        return resp

    sequential = [strip(post_match(service.port, q)[1])
                  for q in queries]
    with concurrent.futures.ThreadPoolExecutor(max_workers=6) as ex:
        rounds = [list(ex.map(
            lambda q: strip(post_match(service.port, q)[1]), queries))
            for _ in range(3)]
    for got in rounds:
        assert json.dumps(got, sort_keys=True) \
            == json.dumps(sequential, sort_keys=True)


def test_unknown_bucket_is_4xx(service):
    x = synthetic_corpus(**{'num_nodes': CORPUS['nodes'],
                            'num_edges': CORPUS['edges'],
                            'dim': CORPUS['dim']}).x
    g, _ = sample_query(x, 30, 60, seed=5)      # outside 8x16
    code, resp = post_match(service.port, query_payload(g))
    assert code == 400
    assert resp['error'] == 'unknown-bucket'
    assert resp['buckets'] == ['8x16']
    assert resp['query'] == {'nodes': 30, 'edges': 60}


def test_unwarmed_bucket_is_structured_503(service):
    """A routed bucket whose executable is missing (warm() skipped or
    raced) is a structured 503 — never an inline compile, never a bare
    500 that loses the payload."""
    saved = dict(service.engine._exec)
    service.engine._exec.clear()
    try:
        code, resp = post_match(service.port, _query(4)[0])
    finally:
        service.engine._exec.update(saved)
    assert code == 503
    assert resp['error'] == 'bucket-not-warm'
    assert '8x16' in resp['detail']


def test_malformed_queries_are_4xx(service):
    import urllib.request
    req = urllib.request.Request(
        f'http://127.0.0.1:{service.port}/match', data=b'not json',
        method='POST')
    try:
        urllib.request.urlopen(req, timeout=10)
        code = 200
    except urllib.error.HTTPError as e:
        code = e.code
        resp = json.loads(e.read())
    assert code == 400 and resp['error'] == 'bad-query'
    # Wrong feature width: structured 400, names both widths.
    code, resp = post_match(service.port,
                            {'nodes': [[1.0, 2.0]], 'edges': []})
    assert code == 400
    assert 'feature width' in resp['detail']
    # GET on /match: 405 with the schema hint.
    code, resp = get_json(service.port, '/match')
    assert code == 405 and 'schema' in resp


def test_metrics_strict_parse_and_gauges(service):
    post_match(service.port, _query(1)[0])
    code, text = get_json(service.port, '/metrics')
    assert code == 200
    families = parse_exposition(text)
    assert families['dgmc_step_latency_seconds']['type'] == 'histogram'
    counts = [v for (name, labels, v)
              in families['dgmc_step_latency_seconds']['samples']
              if name.endswith('_count')]
    assert counts and float(counts[0]) >= 1
    code, health = get_json(service.port, '/healthz')
    assert code == 200
    gauges = health['gauges']
    assert gauges['serve_ready'] == 1
    assert gauges['serve_buckets_warm'] == 1
    assert gauges['corpus_cache_hit'] == 0
    assert gauges['queries_served'] >= 1


def test_trace_id_and_stages_in_response(service):
    """The tentpole's wire surface: a W3C traceparent is adopted and
    echoed (header + payload), the answer carries the per-stage
    decomposition in the shared span vocabulary, and the spans sum to
    no more than the end-to-end trace clock."""
    from dgmc_tpu.obs.qtrace import SERVE_SPAN_NAMES
    sent_id = 'ab' * 16
    tp = f'00-{sent_id}-{"cd" * 8}-01'
    code, resp = post_match(service.port, _query(6)[0], traceparent=tp)
    assert code == 200
    assert resp['trace_id'] == sent_id
    assert resp['server_traceparent'].startswith(f'00-{sent_id}-')
    stages = resp['stages_ms']
    assert stages and set(stages) <= set(SERVE_SPAN_NAMES)
    for name in ('bucket_resolve', 'pad_and_stage',
                 'admission_queue_wait', 'device_execute', 'serialize'):
        assert name in stages
    assert sum(stages.values()) <= resp['trace_ms'] + 1e-6
    # The client-observed clock covers the whole server handler.
    assert resp['client_ms'] > 0
    # A malformed traceparent mints a fresh id instead of failing.
    code, resp = post_match(service.port, _query(6)[0],
                            traceparent='garbage-header')
    assert code == 200
    assert len(resp['trace_id']) == 32 and resp['trace_id'] != sent_id
    # The kept-set lands in a real, bounded qtrace.jsonl.
    tracer = service.qtracer
    assert tracer.flush()
    with open(tracer.path) as f:
        lines = [json.loads(ln) for ln in f if ln.strip()]
    assert lines
    bound = (tracer.capacity + tracer.error_capacity
             + tracer.slowest_k)
    assert len(lines) <= bound
    assert all(rec['kept'] for rec in lines)


def test_qtrace_optout_header(service):
    """``x-qtrace: off`` skips tracing for that request only — the
    overhead-measurement path must cost nothing."""
    before = service.qtracer.summary()['queries']
    code, resp = post_match(service.port, _query(7)[0], qtrace=False)
    assert code == 200
    assert 'trace_id' not in resp and 'stages_ms' not in resp
    assert 'server_traceparent' not in resp
    assert service.qtracer.summary()['queries'] == before


def test_error_classes_strict_parse(service):
    """Satellite 1: the single error counter is gone; every error class
    is a labelled Prometheus counter, strict-parsed."""
    get_json(service.port, '/match')                       # method-405
    post_match(service.port, {'nodes': 'nope'})          # bad-query-400
    x = synthetic_corpus(**{'num_nodes': CORPUS['nodes'],
                            'num_edges': CORPUS['edges'],
                            'dim': CORPUS['dim']}).x
    g, _ = sample_query(x, 30, 60, seed=11)            # bucket-miss-400
    post_match(service.port, query_payload(g))
    saved = dict(service.engine._exec)
    service.engine._exec.clear()                  # bucket-not-warm-503
    try:
        post_match(service.port, _query(8)[0])
    finally:
        service.engine._exec.update(saved)
    orig = service.engine.match                           # engine-500

    def boom(*_a, **_k):
        raise RuntimeError('boom')

    service.engine.match = boom
    try:
        code, resp = post_match(service.port, _query(9)[0])
    finally:
        service.engine.match = orig
    assert code == 500 and resp['error'] == 'engine-fault'

    from dgmc_tpu.serve.service import ERROR_CLASSES
    _, text = get_json(service.port, '/metrics')
    fam = parse_exposition(text)['dgmc_query_errors_total']
    assert fam['type'] == 'counter'
    counts = {labels['class']: value
              for (_n, labels, value) in fam['samples']}
    # The FULL label set is always exported, hit or not.
    assert set(counts) == set(ERROR_CLASSES)
    for cls in ('method-405', 'bad-query-400', 'bucket-miss-400',
                'bucket-not-warm-503', 'engine-500'):
        assert counts[cls] >= 1, cls


def test_stage_histograms_in_metrics(service):
    """Per-stage qtrace histograms export through /metrics with the
    stage label, strict-parsed."""
    post_match(service.port, _query(3)[0])
    _, text = get_json(service.port, '/metrics')
    families = parse_exposition(text)
    fam = families['dgmc_query_stage_seconds']
    assert fam['type'] == 'histogram'
    counts = {labels['stage']: value
              for (name, labels, value) in fam['samples']
              if name.endswith('_count')}
    from dgmc_tpu.obs.qtrace import SERVE_SPAN_NAMES
    assert set(counts) == set(SERVE_SPAN_NAMES)
    assert counts['device_execute'] >= 1
    assert counts['serialize'] >= 1
    kept = {labels['reason']: value
            for (_n, labels, value)
            in families['dgmc_qtrace_kept_total']['samples']}
    assert kept['slowest'] >= 1
    assert families['dgmc_qtrace_queries_total']['samples'][0][2] >= 1


def test_answer_carries_confidence(service):
    """Every 200 answer carries the per-query confidence block beside
    stages_ms: the engine's in-graph proxies, JSON-native floats."""
    from dgmc_tpu.serve.client import confidence_of
    code, resp = post_match(service.port, _query(12)[0])
    assert code == 200
    quality = confidence_of(resp)
    assert set(quality) == {'entropy', 'margin', 'correction',
                            'saturation', 'saturated_frac'}
    for name, v in quality.items():
        assert isinstance(v, float), name
        assert np.isfinite(v), name
    assert quality['entropy'] >= 0
    assert quality['margin'] >= 0
    assert 0 <= quality['saturation'] <= 1
    assert 0 <= quality['saturated_frac'] <= 1
    # The private audit payload never leaks onto the wire.
    assert '_audit' not in resp
    # Errors have no confidence: the helper degrades to {}.
    assert confidence_of({'error': 'bad-query'}) == {}


def test_quality_metrics_and_status(service):
    """The quality plane's live surfaces: dgmc_query_quality histograms
    through the strict parser, and /status carrying the quality payload
    plus the qtrace section beside the timing account."""
    post_match(service.port, _query(13)[0])
    _, text = get_json(service.port, '/metrics')
    families = parse_exposition(text)
    fam = families['dgmc_query_quality']
    assert fam['type'] == 'histogram'
    counts = {labels['signal']: value
              for (name, labels, value) in fam['samples']
              if name.endswith('_count')}
    from dgmc_tpu.obs.quality import QUALITY_SIGNALS
    assert set(counts) == set(QUALITY_SIGNALS)
    assert all(v >= 1 for v in counts.values())
    assert families['dgmc_quality_audited_total']['samples'][0][2] == 0

    _, status = get_json(service.port, '/status')
    serve_q = status['quality']['serve']
    assert serve_q['queries'] >= 1
    assert serve_q['audit']['audited'] == 0  # audit not enabled here
    assert status['qtrace']['queries'] >= 1  # the registered section


def test_shadow_audit_exact_tier(tmp_path):
    """Tentpole (c): a service with the shadow audit on the host-RAM
    offload tier. The audited set is the seeded-hash keep set exactly
    (byte-identical, predictable from audit_keep), and every audited
    query's served shortlist matches the exhaustive corpus scan —
    recall 1.0, because the offload tier is bit-exact."""
    import hashlib
    from dgmc_tpu.obs.qtrace import format_traceparent
    from dgmc_tpu.obs.quality import audit_keep
    args = _args(tmp_path)
    args.offload_corpus = True
    args.audit_sample = 0.5
    args.seed = 3
    svc = ServeService(args).start()
    try:
        sent = []
        for i in range(12):
            tid = hashlib.sha256(f'audit-q{i}'.encode()).hexdigest()[:32]
            tp = format_traceparent(tid, tid[:16])
            code, resp = post_match(svc.port, _query(100 + i)[0],
                                    traceparent=tp)
            assert code == 200 and resp['trace_id'] == tid
            sent.append(tid)
        assert svc.auditor is not None
        assert svc.auditor.drain(timeout_s=60.0)
        expect = [t for t in sent if audit_keep(3, t, 0.5)]
        assert expect, 'seed 3 must keep at least one of these ids'
        audit = svc.obs.quality.payload()['serve']['audit']
        assert audit['trace_ids'] == expect
        assert audit['audited'] == len(expect)
        assert audit['sample_rate'] == 0.5 and audit['seed'] == 3
        assert audit['recall_min'] == 1.0
        assert audit['recall_mean'] == 1.0
        assert audit['exact'] == len(expect)
        assert svc.auditor.dropped == 0 and svc.auditor.errors == 0
    finally:
        svc.stop()
        svc.close()


def test_padding_buckets_in_status(service):
    """The router records collations in the registry: a recorded serve
    run's /status (== timings.json) carries the padding-bucket rows the
    RCP202 compile-churn cross-check reads."""
    post_match(service.port, _query(2)[0])
    _, status = get_json(service.port, '/status')
    rows = status.get('padding_buckets') or []
    serve_rows = [r for r in rows
                  if r.get('nodes') == f'8x{CORPUS["nodes"]}']
    assert serve_rows and serve_rows[0]['count'] >= 1


def test_capacity_metric_families_strict_parse(service):
    """The capacity plane's /metrics surface: the in-flight gauge, the
    per-bucket pad-fraction gauge, the goodput ratio, and the engine
    lock split into wait vs hold histograms — all through the strict
    exposition parser."""
    for seed in (11, 12):
        assert post_match(service.port, _query(seed)[0])[0] == 200
    code, text = get_json(service.port, '/metrics')
    assert code == 200
    families = parse_exposition(text)
    assert families['dgmc_inflight']['type'] == 'gauge'
    # Scraped between requests: nothing is mid-execute.
    assert families['dgmc_inflight']['samples'][0][2] == 0
    pads = {labels.get('bucket'): v for (_n, labels, v)
            in families['dgmc_pad_fraction']['samples']}
    # 6x12 queries into the 8x16 bucket: real, nonzero padding.
    assert '8x16' in pads
    assert 0.0 < pads['8x16'] < 1.0
    ratio = families['dgmc_goodput_ratio']['samples'][0][2]
    assert 0.0 < ratio < 1.0
    for fam in ('dgmc_lock_wait_seconds', 'dgmc_lock_hold_seconds'):
        assert families[fam]['type'] == 'histogram'
        counts = [v for (name, _l, v) in families[fam]['samples']
                  if name.endswith('_count')]
        assert counts and counts[0] >= 2


def test_status_capacity_section_and_artifact(service):
    """/status carries the live queueing model (Little's-law ρ from
    measured arrival × service time, lock wait/hold quantiles, the
    qtrace reconciliation) and ``_flush_capacity`` persists the same
    object as ``capacity.json`` where ``obs.report`` summarizes it."""
    from dgmc_tpu.obs.report import load_run, summarize
    for seed in (13, 14):
        assert post_match(service.port, _query(seed)[0])[0] == 200
    code, status = get_json(service.port, '/status')
    assert code == 200
    cap = status['capacity']
    assert cap['queries'] >= 2
    assert cap['mean_service_ms'] > 0
    assert cap['saturation_qps'] == pytest.approx(
        1000.0 / cap['mean_service_ms'], rel=1e-3)
    # Little's law: ρ = λ × E[service].
    assert cap['utilization'] == pytest.approx(
        cap['arrival_qps'] * cap['mean_service_ms'] / 1e3, abs=5e-3)
    for side in ('lock_wait_ms', 'lock_hold_ms'):
        hist = cap[side]
        assert hist['count'] >= 2
        assert hist['p50_ms'] <= hist['p95_ms'] <= hist['p99_ms']
    rec = cap['admission_reconciliation']
    assert rec['engine_count'] >= rec['qtrace_count'] >= 1
    assert 0.0 < cap['pad_fraction'] < 1.0
    # The artifact side: flush, reload, summarize.
    service._flush_capacity()
    run = load_run(service.obs.dir)
    assert run['capacity']['queries'] == cap['queries']
    s = summarize(run)
    assert s['utilization'] == run['capacity']['utilization']
    assert s['capacity_lock_wait_p95_ms'] \
        == run['capacity']['lock_wait_ms']['p95_ms']
    assert s['capacity_lock_hold_p95_ms'] \
        == run['capacity']['lock_hold_ms']['p95_ms']


@pytest.mark.slow
def test_warm_restart_hits_cache(tmp_path):
    """A second worker over the same checkpoint dir skips the ψ₁ corpus
    recompute: verified cache hit, gauge exported, loads faster than it
    builds."""
    svc1 = ServeService(_args(tmp_path, obs='obs1')).start()
    assert svc1.cache_info['cache'].startswith('miss')
    h1 = np.load(tmp_path / 'ckpt' / 'corpus_cache' / 'h_t.npy')
    svc1.stop()
    svc1.close()
    svc2 = ServeService(_args(tmp_path, obs='obs2')).start()
    try:
        assert svc2.cache_info['cache'] == 'hit'
        _, health = get_json(svc2.port, '/healthz')
        assert health['gauges']['corpus_cache_hit'] == 1
        np.testing.assert_array_equal(svc2.engine.index.h_t, h1)
        code, _resp = post_match(svc2.port, _query(3)[0])
        assert code == 200
    finally:
        svc2.stop()
        svc2.close()
