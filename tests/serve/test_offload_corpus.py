"""``offloaded_corpus_topk`` — the host-RAM corpus tier's search — is
bit-identical to the in-graph blockwise scan, tie order and degenerate
maskings included."""

import numpy as np
import pytest

from dgmc_tpu.ops.offload import offloaded_corpus_topk
from dgmc_tpu.ops.topk import chunked_topk


def _tables(seed=0, B=1, Ns=7, Nt=53, C=8):
    rng = np.random.RandomState(seed)
    h_s = rng.randn(B, Ns, C).astype(np.float32)
    h_t = rng.randn(B, Nt, C).astype(np.float32)
    # Exact duplicate target rows: every source row scores them
    # identically — the tie-order pin.
    h_t[:, 10] = h_t[:, 40]
    h_t[:, 3] = h_t[:, 22]
    return h_s, h_t


@pytest.mark.parametrize('chunk', [8, 16, 53, 64])
def test_bit_identical_to_chunked(chunk):
    h_s, h_t = _tables()
    dv, di = chunked_topk(h_s, h_t, 5, block=8, return_values=True,
                          pallas=False)
    ov, oi, stats = offloaded_corpus_topk(h_s, h_t, 5, chunk, block=8)
    np.testing.assert_array_equal(np.asarray(dv), ov)
    np.testing.assert_array_equal(np.asarray(di), oi)
    assert stats.chunks == -(-53 // chunk)
    assert stats.ring_misses == 1        # only the cold start misses


def test_bit_identical_with_mask():
    h_s, h_t = _tables(seed=1)
    mask = np.ones((1, 53), bool)
    mask[0, 45:] = False
    dv, di = chunked_topk(h_s, h_t, 4, t_mask=mask, block=8,
                          return_values=True, pallas=False)
    ov, oi, _ = offloaded_corpus_topk(h_s, h_t, 4, chunk=16, t_mask=mask,
                                      block=8)
    np.testing.assert_array_equal(np.asarray(dv), ov)
    np.testing.assert_array_equal(np.asarray(di), oi)


def test_degenerate_k_exceeds_valid():
    """k > valid target count: masked columns fill the tail in index
    order, exactly like the device scan."""
    h_s, h_t = _tables(seed=2)
    mask = np.zeros((1, 53), bool)
    mask[0, :3] = True
    dv, di = chunked_topk(h_s, h_t, 6, t_mask=mask, block=8,
                          return_values=True, pallas=False)
    ov, oi, _ = offloaded_corpus_topk(h_s, h_t, 6, chunk=16, t_mask=mask,
                                      block=8)
    np.testing.assert_array_equal(np.asarray(dv), ov)
    np.testing.assert_array_equal(np.asarray(di), oi)


def test_stats_account():
    h_s, h_t = _tables()
    _, _, stats = offloaded_corpus_topk(h_s, h_t, 3, chunk=16, depth=3)
    assert stats.rows == 53
    assert stats.prefetch_depth == 3
    assert stats.host_resident_bytes >= h_t.nbytes
    assert stats.bytes_streamed >= h_t.nbytes  # padded tail included
