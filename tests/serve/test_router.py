"""Query router: bucket parsing/routing, the one-definition signature
contract with the recompile lint (pinned on every registry specimen),
and the structured unknown-bucket error."""

import jax
import numpy as np
import pytest

from dgmc_tpu.analysis import recompile
from dgmc_tpu.analysis.registry import default_specimens
from dgmc_tpu.serve import router as router_mod
from dgmc_tpu.serve.router import (Bucket, QueryRouter,
                                   UnknownBucketError, parse_buckets)


def test_parse_buckets():
    assert parse_buckets('32x96, 16x48,32x96') == [
        Bucket(16, 48), Bucket(32, 96)]
    with pytest.raises(ValueError):
        parse_buckets('32')
    with pytest.raises(ValueError):
        parse_buckets('0x4')
    with pytest.raises(ValueError):
        parse_buckets('')


def test_route_smallest_fit():
    r = QueryRouter([(16, 48), (32, 96), (32, 200)], 100, 400)
    assert r.route(10, 40) == Bucket(16, 48)
    assert r.route(16, 48) == Bucket(16, 48)
    assert r.route(17, 48) == Bucket(32, 96)
    # Fits the node budget of 32x96 but not its edge budget: the
    # wider-edge declaration wins.
    assert r.route(20, 150) == Bucket(32, 200)


def test_unknown_bucket_is_structured():
    r = QueryRouter([(16, 48)], 100, 400)
    with pytest.raises(UnknownBucketError) as ei:
        r.route(17, 10)
    payload = ei.value.payload
    assert payload['error'] == 'unknown-bucket'
    assert payload['query'] == {'nodes': 17, 'edges': 10}
    assert payload['buckets'] == ['16x48']


def test_signature_is_the_lint_definition():
    """ONE definition: the router imports the recompile lint's public
    ``bucket_signature`` — not a copy of it."""
    assert router_mod.bucket_signature is recompile.bucket_signature


def _pair_batch_rows(args):
    """Padding-bucket telemetry rows a specimen's PairBatch args would
    collate as (the ``pad_pair_batch`` recording format)."""
    from dgmc_tpu.utils.data import PairBatch
    rows = []
    for leaf in args:
        if isinstance(leaf, PairBatch):
            b, n_s = leaf.s.x.shape[0], leaf.s.x.shape[1]
            n_t, e_s = leaf.t.x.shape[1], leaf.s.senders.shape[1]
            e_t = leaf.t.senders.shape[1]
            rows.append({'batch': b, 'nodes': f'{n_s}x{n_t}',
                         'edges': f'{e_s}x{e_t}'})
    return rows


def test_router_and_lint_agree_on_every_registry_specimen():
    """The serve router's executable-table key and the recompile lint's
    churn hash must be the SAME function of the same row — asserted
    over every registry specimen's actual pair shapes."""
    checked = 0
    for spec in default_specimens():
        if spec.min_devices and jax.device_count() < spec.min_devices:
            continue
        built = spec.build()
        for row in _pair_batch_rows(built.get('args', ())):
            n_s, n_t = (int(v) for v in row['nodes'].split('x'))
            e_s, e_t = (int(v) for v in row['edges'].split('x'))
            r = QueryRouter([(n_s, e_s)], n_t, e_t)
            bucket = r.route(n_s, e_s)
            want_row = dict(row, batch=1)
            assert r.bucket_row(bucket) == want_row
            assert (r.signature(bucket)
                    == recompile.bucket_signature(want_row))
            checked += 1
    assert checked >= 3, 'registry specimens stopped carrying PairBatch'


def test_pad_and_record(tmp_path):
    from dgmc_tpu.obs.registry import padding_bucket_table
    from dgmc_tpu.utils.data import Graph
    r = QueryRouter([(8, 12)], 50, 60)
    g = Graph(edge_index=np.array([[0, 1], [1, 2]]),
              x=np.ones((5, 4), np.float32))
    q = r.pad_query(g, r.route(5, 2))
    assert q.x.shape == (1, 8, 4)
    assert q.senders.shape == (1, 12)
    assert q.node_mask.sum() == 5 and q.edge_mask.sum() == 2
    rows = [row for row in padding_bucket_table()
            if row.get('nodes') == '8x50' and row.get('edges') == '12x60']
    assert rows and rows[0]['count'] >= 1
