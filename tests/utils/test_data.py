"""Pair-dataset and collation tests, mirroring the reference suite
(reference ``test/utils/test_data.py``): product-vs-sample lengths, field
passthrough, and ValidPairDataset ground-truth construction under a
permuted target."""

import numpy as np

from dgmc_tpu.utils import (Graph, PairDataset, ValidPairDataset,
                            pad_pair_batch, PairLoader)


def toy_graph(n=4, c=3, perm=None, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, c).astype(np.float32)
    ei = np.array([[i, i + 1] for i in range(n - 1)]).T
    ei = np.concatenate([ei, ei[::-1]], axis=1)
    y = np.arange(n) if perm is None else perm
    return Graph(edge_index=ei, x=x, y=np.asarray(y))


class ListDataset(list):
    pass


def test_pair_dataset_lengths():
    ds = ListDataset([toy_graph(seed=i) for i in range(2)])
    assert len(PairDataset(ds, ds, sample=False)) == 4
    assert len(PairDataset(ds, ds, sample=True)) == 2
    p = PairDataset(ds, ds)[1]
    np.testing.assert_array_equal(p.s.x, ds[0].x)
    np.testing.assert_array_equal(p.t.x, ds[1].x)


def test_valid_pair_dataset_gt_under_permutation():
    # Target nodes hold the same classes but permuted: the emitted y_col
    # must map each source node to the position of its class in the target
    # (the contract of reference test/utils/test_data.py:40-74).
    perm = np.array([2, 0, 3, 1])
    src = toy_graph(perm=None)
    tgt = toy_graph(perm=perm)
    ds = ValidPairDataset(ListDataset([src]), ListDataset([tgt]))
    assert len(ds) == 1
    pair = ds[0]
    # Node i in source has class i; in target, class i sits at argwhere.
    expected = np.array([np.argwhere(perm == c)[0, 0] for c in range(4)])
    np.testing.assert_array_equal(pair.y_col, expected)


def test_valid_pair_dataset_filters_missing_classes():
    src = toy_graph(perm=np.array([0, 1, 2, 5]))   # class 5 not in target
    tgt = toy_graph(perm=np.array([0, 1, 2, 3]))
    ds = ValidPairDataset(ListDataset([src, tgt]), ListDataset([tgt]))
    # Only (tgt, tgt) is valid.
    assert len(ds) == 1
    assert ds.pairs[0][0] == 1


def test_pad_pair_batch_shapes_and_masks():
    pairs = [ValidPairDataset(ListDataset([toy_graph()]),
                              ListDataset([toy_graph()]))[0]
             for _ in range(3)]
    batch = pad_pair_batch(pairs, num_nodes_s=6, num_edges_s=10)
    assert batch.s.x.shape == (3, 6, 3)
    assert batch.s.senders.shape == (3, 10)
    assert batch.y.shape == (3, 6)
    assert batch.y_mask[:, :4].all() and not batch.y_mask[:, 4:].any()
    assert batch.s.node_mask[:, :4].all()
    assert not batch.s.node_mask[:, 4:].any()


def test_pair_loader_fixed_shapes_and_short_batch():
    ds = ListDataset([toy_graph(n=3 + (i % 3), seed=i) for i in range(7)])
    pair_ds = PairDataset(ds, ds, sample=True)
    loader = PairLoader(pair_ds, batch_size=4, shuffle=True, seed=1)
    batches = list(loader)
    assert len(batches) == 2
    shapes = {b.s.x.shape for b in batches}
    assert len(shapes) == 1  # single static shape -> single XLA program
    # Short batch: filler rows carry no ground truth.
    assert not batches[-1].y_mask[3:].any()


def test_pad_graphs_width_mismatch_raises_on_both_paths():
    # A graph narrower than feat_dim must raise on the native path (which
    # would otherwise memcpy out of bounds) exactly like the NumPy path.
    import pytest
    from dgmc_tpu.utils.data import pad_graphs
    good = toy_graph(n=4, c=3)
    bad = toy_graph(n=4, c=2, seed=1)
    for native in ('auto', 'never'):
        with pytest.raises(ValueError):
            pad_graphs([good, bad], num_nodes=6, num_edges=10, native=native)


def test_prefetch_loader_full_iteration_and_abandon():
    import threading
    import time
    from dgmc_tpu.utils import PrefetchLoader

    ds = ListDataset([toy_graph(seed=i) for i in range(6)])
    pair_ds = PairDataset(ds, ds, sample=True)
    loader = PairLoader(pair_ds, batch_size=2, shuffle=False)

    # Full iteration yields every batch.
    batches = list(PrefetchLoader(loader, depth=1))
    assert len(batches) == len(loader)

    # Abandoning mid-iteration must release the worker thread (it would
    # otherwise block forever on a full queue).
    before = threading.active_count()
    it = iter(PrefetchLoader(loader, depth=1))
    next(it)
    it.close()
    deadline = time.time() + 5.0
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.05)
    assert threading.active_count() <= before


def test_synthetic_pairs_with_transforms():
    from dgmc_tpu.data import (Compose, Constant, KNNGraph, Cartesian,
                               RandomGraphPairs)
    t = Compose([Constant(), KNNGraph(k=8), Cartesian()])
    ds = RandomGraphPairs(min_inliers=10, max_inliers=15, min_outliers=0,
                          max_outliers=5, transform=t, length=4, seed=3)
    p = ds[0]
    n = p.s.num_nodes
    assert 10 <= n <= 20
    assert p.s.x.shape == (n, 1)
    assert p.s.edge_index.shape[0] == 2 and p.s.edge_index.shape[1] == n * 8
    assert p.s.edge_attr.min() >= 0.0 and p.s.edge_attr.max() <= 1.0
    # Deterministic per (seed, epoch, idx).
    p2 = ds[0]
    np.testing.assert_array_equal(p.s.pos, p2.s.pos)
    ds.set_epoch(1)
    p3 = ds[0]
    assert not np.array_equal(p.s.pos, p3.s.pos)


def test_delaunay_face_to_edge_pipeline():
    from dgmc_tpu.data import Compose, Delaunay, FaceToEdge, Distance
    rng = np.random.RandomState(0)
    g = Graph(edge_index=np.zeros((2, 0), np.int64),
              pos=rng.rand(10, 2).astype(np.float32))
    out = Compose([Delaunay(), FaceToEdge(), Distance()])(g)
    src, dst = out.edge_index
    # Symmetric, no self-loops, attrs normalized.
    assert ((src != dst).all())
    pairs = set(map(tuple, out.edge_index.T))
    assert all((b, a) in pairs for a, b in pairs)
    assert out.edge_attr.shape == (out.edge_index.shape[1], 1)
    assert out.edge_attr.max() <= 1.0
