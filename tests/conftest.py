"""Test bootstrap: force a virtual 8-device CPU platform so sharding tests
run anywhere (SURVEY.md §4's multi-device plan). Bench and examples still
target the real TPU.

Note: the environment may pre-register an experimental TPU plugin at
interpreter startup and programmatically set ``jax_platforms``, so setting
the env var here is not enough — we must override the live config before any
backend is initialized.
"""

import os

os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '') +
                           ' --xla_force_host_platform_device_count=8')

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')
