"""Test bootstrap: force a virtual 8-device CPU platform so sharding tests
run anywhere (SURVEY.md §4's multi-device plan). Bench and examples still
target the real TPU.

Note: the environment may pre-register an experimental TPU plugin at
interpreter startup and programmatically set ``jax_platforms``, so setting
the env var here is not enough — we must override the live config before any
backend is initialized.
"""

import os

os.environ['JAX_PLATFORMS'] = 'cpu'
os.environ['XLA_FLAGS'] = (os.environ.get('XLA_FLAGS', '') +
                           ' --xla_force_host_platform_device_count=8')

import jax  # noqa: E402

jax.config.update('jax_platforms', 'cpu')

# Persistent XLA compilation cache: the suite's wall-clock on a 1-core box
# is dominated by recompiling the same tiny programs every run (~17 min of
# CPU). With a warm cache reruns skip that; the cache key includes the JAX
# version and backend, so upgrades invalidate it safely. The directory is
# gitignored — the first run on a fresh checkout is the only cold one.
_cache_dir = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), '.jax_compile_cache')
jax.config.update('jax_compilation_cache_dir', _cache_dir)
jax.config.update('jax_persistent_cache_min_compile_time_secs', 0.5)
# Subprocess-based tests (fault injection, multihost, dryrun children)
# don't import this conftest; the env vars cover them (both the cache
# dir AND the lowered min-compile-time floor, or sub-second child
# programs would never be cached).
os.environ['JAX_COMPILATION_CACHE_DIR'] = _cache_dir
os.environ['JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS'] = '0.5'
