"""Shared builders for small padded graph batches used across model tests."""

import json
import os

import jax.numpy as jnp
import numpy as np

from dgmc_tpu.ops import GraphBatch


def make_tiny_dbp15k(root, n1=12, n2=14, seed=0):
    """Write a miniature DBP15K zh_en raw layout under ``root`` (shared by
    the smoke fixtures and the subprocess fault-injection test)."""
    rng = np.random.RandomState(seed)
    d = os.path.join(str(root), 'zh_en')
    os.makedirs(d, exist_ok=True)

    def write(name, text):
        with open(os.path.join(d, name), 'w') as f:
            f.write(text)

    write('ent_ids_1', ''.join(f'{i}\te{i}\n' for i in range(n1)))
    write('ent_ids_2', ''.join(f'{100 + i}\tf{i}\n' for i in range(n2)))
    write('triples_1', ''.join(
        f'{rng.randint(n1)}\t0\t{rng.randint(n1)}\n' for _ in range(30)))
    write('triples_2', ''.join(
        f'{100 + rng.randint(n2)}\t0\t{100 + rng.randint(n2)}\n'
        for _ in range(36)))
    write('sup_pairs', ''.join(f'{i}\t{100 + i}\n' for i in range(6)))
    write('ref_pairs', ''.join(f'{i}\t{100 + i}\n' for i in range(6, 12)))
    vecs = rng.randn(120, 8).tolist()
    write('zh_vectorList.json', json.dumps(vecs))
    write('en_vectorList.json', json.dumps(vecs))
    return str(root)


def graph_from_edges(x, edges, num_nodes_pad=None, num_edges_pad=None,
                     edge_attr=None, num_valid_nodes=None):
    """Build a single-graph ``GraphBatch`` (B=1) from a dense edge list.

    x: ``[N, C]``; edges: list of (src, dst).
    """
    x = np.asarray(x, np.float32)
    n = x.shape[0]
    n_pad = num_nodes_pad or n
    e = len(edges)
    e_pad = num_edges_pad or e
    senders = np.zeros(e_pad, np.int32)
    receivers = np.zeros(e_pad, np.int32)
    for i, (s, d) in enumerate(edges):
        senders[i], receivers[i] = s, d
    xp = np.zeros((n_pad, x.shape[1]), np.float32)
    xp[:n] = x
    node_mask = np.zeros(n_pad, bool)
    node_mask[:num_valid_nodes if num_valid_nodes is not None else n] = True
    edge_mask = np.zeros(e_pad, bool)
    edge_mask[:e] = True
    attr = None
    if edge_attr is not None:
        a = np.asarray(edge_attr, np.float32)
        attr = np.zeros((e_pad, a.shape[1]), np.float32)
        attr[:e] = a
    return GraphBatch(
        x=jnp.asarray(xp)[None],
        senders=jnp.asarray(senders)[None],
        receivers=jnp.asarray(receivers)[None],
        node_mask=jnp.asarray(node_mask)[None],
        edge_mask=jnp.asarray(edge_mask)[None],
        edge_attr=None if attr is None else jnp.asarray(attr)[None])


def stack_graphs(g1, g2):
    """Concatenate two B=1 GraphBatches along the batch axis (equal pads)."""
    import jax
    return jax.tree.map(
        lambda a, b: None if a is None else jnp.concatenate([a, b], 0),
        g1, g2, is_leaf=lambda v: v is None)


def path_graph(n=4, c=32, seed=0):
    """The reference tests' canonical graph: an n-node undirected path."""
    rng = np.random.RandomState(seed)
    x = rng.randn(n, c).astype(np.float32)
    edges = []
    for i in range(n - 1):
        edges += [(i, i + 1), (i + 1, i)]
    return graph_from_edges(x, edges)
