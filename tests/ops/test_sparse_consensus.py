"""Fused sparse consensus-delta kernel vs unfused jnp semantics.

Covers forward values, every input cotangent (tile-recompute backward
with f32 weight-grad accumulators), source-axis padding, and bf16 inputs
(f32 output + finite f32-accumulated grads).
"""

import jax
import jax.numpy as jnp
import numpy as np

from dgmc_tpu.ops.pallas.sparse_consensus import (
    fused_candidate_delta, fused_candidate_delta_reference,
    sparse_consensus_delta, sparse_consensus_delta_reference)


def _case(seed=0, B=2, N=700, K=5, R=16, dtype=np.float32):
    r = np.random.RandomState(seed)
    return (jnp.asarray(r.randn(B, N, R).astype(dtype)),
            jnp.asarray(r.randn(B, N, K, R).astype(dtype)),
            jnp.asarray(0.3 * r.randn(R, R).astype(dtype)),
            jnp.asarray(0.1 * r.randn(R).astype(dtype)),
            jnp.asarray(0.3 * r.randn(R, 1).astype(dtype)),
            jnp.asarray(0.1 * r.randn(1).astype(dtype)))


def test_forward_matches_reference():
    args = _case()
    out = sparse_consensus_delta(*args, True)
    ref = sparse_consensus_delta_reference(*args)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_gradients_match_reference():
    args = _case(seed=1)

    def lk(*a):
        return jnp.sum(jnp.sin(sparse_consensus_delta(*a, True)))

    def lr(*a):
        return jnp.sum(jnp.sin(sparse_consensus_delta_reference(*a)))

    gk = jax.grad(lk, argnums=tuple(range(6)))(*args)
    gr = jax.grad(lr, argnums=tuple(range(6)))(*args)
    for i, (a, b) in enumerate(zip(gk, gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-4, rtol=3e-4, err_msg=f'arg {i}')


def test_bf16_inputs_f32_out_and_grads():
    args = _case(seed=2)
    args16 = tuple(a.astype(jnp.bfloat16) for a in args)
    out = sparse_consensus_delta(*args16, True)
    assert out.dtype == jnp.float32
    ref = sparse_consensus_delta_reference(*args)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=0.15, rtol=0.15)
    g = jax.grad(lambda *a: jnp.sum(sparse_consensus_delta(*a, True)),
                 argnums=(2, 4))(*args16)
    for leaf in g:
        assert np.isfinite(np.asarray(leaf, np.float32)).all()


def _rt_case(seed=0, B=2, N_s=300, N_t=90, K=5, R=16, dtype=np.float32):
    r = np.random.RandomState(seed)
    return (jnp.asarray(r.randn(B, N_s, R).astype(dtype)),
            jnp.asarray(r.randn(B, N_t, R).astype(dtype)),
            jnp.asarray(r.randint(0, N_t, (B, N_s, K)).astype(np.int32)),
            jnp.asarray(0.3 * r.randn(R, R).astype(dtype)),
            jnp.asarray(0.1 * r.randn(R).astype(dtype)),
            jnp.asarray(0.3 * r.randn(R, 1).astype(dtype)),
            jnp.asarray(0.1 * r.randn(1).astype(dtype)))


def test_fused_candidate_delta_forward_matches_reference():
    """Widened round-trip boundary: gather + kernel == unfused jnp."""
    args = _rt_case()
    out = fused_candidate_delta(*args, True)
    ref = fused_candidate_delta_reference(*args)
    assert out.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_fused_candidate_delta_gradients_match_reference():
    """The rematerialized backward produces every cotangent — including
    d_o_t through the fused segment-sum (candidates that repeat a target
    row must accumulate) — to reference accuracy."""
    args = _rt_case(seed=3)
    diff = (0, 1, 3, 4, 5, 6)  # all float args (S_idx is integral)

    def lk(o_s, o_t, w1, b1, w2, b2):
        return jnp.sum(jnp.sin(fused_candidate_delta(
            o_s, o_t, args[2], w1, b1, w2, b2, True)))

    def lr(o_s, o_t, w1, b1, w2, b2):
        return jnp.sum(jnp.sin(fused_candidate_delta_reference(
            o_s, o_t, args[2], w1, b1, w2, b2)))

    floats = tuple(args[i] for i in diff)
    gk = jax.grad(lk, argnums=tuple(range(6)))(*floats)
    gr = jax.grad(lr, argnums=tuple(range(6)))(*floats)
    for i, (a, b) in enumerate(zip(gk, gr)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-4, rtol=3e-4, err_msg=f'arg {i}')


def test_fused_candidate_delta_bf16_f32_accum():
    """bf16 operands keep the f32 logit/accumulation contract: f32
    output, finite f32 d_o_t accumulated through the fused segment-sum."""
    args = _rt_case(seed=4)
    a16 = tuple(a if a.dtype == jnp.int32 else a.astype(jnp.bfloat16)
                for a in args)
    out = fused_candidate_delta(*a16, True)
    assert out.dtype == jnp.float32
    ref = fused_candidate_delta_reference(*args)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=0.15, rtol=0.15)
    d_o_t = jax.grad(
        lambda o_t: jnp.sum(fused_candidate_delta(
            a16[0], o_t, a16[2], *a16[3:], True)))(a16[1])
    assert np.isfinite(np.asarray(d_o_t, np.float32)).all()


def test_dgmc_fused_flag_matches_unfused():
    """DGMC(fused_sparse_consensus=True) (interpret mode off-TPU) matches
    the default unfused path end to end."""
    from dgmc_tpu.models import DGMC, RelCNN
    from dgmc_tpu.ops.graph import GraphBatch
    from dgmc_tpu.train import create_train_state, make_train_step
    from dgmc_tpu.utils.data import PairBatch

    r = np.random.RandomState(0)
    n, e, c = 24, 60, 8

    def side(seed):
        rr = np.random.RandomState(seed)
        return GraphBatch(
            x=rr.randn(1, n, c).astype(np.float32),
            senders=rr.randint(0, n, (1, e)).astype(np.int32),
            receivers=rr.randint(0, n, (1, e)).astype(np.int32),
            node_mask=np.ones((1, n), bool),
            edge_mask=np.ones((1, e), bool), edge_attr=None)

    y = r.permutation(n).astype(np.int32)[None]
    batch = PairBatch(s=side(1), t=side(2), y=y, y_mask=y >= 0)
    outs = []
    for fused in (True, False):
        model = DGMC(RelCNN(c, 12, num_layers=1),
                     RelCNN(8, 8, num_layers=1), num_steps=2, k=4,
                     fused_sparse_consensus=fused)
        state = create_train_state(model, jax.random.key(0), batch,
                                   learning_rate=1e-2)
        step = make_train_step(model)
        state, out = step(state, batch, jax.random.key(1))
        state, out = step(state, batch, jax.random.key(2))
        outs.append(float(out['loss']))
    np.testing.assert_allclose(outs[0], outs[1], atol=1e-4, rtol=1e-4)
