"""Streaming Pallas top-k kernel: bit-identical to dense_topk, including
tie order and masked/overhanging-k cases (interpret mode on CPU; the same
assertions were run compiled on the real chip — 20.7 ms vs the scan's
82 ms at 15000x20000, see benchmarks/topk_tpu.json)."""

import jax.numpy as jnp
import numpy as np
import pytest

from dgmc_tpu.ops.pallas.topk import pallas_topk
from dgmc_tpu.ops.topk import dense_topk


def _rand(rng, *shape):
    return jnp.asarray(rng.randn(*shape).astype(np.float32))


def test_matches_dense_continuous():
    rng = np.random.RandomState(0)
    h_s, h_t = _rand(rng, 2, 130, 16), _rand(rng, 2, 1100, 16)
    got = pallas_topk(h_s, h_t, 10, interpret=True)
    want = dense_topk(h_s, h_t, 10)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_matches_dense_with_ties_and_mask():
    rng = np.random.RandomState(1)
    h_s = jnp.asarray(rng.randint(0, 3, (2, 300, 8)).astype(np.float32))
    h_t = jnp.asarray(rng.randint(0, 3, (2, 700, 8)).astype(np.float32))
    mask = jnp.asarray(rng.rand(2, 700) > 0.3)
    got = pallas_topk(h_s, h_t, 7, t_mask=mask, interpret=True)
    want = dense_topk(h_s, h_t, 7, t_mask=mask)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_k_exceeds_valid_targets():
    """More slots than unmasked targets: the masked tail must rank by
    index order, exactly as dense_topk does."""
    rng = np.random.RandomState(2)
    h_s, h_t = _rand(rng, 1, 40, 4), _rand(rng, 1, 20, 4)
    mask = jnp.asarray(np.arange(20)[None] < 5)
    got = pallas_topk(h_s, h_t, 9, t_mask=mask, interpret=True)
    want = dense_topk(h_s, h_t, 9, t_mask=mask)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_return_values():
    rng = np.random.RandomState(3)
    h_s, h_t = _rand(rng, 1, 9, 8), _rand(rng, 1, 33, 8)
    vals, idx = pallas_topk(h_s, h_t, 4, return_values=True, interpret=True)
    scores = jnp.einsum('bsc,btc->bst', h_s, h_t)
    want_vals = jnp.take_along_axis(scores, idx, axis=-1)
    np.testing.assert_allclose(np.asarray(vals), np.asarray(want_vals),
                               rtol=1e-6)
    assert vals.shape == (1, 9, 4) and idx.dtype == jnp.int32


@pytest.mark.parametrize('shape_s,shape_t', [(5, 17), (256, 512)])
def test_exact_tile_boundaries(shape_s, shape_t):
    """Sizes below and exactly at the kernel tile sizes."""
    rng = np.random.RandomState(4)
    h_s, h_t = _rand(rng, 1, shape_s, 8), _rand(rng, 1, shape_t, 8)
    got = pallas_topk(h_s, h_t, 3, interpret=True)
    want = dense_topk(h_s, h_t, 3)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_bfloat16_inputs():
    """bf16 inputs: the kernel rounds scores through bf16 before selection
    (carrying them in float32), so indices and values are bit-identical to
    the dtype-generic scan (verified compiled on the real chip too)."""
    rng = np.random.RandomState(5)
    h_s = jnp.asarray(rng.randn(1, 60, 16)).astype(jnp.bfloat16)
    h_t = jnp.asarray(rng.randn(1, 200, 16)).astype(jnp.bfloat16)
    vals, idx = pallas_topk(h_s, h_t, 6, return_values=True, interpret=True)
    scores = jnp.einsum('bsc,btc->bst', h_s, h_t)
    want_idx = jnp.argsort(-scores.astype(jnp.float32), axis=-1,
                           stable=True)[..., :6]
    np.testing.assert_array_equal(np.asarray(idx), np.asarray(want_idx))
    assert vals.dtype == jnp.bfloat16


def test_chunked_topk_is_not_differentiated():
    """The candidate search is selection, not a differentiable op: grads
    through returned values are zero on every engine (matching the
    reference's use of argKmin outside autograd)."""
    import jax
    from dgmc_tpu.ops.topk import chunked_topk
    rng = np.random.RandomState(6)
    h_s, h_t = _rand(rng, 1, 12, 8), _rand(rng, 1, 30, 8)

    def loss(a, b):
        v, _ = chunked_topk(a, b, 4, return_values=True, pallas=False)
        return (v ** 2).sum()

    g = jax.grad(loss)(h_s, h_t)
    assert float(jnp.abs(g).sum()) == 0.0
