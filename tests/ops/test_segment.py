import jax.numpy as jnp
import numpy as np

from dgmc_tpu.ops import segment_sum, segment_mean


def test_segment_sum():
    data = jnp.array([[1.0, 2.0], [3.0, 4.0], [5.0, 6.0]])
    ids = jnp.array([0, 2, 0])
    out = segment_sum(data, ids, 3)
    np.testing.assert_allclose(out, [[6.0, 8.0], [0.0, 0.0], [3.0, 4.0]])


def test_segment_mean():
    data = jnp.array([[2.0], [4.0], [6.0]])
    ids = jnp.array([1, 1, 0])
    out = segment_mean(data, ids, 3)
    np.testing.assert_allclose(out, [[6.0], [3.0], [0.0]])


def test_segment_mean_weighted():
    data = jnp.array([[2.0], [4.0], [6.0]])
    ids = jnp.array([0, 0, 0])
    w = jnp.array([1.0, 1.0, 0.0])  # mask out the last edge
    out = segment_mean(data, ids, 1, weights=w)
    np.testing.assert_allclose(out, [[3.0]])
