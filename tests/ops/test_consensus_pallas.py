"""Fused consensus-update kernel: forward + gradients must match the
unfused jnp semantics, and DGMC with fused_consensus=True must reproduce
the unfused model exactly (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np

from dgmc_tpu.ops.pallas import (consensus_update,
                                 consensus_update_reference)


def _case(B=2, Ns=20, Nt=37, R=8, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(B, Ns, R).astype(np.float32)),
            jnp.asarray(rng.randn(B, Nt, R).astype(np.float32)),
            jnp.asarray(0.3 * rng.randn(R, R).astype(np.float32)),
            jnp.asarray(0.1 * rng.randn(R).astype(np.float32)),
            jnp.asarray(0.3 * rng.randn(R, 1).astype(np.float32)),
            jnp.asarray(0.1 * rng.randn(1).astype(np.float32)))


def test_forward_matches_reference():
    args = _case()
    want = consensus_update_reference(*args)
    got = consensus_update(*args, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_gradients_match_reference():
    args = _case()

    def loss_ref(a):
        return (consensus_update_reference(*a) ** 2).sum()

    def loss_ker(a):
        return (consensus_update(*a, True) ** 2).sum()

    g_ref = jax.grad(loss_ref)(args)
    g_ker = jax.grad(loss_ker)(args)
    for a, b in zip(g_ref, g_ker):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-4)


def test_dgmc_fused_matches_unfused():
    from dgmc_tpu.models import DGMC
    from tests.train.test_steps import tiny_loader, tiny_model

    base = tiny_model(k=-1)
    fused = DGMC(base.psi_1, base.psi_2, num_steps=base.num_steps, k=-1,
                 fused_consensus=True)
    batch = next(iter(tiny_loader()))
    variables = base.init(
        {'params': jax.random.key(0), 'noise': jax.random.key(1)},
        batch.s, batch.t, train=False)

    def run(model):
        return model.apply(variables, batch.s, batch.t, train=False,
                           rngs={'noise': jax.random.key(2)})

    S0_a, SL_a = run(base)
    S0_b, SL_b = run(fused)
    np.testing.assert_allclose(np.asarray(SL_b.val), np.asarray(SL_a.val),
                               rtol=1e-5, atol=1e-6)
