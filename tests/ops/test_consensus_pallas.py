"""Fused consensus-update kernel: forward + gradients must match the
unfused jnp semantics, and DGMC with fused_consensus=True must reproduce
the unfused model exactly (interpret mode on CPU)."""

import jax
import jax.numpy as jnp
import numpy as np

from dgmc_tpu.ops.pallas import (consensus_update,
                                 consensus_update_reference)


def _case(B=2, Ns=20, Nt=37, R=8, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.randn(B, Ns, R).astype(np.float32)),
            jnp.asarray(rng.randn(B, Nt, R).astype(np.float32)),
            jnp.asarray(0.3 * rng.randn(R, R).astype(np.float32)),
            jnp.asarray(0.1 * rng.randn(R).astype(np.float32)),
            jnp.asarray(0.3 * rng.randn(R, 1).astype(np.float32)),
            jnp.asarray(0.1 * rng.randn(1).astype(np.float32)))


def test_forward_matches_reference():
    args = _case()
    want = consensus_update_reference(*args)
    got = consensus_update(*args, True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_gradients_match_reference():
    args = _case()

    def loss_ref(a):
        return (consensus_update_reference(*a) ** 2).sum()

    def loss_ker(a):
        return (consensus_update(*a, True) ** 2).sum()

    g_ref = jax.grad(loss_ref)(args)
    g_ker = jax.grad(loss_ker)(args)
    for a, b in zip(g_ref, g_ker):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-4)


def test_dgmc_fused_matches_unfused():
    from dgmc_tpu.models import DGMC
    from tests.train.test_steps import tiny_loader, tiny_model

    base = tiny_model(k=-1)
    fused = DGMC(base.psi_1, base.psi_2, num_steps=base.num_steps, k=-1,
                 fused_consensus=True)
    batch = next(iter(tiny_loader()))
    variables = base.init(
        {'params': jax.random.key(0), 'noise': jax.random.key(1)},
        batch.s, batch.t, train=False)

    def run(model):
        return model.apply(variables, batch.s, batch.t, train=False,
                           rngs={'noise': jax.random.key(2)})

    S0_a, SL_a = run(base)
    S0_b, SL_b = run(fused)
    np.testing.assert_allclose(np.asarray(SL_b.val), np.asarray(SL_a.val),
                               rtol=1e-5, atol=1e-6)


def test_bf16_inputs_emit_f32_delta():
    """Under the bf16 compute policy the fused kernel must still hand back
    a float32 delta (the consensus logits S_hat accumulate in f32; the
    unfused path and the sparse kernel both force this via
    preferred_element_type) and stay within bf16 tolerance of the f32
    unfused semantics."""
    args = _case()
    want = consensus_update_reference(*args)
    bf_args = tuple(a.astype(jnp.bfloat16) for a in args)
    got = consensus_update(*bf_args, True)
    assert got.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=5e-2, atol=5e-2)
    # Gradients keep each primal's dtype: the f32 upstream cotangent must
    # not leak f32 into the bf16 backbone backward (cast-back contract).
    grads = jax.grad(lambda a: consensus_update(*a, True).sum())(bf_args)
    assert all(g.dtype == jnp.bfloat16 for g in grads), (
        [g.dtype for g in grads])
