"""Fused SplineConv routing kernel: forward and backward must match the
gather+scatter formulation exactly (interpret mode on CPU; the compiled
kernel was verified bit-identical on the real chip, where it lifts the
dense flagship from ~330 to ~1200 training pairs/sec)."""

import jax
import jax.numpy as jnp
import numpy as np

from dgmc_tpu.models.spline import SplineConv
from dgmc_tpu.ops import GraphBatch
from dgmc_tpu.ops.graph import scatter_to_nodes
from dgmc_tpu.ops.pallas.spline import (route_aggregate,
                                        route_aggregate_fits)
from dgmc_tpu.ops.spline import open_spline_basis


def problem(B=3, N=24, E=80, C=8, O=16, seed=0, mask_frac=0.2):
    rng = np.random.RandomState(seed)
    x = jnp.asarray(rng.randn(B, N, C).astype(np.float32))
    senders = jnp.asarray(rng.randint(0, N, (B, E)).astype(np.int32))
    receivers = jnp.asarray(rng.randint(0, N, (B, E)).astype(np.int32))
    emask = jnp.asarray(rng.rand(B, E) > mask_frac)
    attr = jnp.asarray(rng.rand(B, E, 2).astype(np.float32))
    W = jnp.asarray(rng.randn(25, C, O).astype(np.float32) * 0.1)
    t = (x @ W.transpose(1, 0, 2).reshape(C, 25 * O)).reshape(B, N * 25, O)
    basis, combo = open_spline_basis(attr, 5, 1)
    flat = senders[..., None] * 25 + combo
    return t, flat, basis, receivers, emask, N, E, O


def reference(t, flat, basis, receivers, emask, N, E, O):
    B = t.shape[0]
    A = flat.shape[2]
    picked = jnp.take_along_axis(
        t, flat.reshape(B, E * A, 1), axis=1).reshape(B, E, A, O)
    msgs = jnp.einsum('bea,beao->beo', basis, picked)
    return scatter_to_nodes(msgs, receivers, emask, N, aggr='mean')


def test_forward_matches_gather_scatter():
    t, flat, basis, rcv, em, N, E, O = problem()
    got = route_aggregate(t, flat, basis, rcv, em, N, True)
    want = reference(t, flat, basis, rcv, em, N, E, O)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5)


def test_backward_matches_gather_scatter():
    t, flat, basis, rcv, em, N, E, O = problem(seed=1)

    def fused_loss(t):
        return (route_aggregate(t, flat, basis, rcv, em, N, True) ** 2).sum()

    def ref_loss(t):
        return (reference(t, flat, basis, rcv, em, N, E, O) ** 2).sum()

    g1 = jax.grad(fused_loss)(t)
    g2 = jax.grad(ref_loss)(t)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-4)


def test_all_edges_masked_node_gives_zero():
    t, flat, basis, rcv, em, N, E, O = problem(seed=2, mask_frac=1.01)
    got = route_aggregate(t, flat, basis, rcv, em, N, True)
    assert not np.asarray(em).any()
    np.testing.assert_array_equal(np.asarray(got), 0.0)


def test_m_axis_padding():
    """M = N * 25 not a multiple of the kernel's M tile: results must be
    unaffected by the zero-padding."""
    t, flat, basis, rcv, em, N, E, O = problem(N=11, E=40, seed=3)
    assert (11 * 25) % 256 != 0
    got = route_aggregate(t, flat, basis, rcv, em, N, True)
    want = reference(t, flat, basis, rcv, em, N, E, O)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5)


def test_splineconv_fused_flag_dispatch():
    """fused=True routes through the kernel (interpret off-TPU is not
    wired into the module, so force it via the function); fused=False and
    the CPU auto default agree with each other."""
    rng = np.random.RandomState(4)
    B, N, E, C = 2, 16, 48, 8
    x = jnp.asarray(rng.randn(B, N, C).astype(np.float32))
    gb = GraphBatch(
        x=x,
        senders=jnp.asarray(rng.randint(0, N, (B, E)).astype(np.int32)),
        receivers=jnp.asarray(rng.randint(0, N, (B, E)).astype(np.int32)),
        node_mask=jnp.ones((B, N), bool),
        edge_mask=jnp.asarray(rng.rand(B, E) > 0.2),
        edge_attr=jnp.asarray(rng.rand(B, E, 2).astype(np.float32)))
    conv = SplineConv(8, dim=2, fused=False)
    vs = conv.init(jax.random.PRNGKey(0), x, gb)
    auto = SplineConv(8, dim=2)  # CPU auto => unfused
    np.testing.assert_allclose(np.asarray(conv.apply(vs, x, gb)),
                               np.asarray(auto.apply(vs, x, gb)),
                               atol=1e-6)


def test_fits_gate():
    assert route_aggregate_fits(64, 512, 25, 256)
    assert not route_aggregate_fits(15000, 100000, 25, 32)
    assert not route_aggregate_fits(64, 2048, 25, 512)   # E*O too wide
    assert not route_aggregate_fits(1024, 2048, 25, 32)  # N*E too big


def test_dispatch_context_silences_auto_but_not_explicit():
    from dgmc_tpu.ops.pallas.dispatch import (disable_fused_kernels,
                                              fused_kernels_allowed)
    assert fused_kernels_allowed()
    with disable_fused_kernels():
        assert not fused_kernels_allowed()
        with disable_fused_kernels():
            assert not fused_kernels_allowed()
        assert not fused_kernels_allowed()
    assert fused_kernels_allowed()


def test_dgmc_rejects_explicit_fused_under_corr_sharding():
    import pytest
    from dgmc_tpu.models import DGMC
    from dgmc_tpu.models.spline import SplineCNN
    import jax.sharding as shd
    mesh = jax.sharding.Mesh(np.array(jax.devices()[:1]), ('model',))
    sharding = shd.NamedSharding(mesh, shd.PartitionSpec(None, 'model'))
    psi_1 = SplineCNN(1, 8, dim=2, num_layers=1, fused=True)
    psi_2 = SplineCNN(4, 4, dim=2, num_layers=1)
    model = DGMC(psi_1, psi_2, num_steps=1, corr_sharding=sharding)
    rng = np.random.RandomState(0)
    B, N, E = 1, 8, 16
    gb = GraphBatch(
        x=jnp.ones((B, N, 1)),
        senders=jnp.asarray(rng.randint(0, N, (B, E)).astype(np.int32)),
        receivers=jnp.asarray(rng.randint(0, N, (B, E)).astype(np.int32)),
        node_mask=jnp.ones((B, N), bool),
        edge_mask=jnp.ones((B, E), bool),
        edge_attr=jnp.asarray(rng.rand(B, E, 2).astype(np.float32)))
    with pytest.raises(ValueError, match='fused=True'):
        model.init({'params': jax.random.PRNGKey(0),
                    'noise': jax.random.PRNGKey(1)}, gb, gb)


def test_basis_gradient_matches_gather_scatter():
    """Differentiating w.r.t. basis (i.e. edge attributes) must produce the
    same cotangent as the unfused gather+einsum path — computed via the
    symbolic-zeros-gated analytic rule, not silently zero."""
    t, flat, basis, rcv, em, N, E, O = problem(seed=5)

    def fused_loss(basis):
        return (route_aggregate(t, flat, basis, rcv, em, N, True) ** 2).sum()

    def ref_loss(basis):
        return (reference(t, flat, basis, rcv, em, N, E, O) ** 2).sum()

    g1 = jax.grad(fused_loss)(basis)
    g2 = jax.grad(ref_loss)(basis)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), atol=1e-4)


def test_joint_t_and_basis_gradients():
    t, flat, basis, rcv, em, N, E, O = problem(seed=6)

    def fused_loss(t, basis):
        return (route_aggregate(t, flat, basis, rcv, em, N, True) ** 2).sum()

    def ref_loss(t, basis):
        return (reference(t, flat, basis, rcv, em, N, E, O) ** 2).sum()

    gt1, gb1 = jax.grad(fused_loss, argnums=(0, 1))(t, basis)
    gt2, gb2 = jax.grad(ref_loss, argnums=(0, 1))(t, basis)
    np.testing.assert_allclose(np.asarray(gt1), np.asarray(gt2), atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb1), np.asarray(gb2), atol=1e-4)
