"""Host-RAM offload tier: prefetch-ring semantics + offloaded search
bit-identity (ops/offload.py)."""

import jax
import jax.numpy as jnp
import numpy as np

from dgmc_tpu.ops.offload import (OffloadStats, PrefetchRing,
                                  offloaded_streamed_topk)
from dgmc_tpu.ops.topk import streamed_topk


def test_ring_prefetches_ahead_and_evicts_behind():
    """get(i) serves chunk i, keeps exactly the next `depth` chunks in
    flight, and drops everything behind the cursor — the device-resident
    window is depth+1 chunks whatever the corpus size."""
    fetched = []

    def source(i):
        fetched.append(i)
        return np.full((2, 2), i, np.float32)

    ring = PrefetchRing(source, depth=2, n_chunks=6)
    a = ring.get(0)
    np.testing.assert_array_equal(np.asarray(a), np.zeros((2, 2)))
    # Cold start: 0 was a miss; 1 and 2 are now in flight.
    assert fetched == [0, 1, 2]
    assert ring.misses == 1
    assert ring.in_flight == 3

    ring.get(1)                # hit; window tops up to {1,2,3}; 0 out
    assert fetched == [0, 1, 2, 3]
    assert ring.misses == 1
    assert ring.evictions == 1
    assert sorted(ring._slots) == [1, 2, 3]

    ring.get(4)                      # skip ahead: 4 was never prefetched
    assert ring.misses == 2
    assert sorted(ring._slots) == [4, 5]   # 5 is the last chunk
    assert ring.in_flight == 2

    ring.get(5)
    assert sorted(ring._slots) == [5]
    # Each chunk was fetched exactly once: no refetch churn.
    assert sorted(fetched) == list(range(6))


def test_ring_round_robins_devices():
    """Slot i lands on devices[i % n] — the ring is also the
    data-parallel dispatch (rows are independent)."""
    devs = jax.devices()
    table = np.arange(8, dtype=np.float32).reshape(8, 1)
    ring = PrefetchRing(table, depth=3, devices=devs)
    for i in range(8):
        chunk = ring.get(i)
        assert chunk.devices() == {devs[i % len(devs)]}


def test_ring_array_source_len_inferred():
    table = np.zeros((5, 3), np.float32)
    ring = PrefetchRing(table, depth=1)
    assert ring.n_chunks == 5
    ring.get(0)
    assert ring.in_flight == 2


def test_offloaded_matches_streamed_bit_identical():
    """The offloaded sweep returns the exact device-path result —
    values, indices, tie order, ragged tail included — with the stats
    account matching what actually moved."""
    rng = np.random.RandomState(5)
    base = rng.randn(1, 16, 8).astype(np.float32)
    h_t = np.concatenate([base, base], axis=1)      # forced value ties
    h_s = rng.randn(1, 37, 8).astype(np.float32)    # ragged: 37 % 8 != 0
    tm = rng.rand(1, 32) > 0.4

    dv, di = streamed_topk(h_s, jnp.asarray(h_t), 5, 8,
                           t_mask=jnp.asarray(tm), block=8, pallas=False,
                           return_values=True)
    ov, oi, stats = offloaded_streamed_topk(
        h_s, h_t, 5, 8, t_mask=tm, block=8, depth=2)
    np.testing.assert_array_equal(oi, np.asarray(di))
    np.testing.assert_array_equal(ov, np.asarray(dv))

    assert isinstance(stats, OffloadStats)
    assert stats.rows == 37
    assert stats.chunks == 5                        # ceil(37 / 8)
    assert stats.ring_misses == 1                   # cold start only
    assert stats.host_resident_bytes == (
        h_s.nbytes + ov.nbytes + oi.nbytes)
    # Every chunk moved host->device exactly once (padded tail counts
    # a full chunk).
    assert stats.bytes_streamed == 5 * 8 * 8 * 4
    d = stats.to_json()
    assert d['prefetch_depth'] == 2 and d['devices'] >= 1


def test_offloaded_multi_device_round_robin_identical():
    """Round-robin dispatch over several devices must not change a bit
    of the result (row independence)."""
    rng = np.random.RandomState(6)
    h_s = rng.randn(2, 24, 4).astype(np.float32)
    h_t = rng.randn(2, 16, 4).astype(np.float32)
    dv, di = streamed_topk(h_s, jnp.asarray(h_t), 3, 4, block=4,
                           pallas=False, return_values=True)
    ov, oi, stats = offloaded_streamed_topk(
        h_s, h_t, 3, 4, block=4, depth=3, devices=jax.devices())
    np.testing.assert_array_equal(oi, np.asarray(di))
    np.testing.assert_array_equal(ov, np.asarray(dv))
    assert stats.devices == len(jax.devices())
