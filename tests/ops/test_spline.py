import jax.numpy as jnp
import numpy as np

from dgmc_tpu.ops import open_spline_basis


def test_basis_partition_of_unity():
    pseudo = jnp.array([[0.0, 0.0], [0.3, 0.7], [1.0, 1.0], [0.5, 0.123]])
    basis, combo = open_spline_basis(pseudo, kernel_size=5)
    assert basis.shape == (4, 4) and combo.shape == (4, 4)
    np.testing.assert_allclose(basis.sum(-1), jnp.ones(4), rtol=1e-6)


def test_basis_at_knot_is_one_hot():
    # pseudo 0.25 in K=5 lands exactly on knot 1.
    basis, combo = open_spline_basis(jnp.array([[0.25]]), kernel_size=5)
    np.testing.assert_allclose(basis[0], [1.0, 0.0])
    assert combo[0, 0] == 1


def test_basis_boundaries():
    basis, combo = open_spline_basis(jnp.array([[0.0], [1.0]]), kernel_size=5)
    # pseudo=0 → knot 0 fully; pseudo=1 → knot 4 fully.
    np.testing.assert_allclose(basis[0], [1.0, 0.0])
    assert combo[0, 0] == 0
    np.testing.assert_allclose(basis[1], [0.0, 1.0])
    assert combo[1, 1] == 4


def test_flat_index_layout_2d():
    # knot (i, j) → i + K*j.
    basis, combo = open_spline_basis(jnp.array([[0.25, 0.5]]), kernel_size=5)
    assert combo[0, 0] == 1 + 5 * 2
