import jax
import jax.numpy as jnp
import numpy as np

from dgmc_tpu.ops import masked_softmax


def test_matches_plain_softmax_when_unmasked():
    x = jax.random.normal(jax.random.PRNGKey(0), (3, 5))
    mask = jnp.ones((3, 5), dtype=bool)
    np.testing.assert_allclose(masked_softmax(x, mask),
                               jax.nn.softmax(x, axis=-1), rtol=1e-6)


def test_masked_entries_are_zero_and_rest_renormalized():
    x = jnp.array([[1.0, 2.0, 3.0]])
    mask = jnp.array([[True, False, True]])
    out = masked_softmax(x, mask)
    assert out[0, 1] == 0.0
    np.testing.assert_allclose(out.sum(), 1.0, rtol=1e-6)
    np.testing.assert_allclose(out[0, 2] / out[0, 0], np.exp(2.0), rtol=1e-5)


def test_fully_masked_row_is_zero_not_nan():
    x = jnp.array([[1.0, 2.0]])
    mask = jnp.zeros((1, 2), dtype=bool)
    out = masked_softmax(x, mask)
    np.testing.assert_allclose(out, jnp.zeros((1, 2)))
