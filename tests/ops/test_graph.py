import jax.numpy as jnp
import numpy as np

from dgmc_tpu.ops import GraphBatch, gather_nodes, scatter_to_nodes, degree


def small_batch():
    # Two graphs padded to N=3 nodes, E=4 edges. Graph 0: path 0-1-2 (4
    # directed edges). Graph 1: two real nodes, edge 0->1 and 1->0, two pads.
    x = jnp.arange(2 * 3 * 2, dtype=jnp.float32).reshape(2, 3, 2)
    senders = jnp.array([[0, 1, 1, 2], [0, 1, 0, 0]], dtype=jnp.int32)
    receivers = jnp.array([[1, 0, 2, 1], [1, 0, 0, 0]], dtype=jnp.int32)
    node_mask = jnp.array([[True, True, True], [True, True, False]])
    edge_mask = jnp.array([[True, True, True, True],
                           [True, True, False, False]])
    return GraphBatch(x=x, senders=senders, receivers=receivers,
                      node_mask=node_mask, edge_mask=edge_mask)


def test_gather_nodes():
    g = small_batch()
    out = gather_nodes(g.x, g.senders)
    assert out.shape == (2, 4, 2)
    np.testing.assert_allclose(out[0, 0], g.x[0, 0])
    np.testing.assert_allclose(out[0, 3], g.x[0, 2])


def test_scatter_sum_masks_padded_edges():
    g = small_batch()
    msgs = gather_nodes(g.x, g.senders)
    out = scatter_to_nodes(msgs, g.receivers, g.edge_mask, 3, aggr='sum')
    # Graph 1: node 0 receives only from node 1 (padded edges masked out).
    np.testing.assert_allclose(out[1, 0], g.x[1, 1])
    # Graph 0 node 1 receives from nodes 0 and 2.
    np.testing.assert_allclose(out[0, 1], g.x[0, 0] + g.x[0, 2])


def test_scatter_mean():
    g = small_batch()
    msgs = gather_nodes(g.x, g.senders)
    out = scatter_to_nodes(msgs, g.receivers, g.edge_mask, 3, aggr='mean')
    np.testing.assert_allclose(out[0, 1], (g.x[0, 0] + g.x[0, 2]) / 2)
    # Isolated (padded) node: zero, not NaN.
    np.testing.assert_allclose(out[1, 2], jnp.zeros(2))


def test_degree():
    g = small_batch()
    deg = degree(g.receivers, g.edge_mask, 3)
    np.testing.assert_allclose(deg[0], [1.0, 2.0, 1.0])
    np.testing.assert_allclose(deg[1], [1.0, 1.0, 0.0])
