import jax
import jax.numpy as jnp
import numpy as np

from dgmc_tpu.ops import chunked_topk, dense_topk


def test_chunked_matches_dense():
    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    h_s = jax.random.normal(k1, (2, 17, 8))
    h_t = jax.random.normal(k2, (2, 53, 8))
    for k in (1, 5, 10):
        idx_d = dense_topk(h_s, h_t, k)
        idx_c = chunked_topk(h_s, h_t, k, block=16)
        np.testing.assert_array_equal(idx_d, idx_c)


def test_chunked_matches_dense_with_mask():
    key = jax.random.PRNGKey(1)
    k1, k2, k3 = jax.random.split(key, 3)
    h_s = jax.random.normal(k1, (3, 9, 4))
    h_t = jax.random.normal(k2, (3, 31, 4))
    t_mask = jax.random.bernoulli(k3, 0.7, (3, 31))
    idx_d = dense_topk(h_s, h_t, 4, t_mask=t_mask)
    idx_c = chunked_topk(h_s, h_t, 4, t_mask=t_mask, block=8)
    np.testing.assert_array_equal(idx_d, idx_c)


def test_tie_breaking_prefers_lower_index():
    # All-equal scores: top-k must pick the lowest target indices, in order,
    # in both implementations.
    h_s = jnp.ones((1, 3, 2))
    h_t = jnp.ones((1, 20, 2))
    idx_d = dense_topk(h_s, h_t, 4)
    idx_c = chunked_topk(h_s, h_t, 4, block=4)
    np.testing.assert_array_equal(idx_d, np.tile(np.arange(4), (1, 3, 1)))
    np.testing.assert_array_equal(idx_c, idx_d)


def test_auto_gate_resolved_per_call_not_cached(monkeypatch):
    """The pallas auto-dispatch decision must be re-read on every call: a
    jitted wrapper would bake the trace-time contextvar into a cached jaxpr
    and never consult disable_fused_kernels() again (the nested-jit cache
    ignores contextvars)."""
    from dgmc_tpu.ops.pallas import dispatch

    calls = []
    real = dispatch.fused_kernels_allowed

    def counting():
        calls.append(True)
        return real()

    monkeypatch.setattr(dispatch, 'fused_kernels_allowed', counting)
    h_s = jnp.ones((1, 4, 2))
    h_t = jnp.ones((1, 8, 2))
    chunked_topk(h_s, h_t, 2, block=4)
    chunked_topk(h_s, h_t, 2, block=4)  # same shapes: jit cache hit inside
    assert len(calls) == 2
